"""Numerical-equivalence tests: every parallel layout must compute the same
model as the single-device baseline (the reference's strongest implicit
invariant, SURVEY.md §7 step 9; its TP test does the same against an
unsharded nn.Linear, ref: tests/test_tensor_parallel.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.config import Config, DistributedConfig, ModelConfig, TrainingConfig
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import (
    forward, init_params, pad_layers_for_pp, pp_layer_placement, unpad_layers,
)
from picotron_tpu.ops.losses import cross_entropy
from picotron_tpu.parallel.api import init_sharded_state, make_train_step
from picotron_tpu.parallel.tp import vocab_parallel_ce, vocab_parallel_embed
from picotron_tpu.train_step import init_train_state, make_train_step as make_single_step


def tiny_cfg(**dist) -> Config:
    gas = dist.pop("gas", 2)
    layers = dist.pop("layers", 4)
    attn_impl = dist.pop("attn_impl", "auto")
    return Config(
        distributed=DistributedConfig(**dist),
        # 8 q heads / 4 kv heads so GQA survives tp up to 4
        model=ModelConfig(dtype="float32", num_attention_heads=8,
                          num_key_value_heads=4, num_hidden_layers=layers,
                          attn_impl=attn_impl),
        training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                gradient_accumulation_steps=gas,
                                learning_rate=1e-3, remat=False),
    )


def global_batch(cfg, key=0):
    """(ids, targets) [n_micro, dp*mbs, seq] — same global content for every
    layout."""
    t = cfg.training
    b_global = t.micro_batch_size * cfg.distributed.dp_size
    toks = jax.random.randint(jax.random.key(key),
                              (t.gradient_accumulation_steps, b_global,
                               t.seq_length + 1),
                              0, cfg.model.vocab_size)
    return toks[..., :-1], toks[..., 1:]


def run_parallel(cfg, steps=3):
    from picotron_tpu.data import cp_sequence_permutation

    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
    ids, tgt = global_batch(cfg)
    perm = cp_sequence_permutation(cfg)
    if perm is not None:
        # mirror the dataloader's zigzag reorder (the parity invariant: the
        # permuted layout must train identically to the single-device run)
        ids, tgt = ids[..., perm], tgt[..., perm]
    batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, state


def run_single(cfg_parallel, steps=3):
    """Single-device ground truth on the same global batch."""
    cfg = Config(model=cfg_parallel.model,
                 training=cfg_parallel.training)
    params = init_params(cfg.model, jax.random.key(0))
    state = init_train_state(cfg, params)
    step = jax.jit(make_single_step(cfg))
    batch = global_batch(cfg_parallel)
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses, state


@pytest.mark.parametrize("dist", [
    # Pruned to one sweep entry per axis COMBINATION (r5, VERDICT r4 #8):
    # e.g. dp2xtp4 fell to dp2xtp2 + tp4, dp2xpp2 to dp2xpp2xcp2 +
    # dp2xpp2xtp2 — every axis pair below is still covered by exactly one
    # surviving entry, and single-axis cases stay.
    dict(dp_size=8),
    dict(tp_size=4),
    dict(dp_size=2, tp_size=2),
    dict(cp_size=4),
    dict(cp_size=4, cp_layout="contiguous"),
    dict(dp_size=2, cp_size=2, tp_size=2),
    dict(pp_size=2),
    dict(pp_size=2, tp_size=2),
    dict(pp_size=4, gas=4),
    dict(pp_size=4, gas=4, pp_engine="afab"),
    # uneven layer splits: 5 layers pad to 6/8 slots, remainder to early
    # stages (ref: pipeline_parallel.py:42-51)
    dict(pp_size=2, layers=5, pp_engine="afab"),
    dict(pp_size=4, layers=5, gas=4, tp_size=2),
    dict(dp_size=2, pp_size=2, cp_size=2),
    dict(dp_size=2, pp_size=2, tp_size=2),
    # Ulysses all-to-all sequence parallelism: head-scatter instead of the
    # K/V ring, same numbers (zigzag layout still applies)
    dict(cp_size=4, attn_impl="ulysses"),
    dict(cp_size=2, tp_size=2, attn_impl="ulysses"),
    dict(cp_size=2, tp_size=2, attn_impl="ulysses", sequence_parallel=True),
    # Megatron-style sequence parallelism over tp (seq-sharded residual
    # stream, all_gather/reduce-scatter f/g) must be numerically invisible
    dict(tp_size=4, sequence_parallel=True),
    dict(dp_size=2, tp_size=2, sequence_parallel=True, cp_size=2),
    dict(pp_size=2, tp_size=2, sequence_parallel=True),
    dict(pp_size=2, tp_size=2, sequence_parallel=True, pp_engine="afab"),
])
@pytest.mark.slow
def test_layouts_match_single_device(dist):
    cfg = tiny_cfg(**dist)
    par_losses, par_state = run_parallel(cfg)
    ref_losses, ref_state = run_single(cfg)
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-5)
    par_params = unpad_layers(par_state.params, cfg.model.num_hidden_layers,
                              cfg.distributed.pp_size)
    # Parameters after 3 updates agree. Tolerance note: Adam divides by
    # sqrt(v) which amplifies fp32 reduction-order differences between the
    # sharded and dense reductions during the first steps, so this is
    # necessarily looser than the loss check.
    q_par = np.asarray(par_params["layers"]["q"])
    q_ref = np.asarray(ref_state.params["layers"]["q"])
    np.testing.assert_allclose(q_par, q_ref, rtol=2e-2, atol=1e-3)
    emb_par = np.asarray(par_state.params["embedding"])
    emb_ref = np.asarray(ref_state.params["embedding"])
    np.testing.assert_allclose(emb_par, emb_ref, rtol=2e-2, atol=1e-3)


def test_pp_layer_placement_remainder_to_early_stages():
    # 5 layers on pp=4: stages get 2,1,1,1 (ref: pipeline_parallel.py:42-51)
    padded, slots = pp_layer_placement(5, 4)
    assert padded == 8
    assert slots.tolist() == [0, 1, 2, 4, 6]  # per-stage leading slots
    padded, slots = pp_layer_placement(4, 2)  # even split: canonical
    assert padded == 4 and slots.tolist() == [0, 1, 2, 3]


def test_zero_padded_layers_are_identity():
    """The uneven-PP padding contract: all-zero layer slots change neither
    the forward values nor any real parameter's gradient."""
    from picotron_tpu.ops.losses import cross_entropy

    cfg = ModelConfig(dtype="float32", num_hidden_layers=5,
                      num_attention_heads=8, num_key_value_heads=4)
    params = init_params(cfg, jax.random.key(0))
    padded = pad_layers_for_pp(params, 5, 2)
    assert padded["layers"]["q"].shape[0] == 6
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.key(2), (2, 16), 0, cfg.vocab_size)
    np.testing.assert_allclose(
        np.asarray(forward(params, ids, cfg)),
        np.asarray(forward(padded, ids, cfg)), rtol=1e-6)

    def loss(p):
        return cross_entropy(forward(p, ids, cfg), tgt)

    g_pad = jax.grad(loss)(padded)
    g_ref = jax.grad(loss)(params)
    # pad slots get exactly-zero grads; real slots match the unpadded grads
    jax.tree.map(
        lambda gp, gr: np.testing.assert_allclose(
            np.asarray(unpad_layers({"layers": {"x": gp}}, 5, 2)["layers"]["x"]),
            np.asarray(gr), rtol=1e-5, atol=1e-7),
        g_pad["layers"], g_ref["layers"])
    slots_set = set(pp_layer_placement(5, 2)[1].tolist())
    pad_slots = [i for i in range(6) if i not in slots_set]
    for leaf in jax.tree.leaves(g_pad["layers"]):
        assert np.all(np.asarray(leaf)[pad_slots] == 0.0)


def test_vocab_parallel_embed_matches_lookup():
    menv = MeshEnv.create(tp=8)
    w = jax.random.normal(jax.random.key(0), (64, 16))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)

    out = jax.jit(compat.shard_map(
        vocab_parallel_embed, mesh=menv.mesh,
        in_specs=(P("tp", None), P()), out_specs=P(),
    ))(w, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w[ids]), rtol=1e-6)


def test_vocab_parallel_ce_matches_dense():
    menv = MeshEnv.create(tp=8)
    h = jax.random.normal(jax.random.key(0), (2, 8, 16))
    head = jax.random.normal(jax.random.key(1), (16, 64))
    tgt = jax.random.randint(jax.random.key(2), (2, 8), 0, 64)
    tgt = tgt.at[0, :2].set(-100)  # exercise ignore_index

    loss = jax.jit(compat.shard_map(
        vocab_parallel_ce, mesh=menv.mesh,
        in_specs=(P(), P(None, "tp"), P()), out_specs=P(),
    ))(h, head, tgt)
    want = cross_entropy(h @ head, tgt)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)


@pytest.mark.skipif(
    not compat.HAS_VMA,
    reason="differentiates THROUGH the tp psum in vocab_parallel_ce: "
           "pre-vma shard_map inflates the cotangent by the tp size "
           "(see compat.py)")
def test_vocab_parallel_ce_grad_matches_dense():
    menv = MeshEnv.create(tp=8)
    h = jax.random.normal(jax.random.key(0), (2, 8, 16))
    head = jax.random.normal(jax.random.key(1), (16, 64))
    tgt = jax.random.randint(jax.random.key(2), (2, 8), 0, 64)

    def sharded_loss(h, head):
        return vocab_parallel_ce(h, head, tgt)

    g_par = jax.jit(compat.shard_map(
        jax.grad(sharded_loss, argnums=(0, 1)), mesh=menv.mesh,
        in_specs=(P(), P(None, "tp")), out_specs=(P(), P(None, "tp")),
    ))(h, head)
    g_ref = jax.grad(lambda h, w: cross_entropy(h @ w, tgt), argnums=(0, 1))(h, head)
    np.testing.assert_allclose(np.asarray(g_par[0]), np.asarray(g_ref[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_par[1]), np.asarray(g_ref[1]),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_zero1_moments_sharded_and_parity():
    """ZeRO-1: moments shard over dp; training is numerically identical to
    the unsharded-optimizer run (GSPMD inserts the per-shard update +
    all-gather; the math never changes)."""
    cfg = tiny_cfg(dp_size=4, zero1=True)
    par_losses, par_state = run_parallel(cfg)
    ref_losses, ref_state = run_single(cfg)
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-5)

    # some moment leaf matching the q weight's shape must be dp-sharded
    q_shape = par_state.params["layers"]["q"].shape
    moment_specs = [
        leaf.sharding.spec for leaf in jax.tree.leaves(par_state.opt_state)
        if getattr(leaf, "shape", None) == q_shape]
    assert moment_specs, "no Adam moment found for the q weight"

    def flat_axes(spec):
        return [a for part in spec if part is not None
                for a in (part if isinstance(part, (tuple, list)) else (part,))]

    assert all("dp" in flat_axes(s) for s in moment_specs), moment_specs


def test_zero1_moment_footprint_shrinks_dp_fold():
    """The claimed ~dp_size x cut in resident optimizer-state memory
    (config.py zero1 docstring, measured at scale in PERF.md r4), asserted
    structurally on the virtual mesh: per-device moment bytes under zero1
    must be ~1/dp of the unsharded layout (abstract state — no arrays
    materialize)."""
    from picotron_tpu.parallel.api import init_sharded_state

    def per_device_opt_bytes(cfg):
        menv = MeshEnv.from_config(cfg)
        st = init_sharded_state(cfg, menv, jax.random.key(0), abstract=True)
        total = 0
        for leaf in jax.tree.leaves(st.opt_state):
            shard = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard)) * leaf.dtype.itemsize
        return total

    base = per_device_opt_bytes(tiny_cfg(dp_size=4))
    z1 = per_device_opt_bytes(tiny_cfg(dp_size=4, zero1=True))
    # small non-divisible leaves (norms) stay replicated, so slightly
    # above exactly 4x; anything < 3x would mean the annotation regressed
    assert base / z1 > 3.0, (base, z1)


@pytest.mark.slow
def test_ce_chunking_matches_fused_across_layouts():
    """ce_chunk_size streams the LM-head CE over vocab chunks without
    materializing [tokens, vocab] logits; it must match the fused path to
    fp precision, including through the pipeline engines' gated last-stage
    scoring cond (whose branches must stay collective-free — the chunk
    scan's carry anchoring is the load-bearing detail)."""
    import dataclasses

    base = tiny_cfg(pp_size=2, tp_size=2)
    losses = {}
    for chunk in (0, 16):
        cfg = Config(
            distributed=base.distributed,
            model=base.model,
            training=dataclasses.replace(base.training,
                                         ce_chunk_size=chunk),
        )
        cfg.validate()
        losses[chunk], _ = run_parallel(cfg)
    np.testing.assert_allclose(losses[0], losses[16], rtol=1e-6, atol=1e-7)
