"""Numerical-equivalence tests: every parallel layout must compute the same
model as the single-device baseline (the reference's strongest implicit
invariant, SURVEY.md §7 step 9; its TP test does the same against an
unsharded nn.Linear, ref: tests/test_tensor_parallel.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import Config, DistributedConfig, ModelConfig, TrainingConfig
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import init_params
from picotron_tpu.ops.losses import cross_entropy
from picotron_tpu.parallel.api import init_sharded_state, make_train_step
from picotron_tpu.parallel.tp import vocab_parallel_ce, vocab_parallel_embed
from picotron_tpu.train_step import init_train_state, make_train_step as make_single_step


def tiny_cfg(**dist) -> Config:
    gas = dist.pop("gas", 2)
    return Config(
        distributed=DistributedConfig(**dist),
        # 8 q heads / 4 kv heads so GQA survives tp up to 4
        model=ModelConfig(dtype="float32", num_attention_heads=8,
                          num_key_value_heads=4),
        training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                gradient_accumulation_steps=gas,
                                learning_rate=1e-3, remat=False),
    )


def global_batch(cfg, key=0):
    """(ids, targets) [n_micro, dp*mbs, seq] — same global content for every
    layout."""
    t = cfg.training
    b_global = t.micro_batch_size * cfg.distributed.dp_size
    toks = jax.random.randint(jax.random.key(key),
                              (t.gradient_accumulation_steps, b_global,
                               t.seq_length + 1),
                              0, cfg.model.vocab_size)
    return toks[..., :-1], toks[..., 1:]


def run_parallel(cfg, steps=3):
    from picotron_tpu.data import cp_sequence_permutation

    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
    ids, tgt = global_batch(cfg)
    perm = cp_sequence_permutation(cfg)
    if perm is not None:
        # mirror the dataloader's zigzag reorder (the parity invariant: the
        # permuted layout must train identically to the single-device run)
        ids, tgt = ids[..., perm], tgt[..., perm]
    batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses, state


def run_single(cfg_parallel, steps=3):
    """Single-device ground truth on the same global batch."""
    cfg = Config(model=cfg_parallel.model,
                 training=cfg_parallel.training)
    params = init_params(cfg.model, jax.random.key(0))
    state = init_train_state(cfg, params)
    step = jax.jit(make_single_step(cfg))
    batch = global_batch(cfg_parallel)
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses, state


@pytest.mark.parametrize("dist", [
    dict(dp_size=8),
    dict(tp_size=4),
    dict(dp_size=2, tp_size=2),
    dict(dp_size=2, tp_size=4),
    dict(cp_size=4),
    dict(cp_size=4, cp_layout="contiguous"),
    dict(dp_size=2, cp_size=2, tp_size=2),
    dict(dp_size=2, cp_size=2, tp_size=2, cp_layout="contiguous"),
    dict(pp_size=2),
    dict(dp_size=2, pp_size=2),
    dict(pp_size=2, tp_size=2),
    dict(pp_size=4, gas=4),
    dict(dp_size=2, pp_size=2, cp_size=2),
    dict(dp_size=2, pp_size=2, tp_size=2),
])
def test_layouts_match_single_device(dist):
    cfg = tiny_cfg(**dist)
    par_losses, par_state = run_parallel(cfg)
    ref_losses, ref_state = run_single(cfg)
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-5)
    # Parameters after 3 updates agree. Tolerance note: Adam divides by
    # sqrt(v) which amplifies fp32 reduction-order differences between the
    # sharded and dense reductions during the first steps, so this is
    # necessarily looser than the loss check.
    q_par = np.asarray(par_state.params["layers"]["q"])
    q_ref = np.asarray(ref_state.params["layers"]["q"])
    np.testing.assert_allclose(q_par, q_ref, rtol=2e-2, atol=1e-3)
    emb_par = np.asarray(par_state.params["embedding"])
    emb_ref = np.asarray(ref_state.params["embedding"])
    np.testing.assert_allclose(emb_par, emb_ref, rtol=2e-2, atol=1e-3)


def test_vocab_parallel_embed_matches_lookup():
    menv = MeshEnv.create(tp=8)
    w = jax.random.normal(jax.random.key(0), (64, 16))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)

    out = jax.jit(jax.shard_map(
        vocab_parallel_embed, mesh=menv.mesh,
        in_specs=(P("tp", None), P()), out_specs=P(),
    ))(w, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(w[ids]), rtol=1e-6)


def test_vocab_parallel_ce_matches_dense():
    menv = MeshEnv.create(tp=8)
    h = jax.random.normal(jax.random.key(0), (2, 8, 16))
    head = jax.random.normal(jax.random.key(1), (16, 64))
    tgt = jax.random.randint(jax.random.key(2), (2, 8), 0, 64)
    tgt = tgt.at[0, :2].set(-100)  # exercise ignore_index

    loss = jax.jit(jax.shard_map(
        vocab_parallel_ce, mesh=menv.mesh,
        in_specs=(P(), P(None, "tp"), P()), out_specs=P(),
    ))(h, head, tgt)
    want = cross_entropy(h @ head, tgt)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)


def test_vocab_parallel_ce_grad_matches_dense():
    menv = MeshEnv.create(tp=8)
    h = jax.random.normal(jax.random.key(0), (2, 8, 16))
    head = jax.random.normal(jax.random.key(1), (16, 64))
    tgt = jax.random.randint(jax.random.key(2), (2, 8), 0, 64)

    def sharded_loss(h, head):
        return vocab_parallel_ce(h, head, tgt)

    g_par = jax.jit(jax.shard_map(
        jax.grad(sharded_loss, argnums=(0, 1)), mesh=menv.mesh,
        in_specs=(P(), P(None, "tp")), out_specs=(P(), P(None, "tp")),
    ))(h, head)
    g_ref = jax.grad(lambda h, w: cross_entropy(h @ w, tgt), argnums=(0, 1))(h, head)
    np.testing.assert_allclose(np.asarray(g_par[0]), np.asarray(g_ref[0]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_par[1]), np.asarray(g_ref[1]),
                               rtol=1e-5, atol=1e-6)
