"""slicecheck tests: the slice-boundary auditor (analysis/boundary.py),
the cost model's DCN tier, the multislice planner rows, and the static
schedule-table lint (parallel/mpmd.lint_schedule).

Protocol: the 8 simulated host devices are partitioned into 2 declared
"slices" (tests/conftest.py::slice_partition). The crossing presets pin
that every collective of a correctly declared layout lands in a tier
(intra-slice or declared-boundary, zero violating), a deliberately
mis-declared layout is caught with a named `ici-axis-over-dcn` error,
and the two hierarchical-decomposition mutations the issue names —
deleting the intra-slice reduce-scatter leg, widening the DCN all-reduce
group — each trip a named rule (the PR-15 mutation-test pattern)."""

import dataclasses

import pytest

from picotron_tpu.analysis.boundary import (
    SliceTopology, audit_boundary, classify_ops,
)
from picotron_tpu.analysis.collectives import parse_collectives
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, PipelineConfig, TrainingConfig,
    parse_dcn_axes, resolve_preset,
)


def mkcfg(model="debug-tiny", dist=None, train=None, pipe=None):
    cfg = Config(
        distributed=DistributedConfig(**(dist or {})),
        model=ModelConfig(name=model, **resolve_preset(model)),
        training=TrainingConfig(seq_length=64, micro_batch_size=1,
                                **(train or {})),
        pipeline=PipelineConfig(**(pipe or {})),
    )
    cfg.validate()
    return cfg


def dp_cross_cfg():
    return mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2,
                           slices=2, dcn_axes="dp"),
                 train=dict(gradient_accumulation_steps=2))


def pp_cross_cfg():
    return mkcfg(dist=dict(pp_size=2, tp_size=2, slices=2, dcn_axes="pp"),
                 train=dict(gradient_accumulation_steps=2),
                 pipe=dict(executor="mpmd"))


@pytest.fixture(scope="module")
def dp_cross_text():
    from picotron_tpu.analysis.trace import lower_train_step

    return lower_train_step(dp_cross_cfg()).text


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_parse_dcn_axes():
    assert parse_dcn_axes("dp,pp") == ("dp", "pp")
    assert parse_dcn_axes("pp,dp") == ("dp", "pp")  # dp-first order
    assert parse_dcn_axes("pp") == ("pp",)
    assert parse_dcn_axes("") == ()
    with pytest.raises(ValueError, match="tp"):
        parse_dcn_axes("dp,tp")  # ICI-only axis can never cross DCN
    with pytest.raises(ValueError, match="duplicate"):
        parse_dcn_axes("dp,dp")


def test_config_validates_slice_divisibility():
    with pytest.raises(ValueError, match="slices"):
        mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2, slices=3,
                        dcn_axes="dp"),
              train=dict(gradient_accumulation_steps=2))
    with pytest.raises(ValueError, match="dcn_axes"):
        mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2, slices=2,
                        dcn_axes=""),
              train=dict(gradient_accumulation_steps=2))


# ---------------------------------------------------------------------------
# SliceTopology: device -> slice mapping
# ---------------------------------------------------------------------------


def test_slice_topology_matches_session_partition(slice_partition):
    """The house rule (granule = OUTER factor of the first cut axis on
    the row-major grid) maps the 8 simulated devices exactly onto the
    session's positional 2-slice partition."""
    topo = SliceTopology.from_config(dp_cross_cfg())
    assert topo.n_slices == 2
    assert topo.declared == ("dp",)
    assert topo.cut_axes == ("dp",)
    for sl, ids in slice_partition.items():
        for d in ids:
            assert topo.slice_of(d) == sl, (d, sl)


def test_slice_topology_pp_cut():
    topo = SliceTopology.from_config(pp_cross_cfg())
    assert topo.cut_axes == ("pp",)
    # grid (dp=1, pp=2, ep=1, cp=1, tp=2 -> padded to 8 by dp? no: world=4)
    # row-major (dp, pp, ep, cp, tp): id = pp*2 + tp; pp coord is id // 2
    for d in range(4):
        assert topo.slice_of(d) == d // 2


def test_slice_topology_rejects_indivisible():
    cfg = dp_cross_cfg()
    with pytest.raises(ValueError):
        SliceTopology.from_config(cfg, n_slices=3)


# ---------------------------------------------------------------------------
# replica-group membership parsing
# ---------------------------------------------------------------------------


def test_parse_collectives_members(dp_cross_text):
    ops = parse_collectives(dp_cross_text)
    assert ops, "no collectives in the dp-cross lowering"
    with_members = [o for o in ops if o.members is not None]
    # the StableHLO dense<...> dialect carries explicit member lists:
    # membership must be recovered for (at least) every grouped op
    assert len(with_members) >= len(ops) - 2, (len(with_members), len(ops))
    for o in with_members:
        if o.kind == "collective_permute":
            assert all(len(pair) == 2 for pair in o.members)
        else:
            assert len(o.members) == o.n_groups
            assert all(len(g) == o.group_size for g in o.members)


# ---------------------------------------------------------------------------
# classification: crossing presets are green, mis-declaration is caught
# ---------------------------------------------------------------------------


def test_dp_cross_audit_green(dp_cross_text):
    cfg = dp_cross_cfg()
    rep = audit_boundary(cfg, text=dp_cross_text)
    assert rep.ok(), rep.render(verbose=True)
    info = rep.info["boundary"]
    assert info["audited"] and info["slices"] == 2
    assert info["violating"] == 0 and info["unattributable"] == 0
    assert info["boundary"] > 0, "dp crossers must exist"
    assert info["intra"] > 0, "tp/cp collectives must stay inside"
    assert info["dcn_bytes"] > 0 and info["ici_bytes"] > 0
    # every intra op carries zero DCN bytes and vice versa
    for row in info["table"]:
        if row["class"] == "intra":
            assert row["dcn_bytes"] == 0
        if row["class"] == "boundary":
            assert row["dcn_bytes"] > 0


def test_pp_cross_audit_green_via_shardcheck():
    from picotron_tpu.analysis import run_shardcheck

    rep = run_shardcheck(pp_cross_cfg())
    assert rep.ok(), rep.render(verbose=True)
    info = rep.info["boundary"]
    assert info["audited"] and info["violating"] == 0
    # the stage-boundary ppermutes are declared crossers
    kinds = {r["kind"] for r in info["table"] if r["class"] == "boundary"}
    assert "collective_permute" in kinds
    # satellite 1: the static schedule-table lint surfaces through the
    # variants info for MPMD configs
    lint = rep.info["variants"]["mpmd_stages"]["schedule_lint"]
    assert lint["proven"] and lint["problems"] == 0
    assert lint["kind"] == "1f1b" and lint["ops"] > 0


def test_misdeclared_axis_is_a_named_violation(dp_cross_text):
    """Declaring pp as the crossing axis while the house rule cuts dp
    routes every ICI-only dp collective over DCN — each one is a named
    `ici-axis-over-dcn` error and shardcheck goes red."""
    cfg = dp_cross_cfg()
    rep = audit_boundary(cfg, text=dp_cross_text, dcn_axes="pp")
    assert not rep.ok()
    errs = [f for f in rep.errors() if "ici-axis-over-dcn" in f.message]
    assert errs, rep.render(verbose=True)
    assert rep.info["boundary"]["violating"] == len(errs)
    assert rep.info["boundary"]["boundary"] == 0


def test_violation_names_the_minting_source():
    """Through the runner (which hands the auditor the full lowering),
    a violating op is attributed to the Python site that minted it —
    actionable where a bare StableHLO line number is not."""
    from picotron_tpu.analysis import run_shardcheck

    cfg = mkcfg(dist=dict(dp_size=2, pp_size=2, tp_size=2,
                          slices=2, dcn_axes="pp"),
                train=dict(gradient_accumulation_steps=2))
    rep = run_shardcheck(cfg, checks=("spec", "boundary"))
    errs = [f for f in rep.errors() if "ici-axis-over-dcn" in f.message]
    assert errs, rep.render(verbose=True)
    assert any("minted at" in f.message and ".py:" in f.message
               for f in errs), errs[0].message


def test_single_slice_is_a_no_op(dp_cross_text):
    cfg = mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2),
                train=dict(gradient_accumulation_steps=2))
    rep = audit_boundary(cfg, text=dp_cross_text)
    assert rep.ok()
    assert rep.info["boundary"] == {"slices": 1, "audited": False}


# ---------------------------------------------------------------------------
# hierarchical-decomposition mutations (the PR-15 pattern)
# ---------------------------------------------------------------------------

_GRAD_GROUPS = "[[0, 2, 4, 6], [1, 3, 5, 7]]"


def test_mutation_deleted_intra_scatter_leg(dp_cross_text):
    """Rewriting the fused-dp grad groups so each crossing group keeps a
    per-slice cohort of 1 deletes the intra-slice reduce-scatter leg of
    the hierarchical decomposition: full-width gradients would cross DCN
    instead of one shard per slice. Named rule: hier_intra_scatter."""
    assert _GRAD_GROUPS in dp_cross_text, \
        "lowering changed; update the mutation fixture"
    mutated = dp_cross_text.replace(
        _GRAD_GROUPS, "[[0, 4], [2, 6], [1, 5], [3, 7]]").replace(
        "tensor<2x4xi64>", "tensor<4x2xi64>")
    rep = audit_boundary(dp_cross_cfg(), text=mutated)
    assert not rep.ok()
    assert any(f.path == "hier_intra_scatter" for f in rep.errors()), \
        rep.render(verbose=True)


def test_mutation_widened_dcn_group(dp_cross_text):
    """Unbalancing a crossing group (3 members on one slice, 1 on the
    other) widens the DCN leg past one shard per slice. Named rule:
    hier_dcn_cohort."""
    mutated = dp_cross_text.replace(
        _GRAD_GROUPS, "[[0, 2, 4, 1], [6, 3, 5, 7]]")
    rep = audit_boundary(dp_cross_cfg(), text=mutated)
    assert not rep.ok()
    assert any(f.path == "hier_dcn_cohort" for f in rep.errors()), \
        rep.render(verbose=True)


# ---------------------------------------------------------------------------
# cost model: the dcn tier
# ---------------------------------------------------------------------------


def test_generations_carry_dcn_descriptors():
    from picotron_tpu.analysis.cost_model import Calibration, GENERATIONS

    for name, gen in GENERATIONS.items():
        assert gen.dcn_bandwidth > 0, name
        assert gen.dcn_alpha_s > 0, name
        # DCN is the slow tier by construction
        assert gen.dcn_bandwidth < gen.link_bandwidth, name
        assert gen.dcn_alpha_s > Calibration().alpha_link_s, name


def test_dcn_secs_prices_the_slow_tier():
    from picotron_tpu.analysis.cost_model import CostModel

    m = CostModel("v5e")
    small = m.dcn_secs("all_reduce", 1 << 20, 2)
    big = m.dcn_secs("all_reduce", 1 << 24, 2)
    assert 0 < small < big
    # same bytes over ICI are far cheaper than over DCN
    link = m.dcn_link(2)
    assert link.axis == "dcn" and link.size == 2
    assert link.bandwidth == m.gen.dcn_bandwidth


def test_split_slice_link():
    from picotron_tpu.analysis.cost_model import (
        AxisLink, GENERATIONS, split_slice_link,
    )

    gen = GENERATIONS["v5e"]
    parent = AxisLink("dp", 8, "ring", gen.link_bandwidth, 1)
    intra, dcn = split_slice_link(parent, 2, gen)
    assert intra.size == 4 and intra.axis == "dp"
    assert intra.bandwidth == parent.bandwidth
    assert dcn.size == 2 and dcn.bandwidth == gen.dcn_bandwidth


def test_slice_tiers_and_slice_plans():
    from picotron_tpu.analysis.cost_model import CostModel
    from picotron_tpu.analysis.planner import slice_plans

    cfg = mkcfg(dist=dict(dp_size=4, pp_size=2),
                train=dict(gradient_accumulation_steps=2))
    rows = slice_plans(cfg, CostModel("v5e"), n_slices=2)
    assert {r["axis"] for r in rows} == {"dp", "pp"}
    for r in rows:
        assert r["slices"] == 2 and r["generation"] == "v5e"
        assert r["dcn_bytes"] > 0 and r["dcn_ms"] > 0
        assert r["total_comm_ms"] > 0
        assert r["crossing_terms"], r
    # ranked by total comm, best first
    assert rows == sorted(rows, key=lambda r: r["total_comm_ms"])
    # no legal axis -> empty (tp cannot absorb slices)
    solo = mkcfg(dist=dict(tp_size=2))
    assert slice_plans(solo, CostModel("v5e"), n_slices=2) == []
    assert slice_plans(cfg, CostModel("v5e"), n_slices=1) == []


def test_audit_prices_tiers_with_cost_model(dp_cross_text):
    from picotron_tpu.analysis.cost_model import CostModel

    rep = audit_boundary(dp_cross_cfg(), text=dp_cross_text,
                         cost_model=CostModel("v5e"))
    info = rep.info["boundary"]
    assert info["dcn_generation"] == "v5e"
    assert info["dcn_ms"] > 0 and info["ici_ms"] > 0
    # the DCN leg dominates: slower wire, per-slice shards notwithstanding
    assert info["dcn_ms"] > info["ici_ms"]


# ---------------------------------------------------------------------------
# satellite 1: the static schedule-table lint
# ---------------------------------------------------------------------------


def test_lint_schedule_clean_tables():
    from picotron_tpu.parallel.mpmd import SCHEDULES, build_schedule

    # build_schedule lints at construction (raises ScheduleBufferError on
    # failure) — a representative sweep must come back clean
    for kind in SCHEDULES:
        for pp in (2, 4, 8):
            for n in (2, 8, 16):
                for v in (1, 2) if kind == "interleaved" else (1,):
                    build_schedule(kind, n, pp, v)


def test_lint_schedule_catches_truncated_table():
    from picotron_tpu.parallel.mpmd import build_schedule, lint_schedule

    table = build_schedule("1f1b", 4, 4, 1)
    truncated = [op for op in table if not (op.op == "B" and op.mb == 3)]
    problems = lint_schedule(truncated, 4, 4, 1, kind="1f1b")
    assert problems and any("never consumed" in p for p in problems)


def test_lint_schedule_catches_missing_producer():
    from picotron_tpu.parallel.mpmd import build_schedule, lint_schedule

    table = build_schedule("1f1b", 4, 4, 1)
    dropped = [op for op in table
               if not (op.op == "F" and op.mb == 2 and op.vstage == 1)]
    problems = lint_schedule(dropped, 4, 4, 1, kind="1f1b")
    assert problems and any("never produced" in p for p in problems)


def test_lint_schedule_catches_unbounded_live_set():
    from picotron_tpu.parallel.mpmd import build_schedule, lint_schedule

    # a gpipe table (save-everything) presented as 1f1b blows the
    # in-flight budget: backwards deferred past the pipeline depth
    table = build_schedule("gpipe", 16, 4, 1)
    problems = lint_schedule(table, 16, 4, 1, kind="1f1b")
    assert any("in-flight budget" in p for p in problems)


def test_build_schedule_raises_on_linted_table(monkeypatch):
    import picotron_tpu.parallel.mpmd as mpmd

    monkeypatch.setattr(mpmd, "lint_schedule",
                        lambda *a, **k: ["planted problem"])
    with pytest.raises(mpmd.ScheduleBufferError, match="static lint"):
        mpmd.build_schedule("1f1b", 4, 2, 1)


# ---------------------------------------------------------------------------
# runtime hierarchical dp reduction (parallel/hier_reduce.py)
# ---------------------------------------------------------------------------


def test_use_hier_dp_resolution():
    from picotron_tpu.parallel.hier_reduce import dp_granule, use_hier_dp

    cfg = dp_cross_cfg()
    assert use_hier_dp(cfg)
    assert dp_granule(cfg) == (2, 1)  # dp=2 fully absorbed by 2 slices
    off = dataclasses.replace(
        cfg, distributed=dataclasses.replace(cfg.distributed,
                                             hier_dp_reduce="off"))
    assert not use_hier_dp(off)
    # pp carries the cut: dp never crosses, flat psum is correct
    assert not use_hier_dp(pp_cross_cfg())
    # single slice: nothing to decompose
    solo = mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2),
                 train=dict(gradient_accumulation_steps=2))
    assert not use_hier_dp(solo)


def test_hier_dp_reduce_validation():
    with pytest.raises(ValueError, match="hier_dp_reduce"):
        mkcfg(dist=dict(dp_size=2, hier_dp_reduce="maybe"))
    # 'on' demands a layout where dp physically carries a slice granule
    with pytest.raises(ValueError, match="hier_dp_reduce"):
        mkcfg(dist=dict(dp_size=2, tp_size=2, hier_dp_reduce="on"))
    with pytest.raises(ValueError, match="hier_dp_reduce"):
        mkcfg(dist=dict(pp_size=2, tp_size=2, slices=2, dcn_axes="pp",
                        hier_dp_reduce="on"),
              pipe=dict(executor="mpmd"))
    cfg = mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2, slices=2,
                          dcn_axes="dp", hier_dp_reduce="on"),
                train=dict(gradient_accumulation_steps=2))
    assert cfg.distributed.hier_dp_reduce == "on"


def test_dp_groups_pair_one_member_per_slice():
    from picotron_tpu.parallel.hier_reduce import _dp_groups

    intra, cross = _dp_groups(2, 2)  # dp=4 over 2 slices
    assert intra == [[0, 1], [2, 3]]      # contiguous per-slice cohorts
    assert cross == [[0, 2], [1, 3]]      # one member per slice
    intra, cross = _dp_groups(2, 1)       # dp=2 fully absorbed
    assert intra == [[0], [1]]
    assert cross == [[0, 1]]


def test_runtime_hier_emits_explicit_schedule(dp_cross_text):
    """The REAL traced crossing train step (not a mutation fixture)
    carries the explicit hierarchical schedule: cohort-1 DCN all-reduces
    for the gradient legs, intra-slice reduce-scatter / all-gather wings,
    and zero collectives outside the declared tiers."""
    topo = SliceTopology.from_config(dp_cross_cfg())
    classified = classify_ops(parse_collectives(dp_cross_text), topo)
    boundary = [r for r in classified if r.cls == "boundary"]
    intra = [r for r in classified if r.cls == "intra"]
    assert not [r for r in classified if r.cls == "violating"]
    # grad DCN legs: every crossing reduction beyond the two flat scalar
    # loss psums is a cohort-1 all-reduce (one shard per slice)
    dcn_grad = [r for r in boundary
                if r.kind == "all_reduce" and max(r.cohorts) == 1]
    assert len(dcn_grad) >= 10, [r.kind for r in boundary]
    # the wings stayed on ICI
    assert [r for r in intra if r.kind == "reduce_scatter"]
    assert [r for r in intra if r.kind == "all_gather"]


def test_runtime_mutation_strip_scatter_leg_trips_rule(dp_cross_text):
    """Deleting the intra-slice reduce-scatter wings from the traced
    hierarchical text leaves cohort-1 DCN all-reduces with no scatter
    producing their shards — the explicit-form arm of the presence rule
    fails and hier_intra_scatter fires on the runtime schedule."""
    lines = [ln for ln in dp_cross_text.splitlines()
             if "reduce_scatter" not in ln]
    rep = audit_boundary(dp_cross_cfg(), text="\n".join(lines))
    assert not rep.ok()
    assert any(f.path == "hier_intra_scatter" for f in rep.errors()), \
        rep.render(verbose=True)


def test_hier_off_twin_lowers_flat_and_audits_green():
    """hier_dp_reduce='off' at the same layout keeps the flat fused-dp
    psum: no grad reduce-scatter wings, crossing reductions satisfy the
    fused-form arm (cohorts >= per-slice width), audit still green."""
    from picotron_tpu.analysis.trace import lower_train_step

    cfg = dp_cross_cfg()
    off = dataclasses.replace(
        cfg, distributed=dataclasses.replace(cfg.distributed,
                                             hier_dp_reduce="off"))
    text = lower_train_step(off).text
    topo = SliceTopology.from_config(off)
    classified = classify_ops(parse_collectives(text), topo)
    assert not [r for r in classified
                if r.cls == "intra" and r.kind == "reduce_scatter"]
    for r in classified:
        if r.cls == "boundary" and r.kind == "all_reduce":
            assert min(r.cohorts) >= 2, (r.kind, r.cohorts)
    rep = audit_boundary(off, text=text)
    assert rep.ok(), rep.render(verbose=True)
    assert rep.info["boundary"]["violating"] == 0


def test_hier_flat_loss_parity():
    """Parity twin the issue demands: the hierarchical schedule computes
    the SAME gradient sum as the flat all-reduce at the same layout, in a
    different association order. Documented tolerance: bit-exact on
    integer-valued grads, ~1e-7 relative on float ones (module docstring
    of parallel/hier_reduce.py) — rtol 1e-5 over two real optimizer
    steps leaves two orders of margin."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    def run(cfg):
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        step = make_train_step(cfg, menv)
        t = cfg.training
        toks = jax.random.randint(
            jax.random.key(7),
            (t.gradient_accumulation_steps,
             t.micro_batch_size * cfg.distributed.dp_size,
             t.seq_length + 1), 0, cfg.model.vocab_size)
        sh = NamedSharding(menv.mesh,
                           PartitionSpec(None, ("dp", "ep"), "cp"))
        batch = (jax.device_put(toks[..., :-1], sh),
                 jax.device_put(toks[..., 1:], sh))
        losses = []
        for _ in range(2):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    cfg = dp_cross_cfg()
    flat_cfg = dataclasses.replace(
        cfg, distributed=dataclasses.replace(cfg.distributed,
                                             hier_dp_reduce="off"))
    np.testing.assert_allclose(run(cfg), run(flat_cfg),
                               rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# per-slice MPMD stage placement + boundary DCN pricing
# ---------------------------------------------------------------------------


def test_stage_slice_placement_pp_cut():
    from picotron_tpu.parallel.mpmd import (
        check_stage_slice_placement, stage_slice_placement,
    )

    cfg = pp_cross_cfg()
    assert stage_slice_placement(cfg) == [0, 1]
    # the guard make_mpmd_train_step runs at build time passes
    assert check_stage_slice_placement(cfg) == [0, 1]


def test_stage_slice_placement_dp_cut_spans_legitimately():
    """Under a dp cut every pp device group contains both slices — that
    is the CORRECT layout (the hierarchical dp reduction inside the
    stage programs handles the cut), so placement reports None per group
    and the pure-pp guard stays quiet."""
    from picotron_tpu.parallel.mpmd import (
        check_stage_slice_placement, stage_slice_placement,
    )

    cfg = mkcfg(dist=dict(dp_size=2, pp_size=2, tp_size=2,
                          slices=2, dcn_axes="dp"),
                train=dict(gradient_accumulation_steps=2),
                pipe=dict(executor="mpmd"))
    assert stage_slice_placement(cfg) == [None, None]
    assert check_stage_slice_placement(cfg) == [None, None]


def test_check_stage_slice_placement_raises_on_spanning_group(monkeypatch):
    import picotron_tpu.parallel.mpmd as mpmd

    monkeypatch.setattr(mpmd, "stage_slice_placement",
                        lambda cfg: [0, None])
    with pytest.raises(RuntimeError, match="span"):
        mpmd.check_stage_slice_placement(pp_cross_cfg())


def test_boundary_dcn_traffic_priced():
    from picotron_tpu.analysis.cost_model import CostModel
    from picotron_tpu.parallel.mpmd import boundary_dcn_traffic

    cfg = pp_cross_cfg()
    out = boundary_dcn_traffic(cfg, cost_model=CostModel("v5e"))
    assert out["slices"] == 2 and out["placement"] == [0, 1]
    # 1f1b, pp=2, 2 microbatches: every F hop and every B hop crosses
    assert out["transfers"] == out["crossing"] == 4
    assert out["bytes_per_transfer"] > 0
    assert out["dcn_bytes"] == 4 * out["bytes_per_transfer"]
    assert out["dcn_secs"] > 0 and out["dcn_generation"] == "v5e"
    # single-slice twin: nothing crosses, nothing priced
    solo = mkcfg(dist=dict(pp_size=2, tp_size=2),
                 train=dict(gradient_accumulation_steps=2),
                 pipe=dict(executor="mpmd"))
    s = boundary_dcn_traffic(solo, cost_model=CostModel("v5e"))
    assert s["crossing"] == 0 and s["dcn_bytes"] == 0
    assert "dcn_secs" not in s
