"""slicecheck tests: the slice-boundary auditor (analysis/boundary.py),
the cost model's DCN tier, the multislice planner rows, and the static
schedule-table lint (parallel/mpmd.lint_schedule).

Protocol: the 8 simulated host devices are partitioned into 2 declared
"slices" (tests/conftest.py::slice_partition). The crossing presets pin
that every collective of a correctly declared layout lands in a tier
(intra-slice or declared-boundary, zero violating), a deliberately
mis-declared layout is caught with a named `ici-axis-over-dcn` error,
and the two hierarchical-decomposition mutations the issue names —
deleting the intra-slice reduce-scatter leg, widening the DCN all-reduce
group — each trip a named rule (the PR-15 mutation-test pattern)."""

import dataclasses

import pytest

from picotron_tpu.analysis.boundary import (
    SliceTopology, audit_boundary, classify_ops,
)
from picotron_tpu.analysis.collectives import parse_collectives
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, PipelineConfig, TrainingConfig,
    parse_dcn_axes, resolve_preset,
)


def mkcfg(model="debug-tiny", dist=None, train=None, pipe=None):
    cfg = Config(
        distributed=DistributedConfig(**(dist or {})),
        model=ModelConfig(name=model, **resolve_preset(model)),
        training=TrainingConfig(seq_length=64, micro_batch_size=1,
                                **(train or {})),
        pipeline=PipelineConfig(**(pipe or {})),
    )
    cfg.validate()
    return cfg


def dp_cross_cfg():
    return mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2,
                           slices=2, dcn_axes="dp"),
                 train=dict(gradient_accumulation_steps=2))


def pp_cross_cfg():
    return mkcfg(dist=dict(pp_size=2, tp_size=2, slices=2, dcn_axes="pp"),
                 train=dict(gradient_accumulation_steps=2),
                 pipe=dict(executor="mpmd"))


@pytest.fixture(scope="module")
def dp_cross_text():
    from picotron_tpu.analysis.trace import lower_train_step

    return lower_train_step(dp_cross_cfg()).text


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def test_parse_dcn_axes():
    assert parse_dcn_axes("dp,pp") == ("dp", "pp")
    assert parse_dcn_axes("pp,dp") == ("dp", "pp")  # dp-first order
    assert parse_dcn_axes("pp") == ("pp",)
    assert parse_dcn_axes("") == ()
    with pytest.raises(ValueError, match="tp"):
        parse_dcn_axes("dp,tp")  # ICI-only axis can never cross DCN
    with pytest.raises(ValueError, match="duplicate"):
        parse_dcn_axes("dp,dp")


def test_config_validates_slice_divisibility():
    with pytest.raises(ValueError, match="slices"):
        mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2, slices=3,
                        dcn_axes="dp"),
              train=dict(gradient_accumulation_steps=2))
    with pytest.raises(ValueError, match="dcn_axes"):
        mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2, slices=2,
                        dcn_axes=""),
              train=dict(gradient_accumulation_steps=2))


# ---------------------------------------------------------------------------
# SliceTopology: device -> slice mapping
# ---------------------------------------------------------------------------


def test_slice_topology_matches_session_partition(slice_partition):
    """The house rule (granule = OUTER factor of the first cut axis on
    the row-major grid) maps the 8 simulated devices exactly onto the
    session's positional 2-slice partition."""
    topo = SliceTopology.from_config(dp_cross_cfg())
    assert topo.n_slices == 2
    assert topo.declared == ("dp",)
    assert topo.cut_axes == ("dp",)
    for sl, ids in slice_partition.items():
        for d in ids:
            assert topo.slice_of(d) == sl, (d, sl)


def test_slice_topology_pp_cut():
    topo = SliceTopology.from_config(pp_cross_cfg())
    assert topo.cut_axes == ("pp",)
    # grid (dp=1, pp=2, ep=1, cp=1, tp=2 -> padded to 8 by dp? no: world=4)
    # row-major (dp, pp, ep, cp, tp): id = pp*2 + tp; pp coord is id // 2
    for d in range(4):
        assert topo.slice_of(d) == d // 2


def test_slice_topology_rejects_indivisible():
    cfg = dp_cross_cfg()
    with pytest.raises(ValueError):
        SliceTopology.from_config(cfg, n_slices=3)


# ---------------------------------------------------------------------------
# replica-group membership parsing
# ---------------------------------------------------------------------------


def test_parse_collectives_members(dp_cross_text):
    ops = parse_collectives(dp_cross_text)
    assert ops, "no collectives in the dp-cross lowering"
    with_members = [o for o in ops if o.members is not None]
    # the StableHLO dense<...> dialect carries explicit member lists:
    # membership must be recovered for (at least) every grouped op
    assert len(with_members) >= len(ops) - 2, (len(with_members), len(ops))
    for o in with_members:
        if o.kind == "collective_permute":
            assert all(len(pair) == 2 for pair in o.members)
        else:
            assert len(o.members) == o.n_groups
            assert all(len(g) == o.group_size for g in o.members)


# ---------------------------------------------------------------------------
# classification: crossing presets are green, mis-declaration is caught
# ---------------------------------------------------------------------------


def test_dp_cross_audit_green(dp_cross_text):
    cfg = dp_cross_cfg()
    rep = audit_boundary(cfg, text=dp_cross_text)
    assert rep.ok(), rep.render(verbose=True)
    info = rep.info["boundary"]
    assert info["audited"] and info["slices"] == 2
    assert info["violating"] == 0 and info["unattributable"] == 0
    assert info["boundary"] > 0, "dp crossers must exist"
    assert info["intra"] > 0, "tp/cp collectives must stay inside"
    assert info["dcn_bytes"] > 0 and info["ici_bytes"] > 0
    # every intra op carries zero DCN bytes and vice versa
    for row in info["table"]:
        if row["class"] == "intra":
            assert row["dcn_bytes"] == 0
        if row["class"] == "boundary":
            assert row["dcn_bytes"] > 0


def test_pp_cross_audit_green_via_shardcheck():
    from picotron_tpu.analysis import run_shardcheck

    rep = run_shardcheck(pp_cross_cfg())
    assert rep.ok(), rep.render(verbose=True)
    info = rep.info["boundary"]
    assert info["audited"] and info["violating"] == 0
    # the stage-boundary ppermutes are declared crossers
    kinds = {r["kind"] for r in info["table"] if r["class"] == "boundary"}
    assert "collective_permute" in kinds
    # satellite 1: the static schedule-table lint surfaces through the
    # variants info for MPMD configs
    lint = rep.info["variants"]["mpmd_stages"]["schedule_lint"]
    assert lint["proven"] and lint["problems"] == 0
    assert lint["kind"] == "1f1b" and lint["ops"] > 0


def test_misdeclared_axis_is_a_named_violation(dp_cross_text):
    """Declaring pp as the crossing axis while the house rule cuts dp
    routes every ICI-only dp collective over DCN — each one is a named
    `ici-axis-over-dcn` error and shardcheck goes red."""
    cfg = dp_cross_cfg()
    rep = audit_boundary(cfg, text=dp_cross_text, dcn_axes="pp")
    assert not rep.ok()
    errs = [f for f in rep.errors() if "ici-axis-over-dcn" in f.message]
    assert errs, rep.render(verbose=True)
    assert rep.info["boundary"]["violating"] == len(errs)
    assert rep.info["boundary"]["boundary"] == 0


def test_violation_names_the_minting_source():
    """Through the runner (which hands the auditor the full lowering),
    a violating op is attributed to the Python site that minted it —
    actionable where a bare StableHLO line number is not."""
    from picotron_tpu.analysis import run_shardcheck

    cfg = mkcfg(dist=dict(dp_size=2, pp_size=2, tp_size=2,
                          slices=2, dcn_axes="pp"),
                train=dict(gradient_accumulation_steps=2))
    rep = run_shardcheck(cfg, checks=("spec", "boundary"))
    errs = [f for f in rep.errors() if "ici-axis-over-dcn" in f.message]
    assert errs, rep.render(verbose=True)
    assert any("minted at" in f.message and ".py:" in f.message
               for f in errs), errs[0].message


def test_single_slice_is_a_no_op(dp_cross_text):
    cfg = mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2),
                train=dict(gradient_accumulation_steps=2))
    rep = audit_boundary(cfg, text=dp_cross_text)
    assert rep.ok()
    assert rep.info["boundary"] == {"slices": 1, "audited": False}


# ---------------------------------------------------------------------------
# hierarchical-decomposition mutations (the PR-15 pattern)
# ---------------------------------------------------------------------------

_GRAD_GROUPS = "[[0, 2, 4, 6], [1, 3, 5, 7]]"


def test_mutation_deleted_intra_scatter_leg(dp_cross_text):
    """Rewriting the fused-dp grad groups so each crossing group keeps a
    per-slice cohort of 1 deletes the intra-slice reduce-scatter leg of
    the hierarchical decomposition: full-width gradients would cross DCN
    instead of one shard per slice. Named rule: hier_intra_scatter."""
    assert _GRAD_GROUPS in dp_cross_text, \
        "lowering changed; update the mutation fixture"
    mutated = dp_cross_text.replace(
        _GRAD_GROUPS, "[[0, 4], [2, 6], [1, 5], [3, 7]]").replace(
        "tensor<2x4xi64>", "tensor<4x2xi64>")
    rep = audit_boundary(dp_cross_cfg(), text=mutated)
    assert not rep.ok()
    assert any(f.path == "hier_intra_scatter" for f in rep.errors()), \
        rep.render(verbose=True)


def test_mutation_widened_dcn_group(dp_cross_text):
    """Unbalancing a crossing group (3 members on one slice, 1 on the
    other) widens the DCN leg past one shard per slice. Named rule:
    hier_dcn_cohort."""
    mutated = dp_cross_text.replace(
        _GRAD_GROUPS, "[[0, 2, 4, 1], [6, 3, 5, 7]]")
    rep = audit_boundary(dp_cross_cfg(), text=mutated)
    assert not rep.ok()
    assert any(f.path == "hier_dcn_cohort" for f in rep.errors()), \
        rep.render(verbose=True)


# ---------------------------------------------------------------------------
# cost model: the dcn tier
# ---------------------------------------------------------------------------


def test_generations_carry_dcn_descriptors():
    from picotron_tpu.analysis.cost_model import Calibration, GENERATIONS

    for name, gen in GENERATIONS.items():
        assert gen.dcn_bandwidth > 0, name
        assert gen.dcn_alpha_s > 0, name
        # DCN is the slow tier by construction
        assert gen.dcn_bandwidth < gen.link_bandwidth, name
        assert gen.dcn_alpha_s > Calibration().alpha_link_s, name


def test_dcn_secs_prices_the_slow_tier():
    from picotron_tpu.analysis.cost_model import CostModel

    m = CostModel("v5e")
    small = m.dcn_secs("all_reduce", 1 << 20, 2)
    big = m.dcn_secs("all_reduce", 1 << 24, 2)
    assert 0 < small < big
    # same bytes over ICI are far cheaper than over DCN
    link = m.dcn_link(2)
    assert link.axis == "dcn" and link.size == 2
    assert link.bandwidth == m.gen.dcn_bandwidth


def test_split_slice_link():
    from picotron_tpu.analysis.cost_model import (
        AxisLink, GENERATIONS, split_slice_link,
    )

    gen = GENERATIONS["v5e"]
    parent = AxisLink("dp", 8, "ring", gen.link_bandwidth, 1)
    intra, dcn = split_slice_link(parent, 2, gen)
    assert intra.size == 4 and intra.axis == "dp"
    assert intra.bandwidth == parent.bandwidth
    assert dcn.size == 2 and dcn.bandwidth == gen.dcn_bandwidth


def test_slice_tiers_and_slice_plans():
    from picotron_tpu.analysis.cost_model import CostModel
    from picotron_tpu.analysis.planner import slice_plans

    cfg = mkcfg(dist=dict(dp_size=4, pp_size=2),
                train=dict(gradient_accumulation_steps=2))
    rows = slice_plans(cfg, CostModel("v5e"), n_slices=2)
    assert {r["axis"] for r in rows} == {"dp", "pp"}
    for r in rows:
        assert r["slices"] == 2 and r["generation"] == "v5e"
        assert r["dcn_bytes"] > 0 and r["dcn_ms"] > 0
        assert r["total_comm_ms"] > 0
        assert r["crossing_terms"], r
    # ranked by total comm, best first
    assert rows == sorted(rows, key=lambda r: r["total_comm_ms"])
    # no legal axis -> empty (tp cannot absorb slices)
    solo = mkcfg(dist=dict(tp_size=2))
    assert slice_plans(solo, CostModel("v5e"), n_slices=2) == []
    assert slice_plans(cfg, CostModel("v5e"), n_slices=1) == []


def test_audit_prices_tiers_with_cost_model(dp_cross_text):
    from picotron_tpu.analysis.cost_model import CostModel

    rep = audit_boundary(dp_cross_cfg(), text=dp_cross_text,
                         cost_model=CostModel("v5e"))
    info = rep.info["boundary"]
    assert info["dcn_generation"] == "v5e"
    assert info["dcn_ms"] > 0 and info["ici_ms"] > 0
    # the DCN leg dominates: slower wire, per-slice shards notwithstanding
    assert info["dcn_ms"] > info["ici_ms"]


# ---------------------------------------------------------------------------
# satellite 1: the static schedule-table lint
# ---------------------------------------------------------------------------


def test_lint_schedule_clean_tables():
    from picotron_tpu.parallel.mpmd import SCHEDULES, build_schedule

    # build_schedule lints at construction (raises ScheduleBufferError on
    # failure) — a representative sweep must come back clean
    for kind in SCHEDULES:
        for pp in (2, 4, 8):
            for n in (2, 8, 16):
                for v in (1, 2) if kind == "interleaved" else (1,):
                    build_schedule(kind, n, pp, v)


def test_lint_schedule_catches_truncated_table():
    from picotron_tpu.parallel.mpmd import build_schedule, lint_schedule

    table = build_schedule("1f1b", 4, 4, 1)
    truncated = [op for op in table if not (op.op == "B" and op.mb == 3)]
    problems = lint_schedule(truncated, 4, 4, 1, kind="1f1b")
    assert problems and any("never consumed" in p for p in problems)


def test_lint_schedule_catches_missing_producer():
    from picotron_tpu.parallel.mpmd import build_schedule, lint_schedule

    table = build_schedule("1f1b", 4, 4, 1)
    dropped = [op for op in table
               if not (op.op == "F" and op.mb == 2 and op.vstage == 1)]
    problems = lint_schedule(dropped, 4, 4, 1, kind="1f1b")
    assert problems and any("never produced" in p for p in problems)


def test_lint_schedule_catches_unbounded_live_set():
    from picotron_tpu.parallel.mpmd import build_schedule, lint_schedule

    # a gpipe table (save-everything) presented as 1f1b blows the
    # in-flight budget: backwards deferred past the pipeline depth
    table = build_schedule("gpipe", 16, 4, 1)
    problems = lint_schedule(table, 16, 4, 1, kind="1f1b")
    assert any("in-flight budget" in p for p in problems)


def test_build_schedule_raises_on_linted_table(monkeypatch):
    import picotron_tpu.parallel.mpmd as mpmd

    monkeypatch.setattr(mpmd, "lint_schedule",
                        lambda *a, **k: ["planted problem"])
    with pytest.raises(mpmd.ScheduleBufferError, match="static lint"):
        mpmd.build_schedule("1f1b", 4, 2, 1)
