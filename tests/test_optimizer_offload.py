"""optimizer_offload (ZeRO-Offload-style host-resident optimizer state).

The reference keeps all optimizer state in accelerator memory (its AdamW is
a CUDA kernel, ref: train.py:204-209); host offload is the TPU-memory lever
that fits full-depth SmolLM-1.7B's ~21 GB of fp32 master + grads + moments
on one 15.75 GB v5e chip (PERF.md round 4). These tests pin:

- exact update-math parity with the on-device optax chain (offload changes
  WHERE state lives, not what the update computes),
- end-to-end loss parity with the fp32-master baseline (tolerance = the
  bf16 per-microbatch grads, the standard mixed-precision arrangement),
- the streamed (sliced-scan + barrier-chained) update structure,
- checkpoint save/restore and external param installation.

On the CPU test mesh the memory placement is a no-op (offload_memory_kind
returns None — CPU "device" memory IS host RAM); the real pinned_host
placement is exercised by `pytest -m tpu` (test_tpu_hw.py) and bench.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig,
)
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.optimizer import (
    OffloadAdamState, make_optimizer, offload_adam_update,
)
from picotron_tpu.parallel.api import (
    init_sharded_state, install_params, make_train_step,
)
from picotron_tpu.parallel.sharding import param_shardings


def offload_cfg(offload=True, **tr) -> Config:
    tr.setdefault("seq_length", 32)
    tr.setdefault("micro_batch_size", 2)
    tr.setdefault("gradient_accumulation_steps", 2)
    tr.setdefault("adam_moments_dtype", "bfloat16")
    tr.setdefault("remat", False)
    return Config(
        distributed=DistributedConfig(dp_size=2, tp_size=2),
        model=ModelConfig(num_attention_heads=8, num_key_value_heads=4,
                          num_hidden_layers=4),
        training=TrainingConfig(optimizer_offload=offload, **tr),
    )


def batch_for(cfg, key=1):
    t = cfg.training
    b = t.micro_batch_size * cfg.distributed.dp_size
    toks = jax.random.randint(
        jax.random.key(key),
        (t.gradient_accumulation_steps, b, t.seq_length + 1),
        0, cfg.model.vocab_size)
    menv = MeshEnv.from_config(cfg)
    sh = menv.batch_sharding()
    return (jax.device_put(toks[..., :-1], sh),
            jax.device_put(toks[..., 1:], sh)), menv


def run_steps(cfg, steps=4):
    batch, menv = batch_for(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    losses = []
    for _ in range(steps):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses, state, menv


def test_state_layout():
    cfg = offload_cfg()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    assert isinstance(state.opt_state, OffloadAdamState)
    p0 = jax.tree.leaves(state.params)[0]
    assert p0.dtype == jnp.bfloat16  # device compute copy
    assert jax.tree.leaves(state.opt_state.master)[0].dtype == jnp.float32
    assert jax.tree.leaves(state.opt_state.mu)[0].dtype == jnp.bfloat16
    # the compute copy IS the cast of the master
    m0 = jax.tree.leaves(state.opt_state.master)[0]
    np.testing.assert_array_equal(np.asarray(p0),
                                  np.asarray(m0.astype(jnp.bfloat16)))


def test_abstract_state_matches_concrete():
    cfg = offload_cfg()
    menv = MeshEnv.from_config(cfg)
    concrete = init_sharded_state(cfg, menv, jax.random.key(0))
    abstract = init_sharded_state(cfg, menv, jax.random.key(0),
                                  abstract=True)
    c_flat, c_def = jax.tree.flatten(concrete)
    a_flat, a_def = jax.tree.flatten(abstract)
    assert c_def == a_def
    for c, a in zip(c_flat, a_flat):
        assert c.shape == a.shape and c.dtype == a.dtype


@pytest.mark.slow
def test_loss_parity_with_baseline():
    """Offload must track the fp32-master baseline: identical first step
    (same bf16 forward), then drift bounded by the bf16 per-microbatch
    grads."""
    l_base, _, _ = run_steps(offload_cfg(offload=False))
    l_off, _, _ = run_steps(offload_cfg(offload=True))
    assert l_base[0] == pytest.approx(l_off[0], abs=1e-6)
    for a, b in zip(l_base, l_off):
        assert a == pytest.approx(b, abs=5e-3)
    assert l_off[-1] < l_off[0]  # it optimizes


def test_loss_parity_single_microbatch():
    """ga=1 takes the direct (no-accumulation-scan) path — on the dp2xtp2
    mesh its grads must fp32-promote BEFORE the data-axes psum, tracking
    the baseline exactly like the scan path does (code review r5)."""
    l_base, _, _ = run_steps(offload_cfg(offload=False,
                                         gradient_accumulation_steps=1))
    l_off, _, _ = run_steps(offload_cfg(offload=True,
                                        gradient_accumulation_steps=1))
    assert l_base[0] == pytest.approx(l_off[0], abs=1e-6)
    for a, b in zip(l_base, l_off):
        assert a == pytest.approx(b, abs=5e-3)


def test_update_math_matches_optax_chain():
    """Given identical fp32 grads, the streamed AdamW must reproduce the
    on-device optax chain (clip -> bf16-moment adam -> weight decay -> lr)
    bit-for-bit up to float associativity."""
    t = TrainingConfig(learning_rate=3e-3, weight_decay=0.01,
                       grad_clip_norm=1.0, adam_moments_dtype="bfloat16",
                       lr_schedule="cosine", lr_warmup_steps=2,
                       total_train_steps=10)
    key = jax.random.key(7)
    params = {"a": jax.random.normal(key, (8, 16)),
              "b": {"c": jax.random.normal(key, (4,)) * 3}}
    opt = make_optimizer(t)
    opt_state = opt.init(params)

    off_state = OffloadAdamState(
        count=jnp.zeros((), jnp.int32),
        master=params,
        mu=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.bfloat16), params),
        nu=jax.tree.map(lambda p: jnp.zeros_like(p, jnp.bfloat16), params))

    p_ref = params
    for i in range(3):
        grads = jax.tree.map(
            lambda p: jax.random.normal(jax.random.key(100 + i), p.shape),
            p_ref)
        updates, opt_state = opt.update(grads, opt_state, p_ref)
        p_ref = optax.apply_updates(p_ref, updates)
        compute, off_state = offload_adam_update(
            grads, off_state, t, jnp.bfloat16, transfer=False)
    for r, o in zip(jax.tree.leaves(p_ref),
                    jax.tree.leaves(off_state.master)):
        np.testing.assert_allclose(np.asarray(r), np.asarray(o),
                                   rtol=1e-6, atol=1e-7)
    # the emitted compute copy is the bf16 cast of the new master
    for c, o in zip(jax.tree.leaves(compute),
                    jax.tree.leaves(off_state.master)):
        np.testing.assert_array_equal(
            np.asarray(c), np.asarray(o.astype(jnp.bfloat16)))


def test_grad_scale_folds_into_update():
    """update(grads, scale=s) == update(grads * s) — the fold-in exists so
    the caller never materializes a divided grad tree (PERF.md r4)."""
    t = TrainingConfig(learning_rate=1e-2, grad_clip_norm=0.5)
    params = {"w": jnp.arange(12.0).reshape(3, 4) / 10}
    zeros = jax.tree.map(jnp.zeros_like, params)

    def fresh():
        return OffloadAdamState(count=jnp.zeros((), jnp.int32),
                                master=params, mu=zeros, nu=zeros)

    grads = {"w": jnp.ones((3, 4)) * 8.0}
    _, s1 = offload_adam_update(grads, fresh(), t, jnp.bfloat16,
                                transfer=False, grad_scale=0.25)
    _, s2 = offload_adam_update(jax.tree.map(lambda g: g * 0.25, grads),
                                fresh(), t, jnp.bfloat16, transfer=False)
    np.testing.assert_allclose(np.asarray(s1.master["w"]),
                               np.asarray(s2.master["w"]), rtol=1e-6)


def test_streamed_update_structure(monkeypatch):
    """Exercise the sliced-scan + barrier-chain code path (memory kinds are
    placement no-ops on CPU, but the scan/reshape/barrier structure must
    compile and produce the same numbers as the plain path)."""
    import picotron_tpu.optimizer as opt_mod

    # force streaming: tiny slice floor + tiny row-group target so "big"
    # takes leaf_scanned and "wide" takes leaf_scanned_rows (groups of
    # 64/8-byte-rows = 8 rows -> 256 scan iterations + reshape reassembly)
    monkeypatch.setattr(opt_mod, "_OFFLOAD_MIN_SLICE_BYTES", 4)
    monkeypatch.setattr(opt_mod, "_OFFLOAD_ROW_GROUP_BYTES", 64)
    t = TrainingConfig(learning_rate=1e-2, adam_moments_dtype="bfloat16")
    # three leaves, one per streaming path: "big" -> leaf_scanned (axis-0
    # layer slices), "wide" -> leaf_scanned_rows (axis 0 > 1024, row
    # groups + reshape reassembly), "small" -> leaf_whole
    # big/big2 share a depth (one fused group of TWO members — their
    # distinct values catch a member-order swap in the group scatter),
    # big3 has its own depth (a second, single-member group)
    params = {"big": jnp.arange(24 * 64, dtype=jnp.float32).reshape(24, 64)
              / 512,
              "big2": -jnp.arange(24 * 64, dtype=jnp.float32).reshape(24, 64)
              / 1024,
              "big3": jnp.arange(12 * 64, dtype=jnp.float32).reshape(12, 64)
              / 256,
              "wide": jnp.arange(2048 * 2, dtype=jnp.float32).reshape(
                  2048, 2) / 4096,
              "small": jnp.ones((4,))}
    zeros_b = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.bfloat16), params)
    state = OffloadAdamState(count=jnp.zeros((), jnp.int32), master=params,
                             mu=zeros_b, nu=zeros_b)
    grads = jax.tree.map(jnp.ones_like, params)

    @jax.jit
    def run(grads, state):
        return offload_adam_update(grads, state, t, jnp.bfloat16,
                                   transfer=True)

    compute, new_state = run(grads, state)
    _, plain = offload_adam_update(grads, state, t, jnp.bfloat16,
                                   transfer=False)
    for a, b in zip(jax.tree.leaves(new_state.master),
                    jax.tree.leaves(plain.master)):
        # atol: XLA fuses sqrt/div differently inside the scan body than in
        # the flat path — bounded float associativity, not a math change
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
    assert compute["big"].dtype == jnp.bfloat16


@pytest.mark.slow
def test_checkpoint_roundtrip(tmp_path):
    from picotron_tpu.checkpoint import CheckpointManager

    cfg = offload_cfg()
    cfg = dataclasses.replace(
        cfg, checkpoint=dataclasses.replace(cfg.checkpoint,
                                            save_dir=str(tmp_path),
                                            async_save=False))
    losses, state, menv = run_steps(cfg, steps=2)
    mgr = CheckpointManager(cfg, menv)
    mgr.save(state, trained_tokens=123)
    mgr.wait_until_finished()

    fresh = init_sharded_state(cfg, menv, jax.random.key(9))
    restored, meta = mgr.restore(fresh)
    assert meta["trained_tokens"] == 123
    assert int(restored.opt_state.count) == int(state.opt_state.count)
    for a, b in zip(jax.tree.leaves(restored.opt_state.master),
                    jax.tree.leaves(state.opt_state.master)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the restored state must be trainable (shardings/dtypes all line up)
    batch, _ = batch_for(cfg)
    step = make_train_step(cfg, menv)
    _, m = step(restored, batch)
    assert np.isfinite(float(m["loss"]))


def test_restore_params_only_returns_fp32_master(tmp_path):
    """export/decode restores from an offload checkpoint must get the fp32
    MASTER, not the bf16 compute copy — exporting bf16-rounded weights in
    fp32 containers would be a silent permanent precision loss (code
    review r4)."""
    from picotron_tpu.checkpoint import CheckpointManager, restore_params_only

    cfg = offload_cfg()
    cfg = dataclasses.replace(
        cfg, checkpoint=dataclasses.replace(cfg.checkpoint,
                                            save_dir=str(tmp_path),
                                            async_save=False))
    _, state, menv = run_steps(cfg, steps=1)
    CheckpointManager(cfg, menv).save(state)

    params, step = restore_params_only(cfg, str(tmp_path))
    ref = jax.tree.leaves(state.opt_state.master)[0]
    got = jax.tree.leaves(params)[0]
    assert got.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    # the master is NOT representable in bf16 after one update — restoring
    # the compute copy instead would fail this
    assert not np.array_equal(
        np.asarray(ref),
        np.asarray(ref.astype(jnp.bfloat16).astype(jnp.float32)))


def test_install_params_fills_master_and_compute():
    cfg = offload_cfg()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    new = jax.tree.map(lambda p: jnp.full(p.shape, 0.125, jnp.float32),
                       state.opt_state.master)
    state2 = install_params(cfg, menv, state, new)
    assert jax.tree.leaves(state2.params)[0].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(state2.opt_state.master)[0]),
        np.asarray(jax.tree.leaves(new)[0]))


def test_offload_rejects_fp32_compute():
    with pytest.raises(ValueError, match="bfloat16"):
        Config(
            distributed=DistributedConfig(),
            model=ModelConfig(dtype="float32"),
            training=TrainingConfig(optimizer_offload=True),
        ).validate()
    # afab accumulates param cotangents in the bf16 param dtype (ADVICE r4)
    with pytest.raises(ValueError, match="afab"):
        Config(
            distributed=DistributedConfig(pp_size=2, pp_engine="afab"),
            model=ModelConfig(),
            training=TrainingConfig(optimizer_offload=True),
        ).validate()


def test_zero1_composition_parity():
    """offload x zero1 (VERDICT r4 #3): each process streams 1/dp of the
    host state and the update all-gathers the refreshed bf16 params —
    losses and the (re-assembled) master must match plain offload
    exactly; zero1 changes WHICH process updates an element, never the
    math."""
    base = offload_cfg(offload=True, gradient_accumulation_steps=2)
    z1 = dataclasses.replace(
        base, distributed=dataclasses.replace(base.distributed, zero1=True))
    l_base, s_base, _ = run_steps(base)
    l_z1, s_z1, _ = run_steps(z1)
    np.testing.assert_allclose(l_z1, l_base, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s_base.opt_state.master),
                    jax.tree.leaves(s_z1.opt_state.master)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_zero1_composition_shards_host_state():
    """The zero1-extended host shardings must actually shard the master
    over dp (the memory claim), while params stay full-size."""
    base = offload_cfg(offload=True)
    z1 = dataclasses.replace(
        base, distributed=dataclasses.replace(base.distributed, zero1=True))
    s_base = init_sharded_state(base, MeshEnv.from_config(base),
                                jax.random.key(0))
    s_z1 = init_sharded_state(z1, MeshEnv.from_config(z1),
                              jax.random.key(0))

    def shard_bytes(tree):
        total = 0
        for leaf in jax.tree.leaves(tree):
            shard = leaf.addressable_shards[0].data
            total += shard.size * shard.dtype.itemsize
        return total

    # dp=2: the per-device master shard halves for the shardable leaves
    # (dp x on the big matrices; norms stay replicated)
    assert shard_bytes(s_z1.opt_state.master) < \
        0.75 * shard_bytes(s_base.opt_state.master)
    assert shard_bytes(s_z1.params) == shard_bytes(s_base.params)


def test_zero1_composition_with_grad_clip():
    """The clip consumes the FULL grad tree before the zero1 slice — the
    scale must be identical on every shard (a sliced norm would diverge
    per process and desynchronize the replicas). Slim shape (ga=1,
    2 steps): the clip interaction is per-update, not per-microbatch."""
    base = offload_cfg(offload=True, grad_clip_norm=0.05,
                       gradient_accumulation_steps=1)
    z1 = dataclasses.replace(
        base, distributed=dataclasses.replace(base.distributed, zero1=True))
    l_base, _, _ = run_steps(base, steps=2)
    l_z1, _, _ = run_steps(z1, steps=2)
    np.testing.assert_allclose(l_z1, l_base, rtol=1e-6)


def test_offload_pp_parity():
    """offload x pp (VERDICT r4 #4): the per-vma-class update token chains
    meet pp-sharded stacked leaves and the 1F1B manual-VJP grad path —
    losses must track the non-offload pp baseline step for step."""
    base = offload_cfg(offload=False, gradient_accumulation_steps=4)
    base = dataclasses.replace(
        base, distributed=DistributedConfig(dp_size=2, pp_size=2,
                                            pp_engine="1f1b", tp_size=2))
    off = dataclasses.replace(
        base, training=dataclasses.replace(base.training,
                                           optimizer_offload=True))
    l_base, _, _ = run_steps(base)
    l_off, s_off, _ = run_steps(off)
    assert l_base[0] == pytest.approx(l_off[0], abs=1e-6)
    for a, b in zip(l_base, l_off):
        assert a == pytest.approx(b, abs=5e-3)
    assert l_off[-1] < l_off[0]
    # pp shards the stacked layer leaves: each device's master shard must
    # hold layers/pp of the stack
    stacked = s_off.opt_state.master["layers"]["q"]
    assert stacked.addressable_shards[0].data.shape[0] == \
        stacked.shape[0] // 2
