"""Serving stack tests (picotron_tpu/serve): paged-vs-contiguous greedy
parity, ragged-batch invariance, block-pool accounting, scheduler
admission/preemption, the full queue -> chunked prefill -> continuous
decode -> retirement loop with telemetry, single-compile decode, and the
bench --serve structural comparison against the batch-static sampler."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.config import ModelConfig, ServeConfig, resolve_preset
from picotron_tpu.generate import generate
from picotron_tpu.models.llama import init_params
from picotron_tpu.serve import BlockPool, Request, Scheduler, ServeEngine
from picotron_tpu.serve.scheduler import blocks_for

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny"), "max_position_embeddings": 64})
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def requests5(tiny):
    cfg, _ = tiny
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(0, cfg.vocab_size, size=n)))
               for n in (5, 9, 3, 7, 11)]
    return list(zip(prompts, [6, 3, 8, 5, 4]))


@pytest.fixture(scope="module")
def offline_refs(tiny, requests5):
    """Per-request greedy tokens from the offline contiguous-cache path —
    the parity oracle for every engine configuration."""
    cfg, params = tiny
    return [
        np.asarray(generate(params, cfg, jnp.asarray([p], jnp.int32),
                            n))[0, len(p):].tolist()
        for p, n in requests5
    ]


def scfg(**kw):
    base = dict(decode_slots=3, block_size=4, num_blocks=24,
                prefill_chunk=4, max_model_len=32, decode_interval=3)
    base.update(kw)
    return ServeConfig(**base)


def run_engine(params, cfg, serve_cfg, requests, **kw):
    eng = ServeEngine(params, cfg, serve_cfg, **kw)
    res = eng.run(requests)
    eng.close()
    return eng, res


# ---------------------------------------------------------------------------
# block pool
# ---------------------------------------------------------------------------


def test_block_pool_accounting():
    pool = BlockPool(6)
    a = pool.alloc(4)
    assert len(a) == 4 and pool.in_use == 4 and pool.free_blocks == 2
    assert pool.alloc(3) is None and pool.in_use == 4  # all-or-nothing
    b = pool.alloc(2)
    assert pool.in_use == 6 and pool.peak_in_use == 6
    pool.free(a)
    assert pool.free_blocks == 4
    with pytest.raises(ValueError):
        pool.free(a[:1])  # double free
    with pytest.raises(ValueError):
        pool.free([99])
    pool.free(b)
    assert pool.in_use == 0 and pool.peak_in_use == 6


# ---------------------------------------------------------------------------
# scheduler (pure host logic)
# ---------------------------------------------------------------------------


def make_sched(slots=2, blocks=8, bs=4, max_blocks=8):
    return Scheduler(slots, BlockPool(blocks), bs, max_blocks)


def test_admission_is_fifo_and_block_budgeted():
    s = make_sched(slots=2, blocks=3, bs=4)
    s.submit(Request(0, (1,) * 8, 4))   # needs 2 blocks
    s.submit(Request(1, (1,) * 4, 4))   # needs 1 block
    s.submit(Request(2, (1,) * 4, 4))
    admitted = s.admit()
    # head-of-line: 0 then 1 fill the pool (3 blocks); 2 must wait even
    # though a slot... both slots taken too
    assert [st.req.id for _, st in admitted] == [0, 1]
    assert s.pool.free_blocks == 0
    assert [st.req.id for st in s.queue] == [2]
    # retiring 1 frees its slot + block; 2 admits
    st1 = next(st for _, st in admitted if st.req.id == 1)
    st1.generated.append(5)
    slot1 = s.slots.index(st1)
    s.retire(slot1)
    assert [st.req.id for _, st in s.admit()] == [2]


def test_head_of_line_blocks_admission():
    s = make_sched(slots=2, blocks=3, bs=4)
    s.submit(Request(0, (1,) * 8, 4))   # admission needs 2 blocks
    s.submit(Request(1, (1,) * 8, 4))   # needs 2, only 1 left
    s.submit(Request(2, (1,) * 4, 4))   # needs 1: would fit, but FIFO
    assert [st.req.id for _, st in s.admit()] == [0]
    assert [st.req.id for st in s.queue] == [1, 2]  # no queue jumping


def test_submit_rejects_unservable_request():
    s = make_sched(slots=1, blocks=4, bs=4, max_blocks=4)
    with pytest.raises(ValueError):  # capacity: 5 blocks > table width
        s.submit(Request(0, (1,) * 16, 8))
    s2 = make_sched(slots=1, blocks=2, bs=4, max_blocks=8)
    with pytest.raises(ValueError):  # pool: needs 3 of 2 blocks
        s2.submit(Request(0, (1,) * 8, 4))
    with pytest.raises(ValueError):
        s.submit(Request(1, (), 4))  # empty prompt


def test_preemption_youngest_first_and_requeue_front():
    s = make_sched(slots=2, blocks=4, bs=2)
    s.submit(Request(0, (1, 2, 3), 4))
    s.submit(Request(1, (4, 5, 6), 4))
    s.admit()  # 2 blocks each: pool drained
    assert s.pool.free_blocks == 0
    for slot in (0, 1):
        st = s.slots[slot]
        st.n_prefilled = len(st.prefill_ids)
        st.generated.append(7)
    # slot 0 (oldest) needs a block for its next tokens; pool is empty ->
    # the YOUNGEST (slot 1) is preempted and requeued at the front
    ok, preempted = s.ensure_block(0, horizon=2)
    assert ok and preempted == [1]
    assert s.slots[1] is None
    assert [st.req.id for st in s.queue] == [1]
    assert s.queue[0].generated == [7]  # recompute keeps generated tokens
    assert s.queue[0].blocks == [] and s.pool.free_blocks == 1
    assert s.n_preempted == 1


def test_preemption_single_request_pool_too_small_raises():
    """submit() rejects any request that cannot fit the pool alone, so
    the exhausted-with-one-live-request state is unreachable through the
    public API — the RuntimeError guard is defense-in-depth, covered by
    injecting the state directly."""
    from picotron_tpu.serve.scheduler import RequestState

    s = make_sched(slots=1, blocks=1, bs=2, max_blocks=8)
    st = RequestState(Request(0, (1, 2), 8))
    st.prefill_ids = st.req.prompt
    st.n_prefilled = 2
    st.blocks = s.pool.alloc(1)
    st.generated.extend([3, 4])
    s.slots[0] = st
    with pytest.raises(RuntimeError):
        s.ensure_block(0, horizon=2)


# ---------------------------------------------------------------------------
# engine: paged-vs-contiguous greedy parity
# ---------------------------------------------------------------------------


def test_engine_greedy_parity_vs_offline(tiny, requests5, offline_refs):
    """The full serve loop (chunked prefill + continuous paged decode)
    must emit bit-identical greedy tokens to the offline contiguous-cache
    generate, for every request in a mixed-length trace."""
    cfg, params = tiny
    eng, res = run_engine(params, cfg, scfg(), requests5)
    for r, ref in zip(res, offline_refs):
        assert r["tokens"] == ref
    assert eng.summary["requests"] == len(requests5)


def test_engine_greedy_parity_interval_1(tiny, requests5, offline_refs):
    cfg, params = tiny
    _, res = run_engine(params, cfg, scfg(decode_interval=1), requests5)
    for r, ref in zip(res, offline_refs):
        assert r["tokens"] == ref


def test_engine_parity_under_preemption(tiny, requests5, offline_refs):
    """A pool too small for the full trace forces preemption + recompute
    mid-decode; tokens must not change, and every block must return to
    the pool."""
    cfg, params = tiny
    eng, res = run_engine(params, cfg, scfg(num_blocks=8), requests5)
    assert eng.sched.n_preempted > 0
    for r, ref in zip(res, offline_refs):
        assert r["tokens"] == ref
    assert eng.pool.in_use == 0 and eng.pool.free_blocks == 8


def test_engine_tp_sharded_parity(tiny, requests5, offline_refs):
    """place_for_decode(tp=2) params through the serve engine: pure
    GSPMD, XLA shards the block pool over the kv-head axis — greedy
    tokens must match the single-device offline reference."""
    from picotron_tpu.generate import place_for_decode

    cfg, params = tiny
    sharded = place_for_decode(params, cfg, tp=2)
    assert any(len(x.sharding.device_set) == 2
               for x in jax.tree.leaves(sharded))
    _, res = run_engine(sharded, cfg, scfg(), requests5)
    for r, ref in zip(res, offline_refs):
        assert r["tokens"] == ref


def test_eos_retires_early_and_matches_generate(tiny, requests5):
    """EOS mid-stream: the engine's output must equal the offline path's
    (tokens up to and including the first EOS) and the slot must retire
    without burning the remaining budget."""
    cfg, params = tiny
    prompt, _ = requests5[0]
    full = np.asarray(generate(params, cfg, jnp.asarray([prompt],
                                                       jnp.int32), 8))
    eos = int(full[0, len(prompt) + 2])  # 3rd generated token as EOS
    ref = np.asarray(generate(params, cfg, jnp.asarray([prompt],
                                                       jnp.int32), 8,
                              eos_token_id=eos))[0, len(prompt):]
    ref = list(ref[:list(ref).index(eos) + 1]) if eos in ref else list(ref)
    _, res = run_engine(params, cfg, scfg(), [(prompt, 8)],
                        eos_token_id=eos)
    assert res[0]["tokens"] == ref
    assert res[0]["tokens"][-1] == eos


# ---------------------------------------------------------------------------
# ragged-batch invariance
# ---------------------------------------------------------------------------


def test_ragged_invariance_slot_count_and_order(tiny, requests5,
                                                offline_refs):
    """Emitted tokens are a function of the request alone: slot count,
    submission order, and which requests share the batch must never
    change them (per-slot positions + per-slot block tables + per-request
    sampling keys)."""
    cfg, params = tiny
    for slots in (1, 2, 4):
        _, res = run_engine(params, cfg, scfg(decode_slots=slots),
                            requests5)
        for r, ref in zip(res, offline_refs):
            assert r["tokens"] == ref, f"slots={slots}"
    # reversed submission order (ids pinned so results key back)
    eng = ServeEngine(params, cfg, scfg())
    for i in reversed(range(len(requests5))):
        eng.submit(requests5[i][0], requests5[i][1], req_id=i)
    while eng.sched.has_work():
        eng.step()
    eng.close()
    by_id = {r["id"]: r["tokens"] for r in eng.results}
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref


def test_sampling_order_invariance(tiny, requests5):
    """Temperature sampling keys derive from (request id, token index):
    shuffling submission order must reproduce identical tokens per id."""
    cfg, params = tiny
    outs = []
    for order in (range(4), reversed(range(4))):
        eng = ServeEngine(params, cfg, scfg(decode_slots=2,
                                            decode_interval=2),
                          temperature=0.8, top_k=5, seed=7)
        for i in order:
            eng.submit(requests5[i][0], requests5[i][1], req_id=i)
        while eng.sched.has_work():
            eng.step()
        eng.close()
        outs.append({r["id"]: r["tokens"] for r in eng.results})
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# memory: pool scales with blocks, accounting is leak-free
# ---------------------------------------------------------------------------


def test_cache_memory_scales_with_blocks_not_batch_x_maxlen(tiny):
    """The paged pool's persistent cache memory is num_blocks *
    block_size token-slots — an OVERSUBSCRIBED pool (fewer slots than
    decode_slots x max_model_len would need) must be exactly what gets
    allocated, which is the memory the contiguous cache cannot avoid."""
    cfg, params = tiny
    sc = scfg(decode_slots=3, num_blocks=9)  # 36 token-slots
    eng = ServeEngine(params, cfg, sc)
    contiguous_equiv = sc.decode_slots * blocks_for(32, sc.block_size)
    assert eng._k.shape[1] == 9 < contiguous_equiv
    # 3 slots x 32 max_model_len would be 96 token-slots; the pool holds 36
    assert eng._k.shape[1] * eng._k.shape[2] == 36
    eng.close()


def test_pool_accounting_over_full_trace(tiny, requests5):
    """Alloc/free across admission, decode growth, and retirement: peak
    matches live sequences' block need, and a drained trace leaves the
    pool exactly full — no leak, no double free."""
    cfg, params = tiny
    eng, res = run_engine(params, cfg, scfg(), requests5)
    assert eng.pool.in_use == 0
    assert eng.pool.free_blocks == eng.pool.num_blocks
    # peak is bounded by what the live sequences could ever need, and
    # nonzero because sequences really allocated
    worst = sum(blocks_for(len(p) + n, 4) for p, n in requests5)
    assert 0 < eng.pool.peak_in_use <= worst


# ---------------------------------------------------------------------------
# compile discipline
# ---------------------------------------------------------------------------


def test_single_decode_compile_across_multi_request_trace(tiny, requests5,
                                                          offline_refs):
    """One decode-step compile for the whole continuous-batching
    lifetime: admissions, retirements, ragged lengths, and block-table
    growth are data, not shapes. decode_slots=5 is unique to this test so
    the jit cache cannot hide a second compile behind another test's."""
    cfg, params = tiny
    eng, res = run_engine(params, cfg, scfg(decode_slots=5), requests5)
    assert eng.summary["decode_compiles"] == 1
    assert eng.summary["requests"] == len(requests5)
    for r, ref in zip(res, offline_refs):
        assert r["tokens"] == ref


# ---------------------------------------------------------------------------
# full loop smoke + telemetry report
# ---------------------------------------------------------------------------


def test_serve_loop_telemetry_and_report(tiny, requests5, tmp_path):
    """Tier-1 CPU smoke for the whole serving story: queue -> chunked
    prefill -> continuous decode -> retirement, with the JSONL stream
    carrying queue_wait/prefill/decode bookings, serve_request events,
    and a serve_summary — and tools/telemetry_report.py rendering the
    serving view from it."""
    from picotron_tpu.telemetry import JsonlSink, Telemetry

    cfg, params = tiny
    path = str(tmp_path / "telemetry.jsonl")
    tel = Telemetry(sinks=[JsonlSink(path)])
    eng = ServeEngine(params, cfg, scfg(), telemetry=tel)
    res = eng.run(requests5)
    tel.close()
    assert len(res) == len(requests5)

    events = [json.loads(line) for line in open(path)]
    kinds = {e["kind"] for e in events}
    assert {"serve_request", "serve_summary", "phase"} <= kinds
    cats = {e.get("category") for e in events if e["kind"] == "phase"}
    assert {"queue_wait", "prefill", "decode"} <= cats
    reqs = [e for e in events if e["kind"] == "serve_request"]
    assert len(reqs) == len(requests5)
    assert all(e["ttft_s"] >= 0 for e in reqs)

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import telemetry_report

    s = telemetry_report.summarize(events)
    sv = s["serving"]
    assert sv["requests"] == len(requests5)
    assert sv["output_tokens"] == sum(n for _, n in requests5)
    assert sv["ttft_p50_ms"] >= 0 and sv["ttft_p95_ms"] >= sv["ttft_p50_ms"]
    assert 0 < sv["slot_occupancy"] <= 1
    assert 0 < sv["pool_peak_utilization"] <= 1
    # goodput: prefill + decode book as productive serving time
    assert s["goodput_pct"] is not None and s["goodput_pct"] > 0
    text = telemetry_report.render(s)
    assert "serving:" in text and "TTFT" in text
    md = telemetry_report.render(s, markdown=True)
    assert "### Serving" in md


def test_serve_summary_slot_occupancy_and_queue(tiny):
    """More requests than slots: the queue holds the overflow (nonzero
    queue_wait) and slot occupancy stays high while the batch refills
    mid-flight."""
    cfg, params = tiny
    rng = np.random.default_rng(3)
    reqs = [(list(map(int, rng.integers(0, cfg.vocab_size, size=4))), 6)
            for _ in range(6)]
    eng, res = run_engine(params, cfg, scfg(decode_slots=2), reqs)
    assert len(res) == 6
    assert eng.summary["slot_occupancy"] > 0.5
    assert eng.sched.n_admitted == 6


# ---------------------------------------------------------------------------
# bench --serve: structural comparison vs the batch-static sampler
# ---------------------------------------------------------------------------


def _bench_serve_row(**kw):
    import bench

    args = dict(slots=4, block_size=8, num_blocks=0, prefill_chunk=32,
                prompt_len=32, max_new=96, n_requests=24, rate=0.0,
                decode_interval=6, seed=0, repeats=1)
    args.update(kw)
    return bench.run_serve("debug-tiny", 4, **args)


def test_bench_serve_structural_beats_static():
    """The HEADLINE metric is now the deterministic structural ratio
    static_decode_slot_steps / decode_slot_steps — continuous batching
    on a mixed-length trace must burn strictly fewer decode slot-steps
    than the batch-static sampler (which decodes the trace max for every
    batch), identically on every host. Wall-clock is demoted to a
    median-of-repeats sanity field carrying the CPU noise caveat (the
    KNOWN 0.85-1.19 swing is load noise, not a result)."""
    row = _bench_serve_row(n_requests=12, max_new=48)
    assert row["unit"] == "static_over_serve_decode_slot_steps"
    assert row["value"] > 1.0  # structural win, deterministic
    assert row["decode_slot_steps"] < row["static_decode_slot_steps"]
    assert row["serve_tokens_per_sec"] > 0
    # the ratio is the structural win; wall-clock realizes it modulo
    # dispatch overhead + host noise (10-20x swings documented on this
    # host, PERF.md r4) — bound it loosely rather than flakily
    assert row["vs_static"] > 0.4
    assert row["decode_compiles"] == 0  # warmed by the warm-trace engine
    assert row["ttft_p50_ms"] is not None
    assert row["preemptions"] == 0
    assert "noisy" in row["wall_note"]
    assert len(row["serve_walls_s"]) == row["wall_repeats"] == 1


@pytest.mark.slow
def test_bench_serve_wall_clock_vs_static():
    """Wall-clock tokens/s vs the static sampler, median-of-repeats per
    seed and best-of-3 seeds against host-load noise (the
    max-over-attempts idiom bench --sweep uses, ADVICE r4). At
    debug-tiny scale on a shared CPU the per-dispatch penalty (~1.3x a
    monolithic-scan step) roughly cancels the structural step win, so
    observed ratios sit at parity, 0.9-1.2 across repeated runs
    (PERF.md r7) — the assert pins "no dispatch regression" (>0.85)
    plus the deterministic >=1.4x structural step ratio; the
    unambiguous wall-clock beat is the TPU protocol row in PERF.md,
    where decode is HBM-bound and dispatch overhead is noise."""
    rows = [_bench_serve_row(n_requests=48, prompt_len=16, max_new=96,
                             prefill_chunk=16, seed=s, repeats=3)
            for s in (0, 1, 2)]
    best = max(r["vs_static"] for r in rows)
    assert best > 0.85, f"serve throughput regressed vs static: {best}"
    for r in rows:
        assert len(r["serve_walls_s"]) == 3  # median-of-repeats basis
        assert (r["static_decode_slot_steps"]
                >= 1.4 * r["decode_slot_steps"])


def test_bench_serve_trace_deterministic():
    import bench

    a = bench.make_serve_trace(6, 2.0, 32, 16, 256, seed=5)
    b = bench.make_serve_trace(6, 2.0, 32, 16, 256, seed=5)
    assert a == b
    assert all(t1 <= t2 for (_, _, t1), (_, _, t2) in zip(a, a[1:]))
    assert {len(p) for p, _, _ in a} != {32}  # mixed prompt lengths


# ---------------------------------------------------------------------------
# cancel + deadline shed (PR 20 satellites)
# ---------------------------------------------------------------------------


def test_scheduler_sheds_expired_heads_even_when_slots_busy():
    """Deadline admission is pure host logic on the trace clock: a head
    whose queue wait exceeds its deadline_ms is rejected at the admission
    attempt — even when every slot is busy, so the queue cannot back up
    behind the already-dead. No deadline means never shed."""
    s = make_sched(slots=1, blocks=8, bs=4)
    s.submit(Request(0, (1,) * 4, 4, 0.0))            # no deadline
    s.submit(Request(1, (1,) * 4, 4, 0.0, 10.0))      # 10 ms budget
    s.submit(Request(2, (1,) * 4, 4, 0.0, 50.0))      # 50 ms budget
    admitted = s.admit(0.0)
    assert [st.req.id for _, st in admitted] == [0]
    # 20 ms later: 1 is past its deadline and sheds despite the busy
    # slot; 2 is still inside its budget and stays queued
    assert s.admit(0.020) == []
    assert s.n_shed == 1
    assert [st.req.id for st in s.drain_shed()] == [1]
    assert s.drain_shed() == []
    assert [st.req.id for st in s.queue] == [2]
    s.admit(0.060)
    assert s.n_shed == 2
    assert [st.req.id for st in s.drain_shed()] == [2]


def test_engine_cancel_resident_and_queued_no_leak(tiny, requests5,
                                                   offline_refs):
    """ServeEngine.cancel abandons a request wherever it lives: a
    mid-decode resident frees its blocks back to the pool immediately,
    a queued request just vanishes; neither leaves a result, neither
    leaks a block, and the survivors' greedy tokens are untouched by
    the batch-composition change (ragged-batch invariance)."""
    from picotron_tpu.telemetry import Telemetry

    class _Cap:
        def __init__(self):
            self.events = []

        def emit(self, e):
            self.events.append(e)

        def close(self):
            pass

    cap = _Cap()
    cfg, params = tiny
    eng = ServeEngine(params, cfg, scfg(decode_slots=2),
                      telemetry=Telemetry(sinks=[cap]))
    for p, n in requests5:
        eng.submit(p, n)
    eng.step(0.0)
    eng.step(0.0)  # residents are mid-prefill/decode, not just admitted
    resident = [s.req.id for s in eng.sched.slots if s is not None]
    queued = [s.req.id for s in eng.sched.queue]
    assert resident and queued
    held = eng.pool.in_use
    v_slot, v_queue = resident[0], queued[0]
    assert eng.cancel(v_slot)
    assert eng.pool.in_use < held      # blocks back in the pool NOW
    assert eng.cancel(v_queue)
    assert not eng.cancel(v_slot)      # already gone: unknown id
    assert eng.stats["cancelled"] == 2
    while eng.sched.has_work():
        eng.step()
    assert eng.pool.in_use == 0        # no leak without teardown
    done = {r["id"]: r["tokens"] for r in eng.results}
    assert set(done) == set(range(len(requests5))) - {v_slot, v_queue}
    for rid, toks in done.items():
        assert toks == offline_refs[rid], rid
    cancels = [e for e in cap.events if e.get("kind") == "serve_cancel"]
    assert sorted(e["id"] for e in cancels) == sorted([v_slot, v_queue])
    assert {e["where"] for e in cancels} == {"slot", "queue"}
    eng.close()
