"""shardcheck static-analysis tests: the tier-1 config matrix must audit
green on the simulated CPU mesh, and every analyzer must fail LOUDLY (with
path-level messages) on deliberately broken inputs — a linter that cannot
catch the planted bug is worse than no linter (mutation tests per the
acceptance criteria: non-divisible tp sharding, extra/missing spec leaf,
undonated state buffer)."""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu.analysis import (
    check_donation, check_state_stability, lint_param_specs, lint_sources,
    lint_specs, lower_train_step, parse_collectives, run_shardcheck,
)
from picotron_tpu.analysis.collectives import audit_collectives
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset,
)


def mkcfg(model="debug-tiny", seq=64, mbs=1, ga=1, dist=None, train=None,
          pipe=None):
    from picotron_tpu.config import PipelineConfig

    cfg = Config(
        distributed=DistributedConfig(**(dist or {})),
        model=ModelConfig(name=model, **resolve_preset(model)),
        training=TrainingConfig(seq_length=seq, micro_batch_size=mbs,
                                gradient_accumulation_steps=ga,
                                **(train or {})),
        pipeline=PipelineConfig(**(pipe or {})),
    )
    cfg.validate()
    return cfg


# The breadth matrix the issue asks for: dense/MoE, pp>1, ep>1, offload
# on/off — every layout class the repo trains, audited statically on the
# 8-device simulated mesh.
MATRIX = {
    "dense-1chip": dict(),
    "dense-dp2tp2cp2": dict(dist=dict(dp_size=2, tp_size=2, cp_size=2),
                            ga=2),
    "dense-pp2dp2": dict(dist=dict(pp_size=2, dp_size=2), ga=2),
    # mpmd executor: the audit runs on the SPMD twin lowering; the
    # per-stage programs get their own prover (test_analysis_mpmd)
    "dense-pp2dp2-mpmd": dict(dist=dict(pp_size=2, dp_size=2), ga=2,
                              pipe=dict(executor="mpmd")),
    "moe-ep2dp2": dict(model="debug-tiny-moe",
                       dist=dict(ep_size=2, dp_size=2), ga=2),
    "dense-offload": dict(ga=2, train=dict(optimizer_offload=True)),
    "moe-ep2-offload": dict(model="debug-tiny-moe", dist=dict(ep_size=2),
                            ga=2, train=dict(optimizer_offload=True)),
}


@pytest.mark.parametrize("name", sorted(MATRIX))
def test_matrix_audits_green(name):
    cfg = mkcfg(**MATRIX[name])
    rep = run_shardcheck(cfg)
    assert rep.ok(), rep.render(verbose=True)
    # full donation coverage is part of "green"
    assert rep.info["donation"]["donated"] == \
        rep.info["donation"]["state_leaves"]


# ---------------------------------------------------------------------------
# spec lint mutations
# ---------------------------------------------------------------------------


def test_spec_lint_clean_config():
    rep = lint_param_specs(mkcfg(dist=dict(tp_size=2, pp_size=2)))
    assert rep.ok(), rep.render()


def _spec_fixture(tp=2):
    from picotron_tpu.parallel.api import abstract_master
    from picotron_tpu.parallel.sharding import param_specs

    cfg = mkcfg(dist=dict(tp_size=tp))
    specs = param_specs(cfg)
    params = abstract_master(cfg)
    sizes = {"dp": 1, "pp": 1, "ep": 1, "cp": 1, "tp": tp}
    return specs, params, sizes


def test_spec_lint_rejects_non_divisible_tp():
    specs, params, sizes = _spec_fixture(tp=2)
    sizes["tp"] = 3  # hidden=64, vocab=256: nothing divides by 3
    rep = lint_specs(specs, params, sizes)
    errs = [f for f in rep.errors() if "layers/q" in f.path]
    assert errs, rep.render()
    assert "not divisible" in errs[0].message
    assert "'tp'" in errs[0].message or "tp" in errs[0].message


def test_spec_lint_rejects_missing_and_extra_leaves():
    specs, params, sizes = _spec_fixture()
    del specs["embedding"]
    specs["bogus_extra"] = P()
    rep = lint_specs(specs, params, sizes)
    paths = {f.path: f.message for f in rep.errors()}
    assert "embedding" in paths and "no PartitionSpec" in paths["embedding"]
    assert "bogus_extra" in paths
    assert "no matching param" in paths["bogus_extra"]


def test_spec_lint_rejects_rank_and_duplicate_axis():
    specs, params, sizes = _spec_fixture()
    specs["final_norm"] = P(None, "tp")        # rank-1 param, 2-entry spec
    specs["lm_head"] = P("tp", "tp")           # same axis shards two dims
    rep = lint_specs(specs, params, sizes)
    msgs = {f.path: f.message for f in rep.errors()}
    assert "final_norm" in msgs and "rank" in msgs["final_norm"]
    assert "lm_head" in msgs and "at most one" in msgs["lm_head"]


def test_spec_lint_rejects_unknown_axis():
    specs, params, sizes = _spec_fixture()
    specs["embedding"] = P("tpp", None)
    rep = lint_specs(specs, params, sizes)
    assert any("unknown mesh axis" in f.message and "embedding" in f.path
               for f in rep.errors()), rep.render()


# ---------------------------------------------------------------------------
# collective-schedule audit
# ---------------------------------------------------------------------------


def test_schedule_audit_single_device_has_no_effective_collectives():
    rep = audit_collectives(mkcfg())
    assert rep.ok(), rep.render()
    assert rep.info["collectives"]["total_effective"] == 0
    # size-1 mesh axes DO lower psums, as group-size-1 no-ops
    assert rep.info["collectives"]["compiled_away (size-1 groups)"] > 0


def test_schedule_audit_counts_on_8_device_mesh():
    cfg = mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2), ga=2)
    low = lower_train_step(cfg)
    ops = parse_collectives(low.text)
    # the grad/loss psum over the fused data axes: dp*cp = 4
    assert any(op.kind == "all_reduce" and op.group_size == 4
               for op in ops)
    # tp psums: group size 2
    assert any(op.kind == "all_reduce" and op.group_size == 2
               for op in ops)
    # the cp ring moves K/V blocks via collective_permute
    assert any(op.kind == "collective_permute" and op.effective
               for op in ops)
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    assert rep.info["collectives"]["total_effective"] > 0


def test_schedule_audit_detects_missing_grad_sync():
    """Feed the audit a lowering whose data-axes all-reduce was (textually)
    removed — the detector must call out the missing gradient sync."""
    cfg = mkcfg(dist=dict(dp_size=2), ga=2)
    low = lower_train_step(cfg)
    # delete every dp-group all-reduce line pair marker by renaming the op
    mutated = low.text.replace("stablehlo.all_reduce", "stablehlo.xx_gone")
    rep = audit_collectives(cfg, text=mutated, state=low.state)
    assert not rep.ok()
    assert any("NOT being synchronized" in f.message for f in rep.errors())


def test_gather_budget_flags_oversized_all_gather():
    """Sequence parallelism legitimately all-gathers [mbs, S, H]
    activations; with the budget forced below that size the audit must
    flag every such gather — the 'accidental full replication' detector
    firing on a planted violation."""
    cfg = mkcfg(dist=dict(tp_size=2, sequence_parallel=True), ga=2)
    low = lower_train_step(cfg)
    ok_rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert ok_rep.ok(), ok_rep.render()

    tight = audit_collectives(cfg, text=low.text, state=low.state,
                              budget_bytes=64)
    errs = [f for f in tight.errors() if "all_gather" in f.path]
    assert errs, tight.render()
    assert "replication budget" in errs[0].message


def test_moe_audit_requires_all_to_all():
    cfg = mkcfg(model="debug-tiny-moe", dist=dict(ep_size=2), ga=2)
    low = lower_train_step(cfg)
    mutated = low.text.replace("stablehlo.all_to_all", "stablehlo.xx_gone")
    rep = audit_collectives(cfg, text=mutated, state=low.state)
    assert any("all_to_all" in f.path for f in rep.errors()), rep.render()


def _fused_sp_cfg():
    return mkcfg(dist=dict(dp_size=2, tp_size=2, sequence_parallel=True),
                 ga=2, train=dict(grad_engine="fused",
                                  remat_policy="dots_attn"))


def test_fused_sp_config_audits_green_not_skipped():
    """A `grad_engine: fused` + SP config must be AUDITED (the fused
    engine's manual backward lowers the same SP all-gather/reduce-scatter
    pair the AD engine's transposes produce), not skipped — the audit
    records which engine it saw and the presence of the f/g pair."""
    cfg = _fused_sp_cfg()
    rep = run_shardcheck(cfg)
    assert rep.ok(), rep.render(verbose=True)
    assert rep.info["collectives"]["grad_engine"] == "fused"
    assert rep.info["collectives"]["reduce_scatter"] > 0
    assert rep.info["collectives"]["all_gather"] > 0


def test_fused_sp_audit_flags_deleted_reduce_scatter():
    """Negative test: textually delete the SP reduce-scatters from the
    fused lowering — the audit must flag the missing row-parallel exit."""
    cfg = _fused_sp_cfg()
    low = lower_train_step(cfg)
    mutated = low.text.replace("stablehlo.reduce_scatter",
                               "stablehlo.xx_gone")
    rep = audit_collectives(cfg, text=mutated, state=low.state)
    assert not rep.ok()
    assert any("reduce-scatter" in f.message and "Megatron-SP" in f.message
               for f in rep.errors()), rep.render()


def test_fused_cp_ring_audit_requires_collective_permute():
    """cp>1 ring under the fused engine: the K/V ring's collective_permute
    (forward ring + the backward's dK/dV-carrying ring) must be present;
    deleting them must flag."""
    cfg = mkcfg(dist=dict(dp_size=2, cp_size=4), ga=2,
                train=dict(grad_engine="fused", remat_policy="dots_attn"))
    low = lower_train_step(cfg)
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    assert rep.info["collectives"]["grad_engine"] == "fused"
    assert rep.info["collectives"]["collective_permute"] > 0
    mutated = low.text.replace("stablehlo.collective_permute",
                               "stablehlo.xx_gone")
    bad = audit_collectives(cfg, text=mutated, state=low.state)
    assert any("K/V ring" in f.message for f in bad.errors()), bad.render()


def test_ulysses_audit_requires_cp_all_to_all():
    cfg = mkcfg(model="debug-tiny",
                dist=dict(dp_size=2, cp_size=2), ga=2,
                train=dict(grad_engine="fused", remat_policy="dots_attn"))
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, attn_impl="ulysses",
                                       num_attention_heads=8,
                                       num_key_value_heads=4))
    cfg.validate()
    low = lower_train_step(cfg)
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    mutated = low.text.replace("stablehlo.all_to_all", "stablehlo.xx_gone")
    bad = audit_collectives(cfg, text=mutated, state=low.state)
    assert any("Ulysses" in f.message for f in bad.errors()), bad.render()


# ---------------------------------------------------------------------------
# donation + recompilation hazards
# ---------------------------------------------------------------------------


def _toy_state_batch():
    state = {"params": {"w": jnp.zeros((8, 8), jnp.float32),
                        "b": jnp.zeros((8,), jnp.float32)},
             "step": jnp.zeros((), jnp.int32)}
    batch = (jnp.zeros((4,), jnp.int32),)
    return state, batch


def test_donation_flags_undonated_state_buffer():
    state, batch = _toy_state_batch()

    def step(state, batch):  # a step that forgot donate_argnums
        new = jax.tree.map(lambda x: x + 1, state)
        return new, jnp.float32(0)

    rep = check_donation(jax.jit(step).lower(state, batch))
    assert not rep.ok()
    paths = {f.path for f in rep.errors()}
    assert "params/w" in paths, rep.render()
    assert any("not donated" in f.message for f in rep.errors())


def test_donation_green_with_donate_argnums():
    state, batch = _toy_state_batch()

    def step(state, batch):
        new = jax.tree.map(lambda x: x + 1, state)
        return new, jnp.float32(0)

    rep = check_donation(
        jax.jit(step, donate_argnums=(0,)).lower(state, batch))
    assert rep.ok(), rep.render()
    assert rep.info["donation"]["donated"] == \
        rep.info["donation"]["state_leaves"]


def test_state_stability_detects_dtype_drift():
    state, batch = _toy_state_batch()

    def stable(state, batch):
        return jax.tree.map(lambda x: x + 1, state), {}

    assert check_state_stability(jax.jit(stable), state, batch).ok()

    def drifting(state, batch):  # params leave the step as bf16
        new = dict(state)
        new["params"] = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), state["params"])
        return new, {}

    rep = check_state_stability(jax.jit(drifting), state, batch)
    assert not rep.ok()
    assert any("recompiles" in f.message and "params/w" in f.path
               for f in rep.errors()), rep.render()


def test_state_stability_warns_on_weak_typed_metric():
    state, batch = _toy_state_batch()

    def step(state, batch):
        # a Python scalar reaching the traced output as a weak type
        return state, {"lr": jnp.asarray(3e-4)}

    rep = check_state_stability(jax.jit(step), state, batch)
    assert rep.ok()
    assert any("weak-typed metric" in f.message for f in rep.warnings())


# ---------------------------------------------------------------------------
# source lint
# ---------------------------------------------------------------------------


def test_source_lint_repo_is_clean_of_jax_core():
    """The rule the satellites retrofitted (ulysses/rope): no semi-private
    jax.core use anywhere in the package, and no host callbacks. Private
    jax._src imports stay warnings (mesh.py's pre-init probe is
    deliberate)."""
    rep = lint_sources()
    assert rep.ok(), rep.render()
    assert rep.info["source_lint"]["files"] > 20  # really walked the repo


def test_source_lint_catches_planted_violations(tmp_path):
    bad = tmp_path / "bad_module.py"
    bad.write_text(
        "import jax\n"
        "from jax.core import Tracer\n"
        "from jax import pure_callback\n"
        "import jax._src.core\n"
        "def f(x):\n"
        "    if isinstance(x, jax.core.Tracer):\n"
        "        return jax.pure_callback(abs, x, x)\n"
        "    return x\n"
        "suppressed = jax.core.get_aval  # shardcheck: ok\n")
    rep = lint_sources([str(bad)])
    msgs = [f.message for f in rep.errors()]
    assert any("jax.core" in m and "import" in m for m in msgs)
    assert any("pure_callback" in m for m in msgs)
    # the inline attribute chain on line 6 (isinstance probe) is caught too
    assert any(f.path.endswith(":6") for f in rep.errors()), rep.render()
    # the jax._src import is a warning, not an error
    assert any("_src" in f.message for f in rep.warnings())
    # line 9 is suppressed
    assert not any(":9" in f.path for f in rep.findings)


def test_source_lint_flags_collective_in_python_loop(tmp_path):
    """The unbatched-collective smell: a lax collective issued once per
    Python loop iteration (over layers/microbatches) flags as a warning;
    the same collective inside a function *defined* in the loop (a scan
    body) or past a `# shardcheck: ok` does not — the negative half the
    satellite requires."""
    bad = tmp_path / "loopy.py"
    bad.write_text(
        "import jax\n"
        "from jax import lax\n"
        "def per_layer_sync(grads):\n"
        "    out = []\n"
        "    for g in grads:\n"
        "        out.append(lax.psum(g, 'dp'))\n"          # line 6: flags
        "    return out\n"
        "def ring(x):\n"
        "    while True:\n"
        "        x = jax.lax.ppermute(x, 'cp', [(0, 1)])\n"  # line 10
        "    return x\n"
        "def scan_body_built_in_loop(xs):\n"
        "    fns = []\n"
        "    for _ in range(4):\n"
        "        def body(c, x):\n"
        "            return c, lax.psum(x, 'tp')\n"  # in a fn: no flag
        "        fns.append(body)\n"
        "    return fns\n"
        "def outside(x):\n"
        "    return lax.psum(x, 'dp')\n"             # no loop: no flag
        "def deliberate(xs):\n"
        "    for x in xs:\n"
        "        lax.ppermute(x, 'cp', [(0, 1)])  # shardcheck: ok\n")
    rep = lint_sources([str(bad)])
    assert rep.ok()  # loop-collective is a warning, not an error
    hits = [f for f in rep.warnings() if "inside a" in f.message]
    lines = sorted(int(f.path.rsplit(":", 1)[1]) for f in hits)
    assert lines == [6, 10], rep.render(verbose=True)


def test_source_lint_repo_has_no_unsuppressed_loop_collectives():
    """The deliberate unrolled rings (ops/ring_attention.py) and the
    per-leaf scalar clip psums (optimizer.py) are suppressed in-line;
    anything else would be a new smell."""
    rep = lint_sources()
    loopy = [f for f in rep.warnings()
             if "inside a Python loop" in f.message]
    assert loopy == [], [f.render() for f in loopy]


def test_preflight_raises_on_broken_spec(monkeypatch):
    """train.py wiring: a mutilated param_specs must abort with a
    ShardcheckError whose text carries the path-level finding."""
    from picotron_tpu.analysis import ShardcheckError, preflight
    from picotron_tpu.parallel import sharding as sharding_mod

    cfg = mkcfg()
    real = sharding_mod.param_specs

    def broken(cfg):
        specs = real(cfg)
        del specs["embedding"]
        return specs

    monkeypatch.setattr(sharding_mod, "param_specs", broken)
    with pytest.raises(ShardcheckError, match="embedding"):
        preflight(cfg, checks=("spec",))


def test_preflight_env_escape_hatch(monkeypatch):
    from picotron_tpu.analysis import preflight
    from picotron_tpu.parallel import sharding as sharding_mod

    monkeypatch.setenv("PICOTRON_PREFLIGHT", "0")
    monkeypatch.setattr(sharding_mod, "param_specs",
                        lambda cfg: (_ for _ in ()).throw(AssertionError(
                            "preflight must be skipped")))
    rep = preflight(mkcfg())
    assert rep.ok() and not rep.findings


# ---------------------------------------------------------------------------
# donation edge cases (shardflow satellites)
# ---------------------------------------------------------------------------


def test_donation_aliased_into_two_outputs():
    """A donated buffer whose ORIGINAL also escapes as a second output:
    XLA can alias it into at most one, but the donation *request* is what
    the static check audits — it stays recorded on every leaf, through
    both the args_info path and the HLO-text fallback. The runtime cost of
    the unusable alias is the CompileWatch/goodput layer's to observe."""
    state, batch = _toy_state_batch()

    def step(s, b):
        new = jax.tree.map(lambda x: x + b[0].sum(), s)
        return new, s  # the donated inputs escape unmodified too

    low = jax.jit(step, donate_argnums=(0,)).lower(state, batch)
    rep = check_donation(low, state, batch)
    assert rep.ok(), rep.render(verbose=True)
    assert rep.info["donation"]["donated"] == \
        rep.info["donation"]["state_leaves"] == 3
    # text-fallback parity on the same module
    rep_text = check_donation(low.as_text(), state, batch)
    assert rep_text.ok(), rep_text.render(verbose=True)
    assert rep_text.info["donation"] == rep.info["donation"]


def test_donation_text_fallback_parity_under_compat_shim():
    """This JAX (no varying-manual-axes) lowers the step through the
    compat.py pre-vma shard_map shim; donation attributes must survive
    that path identically in the Lowered.args_info view and the raw
    StableHLO text view (the fallback older jax versions take)."""
    from picotron_tpu.compat import HAS_VMA

    cfg = mkcfg(dist=dict(pp_size=2, dp_size=2), ga=2)
    low = lower_train_step(cfg)
    rep_info = check_donation(low.lowered, low.state, low.batch)
    rep_text = check_donation(low.text, low.state, low.batch)
    assert rep_info.ok(), rep_info.render(verbose=True)
    assert rep_info.info["donation"] == rep_text.info["donation"]
    assert rep_info.info["donation"]["donated"] == \
        rep_info.info["donation"]["state_leaves"]
    if not HAS_VMA:  # the shim path really was exercised
        assert rep_text.ok(), rep_text.render(verbose=True)


def test_donation_full_coverage_through_fused_bwd():
    """The fused grad engine's manual backward must not cost donation on
    any TrainState leaf — its scan carries grads through jaxpr-level
    custom plumbing that once made the aliaser lose track."""
    cfg = _fused_sp_cfg()
    low = lower_train_step(cfg)
    rep = check_donation(low.lowered, low.state, low.batch)
    assert rep.ok(), rep.render(verbose=True)
    assert rep.info["donation"]["donated"] == \
        rep.info["donation"]["state_leaves"]


# ---------------------------------------------------------------------------
# source lint: uncommitted device_put (shardflow satellite)
# ---------------------------------------------------------------------------


def test_source_lint_flags_uncommitted_device_put(tmp_path):
    """jax.device_put with no sharding/device produces an UNCOMMITTED
    array (the variant hazard); with an explicit placement, a device=
    kwarg, or a suppression it passes — positive and negative halves."""
    bad = tmp_path / "puts.py"
    bad.write_text(
        "import jax\n"
        "from jax import device_put\n"
        "def feed(x, sh):\n"
        "    a = jax.device_put(x)\n"                       # line 4: flags
        "    b = jax.device_put(x, sh)\n"                   # positional ok
        "    c = jax.device_put(x, device=sh)\n"            # kwarg ok
        "    d = device_put(x)\n"                           # line 7: flags
        "    e = jax.device_put(x)  # shardcheck: ok\n"     # suppressed
        "    return a, b, c, d, e\n")
    rep = lint_sources([str(bad)])
    assert rep.ok()  # warnings, not errors
    hits = [f for f in rep.warnings() if "UNCOMMITTED" in f.message]
    lines = sorted(int(f.path.rsplit(":", 1)[1]) for f in hits)
    assert lines == [4, 7], rep.render(verbose=True)


def test_source_lint_repo_has_no_uncommitted_device_puts():
    """The rule holds repo-wide: every device_put in picotron_tpu/ passes
    an explicit sharding (serve/engine.py's commit-everything discipline,
    checkpoint restore placement, offload host transfers)."""
    rep = lint_sources()
    assert not [f for f in rep.warnings()
                if "UNCOMMITTED" in f.message], rep.render(verbose=True)


# ---------------------------------------------------------------------------
# runs/ preset gate (shardflow tier-1 regression fence)
# ---------------------------------------------------------------------------


def test_shardflow_runs_gate():
    """Provenance + variant audit over every shipped runs/ preset, fenced
    against tests/data/shardflow_baseline.json. Fails on REGRESSIONS
    only: a NEW implicit reshard or predicted boundary reshard, a proven
    jit entry turning unproven, attribution decaying below the 90%
    acceptance bar, or a config newly failing to trace. Improvements
    (e.g. a pre-vma-fatal config starting to trace on a newer jax) pass —
    regenerate the baseline to lock them in."""
    import subprocess

    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(os.path.dirname(__file__), "data",
                           "shardflow_baseline.json")) as f:
        raw_baseline = json.load(f)
    baseline = raw_baseline["configs"]
    slice_baseline = raw_baseline["slice_presets"]
    cfgs = sorted(
        __import__("glob").glob(os.path.join(root, "runs", "*",
                                             "config.json")))
    assert cfgs, "runs/ presets missing"
    args = [sys.executable, os.path.join(root, "tools", "shardcheck.py"),
            "--provenance", "--variants", "--json"]
    for c in cfgs:
        args += ["--config", c]
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(args, capture_output=True, text=True, env=env,
                         timeout=540, cwd=root)
    rows = [json.loads(line) for line in res.stdout.strip().splitlines()]
    assert len(rows) == len(cfgs), res.stderr[-2000:]

    problems = []
    for row in rows:
        name = os.path.basename(os.path.dirname(row["config"]))
        base = baseline.get(name)
        assert base is not None, f"new preset {name}: add it to the baseline"
        if "fatal" in row:
            if base["status"] != "fatal":
                problems.append(f"{name}: newly fatal — {row['fatal']}")
            continue
        if base["status"] == "fatal":
            continue  # improvement: traces now where it could not before
        prov = row["info"]["provenance"]
        var = row["info"]["variants"]
        if prov["implicit_ops"] > base["implicit_ops"]:
            problems.append(f"{name}: {prov['implicit_ops']} implicit "
                            f"collective(s), baseline "
                            f"{base['implicit_ops']}")
        if prov["boundary_reshards"] > base["boundary_reshards"]:
            problems.append(f"{name}: {prov['boundary_reshards']} predicted "
                            f"boundary reshard(s), baseline "
                            f"{base['boundary_reshards']}")
        if prov["attribution_pct"] < 90.0:
            problems.append(f"{name}: attribution "
                            f"{prov['attribution_pct']}% < 90%")
        for entry in ("train_step", "serve", "serve_disagg",
                      "mpmd_stages"):
            if (base.get(f"{entry}_proven")
                    and not var.get(entry, {}).get("proven")):
                problems.append(f"{name}: {entry} no longer proven "
                                f"compile-once")

    # slice-boundary gate (analysis/boundary.py): the crossing presets
    # must stay audited with every collective in a tier — zero
    # violations, and at least the baseline's declared-boundary traffic
    sargs = [sys.executable,
             os.path.join(root, "tools", "shardcheck.py"),
             "--checks", "spec,boundary", "--json"]
    for name in sorted(slice_baseline):
        sargs += ["--preset", name]
    sres = subprocess.run(sargs, capture_output=True, text=True, env=env,
                          timeout=540, cwd=root)
    srows = [json.loads(line) for line in sres.stdout.strip().splitlines()]
    assert len(srows) == len(slice_baseline), sres.stderr[-2000:]
    for row in srows:
        name = row["config"].split("preset:", 1)[-1]
        base = slice_baseline[name]
        if "fatal" in row:
            problems.append(f"{name}: newly fatal — {row['fatal']}")
            continue
        bnd = row["info"].get("boundary", {})
        if base["slices_audited"] and not bnd.get("audited"):
            problems.append(f"{name}: slice audit no longer runs")
            continue
        if bnd.get("violating", 0) > base["violating"]:
            problems.append(
                f"{name}: {bnd['violating']} ICI-axis-over-DCN "
                f"violation(s), baseline {base['violating']}")
        if bnd.get("boundary", 0) < base["boundary_min"]:
            problems.append(
                f"{name}: only {bnd.get('boundary', 0)} declared "
                f"boundary op(s), baseline floor {base['boundary_min']}")
    assert not problems, "\n".join(problems)
