"""Ring attention equivalence: the cp-sharded ring must match dense causal
attention on the full sequence, forward and backward (the reference has no
such test — its ring is only exercised implicitly; SURVEY.md §4 calls for
parity tests per parallel layer)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.ops.attention import sdpa_attention
from picotron_tpu.ops.ring_attention import ring_attention


def qkv(key=0, b=2, s=32, hq=4, hkv=2, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(ks[0], (b, s, hq, d), dtype)
    k = jax.random.normal(ks[1], (b, s, hkv, d), dtype)
    v = jax.random.normal(ks[2], (b, s, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("cp,hq,hkv", [(2, 4, 4), (4, 4, 2), (8, 8, 1)])
def test_ring_matches_dense_forward(cp, hq, hkv):
    menv = MeshEnv.create(cp=cp)
    q, k, v = qkv(hq=hq, hkv=hkv)

    ring = jax.jit(compat.shard_map(
        ring_attention, mesh=menv.mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"),
    ))
    got = ring(q, k, v)
    want = sdpa_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(
    not compat.HAS_VMA,
    reason="differentiates THROUGH lax.psum: pre-vma shard_map inflates "
           "the cotangent by the cp size (see compat.py)")
def test_ring_matches_dense_grads():
    menv = MeshEnv.create(cp=4)
    q, k, v = qkv()

    def ring_loss(q, k, v):
        out = ring_attention(q, k, v)
        return jax.lax.psum(jnp.sum(out ** 2), "cp")

    g_ring = jax.jit(compat.shard_map(
        jax.grad(ring_loss, argnums=(0, 1, 2)), mesh=menv.mesh,
        in_specs=(P(None, "cp"),) * 3,
        out_specs=(P(None, "cp"),) * 3,
    ))(q, k, v)

    def dense_loss(q, k, v):
        return jnp.sum(sdpa_attention(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


def test_ring_zigzag_layout_matches_dense():
    """Zigzag layout: each cp shard holds one early + one late chunk; the
    position vector travels the ring with its K/V block, so the same ring
    code stays correct (the balanced layout the reference left as a TODO,
    ref: tests/test_dataloader.py:136)."""
    cp, s = 4, 32
    menv = MeshEnv.create(cp=cp)
    q, k, v = qkv(s=s)
    half = s // (2 * cp)
    perm = np.concatenate([
        np.concatenate([np.arange(r * half, (r + 1) * half),
                        np.arange((2 * cp - 1 - r) * half,
                                  (2 * cp - r) * half)])
        for r in range(cp)
    ])
    pos_global = jnp.asarray(perm)

    def ring_zz(q, k, v, pos):
        return ring_attention(q, k, v, q_positions=pos)

    got = jax.jit(compat.shard_map(
        ring_zz, mesh=menv.mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp"), P("cp")),
        out_specs=P(None, "cp"),
    ))(q[:, perm], k[:, perm], v[:, perm], pos_global)
    want = sdpa_attention(q, k, v, causal=True)
    # got is in zigzag order; un-permute to compare
    inv = np.argsort(perm)
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16_close_to_dense():
    menv = MeshEnv.create(cp=4)
    q, k, v = qkv(dtype=jnp.bfloat16)
    ring = jax.jit(compat.shard_map(
        ring_attention, mesh=menv.mesh,
        in_specs=(P(None, "cp"),) * 3, out_specs=P(None, "cp"),
    ))
    got = ring(q, k, v).astype(jnp.float32)
    want = sdpa_attention(q, k, v, causal=True).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-2, atol=5e-2)
