"""`*_bwd_from_saved` twins: the fused grad engine's attention backwards
(ops/attention.py, ops/flash_attention.py, ops/ring_attention.py,
ops/ulysses.py) pinned against AD of the dense forward.

These are FORWARD-only programs (ppermutes/all_to_alls in the primal
direction; no differentiation through collectives), so unlike the
engine-level parity tests they run on pre-vma JAX too. The load-bearing
property: `sdpa_attention_bwd_from_saved` normalizes probabilities by the
PASSED lse, so calling it per visiting block with the GLOBAL (out, lse)
yields that block's additive contribution to the global grads — which is
what the ring backward sums and the AD reference must equal exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.ops.attention import (
    sdpa_attention, sdpa_attention_bwd_from_saved,
)
from picotron_tpu.ops.flash_attention import flash_attention_bwd_from_saved
from picotron_tpu.ops.ring_attention import (
    ring_attention, ring_attention_bwd_from_saved,
)
from picotron_tpu.ops.ulysses import (
    ulysses_attention, ulysses_attention_bwd_from_saved,
)

TOL = dict(rtol=2e-5, atol=2e-5)


def qkvd(key=0, b=2, s=32, hq=4, hkv=2, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 4)
    return (jax.random.normal(ks[0], (b, s, hq, d), dtype),
            jax.random.normal(ks[1], (b, s, hkv, d), dtype),
            jax.random.normal(ks[2], (b, s, hkv, d), dtype),
            jax.random.normal(ks[3], (b, s, hq, d), dtype))


def dense_ref(q, k, v, do):
    _, vjp = jax.vjp(
        lambda q_, k_, v_: sdpa_attention(q_, k_, v_, causal=True),
        q, k, v)
    return vjp(do)


def assert_grads(got, want, tag=""):
    for g, w, n in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   err_msg=f"{tag}{n}", **TOL)


def test_sdpa_bwd_from_saved_matches_ad():
    q, k, v, do = qkvd()
    out, lse = sdpa_attention(q, k, v, causal=True, return_lse=True)
    got = sdpa_attention_bwd_from_saved(q, k, v, out, lse, do, causal=True)
    assert_grads(got, dense_ref(q, k, v, do))


def test_flash_bwd_from_saved_fallback_with_rope():
    # the non-TPU dispatch of flash_attention_bwd_from_saved: unrotated
    # q/k in, grads mapped back through the rotation's transpose
    from picotron_tpu.ops.flash_attention import flash_attention
    from picotron_tpu.ops.rope import rope_tables

    q, k, v, do = qkvd()
    cos, sin = rope_tables(64, q.shape[-1], 10000.0)
    out, lse = flash_attention(q, k, v, causal=True, rope=(cos, sin),
                               return_lse=True)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=True,
                                           rope=(cos, sin)), q, k, v)
    got = flash_attention_bwd_from_saved(q, k, v, out, lse, do,
                                         causal=True, rope=(cos, sin))
    assert_grads(got, vjp(do), "rope-")


@pytest.mark.parametrize("cp,hq,hkv", [(4, 4, 2), (8, 8, 1)])
def test_ring_bwd_from_saved_matches_dense_grads(cp, hq, hkv):
    menv = MeshEnv.create(cp=cp)
    q, k, v, do = qkvd(hq=hq, hkv=hkv)

    def body(q, k, v, do):
        out, lse = ring_attention(q, k, v, return_lse=True)
        return ring_attention_bwd_from_saved(q, k, v, out, lse, do)

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh, in_specs=(P(None, "cp"),) * 4,
        out_specs=(P(None, "cp"),) * 3))(q, k, v, do)
    assert_grads(got, dense_ref(q, k, v, do), f"ring{cp}-")


def test_ring_bwd_from_saved_zigzag_layout():
    cp, s = 4, 32
    menv = MeshEnv.create(cp=cp)
    q, k, v, do = qkvd(s=s)
    half = s // (2 * cp)
    perm = np.concatenate([
        np.concatenate([np.arange(r * half, (r + 1) * half),
                        np.arange((2 * cp - 1 - r) * half,
                                  (2 * cp - r) * half)])
        for r in range(cp)])

    def body(q, k, v, do, pos):
        out, lse = ring_attention(q, k, v, q_positions=pos,
                                  return_lse=True)
        return ring_attention_bwd_from_saved(q, k, v, out, lse, do,
                                             q_positions=pos)

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh,
        in_specs=(P(None, "cp"),) * 4 + (P("cp"),),
        out_specs=(P(None, "cp"),) * 3))(
        q[:, perm], k[:, perm], v[:, perm], do[:, perm],
        jnp.asarray(perm))
    inv = np.argsort(perm)
    got = tuple(np.asarray(g)[:, inv] for g in got)
    assert_grads(got, dense_ref(q, k, v, do), "ring-zz-")


def test_ulysses_bwd_from_saved_matches_dense_grads():
    menv = MeshEnv.create(cp=2)
    q, k, v, do = qkvd()

    def body(q, k, v, do):
        out, lse = ulysses_attention(q, k, v, attn_fn=sdpa_attention,
                                     return_lse=True)
        return ulysses_attention_bwd_from_saved(q, k, v, out, lse, do)

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh, in_specs=(P(None, "cp"),) * 4,
        out_specs=(P(None, "cp"),) * 3))(q, k, v, do)
    assert_grads(got, dense_ref(q, k, v, do), "uly-")


def test_ulysses_bwd_from_saved_zigzag_sorted():
    # zigzag layout + the static seq_sort the fused engine derives from
    # ulysses_static_layout: the bwd must re-apply the identical sort to
    # the inner domain (the saved lse is in the SORTED inner domain)
    cp, s = 2, 32
    menv = MeshEnv.create(cp=cp)
    q, k, v, do = qkvd(s=s)
    half = s // (2 * cp)
    perm = np.concatenate([
        np.concatenate([np.arange(r * half, (r + 1) * half),
                        np.arange((2 * cp - 1 - r) * half,
                                  (2 * cp - r) * half)])
        for r in range(cp)])
    ss = np.argsort(perm)

    def body(q, k, v, do, pos):
        kw = dict(q_positions=pos, seq_sort=ss, full_positions=perm,
                  positions_static=True)
        out, lse = ulysses_attention(q, k, v, attn_fn=sdpa_attention,
                                     return_lse=True, **kw)
        return ulysses_attention_bwd_from_saved(q, k, v, out, lse, do,
                                                **kw)

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh,
        in_specs=(P(None, "cp"),) * 4 + (P("cp"),),
        out_specs=(P(None, "cp"),) * 3))(
        q[:, perm], k[:, perm], v[:, perm], do[:, perm],
        jnp.asarray(perm))
    inv = np.argsort(perm)
    got = tuple(np.asarray(g)[:, inv] for g in got)
    assert_grads(got, dense_ref(q, k, v, do), "uly-zz-")


def test_ring_forward_return_lse_matches_dense():
    # the saved statistic itself: the ring's merged lse == the dense lse
    menv = MeshEnv.create(cp=4)
    q, k, v, _ = qkvd()
    _, lse_ref = sdpa_attention(q, k, v, causal=True, return_lse=True)

    def body(q, k, v):
        return ring_attention(q, k, v, return_lse=True)

    out, lse = jax.jit(compat.shard_map(
        body, mesh=menv.mesh, in_specs=(P(None, "cp"),) * 3,
        out_specs=(P(None, "cp"), P(None, None, "cp"))))(q, k, v)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref), **TOL)
