"""Telemetry subsystem tests: registry instruments, sinks (incl. the
rollback-safe wandb adapter), the step-phase timer / watchdog coupling,
the goodput ledger (replay high-water mark, exact compile split), the
recompile hook, the bus, the frozen stdout log-line contract, and the
post-hoc report tool. The whole-run chaos assertions live with the
scenario tests in test_resilience.py (slow tier)."""

import importlib.util
import json
import os
import sys

import pytest

from picotron_tpu.telemetry import (
    CompileWatch, GoodputLedger, Histogram, JsonlSink, MetricsRegistry,
    PhaseTimer, StdoutSink, Telemetry, WandbSink, bus,
    telemetry_jsonl_path,
)
from picotron_tpu.telemetry.sinks import jsonl_segments


def load_report():
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    r.counter("events/retry").inc()
    r.counter("events/retry").inc(2)
    assert r.counter("events/retry").value == 3
    r.gauge("tokens").set(512)
    assert r.gauge("tokens").value == 512.0
    h = r.histogram("step")
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    assert h.count == 4 and h.sum == 10.0
    assert h.min == 1.0 and h.max == 4.0 and h.mean == 2.5


def test_histogram_percentiles_nearest_rank():
    h = Histogram()
    for v in range(1, 101):  # 1..100
        h.observe(float(v))
    assert h.p50 == 50.0
    assert h.p95 == 95.0
    assert h.percentile(100) == 100.0
    assert h.percentile(0) == 1.0
    assert Histogram().p50 is None  # empty: no value, not a crash


def test_histogram_window_bounds_memory_but_keeps_lifetime_stats():
    h = Histogram(window=8)
    for v in range(100):
        h.observe(float(v))
    assert h.count == 100 and h.min == 0.0 and h.max == 99.0
    # percentiles over the retention window (the recent distribution)
    assert h.p50 >= 92.0


def test_registry_snapshot_shape():
    r = MetricsRegistry()
    r.counter("a").inc()
    r.histogram("b").observe(2.0)
    snap = r.snapshot()
    assert snap["counters"]["a"] == 1
    assert snap["histograms"]["b"]["count"] == 1
    assert snap["histograms"]["b"]["p95"] == 2.0


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------


def test_jsonl_sink_roundtrip_strips_line_and_appends(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = JsonlSink(p)
    s.emit({"kind": "step", "step": 1, "loss": 2.5, "line": "[step ...]"})
    s.close()
    s2 = JsonlSink(p)  # append mode: a restart continues the stream
    s2.emit({"kind": "step", "step": 2, "loss": 2.4})
    s2.emit({"kind": "step", "step": 3})  # emit after close is a no-op
    s2.close()
    s2.emit({"kind": "step", "step": 9})
    rows = [json.loads(ln) for ln in open(p)]
    assert [r["step"] for r in rows] == [1, 2, 3]
    assert "line" not in rows[0]  # presentation, not data


def test_jsonl_sink_rotates_at_size_cap(tmp_path):
    """logging.telemetry_max_mb: when the live segment crosses the byte
    cap it is renamed to `<path>.1` (one older segment kept) and the
    stream continues in a fresh file — a week-long run's telemetry is
    bounded at ~2x the cap, and no event is lost at the seam."""
    p = str(tmp_path / "t.jsonl")
    s = JsonlSink(p, max_bytes=300)
    for step in range(1, 13):
        s.emit({"kind": "step", "step": step, "loss": 2.5})
    s.close()
    assert os.path.exists(p + ".1")  # rotation happened
    assert os.path.getsize(p) < 400  # live segment stays near the cap
    # oldest-first reading reassembles the unbroken stream
    assert jsonl_segments(p) == [p + ".1", p]
    steps = []
    for seg in jsonl_segments(p):
        steps += [json.loads(ln)["step"] for ln in open(seg)]
    assert steps == list(range(1, 13))
    # a second overflow drops the oldest segment (the documented bound:
    # telemetry disk stays ~2x the cap, the tail survives)
    s2 = JsonlSink(p, max_bytes=300)
    for step in range(13, 25):
        s2.emit({"kind": "step", "step": step, "loss": 2.5})
    s2.close()
    tail = []
    for seg in jsonl_segments(p):
        tail += [json.loads(ln)["step"] for ln in open(seg)]
    assert tail[-1] == 24
    assert tail == sorted(tail) and len(tail) < 24
    # an unrotated stream is a single segment; a missing one is none
    single = str(tmp_path / "single.jsonl")
    JsonlSink(single).close()
    assert jsonl_segments(single) == [single]
    assert jsonl_segments(str(tmp_path / "absent.jsonl")) == []


def test_jsonl_sink_unbounded_by_default(tmp_path):
    p = str(tmp_path / "t.jsonl")
    s = JsonlSink(p)
    for step in range(200):
        s.emit({"kind": "step", "step": step})
    s.close()
    assert not os.path.exists(p + ".1")
    assert len(open(p).readlines()) == 200


def test_stdout_sink_gates_on_primary(capsys):
    StdoutSink(is_primary=True).emit({"kind": "step", "line": "hello"})
    StdoutSink(is_primary=False).emit({"kind": "step", "line": "nope"})
    StdoutSink(is_primary=True).emit({"kind": "phase"})  # no line: silent
    out = capsys.readouterr().out
    assert out == "hello\n"


class FakeWandbRun:
    def __init__(self):
        self.calls = []
        self.defined = []
        self.finished = False

    def define_metric(self, *a, **k):
        self.defined.append((a, k))

    def log(self, data, step=None):
        self.calls.append((data, step))

    def finish(self):
        self.finished = True


def test_wandb_sink_survives_rollback_with_monotonic_steps():
    """The satellite fix: wandb drops log(step=...) calls with
    non-monotonic steps, so after a guard rollback (training step goes
    5 -> 3) the sink must keep its OWN axis monotonic and carry the
    training step as a field."""
    run = FakeWandbRun()
    sink = WandbSink(run)
    for training_step in (1, 2, 3, 4, 5, 3, 4, 5, 6):  # rollback at 5->3
        sink.emit({"kind": "step", "ts": 0.0, "step": training_step,
                   "loss": 1.0, "line": "x"})
    wandb_steps = [s for _, s in run.calls]
    assert wandb_steps == sorted(wandb_steps)
    assert len(set(wandb_steps)) == len(wandb_steps)  # strictly increasing
    assert [d["step"] for d, _ in run.calls] == [1, 2, 3, 4, 5, 3, 4, 5, 6]
    assert all("line" not in d and "ts" not in d for d, _ in run.calls)
    # the step axis was define_metric'd where supported
    assert (("step",), {}) in run.defined


def test_wandb_sink_only_forwards_chart_kinds():
    run = FakeWandbRun()
    sink = WandbSink(run)
    sink.emit({"kind": "phase", "phase": "step", "secs": 0.1})
    sink.emit({"kind": "retry", "secs": 1.0})
    sink.emit({"kind": "eval", "step": 4, "val_loss": 3.2})
    assert len(run.calls) == 1
    assert run.calls[0][0] == {"step": 4, "val_loss": 3.2}
    sink.close()
    assert run.finished


def test_telemetry_jsonl_path_per_host(tmp_path):
    from picotron_tpu.config import Config, CheckpointConfig, LoggingConfig

    cfg = Config(checkpoint=CheckpointConfig(save_dir=str(tmp_path / "ck")))
    assert telemetry_jsonl_path(cfg, 0).endswith("ck/telemetry.jsonl")
    assert telemetry_jsonl_path(cfg, 2).endswith("ck/telemetry.p2.jsonl")
    off = Config(logging=LoggingConfig(telemetry_jsonl=False))
    assert telemetry_jsonl_path(off, 0) is None
    redirected = Config(logging=LoggingConfig(
        telemetry_dir=str(tmp_path / "elsewhere")))
    assert telemetry_jsonl_path(redirected, 0).endswith(
        "elsewhere/telemetry.jsonl")


# ---------------------------------------------------------------------------
# phase timer + watchdog coupling
# ---------------------------------------------------------------------------


class FakeWatchdog:
    def __init__(self):
        self.beats = []

    def beat(self, phase, step=None):
        self.beats.append((phase, step))


def test_phase_timer_beats_watchdog_and_reports_duration():
    done = []
    wd = FakeWatchdog()
    timer = PhaseTimer(lambda n, s, st: done.append((n, s, st)),
                       watchdog=wd)
    with timer.phase("data", 3):
        pass
    assert wd.beats == [("data", 3)]  # beat on ENTRY, before the work
    assert len(done) == 1
    name, secs, step = done[0]
    assert name == "data" and step == 3 and secs >= 0


def test_phase_timer_books_even_on_exception():
    done = []
    timer = PhaseTimer(lambda n, s, st: done.append(n))
    with pytest.raises(RuntimeError):
        with timer.phase("save", 1):
            raise RuntimeError("boom")
    assert done == ["save"]


# ---------------------------------------------------------------------------
# goodput ledger
# ---------------------------------------------------------------------------


def test_ledger_phase_categories_and_goodput_fraction():
    led = GoodputLedger()
    assert led.book_phase("data", 1.0, step=1) == "data_wait"
    assert led.book_phase("step", 6.0, step=1) == "compute"
    assert led.book_phase("save", 2.0, step=1) == "ckpt_io"
    assert led.book_phase("sync", 1.0, step=1) == "host_sync"
    assert led.goodput_fraction() == pytest.approx(0.6)
    s = led.summary()
    assert s["goodput_pct"] == 60.0
    assert s["seconds_by_category"]["ckpt_io"] == 2.0


def test_ledger_books_replay_below_high_water_mark():
    """Steps re-trained after a rollback buy back lost ground — badput."""
    led = GoodputLedger()
    for step in (1, 2, 3, 4):
        assert led.book_phase("step", 1.0, step=step) == "compute"
    # rollback to 2: steps 3 and 4 re-run, then new ground at 5
    assert led.book_phase("rollback", 0.5, step=4) == "restore"
    assert led.book_phase("step", 1.0, step=3) == "replay"
    assert led.book_phase("step", 1.0, step=4) == "replay"
    assert led.book_phase("step", 1.0, step=5) == "compute"
    assert led.seconds["replay"] == 2.0
    assert led.seconds["compute"] == 5.0


def test_ledger_resume_seeds_high_water_mark():
    led = GoodputLedger()
    led.resume_from(10)
    assert led.book_phase("step", 1.0, step=10) == "replay"
    assert led.book_phase("step", 1.0, step=11) == "compute"


def test_ledger_compile_split_subtracts_from_phase():
    led = GoodputLedger()
    cat = led.book_phase("step", 10.0, step=1, compile_secs=8.0)
    assert cat == "compute"
    assert led.seconds["compile"] == 8.0
    assert led.seconds["compute"] == pytest.approx(2.0)
    # compile can never exceed the observed phase wall
    led2 = GoodputLedger()
    led2.book_phase("step", 1.0, step=1, compile_secs=5.0)
    assert led2.seconds["compile"] == 1.0
    assert led2.seconds.get("compute", 0.0) == 0.0


def test_ledger_pp_bubble_carved_from_compute():
    """The schedule-table bubble share is badput carved out of a compute
    phase (like compile); a replayed step is already badput wall-to-wall,
    so its bubble share is NOT double-carved."""
    led = GoodputLedger()
    cat = led.book_phase("step", 10.0, step=1, compile_secs=2.0,
                         bubble_secs=3.0)
    assert cat == "compute"
    assert led.seconds["compile"] == 2.0
    assert led.seconds["pp_bubble"] == 3.0
    assert led.seconds["compute"] == pytest.approx(5.0)
    # replay: the whole phase books as replay, bubble untouched
    led2 = GoodputLedger()
    led2.resume_from(5)
    assert led2.book_phase("step", 4.0, step=5, bubble_secs=1.0) == "replay"
    assert led2.seconds.get("pp_bubble", 0.0) == 0.0
    assert led2.seconds["replay"] == 4.0
    # the carve clamps to the phase wall
    led3 = GoodputLedger()
    led3.book_phase("step", 1.0, step=1, bubble_secs=9.0)
    assert led3.seconds["pp_bubble"] == 1.0
    assert led3.seconds.get("compute", 0.0) == 0.0


def test_phase_timer_section_histogram_only():
    """Sections time sub-spans inside a phase: on_section fires with the
    duration, but no watchdog beat (the enclosing phase armed it) and no
    phase booking."""
    phases, sections = [], []
    wd = FakeWatchdog()
    timer = PhaseTimer(lambda n, s, st: phases.append(n), watchdog=wd,
                       on_section=lambda n, s, st: sections.append((n, st)))
    with timer.phase("step", 7):
        with timer.section("pp_stage0", 7):
            pass
        with timer.section("pp_stage1", 7):
            pass
    assert [s[0] for s in sections] == ["pp_stage0", "pp_stage1"]
    assert phases == ["step"]
    assert wd.beats == [("step", 7)]  # sections never beat


def test_facade_pp_bubble_jsonl_reproduces_ledger(tmp_path):
    """With a bubble fraction installed, every step phase emits a
    category='pp_bubble' event next to the shrunken phase event — the
    documented invariant (a post-hoc sum of (category, secs) pairs
    reproduces the ledger) must survive the carve."""
    p = str(tmp_path / "t.jsonl")
    tel = Telemetry(sinks=[JsonlSink(p)])
    tel.set_pp_bubble_fraction(0.25)
    with tel.phases.phase("step", 1):
        pass
    with tel.phases.phase("data", 1):
        pass  # non-step phases never carve
    tel.close()
    rows = [json.loads(ln) for ln in open(p)]
    bubbles = [r for r in rows if r["kind"] == "pp_bubble"]
    assert len(bubbles) == 1 and bubbles[0]["category"] == "pp_bubble"
    sums: dict = {}
    for r in rows:
        if "category" in r and "secs" in r:
            sums[r["category"]] = sums.get(r["category"], 0.0) + r["secs"]
    for cat, secs in tel.ledger.seconds.items():
        assert sums.get(cat, 0.0) == pytest.approx(secs, abs=1e-5), (
            cat, sums, tel.ledger.seconds)
    assert tel.ledger.seconds["pp_bubble"] == pytest.approx(
        0.25 * (tel.ledger.seconds["pp_bubble"]
                + tel.ledger.seconds["compute"]), rel=1e-6)


def test_facade_observe_section_feeds_stage_histograms():
    tel = Telemetry(sinks=[])
    try:
        for secs in (0.01, 0.02, 0.03):
            tel.observe_section("pp_stage0", secs)
        snap = tel.registry.snapshot()["histograms"]["section/pp_stage0"]
        assert snap["count"] == 3
        assert snap["p50"] == pytest.approx(0.02)
    finally:
        tel.close()


def test_ledger_unknown_category_books_as_other():
    led = GoodputLedger()
    led.book("???", 1.0)
    led.book("compute", -1.0)  # non-positive: ignored
    assert led.seconds == {"other": 1.0}


# ---------------------------------------------------------------------------
# compile watch (jax.monitoring)
# ---------------------------------------------------------------------------


def test_compile_watch_counts_real_compiles():
    import jax
    import jax.numpy as jnp

    watch = CompileWatch().install()
    try:
        assert watch.supported  # this JAX publishes the compile events
        assert watch.drain() == (0, 0.0)
        f = jax.jit(lambda x: x * 2 + 1)
        f(jnp.ones(3))
        n, secs = watch.drain()
        assert n >= 1 and secs > 0
        f(jnp.ones(3))  # cached: no new compile
        assert watch.drain()[0] == 0
        f(jnp.ones(5))  # new shape: recompile
        assert watch.drain()[0] >= 1
        assert watch.total_count >= 2
    finally:
        watch.uninstall()


def test_telemetry_flags_unexpected_step_recompile(tmp_path):
    """A compile observed in a 'step' phase after the first flags the
    recompile tripwire (shape/dtype drift symptom)."""
    import jax
    import jax.numpy as jnp

    tel = Telemetry(sinks=[JsonlSink(str(tmp_path / "t.jsonl"))])
    try:
        f = jax.jit(lambda x: x + 1)
        with tel.phases.phase("step", 1):
            f(jnp.ones(3))
        assert tel.registry.counter("compile/unexpected_recompiles").value \
            == 0
        with tel.phases.phase("step", 2):
            f(jnp.ones(7))  # shape drift -> re-jit
    finally:
        tel.close()
    kinds = [json.loads(ln)["kind"] for ln in open(tmp_path / "t.jsonl")]
    assert "recompile" in kinds
    assert tel.registry.counter("compile/unexpected_recompiles").value >= 1
    assert tel.ledger.seconds["compile"] > 0


# ---------------------------------------------------------------------------
# bus + facade
# ---------------------------------------------------------------------------


def test_bus_is_inert_without_install_and_routes_with():
    bus.emit("retry", category="retry_backoff", secs=1.0)  # no-op, no crash
    tel = Telemetry(sinks=[])
    bus.install(tel)
    try:
        bus.emit("retry", category="retry_backoff", secs=1.5, attempt=1)
        assert tel.ledger.seconds["retry_backoff"] == 1.5
        assert tel.registry.counter("events/retry").value == 1
    finally:
        tel.close()
    assert bus.active() is None  # close uninstalls


def test_retry_call_books_backoff_into_ledger():
    from picotron_tpu.resilience.retry import RetryPolicy, retry_call

    tel = Telemetry(sinks=[])
    bus.install(tel)
    try:
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("blip")
            return "ok"

        policy = RetryPolicy(attempts=3, base_delay=0.5, max_delay=1.0,
                             jitter=0.0)
        assert retry_call(flaky, policy=policy, sleep=lambda s: None) == "ok"
        # two retries: delays 0.5 + 1.0 booked as badput
        assert tel.ledger.seconds["retry_backoff"] == pytest.approx(1.5)
        assert tel.registry.counter("events/retry").value == 2
    finally:
        tel.close()


def test_facade_emit_and_run_summary(tmp_path):
    p = str(tmp_path / "t.jsonl")
    tel = Telemetry(sinks=[JsonlSink(p)])
    with tel.phases.phase("data", 1):
        pass
    tel.emit("chaos", chaos_kind="sigterm", step=3)
    tel.record_step(1, "[step 000001] ...", loss=2.0, tokens_per_sec=10.0)
    tel.record_eval(1, 3.5, "[eval  000001] ...")
    tel.close()
    tel.close()  # idempotent
    rows = [json.loads(ln) for ln in open(p)]
    kinds = [r["kind"] for r in rows]
    assert kinds == ["run_start", "phase", "chaos", "step", "eval",
                     "run_summary"]
    assert rows[-1]["goodput"]["accounted_seconds"] >= 0
    assert rows[-1]["metrics"]["counters"]["events/chaos"] == 1
    assert kinds.count("run_summary") == 1


def test_sick_sink_cannot_kill_a_step():
    class Boom(StdoutSink):
        def emit(self, event):
            raise RuntimeError("sink died")

    tel = Telemetry(sinks=[Boom()])
    try:
        tel.record_step(1, "line", loss=1.0)  # must not raise
        tel.emit("retry")
    finally:
        tel.close()


# ---------------------------------------------------------------------------
# the frozen stdout contract
# ---------------------------------------------------------------------------


def test_training_log_line_byte_format_is_frozen():
    """The stdout line is a de-facto API (extract_metrics regex + external
    scrapers): this pins the exact bytes, not just regex-parseability. A
    change here is a breaking change to downstream tooling — don't."""
    from picotron_tpu.utils import training_log_line

    line = training_log_line(7, 2.3456, 13500.0, 1687.5, 0.4321,
                             1230000, 11.5, extras={"grad_norm": 1.25})
    assert line == ("[step 000007] loss: 2.3456 | tokens/s: 13.5K | "
                    "tokens/s/chip: 1.69K | MFU: 43.21% | tokens: 1.23M | "
                    "mem: 11.5GB | grad_norm: 1.2500")


def test_training_log_line_matches_extract_metrics_regex():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from extract_metrics import LINE_RE, parse_human

    from picotron_tpu.utils import training_log_line

    line = training_log_line(12, 5.4321, 98765.0, 12345.6, 0.1234, 999000,
                             3.2)
    m = LINE_RE.search(line)
    assert m is not None
    assert int(m.group("step")) == 12
    assert float(m.group("loss")) == 5.4321
    assert parse_human(m.group("tps")) == pytest.approx(98765.0, rel=0.01)
    assert float(m.group("mfu")) == 12.34


# ---------------------------------------------------------------------------
# report tool + extract_metrics integration
# ---------------------------------------------------------------------------


def _write_events(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_report_reproduces_steps_goodput_and_cross_restart_replay(tmp_path):
    """Two appended process lifetimes: run 1 trains 1-3 and dies; run 2
    resumes from the step-2 checkpoint and re-trains 3 before new ground.
    The report must count distinct steps once, book the re-trained step 3
    as replay, and sum categories exactly."""
    rep = load_report()
    ts = [100.0 + i for i in range(20)]
    events = [
        {"ts": ts[0], "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 4.0},
        {"ts": ts[1], "kind": "compile", "phase": "step", "step": 1,
         "category": "compile", "secs": 6.0},
        {"ts": ts[2], "kind": "phase", "phase": "step", "step": 2,
         "category": "compute", "secs": 4.0},
        {"ts": ts[3], "kind": "phase", "phase": "save", "step": 2,
         "category": "ckpt_io", "secs": 1.0},
        {"ts": ts[4], "kind": "phase", "phase": "step", "step": 3,
         "category": "compute", "secs": 4.0},
        # --- process 2 (appended after a kill + auto_resume) ---
        {"ts": ts[5], "kind": "phase", "phase": "restore", "step": None,
         "category": "restore", "secs": 2.0},
        {"ts": ts[6], "kind": "phase", "phase": "step", "step": 3,
         "category": "compute", "secs": 4.0},   # re-trained -> replay
        {"ts": ts[7], "kind": "phase", "phase": "step", "step": 4,
         "category": "compute", "secs": 4.0},
        {"ts": ts[8], "kind": "step", "step": 4, "loss": 2.5,
         "tokens_per_sec": 1000.0, "trained_tokens": 4096},
        {"ts": ts[9], "kind": "retry", "category": "retry_backoff",
         "secs": 1.0, "target": "checkpoint save"},
    ]
    p = tmp_path / "telemetry.jsonl"
    _write_events(p, events)
    s = rep.summarize(rep.load_events(str(p)))
    assert s["steps"] == {"count": 4, "max": 4, "replayed": 1}
    cats = s["categories"]
    assert cats["replay"] == 4.0
    assert cats["compute"] == 16.0
    assert cats["compile"] == 6.0
    assert cats["restore"] == 2.0
    assert cats["retry_backoff"] == 1.0
    assert s["goodput_pct"] == pytest.approx(
        100.0 * 16.0 / (16 + 4 + 6 + 1 + 2 + 1), abs=0.01)
    assert s["training"]["final_loss"] == 2.5
    # directory form resolves to the contained telemetry.jsonl
    s2 = rep.summarize(rep.load_events(rep.resolve_path(str(tmp_path))))
    assert s2["steps"]["count"] == 4


def test_report_render_text_and_markdown(tmp_path):
    rep = load_report()
    p = tmp_path / "telemetry.jsonl"
    _write_events(p, [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 3.0},
        {"ts": 2.0, "kind": "phase", "phase": "save", "step": 1,
         "category": "ckpt_io", "secs": 1.0},
        {"ts": 3.0, "kind": "chaos", "chaos_kind": "sigterm", "step": 1},
    ])
    s = rep.summarize(rep.load_events(str(p)))
    text = rep.render(s)
    assert "goodput 75.00%" in text and "ckpt_io" in text
    md = rep.render(s, markdown=True)
    assert "| category | seconds | share |" in md
    assert "| compute | 3.000 | 75.0% |" in md
    assert "chaos=1" in md


def test_report_resize_row_includes_pp_resizes(tmp_path):
    """The resize row aggregates elastic_resize events regardless of
    axis: a pp resize (and a joint dp x pp one) must surface in
    `resize.events` with its restore seconds booked under the `resize`
    category — the accounting contract the pp_resize chaos scenario
    asserts end-to-end."""
    rep = load_report()
    p = tmp_path / "telemetry.jsonl"
    _write_events(p, [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 3.0},
        {"ts": 2.0, "kind": "phase", "phase": "resize", "step": None,
         "category": "resize", "secs": 2.0},
        {"ts": 3.0, "kind": "elastic_resize", "step": 4, "axes": ["pp"],
         "from": {"dp": 1, "pp": 1}, "to": {"dp": 1, "pp": 2}},
        {"ts": 4.0, "kind": "phase", "phase": "resize", "step": None,
         "category": "resize", "secs": 0.5},
        {"ts": 5.0, "kind": "elastic_resize", "step": 5,
         "axes": ["dp", "pp"], "from": {"dp": 2, "pp": 2},
         "to": {"dp": 1, "pp": 1}},
    ])
    s = rep.summarize(rep.load_events(str(p)))
    assert s["resize"]["events"] == 2
    assert s["resize"]["seconds"] == pytest.approx(2.5)
    assert s["categories"]["resize"] == pytest.approx(2.5)


def test_report_pipeline_row(tmp_path):
    """A pp run's stream: pp_bubble events + per-stage section histograms
    in the run_summary must surface as the pipeline row (bubble share of
    step wall, per-stage tick p50/p95)."""
    rep = load_report()
    p = tmp_path / "telemetry.jsonl"
    _write_events(p, [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 3.0},
        {"ts": 1.5, "kind": "pp_bubble", "phase": "step", "step": 1,
         "category": "pp_bubble", "secs": 1.0},
        {"ts": 2.0, "kind": "run_summary", "goodput": {},
         "metrics": {"histograms": {
             "section/pp_stage0": {"count": 8, "p50": 0.010, "p95": 0.012},
             "section/pp_stage1": {"count": 8, "p50": 0.011, "p95": 0.014},
             "phase/step": {"count": 1, "p50": 4.0, "p95": 4.0},
         }}},
    ])
    s = rep.summarize(rep.load_events(str(p)))
    pp = s["pipeline"]
    assert pp["bubble_s"] == 1.0
    assert pp["bubble_fraction"] == pytest.approx(0.25)
    assert pp["stages"]["pp_stage0"]["p50_ms"] == 10.0
    assert pp["stages"]["pp_stage1"]["p95_ms"] == 14.0
    assert "phase/step" not in pp.get("stages", {})
    text = rep.render(s)
    assert "bubble 25.0% of step wall" in text
    assert "pp_stage1" in text


def test_report_tolerates_torn_tail_line(tmp_path):
    rep = load_report()
    p = tmp_path / "telemetry.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "phase", "phase": "step",
                            "step": 1, "category": "compute",
                            "secs": 1.0}) + "\n")
        f.write('{"ts": 2.0, "kind": "phase", "ph')  # killed mid-write
    s = rep.summarize(rep.load_events(str(p)))
    assert s["steps"]["count"] == 1


def test_extract_metrics_prefers_telemetry_jsonl(tmp_path):
    """The harvester satellite: a run dir carrying telemetry.jsonl is read
    structurally (full precision + goodput); the console log — present
    with DIFFERENT numbers — must not be consulted."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import extract_metrics as em

    run = tmp_path / "dp2_tp2_pp1_cp1"
    run.mkdir()
    (run / "train.log").write_text(
        "[step 000005] loss: 9.9999 | tokens/s: 1.0K | tokens/s/chip: 500 "
        "| MFU: 1.00% | tokens: 10K | mem: 1.0GB\n")
    events = []
    for s in range(1, 7):
        events.append({"ts": float(s), "kind": "phase", "phase": "step",
                       "step": s, "category": "compute", "secs": 3.0})
        events.append({"ts": float(s), "kind": "step", "step": s,
                       "loss": 6.0 - 0.5 * s, "tokens_per_sec": 2000.0,
                       "tokens_per_sec_per_chip": 500.0, "mfu": 0.45,
                       "trained_tokens": s * 512, "memory_gb": 1.0,
                       "grad_norm": 1.5})
    events.append({"ts": 7.0, "kind": "phase", "phase": "save", "step": 6,
                   "category": "ckpt_io", "secs": 2.0})
    events.append({"ts": 7.5, "kind": "eval", "step": 6, "val_loss": 4.25})
    _write_events(run / "telemetry.jsonl", events)

    stats = em.process_run(str(run), skip_steps=3)
    assert stats["steps"] == 3                      # steps 4..6 from jsonl
    assert stats["final_loss"] == pytest.approx(3.0)  # not the log's 9.9999
    assert stats["mean_mfu_pct"] == pytest.approx(45.0)
    assert stats["mean_grad_norm"] == pytest.approx(1.5)
    assert stats["final_val_loss"] == 4.25
    assert stats["goodput_pct"] == pytest.approx(100.0 * 18 / 20, abs=0.01)

    rows = em.aggregate(str(tmp_path), skip_steps=3)
    assert rows[0]["dp"] == 2 and rows[0]["goodput_pct"] == stats["goodput_pct"]


def test_extract_metrics_telemetry_replay_keeps_last_record(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import extract_metrics as em

    run = tmp_path / "run"
    run.mkdir()
    _write_events(run / "telemetry.jsonl", [
        {"kind": "step", "step": 5, "loss": 99.0, "tokens_per_sec": 1.0,
         "tokens_per_sec_per_chip": 1.0, "mfu": 0.0},
        {"kind": "step", "step": 5, "loss": 2.0, "tokens_per_sec": 1.0,
         "tokens_per_sec_per_chip": 1.0, "mfu": 0.0},  # post-rollback re-run
    ])
    stats = em.process_run(str(run), skip_steps=3)
    assert stats["steps"] == 1 and stats["final_loss"] == 2.0


def test_report_reads_rotated_stream_oldest_first(tmp_path):
    """A size-rotated stream (telemetry.jsonl.1 + telemetry.jsonl) must
    be read oldest segment first: the replayed-step bookkeeping is
    order-sensitive — reading the live segment first would count the
    re-trained step as first-sight and the original as the replay."""
    rep = load_report()
    p = str(tmp_path / "telemetry.jsonl")
    with open(p + ".1", "w") as f:
        for s in (1, 2, 3):
            f.write(json.dumps({"ts": float(s), "kind": "phase",
                                "phase": "step", "step": s,
                                "category": "compute", "secs": 2.0}) + "\n")
    _write_events(p, [
        {"ts": 4.0, "kind": "phase", "phase": "step", "step": 3,
         "category": "compute", "secs": 2.0},  # re-trained after rollback
        {"ts": 5.0, "kind": "phase", "phase": "step", "step": 4,
         "category": "compute", "secs": 2.0},
    ])
    s = rep.summarize(rep.load_events(p))
    assert s["steps"] == {"count": 4, "max": 4, "replayed": 1}
    assert s["categories"]["replay"] == 2.0
    assert s["categories"]["compute"] == 8.0


def test_report_sentinel_section_from_alert_events(tmp_path):
    rep = load_report()
    p = tmp_path / "telemetry.jsonl"
    _write_events(p, [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 1.0},
        {"ts": 2.0, "kind": "sentinel_alert", "quantity": "sync_share",
         "value": 0.45, "baseline": 0.1, "ratio": 4.5, "step": 40},
    ])
    s = rep.summarize(rep.load_events(str(p)))
    assert s["sentinel"] == {"alerts": 1, "quantity": "sync_share",
                             "worst_ratio": 4.5}
    text = rep.render(s)
    assert "sentinel: 1 alert(s) — worst sync_share at 4.50x baseline" \
        in text
    md = rep.render(s, markdown=True)
    assert "**sentinel: 1 alert(s)" in md
    # clean stream: no sentinel row at all
    p2 = tmp_path / "clean.jsonl"
    _write_events(p2, [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 1.0}])
    s2 = rep.summarize(rep.load_events(str(p2)))
    assert "sentinel" not in s2
    assert "sentinel" not in rep.render(s2)


def test_extract_metrics_sentinel_column_and_rotated_stream(tmp_path):
    """The harvester satellite: `sentinel_alerts` is a first-class sweep
    column (0 on a clean run, counted across rotated segments) so a
    regression sweep can filter on it."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import extract_metrics as em

    run = tmp_path / "run"
    run.mkdir()
    p = str(run / "telemetry.jsonl")
    with open(p + ".1", "w") as f:
        for s in range(1, 4):
            f.write(json.dumps({"kind": "step", "step": s, "loss": 3.0,
                                "tokens_per_sec": 1.0,
                                "tokens_per_sec_per_chip": 1.0,
                                "mfu": 0.0}) + "\n")
    _write_events(run / "telemetry.jsonl", [
        {"kind": "sentinel_alert", "quantity": "step_time", "value": 3.0,
         "baseline": 1.0, "ratio": 3.0, "step": 5},
        {"kind": "step", "step": 5, "loss": 2.0, "tokens_per_sec": 1.0,
         "tokens_per_sec_per_chip": 1.0, "mfu": 0.0},
    ])
    stats = em.process_run(str(run), skip_steps=0)
    assert stats["sentinel_alerts"] == 1
    assert stats["steps"] == 4  # both segments consulted
    assert stats["final_loss"] == 2.0

    clean = tmp_path / "clean"
    clean.mkdir()
    _write_events(clean / "telemetry.jsonl", [
        {"kind": "step", "step": 1, "loss": 2.0, "tokens_per_sec": 1.0,
         "tokens_per_sec_per_chip": 1.0, "mfu": 0.0}])
    assert em.process_run(str(clean), skip_steps=0)["sentinel_alerts"] == 0


def test_extract_metrics_falls_back_to_log_when_jsonl_empty(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import extract_metrics as em

    run = tmp_path / "run"
    run.mkdir()
    (run / "telemetry.jsonl").write_text("")
    (run / "train.log").write_text(
        "[step 000005] loss: 4.5000 | tokens/s: 1.0K | tokens/s/chip: 500 "
        "| MFU: 10.00% | tokens: 10K | mem: 1.0GB\n")
    stats = em.process_run(str(run), skip_steps=3)
    assert stats["final_loss"] == 4.5
