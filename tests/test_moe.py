"""Mixture-of-experts tests: routing math, the capacity-bounded dispatch
against a per-expert dense reference, and expert-parallel (ep) layout parity
on the simulated mesh (beyond the reference — SURVEY §2.2 marks EP absent)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import Config, DistributedConfig, ModelConfig, TrainingConfig
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import init_params
from picotron_tpu.ops.moe import moe_mlp, route_topk
from picotron_tpu.parallel.api import init_sharded_state, make_train_step
from picotron_tpu.train_step import init_train_state, make_train_step as make_single_step


def moe_weights(key, e=4, h=16, f=32):
    ks = jax.random.split(key, 4)
    s = 0.1
    return (jax.random.normal(ks[0], (h, e)) * s,
            jax.random.normal(ks[1], (e, h, f)) * s,
            jax.random.normal(ks[2], (e, h, f)) * s,
            jax.random.normal(ks[3], (e, f, h)) * s)


def dense_moe_reference(x, router_w, w_gate, w_up, w_down, top_k):
    """Loop-over-experts reference: every expert runs on every token, the
    top-k mask + renormalized gates select the combination — no capacity."""
    n, h = x.shape
    e = router_w.shape[1]
    probs = jax.nn.softmax(x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    top_p, top_i = jax.lax.top_k(probs, top_k)
    gate = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    out = jnp.zeros((n, h), jnp.float32)
    for j in range(e):
        expert = (jax.nn.silu(x @ w_gate[j]) * (x @ w_up[j])) @ w_down[j]
        w = jnp.sum(jnp.where(top_i == j, gate, 0.0), axis=-1)
        out = out + expert.astype(jnp.float32) * w[:, None]
    return out


def test_route_topk_slots_and_gates():
    logits = jnp.array([[5.0, 1.0, 0.0], [4.0, 3.0, 0.0], [9.0, 0.0, 1.0]])
    r = route_topk(logits, k=2)
    # every token's top-1 is expert 0; slots fill in token order 0,1,2
    np.testing.assert_array_equal(np.asarray(r.expert_idx[:, 0]), [0, 0, 0])
    np.testing.assert_array_equal(np.asarray(r.slot[:, 0]), [0, 1, 2])
    assert bool(r.slot[2, 0] >= 2)  # third assignment overflows capacity 2
    np.testing.assert_allclose(np.asarray(jnp.sum(r.gate, -1)), 1.0, rtol=1e-6)


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_mlp_matches_dense_reference(top_k):
    key = jax.random.key(0)
    router_w, w_gate, w_up, w_down = moe_weights(key)
    x = jax.random.normal(jax.random.key(1), (2, 12, 16))  # [B, S, H]
    out, aux, drop = moe_mlp(x, router_w, w_gate, w_up, w_down,
                             num_experts=4, top_k=top_k, capacity_factor=8.0,
                             router_aux_coef=0.01)  # no drops
    ref = dense_moe_reference(x.reshape(24, 16), router_w, w_gate, w_up,
                              w_down, top_k).reshape(2, 12, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(float(aux))
    assert float(drop) == 0.0


def test_moe_mlp_grads_match_dense_reference():
    key = jax.random.key(0)
    weights = moe_weights(key)
    x = jax.random.normal(jax.random.key(1), (2, 12, 16))

    def loss_moe(x, *w):
        out, _, _ = moe_mlp(x, *w, num_experts=4, top_k=2,
                            capacity_factor=8.0)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(x, *w):
        out = dense_moe_reference(x.reshape(24, 16), *w, top_k=2)
        return jnp.sum(out ** 2)

    gm = jax.grad(loss_moe, argnums=(0, 1, 2, 3, 4))(x, *weights)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, *weights)
    for a, b, name in zip(gm, gr, ["x", "router", "gate", "up", "down"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_moe_capacity_drops_tokens():
    """With capacity_factor << 1 the dispatch drops overflow assignments —
    output differs from the no-capacity reference but stays finite."""
    key = jax.random.key(0)
    router_w, w_gate, w_up, w_down = moe_weights(key)
    x = jax.random.normal(jax.random.key(1), (1, 64, 16))
    out, _, drop = moe_mlp(x, router_w, w_gate, w_up, w_down, num_experts=4,
                           top_k=2, capacity_factor=0.25)
    assert np.all(np.isfinite(np.asarray(out)))
    # the drop-fraction observability scalar reports the overflow
    assert 0.0 < float(drop) < 1.0


# --- layout parity on the simulated mesh ------------------------------------


def moe_cfg(**dist) -> Config:
    gas = dist.pop("gas", 2)
    return Config(
        distributed=DistributedConfig(**dist),
        model=ModelConfig(name="debug-tiny-moe", dtype="float32",
                          num_attention_heads=8, num_key_value_heads=4,
                          num_hidden_layers=2, num_experts=8,
                          num_experts_per_token=2,
                          # generous capacity: drops depend on the per-device
                          # token count, which varies across layouts — a
                          # drop-free regime makes every layout exact
                          capacity_factor=8.0),
        training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                gradient_accumulation_steps=gas,
                                learning_rate=1e-3, remat=False),
    )


def global_batch(cfg, key=0):
    t = cfg.training
    b_global = (t.micro_batch_size * cfg.distributed.dp_size
                * cfg.distributed.ep_size)
    toks = jax.random.randint(jax.random.key(key),
                              (t.gradient_accumulation_steps, b_global,
                               t.seq_length + 1),
                              0, cfg.model.vocab_size)
    return toks[..., :-1], toks[..., 1:]


@pytest.mark.parametrize("dist", [
    dict(ep_size=4),
    # (ep x dp pruned r5: dp is a pure batch psum exercised by every
    # other layout file; ep's own data-axis role is covered by ep_size=4)
    dict(ep_size=2, tp_size=2),
    dict(ep_size=2, tp_size=2, sequence_parallel=True),
    dict(ep_size=2, pp_size=2),
    dict(ep_size=2, pp_size=2, pp_engine="afab"),
    dict(ep_size=2, cp_size=2),
])
@pytest.mark.slow
def test_moe_layouts_match_single_device(dist):
    cfg = moe_cfg(**dist)
    cfg.validate()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    ids, tgt = global_batch(cfg)
    sh = NamedSharding(menv.mesh, P(None, ("dp", "ep"), "cp"))
    batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
    par_losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        par_losses.append(float(metrics["loss"]))

    ref_cfg = Config(model=cfg.model, training=cfg.training)
    params = init_params(ref_cfg.model, jax.random.key(0))
    ref_state = init_train_state(ref_cfg, params)
    ref_step = jax.jit(make_single_step(ref_cfg))
    ref_losses = []
    for _ in range(3):
        ref_state, loss = ref_step(ref_state, (ids, tgt))
        ref_losses.append(float(loss))

    # Tolerance: with router_aux_global (the default) the balance/z
    # statistics are pmean'd over the data axes, so every layout computes
    # the exact global-batch aux loss (VERDICT r2 weak #4 closed; measured
    # aux contribution to layout skew < 2e-4). cp layouts keep a wider
    # band for a DIFFERENT, inherent effect: splitting the sequence changes
    # the router matmul's shape, fp reassociation perturbs near-tie logits,
    # and top-k flips a handful of token->expert assignments — a discrete
    # jump no statistic can absorb (measured ~3e-3 at coef=0 too).
    rtol = 1e-3 if dist.get("cp_size", 1) > 1 else 2e-4
    np.testing.assert_allclose(par_losses, ref_losses, rtol=rtol, atol=2e-5)


@pytest.mark.slow
def test_zero1_with_ep_shards_moments_over_both_data_axes():
    """ZeRO-1 under expert parallelism: non-expert moments shard over the
    fused ('dp','ep') data axes; expert-bank moments (already ep-sharded)
    gain only 'dp'. Training stays numerically identical."""
    cfg = moe_cfg(ep_size=2, dp_size=2, zero1=True)
    cfg.validate()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    ids, tgt = global_batch(cfg)
    sh = NamedSharding(menv.mesh, P(None, ("dp", "ep"), "cp"))
    batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))

    ref_cfg = Config(model=cfg.model, training=cfg.training)
    params = init_params(ref_cfg.model, jax.random.key(0))
    ref_state = init_train_state(ref_cfg, params)
    ref_step = jax.jit(make_single_step(ref_cfg))
    ref_losses = []
    for _ in range(3):
        ref_state, loss = ref_step(ref_state, (ids, tgt))
        ref_losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-3, atol=2e-5)

    def flat_axes(spec):
        return [a for part in spec if part is not None
                for a in (part if isinstance(part, (tuple, list)) else (part,))]

    q_shape = state.params["layers"]["q"].shape
    wg_shape = state.params["layers"]["w_gate"].shape
    q_specs = [x.sharding.spec for x in jax.tree.leaves(state.opt_state)
               if getattr(x, "shape", None) == q_shape]
    wg_specs = [x.sharding.spec for x in jax.tree.leaves(state.opt_state)
                if getattr(x, "shape", None) == wg_shape]
    assert q_specs and wg_specs
    for s in q_specs:  # non-expert: both data axes
        assert {"dp", "ep"} <= set(flat_axes(s)), s
    for s in wg_specs:  # expert bank: ep already shards experts; dp added
        assert "dp" in flat_axes(s), s


def test_route_topk_z_loss_and_z_coef_wiring():
    logits = jnp.array([[5.0, 1.0, 0.0], [4.0, 3.0, 0.0]])
    r = route_topk(logits, k=2)
    expect = float(np.mean(np.asarray(
        jax.nn.logsumexp(logits, axis=-1)) ** 2))
    np.testing.assert_allclose(float(r.z_loss), expect, rtol=1e-6)

    # the coefficient reaches the pre-weighted aux
    w = moe_weights(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 8, 16))
    _, aux0, _ = moe_mlp(x, *w, num_experts=4, top_k=2, capacity_factor=8.0,
                         router_aux_coef=0.01, router_z_coef=0.0)
    _, aux1, _ = moe_mlp(x, *w, num_experts=4, top_k=2, capacity_factor=8.0,
                         router_aux_coef=0.01, router_z_coef=1.0)
    assert float(aux1) > float(aux0)


def test_moe_drop_frac_metric_surfaces_in_step_and_log_line():
    """The capacity drop fraction must reach the step metrics (and via
    train.py, the training_log_line) — drops were previously silent in
    training logs (VERDICT r2 weak #4)."""
    from picotron_tpu.utils import training_log_line

    # tight capacity to force drops
    cfg = moe_cfg(ep_size=2, dp_size=2)
    cfg = Config(
        distributed=cfg.distributed,
        model=ModelConfig(name="debug-tiny-moe", dtype="float32",
                          num_attention_heads=8, num_key_value_heads=4,
                          num_hidden_layers=2, num_experts=8,
                          num_experts_per_token=2, capacity_factor=0.25),
        training=cfg.training,
    )
    cfg.validate()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    ids, tgt = global_batch(cfg)
    sh = NamedSharding(menv.mesh, P(None, ("dp", "ep"), "cp"))
    batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
    _, metrics = step(state, batch)
    drop = float(metrics["moe_drop_frac"])
    assert 0.0 < drop < 1.0, drop

    line = training_log_line(1, float(metrics["loss"]), 1e3, 1e3, 0.1, 1000,
                             extras={"moe_drop_frac": drop})
    assert "moe_drop_frac" in line


@pytest.mark.slow
def test_moe_padded_pp_slots_contribute_no_router_stats():
    """Uneven layer/pp splits pad the stack with zero layers; a padded
    slot's all-zero router must contribute NO z-loss, balance loss, or
    drop-fraction (its uniform logits would otherwise add log(E)^2 z-loss
    per token and tie-broken capacity overflow to the metric). Pinned by
    loss parity against the unpadded single-device run with z-loss ON."""
    import dataclasses

    cfg = moe_cfg(ep_size=2, pp_size=2)
    cfg = Config(
        distributed=cfg.distributed,
        model=dataclasses.replace(cfg.model, num_hidden_layers=3,
                                  router_z_coef=1e-3),
        training=cfg.training,
    )
    cfg.validate()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    ids, tgt = global_batch(cfg)
    sh = NamedSharding(menv.mesh, P(None, ("dp", "ep"), "cp"))
    batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
    par_losses, par_drops = [], []
    for _ in range(3):
        state, metrics = step(state, batch)
        par_losses.append(float(metrics["loss"]))
        par_drops.append(float(metrics["moe_drop_frac"]))

    ref_cfg = Config(model=cfg.model, training=cfg.training)
    params = init_params(ref_cfg.model, jax.random.key(0))
    ref_state = init_train_state(ref_cfg, params)
    ref_step = jax.jit(make_single_step(ref_cfg))
    ref_losses = []
    for _ in range(3):
        ref_state, loss = ref_step(ref_state, (ids, tgt))
        ref_losses.append(float(loss))
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-5)
    # generous capacity: nothing drops, and padding must not fake drops
    assert all(d == 0.0 for d in par_drops), par_drops
