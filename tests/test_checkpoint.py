"""Checkpoint tests: save/resume round trip, cross-topology resharding on
restore (the improvement over the reference's identical-topology assertion,
ref: checkpoint.py:263), and the HF safetensors import/export round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.config import (
    CheckpointConfig, Config, DistributedConfig, ModelConfig, TrainingConfig,
)
from picotron_tpu.checkpoint import (
    CheckpointManager, load_hf_safetensors, save_hf_safetensors,
)
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.models.llama import forward, init_params
from picotron_tpu.parallel.api import init_sharded_state, make_train_step


def make_cfg(tmp_path, **dist):
    return Config(
        distributed=DistributedConfig(**dist),
        model=ModelConfig(dtype="float32", num_attention_heads=8,
                          num_key_value_heads=4),
        training=TrainingConfig(seq_length=32, micro_batch_size=1,
                                gradient_accumulation_steps=1, remat=False),
        checkpoint=CheckpointConfig(save_dir=str(tmp_path / "ckpt")),
    )


def batch_for(cfg, menv):
    t = cfg.training
    b = t.micro_batch_size * cfg.distributed.dp_size
    toks = jax.random.randint(
        jax.random.key(7), (t.gradient_accumulation_steps, b, t.seq_length + 1),
        0, cfg.model.vocab_size)
    sh = menv.batch_sharding()
    return (jax.device_put(toks[..., :-1], sh),
            jax.device_put(toks[..., 1:], sh))


def test_save_restore_roundtrip(tmp_path):
    cfg = make_cfg(tmp_path, dp_size=2, tp_size=2)
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    state, _ = step(state, batch_for(cfg, menv))

    mgr = CheckpointManager(cfg, menv)
    mgr.save(state, trained_tokens=1234,
             dataloader_state={"epoch": 2, "cursor": 6})
    mgr.wait_until_finished()  # async save: durable only after the barrier
    assert mgr.latest_step() == 1

    template = init_sharded_state(cfg, menv, jax.random.key(99))
    restored, meta = mgr.restore(template)
    assert meta["trained_tokens"] == 1234
    assert meta["dataloader"] == {"epoch": 2, "cursor": 6}
    assert int(restored.step) == 1
    np.testing.assert_array_equal(
        np.asarray(restored.params["embedding"]),
        np.asarray(state.params["embedding"]))
    # optimizer state restored too (ref: checkpoint.py:256 stores it)
    got_leaves = jax.tree_util.tree_leaves(restored.opt_state)
    want_leaves = jax.tree_util.tree_leaves(state.opt_state)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    # the restored state must be directly trainable (placement-consistent)
    stepped, _ = step(restored, batch_for(cfg, menv))
    assert int(stepped.step) == 2


def test_async_save_overlaps_training_and_survives_donation(tmp_path):
    """Async save must capture the state at save() time: the trainer keeps
    stepping (with donated buffers!) while the write is in flight, so a
    save that lazily read device memory would persist garbage. Also pins
    that resume-from-an-async-save works and that wait_until_finished
    makes it durable (VERDICT r2 next-round #6)."""
    cfg = make_cfg(tmp_path, dp_size=2, tp_size=2)
    assert cfg.checkpoint.async_save  # the default
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    batch = batch_for(cfg, menv)
    state, _ = step(state, batch)
    saved_embedding = np.asarray(state.params["embedding"]).copy()

    mgr = CheckpointManager(cfg, menv)
    mgr.save(state, trained_tokens=99)
    # training continues while the write is (possibly still) in flight;
    # donation invalidates the old state buffers
    for _ in range(3):
        state, _ = step(state, batch)
    assert int(state.step) == 4
    mgr.wait_until_finished()
    assert mgr.latest_step() == 1

    template = init_sharded_state(cfg, menv, jax.random.key(99))
    restored, meta = mgr.restore(template)
    assert int(restored.step) == 1 and meta["trained_tokens"] == 99
    np.testing.assert_array_equal(
        np.asarray(restored.params["embedding"]), saved_embedding)


def test_latest_step_skips_unfinalized_checkpoints(tmp_path):
    """A crashed/in-flight async save leaves a step dir without a finalized
    `state` directory — restore must never be pointed at it."""
    cfg = make_cfg(tmp_path, dp_size=2)
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    mgr = CheckpointManager(cfg, menv)
    mgr.save(state)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 0
    # simulate a torn step_5 save: meta.json present, state dir absent
    torn = tmp_path / "ckpt" / "step_00000005"
    torn.mkdir(parents=True)
    (torn / "meta.json").write_text("{}")
    assert mgr.latest_step() == 0


def _save_two_steps(tmp_path, cfg, menv):
    """Two durable, verified real checkpoints (steps 1 and 2)."""
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    mgr = CheckpointManager(cfg, menv)
    batch = batch_for(cfg, menv)
    state, _ = step(state, batch)
    mgr.save(state, trained_tokens=100)
    state, _ = step(state, batch)
    mgr.save(state, trained_tokens=200)
    mgr.wait_until_finished()
    return mgr, state


def test_torn_meta_json_falls_back_to_prior_verified(tmp_path):
    """A crash (or chaos ckpt_torn_meta) tearing the newest step's
    meta.json must cost a lineage fallback, not a JSONDecodeError at
    resume: latest_valid_step skips it, restore lands on the prior
    verified step."""
    cfg = make_cfg(tmp_path, dp_size=2)
    menv = MeshEnv.from_config(cfg)
    mgr, _ = _save_two_steps(tmp_path, cfg, menv)
    meta_path = tmp_path / "ckpt" / "step_00000002" / "meta.json"
    meta_path.write_bytes(meta_path.read_bytes()[:40])  # torn mid-write
    assert mgr.latest_step() == 2          # still durable...
    assert mgr.latest_valid_step() == 1    # ...but no longer trusted
    template = init_sharded_state(cfg, menv, jax.random.key(9))
    restored, meta = mgr.restore(template)
    assert int(restored.step) == 1 and meta["trained_tokens"] == 100


def test_valid_manifest_with_deleted_array_file_falls_back(tmp_path):
    """Manifest present and well-formed, but an array payload file was
    deleted (partial store loss): verification flags the missing leaf and
    the lineage walk falls back."""
    import os

    cfg = make_cfg(tmp_path, dp_size=2)
    menv = MeshEnv.from_config(cfg)
    mgr, _ = _save_two_steps(tmp_path, cfg, menv)
    state_dir = tmp_path / "ckpt" / "step_00000002" / "state"
    victim = max(
        (p for p in state_dir.rglob("*") if p.is_file()),
        key=lambda p: p.stat().st_size)
    os.remove(victim)
    res = mgr.verify_step(2)
    assert res.status == "corrupt"
    assert any("missing" in f for f in res.failures)
    assert mgr.latest_valid_step() == 1
    template = init_sharded_state(cfg, menv, jax.random.key(9))
    restored, _ = mgr.restore(template)
    assert int(restored.step) == 1


def test_explicit_step_restore_validates_durability(tmp_path):
    """restore(step=N) used to skip the durability probe and die on a raw
    JSON/Orbax error; now it validates first and names the valid steps."""
    cfg = make_cfg(tmp_path, dp_size=2)
    menv = MeshEnv.from_config(cfg)
    mgr, _ = _save_two_steps(tmp_path, cfg, menv)
    # a torn step dir: meta.json only, no finalized state (crashed save)
    torn = tmp_path / "ckpt" / "step_00000007"
    torn.mkdir(parents=True)
    (torn / "meta.json").write_text("{}")
    template = init_sharded_state(cfg, menv, jax.random.key(9))
    with pytest.raises(FileNotFoundError,
                       match=r"not durable.*available valid steps.*\[1, 2\]"):
        mgr.restore(template, step=7)
    with pytest.raises(FileNotFoundError, match="available valid steps"):
        mgr.restore(template, step=42)  # absent entirely


def test_gc_keeps_last_verified_with_keep_last_1(tmp_path):
    """Retention GC under keep_last=1 with a corrupt newest checkpoint:
    the last verified step must survive the prune and still restore."""
    import dataclasses
    import os

    cfg = make_cfg(tmp_path, dp_size=2)
    menv = MeshEnv.from_config(cfg)
    mgr, _ = _save_two_steps(tmp_path, cfg, menv)
    state_dir = tmp_path / "ckpt" / "step_00000002" / "state"
    victim = max((p for p in state_dir.rglob("*") if p.is_file()),
                 key=lambda p: p.stat().st_size)
    os.truncate(victim, 1)
    cfg1 = dataclasses.replace(
        cfg, checkpoint=dataclasses.replace(cfg.checkpoint, keep_last=1))
    res = CheckpointManager(cfg1, menv).gc()
    assert res["deleted"] == [] and res["kept"] == [1, 2]
    assert CheckpointManager(cfg1, menv).latest_valid_step() == 1


def test_restore_across_topologies(tmp_path):
    """Save under dp=2,tp=2 / restore under dp=1,tp=2 with
    checkpoint.elastic on: Orbax reshards into the template's shardings —
    the reference hard-fails on this (ref: checkpoint.py:263 resume
    assumes identical topology). Gradient accumulation doubles so the
    global batch is unchanged (the elastic invariant); the restore must
    surface the resize record it booked. Only dp/pp are resizable —
    changing tp here would be a hard error (see test_elastic.py)."""
    import dataclasses

    cfg_a = make_cfg(tmp_path, dp_size=2, tp_size=2)
    menv_a = MeshEnv.from_config(cfg_a)
    state = init_sharded_state(cfg_a, menv_a, jax.random.key(0))
    CheckpointManager(cfg_a, menv_a).save(state)

    cfg_b = make_cfg(tmp_path, tp_size=2)
    cfg_b = dataclasses.replace(
        cfg_b,
        training=dataclasses.replace(cfg_b.training,
                                     gradient_accumulation_steps=2),
        checkpoint=dataclasses.replace(cfg_b.checkpoint, elastic=True))
    assert cfg_b.global_batch_size == cfg_a.global_batch_size
    menv_b = MeshEnv.from_config(cfg_b)
    template = init_sharded_state(cfg_b, menv_b, jax.random.key(1))
    restored, meta = CheckpointManager(cfg_b, menv_b).restore(template)
    np.testing.assert_array_equal(
        np.asarray(restored.params["layers"]["q"]),
        np.asarray(state.params["layers"]["q"]))
    # restored arrays carry the *new* topology's shardings
    assert restored.params["layers"]["q"].sharding == template.params["layers"]["q"].sharding
    resize = meta["elastic_resize"]
    assert resize["axes"] == ["dp"]
    assert resize["from"]["dp"] == 2 and resize["to"]["dp"] == 1


def test_restore_topology_mismatch_raises_without_elastic(tmp_path):
    """The satellite bugfix: restoring into a mismatching mesh with
    elastic OFF must be a hard error naming both topologies and the
    offline re-stamp invocation — never a silent wrong-shape resume."""
    cfg_a = make_cfg(tmp_path, dp_size=2, tp_size=2)
    menv_a = MeshEnv.from_config(cfg_a)
    state = init_sharded_state(cfg_a, menv_a, jax.random.key(0))
    CheckpointManager(cfg_a, menv_a).save(state)

    cfg_b = make_cfg(tmp_path, tp_size=2)
    menv_b = MeshEnv.from_config(cfg_b)
    template = init_sharded_state(cfg_b, menv_b, jax.random.key(1))
    with pytest.raises(RuntimeError) as exc:
        CheckpointManager(cfg_b, menv_b).restore(template)
    msg = str(exc.value)
    assert "dp2 pp1 ep1 cp1 tp2" in msg    # saved topology
    assert "dp1 pp1 ep1 cp1 tp2" in msg    # this run's mesh
    assert "tools/elastic_resize.py" in msg
    assert "--dp 1" in msg                 # re-stamp to the run's mesh
    assert "checkpoint.elastic" in msg


def test_hf_safetensors_roundtrip(tmp_path):
    cfg = ModelConfig(dtype="float32")
    params = init_params(cfg, jax.random.key(3))
    save_hf_safetensors(params, str(tmp_path / "hf"))
    loaded = load_hf_safetensors(str(tmp_path / "hf"), cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, loaded)
    # and the loaded tree actually runs
    ids = jax.random.randint(jax.random.key(4), (1, 16), 0, cfg.vocab_size)
    logits = forward(loaded, ids, cfg)
    assert logits.shape == (1, 16, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_hf_safetensors_roundtrip_moe(tmp_path):
    """Mixtral-style MoE naming (block_sparse_moe.gate /
    experts.<j>.{w1,w2,w3}) round-trips through export -> import."""
    cfg = ModelConfig(dtype="float32", num_experts=4, num_experts_per_token=2)
    params = init_params(cfg, jax.random.key(5))
    save_hf_safetensors(params, str(tmp_path / "hf"))
    # spot-check the on-disk naming is Mixtral's
    from safetensors.numpy import load_file
    tensors = load_file(str(tmp_path / "hf" / "model.safetensors"))
    assert "model.layers.0.block_sparse_moe.gate.weight" in tensors
    assert "model.layers.0.block_sparse_moe.experts.3.w2.weight" in tensors
    loaded = load_hf_safetensors(str(tmp_path / "hf"), cfg)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, loaded)


def test_tied_head_checkpoint_unties(tmp_path):
    """A checkpoint without lm_head.weight falls back to embedding^T
    (ref: checkpoint.py:88-91 force-creates the untied head)."""
    import os
    from safetensors.numpy import load_file, save_file

    cfg = ModelConfig(dtype="float32")
    params = init_params(cfg, jax.random.key(3))
    save_hf_safetensors(params, str(tmp_path / "hf"))
    p = str(tmp_path / "hf" / "model.safetensors")
    tensors = load_file(p)
    del tensors["lm_head.weight"]
    save_file(tensors, p)

    loaded = load_hf_safetensors(str(tmp_path / "hf"), cfg)
    np.testing.assert_array_equal(
        np.asarray(loaded["lm_head"]),
        np.asarray(loaded["embedding"]).T)


def test_hf_safetensors_roundtrip_qwen2_bias_tied(tmp_path):
    """Qwen2-style checkpoints: qkv biases map to b_q/b_k/b_v (no
    transpose), and a tied config materializes NO lm_head param — while the
    same file loaded as an untied config unties by copying the embedding."""
    import dataclasses

    from picotron_tpu.config import resolve_preset
    from picotron_tpu.models.llama import head_weight, init_params

    cfg = ModelConfig(dtype="float32", **resolve_preset("debug-tiny-qwen"))
    params = init_params(cfg, jax.random.key(3))
    # make the biases non-trivial so the roundtrip actually checks them
    params["layers"]["b_q"] = jax.random.normal(
        jax.random.key(4), params["layers"]["b_q"].shape)
    save_hf_safetensors(params, str(tmp_path / "hf"))

    back = load_hf_safetensors(str(tmp_path / "hf"), cfg)
    assert "lm_head" not in back
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-6),
        params, back)

    untied = dataclasses.replace(cfg, tie_word_embeddings=False)
    back2 = load_hf_safetensors(str(tmp_path / "hf"), untied)
    np.testing.assert_allclose(np.asarray(back2["lm_head"]),
                               np.asarray(head_weight(params)), rtol=1e-6)


def test_hf_load_rejects_layer_count_mismatch(tmp_path):
    """A config expecting fewer layers than the file holds must error, not
    silently truncate the model (caught live: a 10-layer export loaded
    through a 4-layer preset)."""
    cfg10 = ModelConfig(dtype="float32", num_hidden_layers=6)
    save_hf_safetensors(init_params(cfg10, jax.random.key(0)),
                        str(tmp_path / "hf"))
    with pytest.raises(ValueError, match="6 layers but the config"):
        load_hf_safetensors(str(tmp_path / "hf"),
                            ModelConfig(dtype="float32", num_hidden_layers=4))
