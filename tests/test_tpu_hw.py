"""On-hardware kernel regression tests — `pytest -m tpu` on the bench chip.

The regular suite exercises the Pallas kernels in interpreter mode on the
simulated CPU mesh; these run the COMPILED kernels on the real TPU and gate
them against the jnp reference (the pytest version of tools/flash_smoke.py —
VERDICT r2 next-round #8: hardware kernel correctness as a one-command check
instead of a manual script). Tolerances are bf16-level: blockwise-vs-fused
softmax reassociation puts maxdiffs in the 0.01-0.25 band on real data.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu

_on_tpu = jax.devices()[0].platform == "tpu"
if _on_tpu:  # imports are safe either way; guard only the device check
    pass


@pytest.fixture(scope="module")
def qkv():
    if not _on_tpu:
        pytest.skip("no TPU attached")
    ks = jax.random.split(jax.random.key(0), 3)
    b, s, hq, hkv, d = 2, 2048, 16, 4, 64
    return (jax.random.normal(ks[0], (b, s, hq, d), jnp.bfloat16),
            jax.random.normal(ks[1], (b, s, hkv, d), jnp.bfloat16),
            jax.random.normal(ks[2], (b, s, hkv, d), jnp.bfloat16))


def _maxdiff(a, b):
    return float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())


def test_flash_forward_matches_sdpa_on_chip(qkv):
    from picotron_tpu.ops.attention import sdpa_attention
    from picotron_tpu.ops.flash_attention import flash_attention

    q, k, v = qkv
    got = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, interpret=False))(q, k, v)
    want = jax.jit(lambda q, k, v: sdpa_attention(q, k, v, causal=True))(
        q, k, v)
    assert _maxdiff(got, want) < 0.05


def test_flash_backward_matches_sdpa_on_chip(qkv):
    from picotron_tpu.ops.attention import sdpa_attention
    from picotron_tpu.ops.flash_attention import flash_attention

    q, k, v = qkv

    def floss(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, interpret=False).astype(jnp.float32) ** 2)

    def rloss(q, k, v):
        return jnp.sum(
            sdpa_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

    got = jax.jit(jax.grad(floss, (0, 1, 2)))(q, k, v)
    want = jax.jit(jax.grad(rloss, (0, 1, 2)))(q, k, v)
    for x, y, n in zip(got, want, "qkv"):
        # grads of a sum-of-squares over 2048 tokens: bf16 accumulation
        # reassociation puts the band well above fwd's
        assert _maxdiff(x, y) < 0.5, f"d{n}"


def test_flash_fused_rope_matches_unfused_on_chip(qkv):
    from picotron_tpu.ops.flash_attention import flash_attention
    from picotron_tpu.ops.rope import apply_rope, rope_tables

    q, k, v = qkv
    s, d = q.shape[1], q.shape[3]
    cos, sin = rope_tables(s, d)

    def fused(q, k, v):
        return flash_attention(q, k, v, causal=True, rope=(cos, sin),
                               interpret=False).astype(jnp.float32)

    def unfused(q, k, v):
        return flash_attention(apply_rope(q, cos, sin),
                               apply_rope(k, cos, sin), v, causal=True,
                               interpret=False).astype(jnp.float32)

    got = jax.jit(fused)(q, k, v)
    want = jax.jit(unfused)(q, k, v)
    assert _maxdiff(got, want) < 0.05
    gf = jax.jit(jax.grad(lambda *a: jnp.sum(fused(*a) ** 2), (0, 1, 2)))
    gu = jax.jit(jax.grad(lambda *a: jnp.sum(unfused(*a) ** 2), (0, 1, 2)))
    for x, y, n in zip(gf(q, k, v), gu(q, k, v), "qkv"):
        assert _maxdiff(x, y) < 0.5, f"d{n}"


def test_train_step_runs_on_chip():
    """One real bf16 train step of a depth-reduced SmolLM on the chip —
    the bench path's compile+execute sanity, minus the timing."""
    if not _on_tpu:
        pytest.skip("no TPU attached")
    from picotron_tpu.config import (
        Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset,
    )
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    preset = resolve_preset("SmolLM-360M")
    preset["num_hidden_layers"] = 4
    cfg = Config(
        distributed=DistributedConfig(dp_size=1),
        model=ModelConfig(name="SmolLM-360M", **preset),
        training=TrainingConfig(seq_length=512, micro_batch_size=1,
                                gradient_accumulation_steps=1, remat=True),
    )
    cfg.validate()
    menv = MeshEnv.create(dp=1, devices=jax.devices()[:1])
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    toks = jax.random.randint(jax.random.key(1), (1, 1, 513), 0,
                              cfg.model.vocab_size)
    sh = menv.batch_sharding()
    batch = (jax.device_put(toks[..., :-1], sh),
             jax.device_put(toks[..., 1:], sh))
    state, metrics = step(state, batch)
    loss = float(metrics["loss"])
    assert np.isfinite(loss) and 2.0 < loss < 20.0, loss


def test_optimizer_offload_pinned_host_on_chip():
    """optimizer_offload with REAL memory placement: the fp32 master + Adam
    moments must live in pinned_host, the compute copy in device memory,
    and a step must run and keep the kinds (the CPU-mesh offload tests run
    the same code path placement-free — offload_memory_kind is None there)."""
    if not _on_tpu:
        pytest.skip("no TPU attached")
    from picotron_tpu.config import (
        Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset,
    )
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    preset = resolve_preset("SmolLM-360M")
    preset["num_hidden_layers"] = 4
    cfg = Config(
        distributed=DistributedConfig(dp_size=1),
        model=ModelConfig(name="SmolLM-360M", **preset),
        training=TrainingConfig(seq_length=512, micro_batch_size=1,
                                gradient_accumulation_steps=2, remat=True,
                                adam_moments_dtype="bfloat16",
                                optimizer_offload=True),
    )
    cfg.validate()
    menv = MeshEnv.create(dp=1, devices=jax.devices()[:1])
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    assert jax.tree.leaves(state.opt_state.master)[0].sharding.memory_kind \
        == "pinned_host"
    assert jax.tree.leaves(state.opt_state.mu)[0].sharding.memory_kind \
        == "pinned_host"
    assert jax.tree.leaves(state.params)[0].sharding.memory_kind == "device"
    assert jax.tree.leaves(state.params)[0].dtype == jnp.bfloat16

    step = make_train_step(cfg, menv)
    toks = jax.random.randint(jax.random.key(1), (2, 1, 513), 0,
                              cfg.model.vocab_size)
    sh = menv.batch_sharding()
    batch = (jax.device_put(toks[..., :-1], sh),
             jax.device_put(toks[..., 1:], sh))
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # repeated batch must memorize
    # the state kinds survive the donated round trip
    assert jax.tree.leaves(state.opt_state.master)[0].sharding.memory_kind \
        == "pinned_host"
    assert int(state.opt_state.count) == 3
