"""Test scaffold: an 8-device simulated CPU mesh in a single process.

This upgrades the reference's test story (two standalone torchrun scripts
needing 4 GPUs + NCCL, ref: tests/test_tensor_parallel.py:2) to pytest on a
host-platform simulated mesh — SURVEY.md §4's recommendation.

Note: the environment's sitecustomize imports jax and registers a TPU backend
at interpreter startup, so env-var-only platform selection is too late here;
we force CPU via jax.config before any backend client is created. Only
bench.py touches the real chip.
"""

import os
import sys


def _tpu_marker_requested(argv) -> bool:
    """`pytest -m tpu` selects the on-hardware tests (tests/test_tpu_hw.py)
    — those need the REAL chip, so the CPU forcing below must not run."""
    for i, a in enumerate(argv):
        expr = None
        if a == "-m" and i + 1 < len(argv):
            expr = argv[i + 1]
        elif a.startswith("-m=") or a.startswith("--markexpr="):
            expr = a.split("=", 1)[1]
        if expr and "tpu" in expr and "not tpu" not in expr:
            return True
    return False


ON_HARDWARE = _tpu_marker_requested(sys.argv)

if not ON_HARDWARE:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

if not ON_HARDWARE:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: on-hardware kernel regression tests (run `pytest -m tpu` on "
        "a machine with a real TPU; skipped/deselected otherwise)")
    config.addinivalue_line(
        "markers",
        "slow: multi-process integration and heavy layout-parity compiles. "
        "Dev loop: `pytest -m 'not slow'` (< 10 min); CI/full: plain "
        "`pytest tests/` runs everything — semantics identical, the marker "
        "only partitions wall-time")


def pytest_collection_modifyitems(config, items):
    if ON_HARDWARE:
        return
    skip = pytest.mark.skip(
        reason="needs a real TPU; run `pytest -m tpu` on the bench chip")
    for item in items:
        if "tpu" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def slice_partition(devices):
    """The 8 simulated host devices partitioned into 2 declared 'slices'.

    Host CPUs carry no slice_index, so the partition is positional —
    devices [0..3] are slice 0, [4..7] slice 1 — matching the house rule
    (mesh._split_axes_over_dcn) that the slice granule is the OUTER
    factor of the first DCN-tolerant axis: on the row-major
    (dp, pp, ep, cp, tp) grid, the outer half of the leading cut axis is
    exactly the first four flat device ids. The slice-boundary tests
    (tests/test_boundary.py) audit traced replica groups against this
    partition via analysis.boundary.SliceTopology."""
    n = len(devices)
    return {0: tuple(range(n // 2)), 1: tuple(range(n // 2, n))}
