"""Test scaffold: an 8-device simulated CPU mesh in a single process.

This upgrades the reference's test story (two standalone torchrun scripts
needing 4 GPUs + NCCL, ref: tests/test_tensor_parallel.py:2) to pytest on a
host-platform simulated mesh — SURVEY.md §4's recommendation.

Note: the environment's sitecustomize imports jax and registers a TPU backend
at interpreter startup, so env-var-only platform selection is too late here;
we force CPU via jax.config before any backend client is created. Only
bench.py touches the real chip.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 simulated devices, got {len(devs)}"
    return devs
