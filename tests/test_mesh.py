import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu.mesh import AXES, MeshEnv


def test_mesh_axes_and_sizes(devices):
    env = MeshEnv.create(dp=2, pp=2, cp=1, tp=2)
    assert env.mesh.axis_names == AXES
    assert (env.dp, env.pp, env.cp, env.tp) == (2, 2, 1, 2)
    assert env.world_size == 8


def test_tp_innermost(devices):
    # TP must be the fastest-varying axis: adjacent device ids in the same tp
    # group (ref: process_group_manager.py:13 grid layout).
    env = MeshEnv.create(dp=2, pp=1, cp=2, tp=2)
    grid = np.array(env.mesh.devices)
    ids = np.vectorize(lambda d: d.id)(grid)
    # along tp, ids are consecutive
    assert (ids[..., 1] - ids[..., 0] == 1).all()


def test_oversubscription_raises(devices):
    with pytest.raises(ValueError):
        MeshEnv.create(dp=4, pp=2, cp=2, tp=2)


def test_batch_sharding_slices_seq_over_cp(devices):
    env = MeshEnv.create(dp=2, cp=2, tp=2)
    x = np.arange(1 * 4 * 8, dtype=np.int32).reshape(1, 4, 8)
    arr = jax.device_put(x, env.batch_sharding())
    # each shard holds the full micro dim, batch/dp, seq/cp
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (1, 2, 4)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_cluster_env_detection():
    from picotron_tpu.mesh import _cluster_env_detected

    assert not _cluster_env_detected({})
    assert not _cluster_env_detected({"TPU_WORKER_HOSTNAMES": ""})
    assert not _cluster_env_detected({"TPU_WORKER_HOSTNAMES": "host0"})
    assert _cluster_env_detected({"TPU_WORKER_HOSTNAMES": "host0,host1"})
    assert _cluster_env_detected({"COORDINATOR_ADDRESS": "10.0.0.1:1234"})
    assert _cluster_env_detected({"SLURM_JOB_ID": "42"})
    assert _cluster_env_detected({"OMPI_COMM_WORLD_SIZE": "4"})


def test_multihost_initialize_singlehost_noop():
    """On a single host (no cluster env), multihost_initialize must be a
    no-op rather than hanging waiting for a coordinator (SURVEY §2 row 22:
    the launcher path)."""
    import os

    from picotron_tpu.mesh import multihost_initialize

    saved = {k: os.environ.pop(k, None) for k in
             ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
              "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
              "TPU_WORKER_HOSTNAMES")}
    try:
        multihost_initialize()  # returns immediately, initializes nothing
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
