import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu.mesh import AXES, MeshEnv


def test_mesh_axes_and_sizes(devices):
    env = MeshEnv.create(dp=2, pp=2, cp=1, tp=2)
    assert env.mesh.axis_names == AXES
    assert (env.dp, env.pp, env.cp, env.tp) == (2, 2, 1, 2)
    assert env.world_size == 8


def test_tp_innermost(devices):
    # TP must be the fastest-varying axis: adjacent device ids in the same tp
    # group (ref: process_group_manager.py:13 grid layout).
    env = MeshEnv.create(dp=2, pp=1, cp=2, tp=2)
    grid = np.array(env.mesh.devices)
    ids = np.vectorize(lambda d: d.id)(grid)
    # along tp, ids are consecutive
    assert (ids[..., 1] - ids[..., 0] == 1).all()


def test_oversubscription_raises(devices):
    with pytest.raises(ValueError):
        MeshEnv.create(dp=4, pp=2, cp=2, tp=2)


def test_batch_sharding_slices_seq_over_cp(devices):
    env = MeshEnv.create(dp=2, cp=2, tp=2)
    x = np.arange(1 * 4 * 8, dtype=np.int32).reshape(1, 4, 8)
    arr = jax.device_put(x, env.batch_sharding())
    # each shard holds the full micro dim, batch/dp, seq/cp
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (1, 2, 4)
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_cluster_env_detection():
    from picotron_tpu.mesh import _cluster_env_detected

    assert not _cluster_env_detected({})
    assert not _cluster_env_detected({"TPU_WORKER_HOSTNAMES": ""})
    assert not _cluster_env_detected({"TPU_WORKER_HOSTNAMES": "host0"})
    assert _cluster_env_detected({"TPU_WORKER_HOSTNAMES": "host0,host1"})
    assert _cluster_env_detected({"COORDINATOR_ADDRESS": "10.0.0.1:1234"})
    # Single-task launches must stay local even under a launcher env: a
    # 1-task mpirun or a single-node SLURM interactive shell would hang in
    # jax.distributed.initialize() waiting for a coordinator (ADVICE r2).
    assert not _cluster_env_detected({"OMPI_COMM_WORLD_SIZE": "1"})
    assert not _cluster_env_detected({"OMPI_COMM_WORLD_SIZE": "garbage"})
    assert _cluster_env_detected({"OMPI_COMM_WORLD_SIZE": "4"})
    assert not _cluster_env_detected({"SLURM_JOB_ID": "42"})
    assert not _cluster_env_detected({"SLURM_JOB_ID": "42",
                                      "SLURM_NTASKS": "1"})
    assert _cluster_env_detected({"SLURM_JOB_ID": "42",
                                  "SLURM_NTASKS": "8"})
    assert _cluster_env_detected({"SLURM_JOB_ID": "42",
                                  "SLURM_JOB_NUM_NODES": "2"})
    # NTASKS without a SLURM job id is not a SLURM launch
    assert not _cluster_env_detected({"SLURM_NTASKS": "8"})


def test_split_axes_over_dcn():
    from picotron_tpu.mesh import _split_axes_over_dcn

    # 2 slices absorbed by dp
    dcn, per = _split_axes_over_dcn((4, 2, 1, 1, 2), 2)
    assert dcn == (2, 1, 1, 1, 1) and per == (2, 2, 1, 1, 2)
    # 4 slices: dp takes 2, pp takes the remaining 2
    dcn, per = _split_axes_over_dcn((2, 2, 1, 2, 2), 4)
    assert dcn == (2, 2, 1, 1, 1) and per == (1, 1, 1, 2, 2)
    # slice counts that would have to split ep/cp/tp over DCN must raise —
    # even when the inner axis sizes are divisible (tp=8, 2 slices)
    with pytest.raises(ValueError, match="DCN-tolerant"):
        _split_axes_over_dcn((1, 1, 1, 1, 8), 2)
    with pytest.raises(ValueError, match="DCN-tolerant"):
        _split_axes_over_dcn((1, 1, 1, 4, 2), 2)
    with pytest.raises(ValueError, match="DCN-tolerant"):
        _split_axes_over_dcn((1, 1, 1, 1, 8), 3)


def test_topology_grid_unsatisfiable_multislice_raises(devices):
    """A slice count dp*pp cannot absorb must be a hard layout error, not a
    warning + naive reshape that silently routes tp over DCN."""
    from picotron_tpu import mesh as mesh_mod

    class FakeDev:
        def __init__(self, d, s):
            self._d = d
            self.slice_index = s

        def __getattr__(self, name):
            return getattr(self._d, name)

    devs = [FakeDev(d, i // 4) for i, d in enumerate(devices[:8])]
    with pytest.raises(ValueError, match="DCN-tolerant"):
        mesh_mod._topology_grid((1, 1, 1, 2, 4), devs)


def test_launcher_contract_partial_raises(monkeypatch):
    from picotron_tpu.mesh import launcher_contract

    for k in ("PICOTRON_COORDINATOR", "PICOTRON_NUM_PROCESSES",
              "PICOTRON_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    assert launcher_contract() is None
    monkeypatch.setenv("PICOTRON_NUM_PROCESSES", "2")
    with pytest.raises(ValueError, match="partial PICOTRON"):
        launcher_contract()
    monkeypatch.setenv("PICOTRON_COORDINATOR", "127.0.0.1:1234")
    monkeypatch.setenv("PICOTRON_PROCESS_ID", "0")
    assert launcher_contract() == ("127.0.0.1:1234", 2, 0)


def test_topology_grid_routes_multislice_to_hybrid(devices, monkeypatch):
    """Devices reporting distinct slice_index values must go through
    create_hybrid_device_mesh with dp over DCN (VERDICT r2 missing #1)."""
    from jax.experimental import mesh_utils

    from picotron_tpu import mesh as mesh_mod

    calls = {}

    def fake_hybrid(per_slice, dcn, devices=None, **kw):
        calls["per_slice"], calls["dcn"] = tuple(per_slice), tuple(dcn)
        return np.array(devices).reshape(
            tuple(a * b for a, b in zip(per_slice, dcn)))

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)

    class FakeDev:
        def __init__(self, d, s):
            self._d = d
            self.slice_index = s

        def __getattr__(self, name):
            return getattr(self._d, name)

    devs = [FakeDev(d, i // 4) for i, d in enumerate(devices[:8])]
    grid = mesh_mod._topology_grid((2, 2, 1, 1, 2), devs)
    assert grid.shape == (2, 2, 1, 1, 2)
    assert calls["dcn"] == (2, 1, 1, 1, 1)
    assert calls["per_slice"] == (1, 2, 1, 1, 2)


def test_topology_grid_fallback_on_mesh_utils_failure(devices, monkeypatch):
    from jax.experimental import mesh_utils

    from picotron_tpu import mesh as mesh_mod

    def boom(*a, **kw):
        raise ValueError("unsatisfiable torus mapping")

    monkeypatch.setattr(mesh_utils, "create_device_mesh", boom)
    with pytest.warns(UserWarning, match="topology-aware"):
        grid = mesh_mod._topology_grid((2, 1, 1, 2, 2), list(devices[:8]))
    ids = np.vectorize(lambda d: d.id)(grid)
    assert (ids.ravel() == [d.id for d in devices[:8]]).all()


def test_multihost_initialize_singlehost_noop():
    """On a single host (no cluster env), multihost_initialize must be a
    no-op rather than hanging waiting for a coordinator (SURVEY §2 row 22:
    the launcher path)."""
    import os

    from picotron_tpu.mesh import multihost_initialize

    saved = {k: os.environ.pop(k, None) for k in
             ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
              "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE",
              "TPU_WORKER_HOSTNAMES")}
    try:
        multihost_initialize()  # returns immediately, initializes nothing
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
