import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu.mesh import AXES, MeshEnv


def test_mesh_axes_and_sizes(devices):
    env = MeshEnv.create(dp=2, pp=2, cp=1, tp=2)
    assert env.mesh.axis_names == AXES
    assert (env.dp, env.pp, env.cp, env.tp) == (2, 2, 1, 2)
    assert env.world_size == 8


def test_tp_innermost(devices):
    # TP must be the fastest-varying axis: adjacent device ids in the same tp
    # group (ref: process_group_manager.py:13 grid layout).
    env = MeshEnv.create(dp=2, pp=1, cp=2, tp=2)
    grid = np.array(env.mesh.devices)
    ids = np.vectorize(lambda d: d.id)(grid)
    # along tp, ids are consecutive
    assert (ids[..., 1] - ids[..., 0] == 1).all()


def test_oversubscription_raises(devices):
    with pytest.raises(ValueError):
        MeshEnv.create(dp=4, pp=2, cp=2, tp=2)


def test_batch_sharding_slices_seq_over_cp(devices):
    env = MeshEnv.create(dp=2, cp=2, tp=2)
    x = np.arange(1 * 4 * 8, dtype=np.int32).reshape(1, 4, 8)
    arr = jax.device_put(x, env.batch_sharding())
    # each shard holds the full micro dim, batch/dp, seq/cp
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (1, 2, 4)
    np.testing.assert_array_equal(np.asarray(arr), x)
