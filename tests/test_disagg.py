"""Disaggregated serving + speculative decode tests
(picotron_tpu/serve/disagg, serve/spec_decode): greedy/sampled token
parity vs the offline oracle and the colocated engine (including under
preemption and across the handoff boundary), both-pools exhaustion
without leak or deadlock, youngest-first preemption across the
boundary, compile-once discipline for the pool programs (runtime and
statically via the variant prover), handoff telemetry, the
bench --serve --disagg stall-drop headline, and the MoE rejection
cross-validation."""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.config import (
    Config, ModelConfig, ServeConfig, resolve_preset,
)
from picotron_tpu.generate import generate
from picotron_tpu.models.llama import init_params
from picotron_tpu.serve import (
    BlockPool, DisaggScheduler, DisaggServeEngine, Request, ServeEngine,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny"), "max_position_embeddings": 64})
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def requests5(tiny):
    cfg, _ = tiny
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(0, cfg.vocab_size, size=n)))
               for n in (5, 9, 3, 7, 11)]
    return list(zip(prompts, [6, 3, 8, 5, 4]))


@pytest.fixture(scope="module")
def offline_refs(tiny, requests5):
    """Per-request greedy tokens from the offline contiguous-cache path —
    the parity oracle for every engine configuration."""
    cfg, params = tiny
    return [
        np.asarray(generate(params, cfg, jnp.asarray([p], jnp.int32),
                            n))[0, len(p):].tolist()
        for p, n in requests5
    ]


def scfg(**kw):
    base = dict(decode_slots=3, block_size=4, num_blocks=24,
                prefill_chunk=4, max_model_len=32, decode_interval=3,
                disagg=True)
    base.update(kw)
    return ServeConfig(**base)


def run_disagg(params, cfg, serve_cfg, requests, **kw):
    eng = DisaggServeEngine(params, cfg, serve_cfg, **kw)
    res = eng.run(requests)
    eng.close()
    return eng, res


def tokens_by_id(res):
    return {r["id"]: r["tokens"] for r in res}


# ---------------------------------------------------------------------------
# token parity: disagg must be bit-identical to the offline oracle
# ---------------------------------------------------------------------------


def test_disagg_greedy_parity_matches_offline(tiny, requests5,
                                              offline_refs):
    """Every request crosses the handoff boundary (prefill pool ->
    device_put -> decode pool) and the greedy tokens must still be
    bit-identical to the offline contiguous-cache sampler."""
    cfg, params = tiny
    eng, res = run_disagg(params, cfg, scfg(), requests5)
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref
    s = eng.summary
    assert s["disagg"] is True
    assert s["handoffs"] >= len(requests5) - 1  # first-token-only
    # requests may retire prefill-side without a handoff
    assert s["handoff_blocks"] > 0 and s["handoff_s"] >= 0
    # a drained trace leaves BOTH pools empty — no leaked blocks
    assert eng.sched.pool.in_use == 0
    assert eng.sched.prefill_pool.in_use == 0


def test_disagg_pools_are_separately_placed(tiny, requests5,
                                            offline_refs):
    """With >1 visible device (conftest forces 8 simulated CPU hosts)
    the pools land on DIFFERENT devices and the handoff is a real
    cross-device transfer — parity must survive it."""
    cfg, params = tiny
    eng = DisaggServeEngine(params, cfg, scfg())
    p_dev = next(iter(eng._k_p.sharding.device_set))
    d_dev = next(iter(eng._k.sharding.device_set))
    assert p_dev != d_dev, "prefill and decode pools share a device"
    res = eng.run(requests5)
    eng.close()
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref


def test_disagg_parity_under_decode_pool_preemption(tiny, requests5,
                                                    offline_refs):
    """A decode pool too small for the live set forces youngest-first
    preemption; preempted requests recompute THROUGH the prefill pool
    (a second handoff) and tokens must not change."""
    cfg, params = tiny
    eng, res = run_disagg(params, cfg, scfg(num_blocks=6), requests5)
    assert eng.sched.n_preempted > 0
    assert eng.sched.n_handoffs > len(requests5)  # re-handoffs happened
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref
    assert eng.sched.pool.in_use == 0
    assert eng.sched.prefill_pool.in_use == 0


def test_disagg_sampled_parity_vs_colocated(tiny, requests5):
    """Sampled decode (temperature + top-k) must be bit-identical
    between the colocated and disaggregated engines: sampling keys fold
    (request id, token index) only, so WHERE a token is sampled — which
    pool, which slot, before or after a handoff — cannot perturb it."""
    cfg, params = tiny
    kw = dict(temperature=0.7, top_k=8, seed=3)
    colo = ServeEngine(params, cfg, scfg(disagg=False), **kw)
    res_c = colo.run(requests5)
    colo.close()
    _, res_d = run_disagg(params, cfg, scfg(), requests5, **kw)
    assert tokens_by_id(res_c) == tokens_by_id(res_d)


# ---------------------------------------------------------------------------
# both-pools exhaustion: no leak, no deadlock, youngest-first boundary
# ---------------------------------------------------------------------------


def test_both_pools_exhausted_no_leak_no_deadlock(tiny, requests5,
                                                  offline_refs):
    """Prefill pool (2 slots / 4 blocks) and decode pool (4 blocks) both
    at the survivability minimum, both live at once: the trace must
    drain (no deadlock), every block must return (no leak), preemption
    must fire, and tokens must still match the oracle."""
    cfg, params = tiny
    eng, res = run_disagg(
        params, cfg,
        scfg(num_blocks=4, prefill_slots=2, prefill_num_blocks=4),
        requests5)
    assert len(res) == len(requests5)
    assert eng.sched.n_preempted > 0
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref
    assert eng.sched.pool.in_use == 0
    assert eng.sched.prefill_pool.in_use == 0
    assert eng.sched.pool.free_blocks == eng.sched.pool.num_blocks


def test_handoff_preempts_only_strictly_younger():
    """Youngest-first ACROSS the handoff boundary (pure host logic): an
    OLD candidate at the boundary may evict the youngest decode
    resident; the youngest candidate gets None (it must wait — someone
    older is progressing, so no livelock)."""
    sched = DisaggScheduler(2, 2, BlockPool(8), BlockPool(2), 4, 8)
    for i in range(4):
        sched.submit(Request(i, (1,) * 4, 4))
    # admit 0,1 into prefill; finish their prefills; sample a token
    for slot, st in sched.admit():
        sched.note_prefilled(slot, len(st.prefill_ids))
        st.generated.append(7)
    # hand both off: decode pool (2 blocks) holds exactly both prefixes
    assert sched.handoff(0) is not None
    assert sched.handoff(1) is not None
    # admit 2,3 behind them and bring them to the boundary
    for slot, st in sched.admit():
        sched.note_prefilled(slot, len(st.prefill_ids))
        st.generated.append(7)
    ready = sched.handoff_ready()
    assert ready  # oldest-first ordering
    # candidate 2 is YOUNGER than both decode residents (0, 1): it must
    # not evict either — handoff returns None and nobody was preempted
    assert sched.handoff(ready[0]) is None
    assert sched.n_preempted == 0
    # retire resident 0; its decode slot+block free up; now candidate 2
    # hands off WITHOUT preempting (free resources first)
    slot0 = next(i for i, s in enumerate(sched.slots)
                 if s is not None and s.req.id == 0)
    sched.retire(slot0)
    out = sched.handoff(ready[0])
    assert out is not None and out[3] == []  # no victims
    # decode growth for the OLDER resident (1) preempts the YOUNGER (2)
    slot1 = next(i for i, s in enumerate(sched.slots)
                 if s is not None and s.req.id == 1)
    st1 = sched.slots[slot1]
    st1.generated.extend([7] * 2)  # grow past its block
    preempted = sched.ensure_block(slot1, horizon=1)
    assert preempted, "growth should have evicted the younger resident"
    assert sched.queue[0].req.id == 2  # requeued at the FRONT
    assert sched.n_preempted == 1


# ---------------------------------------------------------------------------
# speculative decode: parity + acceptance accounting
# ---------------------------------------------------------------------------


def test_spec_greedy_parity_matches_offline(tiny, requests5,
                                            offline_refs):
    """The n-gram speculator's verify-and-accept emits target-sampled
    tokens only, so greedy output is token-identical to non-speculative
    greedy — acceptance decides how MANY tokens emit per dispatch, never
    WHICH."""
    cfg, params = tiny
    eng = ServeEngine(params, cfg,
                      scfg(disagg=False, speculator="ngram", draft_len=2))
    res = eng.run(requests5)
    eng.close()
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref
    s = eng.summary
    assert s["speculator"] == "ngram" and s["draft_len"] == 2
    assert s["draft_tokens"] > 0
    assert s["acceptance_rate"] is not None
    assert 0.0 <= s["acceptance_rate"] <= 1.0


def test_spec_sampled_parity_under_accept_reject(tiny, requests5):
    """Sampling-key discipline under speculative accept/reject: at
    temperature > 0 every emitted token is sampled with the key folded
    from (request id, token index), so a rejected draft cannot shift any
    later token — spec and non-spec streams must be bit-identical."""
    cfg, params = tiny
    kw = dict(temperature=0.8, top_k=5, seed=2)
    plain = ServeEngine(params, cfg, scfg(disagg=False), **kw)
    res_p = plain.run(requests5)
    plain.close()
    spec = ServeEngine(
        params, cfg, scfg(disagg=False, speculator="ngram", draft_len=3),
        **kw)
    res_s = spec.run(requests5)
    spec.close()
    assert tokens_by_id(res_p) == tokens_by_id(res_s)


def test_spec_on_disagg_parity_with_preemption(tiny, requests5,
                                               offline_refs):
    """The full stack at once: speculative decode on the disaggregated
    engine with a decode pool tight enough to preempt — still greedy
    bit-parity with the offline oracle."""
    cfg, params = tiny
    eng, res = run_disagg(
        params, cfg,
        scfg(num_blocks=6, speculator="ngram", draft_len=2), requests5)
    assert eng.sched.n_preempted > 0
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref


def test_spec_acceptance_nonzero_on_looping_generation(tiny):
    """A long greedy generation from a tiny model falls into repetition;
    the self-drafting n-gram speculator must catch some of it —
    accepted_draft_tokens > 0 and decode dispatches strictly fewer than
    the non-speculative engine needs for the same tokens."""
    cfg, params = tiny
    req = [([5, 9, 5, 9], 28)]
    sc = dict(decode_slots=1, block_size=4, num_blocks=8,
              prefill_chunk=4, max_model_len=32, decode_interval=2,
              disagg=False)
    plain = ServeEngine(params, cfg, ServeConfig(**sc))
    res_p = plain.run(req)
    plain.close()
    spec = ServeEngine(params, cfg, ServeConfig(
        **sc, speculator="ngram", draft_len=3))
    res_s = spec.run(req)
    spec.close()
    assert res_p[0]["tokens"] == res_s[0]["tokens"]
    assert spec.summary["accepted_draft_tokens"] > 0
    assert spec.summary["decode_steps"] < plain.summary["decode_steps"]


def test_spec_draft_len_over_context_window_rejected(tiny):
    from picotron_tpu.serve import spec_decode

    cfg, params = tiny
    with pytest.raises(ValueError, match="context window"):
        ServeEngine(params, cfg, scfg(
            disagg=False, speculator="ngram",
            draft_len=spec_decode.max_draft_len() + 1))


# ---------------------------------------------------------------------------
# compile discipline: each pool program compiles exactly once
# ---------------------------------------------------------------------------


def test_disagg_single_decode_compile(tiny, requests5, offline_refs):
    """One decode compile for the whole disaggregated lifetime:
    admissions, handoffs, preemptions, and cross-pool block tables are
    data, not shapes. decode_slots=4 is unique to this module so the jit
    cache cannot hide a second compile behind another test's."""
    cfg, params = tiny
    eng, res = run_disagg(params, cfg,
                          scfg(decode_slots=4, num_blocks=7), requests5)
    assert eng.summary["decode_compiles"] == 1
    assert eng.sched.n_preempted > 0  # tables churned, shapes did not
    by_id = tokens_by_id(res)
    for i, ref in enumerate(offline_refs):
        assert by_id[i] == ref


def test_prove_disagg_programs_static():
    """The PR-9 variant prover proves all four disaggregated programs
    (prefill pool, decode pool, handoff gather/scatter) compile once,
    without touching a device; MoE is rejected with the config error."""
    from picotron_tpu.analysis.variants import (
        CHECK, prove_disagg_programs,
    )

    mcfg = ModelConfig(**resolve_preset("debug-tiny"))
    info = prove_disagg_programs(mcfg, scfg()).info[CHECK]
    assert info["proven"] is True
    assert info["programs"] == 4
    assert set(info["signatures"]) == {
        "prefill_pool", "decode_pool", "handoff_gather",
        "handoff_scatter"}
    # the speculator adds the rolling context to the decode signature
    spec = prove_disagg_programs(
        mcfg, scfg(speculator="ngram", draft_len=2)).info[CHECK]
    assert spec["proven"] is True
    assert spec["signatures"] != info["signatures"]
    with pytest.raises(ValueError, match="MoE"):
        prove_disagg_programs(
            ModelConfig(**resolve_preset("debug-tiny-moe")), scfg())


def test_audit_variants_includes_serve_disagg():
    from picotron_tpu.analysis.variants import CHECK, audit_variants

    cfg = Config(model=ModelConfig(**resolve_preset("debug-tiny")))
    info = audit_variants(cfg).info[CHECK]
    assert info["serve_disagg"]["proven"] is True
    moe = Config(model=ModelConfig(**resolve_preset("debug-tiny-moe")))
    info_moe = audit_variants(moe).info[CHECK]
    assert "unavailable" in info_moe["serve_disagg"]


# ---------------------------------------------------------------------------
# config / engine cross-validation: MoE is rejected early and clearly
# ---------------------------------------------------------------------------


def test_config_rejects_moe_disagg_and_speculator():
    moe = ModelConfig(**resolve_preset("debug-tiny-moe"))
    with pytest.raises(ValueError, match="MoE"):
        Config(model=moe, serve=ServeConfig(disagg=True)).validate()
    with pytest.raises(ValueError, match="MoE"):
        Config(model=moe,
               serve=ServeConfig(speculator="ngram")).validate()
    # dense passes; MoE without serving features passes
    Config(model=ModelConfig(**resolve_preset("debug-tiny")),
           serve=ServeConfig(disagg=True,
                             speculator="ngram")).validate()
    Config(model=moe).validate()


def test_engines_reject_moe_at_construction():
    moe = ModelConfig(dtype="float32",
                      **resolve_preset("debug-tiny-moe"))
    with pytest.raises(ValueError, match="num_experts"):
        ServeEngine({}, moe, scfg(disagg=False))
    with pytest.raises(ValueError, match="num_experts"):
        DisaggServeEngine({}, moe, scfg())


def test_serve_config_validates_disagg_fields():
    with pytest.raises(ValueError, match="speculator"):
        ServeConfig(speculator="medusa").validate()
    with pytest.raises(ValueError, match="draft_len"):
        ServeConfig(speculator="ngram", draft_len=0).validate()
    with pytest.raises(ValueError, match="prefill_device"):
        ServeConfig(prefill_device=-2).validate()


# ---------------------------------------------------------------------------
# telemetry: handoff ledger category + per-pool serving view
# ---------------------------------------------------------------------------


def test_disagg_telemetry_handoff_and_report(tiny, requests5, tmp_path):
    """The disaggregated stream books the handoff transport as its own
    (non-goodput) ledger category, the serve_summary carries the
    per-pool and acceptance aggregates, and tools/telemetry_report.py
    renders the disagg + speculative rows from the stream alone."""
    from picotron_tpu.telemetry import JsonlSink, Telemetry
    from picotron_tpu.telemetry.goodput import (
        CATEGORIES, GOODPUT_CATEGORIES,
    )

    assert "handoff" in CATEGORIES
    assert "handoff" not in GOODPUT_CATEGORIES  # transport is badput
    cfg, params = tiny
    path = str(tmp_path / "telemetry.jsonl")
    tel = Telemetry(sinks=[JsonlSink(path)])
    eng = DisaggServeEngine(params, cfg,
                            scfg(speculator="ngram", draft_len=2),
                            telemetry=tel)
    eng.run(requests5)
    tel.close()

    events = [json.loads(line) for line in open(path)]
    cats = {e.get("category") for e in events if e["kind"] == "phase"}
    assert {"prefill", "decode", "handoff"} <= cats
    summ = next(e for e in events if e["kind"] == "serve_summary")
    assert summ["disagg"] is True and summ["handoffs"] > 0
    assert summ["prefill_slot_occupancy"] > 0
    assert summ["acceptance_rate"] is not None

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import telemetry_report

    s = telemetry_report.summarize(events)
    sv = s["serving"]
    assert sv["handoffs"] == summ["handoffs"]
    assert sv["prefill_slot_occupancy"] == summ["prefill_slot_occupancy"]
    assert sv["acceptance_rate"] == summ["acceptance_rate"]
    assert "handoff" in s["categories"]
    text = telemetry_report.render(s)
    assert "disagg:" in text and "speculative:" in text


def test_extract_metrics_serve_columns(tiny, requests5, tmp_path):
    """A serving-only telemetry stream (no train steps) must still yield
    a harvest row: serve_* TTFT/TPOT/acceptance columns from the
    serve_summary event."""
    from picotron_tpu.telemetry import JsonlSink, Telemetry

    cfg, params = tiny
    run_dir = tmp_path / "serve_run"
    run_dir.mkdir()
    path = str(run_dir / "telemetry.jsonl")
    tel = Telemetry(sinks=[JsonlSink(path)])
    eng = DisaggServeEngine(params, cfg,
                            scfg(speculator="ngram", draft_len=2),
                            telemetry=tel)
    eng.run(requests5)
    tel.close()

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import extract_metrics

    stats = extract_metrics.process_telemetry(path)
    assert stats is not None
    assert stats["serve_requests"] == len(requests5)
    assert stats["serve_ttft_p50_ms"] >= 0
    assert stats["serve_tpot_p50_ms"] >= 0
    assert "serve_acceptance_rate" in stats
    assert stats["serve_handoffs"] > 0


# ---------------------------------------------------------------------------
# cost model: the handoff has a price
# ---------------------------------------------------------------------------


def test_cost_model_prices_kv_handoff():
    from picotron_tpu.analysis.cost_model import CostModel

    mcfg = ModelConfig(**resolve_preset("debug-tiny"))
    cm = CostModel("v5e")
    secs, nbytes = cm.price_kv_handoff(mcfg, scfg())
    # 2 (K+V) x layers x blocks-for-32-tokens x 4 x Hkv x Dh x 2 bytes
    blocks = -(-32 // 4)
    expect = (2 * mcfg.num_hidden_layers * blocks * 4
              * mcfg.num_key_value_heads * mcfg.head_dim * 2)
    assert nbytes == expect
    assert secs > 0
    # fewer tokens -> strictly cheaper; more hops -> strictly dearer
    secs_small, b_small = cm.price_kv_handoff(mcfg, scfg(), n_tokens=4)
    assert b_small < nbytes and secs_small < secs
    secs2, _ = cm.price_kv_handoff(mcfg, scfg(), hops=2)
    assert secs2 > secs


# ---------------------------------------------------------------------------
# bench --serve --disagg: the stall-drop headline
# ---------------------------------------------------------------------------


def test_bench_disagg_stall_drop_on_burst_trace(tiny):
    """The deterministic long-prefill burst: the colocated engine's
    slot-coupled admission serializes the long prefills behind the
    shorts and stalls decode for the whole grind; the disaggregated
    engine overlaps them — max consecutive decode-dispatch stall ticks
    must DROP. Plus the SLO-curve and acceptance-sweep artifacts."""
    import bench

    row = bench.run_serve_disagg(
        "debug-tiny", 2, slots=2, block_size=4, num_blocks=0,
        prefill_chunk=4, prompt_len=24, max_new=16, n_requests=4,
        rate=0.0, decode_interval=2, draft_lens=(2,))
    assert row["unit"] == "decode_stall_ticks_drop"
    assert row["value"] > 0, (
        f"disagg did not reduce decode stalls: colocated "
        f"{row['colocated_stall_ticks_max']} vs disagg "
        f"{row['disagg_stall_ticks_max']}")
    assert (row["disagg_stall_ticks_max"]
            < row["colocated_stall_ticks_max"])
    assert row["handoffs"] > 0
    assert row["decode_compiles"] == 0  # warmed before measurement
    assert row["predicted_handoff_ms_worstcase"] > 0
    assert len(row["slo_curve"]) == 1  # rate=0: saturation point only
    for tag in ("colocated", "disagg"):
        assert row["slo_curve"][0][tag]["ttft_p50_ms"] is not None
    sweep = row["acceptance_sweep"]
    assert [p["draft_len"] for p in sweep] == [2]
    assert sweep[0]["draft_tokens"] > 0
    assert "wall_note" in row


def test_bench_burst_trace_deterministic():
    import bench

    a = bench.make_burst_trace(3, 32, 4, 3, 24, 256, seed=1)
    b = bench.make_burst_trace(3, 32, 4, 3, 24, 256, seed=1)
    assert a == b
    assert all(t == 0.0 for _, _, t in a)  # everything arrives at once
    lens = [len(p) for p, _, _ in a]
    assert lens[:3] == [4, 4, 4] and lens[3:] == [32, 32, 32]
    budgets = [n for _, n, _ in a]
    assert budgets[0] > budgets[3]  # shorts decode long, longs short
