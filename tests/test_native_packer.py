"""Native C++ block packer: build, contract, and equivalence with the pure
Python fallback (the native component of the data pipeline; the reference's
only native data-path code is the HF tokenizer core, ref: data.py:57-100)."""

import numpy as np
import pytest

from picotron_tpu.native import BlockPacker, PyBlockPacker, make_packer


def test_native_builds_and_loads():
    p = make_packer(8)
    # The toolchain ships g++, so the native path must actually build here —
    # silently testing only the fallback would defeat the point.
    assert isinstance(p, BlockPacker)


@pytest.mark.parametrize("cls", [BlockPacker, PyBlockPacker])
def test_packing_with_carry(cls):
    p = cls(block_size=5)
    p.feed(np.arange(7))          # 1 block + carry [5, 6]
    assert p.num_ready == 1 and p.carry_len == 2
    p.feed(np.arange(7, 12))      # carry 2 + 5 = 7 -> block 2 + carry 2
    assert p.num_ready == 2 and p.carry_len == 2
    out = p.take()
    np.testing.assert_array_equal(out, np.arange(10).reshape(2, 5))
    assert p.num_ready == 0 and p.carry_len == 2  # carry survives take()
    p.feed(np.arange(12, 15))     # carry 2 + 3 = 5 -> one more block
    np.testing.assert_array_equal(p.take(), np.arange(10, 15).reshape(1, 5))


@pytest.mark.parametrize("cls", [BlockPacker, PyBlockPacker])
def test_take_max_blocks(cls):
    p = cls(block_size=2)
    p.feed(np.arange(10))
    first = p.take(max_blocks=2)
    np.testing.assert_array_equal(first, [[0, 1], [2, 3]])
    assert p.num_ready == 3
    np.testing.assert_array_equal(p.take(), [[4, 5], [6, 7], [8, 9]])


def test_native_matches_python_on_random_stream():
    rng = np.random.default_rng(0)
    native, py = BlockPacker(17), PyBlockPacker(17)
    for _ in range(50):
        chunk = rng.integers(0, 1000, rng.integers(0, 60)).astype(np.int32)
        native.feed(chunk)
        py.feed(chunk)
    assert native.num_ready == py.num_ready
    assert native.carry_len == py.carry_len
    np.testing.assert_array_equal(native.take(), py.take())


def test_empty_and_invalid():
    p = make_packer(4)
    p.feed(np.empty((0,), dtype=np.int32))
    assert p.num_ready == 0
    assert p.take().shape == (0, 4)
    with pytest.raises(ValueError):
        make_packer(0)
