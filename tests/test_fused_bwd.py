"""Fused-accumulation grad engine (parallel/fused_bwd.py) parity.

The fused engine re-derives the decoder backward by hand (manual layer
scan, in-scan dW accumulation, *_bwd_from_saved attention backwards) —
every test here pins it against the AD engine on the same config, so any
divergence in the re-implemented forward/backward math shows up as a
loss/grad mismatch.

Two tiers of pinning:

- `assert_grads_match` compares the raw fp32 gradient trees of ONE
  `_device_grads` call per engine (model dtype float32, no optimizer):
  deterministic to ~1e-6, runs on pre-vma JAX too (both engines share the
  same collectives, so the check_rep=False transpose caveat cancels), and
  covers every eligibility axis — tp, SP, cp ring (contiguous + zigzag),
  Ulysses, MoE (+ep, +capacity drops).
- `assert_engines_match` runs two full optimizer steps (bf16 + offload,
  the production arrangement) and compares losses + fp32 masters.
  Tolerances are bf16-activation-level: both engines compute per-layer dW
  in bf16 before the fp32 accumulate, but XLA fuses the two graphs
  differently. vma-only (see `requires_vma`).
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig,
)
from tests.test_optimizer_offload import batch_for, run_steps

# engine parity (fused == AD to bf16 tolerance) is only promised on the
# vma shard_map type system — pre-vma JAX runs via compat.py's
# check_rep=False fallback where grad-through-psum transposes are
# axis-size-inflated, so the two engines legitimately diverge
requires_vma = pytest.mark.skipif(
    not compat.HAS_VMA,
    reason="engine parity requires the vma shard_map type system "
           "(see compat.py)")


def engine_cfg(engine: str, model_kw=None, dist_kw=None, **tr) -> Config:
    tr.setdefault("seq_length", 64)
    tr.setdefault("micro_batch_size", 2)
    tr.setdefault("gradient_accumulation_steps", 2)
    tr.setdefault("optimizer_offload", True)
    tr.setdefault("remat", True)
    tr.setdefault("remat_policy", "dots_attn")
    tr.setdefault("learning_rate", 1e-2)
    mk = dict(num_attention_heads=8, num_key_value_heads=4,
              num_hidden_layers=3, hidden_size=64, intermediate_size=96,
              vocab_size=256, max_position_embeddings=64)
    mk.update(model_kw or {})
    return Config(
        distributed=DistributedConfig(**(dist_kw or {"dp_size": 2})),
        model=ModelConfig(**mk),
        training=TrainingConfig(grad_engine=engine, **tr),
    )


def losses_and_master(cfg, steps=2):
    losses, state, _ = run_steps(cfg, steps=steps)
    tree = (state.opt_state.master if cfg.training.optimizer_offload
            else state.params)
    return losses, jax.tree.map(np.asarray, tree)


def assert_engines_match(mk=None, dk=None, **tr):
    ad = engine_cfg("ad", model_kw=mk, dist_kw=dk, **tr)
    fused = engine_cfg("fused", model_kw=mk, dist_kw=dk, **tr)
    l_ad, m_ad = losses_and_master(ad)
    l_f, m_f = losses_and_master(fused)
    np.testing.assert_allclose(l_f, l_ad, rtol=2e-4)
    for a, b in zip(jax.tree.leaves(m_ad), jax.tree.leaves(m_f)):
        np.testing.assert_allclose(a, b, rtol=3e-3, atol=3e-5)


# ---------------------------------------------------------------------------
# raw fp32 gradient parity — one _device_grads call per engine
# ---------------------------------------------------------------------------


def fp32_cfg(engine, mk=None, dk=None, **tr):
    return engine_cfg(engine, model_kw={"dtype": "float32", **(mk or {})},
                      dist_kw=dk, optimizer_offload=False, **tr)


def device_grads_of(cfg):
    """(grads, loss, extras) from one jitted _device_grads call — the
    engines' actual output, before any optimizer touches it."""
    from picotron_tpu.parallel.api import _device_grads, init_sharded_state
    from picotron_tpu.parallel.sharding import batch_spec, param_specs

    batch, menv = batch_for(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    fn = jax.jit(compat.shard_map(
        partial(_device_grads, cfg=cfg), mesh=menv.mesh,
        in_specs=(param_specs(cfg), (batch_spec(), batch_spec())),
        out_specs=(param_specs(cfg), P(), P())))
    grads, loss, extras = fn(state.params, batch)
    return (jax.tree.map(np.asarray, grads), float(loss),
            {k: float(v) for k, v in extras.items()})


def assert_grads_match(mk=None, dk=None, **tr):
    g_ad, l_ad, e_ad = device_grads_of(fp32_cfg("ad", mk, dk, **tr))
    g_f, l_f, e_f = device_grads_of(fp32_cfg("fused", mk, dk, **tr))
    np.testing.assert_allclose(l_f, l_ad, rtol=2e-4)
    assert set(e_f) == set(e_ad)
    for k in e_ad:
        np.testing.assert_allclose(e_f[k], e_ad[k], rtol=1e-5, err_msg=k)
    flat_ad = jax.tree_util.tree_flatten_with_path(g_ad)[0]
    for (path, a), b in zip(flat_ad, jax.tree.leaves(g_f)):
        scale = np.abs(a).max() + 1e-12
        np.testing.assert_array_less(
            np.abs(a - b).max() / scale, 1e-4,
            err_msg=f"{jax.tree_util.keystr(path)} (rel-to-max)")


@requires_vma
def test_parity_dense_dp():
    assert_engines_match()


@pytest.mark.slow
@requires_vma
def test_parity_tp_vocab_parallel():
    # tp=2 exercises the ctx.f/g hook transposes and the vocab-parallel CE
    # inside the segment VJPs
    assert_engines_match(dk={"dp_size": 2, "tp_size": 2})


@pytest.mark.slow
@requires_vma
def test_parity_qwen_bias_tied():
    # qkv bias leaves + tied embeddings (head grads flow into the
    # embedding leaf through head_weight's transpose)
    assert_engines_match(mk=dict(attention_bias=True,
                                 tie_word_embeddings=True))


@pytest.mark.slow
@requires_vma
def test_parity_sdpa_path():
    assert_engines_match(mk=dict(attn_impl="reference"))


@pytest.mark.slow
@requires_vma
def test_parity_without_offload():
    # the engine is independent of where the optimizer state lives
    assert_engines_match(optimizer_offload=False)


def test_auto_resolves_fused_only_when_supported():
    from picotron_tpu.parallel.fused_bwd import fused_bwd_supported

    assert fused_bwd_supported(engine_cfg("auto"))
    # the widened axes (this PR): SP, cp ring/ulysses, MoE (+ep)
    assert fused_bwd_supported(
        engine_cfg("auto", dist_kw={"dp_size": 2, "tp_size": 2,
                                    "sequence_parallel": True}))
    assert fused_bwd_supported(
        engine_cfg("auto", dist_kw={"dp_size": 2, "cp_size": 2}))
    assert fused_bwd_supported(
        engine_cfg("auto", dist_kw={"cp_size": 2},
                   model_kw={"attn_impl": "ulysses"}))
    assert fused_bwd_supported(
        engine_cfg("auto", model_kw={"num_experts": 4,
                                     "num_experts_per_token": 2}))
    assert fused_bwd_supported(
        engine_cfg("auto", dist_kw={"dp_size": 2, "ep_size": 2},
                   model_kw={"num_experts": 4,
                             "num_experts_per_token": 2}))
    # still AD-only: pp > 1, non-dots_attn remat, remat off
    assert not fused_bwd_supported(
        engine_cfg("auto", dist_kw={"dp_size": 2, "pp_size": 2}))
    assert not fused_bwd_supported(
        engine_cfg("auto", remat_policy="dots"))
    assert not fused_bwd_supported(engine_cfg("auto", remat=False))


def test_fused_rejects_unsupported_config():
    with pytest.raises(ValueError, match="fused"):
        engine_cfg("fused", remat_policy="dots").validate()
    with pytest.raises(ValueError, match="fused"):
        engine_cfg("fused",
                   dist_kw={"dp_size": 2, "pp_size": 2}).validate()


# ---------------------------------------------------------------------------
# per-axis fp32 gradient parity (run on pre-vma JAX too — see module doc)
# ---------------------------------------------------------------------------


def test_grads_parity_sequence_parallel():
    # Megatron-SP: the ctx.f/g all_gather / reduce-scatter pair inside the
    # fused engine's segment VJPs, seq-sharded saved layer inputs
    assert_grads_match(dk={"dp_size": 2, "tp_size": 2,
                           "sequence_parallel": True})


def test_grads_parity_cp4_ring_zigzag():
    # ring backward from the saved merged LSE: a second ppermute ring
    # carrying dK/dV accumulators, zigzag positions traveling with blocks
    assert_grads_match(dk={"dp_size": 2, "cp_size": 4})


def test_grads_parity_cp2_ulysses():
    # Ulysses backward: the all_to_all pair in both directions around the
    # bwd-from-saved kernel, inner-domain saved LSE, static zigzag sort
    assert_grads_match(dk={"dp_size": 2, "cp_size": 2},
                       mk={"attn_impl": "ulysses"})


def test_grads_parity_moe_ep():
    # MoE segment VJP: routing recomputed from the saved layer input,
    # router aux fold (aux * count) gradient, expert-parallel all_to_all
    assert_grads_match(dk={"dp_size": 2, "ep_size": 2},
                       mk={"num_experts": 4, "num_experts_per_token": 2})


@pytest.mark.slow
def test_grads_parity_cp2_ring_contiguous():
    assert_grads_match(dk={"dp_size": 2, "cp_size": 2,
                           "cp_layout": "contiguous"})


@pytest.mark.slow
def test_grads_parity_moe_capacity_drops():
    # a tight capacity bound forces real drops: the drop statistic must
    # ride the fused path into extras identically, and dropped tokens'
    # zero-contribution must match the AD engine's
    assert_grads_match(
        dk={"dp_size": 2, "ep_size": 2},
        mk={"num_experts": 4, "num_experts_per_token": 2,
            "capacity_factor": 0.25})
    _, _, extras = device_grads_of(fp32_cfg(
        "fused", {"num_experts": 4, "num_experts_per_token": 2,
                  "capacity_factor": 0.25},
        {"dp_size": 2, "ep_size": 2}))
    assert extras["moe_drop_frac"] > 0.0


@pytest.mark.slow
def test_grads_parity_sp_qwen_bias_tied():
    assert_grads_match(dk={"dp_size": 2, "tp_size": 2,
                           "sequence_parallel": True},
                       mk={"attention_bias": True,
                           "tie_word_embeddings": True})


@pytest.mark.slow
@requires_vma
def test_parity_sequence_parallel_e2e():
    # full bf16 + offload steps through the optimizer (conventions of the
    # dense e2e tests above)
    assert_engines_match(dk={"dp_size": 2, "tp_size": 2,
                             "sequence_parallel": True})


@pytest.mark.slow
@requires_vma
def test_parity_cp4_ring_e2e():
    assert_engines_match(dk={"dp_size": 2, "cp_size": 4})


@pytest.mark.slow
@requires_vma
def test_parity_cp2_ulysses_e2e():
    assert_engines_match(dk={"dp_size": 2, "cp_size": 2},
                         mk={"attn_impl": "ulysses"})


@pytest.mark.slow
@requires_vma
def test_parity_moe_ep_e2e():
    assert_engines_match(dk={"dp_size": 2, "ep_size": 2},
                         mk={"num_experts": 4, "num_experts_per_token": 2})


@pytest.mark.slow
@requires_vma
def test_grad_clip_parity():
    # the global-norm clip consumes the accumulated grads — same totals,
    # same clip scale, regardless of engine
    assert_engines_match(grad_clip_norm=0.1)
