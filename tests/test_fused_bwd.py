"""Fused-accumulation grad engine (parallel/fused_bwd.py) parity.

The fused engine re-derives the decoder backward by hand (manual layer
scan, in-scan dW accumulation, flash-bwd-from-saved) — every test here
pins it against the AD engine on the same config, so any divergence in
the re-implemented forward/backward math shows up as a loss/grad mismatch.
Tolerances are bf16-activation-level: both engines compute per-layer dW
in bf16 before the fp32 accumulate, but XLA fuses the two graphs
differently.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu import compat
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig,
)
from tests.test_optimizer_offload import batch_for, run_steps

# engine parity (fused == AD to bf16 tolerance) is only promised on the
# vma shard_map type system — pre-vma JAX runs via compat.py's
# check_rep=False fallback where grad-through-psum transposes are
# axis-size-inflated, so the two engines legitimately diverge
requires_vma = pytest.mark.skipif(
    not compat.HAS_VMA,
    reason="engine parity requires the vma shard_map type system "
           "(see compat.py)")


def engine_cfg(engine: str, model_kw=None, dist_kw=None, **tr) -> Config:
    tr.setdefault("seq_length", 64)
    tr.setdefault("micro_batch_size", 2)
    tr.setdefault("gradient_accumulation_steps", 2)
    tr.setdefault("optimizer_offload", True)
    tr.setdefault("remat", True)
    tr.setdefault("remat_policy", "dots_attn")
    tr.setdefault("learning_rate", 1e-2)
    mk = dict(num_attention_heads=8, num_key_value_heads=4,
              num_hidden_layers=3, hidden_size=64, intermediate_size=96,
              vocab_size=256, max_position_embeddings=64)
    mk.update(model_kw or {})
    return Config(
        distributed=DistributedConfig(**(dist_kw or {"dp_size": 2})),
        model=ModelConfig(**mk),
        training=TrainingConfig(grad_engine=engine, **tr),
    )


def losses_and_master(cfg, steps=2):
    losses, state, _ = run_steps(cfg, steps=steps)
    tree = (state.opt_state.master if cfg.training.optimizer_offload
            else state.params)
    return losses, jax.tree.map(np.asarray, tree)


def assert_engines_match(mk=None, dk=None, **tr):
    ad = engine_cfg("ad", model_kw=mk, dist_kw=dk, **tr)
    fused = engine_cfg("fused", model_kw=mk, dist_kw=dk, **tr)
    l_ad, m_ad = losses_and_master(ad)
    l_f, m_f = losses_and_master(fused)
    np.testing.assert_allclose(l_f, l_ad, rtol=2e-4)
    for a, b in zip(jax.tree.leaves(m_ad), jax.tree.leaves(m_f)):
        np.testing.assert_allclose(a, b, rtol=3e-3, atol=3e-5)


@requires_vma
def test_parity_dense_dp():
    assert_engines_match()


@pytest.mark.slow
@requires_vma
def test_parity_tp_vocab_parallel():
    # tp=2 exercises the ctx.f/g hook transposes and the vocab-parallel CE
    # inside the segment VJPs
    assert_engines_match(dk={"dp_size": 2, "tp_size": 2})


@pytest.mark.slow
@requires_vma
def test_parity_qwen_bias_tied():
    # qkv bias leaves + tied embeddings (head grads flow into the
    # embedding leaf through head_weight's transpose)
    assert_engines_match(mk=dict(attention_bias=True,
                                 tie_word_embeddings=True))


@pytest.mark.slow
@requires_vma
def test_parity_sdpa_path():
    assert_engines_match(mk=dict(attn_impl="reference"))


@pytest.mark.slow
@requires_vma
def test_parity_without_offload():
    # the engine is independent of where the optimizer state lives
    assert_engines_match(optimizer_offload=False)


def test_auto_resolves_fused_only_when_supported():
    from picotron_tpu.parallel.fused_bwd import fused_bwd_supported

    assert fused_bwd_supported(engine_cfg("auto"))
    assert not fused_bwd_supported(
        engine_cfg("auto", dist_kw={"dp_size": 2, "pp_size": 2}))
    assert not fused_bwd_supported(
        engine_cfg("auto", remat_policy="dots"))
    assert not fused_bwd_supported(
        engine_cfg("auto", model_kw={"num_experts": 4,
                                     "num_experts_per_token": 2}))


def test_fused_rejects_unsupported_config():
    with pytest.raises(ValueError, match="fused"):
        engine_cfg("fused", remat_policy="dots").validate()


@pytest.mark.slow
@requires_vma
def test_grad_clip_parity():
    # the global-norm clip consumes the accumulated grads — same totals,
    # same clip scale, regardless of engine
    assert_engines_match(grad_clip_norm=0.1)
