"""Fleet serving tests (picotron_tpu/serve/fleet): failover re-dispatch
token parity at temperature > 0 across engine counts, deterministic
deadline shedding (order-invariant, like the PR-7 sampling tests),
graceful drain-then-retire with zero leaked blocks, least-loaded
routing, watchdog hang naming, and the fleet config guards."""

import json

import jax
import numpy as np
import pytest

from picotron_tpu.config import (
    Config, ModelConfig, ServeConfig, resolve_preset,
)
from picotron_tpu.models.llama import init_params
from picotron_tpu.resilience import chaos
from picotron_tpu.serve import FleetSupervisor, ServeEngine
from picotron_tpu.telemetry import Telemetry, bus
from picotron_tpu.telemetry.flightdeck import FlightRecorder


@pytest.fixture(autouse=True)
def _chaos_hygiene():
    chaos.install("")
    yield
    chaos.install("")


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny"), "max_position_embeddings": 64})
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def requests5(tiny):
    cfg, _ = tiny
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(0, cfg.vocab_size, size=n)))
               for n in (5, 9, 3, 7, 11)]
    return list(zip(prompts, [6, 3, 8, 5, 4]))


def scfg(**kw):
    base = dict(decode_slots=3, block_size=4, num_blocks=24,
                prefill_chunk=4, max_model_len=32, decode_interval=3)
    base.update(kw)
    return ServeConfig(**base)


@pytest.fixture(scope="module")
def sampled_refs(tiny, requests5):
    """Per-request tokens from a plain single ServeEngine at temperature
    0.7 — the parity oracle every fleet configuration (any size, any
    failover history) must reproduce bit-for-bit."""
    cfg, params = tiny
    eng = ServeEngine(params, cfg, scfg(), temperature=0.7, seed=7)
    res = eng.run(requests5)
    eng.close()
    return {r["id"]: r["tokens"] for r in res}


def make_fleet(params, cfg, n=2, **kw):
    return FleetSupervisor(params, cfg, scfg(fleet_size=n, **kw.pop(
        "cfg_kw", {})), temperature=0.7, seed=7, **kw)


class _Capture:
    """Minimal telemetry sink: keep every event dict for assertions."""

    def __init__(self):
        self.events = []

    def emit(self, e):
        self.events.append(e)

    def close(self):
        pass

    def of(self, kind):
        return [e for e in self.events if e.get("kind") == kind]


# ---------------------------------------------------------------------------
# failover re-dispatch parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_engines", [1, 2])
def test_fleet_token_parity_at_temperature(tiny, requests5, sampled_refs,
                                           n_engines):
    """Fleet size is invisible in the tokens: the sampling key folds
    (request id, token index), never (engine, slot), so 1 and 2 replicas
    emit identical streams at temperature 0.7."""
    cfg, params = tiny
    fl = make_fleet(params, cfg, n=n_engines)
    res = fl.run(requests5)
    assert {r["id"]: r["tokens"] for r in res} == sampled_refs
    assert fl.leaked_blocks() == 0
    assert fl.summary["fleet_size"] == n_engines
    fl.close()


def test_midflight_kill_redispatches_with_bit_parity(tiny, requests5,
                                                     sampled_refs):
    """The tentpole pin: kill 1 of 2 engines while requests are resident
    (some mid-decode), and the survivor finishes EVERYTHING — the
    re-dispatched continuations bit-identical to the fault-free oracle
    at temperature 0.7, zero blocks leaked on the survivor pool, and the
    death + every re-dispatch on the telemetry stream."""
    cfg, params = tiny
    cap = _Capture()
    tel = Telemetry(sinks=[cap])
    fl = make_fleet(params, cfg, n=2, telemetry=tel)
    for p, n in requests5:
        fl.submit(p, n)
    fl.tick()
    resident = sorted(s.req.id for s in fl.engines[0].sched.slots
                      if s is not None)
    assert resident, "nothing resident on engine 0 after a tick"
    moved = fl.kill_engine(0, cause="test")
    assert moved >= len(resident)
    assert fl.alive == [False, True]
    while fl.has_work():
        fl.tick()
    fl._emit_summary(0.0)

    assert {r["id"]: r["tokens"] for r in fl.results} == sampled_refs
    # survivor pool only: engine 0's pool was discarded with the engine
    assert fl.leaked_blocks() == 0
    dead = cap.of("serve_engine_dead")
    assert len(dead) == 1
    assert dead[0]["engine"] == 0 and dead[0]["inflight"] == moved
    redis = cap.of("serve_redispatch")
    assert len(redis) == moved == fl.summary["redispatched"]
    assert set(e["id"] for e in redis) >= set(resident)
    assert all(e["from_engine"] == 0 and e["to_engine"] == 1
               for e in redis)
    assert fl.summary["engines_dead"] == 1
    tel.close()


def test_kill_survivors_then_last_engine_raises(tiny, requests5):
    cfg, params = tiny
    fl = make_fleet(params, cfg, n=2)
    for p, n in requests5:
        fl.submit(p, n)
    fl.tick()
    fl.kill_engine(1)
    with pytest.raises(RuntimeError, match="no replicas survive"):
        fl.kill_engine(0)
    fl.close()


def test_chaos_engine_dead_in_route_loop(tiny, requests5, sampled_refs):
    """The chaos grammar's serve-side kill: `engine_dead@REQ` fires at
    the routing of request REQ, the fleet absorbs it as an abrupt engine
    death, and the run still drains to bit-parity."""
    chaos.install("engine_dead@2")
    cfg, params = tiny
    fl = make_fleet(params, cfg, n=2)
    res = fl.run(requests5)
    assert {r["id"]: r["tokens"] for r in res} == sampled_refs
    assert fl.summary["engines_dead"] == 1
    assert fl.leaked_blocks() == 0
    fl.close()


# ---------------------------------------------------------------------------
# deadline shedding
# ---------------------------------------------------------------------------


def _shed_trace(requests5):
    """Staggered arrivals with a deadline tight enough that a 1-slot
    engine must shed the tail of the burst."""
    return [(p, n, 0.0 if i < 3 else 0.002, 1.0)
            for i, (p, n) in enumerate(requests5)]


def test_shed_is_deterministic_on_the_virtual_clock(tiny, requests5):
    """The shed set is a pure function of the trace: the fleet loop
    advances a virtual clock by tick_s per iteration, so queue waits —
    and therefore shed decisions — cannot depend on host speed. Two runs
    agree exactly; every shed request is accounted (completed + shed =
    submitted) and excluded from results."""
    cfg, params = tiny

    def leg():
        fl = FleetSupervisor(params, cfg,
                             scfg(fleet_size=1, decode_slots=1,
                                  deadline_ms=1.0),
                             temperature=0.7, seed=7, tick_s=0.001)
        res = fl.run(_shed_trace(requests5))
        out = (sorted(s["id"] for s in fl.all_shed),
               {r["id"]: r["tokens"] for r in res},
               fl.leaked_blocks())
        fl.close()
        return out

    shed_a, res_a, leak_a = leg()
    shed_b, res_b, leak_b = leg()
    assert shed_a == shed_b and res_a == res_b
    assert shed_a, "trace shed nothing — the pin proves nothing"
    assert len(shed_a) + len(res_a) == len(requests5)
    assert not set(shed_a) & set(res_a)
    assert leak_a == leak_b == 0


def test_shed_decision_is_submission_order_invariant(tiny, requests5,
                                                     sampled_refs):
    """Like the PR-7 sampling pins: the fleet queue orders by (arrival,
    id), so submitting the same requests in a different order changes
    nothing — same shed set, same tokens for the admitted."""
    cfg, params = tiny
    trace = _shed_trace(requests5)

    def leg(order):
        fl = FleetSupervisor(params, cfg,
                             scfg(fleet_size=1, decode_slots=1,
                                  deadline_ms=1.0),
                             temperature=0.7, seed=7, tick_s=0.001)
        for i in order:
            p, n, arr, dl = trace[i]
            fl.submit(p, n, req_id=i, arrival=arr, deadline_ms=dl)
        while fl.has_work():
            fl.tick()
        out = (sorted(s["id"] for s in fl.all_shed),
               {r["id"]: r["tokens"] for r in fl.results})
        fl.close()
        return out

    fwd = leg(range(len(trace)))
    rev = leg(range(len(trace) - 1, -1, -1))
    assert fwd == rev
    shed, res = fwd
    assert shed
    for rid, toks in res.items():
        assert toks == sampled_refs[rid], rid


def test_shed_storm_chaos_forces_sheds(tiny, requests5):
    """`shed_storm@REQxN` drains an N-request budget through the routing
    point — forced overload independent of any deadline: request 2 and
    the next routed request shed, everything else completes."""
    chaos.install("shed_storm@2x2")
    cfg, params = tiny
    fl = make_fleet(params, cfg, n=2)
    res = fl.run(requests5)
    assert sorted(s["id"] for s in fl.shed_results) == [2, 3]
    assert all(s["shed"] for s in fl.shed_results)
    assert sorted(r["id"] for r in res) == [0, 1, 4]
    assert fl.summary["shed"] == 2
    fl.close()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------


def test_drain_then_retire_leaves_no_residents_no_leaks(tiny, requests5,
                                                        sampled_refs):
    cfg, params = tiny
    cap = _Capture()
    tel = Telemetry(sinks=[cap])
    fl = make_fleet(params, cfg, n=2, telemetry=tel)
    for p, n in requests5:
        fl.submit(p, n)
    fl.tick()
    fl.drain(0)
    while fl.has_work() or fl.draining:
        fl.tick()
    fl._emit_summary(0.0)

    assert fl.drained == [0] and fl.alive == [False, True]
    eng = fl.engines[0]
    assert not eng.sched.has_work() and eng.pool.in_use == 0
    assert fl.leaked_blocks() == 0
    assert {r["id"]: r["tokens"] for r in fl.results} == sampled_refs
    drains = cap.of("serve_drain")
    assert len(drains) == 1 and drains[0]["engine"] == 0
    assert drains[0]["pool_in_use"] == 0
    assert fl.summary["drains"] == 1
    tel.close()


def test_drain_grace_expiry_redispatches_residents(tiny, requests5):
    """A drain that outlives drain_grace_s (virtual seconds) forcibly
    re-dispatches the stragglers instead of waiting forever. Long
    requests (1 token per dispatch, 16-token budgets) guarantee the
    residents are still mid-decode when the zero grace expires."""
    cfg, params = tiny
    fl = FleetSupervisor(params, cfg,
                         scfg(fleet_size=2, drain_grace_s=0.0,
                              decode_interval=1),
                         temperature=0.7, seed=7, tick_s=0.001)
    for p, _ in requests5:
        fl.submit(p, 16)
    fl.tick()
    assert fl.engines[0].sched.has_work()
    fl.drain(0)
    for _ in range(50):
        fl.tick()
        if 0 in fl.drained:
            break
    assert 0 in fl.drained
    assert fl.engines[0].pool.in_use == 0
    assert fl.n_redispatched > 0
    while fl.has_work():
        fl.tick()
    assert len(fl.results) == len(requests5)
    assert fl.leaked_blocks() == 0
    fl.close()


def test_drain_last_routable_engine_rejected(tiny):
    cfg, params = tiny
    fl = make_fleet(params, cfg, n=2)
    fl.drain(0)
    with pytest.raises(ValueError, match="last routable"):
        fl.drain(1)
    fl.close()


# ---------------------------------------------------------------------------
# routing + health
# ---------------------------------------------------------------------------


def test_routing_is_least_loaded(tiny, requests5):
    cfg, params = tiny
    fl = make_fleet(params, cfg, n=2)
    fl.run(requests5)
    per = [pe["requests"] for pe in fl.summary["per_engine"]]
    assert sum(per) == len(requests5)
    assert all(n > 0 for n in per), (
        f"least-loaded routing left an engine idle: {per}")
    fl.close()


def test_watchdog_names_hung_decode_dispatch(tiny, requests5, tmp_path):
    """A wedged decode dispatch (chaos decode_hang in the fleet loop)
    trips the supervisor watchdog with a phase naming the exact engine
    and dispatch, and the flightdeck postmortem reason is serve_hang —
    the serving twin of the training watchdog contract. on_timeout
    stands in for the supervisor exit(77) so the test survives."""
    cfg, params = tiny
    tel = Telemetry(sinks=[])
    tel.flight = FlightRecorder(str(tmp_path), max_steps=4)
    bus.install(tel)
    chaos.install("decode_hang@0~1.2")
    fired = []
    try:
        fl = make_fleet(params, cfg, n=2, telemetry=tel,
                        watchdog_timeout=0.3,
                        watchdog_on_timeout=lambda: fired.append(
                            fl.watchdog._last))
        fl.run(requests5)
    finally:
        bus.install(None)
    assert fired, "watchdog never fired on a 1.2s hang at 0.3s timeout"
    _, phase, _ = fired[0]
    assert phase.startswith("serve engine=") and "dispatch=decode" in phase
    pm = json.loads(
        (tmp_path / "flightdeck_postmortem.json").read_text())
    assert pm["reason"] == "serve_hang"
    assert "dispatch=decode" in pm["extra"]["phase"]
    fl.close()


# ---------------------------------------------------------------------------
# config guards
# ---------------------------------------------------------------------------


def test_config_rejects_fleet_moe_and_speculator():
    moe = ModelConfig(**resolve_preset("debug-tiny-moe"))
    with pytest.raises(ValueError, match="fleet_size"):
        Config(model=moe, serve=ServeConfig(fleet_size=2)).validate()
    with pytest.raises(ValueError, match="speculator"):
        Config(model=ModelConfig(**resolve_preset("debug-tiny")),
               serve=ServeConfig(fleet_size=2,
                                 speculator="ngram")).validate()
    # fleet of 1 with a speculator is the existing single-engine path;
    # a dense fleet of 2 is the supported configuration
    Config(model=ModelConfig(**resolve_preset("debug-tiny")),
           serve=ServeConfig(fleet_size=1,
                             speculator="ngram")).validate()
    Config(model=ModelConfig(**resolve_preset("debug-tiny")),
           serve=ServeConfig(fleet_size=2)).validate()


def test_serve_config_validates_fleet_fields():
    with pytest.raises(ValueError, match="fleet_size"):
        ServeConfig(fleet_size=0).validate()
    with pytest.raises(ValueError, match="deadline_ms"):
        ServeConfig(deadline_ms=-1.0).validate()
    with pytest.raises(ValueError, match="drain_grace_s"):
        ServeConfig(drain_grace_s=-0.1).validate()


def test_supervisor_rejects_speculator(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="speculator"):
        FleetSupervisor(params, cfg,
                        scfg(fleet_size=2, speculator="ngram",
                             draft_len=2))
