"""Pipeline-engine tests: 1F1B vs AFAB equivalence, the 1F1B memory bound,
and remat-policy effect in the pipeline path (ref: the reference validates
its schedules by loss parity between pipeline_parallel_1f1b and
pipeline_parallel_afab, pipeline_parallel.py:122-215 vs 77-118)."""

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import Config, DistributedConfig, ModelConfig, TrainingConfig
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.parallel.api import init_sharded_state, make_train_step


def pp_cfg(engine, pp=2, gas=4, tp=1, remat=False, remat_policy="dots",
           seq=32, mbs=2, hidden=64):
    return Config(
        distributed=DistributedConfig(pp_size=pp, tp_size=tp, pp_engine=engine),
        model=ModelConfig(dtype="float32", hidden_size=hidden,
                          num_attention_heads=8, num_key_value_heads=4),
        training=TrainingConfig(seq_length=seq, micro_batch_size=mbs,
                                gradient_accumulation_steps=gas,
                                learning_rate=1e-3, remat=remat,
                                remat_policy=remat_policy),
    )


def batch_for(cfg, menv, key=0):
    t = cfg.training
    b_global = t.micro_batch_size * cfg.distributed.dp_size
    toks = jax.random.randint(
        jax.random.key(key),
        (t.gradient_accumulation_steps, b_global, t.seq_length + 1),
        0, cfg.model.vocab_size)
    sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
    return (jax.device_put(toks[..., :-1], sh),
            jax.device_put(toks[..., 1:], sh))


def run_engine(cfg, steps=3):
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    batch = batch_for(cfg, menv)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, state


@pytest.mark.parametrize("layout", [
    # (pp2/gas4 pruned r5: strict subset of pp4/gas4 and pp2xtp2)
    dict(pp=4, gas=4),
    dict(pp=2, gas=4, tp=2),
    dict(pp=2, gas=3, remat=True),  # odd n_micro + remat'd tick bodies
])
@pytest.mark.slow
def test_1f1b_matches_afab(layout):
    """The two engines compute the same gradients (same math, different
    schedule); only fp reduction order differs."""
    l_1f1b, s_1f1b = run_engine(pp_cfg("1f1b", **layout))
    l_afab, s_afab = run_engine(pp_cfg("afab", **layout))
    np.testing.assert_allclose(l_1f1b, l_afab, rtol=1e-5, atol=1e-6)
    for name in ("embedding", "lm_head"):
        np.testing.assert_allclose(
            np.asarray(s_1f1b.params[name]), np.asarray(s_afab.params[name]),
            rtol=2e-3, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(s_1f1b.params["layers"]["q"]),
        np.asarray(s_afab.params["layers"]["q"]), rtol=2e-3, atol=1e-4)


def _compiled_temp_bytes(cfg):
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    batch = batch_for(cfg, menv)
    stats = step.lower(state, batch).compile().memory_analysis()
    return stats.temp_size_in_bytes


@pytest.mark.slow
def test_1f1b_memory_bound():
    """1F1B's live activation set is <= pp microbatches (ring buffer);
    AFAB's grows with n_micro (per-tick scan residuals). With activations
    sized to dominate the parameter buffers, compiled temp memory must be
    materially smaller for 1F1B at large n_micro."""
    layout = dict(pp=2, gas=16, seq=512, mbs=4, remat=True,
                  remat_policy="full")
    t_afab = _compiled_temp_bytes(pp_cfg("afab", **layout))
    t_1f1b = _compiled_temp_bytes(pp_cfg("1f1b", **layout))
    # boundary activation = mbs*seq*hidden*4B = 512KB; AFAB stores one per
    # tick (17) vs 1F1B's ring of pp (2) — expect several MB of daylight.
    assert t_1f1b < t_afab, (t_1f1b, t_afab)
    assert t_afab - t_1f1b > 4 * layout["mbs"] * layout["seq"] * 64, \
        (t_1f1b, t_afab)


def test_1f1b_tick_count_and_schedule_rate():
    """The 1F1B scan must run n_micro + 2(pp-1) ticks — the full-rate
    schedule (one active F and one active B per stage per steady tick), not
    the half-rate 2*n_micro + 2(pp-1) - 1 of VERDICT r2 weak #1. Pinned via
    the helper AND the traced scan length."""
    import re

    from picotron_tpu.parallel.pp import pp_1f1b_ring_slots, pp_1f1b_ticks

    assert pp_1f1b_ticks(8, 4) == 14
    assert pp_1f1b_ticks(4, 1) == 4
    assert pp_1f1b_ring_slots(8, 4) == 6
    assert pp_1f1b_ring_slots(2, 4) == 2  # never larger than n_micro
    assert pp_1f1b_ring_slots(4, 1) == 1

    pp_size, gas = 4, 8
    cfg = pp_cfg("1f1b", pp=pp_size, gas=gas)
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    batch = batch_for(cfg, menv)
    jaxpr = str(jax.make_jaxpr(lambda s, b: step(s, b))(state, batch))
    lengths = {int(x) for x in re.findall(r"length=(\d+)", jaxpr)}
    assert pp_1f1b_ticks(gas, pp_size) in lengths, lengths
    old_ticks = 2 * gas + 2 * (pp_size - 1) - 1
    assert old_ticks not in lengths, lengths


@pytest.mark.slow
def test_afab_remat_policy_reaches_pipeline_tick():
    """remat_policy must change what the AFAB tick scan saves (VERDICT r1:
    the pp path used to blanket-full-remat regardless of policy)."""
    jaxprs = {}
    losses = {}
    for policy in ("full", "dots", "dots_attn", "dots_norms"):
        cfg = pp_cfg("afab", pp=2, gas=2, remat=True, remat_policy=policy)
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        step = make_train_step(cfg, menv)
        batch = batch_for(cfg, menv)
        jaxprs[policy] = str(jax.make_jaxpr(lambda s, b: step(s, b))(state, batch))
        _, metrics = step(state, batch)
        losses[policy] = float(metrics["loss"])
    assert jaxprs["full"] != jaxprs["dots"]
    # each named policy must actually differ from its neighbors (a
    # checkpoint_name typo would silently degrade it) and keep numerics
    assert jaxprs["dots_norms"] != jaxprs["dots"]
    assert jaxprs["dots_attn"] != jaxprs["dots"]
    assert jaxprs["dots_attn"] != jaxprs["full"]
    for policy in ("dots", "dots_attn", "dots_norms"):
        np.testing.assert_allclose(losses["full"], losses[policy],
                                   rtol=1e-6)


def test_dots_offload_policy_compiles_and_matches():
    """dots_offload (activations parked in pinned host — placement is a
    no-op on CPU but the offload-annotated jaxpr must compile and keep
    numerics; the on-chip economics are recorded in PERF.md r4)."""
    losses = {}
    for policy in ("dots", "dots_offload"):
        cfg = pp_cfg("afab", pp=2, gas=2, remat=True, remat_policy=policy)
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        step = make_train_step(cfg, menv)
        _, metrics = step(state, batch_for(cfg, menv))
        losses[policy] = float(metrics["loss"])
    np.testing.assert_allclose(losses["dots"], losses["dots_offload"],
                               rtol=1e-6)
