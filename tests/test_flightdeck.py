"""Flightdeck tests (picotron_tpu/telemetry/flightdeck): the span
tracer's recording/ordering/bounding invariants, the Perfetto-schema
validity of an exported trace carrying every span family (train phases,
MPMD pp2 stage ticks, the serve request lifecycle, resilience
instants), the flight recorder's ring/dump semantics, the drift
sentinel's exactly-one-alert contract (and its silence on a clean
twin), the config-driven install() policy, and the
tools/trace_export.py --validate gate run as a subprocess smoke —
tier-1, like the shardcheck gates."""

import importlib.util
import json
import os
import re
import subprocess
import sys

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, PipelineConfig, ServeConfig,
    TrainingConfig, config_from_dict, resolve_preset,
)
from picotron_tpu.telemetry import JsonlSink, Telemetry, bus
from picotron_tpu.telemetry.flightdeck import (
    DriftSentinel, FlightRecorder, SpanTracer, TID_PP_BASE, TID_SENTINEL,
    TID_SERVE, TID_TRAIN, install as flightdeck_install,
)
from picotron_tpu.telemetry.flightdeck.flight import POSTMORTEM_NAME

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
TRACE_EXPORT = os.path.join(TOOLS, "trace_export.py")


def load_trace_export():
    spec = importlib.util.spec_from_file_location(
        "trace_export", TRACE_EXPORT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class Clock:
    """Deterministic tracer clock (seconds)."""

    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_tracer_span_ordering_nesting_and_backdating():
    c = Clock()
    tr = SpanTracer(clock=c)
    outer_start = tr.now()
    c.t = 100.010
    inner_start = tr.now()
    c.t = 100.020
    tr.complete("inner", start_s=inner_start, dur_s=0.010, mb=1)
    c.t = 100.030
    tr.complete("outer", start_s=outer_start, dur_s=0.030)
    # the phase-hook shape: duration learned only after the fact
    c.t = 100.050
    tr.complete("late", dur_s=0.010)
    doc = tr.to_json()
    events = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    # sorted by ts regardless of recording order: outer first
    assert [e["name"] for e in events] == ["outer", "inner", "late"]
    outer, inner, late = events
    assert outer["ts"] == pytest.approx(0.0, abs=1e-6)
    assert outer["dur"] == pytest.approx(30_000.0)  # microseconds
    # nesting invariant: the inner span lies within the outer window
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    # back-dated span starts dur_s before now
    assert late["ts"] == pytest.approx(40_000.0)
    assert inner["args"] == {"mb": 1}
    assert all(e["ph"] == "X" and e["tid"] == TID_TRAIN for e in events)


def test_tracer_instants_counters_and_lane_labels():
    tr = SpanTracer(clock=Clock())
    tr.instant("rollback", step=4)
    tr.counter("step_time_s", value=1.25)
    tr.complete("stage1/tick3/F/mb0", tid=TID_PP_BASE + 1, dur_s=0.001)
    tr.complete("prefill", tid=TID_SERVE, dur_s=0.001, ids=[0, 1])
    doc = tr.to_json()
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    # lanes self-label: train, serve, flightdeck, pp_stage1
    names = {e["tid"]: e["args"]["name"] for e in meta}
    assert names[TID_TRAIN] == "train"
    assert names[TID_SERVE] == "serve"
    assert names[TID_SENTINEL] == "flightdeck"
    assert names[TID_PP_BASE + 1] == "pp_stage1"
    inst = next(e for e in doc["traceEvents"] if e["ph"] == "i")
    assert inst["s"] == "p" and inst["args"] == {"step": 4}
    cnt = next(e for e in doc["traceEvents"] if e["ph"] == "C")
    assert cnt["tid"] == TID_SENTINEL and cnt["args"] == {"value": 1.25}


def test_tracer_bounded_ring_counts_drops():
    tr = SpanTracer(clock=Clock(), max_events=5)
    for i in range(8):
        tr.complete(f"s{i}", dur_s=0.001)
    assert len(tr) == 5 and tr.dropped == 3
    doc = tr.to_json()
    assert doc["otherData"]["dropped_events"] == 3
    # a truncated trace is never mistaken for a quiet one
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) == 5


def test_tracer_mark_since_and_atomic_export(tmp_path):
    tr = SpanTracer(clock=Clock())
    tr.complete("before", dur_s=0.0)
    m = tr.mark()
    tr.complete("after1", dur_s=0.0)
    tr.instant("after2")
    assert [e["name"] for e in tr.since(m)] == ["after1", "after2"]
    assert tr.since(tr.mark()) == []
    path = str(tmp_path / "trace.json")
    assert tr.export(path) == path
    assert not os.path.exists(path + ".tmp")
    doc = json.load(open(path))
    assert doc["traceEvents"][0]["ph"] == "M"  # metadata lanes lead


# ---------------------------------------------------------------------------
# the acceptance trace: every span family on one validated timeline
# ---------------------------------------------------------------------------


def _mpmd_cfg():
    return Config(
        distributed=DistributedConfig(pp_size=2, dp_size=1, tp_size=1),
        model=ModelConfig(dtype="float32", hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=4),
        training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                gradient_accumulation_steps=2,
                                learning_rate=1e-3, remat=False),
        pipeline=PipelineConfig(executor="mpmd", schedule="1f1b"),
    )


def test_dryrun_trace_has_all_span_families_and_validates(tmp_path):
    """The acceptance pin: a 2-step CPU dryrun (real MPMD pp2 executor +
    real disaggregated serve engine, driven through the facade) exports
    one Chrome-trace JSON carrying train phases, per-op stage-tick
    spans, the serve request lifecycle (queue_wait -> prefill -> handoff
    -> decode with request ids), and a resilience instant — and
    `tools/trace_export.py --validate` accepts it (subprocess, the same
    gate a CI smoke would run)."""
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.models.llama import init_params
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step
    from picotron_tpu.serve import DisaggServeEngine

    trace_path = str(tmp_path / "trace.json")
    tel = Telemetry(sinks=[])
    tel.tracer = SpanTracer()
    tel.trace_path = trace_path
    bus.install(tel)  # the MPMD walker finds the tracer via the bus
    try:
        cfg = _mpmd_cfg()
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        step_fn = make_train_step(cfg, menv)
        t = cfg.training
        toks = jax.random.randint(
            jax.random.key(1),
            (t.gradient_accumulation_steps, t.micro_batch_size,
             t.seq_length + 1), 0, cfg.model.vocab_size)
        sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
        batch = (jax.device_put(toks[..., :-1], sh),
                 jax.device_put(toks[..., 1:], sh))
        for step in (1, 2):
            with tel.phases.phase("data", step):
                pass
            with tel.phases.phase("step", step):
                state, metrics = step_fn(state, batch)
                jax.block_until_ready(metrics)
            tel.record_step(step, "[step] ...", loss=float(metrics["loss"]))

        mcfg = ModelConfig(dtype="float32", **{
            **resolve_preset("debug-tiny"), "max_position_embeddings": 64})
        params = init_params(mcfg, jax.random.key(0))
        rng = np.random.default_rng(0)
        reqs = [(list(map(int, rng.integers(0, mcfg.vocab_size, size=n))), 3)
                for n in (5, 7)]
        eng = DisaggServeEngine(
            params, mcfg,
            ServeConfig(decode_slots=2, block_size=4, num_blocks=16,
                        prefill_chunk=4, max_model_len=32,
                        decode_interval=2, disagg=True),
            telemetry=tel)
        eng.run(reqs)
        eng.close()

        tel.emit("chaos", chaos_kind="sigterm", step=2)
    finally:
        tel.close()  # exports the trace

    doc = json.load(open(trace_path))
    events = doc["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    names_by_lane = {}
    for e in spans:
        names_by_lane.setdefault(e["tid"], set()).add(e["name"])
    # train phases
    assert {"data", "step"} <= names_by_lane[TID_TRAIN]
    # MPMD stage/tick/op/mb spans on both pp stage lanes
    tick_re = re.compile(r"stage\d+/tick\d+/\w+/mb\d+")
    for stage in (0, 1):
        lane = names_by_lane.get(TID_PP_BASE + stage, set())
        assert any(tick_re.fullmatch(n) for n in lane), (stage, lane)
    # serve request lifecycle, ids attached
    assert {"queue_wait", "prefill", "handoff",
            "decode"} <= names_by_lane[TID_SERVE]
    serve = [e for e in spans if e["tid"] == TID_SERVE]
    assert any("id" in e.get("args", {}) for e in serve
               if e["name"] == "queue_wait")
    assert any("ids" in e.get("args", {}) for e in serve
               if e["name"] in ("prefill", "decode"))
    # resilience instant
    assert any(e["ph"] == "i" and e["name"] == "chaos" for e in events)
    # lanes are labeled
    labels = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"train", "serve", "pp_stage0", "pp_stage1"} <= labels

    proc = subprocess.run(
        [sys.executable, TRACE_EXPORT, "--validate", trace_path],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.startswith("OK:")


def test_trace_validate_catches_violations(tmp_path):
    bad = {"traceEvents": [
        {"name": "a", "ph": "X", "pid": 0, "tid": 0, "ts": 100.0,
         "dur": 5.0},
        {"name": "b", "ph": "X", "pid": 0, "tid": 0, "ts": 50.0,
         "dur": -1.0},                                   # rewind + negative
        {"name": "c", "ph": "B", "pid": 0, "tid": 1, "ts": 160.0},
        {"name": "d", "ph": "E", "pid": 0, "tid": 2, "ts": 170.0},
        {"name": "e", "ph": "X", "pid": "zero", "tid": 0, "ts": 180.0,
         "dur": 1.0},                                    # string pid
        {"name": "f", "ph": "??", "ts": 190.0},          # invalid ph
    ]}
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    te = load_trace_export()
    errors = te.validate(str(p))
    text = "\n".join(errors)
    assert "not monotonic" in text
    assert "dur >= 0" in text
    assert "never closed" in text
    assert "E without matching B" in text
    assert "pid/tid must be integers" in text
    assert "invalid ph" in text

    proc = subprocess.run(
        [sys.executable, TRACE_EXPORT, "--validate", str(p)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "TRACE VIOLATION" in proc.stderr

    # a clean trace passes in-module too
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"traceEvents": [
        {"name": "s", "ph": "X", "pid": 0, "tid": 0, "ts": 1.0,
         "dur": 2.0}]}))
    assert te.validate(str(good)) == []


def test_trace_export_converts_jsonl_to_valid_trace(tmp_path):
    """The post-hoc fallback: a telemetry.jsonl becomes a valid trace
    with serve phases on the serve lane (ids carried), train phases on
    the train lane (back-dated from their end-stamped events), and
    resilience kinds as instants."""
    te = load_trace_export()
    src = tmp_path / "telemetry.jsonl"
    with open(src, "w") as f:
        for e in [
            {"ts": 100.0, "kind": "run_start"},
            {"ts": 103.0, "kind": "phase", "phase": "step", "step": 1,
             "category": "compute", "secs": 2.0},
            {"ts": 103.5, "kind": "phase", "phase": "prefill",
             "category": "serve", "secs": 0.25, "ids": [0, 1]},
            {"ts": 104.0, "kind": "chaos", "chaos_kind": "sigterm",
             "step": 1},
        ]:
            f.write(json.dumps(e) + "\n")
    doc = te.convert(te.load_events(str(src)))
    spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert spans["step"]["tid"] == TID_TRAIN
    # end-stamped at 103.0 with secs=2.0 -> starts 1.0s after run_start
    assert spans["step"]["ts"] == pytest.approx(1.0e6)
    assert spans["step"]["dur"] == pytest.approx(2.0e6)
    assert spans["prefill"]["tid"] == TID_SERVE
    assert spans["prefill"]["args"]["ids"] == [0, 1]
    assert any(e["ph"] == "i" and e["name"] == "chaos"
               for e in doc["traceEvents"])
    out = tmp_path / "converted.json"
    out.write_text(json.dumps(doc))
    assert te.validate(str(out)) == []


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def _feed_step(fr, step, step_s=1.0, **metrics):
    fr.on_phase("data", 0.1, step=step)
    fr.on_phase("step", step_s, step=step)
    fr.on_step(step, {"loss": 2.0, "line": "[step] ...", **metrics})


def test_flight_ring_evicts_oldest_and_dumps(tmp_path):
    fr = FlightRecorder(str(tmp_path), max_steps=3)
    for s in range(1, 7):
        _feed_step(fr, s)
    fr.on_event("chaos", {"chaos_kind": "sigterm", "step": 6,
                          "line": "noise"})
    path = fr.dump("watchdog", step=6, phase="data", stalled_s=12.5)
    assert path == os.path.join(str(tmp_path), POSTMORTEM_NAME)
    doc = json.load(open(path))
    assert doc["reason"] == "watchdog"
    assert doc["step"] == 6
    # the bounded ring holds exactly the last 3 steps
    assert [r["step"] for r in doc["steps"]] == [4, 5, 6]
    rec = doc["steps"][-1]
    assert rec["phases"]["step"] == pytest.approx(1.0)
    assert rec["metrics"]["loss"] == 2.0
    assert "line" not in rec["metrics"]  # presentation, not signal
    assert doc["recent_events"] == [{"kind": "chaos",
                                     "chaos_kind": "sigterm", "step": 6}]
    assert doc["extra"] == {"phase": "data", "stalled_s": 12.5}
    assert fr.dumps == 1


def test_flight_partial_step_and_fallback_step(tmp_path):
    fr = FlightRecorder(str(tmp_path), max_steps=4)
    _feed_step(fr, 1)
    fr.on_phase("data", 0.5, step=2)  # step 2 dies mid-flight
    doc = fr.snapshot("exception")
    assert doc["step"] == 2  # no explicit step: last seen wins
    partial = doc["steps"][-1]
    assert partial["partial"] is True and partial["step"] == 2
    assert partial["phases"] == {"data": 0.5}
    assert doc["steps"][0]["step"] == 1
    assert fr.last_step() == 2
    assert FlightRecorder(str(tmp_path)).last_step() is None


def test_flight_dump_never_raises_and_last_writer_wins(tmp_path):
    # unwritable directory: best-effort None, no exception
    fr = FlightRecorder(str(tmp_path / "does" / "not" / "exist"))
    assert fr.dump("watchdog") is None and fr.dumps == 0
    fr2 = FlightRecorder(str(tmp_path))
    _feed_step(fr2, 1)
    fr2.dump("rollback", step=1)
    _feed_step(fr2, 2)
    fr2.dump("preempted", step=2)
    doc = json.load(open(os.path.join(str(tmp_path), POSTMORTEM_NAME)))
    assert doc["reason"] == "preempted" and doc["step"] == 2
    assert fr2.dumps == 2


def test_flight_attributes_tracer_spans_per_step(tmp_path):
    c = Clock()
    tr = SpanTracer(clock=c)
    tr.complete("preamble", dur_s=0.0)  # before the recorder attaches
    fr = FlightRecorder(str(tmp_path), max_steps=4, tracer=tr)
    tr.complete("stage0/tick0/F/mb0", tid=TID_PP_BASE, dur_s=0.001)
    fr.on_step(1, {})
    tr.complete("stage0/tick1/B/mb0", tid=TID_PP_BASE, dur_s=0.001)
    fr.on_step(2, {})
    doc = fr.snapshot("watchdog")
    assert [s["name"] for s in doc["steps"][0]["spans"]] == \
        ["stage0/tick0/F/mb0"]
    assert [s["name"] for s in doc["steps"][1]["spans"]] == \
        ["stage0/tick1/B/mb0"]


# ---------------------------------------------------------------------------
# drift sentinel
# ---------------------------------------------------------------------------


def _run_sentinel(sen, step_times, data=0.0, sync=0.0):
    alerts = []
    for i, st in enumerate(step_times, start=1):
        if data:
            sen.observe_phase("data", data)
        if sync:
            sen.observe_phase("sync", sync)
        sen.observe_phase("step", st)
        a = sen.on_step(i)
        if a is not None:
            alerts.append(a)
    return alerts


def test_sentinel_fires_exactly_once_on_sustained_regression():
    sen = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=3)
    # 8 clean steps at 1.0s, then a sustained 3x regression
    alerts = _run_sentinel(sen, [1.0] * 8 + [3.0] * 6)
    assert len(alerts) == 1
    a = alerts[0]
    assert a["quantity"] == "step_time"
    assert a["value"] == pytest.approx(3.0)
    assert a["baseline"] == pytest.approx(1.0)
    assert a["ratio"] == pytest.approx(3.0)
    assert a["streak"] == 3
    assert a["step"] == 11  # the patience'th consecutive breach
    assert a["step_time_p50_s"] == pytest.approx(1.0)
    assert sen.alerted and sen.stats()["alerts"] == 1
    # breaching samples stayed OUT of the baseline window
    assert sen.stats()["step_time_p50_s"] == pytest.approx(1.0)


def test_sentinel_silent_on_clean_twin_and_during_warmup():
    sen = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=3)
    jittered = [1.0 + 0.002 * ((i % 5) - 2) for i in range(24)]
    assert _run_sentinel(sen, jittered) == []
    assert not sen.alerted
    assert sen.stats()["step_time_p50_s"] == pytest.approx(1.0, abs=0.01)
    # warmup: a spike before the baseline exists is never judged
    sen2 = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=1)
    assert _run_sentinel(sen2, [1.0, 50.0, 1.0]) == []


def test_sentinel_transient_blip_resets_streak():
    sen = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=3)
    # two-step blips (below patience) never alert, however many
    alerts = _run_sentinel(
        sen, [1.0] * 8 + [3.0, 3.0, 1.0, 3.0, 3.0, 1.0, 3.0, 3.0, 1.0])
    assert alerts == []


def test_sentinel_zscore_suppresses_noisy_ratio_trips():
    sen = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=2)
    noisy = [0.5, 1.5] * 4  # median 1.0, wide std
    assert _run_sentinel(sen, noisy + [1.6] * 4) == []  # ratio yes, z no
    alerts = _run_sentinel(sen, [10.0] * 2)  # far outside the noise
    assert len(alerts) == 1 and alerts[0]["quantity"] == "step_time"


def test_sentinel_sync_share_judged_against_cost_model_prediction():
    sen = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=2,
                        predicted={"total_s": 2.0, "exposed_comm_s": 0.2})
    assert sen.predicted_sync_share() == pytest.approx(0.1)
    # measured sync share 0.10 == predicted: clean
    assert _run_sentinel(sen, [0.9] * 6, sync=0.1) == []
    # exposed comm grows to a 0.45 share while step wall stays 1.0s —
    # step_time cannot fire, sync_share (vs the prediction) must
    alerts = _run_sentinel(sen, [0.55] * 2, sync=0.45)
    assert len(alerts) == 1
    assert alerts[0]["quantity"] == "sync_share"
    assert alerts[0]["baseline"] == pytest.approx(0.1)
    assert sen.stats()["predicted_sync_share"] == pytest.approx(0.1)


def test_sentinel_data_wait_share_regression():
    sen = DriftSentinel(window=8, zscore=4.0, ratio=1.5, patience=2)
    assert _run_sentinel(sen, [1.0] * 8, data=0.1) == []
    alerts = _run_sentinel(sen, [1.0] * 2, data=1.0)
    assert len(alerts) == 1
    assert alerts[0]["quantity"] == "data_wait_share"


def test_sentinel_eval_only_iterations_are_skipped():
    sen = DriftSentinel(window=8, patience=1)
    sen.observe_phase("data", 0.5)  # no step/sync phase this iteration
    assert sen.on_step(1) is None
    assert sen.stats()["window"] == 0


# ---------------------------------------------------------------------------
# facade integration: one alert, one auto-dump, report surfaces it
# ---------------------------------------------------------------------------


def test_facade_sentinel_alert_emits_once_and_autodumps(tmp_path):
    p = str(tmp_path / "telemetry.jsonl")
    tel = Telemetry(sinks=[JsonlSink(p)])
    tel.flight = FlightRecorder(str(tmp_path), max_steps=4)
    tel.sentinel = DriftSentinel(window=8, zscore=4.0, ratio=1.5,
                                 patience=2)
    times = [1.0] * 8 + [4.0] * 5
    for i, st in enumerate(times, start=1):
        tel.emit("phase", phase="step", secs=st, book=False, step=i)
        tel.record_step(i, "[step] ...", loss=2.0)
    tel.close()
    rows = [json.loads(ln) for ln in open(p)]
    alerts = [r for r in rows if r["kind"] == "sentinel_alert"]
    assert len(alerts) == 1
    assert alerts[0]["quantity"] == "step_time"
    assert alerts[0]["step"] == 10
    # the run summary carries the sentinel stats block
    summary = rows[-1]
    assert summary["kind"] == "run_summary"
    assert summary["sentinel"]["alerts"] == 1
    # auto-dump: the postmortem names the alert and the fault step
    doc = json.load(open(tmp_path / POSTMORTEM_NAME))
    assert doc["reason"] == "sentinel_alert"
    assert doc["step"] == 10
    assert doc["extra"]["alert"]["quantity"] == "step_time"
    assert doc["steps"]  # the last-K window came along

    # the report tool renders the sentinel row from the same stream
    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(TOOLS, "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    s = rep.summarize(rep.load_events(p))
    assert s["sentinel"]["alerts"] == 1
    assert s["sentinel"]["quantity"] == "step_time"
    text = rep.render(s)
    assert "sentinel: 1 alert(s)" in text
    assert "flightdeck_postmortem.json" in text


def test_facade_clean_run_emits_no_sentinel_events(tmp_path):
    p = str(tmp_path / "telemetry.jsonl")
    tel = Telemetry(sinks=[JsonlSink(p)])
    tel.flight = FlightRecorder(str(tmp_path), max_steps=4)
    tel.sentinel = DriftSentinel(window=8, zscore=4.0, ratio=1.5,
                                 patience=2)
    for i in range(1, 14):
        tel.emit("phase", phase="step", secs=1.0, book=False, step=i)
        tel.record_step(i, "[step] ...", loss=2.0)
    tel.close()
    kinds = [json.loads(ln)["kind"] for ln in open(p)]
    assert "sentinel_alert" not in kinds
    assert not os.path.exists(tmp_path / POSTMORTEM_NAME)  # no dump


# ---------------------------------------------------------------------------
# install(): the config-driven attachment policy
# ---------------------------------------------------------------------------


def test_install_attaches_per_config(tmp_path):
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(tmp_path / "ck")},
        "logging": {"trace_dir": str(tmp_path / "tr"),
                    "flight_steps": 4, "sentinel": True,
                    "sentinel_window": 16, "sentinel_patience": 2},
    })
    tel = Telemetry(sinks=[])
    try:
        flightdeck_install(tel, cfg)
        assert tel.tracer is not None
        assert tel.trace_path == str(tmp_path / "tr" / "trace.json")
        assert tel.flight is not None and tel.flight.max_steps == 4
        assert tel.flight.path == str(
            tmp_path / "ck" / POSTMORTEM_NAME)
        assert tel.flight.tracer is tel.tracer
        assert tel.sentinel is not None
        assert tel.sentinel.window == 16 and tel.sentinel.patience == 2
    finally:
        tel.close()


def test_install_defaults_leave_hot_path_untouched(tmp_path):
    # default logging config + a save_dir: the flight recorder is on
    # (abnormal exits always leave a postmortem) but the tracer and
    # sentinel — the pieces with per-phase cost — stay None
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(tmp_path / "ck")}})
    tel = Telemetry(sinks=[])
    try:
        flightdeck_install(tel, cfg)
        assert tel.tracer is None and tel.trace_path is None
        assert tel.sentinel is None
        assert tel.flight is not None and tel.flight.max_steps == 8
        # flight_steps=0 is the postmortem off-switch
        cfg2 = config_from_dict({
            "model": {"name": "debug-tiny"},
            "checkpoint": {"save_dir": str(tmp_path / "ck2")},
            "logging": {"flight_steps": 0}})
        tel2 = Telemetry(sinks=[])
        flightdeck_install(tel2, cfg2)
        assert tel2.flight is None
        assert tel2.tracer is None and tel2.sentinel is None
        tel2.close()
    finally:
        tel.close()


def test_install_multiprocess_trace_paths(tmp_path):
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "logging": {"trace_dir": str(tmp_path / "tr")}})
    tel = Telemetry(sinks=[])
    try:
        flightdeck_install(tel, cfg, process_index=2)
        assert tel.trace_path == str(tmp_path / "tr" / "trace.p2.json")
        assert tel.tracer.pid == 2
    finally:
        tel.close()


def test_logging_config_validates_flightdeck_fields():
    def cfg(**logging):
        return config_from_dict({"model": {"name": "debug-tiny"},
                                 "logging": logging})

    cfg(sentinel=True, sentinel_window=4).validate()  # the floor is legal
    with pytest.raises(ValueError):
        cfg(telemetry_max_mb=-1).validate()
    with pytest.raises(ValueError):
        cfg(flight_steps=-1).validate()
    with pytest.raises(ValueError):
        cfg(sentinel_window=2).validate()
    with pytest.raises(ValueError):
        cfg(sentinel_ratio=1.0).validate()
    with pytest.raises(ValueError):
        cfg(sentinel_zscore=0.0).validate()
    with pytest.raises(ValueError):
        cfg(sentinel_patience=0).validate()
