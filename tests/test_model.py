import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.config import Config, ModelConfig, TrainingConfig
from picotron_tpu.models.llama import (
    DEFAULT_CTX,
    forward,
    init_params,
    loss_fn,
    param_count,
)
from picotron_tpu.ops.attention import repeat_kv, sdpa_attention
from picotron_tpu.ops.rmsnorm import rms_norm
from picotron_tpu.ops.rope import apply_rope, rope_tables

TINY = ModelConfig(dtype="float32")  # debug-tiny defaults, fp32 for exactness


def test_rope_matches_manual_rotate_half():
    # Against a direct transcription of the reference formula
    # (ref: model.py:12-31): full-width tables repeated (1,2), rotate_half.
    S, D = 16, 8
    cos, sin = rope_tables(S, D, base=10000.0)
    x = jax.random.normal(jax.random.key(0), (2, S, 3, D), jnp.float32)

    # manual: cos_full/sin_full [S, D]
    theta = 1.0 / (10000.0 ** (np.arange(0, D, 2, dtype=np.float64) / D))
    ang = np.arange(S)[:, None] * theta[None, :]
    cos_full = np.tile(np.cos(ang), (1, 2))
    sin_full = np.tile(np.sin(ang), (1, 2))
    xn = np.asarray(x)
    x1, x2 = xn[..., : D // 2], xn[..., D // 2:]
    rot = np.concatenate([-x2, x1], axis=-1)
    want = xn * cos_full[None, :, None, :] + rot * sin_full[None, :, None, :]

    got = apply_rope(x, cos, sin)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_rope_positions_slice_equivalence():
    # CP shards pass global positions; must equal slicing the full result.
    S, D = 32, 8
    cos, sin = rope_tables(S, D)
    x = jax.random.normal(jax.random.key(1), (1, S, 2, D))
    full = apply_rope(x, cos, sin)
    half = apply_rope(x[:, 16:], cos, sin, positions=jnp.arange(16, 32))
    np.testing.assert_allclose(np.asarray(full[:, 16:]), np.asarray(half),
                               rtol=1e-6, atol=1e-6)


def test_rmsnorm_fp32_stats():
    x = (jax.random.normal(jax.random.key(0), (4, 64)) * 10).astype(jnp.bfloat16)
    w = jnp.full((64,), 2.0, jnp.float32)
    out = rms_norm(x, w, eps=1e-5)
    assert out.dtype == jnp.bfloat16
    xf = np.asarray(x, np.float32)
    want = 2.0 * xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(out, np.float32), want, rtol=0.02, atol=0.02)


def test_repeat_kv():
    x = jnp.arange(2 * 3 * 2 * 4).reshape(2, 3, 2, 4).astype(jnp.float32)
    r = repeat_kv(x, 3)
    assert r.shape == (2, 3, 6, 4)
    # head j of output maps to kv head j // 3 (repeat_interleave semantics)
    np.testing.assert_array_equal(np.asarray(r[:, :, 0]), np.asarray(x[:, :, 0]))
    np.testing.assert_array_equal(np.asarray(r[:, :, 2]), np.asarray(x[:, :, 0]))
    np.testing.assert_array_equal(np.asarray(r[:, :, 3]), np.asarray(x[:, :, 1]))


def test_sdpa_causal_masking():
    # Future tokens must not influence the past: perturb the last token.
    B, S, H, D = 1, 8, 2, 4
    q = jax.random.normal(jax.random.key(0), (B, S, H, D))
    k = jax.random.normal(jax.random.key(1), (B, S, H, D))
    v = jax.random.normal(jax.random.key(2), (B, S, H, D))
    out1 = sdpa_attention(q, k, v, causal=True)
    k2 = k.at[:, -1].add(100.0)
    v2 = v.at[:, -1].add(100.0)
    out2 = sdpa_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]))


def test_sdpa_lse_consistency():
    # merging two K/V halves with LSE must reproduce full attention
    # (the identity the CP ring relies on, ref: context_parallel.py:157-187)
    B, S, H, D = 1, 8, 2, 4
    q = jax.random.normal(jax.random.key(3), (B, S, H, D))
    k = jax.random.normal(jax.random.key(4), (B, S, H, D))
    v = jax.random.normal(jax.random.key(5), (B, S, H, D))
    full = sdpa_attention(q, k, v, causal=False)

    o1, l1 = sdpa_attention(q, k[:, :4], v[:, :4], causal=False, return_lse=True)
    o2, l2 = sdpa_attention(q, k[:, 4:], v[:, 4:], causal=False, return_lse=True)
    lse = np.logaddexp(np.asarray(l1), np.asarray(l2))  # [B, H, S]
    w1 = np.exp(np.asarray(l1) - lse).transpose(0, 2, 1)[..., None]
    w2 = np.exp(np.asarray(l2) - lse).transpose(0, 2, 1)[..., None]
    merged = w1 * np.asarray(o1) + w2 * np.asarray(o2)
    np.testing.assert_allclose(merged, np.asarray(full), rtol=1e-5, atol=1e-5)


def test_init_statistics():
    p = init_params(TINY, jax.random.key(0))
    # embedding ~ N(0,1) (ref: model.py:222)
    emb = np.asarray(p["embedding"])
    assert abs(emb.std() - 1.0) < 0.05
    # linear ~ U(+-sqrt(1/fan_in)) (ref: model.py:110-120)
    qw = np.asarray(p["layers"]["q"])
    bound = (1.0 / TINY.hidden_size) ** 0.5
    assert qw.max() <= bound and qw.min() >= -bound
    assert abs(qw.std() - bound / np.sqrt(3)) < 0.01 * bound
    # norms are ones
    assert (np.asarray(p["final_norm"]) == 1.0).all()


def test_param_count_matches_formula():
    from picotron_tpu.config import num_params
    p = init_params(TINY, jax.random.key(0))
    assert param_count(p) == num_params(TINY)


def test_forward_shapes_and_dtype():
    cfg = ModelConfig()  # bf16 compute
    p = init_params(cfg, jax.random.key(0))
    ids = jnp.zeros((2, 16), jnp.int32)
    logits = forward(p, ids, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.bfloat16


def test_model_is_causal_end_to_end():
    cfg = TINY
    p = init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab_size)
    base = forward(p, ids, cfg)
    ids2 = ids.at[0, -1].set((ids[0, -1] + 1) % cfg.vocab_size)
    pert = forward(p, ids2, cfg)
    np.testing.assert_allclose(np.asarray(base[0, :-1]), np.asarray(pert[0, :-1]),
                               rtol=1e-4, atol=1e-4)


def test_loss_sane_at_init():
    cfg = TINY
    p = init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    tgt = jax.random.randint(jax.random.key(2), (2, 32), 0, cfg.vocab_size)
    loss = loss_fn(p, ids, tgt, cfg)
    # init loss should be near ln(vocab) for random labels... init scheme has
    # N(0,1) embeddings so logits are not tiny; allow a generous band
    assert 0.5 * np.log(cfg.vocab_size) < float(loss) < 4 * np.log(cfg.vocab_size)


def test_training_reduces_loss():
    from picotron_tpu.train_step import init_train_state, make_train_step

    cfg = Config(
        model=ModelConfig(dtype="float32"),
        training=TrainingConfig(learning_rate=1e-3, seq_length=32,
                                micro_batch_size=4,
                                gradient_accumulation_steps=2),
    )
    p = init_params(cfg.model, jax.random.key(0))
    state = init_train_state(cfg, p)
    step = jax.jit(make_train_step(cfg))

    # one fixed batch, overfit it
    key = jax.random.key(42)
    ids = jax.random.randint(key, (2, 4, 33), 0, cfg.model.vocab_size)
    batch = (ids[..., :-1], ids[..., 1:])

    first = None
    for _ in range(20):
        state, loss = step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7, f"loss did not drop: {first} -> {float(loss)}"
    assert int(state.step) == 20


def test_sdpa_fully_masked_rows_no_nan():
    # A ring-CP block where every KV position is in the future of every query:
    # output must be 0 with lse = -inf, never NaN (merge weight is then 0).
    q = jax.random.normal(jax.random.key(0), (1, 2, 2, 4))
    k = jax.random.normal(jax.random.key(1), (1, 2, 2, 4))
    v = jax.random.normal(jax.random.key(2), (1, 2, 2, 4))
    out, lse = sdpa_attention(q, k, v, causal=True,
                              q_positions=jnp.array([0, 1]),
                              kv_positions=jnp.array([4, 5]),
                              return_lse=True)
    assert not np.isnan(np.asarray(out)).any()
    np.testing.assert_array_equal(np.asarray(out), 0.0)
    assert np.isneginf(np.asarray(lse)).all()


def test_sdpa_gqa_internal_expansion():
    # kv_heads < q_heads handled inside sdpa (callers pass unexpanded K/V)
    q = jax.random.normal(jax.random.key(0), (1, 8, 4, 8))
    k = jax.random.normal(jax.random.key(1), (1, 8, 2, 8))
    v = jax.random.normal(jax.random.key(2), (1, 8, 2, 8))
    got = sdpa_attention(q, k, v, causal=True)
    want = sdpa_attention(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_lr_schedules():
    """Warmup + cosine/linear decay shapes; constant stays the reference's
    behavior (ref: train.py:209 bare AdamW)."""
    from picotron_tpu.config import TrainingConfig
    from picotron_tpu.optimizer import make_lr

    t = TrainingConfig(learning_rate=1e-3, total_train_steps=100,
                       lr_schedule="cosine", lr_warmup_steps=10,
                       lr_min_ratio=0.1)
    lr = make_lr(t)
    assert float(lr(0)) == 0.0
    np.testing.assert_allclose(float(lr(10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(lr(100)), 1e-4, rtol=1e-3)  # floor
    assert float(lr(50)) < 1e-3

    t = TrainingConfig(learning_rate=1e-3, total_train_steps=100,
                       lr_schedule="linear", lr_warmup_steps=0,
                       lr_min_ratio=0.5)
    lr = make_lr(t)
    np.testing.assert_allclose(float(lr(0)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(lr(100)), 5e-4, rtol=1e-6)

    t = TrainingConfig(learning_rate=1e-3)
    assert make_lr(t) == 1e-3  # plain constant: no schedule object at all

    with pytest.raises(ValueError, match="lr_schedule"):
        Config(training=TrainingConfig(lr_schedule="step")).validate()


def test_lr_schedule_trains_and_resumes_with_optimizer_step():
    """The schedule reads the optimizer's own step count, so a restored
    state continues the schedule (not restarts warmup)."""
    from picotron_tpu.config import TrainingConfig
    from picotron_tpu.train_step import init_train_state, make_train_step

    cfg = Config(
        model=ModelConfig(dtype="float32"),
        training=TrainingConfig(learning_rate=1e-3, seq_length=32,
                                micro_batch_size=2,
                                gradient_accumulation_steps=1,
                                total_train_steps=10,
                                lr_schedule="cosine", lr_warmup_steps=3),
    )
    p = init_params(cfg.model, jax.random.key(0))
    state = init_train_state(cfg, p)
    step = jax.jit(make_train_step(cfg))
    ids = jax.random.randint(jax.random.key(1), (1, 2, 33), 0,
                             cfg.model.vocab_size)
    batch = (ids[..., :-1], ids[..., 1:])
    p0 = np.asarray(p["embedding"]).copy()
    state, _ = step(state, batch)
    # warmup step 0: lr == 0 -> params untouched (AdamW update scaled by 0)
    np.testing.assert_array_equal(np.asarray(state.params["embedding"]), p0)
    state, _ = step(state, batch)
    assert not np.array_equal(np.asarray(state.params["embedding"]), p0)


def test_qwen2_style_bias_tied_structure_and_training():
    """Qwen2 architecture variants: qkv bias params exist and train; tied
    embeddings mean NO lm_head leaf, logits read the transposed embedding,
    and the embedding receives gradient from both its uses."""
    from picotron_tpu.config import TrainingConfig, resolve_preset
    from picotron_tpu.models.llama import forward, head_weight
    from picotron_tpu.train_step import init_train_state, make_train_step

    cfg = Config(
        model=ModelConfig(dtype="float32",
                          **resolve_preset("debug-tiny-qwen")),
        training=TrainingConfig(learning_rate=1e-3, seq_length=32,
                                micro_batch_size=4,
                                gradient_accumulation_steps=2),
    )
    p = init_params(cfg.model, jax.random.key(0))
    assert "lm_head" not in p
    assert p["layers"]["b_q"].shape == (4, 64)
    np.testing.assert_array_equal(np.asarray(head_weight(p)),
                                  np.asarray(p["embedding"]).T)

    state = init_train_state(cfg, p)
    step = jax.jit(make_train_step(cfg))
    ids = jax.random.randint(jax.random.key(42), (2, 4, 33), 0,
                             cfg.model.vocab_size)
    batch = (ids[..., :-1], ids[..., 1:])
    first = None
    for _ in range(20):
        state, loss = step(state, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.8, (first, float(loss))
    # the bias actually trains (gradient flows through the qkv adds)
    assert float(jnp.abs(state.params["layers"]["b_q"]).max()) > 0

    # forward works and matches head_weight semantics
    logits = forward(state.params, ids[0, :, :-1], cfg.model)
    assert logits.shape == (4, 32, cfg.model.vocab_size)


@pytest.mark.slow
def test_qwen2_style_layouts_match_single_device():
    """Tied+bias model under dp*tp (vocab-sharded tied head: the embedding
    shard transposes into the head shard) and pp (gated last-stage scoring
    reads the promoted embedding) must match the single-device run."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from picotron_tpu.config import DistributedConfig, TrainingConfig, resolve_preset
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step
    from picotron_tpu.train_step import (
        init_train_state, make_train_step as make_single_step,
    )

    # pp2xtp2 exercises the gated last-stage scoring with the tied head;
    # +sp swaps in the no-split CE path (dp2xtp2 pruned r5 — plain
    # tp-sharded tying is a strict subset of both)
    for dist in (dict(pp_size=2, tp_size=2),
                 dict(pp_size=2, tp_size=2, sequence_parallel=True)):
        cfg = Config(
            distributed=DistributedConfig(**dist),
            model=ModelConfig(dtype="float32",
                              **resolve_preset("debug-tiny-qwen")),
            training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                    gradient_accumulation_steps=2,
                                    learning_rate=1e-3, remat=False),
        )
        cfg.validate()
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        step = make_train_step(cfg, menv)
        b = 2 * cfg.distributed.dp_size
        toks = jax.random.randint(jax.random.key(1), (2, b, 33), 0,
                                  cfg.model.vocab_size)
        sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
        batch = (jax.device_put(toks[..., :-1], sh),
                 jax.device_put(toks[..., 1:], sh))
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))

        ref_cfg = Config(model=cfg.model, training=cfg.training)
        params = init_params(ref_cfg.model, jax.random.key(0))
        rs = init_train_state(ref_cfg, params)
        rstep = jax.jit(make_single_step(ref_cfg))
        ref = []
        for _ in range(3):
            rs, loss = rstep(rs, (toks[..., :-1], toks[..., 1:]))
            ref.append(float(loss))
        np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=2e-5,
                                   err_msg=str(dist))


def test_flops_accounting_counts_tied_head():
    """The LM-head matmul executes whether or not the weight is tied, so
    flops_per_token must be identical for tied and untied variants of the
    same architecture (else tied models understate MFU)."""
    import dataclasses

    from picotron_tpu.config import num_params, resolve_preset
    from picotron_tpu.utils import flops_per_token

    tied = ModelConfig(**resolve_preset("debug-tiny-qwen"))
    untied = dataclasses.replace(tied, tie_word_embeddings=False)
    assert flops_per_token(tied, 128) == flops_per_token(untied, 128)
    # while the PARAM count differs by exactly the head
    assert (num_params(untied) - num_params(tied)
            == tied.hidden_size * tied.vocab_size)


def test_llama3_rope_scaling():
    """llama3-type frequency banding: high-frequency (short-wavelength)
    components untouched, low-frequency divided by `factor`, smooth
    interpolation between; the scaled tables actually reach the model."""
    import dataclasses

    from picotron_tpu.config import resolve_preset
    from picotron_tpu.models.llama import model_rope_tables
    from picotron_tpu.ops.rope import llama3_scale_freqs, rope_tables

    inv = 1.0 / (10000.0 ** (np.arange(0, 64, 2) / 64))
    scaled = np.asarray(llama3_scale_freqs(
        jnp.asarray(inv, jnp.float32), factor=8.0,
        original_max_position=8192))
    wavelen = 2 * np.pi / inv
    hi = wavelen < 8192 / 4.0   # short wavelengths: unchanged
    lo = wavelen > 8192 / 1.0   # long wavelengths: / factor
    np.testing.assert_allclose(scaled[hi], inv[hi], rtol=1e-6)
    np.testing.assert_allclose(scaled[lo], inv[lo] / 8.0, rtol=1e-6)
    mid = ~(hi | lo)
    assert np.all(scaled[mid] < inv[mid]) and np.all(
        scaled[mid] > inv[mid] / 8.0)

    # presets carry the scaling and the model helper applies it
    cfg = ModelConfig(**resolve_preset("Llama-3.2-1B"))
    assert cfg.rope_scaling_dict["factor"] == 32.0
    assert cfg.tie_word_embeddings
    small = dataclasses.replace(cfg, max_position_embeddings=64)
    cos_s, _ = model_rope_tables(small)
    cos_u, _ = rope_tables(64, cfg.head_dim, cfg.rope_theta)
    assert not np.allclose(np.asarray(cos_s), np.asarray(cos_u))

    with pytest.raises(ValueError, match="rope_scaling"):
        rope_tables(16, 8, rope_scaling={"rope_type": "yarn"})


def test_rope_scaled_model_trains_and_decodes():
    """A tiny model with llama3 rope scaling trains (loss drops) and its
    KV-cache decode matches the full forward — the scaling reaches every
    path through model_rope_tables."""
    from picotron_tpu.config import TrainingConfig
    from picotron_tpu.models.llama import forward
    from picotron_tpu.train_step import init_train_state, make_train_step
    from test_generate import teacher_forced_cache_logits

    cfg_m = ModelConfig(
        dtype="float32", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rope_scaling={"rope_type": "llama3", "factor": 4.0,
                      "original_max_position_embeddings": 16})
    cfg = Config(model=cfg_m,
                 training=TrainingConfig(learning_rate=1e-3, seq_length=32,
                                         micro_batch_size=4,
                                         gradient_accumulation_steps=1))
    p = init_params(cfg.model, jax.random.key(0))
    state = init_train_state(cfg, p)
    step = jax.jit(make_train_step(cfg))
    ids = jax.random.randint(jax.random.key(1), (1, 4, 33), 0, 256)
    batch = (ids[..., :-1], ids[..., 1:])
    first = None
    for _ in range(15):
        state, loss = step(state, batch)
        first = first if first is not None else float(loss)
    assert float(loss) < first

    toks = jax.random.randint(jax.random.key(2), (2, 9), 0, 256)
    want = forward(p, cfg=cfg_m, input_ids=toks).astype(jnp.float32)
    got = teacher_forced_cache_logits(p, cfg_m, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gelu_hidden_act_changes_mlp_and_matches_reference():
    """hidden_act='gelu' (GeGLU, the Gemma-style gated MLP): the gate
    branch must use tanh-approx gelu instead of silu, in the dense MLP,
    the MoE expert bank, and by construction the decode path (all three
    route through models.llama.mlp_act)."""
    from picotron_tpu.config import resolve_preset
    from picotron_tpu.models.llama import forward, init_params, mlp_act

    base = dict(resolve_preset("debug-tiny"), dtype="float32")
    cfg_s = ModelConfig(**base)
    cfg_g = ModelConfig(**{**base, "hidden_act": "gelu"})
    params = init_params(cfg_s, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, 256)

    out_s = forward(params, ids, cfg_s)
    out_g = forward(params, ids, cfg_g)
    assert not np.allclose(np.asarray(out_s), np.asarray(out_g))

    # "gelu" is the EXACT erf GELU (transformers' ACT2FN "gelu");
    # "gelu_tanh" is the tanh approximation (gelu_pytorch_tanh/gelu_new)
    x = jnp.linspace(-3, 3, 64)
    np.testing.assert_allclose(
        np.asarray(mlp_act(cfg_g)(x)),
        np.asarray(jax.nn.gelu(x, approximate=False)), rtol=1e-7)
    cfg_gt = ModelConfig(**{**base, "hidden_act": "gelu_tanh"})
    np.testing.assert_allclose(
        np.asarray(mlp_act(cfg_gt)(x)),
        np.asarray(jax.nn.gelu(x, approximate=True)), rtol=1e-7)
    from picotron_tpu.config import model_config_from_hf_json
    hf_base = {"vocab_size": 8, "hidden_size": 8, "intermediate_size": 8,
               "num_hidden_layers": 1, "num_attention_heads": 2}
    assert model_config_from_hf_json(
        {**hf_base, "hidden_act": "gelu"})["hidden_act"] == "gelu"
    assert model_config_from_hf_json(
        {**hf_base, "hidden_act": "gelu_pytorch_tanh"})["hidden_act"] \
        == "gelu_tanh"

    with pytest.raises(ValueError, match="hidden_act"):
        ModelConfig(**{**base, "hidden_act": "relu"}).validate()

    # MoE bank honors it too
    from picotron_tpu.ops.moe import _swiglu_experts
    slots = jax.random.normal(jax.random.key(2), (2, 8, 16))
    wg = jax.random.normal(jax.random.key(3), (2, 16, 32)) * 0.1
    wu = jax.random.normal(jax.random.key(4), (2, 16, 32)) * 0.1
    wd = jax.random.normal(jax.random.key(5), (2, 32, 16)) * 0.1
    o_s = _swiglu_experts(slots, wg, wu, wd)
    o_g = _swiglu_experts(slots, wg, wu, wd,
                          act=mlp_act(cfg_g))
    assert not np.allclose(np.asarray(o_s), np.asarray(o_g))
