"""TP strategy + deferred-sync tests — the PR's acceptance bar in test
form: every strategy x sync-mode combo must (a) train to the SAME loss as
its megatron-sync twin at the SAME layout (deferred is a pure
rescheduling; 2d reassociates the exit psum identically), (b) produce the
same fp32 gradients from the fused engine as from dense AD, (c) lower the
collectives the schedule promises — with mutation tests proving the audit
catches their deletion — and (d) be enumerable/round-trippable by the
planner with a cost model that prices deferred's exposed-comm win.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from picotron_tpu.analysis import collect_sites, lower_train_step
from picotron_tpu.analysis.collectives import (
    audit_collectives, parse_collectives,
)
from picotron_tpu.analysis.cost_model import (
    CostModel, GENERATIONS, choose_tp_strategy, feasible_tp_meshes,
    price_tp_strategy, tp_strategy_table,
)
from picotron_tpu.analysis.dataflow import (
    attribute_collectives, intended_rule, root_paths,
)
from picotron_tpu.analysis.planner import candidate_configs, plan
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig,
    config_from_dict, parse_tp_strategy, resolved_tp_mesh,
    resolved_tp_strategy,
)
from tests.test_fused_bwd import assert_grads_match
from tests.test_parallel import run_parallel, tiny_cfg
from tests.test_tools import load_tool


# ---------------------------------------------------------------------------
# parse / resolve units
# ---------------------------------------------------------------------------


def test_parse_presets_and_subset_spec():
    assert parse_tp_strategy("megatron") == {
        "qkv": "col", "o": "row", "up": "col", "down": "row", "head": "col"}
    assert parse_tp_strategy("2d")["down"] == "2d"
    assert parse_tp_strategy("adaptive") is None
    # subset spec: named classes override, the rest stay megatron
    mix = parse_tp_strategy("qkv=2d,o=2d")
    assert mix["qkv"] == "2d" and mix["o"] == "2d"
    assert mix["up"] == "col" and mix["down"] == "row"


@pytest.mark.parametrize("spec,frag", [
    ("bogus", "preset"),
    ("qkv=diag", "col/row/2d"),
    ("attn=col", "unknown layer class"),
    ("qkv=2d", "legal"),          # 2d entry feeding a row exit
    ("head=row", "head"),
])
def test_parse_rejects_malformed_specs(spec, frag):
    with pytest.raises(ValueError, match=frag):
        parse_tp_strategy(spec)


def test_resolved_tp_mesh_explicit_wins_else_most_square():
    def cfg(tp, mesh="", kv=4):
        c = Config(
            distributed=DistributedConfig(dp_size=8 // tp, tp_size=tp,
                                          tp_strategy="2d", tp_mesh=mesh),
            model=ModelConfig(num_attention_heads=8,
                              num_key_value_heads=kv),
            training=TrainingConfig(seq_length=64))
        c.validate()
        return c

    assert resolved_tp_mesh(cfg(4, mesh="4x1")) == (4, 1)
    assert resolved_tp_mesh(cfg(4)) == (2, 2)
    # kv=2 at tp=4... kv must divide tp, so use tp=2: most-square of 2 is
    # (2,1) — the tie toward smaller tp_y (less replicated row compute)
    assert resolved_tp_mesh(cfg(2)) == (2, 1)


def test_resolved_strategy_tp1_is_megatron_and_adaptive_is_deterministic():
    c = Config(model=ModelConfig(), training=TrainingConfig(seq_length=64))
    c.validate()
    assert resolved_tp_strategy(c)["qkv"] == "col"
    a = tiny_cfg(dp_size=2, tp_size=4, tp_strategy="adaptive",
                 tp_mesh="2x2")
    r1 = resolved_tp_strategy(a, generation="v5e")
    assert r1 == resolved_tp_strategy(a, generation="v5e")
    assert r1 == choose_tp_strategy(a, generation="v5e")
    assert set(r1) == {"qkv", "o", "up", "down", "head"}


@pytest.mark.parametrize("dist,frag", [
    (dict(tp_size=2, tp_strategy="2d", pp_size=2), "pp_size > 1"),
    (dict(tp_size=2, tp_strategy="2d", cp_size=2), "cp_size > 1"),
    (dict(tp_size=2, tp_strategy="2d", sequence_parallel=True),
     "sequence_parallel"),
    (dict(tp_size=2, tp_strategy="row", tp_sync="deferred"),
     "tp_strategy='megatron'"),
    (dict(tp_size=2, tp_sync="deferred", pp_size=2), "pp_size=1"),
    (dict(tp_size=1, tp_sync="deferred"), "tp_size > 1"),
    (dict(tp_size=4, tp_strategy="2d", tp_mesh="2x4"), "factor the tp"),
    (dict(tp_size=2, tp_strategy="megatron", tp_mesh="2x1"),
     "only applies"),
])
def test_validation_gates_illegal_combos(dist, frag):
    with pytest.raises(ValueError, match=frag):
        tiny_cfg(**dist).validate()


# ---------------------------------------------------------------------------
# loss pins: strategy/sync runs == megatron-sync twin at the SAME layout
# ---------------------------------------------------------------------------

_STRATEGY_KNOBS = ("tp_strategy", "tp_sync", "tp_mesh")


def assert_loss_pinned_to_sync_twin(**dist):
    losses, _ = run_parallel(tiny_cfg(**dict(dist)))
    twin = {k: v for k, v in dist.items() if k not in _STRATEGY_KNOBS}
    ref, _ = run_parallel(tiny_cfg(**twin))
    np.testing.assert_allclose(losses, ref, rtol=2e-4, atol=2e-5)


def test_loss_pin_deferred_tp2():
    assert_loss_pinned_to_sync_twin(dp_size=2, tp_size=2,
                                    tp_sync="deferred")


def test_loss_pin_2d_tp4():
    assert_loss_pinned_to_sync_twin(dp_size=2, tp_size=4,
                                    tp_strategy="2d", tp_mesh="2x2")


@pytest.mark.slow
def test_loss_pin_deferred_composes_with_sp_and_cp():
    assert_loss_pinned_to_sync_twin(tp_size=2, cp_size=2,
                                    sequence_parallel=True,
                                    tp_sync="deferred")


@pytest.mark.slow
def test_loss_pin_row_tp2():
    """The row strategy flips q/o/up/down sharding, and sharded init draws
    row-sharded leaves differently than col-sharded ones — so unlike
    deferred/2d (megatron param specs, bit-identical init) a plain twin
    comparison would train two different random inits. Transplant the
    megatron twin's initial params into the row run and pin from there."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, \
        make_train_step
    from tests.test_parallel import global_batch

    cfg_meg = tiny_cfg(dp_size=2, tp_size=2)
    cfg_row = tiny_cfg(dp_size=2, tp_size=2, tp_strategy="row")

    def run(cfg, params_np=None, steps=3):
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        if params_np is not None:
            params = jax.tree.map(
                lambda v, a: jax.device_put(v, a.sharding),
                params_np, state.params)
            state = state._replace(params=params)
        step = make_train_step(cfg, menv)
        sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
        ids, tgt = global_batch(cfg)
        batch = (jax.device_put(ids, sh), jax.device_put(tgt, sh))
        losses = []
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses, jax.tree.map(np.asarray, state.params)

    meg_init = jax.tree.map(
        np.asarray,
        init_sharded_state(cfg_meg, MeshEnv.from_config(cfg_meg),
                           jax.random.key(0)).params)
    row_losses, _ = run(cfg_row, params_np=meg_init)
    ref_losses, _ = run(cfg_meg)
    np.testing.assert_allclose(row_losses, ref_losses, rtol=2e-4,
                               atol=2e-5)


@pytest.mark.slow
def test_loss_pin_adaptive_tp4_and_2d_meshes_match():
    assert_loss_pinned_to_sync_twin(dp_size=2, tp_size=4,
                                    tp_strategy="adaptive", tp_mesh="2x2")
    # every feasible factorization reassociates the same psum: bit-level
    # agreement between meshes is not promised, loss-level parity is
    for mesh in ("4x1", "1x4"):
        assert_loss_pinned_to_sync_twin(dp_size=2, tp_size=4,
                                        tp_strategy="2d", tp_mesh=mesh)


# ---------------------------------------------------------------------------
# fused-engine fp32 gradient parity per strategy x sync-mode
# ---------------------------------------------------------------------------


def test_grads_parity_deferred_sync():
    # deferred: the fused segment VJPs replay the hoisted-gather schedule
    # (pre-norm AG, block-exit RS) instead of the megatron f/g pair
    assert_grads_match(dk={"dp_size": 2, "tp_size": 2,
                           "tp_sync": "deferred"})


def test_grads_parity_2d_tp4():
    # 2d: subgroup AG transposes to subgroup RS, the tp_x psum transposes
    # to identity — the manual VJPs must mirror both
    assert_grads_match(dk={"dp_size": 2, "tp_size": 4,
                           "tp_strategy": "2d", "tp_mesh": "2x2"})


@pytest.mark.slow
def test_grads_parity_row_first():
    assert_grads_match(dk={"dp_size": 2, "tp_size": 2,
                           "tp_strategy": "row"})


@pytest.mark.slow
def test_grads_parity_deferred_with_sequence_parallel():
    assert_grads_match(dk={"dp_size": 2, "tp_size": 2,
                           "sequence_parallel": True,
                           "tp_sync": "deferred"})


@pytest.mark.slow
def test_grads_parity_mixed_pair_spec():
    # attention pair 2d, mlp pair megatron — the per-class threading, not
    # just the presets
    assert_grads_match(dk={"dp_size": 2, "tp_size": 4,
                           "tp_strategy": "qkv=2d,o=2d",
                           "tp_mesh": "2x2"})


# ---------------------------------------------------------------------------
# collective presence + mutation (analysis/collectives.py rules)
# ---------------------------------------------------------------------------


def _lowcfg(preset):
    sc = load_tool("shardcheck")
    cfg = sc.preset_config(preset)
    return cfg, lower_train_step(cfg)


@pytest.fixture(scope="module")
def deferred_low():
    return _lowcfg("tiny-tp-deferred")


@pytest.fixture(scope="module")
def tp2d_low():
    return _lowcfg("tiny-tp2d")


def test_deferred_audit_green_with_rs_ag_pair(deferred_low):
    cfg, low = deferred_low
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    assert rep.info["collectives"]["reduce_scatter"] > 0
    assert rep.info["collectives"]["all_gather"] > 0
    ops = parse_collectives(low.text)
    tp = cfg.distributed.tp_size
    assert any(o.kind == "reduce_scatter" and o.group_size == tp
               for o in ops)
    assert any(o.kind == "all_gather" and o.group_size == tp for o in ops)


def test_deferred_audit_flags_deleted_reduce_scatter(deferred_low):
    cfg, low = deferred_low
    mutated = low.text.replace("stablehlo.reduce_scatter",
                               "stablehlo.xx_gone")
    rep = audit_collectives(cfg, text=mutated, state=low.state)
    assert not rep.ok()
    assert any("deferred" in f.message and "reduce-scatter" in f.message
               for f in rep.errors()), rep.render()


def test_deferred_audit_flags_deleted_hoisted_gather(deferred_low):
    cfg, low = deferred_low
    mutated = low.text.replace("stablehlo.all_gather", "stablehlo.xx_gone")
    rep = audit_collectives(cfg, text=mutated, state=low.state)
    assert not rep.ok()
    assert any("tp_sync=deferred" in f.message and "all-gather"
               in f.message for f in rep.errors()), rep.render()


def test_2d_audit_green_with_subgroup_collectives(tp2d_low):
    cfg, low = tp2d_low
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    tp_x, tp_y = resolved_tp_mesh(cfg)
    ops = parse_collectives(low.text)
    # the inner-subgroup feature gather and the outer-subgroup psum are
    # both PROPER subgroups of tp — the audit keys on their group sizes
    assert any(o.kind == "all_gather" and o.group_size == tp_y
               for o in ops)
    assert any(o.kind == "all_reduce" and o.group_size == tp_x
               for o in ops)


def test_2d_audit_flags_deleted_subgroup_gather(tp2d_low):
    cfg, low = tp2d_low
    mutated = low.text.replace("stablehlo.all_gather", "stablehlo.xx_gone")
    rep = audit_collectives(cfg, text=mutated, state=low.state)
    assert not rep.ok()
    assert any("inner-subgroup" in f.message for f in rep.errors()), \
        rep.render()


def test_row_audit_flags_deleted_entry_psum():
    cfg = tiny_cfg(dp_size=2, tp_size=2, tp_strategy="row")
    low = lower_train_step(cfg)
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    mutated = low.text.replace("stablehlo.all_reduce", "stablehlo.xx_gone")
    bad = audit_collectives(cfg, text=mutated, state=low.state)
    assert any("row-first" in f.message for f in bad.errors()), bad.render()


# ---------------------------------------------------------------------------
# shardflow provenance: 0 implicit ops, every site intended
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("preset", ["tiny-tp-deferred",
                                    "tiny-tp-deferred-fused", "tiny-tp2d"])
def test_provenance_zero_implicit_on_strategy_presets(preset):
    cfg, low = _lowcfg(preset)
    sites = collect_sites(low.jaxpr, root_paths(low.state, low.batch))
    ops = [o for o in parse_collectives(low.text) if o.effective]
    attributed, implicit = attribute_collectives(cfg, sites, ops)
    assert ops and implicit == [], [op.line for op in implicit]
    assert len(attributed) == len(ops)
    unexplained = [s.describe() for _, s in attributed
                   if intended_rule(cfg, s) is None]
    assert unexplained == []


# ---------------------------------------------------------------------------
# planner: strategy x sync-mode as free axes + overrides round-trip
# ---------------------------------------------------------------------------


def strat_base(ga=8):
    cfg = Config(
        distributed=DistributedConfig(),
        # 8 q / 4 kv heads so tp=4 (and its 2x2 mesh) is enumerable
        model=ModelConfig(num_attention_heads=8, num_key_value_heads=4),
        training=TrainingConfig(seq_length=64, micro_batch_size=1,
                                gradient_accumulation_steps=ga),
    )
    cfg.validate()
    return cfg


def test_planner_enumerates_strategy_and_sync_axes():
    cands = candidate_configs(strat_base(), 8)
    axes = {(c.distributed.tp_size, c.distributed.tp_strategy,
             c.distributed.tp_sync, c.distributed.tp_mesh) for c in cands}
    assert (2, "megatron", "deferred", "") in axes
    assert (4, "2d", "sync", "2x2") in axes
    # row-first is dominated (entry psum over the full projection width
    # + exit feature gather) — deliberately not enumerated
    assert all(c.distributed.tp_strategy != "row" for c in cands)
    # tp=1 points never carry strategy knobs
    assert all(c.distributed.tp_sync == "sync" for c in cands
               if c.distributed.tp_size == 1)


def test_strategy_overrides_line_round_trips():
    pts = plan(strat_base(), 8, CostModel("v5e"))
    picked = [next(p for p in pts if p.cfg.distributed.tp_sync ==
                   "deferred"),
              next(p for p in pts if p.cfg.distributed.tp_strategy ==
                   "2d")]
    for point in picked:
        line = point.overrides_line()
        raw = {"model": {"num_attention_heads": 8,
                         "num_key_value_heads": 4, "vocab_size": 256,
                         "hidden_size": 64, "intermediate_size": 128,
                         "num_hidden_layers": 4},
               "training": {"seq_length": 64, "micro_batch_size": 1,
                            "gradient_accumulation_steps": 8}}
        for ov in line.split()[1:]:
            dotted, _, val = ov.partition("=")
            node = raw
            *path, key = dotted.split(".")
            for part in path:
                node = node.setdefault(part, {})
            try:
                node[key] = json.loads(val)
            except ValueError:
                node[key] = val
        cfg = config_from_dict(raw)  # validates
        d = point.cfg.distributed
        assert cfg.distributed.tp_strategy == d.tp_strategy
        assert cfg.distributed.tp_sync == d.tp_sync
        assert cfg.distributed.tp_mesh == d.tp_mesh
        assert cfg.distributed.tp_size == d.tp_size


# ---------------------------------------------------------------------------
# cost model: deferred's exposed-comm win + the strategy table
# ---------------------------------------------------------------------------


def smol_tp_cfg(tp=4):
    from picotron_tpu.config import resolve_preset

    cfg = Config(
        distributed=DistributedConfig(dp_size=2, tp_size=tp),
        model=ModelConfig(name="SmolLM-1.7B",
                          **resolve_preset("SmolLM-1.7B")),
        training=TrainingConfig(seq_length=2048,
                                gradient_accumulation_steps=8),
    )
    cfg.validate()
    return cfg


@pytest.mark.parametrize("gen", GENERATIONS)
def test_deferred_exposed_comm_strictly_lower_at_tp4(gen):
    """The PR's headline prediction: at tp >= 4 the deferred schedule's
    exposed TP comm ((1 + expose_deferred) * V(n-1)/n, the AG half
    overlapping the next block's entry) beats the synchronous psum's
    2V(n-1)/n — on EVERY ICI generation."""
    model = CostModel(gen)
    cfg = smol_tp_cfg(tp=4)
    sync = model.predict(cfg)
    deferred = price_tp_strategy(model, cfg, "megatron", sync="deferred")
    assert deferred.exposed_comm_s < sync.exposed_comm_s
    assert deferred.total_s < sync.total_s


def test_price_tp_strategy_is_a_pure_probe():
    model = CostModel("v5e")
    cfg = smol_tp_cfg()
    before = dataclasses.asdict(cfg.distributed)
    price_tp_strategy(model, cfg, "row")
    price_tp_strategy(model, cfg, "2d", tp_mesh="2x2")
    assert dataclasses.asdict(cfg.distributed) == before


def test_feasible_tp_meshes_respect_head_divisibility():
    meshes = feasible_tp_meshes(smol_tp_cfg(tp=4))
    assert all(x > 1 and y > 1 and x * y == 4 for x, y in meshes)
    # debug-tiny-dims model with kv=4: tp_x must divide kv
    c = strat_base()
    c = dataclasses.replace(c, distributed=dataclasses.replace(
        c.distributed, tp_size=8, dp_size=1))
    assert all(x <= 4 for x, y in feasible_tp_meshes(c))


def test_tp_strategy_table_rows_and_divisibility_skip():
    model = CostModel("v5e")
    rows = tp_strategy_table(model, strat_base(), tp_degrees=(2, 4, 16))
    # kv=4: tp=16 is unshardable and must be skipped, not crash
    assert [r["tp"] for r in rows] == [2, 4]
    for r in rows:
        assert {"megatron_ms", "deferred_ms", "row_ms", "adaptive",
                "winner"} <= set(r)
        assert r["megatron_exposed_delta_ms"] == 0.0
    tp4 = rows[-1]
    assert tp4["mesh_factorization"] == "2x2"
    assert tp4["2d_ms"] > 0


# ---------------------------------------------------------------------------
# CLI smokes: layout_planner table, telemetry split, create_config flags
# ---------------------------------------------------------------------------


def test_cli_tp_strategy_table(capsys):
    lp = load_tool("layout_planner")
    rc = lp.main(["--model", "SmolLM-1.7B", "--seq", "2048",
                  "--tp-strategy-table", "--tp-degrees", "4", "--json"])
    assert rc == 0
    out = [json.loads(l) for l in
           capsys.readouterr().out.strip().splitlines()]
    assert {o["generation"] for o in out} == set(GENERATIONS)
    for o in out:
        (row,) = o["rows"]
        assert row["deferred_exposed_delta_ms"] < 0
        assert row["winner"] == "deferred"


def test_cli_telemetry_comm_row_splits_tp_exposure(tmp_path, capsys):
    cc = load_tool("create_config")
    # SmolLM at seq 2048, not debug-tiny: the split's direction is only
    # meaningful in the bandwidth-dominated regime — at tiny volumes the
    # per-collective latency term dominates and deferred's RS+AG pair
    # legitimately prices above the single sync psum
    args = cc.build_parser().parse_args(
        ["--exp-name", "t", "--out-dir", str(tmp_path), "--model",
         "SmolLM-1.7B", "--dp", "2", "--tp", "4",
         "--tp-sync", "deferred", "--seq-len", "2048", "--use-cpu"])
    cfg_path = cc.create_single_config(args)
    capsys.readouterr()
    written = json.load(open(cfg_path))
    assert written["distributed"]["tp_sync"] == "deferred"

    tele = tmp_path / "telemetry.jsonl"
    tele.write_text(json.dumps(
        {"kind": "phase", "phase": "step", "step": 0,
         "category": "compute", "secs": 0.5, "ts": 1.0}) + "\n")
    tr = load_tool("telemetry_report")
    rc = tr.main([str(tele), "--config", cfg_path, "--json"])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    cm = s["comm"]
    assert cm["predicted_tp_comm_exposed_ms"] > 0
    assert cm["predicted_tp_comm_overlapped_ms"] > 0
    # sync twin: the SAME traffic, all exposed — the split must show the
    # deferred schedule moving time across, not traffic appearing
    written["distributed"]["tp_sync"] = "sync"
    sync_path = tmp_path / "sync.json"
    sync_path.write_text(json.dumps(written))
    rc = tr.main([str(tele), "--config", str(sync_path), "--json"])
    assert rc == 0
    cm_sync = json.loads(capsys.readouterr().out)["comm"]
    assert (cm["predicted_tp_comm_exposed_ms"]
            < cm_sync["predicted_tp_comm_exposed_ms"])
