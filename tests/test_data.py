"""Dataloader tests (the reference's test_dataloader.py checks the CP split
against an inline reference implementation; here the cp/dp splits are
shardings, so we check shapes, shard contents, determinism, and the epoch
wraparound — including the wraparound case the reference left commented out,
ref: tests/test_dataloader.py:180-212)."""

import jax
import numpy as np
import pytest

from picotron_tpu.config import Config, DistributedConfig, ModelConfig, TrainingConfig
from picotron_tpu.data import MicroBatchDataLoader, SyntheticSource, tokenize_and_chunk
from picotron_tpu.mesh import MeshEnv


def make_cfg(**kw):
    dist = kw.pop("dist", {})
    return Config(
        distributed=DistributedConfig(**dist),
        model=ModelConfig(),
        training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                gradient_accumulation_steps=2, **kw),
    )


def test_shapes_and_batch_math():
    cfg = make_cfg(dist=dict(dp_size=2, cp_size=2, tp_size=2,
                             cp_layout="contiguous"))
    menv = MeshEnv.from_config(cfg)
    dl = MicroBatchDataLoader(cfg, menv)
    assert dl.global_batch_size == 2 * 2 * 2  # mbs * grad_acc * dp (ref: data.py:17)
    ids, tgt = next(dl)
    # [grad_acc, mbs * dp, seq]
    assert ids.shape == (2, 4, 32)
    assert tgt.shape == (2, 4, 32)
    # target is input shifted by one
    np.testing.assert_array_equal(np.asarray(ids)[..., 1:],
                                  np.asarray(tgt)[..., :-1])


def test_zigzag_layout_is_consistent_permutation():
    """Zigzag reorders the sequence axis; un-permuting must recover the
    contiguous stream with its shift-by-one target relation intact."""
    from picotron_tpu.data import cp_sequence_permutation

    cfg = make_cfg(dist=dict(dp_size=2, cp_size=2, tp_size=2))
    assert cfg.distributed.cp_layout == "zigzag"  # the default
    menv = MeshEnv.from_config(cfg)
    dl = MicroBatchDataLoader(cfg, menv)
    ids, tgt = next(dl)
    perm = cp_sequence_permutation(cfg)
    inv = np.argsort(perm)
    ids_lin = np.asarray(ids)[..., inv]
    tgt_lin = np.asarray(tgt)[..., inv]
    np.testing.assert_array_equal(ids_lin[..., 1:], tgt_lin[..., :-1])
    # each cp shard holds one early chunk and one late chunk
    s, cp = cfg.training.seq_length, cfg.distributed.cp_size
    half = s // (2 * cp)
    shard0 = perm[: s // cp]
    np.testing.assert_array_equal(shard0[:half], np.arange(half))
    np.testing.assert_array_equal(
        shard0[half:], np.arange(s - half, s))


def test_sharding_matches_mesh():
    cfg = make_cfg(dist=dict(dp_size=2, cp_size=2, tp_size=2))
    menv = MeshEnv.from_config(cfg)
    dl = MicroBatchDataLoader(cfg, menv)
    ids, _ = next(dl)
    # dp shards the batch dim, cp the sequence dim — the reference's
    # DistributedSampler-by-dp + collate cp slice (ref: data.py:40-45,102-116)
    shard_shapes = {tuple(s.data.shape) for s in ids.addressable_shards}
    assert shard_shapes == {(2, 2, 16)}
    # the cp=0 shard of dp=0 holds the first half of the sequence
    full = np.asarray(ids)
    for shard in ids.addressable_shards:
        idx = shard.index
        np.testing.assert_array_equal(shard.data, full[idx])


def test_deterministic_and_infinite():
    cfg = make_cfg(num_samples=16)  # 16 blocks; one step consumes 4
    menv = MeshEnv.from_config(cfg)
    dl1 = MicroBatchDataLoader(cfg, menv)
    dl2 = MicroBatchDataLoader(cfg, menv)
    a = np.asarray(next(dl1)[0])
    b = np.asarray(next(dl2)[0])
    np.testing.assert_array_equal(a, b)  # same seed -> same stream
    # 16 blocks / 4 per step = 4 steps per epoch; step 5 wraps
    for _ in range(4):
        next(dl1)
    assert dl1.epoch == 1  # wrapped around without raising
    wrapped = np.asarray(next(dl1)[0])
    assert wrapped.shape == a.shape


def test_too_small_dataset_raises():
    cfg = make_cfg(num_samples=3)  # < 4 rows per step
    menv = MeshEnv.from_config(cfg)
    with pytest.raises(ValueError, match="blocks"):
        MicroBatchDataLoader(cfg, menv)


def _rows(batch):
    """Flatten one (ids, tgt) batch to its global sample rows, layout-
    free: [ga, mbs*dp*ep, seq] -> [gbs, seq] in C order — the same order
    _assemble_next consumed the source, whatever the factorization."""
    ids = np.asarray(batch[0])
    return ids.reshape(-1, ids.shape[-1])


def _layout_cfg(dp, mbs, ga, num_samples=16):
    return Config(
        distributed=DistributedConfig(dp_size=dp),
        model=ModelConfig(),
        training=TrainingConfig(seq_length=32, micro_batch_size=mbs,
                                gradient_accumulation_steps=ga,
                                num_samples=num_samples),
    )


def test_cursor_is_layout_independent_at_constant_global_batch():
    """The elastic-resize cursor invariant (resilience/elastic.py): at
    constant global batch the (epoch, cursor) position is process-count
    independent, so a dp=2 run's state carries verbatim into dp=1 and
    dp=4 layouts. Pinned token-exactly: a 2+2+2-step N->M->N trace
    through three factorizations of gbs=4 must reproduce the never-
    resized stream sample for sample — none replayed, none skipped —
    including across an epoch wrap (16 blocks / 4 per step = 4 steps
    per epoch, so the trace wraps inside the dp=4 leg)."""
    layouts = [(2, 2, 1), (1, 2, 2), (4, 1, 1)]  # (dp, mbs, ga), gbs=4

    cfg0 = _layout_cfg(*layouts[0])
    dl = MicroBatchDataLoader(cfg0, MeshEnv.from_config(cfg0))
    pure = [_rows(next(dl)) for _ in range(6)]

    traced, state = [], None
    for dp, mbs, ga in layouts:
        cfg = _layout_cfg(dp, mbs, ga)
        assert cfg.global_batch_size == cfg0.global_batch_size
        leg = MicroBatchDataLoader(cfg, MeshEnv.from_config(cfg))
        if state is not None:
            leg.set_state(state)
        traced += [_rows(next(leg)) for _ in range(2)]
        state = leg.state
    assert state == {"epoch": 1, "cursor": 8}  # wrapped, 2 steps in
    for step, (want, got) in enumerate(zip(pure, traced), start=1):
        np.testing.assert_array_equal(want, got,
                                      err_msg=f"step {step} diverged")


def test_reset_repositions_across_changed_layout():
    """reset() (the mid-run form of set_state, used by rollback) honors a
    cursor recorded under a different gbs factorization: a dp=1 loader
    reset to a dp=2 run's position continues the dp=2 stream exactly."""
    cfg_a = _layout_cfg(2, 2, 1)
    dl_a = MicroBatchDataLoader(cfg_a, MeshEnv.from_config(cfg_a))
    for _ in range(2):
        next(dl_a)
    mark = dl_a.state
    want = [_rows(next(dl_a)) for _ in range(2)]

    cfg_b = _layout_cfg(1, 2, 2)
    dl_b = MicroBatchDataLoader(cfg_b, MeshEnv.from_config(cfg_b))
    next(dl_b)  # consume from the start, then jump
    dl_b.reset(mark)
    got = [_rows(next(dl_b)) for _ in range(2)]
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert dl_b.state == dl_a.state


def test_tokenize_and_chunk():
    datasets = pytest.importorskip("datasets")

    class WordTokenizer:
        """Minimal tokenizer: one token per character."""
        def __call__(self, texts):
            return {"input_ids": [[ord(c) % 97 for c in t] for t in texts]}

    raw = datasets.Dataset.from_dict({"text": ["abcdefgh" * 4, "xyz" * 7]})
    chunked = tokenize_and_chunk(raw, WordTokenizer(), seq_length=9)
    # 32 + 21 = 53 tokens -> 5 blocks of 10
    assert len(chunked) == 5
    assert all(len(r["input_ids"]) == 10 for r in chunked)
    # concatenation crosses document boundaries (ref: data.py:70-90 packs
    # documents back-to-back)
    flat = [t for r in chunked for t in r["input_ids"]]
    want = [ord(c) % 97 for c in "abcdefgh" * 4 + "xyz" * 7][:50]
    assert flat == want
