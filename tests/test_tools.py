"""Experiment-tooling tests: config generator round-trip, metrics harvester
parsing (the log-line format is a de-facto API between utils.training_log_line
and tools/extract_metrics.py — same contract the reference has between
train.py prints and its extract_metrics regexes), and the job scheduler's
status state machine."""

import importlib.util
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_create_config_roundtrip(tmp_path):
    cc = load_tool("create_config")
    args = cc.build_parser().parse_args([
        "--exp-name", "dp2_tp2", "--out-dir", str(tmp_path),
        "--model", "debug-tiny", "--dp", "2", "--tp", "2",
        "--seq-len", "64", "--mbs", "2", "--grad-acc", "3",
    ])
    path = cc.create_single_config(args)
    from picotron_tpu.config import load_config
    cfg = load_config(path)
    assert cfg.distributed.dp_size == 2 and cfg.distributed.tp_size == 2
    assert cfg.global_batch_size == 2 * 3 * 2


def test_create_config_rejects_bad_layout(tmp_path):
    cc = load_tool("create_config")
    args = cc.build_parser().parse_args([
        "--exp-name", "bad", "--out-dir", str(tmp_path),
        "--model", "debug-tiny", "--tp", "3",  # 4 heads % 3 != 0
    ])
    with pytest.raises(ValueError):
        cc.create_single_config(args)


def test_extract_metrics_parses_log_line(tmp_path):
    from picotron_tpu.utils import training_log_line
    em = load_tool("extract_metrics")

    run = tmp_path / "dp4_tp2_pp1_cp1"
    run.mkdir()
    lines = [training_log_line(s, 5.0 - 0.1 * s, 12345.0, 1543.1, 0.1854,
                               s * 512) for s in range(1, 8)]
    (run / "train.log").write_text("\n".join(lines) + "\n")

    stats = em.process_file(str(run / "train.log"), skip_steps=3)
    assert stats["steps"] == 4  # steps 4..7
    assert stats["final_loss"] == pytest.approx(4.3)
    assert stats["mean_mfu_pct"] == pytest.approx(18.54)
    assert stats["mean_tokens_per_sec"] == pytest.approx(12300, rel=0.01)

    rows = em.aggregate(str(tmp_path), skip_steps=3)
    assert rows[0]["dp"] == 4 and rows[0]["tp"] == 2
    assert (run / "metrics.csv").exists()


def test_parse_human_inverts_human_format():
    from picotron_tpu.utils import human_format
    em = load_tool("extract_metrics")
    for v in (950.0, 12300.0, 27200000.0):
        assert em.parse_human(human_format(v)) == pytest.approx(v, rel=0.01)


def test_job_status_machine(tmp_path):
    sj = load_tool("submit_jobs")
    run = tmp_path / "run_a"
    run.mkdir()
    (run / "config.json").write_text("{}")

    jobs = sj.discover_jobs(str(tmp_path))
    assert len(jobs) == 1
    job = jobs[0]
    assert job.status == "init"
    job.set_status("running")
    assert job.status == "running"

    # post-mortem classification (ref: base_job.slurm:82-94)
    (run / "train.log").write_text("... RESOURCE_EXHAUSTED: out of memory ...")
    assert job.classify(returncode=1) == "oom"
    (run / "train.log").write_text("... DEADLINE_EXCEEDED ...")
    assert job.classify(returncode=1) == "timeout"
    (run / "train.log").write_text("some other crash")
    assert job.classify(returncode=1) == "fail"
    assert job.classify(returncode=0) == "completed"


def test_create_config_from_hf_config_json(tmp_path):
    """--from-hf-config: the offline AutoConfig (VERDICT r3 missing #1) —
    a non-preset Llama-family model resolves from its local config.json;
    Qwen2 model_type implies qkv bias; Mixtral fields map to the MoE
    knobs; unsupported architectures are rejected."""
    import json

    hf = {
        "model_type": "qwen2", "vocab_size": 1024, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "max_position_embeddings": 512, "rope_theta": 1e6,
        "rms_norm_eps": 1e-6, "tie_word_embeddings": True,
    }
    p = tmp_path / "config.json"
    p.write_text(json.dumps(hf))

    cc = load_tool("create_config")
    args = cc.build_parser().parse_args([
        "--exp-name", "custom", "--out-dir", str(tmp_path),
        "--model", "my-custom-model", "--from-hf-config", str(p),
        "--dp", "2", "--seq-len", "64", "--mbs", "1", "--grad-acc", "1",
        "--use-cpu",
    ])
    path = cc.create_single_config(args)
    from picotron_tpu.config import load_config, model_config_from_hf_json
    cfg = load_config(path)
    assert cfg.model.vocab_size == 1024
    assert cfg.model.num_hidden_layers == 2
    assert cfg.model.rope_theta == 1e6
    assert cfg.model.attention_bias is True  # qwen2 implies qkv bias
    assert cfg.model.tie_word_embeddings is True
    cfg.validate()

    moe = model_config_from_hf_json({
        "model_type": "mixtral", "vocab_size": 512, "hidden_size": 32,
        "intermediate_size": 64, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_local_experts": 8,
        "num_experts_per_tok": 2,
    })
    assert moe["num_experts"] == 8 and moe["num_experts_per_token"] == 2
    assert moe["attention_bias"] is False

    with pytest.raises(ValueError, match="not a supported"):
        model_config_from_hf_json({"model_type": "gpt_bigcode",
                                   "vocab_size": 1, "hidden_size": 1,
                                   "intermediate_size": 1,
                                   "num_hidden_layers": 1,
                                   "num_attention_heads": 1})


def test_slurm_render_golden(tmp_path):
    """The sbatch branch's render (ref: submit_slurm_jobs.py:68-103): the
    script must carry the exact #SBATCH directives, the status.txt state
    transitions, and grep alternations built from the SAME pattern
    constants the local launcher classifies with."""
    sj = load_tool("submit_jobs")
    run = tmp_path / "llama-dp8"
    run.mkdir()
    (run / "config.json").write_text("{}")
    job = sj.discover_jobs(str(tmp_path))[0]

    script = sj.render_slurm(job, nodes=4, time_limit="03:30:00")
    assert script == str(run / "job.slurm")
    text = open(script).read()
    expected = sj.SLURM_TEMPLATE.format(
        name="llama-dp8", nodes=4, run_dir=str(run),
        time_limit="03:30:00", repo_root=sj.REPO_ROOT,
        oom_re="|".join(sj.OOM_PATTERNS),
        timeout_re="|".join(sj.TIMEOUT_PATTERNS))
    assert text == expected
    # structural invariants a template edit must not silently break
    assert "#SBATCH --job-name=llama-dp8" in text
    assert "#SBATCH --nodes=4" in text
    assert "#SBATCH --time=03:30:00" in text
    assert f"srun python -m picotron_tpu.train --config {run}/config.json" \
        in text
    for state in ("running", "completed", "oom", "timeout", "fail"):
        assert f"echo {state} > " in text
    assert "RESOURCE_EXHAUSTED|Out of memory|OutOfMemoryError" in text


def test_slurm_dry_run_renders_without_submitting(tmp_path, capsys,
                                                  monkeypatch):
    """--dry-run must render + print the script, call NO sbatch, and leave
    status.txt untouched (VERDICT r3: the sbatch branch had never executed,
    not even render-only)."""
    import subprocess as sp

    sj = load_tool("submit_jobs")
    run = tmp_path / "run_a"
    run.mkdir()
    (run / "config.json").write_text("{}")

    def boom(*a, **k):
        raise AssertionError("dry run must not invoke subprocess")

    monkeypatch.setattr(sp, "run", boom)
    monkeypatch.setattr(
        sys, "argv",
        ["submit_jobs", str(tmp_path), "--launcher", "slurm", "--dry-run"])
    sj.main()
    out = capsys.readouterr().out
    assert "rendered" in out and "srun python -m picotron_tpu.train" in out
    assert (run / "job.slurm").exists()
    assert (run / "status.txt").read_text().strip() == "init"


def test_watch_queue_flips_pending_to_running_and_catches_dead(tmp_path,
                                                               monkeypatch):
    """The squeue poller (ref: base_job.slurm:16-32): PENDING -> RUNNING
    when SLURM starts the job; a job that leaves the queue while still
    'pending' (killed before its script's first line) is marked fail
    instead of dangling forever."""
    import subprocess as sp

    sj = load_tool("submit_jobs")
    for name in ("run_a", "run_b"):
        d = tmp_path / name
        d.mkdir()
        (d / "config.json").write_text("{}")
    job_a, job_b = sj.discover_jobs(str(tmp_path))
    job_a.set_status("pending")
    job_b.set_status("pending")

    # poll 1: a PENDING, b RUNNING; poll 2: a gone (never started), b gone
    polls = iter([
        "1001 PENDING\n1002 RUNNING\n",
        "",
    ])

    class R:
        def __init__(self, out):
            self.stdout = out
            self.returncode = 0

    def fake_run(cmd, **kw):
        assert cmd[0] == "squeue"
        return R(next(polls))

    monkeypatch.setattr(sp, "run", fake_run)
    monkeypatch.setattr(sj.time, "sleep", lambda s: None)
    sj.watch_queue(str(tmp_path), {"run_a": "1001", "run_b": "1002"},
                   interval=0, max_polls=2)
    assert job_a.status == "fail"      # left queue while pending
    assert job_b.status == "running"   # started; epilogue owns the rest


def test_dry_run_requires_slurm_launcher(tmp_path, monkeypatch):
    sj = load_tool("submit_jobs")
    monkeypatch.setattr(
        sys, "argv", ["submit_jobs", str(tmp_path), "--dry-run"])
    with pytest.raises(SystemExit):
        sj.main()


def test_extract_metrics_harvests_extras_and_val_loss(tmp_path):
    """The harvester picks up trailing extras (moe_drop_frac) and dedicated
    eval lines from the de-facto log-line API."""
    import sys

    sys.path.insert(0, "tools")
    from extract_metrics import process_file

    log = tmp_path / "train.log"
    lines = []
    for s in range(1, 7):
        lines.append(
            f"[step {s:06d}] loss: 5.{s}000 | tokens/s: 1.5K | "
            f"tokens/s/chip: 750 | MFU: 45.00% | tokens: 10K | "
            f"mem: 1.0GB | moe_drop_frac: 0.0{s}00")
    lines.append("[eval  000004] val_loss: 5.4321 (8 batches)")
    lines.append("[eval  000006] val_loss: 5.2100 (8 batches)")
    log.write_text("\n".join(lines))
    out = process_file(str(log))
    assert abs(out["mean_moe_drop_frac"] - 0.05) < 1e-9  # steps 4..6
    assert out["final_val_loss"] == 5.21


def test_extract_metrics_extras_skip_stable_suffixed_fields(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    from extract_metrics import process_file

    log = tmp_path / "train.log"
    log.write_text(
        "[step 000004] loss: 5.0000 | tokens/s: 1.5K | tokens/s/chip: 750 "
        "| MFU: 45.00% | tokens: 10K | mem: 1.0GB\n"
        "[step 000005] loss: 5.0000 | tokens/s: 1.5K | tokens/s/chip: 750 "
        "| MFU: 45.00% | tokens: 20K | mem: 1.0GB\n")
    out = process_file(str(log))
    assert "mean_tokens" not in out and "mean_mem" not in out


def test_trace_summary_tool(tmp_path, capsys):
    """trace_summary aggregates device events from an xprof-style trace."""
    import gzip
    import json
    import sys

    sys.path.insert(0, "tools")
    import trace_summary

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 3000,
         "name": "fusion.1"},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 3000, "dur": 1000,
         "name": "fusion.2"},
        {"ph": "X", "pid": 2, "tid": 1, "ts": 0, "dur": 9999,
         "name": "host_noise"},
    ]
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    totals, procs = trace_summary.summarize(
        trace_summary.load_events(str(tmp_path)))
    assert totals == {"fusion.1": 3000, "fusion.2": 1000}
    assert list(procs.values()) == ["/device:TPU:0"]


def test_telemetry_report_cli_markdown_smoke(tmp_path, capsys):
    """tools/telemetry_report.py end-to-end over a tiny synthetic stream:
    the CLI path resolution (run dir -> telemetry.jsonl), the summarize
    pass, and the --markdown render contract the docs reference."""
    import json

    events = [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 8.0},
        {"ts": 2.0, "kind": "phase", "phase": "save", "step": 1,
         "category": "ckpt_io", "secs": 2.0},
        {"ts": 3.0, "kind": "step", "step": 1, "loss": 3.25,
         "tokens_per_sec": 1500.0},
        {"ts": 4.0, "kind": "retry", "category": "retry_backoff",
         "secs": 0.5, "target": "checkpoint save", "attempt": 1},
    ]
    with open(tmp_path / "telemetry.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    tr = load_tool("telemetry_report")
    assert tr.main([str(tmp_path), "--markdown"]) == 0
    out = capsys.readouterr().out
    assert "goodput 76.19%" in out  # 8 / (8 + 2 + 0.5)
    assert "| category | seconds | share |" in out
    assert "| compute | 8.000 | 76.2% |" in out
    assert "retry=1" in out
    # --json emits one machine-readable object
    assert tr.main([str(tmp_path / "telemetry.jsonl"), "--json"]) == 0
    row = json.loads(capsys.readouterr().out)
    assert row["steps"]["count"] == 1
    assert row["categories"]["ckpt_io"] == 2.0
    # an empty stream is an error, not a zero-filled report
    (tmp_path / "empty.jsonl").write_text("")
    assert tr.main([str(tmp_path / "empty.jsonl")]) == 1


def test_telemetry_report_comm_row(tmp_path, capsys):
    """--config adds the `comm` row: the ICI cost model's predicted comm
    time next to the measured sync-phase median, so calibration drift is
    visible per run."""
    import json

    events = [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 0.5},
        {"ts": 1.5, "kind": "phase", "phase": "sync", "step": 1,
         "category": "logging", "secs": 0.02},
        {"ts": 2.0, "kind": "phase", "phase": "step", "step": 2,
         "category": "compute", "secs": 0.5},
        {"ts": 2.5, "kind": "phase", "phase": "sync", "step": 2,
         "category": "logging", "secs": 0.03},
    ]
    with open(tmp_path / "telemetry.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps({
        "distributed": {"dp_size": 2, "tp_size": 2},
        "model": {"name": "debug-tiny"},
        "training": {"seq_length": 64, "micro_batch_size": 1,
                     "gradient_accumulation_steps": 2},
    }))

    tr = load_tool("telemetry_report")
    assert tr.main([str(tmp_path), "--config", str(cfg_path),
                    "--generation", "v5e", "--json"]) == 0
    row = json.loads(capsys.readouterr().out)
    comm = row["comm"]
    assert comm["generation"] == "v5e"
    assert comm["predicted_comm_ms"] > 0
    assert comm["measured_sync_p50_ms"] == 30.0
    assert "comm_drift_pct" in comm
    # text render carries the row too
    assert tr.main([str(tmp_path), "--config", str(cfg_path)]) == 0
    assert "comm [v5e]: predicted" in capsys.readouterr().out
    # without --config the row is absent (no silent v5e default)
    assert tr.main([str(tmp_path), "--json"]) == 0
    assert "comm" not in json.loads(capsys.readouterr().out)


def test_telemetry_report_comm_row_without_sync_records(tmp_path, capsys):
    """Regression: a stream with NO sync-phase records (an MPMD run, or a
    telemetry.jsonl cut before the first optimizer step) must render the
    comm row's measured side as 'n/a' — not crash, not print None."""
    import json

    events = [
        {"ts": 1.0, "kind": "phase", "phase": "step", "step": 1,
         "category": "compute", "secs": 0.5},
        {"ts": 2.0, "kind": "phase", "phase": "step", "step": 2,
         "category": "compute", "secs": 0.5},
    ]
    with open(tmp_path / "telemetry.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps({
        "distributed": {"dp_size": 2, "tp_size": 2},
        "model": {"name": "debug-tiny"},
        "training": {"seq_length": 64, "micro_batch_size": 1,
                     "gradient_accumulation_steps": 2},
    }))

    tr = load_tool("telemetry_report")
    assert tr.main([str(tmp_path), "--config", str(cfg_path),
                    "--json"]) == 0
    comm = json.loads(capsys.readouterr().out)["comm"]
    assert comm["measured_sync_p50_ms"] is None
    assert "comm_drift_pct" not in comm
    for flags in ([], ["--markdown"]):
        assert tr.main([str(tmp_path), "--config", str(cfg_path),
                        *flags]) == 0
        out = capsys.readouterr().out
        assert "measured sync p50 n/a" in out
        assert "None ms" not in out


def test_bench_fails_fast_without_tpu_backend():
    """The satellite: a down TPU tunnel must yield ONE actionable line
    ('no TPU backend reachable ... rerun with --cpu or fix the tunnel'),
    not the raw xla_bridge traceback BENCH_r05.json captured. Forces a
    backend that cannot initialize in a fresh interpreter."""
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cuda")
    env.pop("XLA_FLAGS", None)  # the conftest CPU forcing must not leak
    res = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "..", "bench.py"),
         "--steps", "1"],
        capture_output=True, text=True, env=env, timeout=120)
    assert res.returncode == 1
    assert "no TPU backend reachable" in res.stderr
    assert "rerun with --cpu or fix the tunnel" in res.stderr
    assert "Traceback" not in res.stderr


def test_shardcheck_cli_smoke(capsys):
    """tools/shardcheck.py end-to-end on the CPU backend: preset
    resolution, the full analyzer stack, and the JSON output contract
    (the acceptance-criteria entry point: `python tools/shardcheck.py
    --preset ...` runs green without a TPU)."""
    import json

    sc = load_tool("shardcheck")
    rc = sc.main(["--preset", "tiny-1chip", "--json"])
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert rc == 0
    assert row["config"] == "preset:tiny-1chip"
    assert row["ok"] is True and row["errors"] == 0
    assert row["info"]["donation"]["donated"] == \
        row["info"]["donation"]["state_leaves"]


def test_bench_decode_harness_smoke():
    """bench.run_decode end-to-end at debug-tiny scale on the CPU backend:
    the prefill/decode differencing, the JSON schema, and the
    place_for_decode plumbing must not bitrot between hardware runs (the
    real numbers come from `bench.py --decode` on the chip; PERF.md r5)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    row = bench.run_decode("debug-tiny", 0, prompt_len=16, max_new=4,
                           batch=2, steps=1)
    assert row["unit"] == "decode_tokens_per_sec"
    assert row["value"] > 0
    assert row["prefill_tokens_per_sec"] > 0
    assert row["batch"] == 2 and row["max_new_tokens"] == 4


def test_bench_decode_tp_sharded_smoke():
    """bench.py --decode --tp 2 on the CPU mesh: the tp-sharded decode
    plumbing (place_for_decode through run_decode) and the metric naming —
    greedy token parity of the sharded decode itself is pinned by
    tests/test_generate.py; the 7B anchor is `--model Llama-2-7B
    --layers 4` on hardware (VERDICT r5 next #6)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    row = bench.run_decode("debug-tiny", 0, prompt_len=16, max_new=4,
                           batch=2, steps=1, tp=2)
    assert row["tp"] == 2
    assert row["metric"].endswith("-tp2")
    assert row["value"] > 0


def test_bench_bwd_grid_sweep_smoke():
    """--bwd-grid-sweep structural smoke on the CPU backend: every combo
    row carries the schema (block shape, pair timing, roofline fraction)
    and flags itself as the jnp fallback; the 16k numbers come from
    hardware (PERF.md)."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    rows = bench.run_bwd_grid_sweep("debug-tiny", seq=128, batch=1,
                                    steps=1, blocks=[(64, 64), (128, 64)])
    assert len(rows) == 2
    for row in rows:
        assert row["is_tpu_kernel"] is False
        assert row["pair_ms"] > 0 and row["fwd_ms"] > 0
        assert row["unit"] == "pair_fraction_of_peak"
