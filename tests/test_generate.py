"""Generation tests: the KV-cache decode path must reproduce the training
forward exactly (greedy decode == argmax over a full recompute at every
step), plus sampling/EOS mechanics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.config import ModelConfig, resolve_preset
from picotron_tpu.generate import generate, init_cache
from picotron_tpu.models.llama import forward, init_params


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny"), "max_position_embeddings": 64})
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def teacher_forced_cache_logits(params, cfg, ids):
    """Per-position logits from the KV-cache path: prefill on ids[:, :1],
    then decode each given token — the cache must reproduce the full
    forward's logits (token-exact sequence comparison would be brittle:
    greedy argmax flips on fp near-ties and the sequences then diverge
    completely, telling us nothing about cache correctness)."""
    from picotron_tpu.generate import _decode_layers, _logits_last, init_cache
    from picotron_tpu.models.llama import compute_dtype, model_rope_tables

    b, n = ids.shape
    cos, sin = model_rope_tables(cfg)
    cache = init_cache(cfg, b, n)
    outs = []
    for t in range(n):
        x = params["embedding"][ids[:, t:t + 1]].astype(compute_dtype(cfg))
        x, cache = _decode_layers(params, x, cache, jnp.array([t]), cfg,
                                  cos, sin)
        outs.append(_logits_last(params, x, cfg))
    return jnp.stack(outs, axis=1)  # [B, N, V]


def test_cache_decode_logits_match_full_forward(tiny):
    cfg, params = tiny
    ids = jax.random.randint(jax.random.key(1), (2, 12), 0, cfg.vocab_size)
    want = forward(params, cfg=cfg, input_ids=ids).astype(jnp.float32)
    got = teacher_forced_cache_logits(params, cfg, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_cache_decode_logits_match_full_forward_moe():
    cfg = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny-moe"), "max_position_embeddings": 64,
        # decode batches are tiny; keep capacity loose so the expert path
        # matches the full-recompute reference (no drops). NOTE: routing is
        # still per-call, so capacity slots differ between a 12-token batch
        # and 12 single-token calls — drop-free capacity makes them equal.
        "capacity_factor": 64.0})
    params = init_params(cfg, jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)
    want = forward(params, cfg=cfg, input_ids=ids).astype(jnp.float32)
    got = teacher_forced_cache_logits(params, cfg, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_greedy_generate_matches_full_recompute(tiny):
    """End-to-end greedy generate through _generate_jit's scan (positions,
    cache slots, sampling) vs a full-forward recompute per step. This is
    the test that catches decode-position off-by-ones (code review r3: the
    scan fed token i at position p_len+i instead of p_len+i-1 and the
    teacher-forced tests, which hand-build positions, stayed green). A few
    greedy steps on an fp32 tiny model carry no practical argmax-tie
    hazard."""
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.key(1), (2, 7), 0, cfg.vocab_size)
    ids = jnp.asarray(prompt, jnp.int32)
    for _ in range(4):
        logits = forward(params, ids, cfg)
        nxt = jnp.argmax(logits[:, -1].astype(jnp.float32), axis=-1)
        ids = jnp.concatenate([ids, nxt[:, None].astype(jnp.int32)], axis=1)
    got = generate(params, cfg, prompt, max_new_tokens=4)
    assert got.shape == (2, 11) and got.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ids))


def test_sampling_shapes_and_determinism(tiny):
    cfg, params = tiny
    prompt = jnp.zeros((3, 4), jnp.int32)
    a = generate(params, cfg, prompt, 5, temperature=0.8, top_k=10,
                 key=jax.random.key(7))
    b = generate(params, cfg, prompt, 5, temperature=0.8, top_k=10,
                 key=jax.random.key(7))
    assert a.shape == (3, 9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = generate(params, cfg, prompt, 5, temperature=0.8, top_k=10,
                 key=jax.random.key(8))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_eos_early_exit_token_parity(tiny):
    """With eos_token_id the decode loop is a while_loop that stops once
    every row has emitted EOS, instead of burning max_new_tokens steps.
    Token parity with the non-early-exit path: run WITHOUT eos (the
    fixed-trip scan), post-pad everything after each row's first EOS,
    and the early-exit output must be identical."""
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.key(3), (3, 5), 0,
                                cfg.vocab_size)
    free = np.asarray(generate(params, cfg, prompt, 10))  # scan path
    eos = int(free[0, 5 + 1])  # row 0's second generated token
    want = free.copy()
    for row in want:
        gen = row[5:]
        hits = np.where(gen == eos)[0]
        if hits.size:
            gen[hits[0]:] = eos
    got = np.asarray(generate(params, cfg, prompt, 10, eos_token_id=eos))
    np.testing.assert_array_equal(got, want)


def test_eos_early_exit_single_token(tiny):
    cfg, params = tiny
    prompt = jnp.zeros((2, 3), jnp.int32)
    out = generate(params, cfg, prompt, 1, eos_token_id=0)
    assert out.shape == (2, 4)


def test_eos_padding(tiny):
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.key(2), (2, 4), 0, cfg.vocab_size)
    # force every token to be EOS by choosing eos == the greedy argmax of
    # the first step for row 0: cheaper — just use a vocab-wide sweep:
    # generate with eos_token_id set to whatever greedy produced first.
    greedy = generate(params, cfg, prompt, 6)
    eos = int(greedy[0, 4])  # row 0's first generated token
    out = np.asarray(generate(params, cfg, prompt, 6, eos_token_id=eos))
    # once a row hits eos, everything after must be eos
    for row in out:
        gen = row[4:]
        hits = np.where(gen == eos)[0]
        if hits.size:
            assert (gen[hits[0]:] == eos).all()


def test_cache_shapes(tiny):
    cfg, params = tiny
    cache = init_cache(cfg, batch=2, max_length=16)
    assert cache.k.shape == (cfg.num_hidden_layers, 2, 16,
                             cfg.num_key_value_heads, cfg.head_dim)


def test_cache_decode_matches_forward_qwen2_bias_tied():
    cfg = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny-qwen"), "max_position_embeddings": 64})
    params = init_params(cfg, jax.random.key(0))
    # non-zero biases, or a decode path that silently drops them would pass
    for b in ("b_q", "b_k", "b_v"):
        params["layers"][b] = 0.1 * jax.random.normal(
            jax.random.key(hash(b) % 1000), params["layers"][b].shape)
    ids = jax.random.randint(jax.random.key(1), (2, 10), 0, cfg.vocab_size)
    want = forward(params, cfg=cfg, input_ids=ids).astype(jnp.float32)
    got = teacher_forced_cache_logits(params, cfg, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_tp_sharded_decode_greedy_parity(tiny):
    """place_for_decode(tp=2) must produce token-identical greedy output to
    the single-device path: same pure-GSPMD decode program, shardings
    propagated from the param placement (VERDICT r3 weak #6 — the decode
    path usable at 7B scale)."""
    from picotron_tpu.generate import place_for_decode

    cfg, params = tiny
    prompt = jnp.asarray([[5, 12, 7, 3], [1, 2, 3, 4]], jnp.int32)
    ref = generate(params, cfg, prompt, 12)

    sharded = place_for_decode(params, cfg, tp=2)
    emb = jax.tree.leaves(sharded)  # placement really sharded something
    assert any(len(x.sharding.device_set) == 2 for x in emb)
    out = generate(sharded, cfg, prompt, 12)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_tp_decode_rejects_indivisible_heads(tiny):
    from picotron_tpu.generate import place_for_decode

    cfg, params = tiny
    with pytest.raises(ValueError):
        place_for_decode(params, cfg, tp=3)  # 8 q heads % 3 != 0


def test_restore_params_only_bf16_dtype(tmp_path):
    """--load-dtype bfloat16: the restore template casts during restore, so
    decode-scale loads never materialize the fp32 tree."""
    import dataclasses

    from picotron_tpu.checkpoint import CheckpointManager, restore_params_only
    from picotron_tpu.config import (
        Config, DistributedConfig, TrainingConfig,
    )
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state

    cfg = Config(
        distributed=DistributedConfig(),
        model=ModelConfig(**resolve_preset("debug-tiny")),
        training=TrainingConfig(seq_length=32, micro_batch_size=1,
                                remat=False),
    )
    cfg = dataclasses.replace(
        cfg, checkpoint=dataclasses.replace(cfg.checkpoint,
                                            save_dir=str(tmp_path),
                                            async_save=False))
    cfg.validate()
    menv = MeshEnv.create(dp=1, devices=jax.devices()[:1])
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    CheckpointManager(cfg, menv).save(state)

    params, step = restore_params_only(cfg, str(tmp_path),
                                       dtype=jnp.bfloat16)
    assert step == 0
    for leaf in jax.tree.leaves(params):
        assert leaf.dtype == jnp.bfloat16
    ref = jax.tree.leaves(state.params)[0]
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(params)[0]),
        np.asarray(ref.astype(jnp.bfloat16)))
