import json

import pytest

from picotron_tpu.config import (
    Config,
    config_from_dict,
    load_config,
    num_params,
    resolve_preset,
)


def test_defaults_validate():
    cfg = Config()
    cfg.validate()
    assert cfg.distributed.world_size == 1


def test_reference_schema_loads(tmp_path):
    # A verbatim reference-style config (schema of template/base_config.json)
    raw = {
        "distributed": {
            "tp_size": 2, "cp_size": 1, "pp_size": 2, "dp_size": 2,
            "pp_engine": "1f1b", "backend": "nccl", "use_cpu": False,
        },
        "model": {
            "name": "HuggingFaceTB/SmolLM-360M-Instruct",
            "num_hidden_layers": 16,
            "num_attention_heads": 16,
            "num_key_value_heads": 4,
            "dtype": "bfloat16",
            "use_flash_attention": True,
            "use_fused_adam": True,
        },
        "training": {
            "seed": 42, "learning_rate": 3e-4, "total_train_steps": 200,
            "seq_length": 1024, "micro_batch_size": 32,
            "gradient_accumulation_steps": 1, "num_samples": 400000,
            "max_tokens": None,
        },
        "dataset": {"name": "roneneldan/TinyStories", "subset_name": None,
                    "num_workers": 0, "num_proc": 1},
        "checkpoint": {"save_dir": "ckpt", "save_frequency": 300, "load_path": ""},
        "logging": {"use_wandb": False, "project_name": "picotron", "run_name": None},
        "environment": {"OMP_NUM_THREADS": "1", "FLASH_ATTEN": "1", "HF_TOKEN": None},
    }
    p = tmp_path / "config.json"
    p.write_text(json.dumps(raw))
    cfg = load_config(str(p))
    # overrides beat the preset
    assert cfg.model.num_hidden_layers == 16
    assert cfg.model.num_key_value_heads == 4
    # preset fills the rest
    assert cfg.model.hidden_size == 960
    assert cfg.model.vocab_size == 49152
    assert cfg.global_batch_size == 32 * 1 * 2
    assert cfg.tokens_per_step == 64 * 1024


def test_preset_aliases():
    assert resolve_preset("SmolLM-1.7B")["hidden_size"] == 2048
    assert resolve_preset("Llama-2-7B")["num_hidden_layers"] == 32
    with pytest.raises(KeyError):
        resolve_preset("nonexistent-model")


def test_validation_errors():
    with pytest.raises(ValueError):
        config_from_dict({"distributed": {"tp_size": 3},
                          "model": {"name": "debug-tiny"}})  # 4 heads % 3 != 0
    with pytest.raises(ValueError):
        config_from_dict({"distributed": {"cp_size": 3},
                          "model": {"name": "debug-tiny"},
                          "training": {"seq_length": 128}})  # 128 % 3 != 0


def test_num_params_llama2_7b():
    from picotron_tpu.config import ModelConfig
    m = ModelConfig(name="meta-llama/Llama-2-7b-hf", **resolve_preset("Llama-2-7B"))
    n = num_params(m)
    # ~6.74B params + untied head
    assert 6.5e9 < n < 7.1e9


def test_ce_chunk_and_lr_ratio_validation():
    import pytest

    from picotron_tpu.config import Config, TrainingConfig

    with pytest.raises(ValueError, match="ce_chunk_size"):
        Config(training=TrainingConfig(ce_chunk_size=-16)).validate()
    # non-dividing chunk would silently fall back to fused — reject
    with pytest.raises(ValueError, match="divide"):
        Config(training=TrainingConfig(ce_chunk_size=100)).validate()
    Config(training=TrainingConfig(ce_chunk_size=64)).validate()  # 256 % 64
    # chunk >= vocab shard would silently degenerate to the fused CE path —
    # the exact fallback the user set the knob to avoid (ADVICE r3)
    with pytest.raises(ValueError, match="smaller"):
        Config(training=TrainingConfig(ce_chunk_size=512)).validate()
    with pytest.raises(ValueError, match="lr_min_ratio"):
        Config(training=TrainingConfig(lr_min_ratio=-0.1)).validate()


def test_serve_config_validation():
    import pytest

    from picotron_tpu.config import Config, ModelConfig, ServeConfig

    Config().validate()  # defaults carry a valid serve block
    for bad in (dict(decode_slots=0), dict(block_size=0),
                dict(prefill_chunk=0), dict(decode_interval=0),
                dict(num_blocks=-1), dict(max_model_len=-1)):
        with pytest.raises(ValueError, match="serve"):
            Config(serve=ServeConfig(**bad)).validate()
    # per-sequence serving capacity cannot exceed the model's positions
    from picotron_tpu.config import TrainingConfig

    small = TrainingConfig(seq_length=64)
    with pytest.raises(ValueError, match="max_model_len"):
        Config(model=ModelConfig(max_position_embeddings=128),
               training=small,
               serve=ServeConfig(max_model_len=256)).validate()
    Config(model=ModelConfig(max_position_embeddings=256),
           training=small,
           serve=ServeConfig(max_model_len=256)).validate()


def test_serve_config_from_dict_round_trip():
    from picotron_tpu.config import config_from_dict

    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "serve": {"decode_slots": 4, "block_size": 8, "num_blocks": 16,
                  "prefill_chunk": 32, "max_model_len": 256,
                  "decode_interval": 2},
    })
    assert cfg.serve.decode_slots == 4 and cfg.serve.num_blocks == 16
    assert cfg.serve.decode_interval == 2
    # unknown keys in the section are ignored (reference-JSON compat)
    cfg2 = config_from_dict({"model": {"name": "debug-tiny"},
                             "serve": {"decode_slots": 2, "bogus": 1}})
    assert cfg2.serve.decode_slots == 2
