"""Flash-attention kernel tests.

These run the Pallas kernels in interpreter mode (`interpret=True`) so the
exact kernel code paths — online-softmax recurrence, GQA index maps, causal
position masking, block skipping, custom VJP incl. the LSE cotangent — are
pinned against the jnp reference on the CPU test platform. The compiled
path is exercised on real hardware by bench.py and the TPU smoke script.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.ops.attention import sdpa_attention
from picotron_tpu.ops.flash_attention import flash_attention


def qkv(key=0, b=2, s=128, hq=4, hkv=2, d=32, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 3)
    return (jax.random.normal(ks[0], (b, s, hq, d), dtype),
            jax.random.normal(ks[1], (b, s, hkv, d), dtype),
            jax.random.normal(ks[2], (b, s, hkv, d), dtype))


@pytest.mark.parametrize("hq,hkv,causal", [(4, 4, True), (4, 2, True),
                                           (8, 1, True), (4, 2, False)])
def test_kernel_forward_matches_sdpa(hq, hkv, causal):
    q, k, v = qkv(hq=hq, hkv=hkv)
    got, lse_f = flash_attention(q, k, v, causal=causal, return_lse=True,
                                 block_q=32, block_k=32, interpret=True)
    want, lse_r = sdpa_attention(q, k, v, causal=causal, return_lse=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_r),
                               rtol=2e-5, atol=2e-5)


def test_kernel_grads_match_sdpa():
    q, k, v = qkv()

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32,
                                       block_k=32, interpret=True) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(sdpa_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_f, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


def test_kernel_lse_cotangent():
    """The CP ring differentiates through the block LSE; the kernel VJP folds
    the LSE cotangent into the delta term."""
    q, k, v = qkv(s=64, d=16)

    def loss_f(q, k, v):
        o, lse = flash_attention(q, k, v, causal=True, return_lse=True,
                                 block_q=16, block_k=16, interpret=True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_r(q, k, v):
        o, lse = sdpa_attention(q, k, v, causal=True, return_lse=True)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_f, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_r, (0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


def test_kernel_shifted_positions():
    """Ring-style call: q is a later shard, K/V an earlier block — cross
    positions, fully unmasked + partially masked blocks."""
    q, k, v = qkv(s=64, d=16)
    q_pos = jnp.arange(64) + 64   # q shard [64, 128)
    kv_pos = jnp.arange(64)       # kv block [0, 64) -> fully visible
    got, lse_f = flash_attention(q, k, v, causal=True, q_positions=q_pos,
                                 kv_positions=kv_pos, return_lse=True,
                                 block_q=16, block_k=16, interpret=True)
    want, lse_r = sdpa_attention(q, k, v, causal=True, q_positions=q_pos,
                                 kv_positions=kv_pos, return_lse=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # reversed: kv block strictly in the future -> all rows empty
    got2, lse2 = flash_attention(q, k, v, causal=True, q_positions=kv_pos,
                                 kv_positions=q_pos, return_lse=True,
                                 block_q=16, block_k=16, interpret=True)
    assert bool(jnp.all(got2 == 0.0))
    assert bool(jnp.all(jnp.isneginf(lse2)))


def test_cpu_fallback_is_sdpa():
    """Default (non-TPU) dispatch routes to the jnp twin and composes with
    the rest of the stack."""
    q, k, v = qkv(s=32, d=16)
    got = flash_attention(q, k, v, causal=True)
    want = sdpa_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_bf16_kernel_close():
    q, k, v = qkv(dtype=jnp.bfloat16, s=64, d=32)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                          interpret=True).astype(jnp.float32)
    want = sdpa_attention(q, k, v, causal=True).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-2, atol=5e-2)


# -- fused RoPE ------------------------------------------------------------


def test_kernel_fused_rope_matches_unfused():
    """rope=(cos, sin) rotates q/k inside the kernels; must equal jnp
    apply_rope followed by the plain kernel — forward, lse, and all grads
    (dq/dk exercise the in-kernel inverse rotation)."""
    from picotron_tpu.ops.rope import apply_rope, rope_tables

    q, k, v = qkv(s=128, hq=4, hkv=2)
    cos, sin = rope_tables(256, q.shape[-1])
    pos = jnp.arange(40, 168)  # shard-like offset positions

    def loss_fused(q, k, v):
        out, lse = flash_attention(
            q, k, v, causal=True, rope=(cos, sin), q_positions=pos,
            kv_positions=pos, return_lse=True, block_q=32, block_k=32,
            interpret=True)
        return jnp.sum(out ** 2) + jnp.sum(jnp.where(jnp.isfinite(lse),
                                                     lse, 0.0)), (out, lse)

    def loss_ref(q, k, v):
        qr = apply_rope(q, cos, sin, pos)
        kr = apply_rope(k, cos, sin, pos)
        out, lse = flash_attention(
            qr, kr, v, causal=True, q_positions=pos, kv_positions=pos,
            return_lse=True, block_q=32, block_k=32, interpret=True)
        return jnp.sum(out ** 2) + jnp.sum(jnp.where(jnp.isfinite(lse),
                                                     lse, 0.0)), (out, lse)

    gf, aux_f = jax.grad(loss_fused, (0, 1, 2), has_aux=True)(q, k, v)
    gr, aux_r = jax.grad(loss_ref, (0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(aux_f[0]), np.asarray(aux_r[0]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(aux_f[1]), np.asarray(aux_r[1]),
                               rtol=2e-5, atol=2e-5)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=f"d{name}")


def test_static_causal_matches_dynamic_positions():
    """positions=None takes the static-causal fast path (program-id block
    classes + DMA-free skipped tiles, PERF.md r5); an explicit arange must
    produce bit-identical out AND grads through the dynamic-masking path."""
    B, S, H, D = 2, 512, 4, 64
    from picotron_tpu.ops.rope import rope_tables

    q = jax.random.normal(jax.random.key(0), (B, S, H, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, S, H, D), jnp.float32)
    rope = rope_tables(1024, D)

    def loss(q, k, v, pos):
        out = flash_attention(q, k, v, causal=True, rope=rope,
                              q_positions=pos, kv_positions=pos,
                              block_q=128, block_k=128, interpret=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    vs = jax.value_and_grad(loss, argnums=(0, 1, 2))
    l_s, g_s = vs(q, k, v, None)
    l_d, g_d = vs(q, k, v, jnp.arange(S))
    assert float(l_s) == float(l_d)
    for a, b in zip(g_s, g_d):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_static_causal_rectangular_and_mixed_blocks():
    """sk > sq with bq != bk: exercises _q_eff's upper clamp (the last kv
    blocks see no q block — an unclamped index would address past the q
    array) and the bq != bk block-class integer math."""
    from picotron_tpu.ops.rope import rope_tables

    B, SQ, SK, H, D = 1, 256, 512, 2, 64
    q = jax.random.normal(jax.random.key(0), (B, SQ, H, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, SK, H, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, SK, H, D), jnp.float32)
    rope = rope_tables(1024, D)

    def loss(q, k, v, qpos, kpos):
        out = flash_attention(q, k, v, causal=True, rope=rope,
                              q_positions=qpos, kv_positions=kpos,
                              block_q=64, block_k=128, interpret=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    vs = jax.value_and_grad(loss, argnums=(0, 1, 2))
    l_s, g_s = vs(q, k, v, None, None)
    l_d, g_d = vs(q, k, v, jnp.arange(SQ), jnp.arange(SK))
    assert float(l_s) == float(l_d)
    for a, b in zip(g_s, g_d):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
