"""Multi-process execution tests: the full trainer CLI under a real
2-process `jax.distributed` runtime on CPU (gloo cross-process collectives).

This is the TPU-native analogue of the reference's de-facto cluster-free
test — a real multi-process gloo run of the training loop
(ref: README.md:40-47, train.py:83-94) — and the round-3 item VERDICT r2
called the single highest-leverage gap: nothing had ever executed with
`jax.process_count() > 1`. Each test launches two fresh subprocesses with
the PICOTRON_* launcher contract (mesh.multihost_initialize), each
provisioning half the world's simulated devices, and asserts loss parity
with the same config run in a single process.

Layouts are chosen so the axis that spans the process boundary varies:
devices enumerate process-major, and the mesh grid is (dp, pp, ep, cp, tp)
row-major, so the outermost nontrivial axis is the one whose collectives
cross gloo — dp (gradient psum), pp (boundary ppermute), and ep (MoE
dispatch all_to_all) each get a layout.
"""

import json
import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # integration tier: run with plain `pytest tests/`; dev loop = -m 'not slow'

sys.path.insert(0, "tools")

from extract_metrics import LINE_RE  # noqa: E402

STEPS = 3


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_cfg(tmp_path, distributed, model="debug-tiny",
               attn_impl=None, dataset=None, name="cfg.json",
               overrides=None):
    cfg = {
        "distributed": {"use_cpu": True, **distributed},
        "model": {"name": model, "dtype": "float32",
                  **({"attn_impl": attn_impl} if attn_impl else {})},
        "training": {"total_train_steps": STEPS, "seq_length": 32,
                     "micro_batch_size": 2,
                     "gradient_accumulation_steps": 2,
                     "remat": False, "seed": 3},
        "dataset": dataset or {"name": "synthetic", "num_workers": 0},
        "checkpoint": {"save_dir": str(tmp_path / "ckpt")},
        "logging": {"log_frequency": 1},
    }
    for section, vals in (overrides or {}).items():
        cfg.setdefault(section, {}).update(vals)
    path = tmp_path / name
    path.write_text(json.dumps(cfg))
    return str(path)


def _launch(cfg_path, n_proc, port, extra_env=None):
    """Spawn the trainer CLI in n_proc coordinated processes; return the
    list of Popen handles."""
    procs = []
    for pid in range(n_proc):
        env = dict(os.environ)
        # Fresh device provisioning per process: the trainer's use_cpu path
        # must set the per-process count itself (the inherited pytest flag
        # would give every process the full 8 and break the world math).
        env.pop("XLA_FLAGS", None)
        env.update(extra_env or {})
        env.update({
            "PICOTRON_COORDINATOR": f"127.0.0.1:{port}",
            "PICOTRON_NUM_PROCESSES": str(n_proc),
            "PICOTRON_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "picotron_tpu.train",
             "--config", cfg_path],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env))
    return procs


def _losses(out: str) -> list[float]:
    return [float(m.group("loss")) for line in out.splitlines()
            if (m := LINE_RE.search(line))]


def _run_single(cfg_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    for k in ("PICOTRON_COORDINATOR", "PICOTRON_NUM_PROCESSES",
              "PICOTRON_PROCESS_ID"):
        env.pop(k, None)
    res = subprocess.run(
        [sys.executable, "-m", "picotron_tpu.train", "--config", cfg_path],
        capture_output=True, text=True, env=env, timeout=600)
    assert res.returncode == 0, f"single-process run failed:\n{res.stderr[-2000:]}"
    return _losses(res.stdout)


@pytest.mark.parametrize("layout", [
    # dp spans the process boundary: cross-process gradient psum
    {"dp_size": 2, "pp_size": 2, "tp_size": 2},
    # pp spans the process boundary: cross-process pipeline ppermute
    # (dp=1, so pp is outermost nontrivial); afab engine for AD coverage
    {"pp_size": 2, "cp_size": 2, "tp_size": 2, "pp_engine": "afab"},
    # ep spans the process boundary: cross-process MoE dispatch all_to_all
    # (the one collective family the other layouts don't exercise)
    {"ep_size": 2, "cp_size": 2, "tp_size": 2, "_model": "debug-tiny-moe"},
    # cp spans the process boundary with ULYSSES: the head/seq-trading
    # all_to_all pair + the static zigzag sort cross gloo (VERDICT r3 weak
    # #4: ulysses had only ever run single-process); dp=pp=1 so cp is the
    # outermost nontrivial axis
    {"cp_size": 2, "tp_size": 1, "_attn": "ulysses"},
])
def test_two_process_training_matches_single(tmp_path, layout):
    layout = dict(layout)
    model = layout.pop("_model", "debug-tiny")
    attn = layout.pop("_attn", None)
    cfg_path = _write_cfg(tmp_path, layout, model=model, attn_impl=attn)
    single = _run_single(cfg_path)
    assert len(single) == STEPS and all(np.isfinite(single))

    procs = _launch(cfg_path, n_proc=2, port=_free_port())
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"multi-process run failed:\n{err[-3000:]}"
    # process 0 is the logging host; its log lines carry the psum'd global
    # loss, which must match the single-process run step for step
    multi = _losses(outs[0][1])
    assert len(multi) == STEPS
    np.testing.assert_allclose(multi, single, rtol=1e-5, atol=1e-6)
    assert "training done" in outs[0][1]
    # the non-logging process must stay silent on stdout (log_print gate)
    assert _losses(outs[1][1]) == []


def test_loader_callback_path_matches_device_put(monkeypatch):
    """The dataloader's multi-process feeding path
    (`make_array_from_callback`, taken when process_count > 1) must place
    exactly the same values per shard as the single-process device_put
    path. Forced here on the single-process 8-device mesh by patching
    process_count — the callback path is valid (if unnecessary) there, so
    the two batches must be identical."""
    import jax

    from picotron_tpu.config import config_from_dict
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.mesh import MeshEnv

    cfg = config_from_dict({
        "distributed": {"dp_size": 2, "cp_size": 2, "tp_size": 2},
        "model": {"name": "debug-tiny"},
        "training": {"seq_length": 32, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 2, "seed": 7},
    })
    menv = MeshEnv.from_config(cfg)

    ids_a, tgt_a = next(MicroBatchDataLoader(cfg, menv))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    ids_b, tgt_b = next(MicroBatchDataLoader(cfg, menv))

    np.testing.assert_array_equal(np.asarray(ids_a), np.asarray(ids_b))
    np.testing.assert_array_equal(np.asarray(tgt_a), np.asarray(tgt_b))
    for sa, sb in zip(ids_a.addressable_shards, ids_b.addressable_shards):
        assert sa.device == sb.device
        np.testing.assert_array_equal(np.asarray(sa.data), np.asarray(sb.data))


def _communicate(procs):
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            p.kill()
    return outs


def test_two_process_sigterm_emergency_resume(tmp_path):
    """Kill-and-resume across the real 2-process gloo runtime: chaos
    delivers SIGTERM to BOTH processes at the same step (the way a pod
    preemption hits every host), the coordinated emergency save must be
    durable, both processes must exit EXIT_PREEMPTED, and the auto_resume
    relaunch must continue to a loss curve matching an uninterrupted
    single-process run — dp spans the process boundary, so the emergency
    checkpoint's optimizer state crossed gloo both ways."""
    from picotron_tpu.resilience import EXIT_PREEMPTED

    total = 5
    layout = {"dp_size": 2, "tp_size": 2}
    # uninterrupted single-process reference, separate save_dir
    ref_cfg = _write_cfg(
        tmp_path, layout, name="ref.json",
        overrides={"training": {"total_train_steps": total},
                   "checkpoint": {"save_dir": str(tmp_path / "ckpt_ref")}})
    single = _run_single(ref_cfg)
    assert len(single) == total

    cfg_path = _write_cfg(
        tmp_path, layout,
        overrides={"training": {"total_train_steps": total},
                   "checkpoint": {"save_frequency": 0,
                                  "auto_resume": True},
                   "resilience": {"chaos": "sigterm@2"}})
    outs = _communicate(_launch(cfg_path, n_proc=2, port=_free_port()))
    for rc, _, err in outs:
        assert rc == EXIT_PREEMPTED, f"expected exit 75, got {rc}:\n{err[-3000:]}"
    assert "emergency checkpoint" in outs[0][1]
    meta = json.loads((tmp_path / "ckpt" / "step_00000002" /
                       "meta.json").read_text())
    assert meta["step"] == 2 and meta["dataloader"] == {"epoch": 0,
                                                        "cursor": 16}

    # supervisor resubmission: same config, chaos disabled via the env
    # override, auto_resume picks up the emergency checkpoint
    outs2 = _communicate(_launch(cfg_path, n_proc=2, port=_free_port(),
                                 extra_env={"PICOTRON_CHAOS": ""}))
    for rc, _, err in outs2:
        assert rc == 0, f"resumed run failed:\n{err[-3000:]}"
    assert "auto_resume: found checkpoints" in outs2[0][1]
    assert "training done" in outs2[0][1]
    stitched = _losses(outs[0][1]) + _losses(outs2[0][1])
    assert len(stitched) == total
    np.testing.assert_allclose(stitched, single, rtol=1e-5, atol=1e-6)


def _build_disk_corpus(path, blocks=256, seq=32, vocab=256, seed=11):
    import datasets

    rng = np.random.default_rng(seed)
    rows = rng.integers(0, vocab, (blocks, seq + 1)).astype("int32")
    datasets.Dataset.from_dict({"input_ids": rows.tolist()}).save_to_disk(
        str(path))


def test_two_process_file_backed_dataset_matches_single(tmp_path):
    """A file-backed (pre-chunked, datasets.save_to_disk) corpus must yield
    IDENTICAL global batches on every process: each process assembles the
    full global batch from the same shuffled epoch view and
    make_array_from_callback takes only its local shards, so the psum'd
    loss matches the single-process run step for step (VERDICT r3 weak #5:
    the multi-process loader argument had only ever been exercised with
    the synthetic source)."""
    corpus = tmp_path / "corpus"
    _build_disk_corpus(corpus)
    cfg_path = _write_cfg(
        tmp_path, {"dp_size": 2, "tp_size": 2},
        dataset={"name": str(corpus), "num_workers": 0})
    single = _run_single(cfg_path)
    assert len(single) == STEPS and all(np.isfinite(single))

    procs = _launch(cfg_path, n_proc=2, port=_free_port())
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"multi-process run failed:\n{err[-3000:]}"
    multi = _losses(outs[0][1])
    np.testing.assert_allclose(multi, single, rtol=1e-5, atol=1e-6)
