"""Mesh-attention tests: the 2D-mesh cp schedule (ops/mesh_attention.py)
pinned against dense AD, its config surface validated loudly, the
collective-schedule audit's mesh rules mutation-tested, and the cost
model's submesh pricing + crossover prediction checked for the properties
PERF.md Round 13 claims (ring/ulysses degenerates exact, mesh wins where
ulysses is head-infeasible, topology moves the crossover)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from picotron_tpu import compat
from picotron_tpu.analysis.collectives import audit_collectives
from picotron_tpu.analysis.cost_model import (
    CostModel, GENERATIONS, cp_crossover, cp_crossover_table,
    cp_flavor_costs, feasible_cp_meshes, place_axes, split_cp_link,
)
from picotron_tpu.analysis import lower_train_step
from picotron_tpu.analysis.planner import candidate_configs, plan
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig, parse_cp_mesh,
    resolve_preset, resolved_cp_flavor, resolved_cp_mesh,
)
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.ops.attention import sdpa_attention
from picotron_tpu.ops.mesh_attention import (
    mesh_attention, mesh_attention_bwd_from_saved, mesh_groups,
)
from picotron_tpu.ops.ring_attention import ring_attention

TOL = dict(rtol=2e-5, atol=2e-5)


def qkvd(key=0, b=2, s=32, hq=4, hkv=2, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 4)
    return (jax.random.normal(ks[0], (b, s, hq, d), dtype),
            jax.random.normal(ks[1], (b, s, hkv, d), dtype),
            jax.random.normal(ks[2], (b, s, hkv, d), dtype),
            jax.random.normal(ks[3], (b, s, hq, d), dtype))


def dense_ref(q, k, v, do):
    _, vjp = jax.vjp(
        lambda q_, k_, v_: sdpa_attention(q_, k_, v_, causal=True),
        q, k, v)
    return vjp(do)


def assert_grads(got, want, tag=""):
    for g, w, n in zip(got, want, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   err_msg=f"{tag}{n}", **TOL)


def zigzag_perm(s, cp):
    half = s // (2 * cp)
    return np.concatenate([
        np.concatenate([np.arange(r * half, (r + 1) * half),
                        np.arange((2 * cp - 1 - r) * half,
                                  (2 * cp - r) * half)])
        for r in range(cp)])


# ---------------------------------------------------------------------------
# schedule structure
# ---------------------------------------------------------------------------


def test_mesh_groups_row_major():
    groups, perm = mesh_groups(2, 4)
    # rows are contiguous cp-index ranges (they land on innermost links)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
    # the ring rotates each column to the next row's same column
    assert sorted(perm) == sorted(
        [(i, (i + 4) % 8) for i in range(8)])
    # every device sends and receives exactly once per hop
    assert len({s for s, _ in perm}) == 8
    assert len({d for _, d in perm}) == 8


# ---------------------------------------------------------------------------
# forward/backward parity vs dense AD
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cp_x,cp_y,hq,hkv",
                         [(2, 2, 4, 2), (4, 2, 4, 2), (2, 4, 4, 4)])
def test_mesh_forward_matches_dense(cp_x, cp_y, hq, hkv):
    cp = cp_x * cp_y
    menv = MeshEnv.create(cp=cp)
    q, k, v, _ = qkvd(hq=hq, hkv=hkv)
    want = sdpa_attention(q, k, v, causal=True)

    def body(q, k, v):
        return mesh_attention(q, k, v, cp_mesh=(cp_x, cp_y))

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh, in_specs=(P(None, "cp"),) * 3,
        out_specs=P(None, "cp")))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


@pytest.mark.parametrize("cp_x,cp_y,hq,hkv",
                         [(2, 2, 4, 2), (4, 2, 4, 2), (2, 4, 4, 4)])
def test_mesh_bwd_from_saved_matches_dense_grads(cp_x, cp_y, hq, hkv):
    cp = cp_x * cp_y
    menv = MeshEnv.create(cp=cp)
    q, k, v, do = qkvd(hq=hq, hkv=hkv)

    def body(q, k, v, do):
        out, lse = mesh_attention(q, k, v, cp_mesh=(cp_x, cp_y),
                                  return_lse=True)
        return mesh_attention_bwd_from_saved(q, k, v, out, lse, do,
                                             cp_mesh=(cp_x, cp_y))

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh, in_specs=(P(None, "cp"),) * 4,
        out_specs=(P(None, "cp"),) * 3))(q, k, v, do)
    assert_grads(got, dense_ref(q, k, v, do), f"mesh{cp_x}x{cp_y}-")


def test_mesh_degenerate_cp_y1_is_ring():
    """cp_mesh=(cp,1) elides the all_to_all pair entirely — the schedule
    IS the 1D K/V ring, so outputs agree with ring_attention to fp noise
    and with dense to the shared tolerance."""
    cp = 4
    menv = MeshEnv.create(cp=cp)
    q, k, v, _ = qkvd()

    def mesh_body(q, k, v):
        return mesh_attention(q, k, v, cp_mesh=(cp, 1))

    def ring_body(q, k, v):
        return ring_attention(q, k, v)

    sm = dict(mesh=menv.mesh, in_specs=(P(None, "cp"),) * 3,
              out_specs=P(None, "cp"))
    got = jax.jit(compat.shard_map(mesh_body, **sm))(q, k, v)
    ring = jax.jit(compat.shard_map(ring_body, **sm))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ring),
                               rtol=1e-6, atol=1e-6)


def test_mesh_degenerate_cp_x1_is_ulysses():
    """cp_mesh=(1,cp): no ring hops, one full-axis head scatter — the
    Ulysses schedule. Needs cp | heads, like Ulysses."""
    cp = 4
    menv = MeshEnv.create(cp=cp)
    q, k, v, _ = qkvd(hq=4, hkv=4)
    want = sdpa_attention(q, k, v, causal=True)

    def body(q, k, v):
        return mesh_attention(q, k, v, cp_mesh=(1, cp))

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh, in_specs=(P(None, "cp"),) * 3,
        out_specs=P(None, "cp")))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


def test_mesh_bwd_from_saved_zigzag_layout():
    """Positions travel with their blocks: the zigzag sequence layout must
    mask correctly through both the head scatter and the row ring."""
    cp, s = 4, 32
    menv = MeshEnv.create(cp=cp)
    q, k, v, do = qkvd(s=s)
    perm = zigzag_perm(s, cp)

    def body(q, k, v, do, pos):
        out, lse = mesh_attention(q, k, v, cp_mesh=(2, 2),
                                  q_positions=pos, return_lse=True)
        return mesh_attention_bwd_from_saved(q, k, v, out, lse, do,
                                             cp_mesh=(2, 2),
                                             q_positions=pos)

    got = jax.jit(compat.shard_map(
        body, mesh=menv.mesh,
        in_specs=(P(None, "cp"),) * 4 + (P("cp"),),
        out_specs=(P(None, "cp"),) * 3))(
        q[:, perm], k[:, perm], v[:, perm], do[:, perm],
        jnp.asarray(perm))
    inv = np.argsort(perm)
    got = tuple(np.asarray(g)[:, inv] for g in got)
    assert_grads(got, dense_ref(q, k, v, do), "mesh-zz-")


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------


def mkcfg(model="debug-tiny", seq=64, dist=None, train=None, **model_over):
    over = resolve_preset(model)
    over.update(model_over)
    cfg = Config(
        distributed=DistributedConfig(**(dist or {})),
        model=ModelConfig(name=model, **over),
        training=TrainingConfig(seq_length=seq, micro_batch_size=1,
                                gradient_accumulation_steps=2,
                                **(train or {})),
    )
    cfg.validate()
    return cfg


def test_parse_cp_mesh():
    assert parse_cp_mesh("2x4") == (2, 4)
    with pytest.raises(ValueError, match="XxY"):
        parse_cp_mesh("2by4")
    with pytest.raises(ValueError, match=">= 1"):
        parse_cp_mesh("0x2")


def test_cp_mesh_must_factor_cp_degree():
    with pytest.raises(ValueError, match="must factor the cp degree"):
        mkcfg(dist=dict(dp_size=2, cp_size=4, cp_flavor="mesh",
                        cp_mesh="3x2"))


def test_cp_flavor_contradicting_attn_impl_rejected():
    with pytest.raises(ValueError, match="contradicts"):
        mkcfg(dist=dict(dp_size=2, cp_size=4, cp_flavor="mesh"),
              attn_impl="ring")


def test_cp_mesh_requires_mesh_flavor():
    with pytest.raises(ValueError, match="only applies to the mesh"):
        mkcfg(dist=dict(dp_size=2, cp_size=4, cp_flavor="ring",
                        cp_mesh="2x2"))


def test_mesh_head_divisibility_enforced():
    # debug-tiny has hkv=2: cp_y=4 cannot scatter the kv heads
    with pytest.raises(ValueError, match="scatters"):
        mkcfg(dist=dict(cp_size=8, cp_flavor="mesh", cp_mesh="2x4"))


def test_resolved_flavor_and_default_factorization():
    cfg = mkcfg(dist=dict(dp_size=2, cp_size=4), attn_impl="mesh")
    assert resolved_cp_flavor(cfg) == "mesh"
    # most-square feasible: debug-tiny (hq=4, hkv=2) at cp=4 -> 2x2
    assert resolved_cp_mesh(cfg) == (2, 2)


# ---------------------------------------------------------------------------
# collective-schedule audit (mutation-tested, like the ulysses rule)
# ---------------------------------------------------------------------------


def _mesh_cfg():
    return mkcfg(dist=dict(dp_size=2, cp_size=4, cp_flavor="mesh",
                           cp_mesh="2x2"),
                 train=dict(grad_engine="fused",
                            remat_policy="dots_attn"),
                 attn_impl="mesh", num_attention_heads=8,
                 num_key_value_heads=4)


def test_mesh_audit_requires_subgroup_a2a_and_row_ring():
    cfg = _mesh_cfg()
    low = lower_train_step(cfg)
    rep = audit_collectives(cfg, text=low.text, state=low.state)
    assert rep.ok(), rep.render()
    # delete the head-scatter all_to_alls: the inner-factor rule must fire
    bad = audit_collectives(
        cfg, text=low.text.replace("stablehlo.all_to_all",
                                   "stablehlo.xx_gone"),
        state=low.state)
    assert any("mesh cp flavor" in f.message and "all_to_all" in f.message
               for f in bad.errors()), bad.render()
    # delete the row ring's collective_permutes: the outer rule must fire
    bad = audit_collectives(
        cfg, text=low.text.replace("stablehlo.collective_permute",
                                   "stablehlo.xx_gone"),
        state=low.state)
    assert any("mesh cp flavor" in f.message for f in bad.errors()), \
        bad.render()


# ---------------------------------------------------------------------------
# cost model: submesh pricing + crossover
# ---------------------------------------------------------------------------


def test_split_cp_link_physics():
    gen = GENERATIONS["v5e"]
    link = place_axes({"cp": 8}, gen)["cp"]
    outer, inner = split_cp_link(link, 4, 2, gen)
    # inner subgroup: contiguous slice of the parent axis
    assert inner.size == 2
    assert inner.bandwidth == link.bandwidth
    assert inner.stride == link.stride
    assert inner.kind == "line"  # 2 < v5e wrap_min (16)
    # outer ring: strided by cp_y, bandwidth shared by cp_y row rings
    assert outer.size == 4
    assert outer.bandwidth == link.bandwidth / 2
    assert outer.stride == link.stride * 2
    assert outer.kind == link.kind
    # a wrap-capable generation closes the inner ring once cp_y >= wrap_min
    gen4 = GENERATIONS["v4"]
    link4 = place_axes({"cp": 16}, gen4)["cp"]
    _, inner4 = split_cp_link(link4, 4, 4, gen4)
    assert inner4.kind == "ring"


def test_mesh_terms_replace_ring_terms():
    cfg = _mesh_cfg()
    cost = CostModel("v5e").predict(cfg)
    names = {t.name for t in cost.comm}
    assert "mesh_a2a" in names and "mesh_ring" in names
    assert "cp_ring" not in names and "ulysses_a2a" not in names
    ring_cfg = dataclasses.replace(cfg, distributed=dataclasses.replace(
        cfg.distributed, cp_flavor="ring", cp_mesh=""),
        model=dataclasses.replace(cfg.model, attn_impl="ring"))
    ring_names = {t.name for t in CostModel("v5e").predict(ring_cfg).comm}
    assert "cp_ring" in ring_names and "mesh_ring" not in ring_names


def test_feasible_cp_meshes_respects_heads():
    # debug-tiny (hq=4, hkv=2): only cp_y=2 survives at cp=8
    cfg = mkcfg(dist=dict(cp_size=8))
    assert feasible_cp_meshes(cfg) == [(4, 2)]
    # MHA heads open every factorization
    cfg = mkcfg(dist=dict(cp_size=8), num_attention_heads=8,
                num_key_value_heads=8)
    assert feasible_cp_meshes(cfg) == [(4, 2), (2, 4)]


def _crossover_base(preset, seq=16384):
    cfg = Config(
        distributed=DistributedConfig(),
        model=ModelConfig(name=preset, **resolve_preset(preset)),
        training=TrainingConfig(seq_length=seq, micro_batch_size=1,
                                gradient_accumulation_steps=1),
    )
    cfg.validate()
    return cfg


def test_gqa_crossover_where_ulysses_dies():
    """Llama-3.1-8B (hkv=8): ulysses is head-infeasible past cp=8, so the
    mesh flavor takes over at cp=16 — on every ICI generation."""
    base = _crossover_base("meta-llama/Llama-3.1-8B")
    for gen in GENERATIONS:
        model = CostModel(gen)
        assert cp_crossover(model, base) == 16, gen
        rows = {r["cp"]: r for r in cp_crossover_table(model, base)}
        assert rows[16]["ulysses_ms"] is None
        assert rows[16]["winner"] == "mesh"
        assert rows[16]["mesh_ms"] < rows[16]["ring_ms"]


def test_mha_model_never_crosses_to_mesh():
    """Llama-2-7B (MHA, hkv=32): ulysses stays feasible at every swept
    degree and mesh (= ulysses a2a over a subgroup + an extra ring leg)
    never wins."""
    base = _crossover_base("meta-llama/Llama-2-7b-hf", seq=4096)
    assert cp_crossover(CostModel("v5e"), base) is None
    costs = cp_flavor_costs(CostModel("v5e"), dataclasses.replace(
        base, distributed=dataclasses.replace(base.distributed,
                                              cp_size=8)))
    assert costs["ulysses"] is not None
    assert costs["ulysses"].total_s <= costs["mesh"][0].total_s


# ---------------------------------------------------------------------------
# planner enumeration
# ---------------------------------------------------------------------------


def test_planner_enumerates_cp_flavors_and_overrides_round_trip():
    base = mkcfg()
    base = dataclasses.replace(base, training=dataclasses.replace(
        base.training, gradient_accumulation_steps=8))
    cands = candidate_configs(base, 8)
    by_flavor = {}
    for c in cands:
        if c.distributed.cp_size == 4:
            by_flavor.setdefault(c.distributed.cp_flavor, c)
    assert "ring" in by_flavor and "mesh" in by_flavor
    assert by_flavor["mesh"].distributed.cp_mesh == "2x2"

    pts = plan(base, 8, CostModel("v5p"))
    mesh_pts = [p for p in pts if p.cfg.distributed.cp_flavor == "mesh"]
    assert mesh_pts, "planner pruned every mesh candidate"
    line = mesh_pts[0].overrides_line()
    assert "distributed.cp_flavor=mesh" in line
    assert "distributed.cp_mesh=" in line
    assert "model.attn_impl=" in line


def test_cli_cp_crossover_json(capsys):
    import importlib.util
    import json
    import os

    root = os.path.join(os.path.dirname(__file__), "..")
    spec = importlib.util.spec_from_file_location(
        "layout_planner", os.path.join(root, "tools", "layout_planner.py"))
    lp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lp)
    rc = lp.main(["--cp-crossover", "--model", "meta-llama/Llama-3.1-8B",
                  "--seq", "16384", "--json"])
    assert rc == 0
    rows = [json.loads(l) for l in
            capsys.readouterr().out.strip().splitlines()]
    assert {r["generation"] for r in rows} == set(GENERATIONS)
    assert all(r["crossover_cp"] == 16 for r in rows)
