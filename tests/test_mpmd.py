"""MPMD pipeline executor tests: schedule-table correctness on the edge
shapes, loss/param parity against the SPMD scan twin (the same-math
different-schedule invariant test_pp_engines pins for 1f1b vs afab), the
per-stage compile-once proof, and the config validation fence.

The schedule table is pure host code (no devices), so the table tests run
anywhere; the parity tests compile the per-stage programs on the 8-device
simulated CPU mesh."""

import collections

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, PipelineConfig, TrainingConfig,
)
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.parallel.api import init_sharded_state, make_train_step
from picotron_tpu.parallel.mpmd import (
    SCHEDULES, build_schedule, mpmd_microbatch_losses,
    pipeline_bubble_fraction, schedule_stats,
)


# ---------------------------------------------------------------------------
# schedule table
# ---------------------------------------------------------------------------


def check_schedule(table, kind, n_micro, pp, v=1):
    """Structural validity: right op multiset, one op per (group, tick),
    round-robin placement, and every dependency edge respected."""
    V = pp * (v if kind == "interleaved" else 1)
    split = kind == "zb"
    by_kind = collections.Counter(op.op for op in table)
    assert by_kind["F"] == n_micro * V
    if split:
        assert by_kind["BX"] == by_kind["BW"] == n_micro * V
    else:
        assert by_kind["B"] == n_micro * V

    seen = set()
    for op in table:
        assert op.group == op.vstage % pp  # round-robin chunk placement
        assert (op.tick, op.group) not in seen  # one op per group per tick
        seen.add((op.tick, op.group))

    done = {}  # (op_kind, mb, vstage) -> completion tick
    for op in table:
        k = "B" if op.op == "BX" else op.op
        if op.op == "F" and op.vstage > 0:
            assert done[("F", op.mb, op.vstage - 1)] <= op.tick
        if op.op in ("B", "BX"):
            assert done[("F", op.mb, op.vstage)] <= op.tick
            if op.vstage < V - 1:
                assert done[("B", op.mb, op.vstage + 1)] <= op.tick
        if op.op == "BW":
            assert done[("B", op.mb, op.vstage)] <= op.tick
        done[(k, op.mb, op.vstage)] = op.tick + 1


@pytest.mark.parametrize("kind", SCHEDULES)
@pytest.mark.parametrize("n_micro,pp", [(8, 4), (4, 2), (5, 3)])
def test_schedule_table_valid(kind, n_micro, pp):
    v = 2 if kind == "interleaved" else 1
    check_schedule(build_schedule(kind, n_micro, pp, v), kind, n_micro,
                   pp, v)


def test_1f1b_canonical_makespan():
    """The greedy simulator must reproduce the canonical 1F1B makespan,
    2*n_micro + 2*(pp-1) chunk-op ticks, not merely *a* valid schedule."""
    for n, pp in [(8, 4), (4, 2), (16, 4), (4, 4)]:
        s = schedule_stats("1f1b", n, pp)
        assert s["ticks"] == 2 * n + 2 * (pp - 1), (n, pp, s)
        assert s["bubble_units"] == pytest.approx(pp - 1)


# -- edge shapes (the satellite's explicit list) ----------------------------


def test_schedule_n_micro_less_than_pp():
    """n_micro < pp: fewer microbatches than stages — the table must stay
    valid and simply drain early (bubble-dominated, but correct)."""
    for kind in SCHEDULES:
        v = 2 if kind == "interleaved" else 1
        check_schedule(build_schedule(kind, 2, 4, v), kind, 2, 4, v)
    s = schedule_stats("1f1b", 2, 4)
    assert s["ticks"] == 2 * 2 + 2 * 3
    assert s["bubble_fraction"] > 0.5  # mostly bubble, honestly priced


def test_schedule_n_micro_one():
    """n_micro == 1: a single microbatch walks straight down and back up —
    V forwards then V backwards, zero overlap possible."""
    for kind in ("1f1b", "gpipe"):
        table = build_schedule(kind, 1, 4)
        check_schedule(table, kind, 1, 4)
        ops = [(op.op, op.vstage) for op in sorted(table,
                                                   key=lambda o: o.tick)]
        assert ops == [("F", j) for j in range(4)] + \
            [("B", j) for j in reversed(range(4))]


def test_schedule_pp_one_passthrough():
    """pp == 1: no pipeline — an alternating F/B stream (gpipe: all F then
    all B) with zero bubble; the executor degenerates to plain microbatch
    accumulation."""
    for kind in ("1f1b", "gpipe", "zb"):
        table = build_schedule(kind, 4, 1)
        check_schedule(table, kind, 4, 1)
        # one op per tick, no idle ticks anywhere
        ticks = sorted(op.tick for op in table)
        assert ticks == list(range(len(table)))
        assert schedule_stats(kind, 4, 1)["bubble_units"] == \
            pytest.approx(0.0)


def test_schedule_rejects_bad_args():
    with pytest.raises(ValueError, match="unknown schedule"):
        build_schedule("afab", 4, 2)
    with pytest.raises(ValueError, match="n_micro >= 1"):
        build_schedule("1f1b", 0, 2)
    with pytest.raises(ValueError, match="only applies"):
        build_schedule("1f1b", 4, 2, interleave=2)


def test_schedule_ranking_at_pp4():
    """The tick accounting the planner and bench report: at pp=4, n=8 the
    spmd twin's full-price bubble (6 units) dominates 1f1b (3), interleaved
    v=2 beats 1f1b (2.5), and the zero-bubble split beats both (1)."""
    n, pp = 8, 4
    b = {k: schedule_stats(k, n, pp, 2 if k == "interleaved" else 1)
         ["bubble_units"] for k in ("spmd", "1f1b", "gpipe", "interleaved",
                                    "zb")}
    assert b["spmd"] == pytest.approx(6.0)
    assert b["1f1b"] == pytest.approx(3.0)
    assert b["interleaved"] < b["1f1b"]
    assert b["zb"] < b["interleaved"]


def test_pipeline_bubble_fraction_from_config():
    base = dict(
        model=ModelConfig(dtype="float32", hidden_size=64,
                          num_attention_heads=8, num_key_value_heads=4),
        training=TrainingConfig(seq_length=32, micro_batch_size=1,
                                gradient_accumulation_steps=8),
    )
    flat = Config(distributed=DistributedConfig(), **base)
    assert pipeline_bubble_fraction(flat) == 0.0
    spmd = Config(distributed=DistributedConfig(pp_size=4), **base)
    assert pipeline_bubble_fraction(spmd) == pytest.approx(6.0 / 14.0)
    mpmd = Config(distributed=DistributedConfig(pp_size=4),
                  pipeline=PipelineConfig(executor="mpmd"), **base)
    assert pipeline_bubble_fraction(mpmd) == pytest.approx(
        schedule_stats("1f1b", 8, 4)["bubble_fraction"])
    assert pipeline_bubble_fraction(mpmd) < pipeline_bubble_fraction(spmd)


# ---------------------------------------------------------------------------
# config validation fence
# ---------------------------------------------------------------------------


def mpmd_cfg(pp=2, dp=1, tp=1, gas=4, interleave=1, schedule="1f1b",
             remat=False, layers=4, **train_kw):
    return Config(
        distributed=DistributedConfig(pp_size=pp, dp_size=dp, tp_size=tp),
        model=ModelConfig(dtype="float32", hidden_size=64,
                          num_hidden_layers=layers, num_attention_heads=8,
                          num_key_value_heads=4),
        training=TrainingConfig(seq_length=32, micro_batch_size=2,
                                gradient_accumulation_steps=gas,
                                learning_rate=1e-3, remat=remat, **train_kw),
        pipeline=PipelineConfig(executor="mpmd", schedule=schedule,
                                interleave=interleave),
    )


def test_mpmd_config_validation():
    mpmd_cfg().validate()  # the happy path
    mpmd_cfg(pp=2, interleave=2, schedule="interleaved").validate()
    with pytest.raises(ValueError, match="pp_size >= 2"):
        mpmd_cfg(pp=1).validate()
    with pytest.raises(ValueError, match="optimizer"):
        mpmd_cfg(optimizer_offload=True).validate()
    with pytest.raises(ValueError, match="interleave >= 2"):
        mpmd_cfg(schedule="interleaved").validate()
    with pytest.raises(ValueError, match="divide"):
        # 4 layers over pp=2 -> 2 slots per group; v=3 cannot divide it
        mpmd_cfg(interleave=3, schedule="interleaved").validate()
    with pytest.raises(ValueError, match="executor"):
        Config(distributed=DistributedConfig(pp_size=2),
               pipeline=PipelineConfig(executor="simd")).validate()


# ---------------------------------------------------------------------------
# parity with the SPMD twin
# ---------------------------------------------------------------------------


def batch_for(cfg, menv, key=0):
    t = cfg.training
    b_global = t.micro_batch_size * cfg.distributed.dp_size
    toks = jax.random.randint(
        jax.random.key(key),
        (t.gradient_accumulation_steps, b_global, t.seq_length + 1),
        0, cfg.model.vocab_size)
    sh = NamedSharding(menv.mesh, P(None, "dp", "cp"))
    return (jax.device_put(toks[..., :-1], sh),
            jax.device_put(toks[..., 1:], sh))


def run_steps(cfg, steps=3):
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)
    batch = batch_for(cfg, menv)
    losses = []
    for _ in range(steps):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    return losses, state


def spmd_twin(cfg):
    import dataclasses

    return dataclasses.replace(cfg, pipeline=PipelineConfig())


def assert_parity(cfg_mpmd, steps=3, rtol=1e-5, param_atol=1e-4):
    l_m, s_m = run_steps(cfg_mpmd, steps)
    l_s, s_s = run_steps(spmd_twin(cfg_mpmd), steps)
    np.testing.assert_allclose(l_m, l_s, rtol=rtol, atol=1e-6)
    for name in ("embedding", "lm_head"):
        np.testing.assert_allclose(
            np.asarray(s_m.params[name]), np.asarray(s_s.params[name]),
            rtol=2e-3, atol=param_atol)
    np.testing.assert_allclose(
        np.asarray(s_m.params["layers"]["q"]),
        np.asarray(s_s.params["layers"]["q"]), rtol=2e-3, atol=param_atol)


def test_mpmd_matches_spmd_pp2_dp2():
    """The acceptance pin: the MPMD executor's host-driven schedule must
    train identically to the SPMD lockstep scan (same math, different
    dispatch) with dp grad sync in the finish program."""
    assert_parity(mpmd_cfg(pp=2, dp=2, gas=4))


@pytest.mark.slow
def test_mpmd_matches_spmd_pp4_interleaved_remat():
    """pp=4, interleaved v=2 (8 virtual stage programs), remat'd stage
    bodies, odd n_micro — the deep end of the schedule space."""
    assert_parity(mpmd_cfg(pp=4, dp=2, gas=3, interleave=2,
                           schedule="interleaved", layers=8, remat=True))


@pytest.mark.slow
def test_mpmd_matches_spmd_tp_x_pp():
    """tp x pp: stage programs run on tp-sharded submeshes; the boundary
    device_puts carry tp-sharded activations between stage meshes. Step-1
    losses match at 1e-5; later steps drift a few e-4 because the tp psum
    reduction order differs between the per-stage programs and the twin's
    single lowering, and adam's rescaling amplifies it (near-zero grad
    elements can flip sign, moving a handful of params by ~lr*steps)."""
    assert_parity(mpmd_cfg(pp=2, tp=2, dp=2, gas=4), rtol=5e-4,
                  param_atol=2e-3)


def test_mpmd_per_microbatch_losses_match_spmd():
    """Per-microbatch forward parity (not just the step-mean): each
    microbatch's (nll, count) through the per-stage programs must match a
    replicated single-device forward of the same params."""
    cfg = mpmd_cfg(pp=2, dp=2, gas=4)
    cfg.validate()
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    batch = batch_for(cfg, menv)
    nll, cnt = mpmd_microbatch_losses(cfg, menv, state.params, batch)
    assert nll.shape == (4,) and cnt.shape == (4,)

    # reference: an unsharded single-program forward of the same params
    from picotron_tpu.models.llama import loss_sum_count

    params_g = jax.tree.map(np.asarray, state.params)
    ids, tgt = jax.tree.map(np.asarray, batch)
    for m in range(cfg.training.gradient_accumulation_steps):
        ref_nll, ref_cnt, _ = loss_sum_count(params_g, ids[m], tgt[m],
                                             cfg.model)
        np.testing.assert_allclose(nll[m], float(ref_nll), rtol=2e-4)
        assert cnt[m] == int(ref_cnt)


# ---------------------------------------------------------------------------
# per-stage compile-once proof
# ---------------------------------------------------------------------------


def test_mpmd_stage_programs_proven_compile_once():
    from picotron_tpu.analysis.variants import prove_mpmd_stages

    cfg = mpmd_cfg(pp=2, dp=2, gas=4)
    cfg.validate()
    rep = prove_mpmd_stages(cfg)
    assert rep.ok(), rep.render(verbose=True)
    info = rep.info["variants"]
    assert info["proven"] and info["programs"] == 4  # 2 stages x fwd/bwd
    for entry, sub in info["entries"].items():
        assert sub["proven"], (entry, sub)


@pytest.mark.slow
def test_mpmd_stage_programs_proven_interleaved():
    from picotron_tpu.analysis.variants import prove_mpmd_stages

    cfg = mpmd_cfg(pp=2, dp=2, gas=4, interleave=2, schedule="interleaved")
    cfg.validate()
    rep = prove_mpmd_stages(cfg)
    assert rep.ok(), rep.render(verbose=True)
    assert rep.info["variants"]["programs"] == 8  # 4 virtual stages x f/b


# ---------------------------------------------------------------------------
# elastic pp resize: schedule rebuild + mid-schedule fault surface
# ---------------------------------------------------------------------------


def test_schedule_rebuild_across_stage_counts():
    """The elastic pp-resize contract: the schedule table derives purely
    from (schedule, n_micro, pp) config — a resized run rebuilds a valid
    table for the new stage count with no carried state, at every stage
    count the resize saga visits."""
    for pp in (2, 4):
        for n in (2, 4, 8):
            table = build_schedule("1f1b", n, pp)
            check_schedule(table, "1f1b", n, pp)
            assert len({op.group for op in table}) == pp


class _FakeStage:
    """Host-side stand-in for _StagePrograms (first/last of a pp=2
    pipeline): numpy math, no compiled programs — lets the schedule-walk
    mechanics (buffer lifecycle, heartbeats, chaos ticks, the orphan
    diagnostic) run without building a mesh."""

    x_sharding = None

    def __init__(self, first, last):
        self.first, self.last = first, last

    def fwd(self, params, *a):
        if self.first:
            return np.float32(1.0)  # boundary activation
        nll_acc, cnt_acc = a[-2], a[-1]  # last: (x, tgt, idx, nll, cnt)
        return (np.float32(0.5), np.int32(4),
                nll_acc + np.float32(0.5), cnt_acc + np.int32(4))

    def bwd(self, params, *a):
        acc = a[-1]
        if self.first:  # (ids, idx, g_in, acc) -> acc
            return acc + 1
        return acc + 1, np.float32(0.1)  # (x, tgt, idx, acc) -> acc, g_x


def _fake_walk(table, step=None):
    from picotron_tpu.parallel import mpmd

    stages = [_FakeStage(True, False), _FakeStage(False, True)]
    return mpmd._run_schedule(
        stages, table, [None, None], [0, 0],
        (np.float32(0.0), np.int32(0)), None, None, [0, 1], [0, 1],
        step=step)


def test_schedule_walk_names_orphaned_buffers():
    """A truncated table (the final stage-0 backward dropped) leaves its
    inbound cotangent live: the walk must raise the named diagnostic
    listing exactly the orphaned (vstage, mb) keys — not a bare assert."""
    from picotron_tpu.parallel import mpmd

    table = build_schedule("1f1b", 2, 2)
    accs, nll, cnt, _, _ = _fake_walk(table)  # full table: clean walk
    assert accs == [2, 2] and float(nll) == 1.0 and int(cnt) == 8

    drop = max(i for i, op in enumerate(table)
               if op.op == "B" and op.vstage == 0)
    mb = table[drop].mb
    with pytest.raises(mpmd.ScheduleBufferError) as exc:
        _fake_walk(table[:drop] + table[drop + 1:])
    msg = str(exc.value)
    assert "live boundary buffer" in msg
    assert f"cotangent (vstage=0, mb={mb})" in msg


def test_sigterm_mid_walk_drains_to_step_boundary():
    """A SIGTERM delivered at a named (stage, tick, op) inside the walk
    only sets the preemption flag — the walk drains to the step boundary
    and returns complete accumulators, so the emergency checkpoint the
    driver then writes never sees half-accumulated grads."""
    from picotron_tpu.resilience import chaos
    from picotron_tpu.resilience.preemption import PreemptionHandler

    table = build_schedule("1f1b", 2, 2)
    tick = table[len(table) // 2].tick  # a mid-walk tick
    chaos.install(f"sigterm@7#{tick}")
    try:
        with PreemptionHandler() as ph:
            accs, nll, cnt, _, _ = _fake_walk(table, step=7)
            assert ph.triggered  # the signal landed mid-walk...
        # ...but the walk drained: full gradient accumulation, every
        # boundary buffer consumed (no ScheduleBufferError)
        assert accs == [2, 2] and int(cnt) == 8
    finally:
        chaos.install("")


def test_watchdog_beat_names_live_schedule_op():
    """Each dispatched op heartbeats the armed watchdog with a phase
    naming the live (stage, tick, op, mb) — a mid-schedule stall is
    reported as that op, not a bare stack dump."""
    import re

    from picotron_tpu.resilience import watchdog

    w = watchdog.Watchdog(timeout=60.0)
    w.start()
    try:
        _fake_walk(build_schedule("1f1b", 2, 2), step=3)
        _t, phase, step = w._last
        assert re.fullmatch(r"pp_schedule stage=\d+ tick=\d+ op=\w+ mb=\d+",
                            phase), phase
        assert step == 3
    finally:
        w.stop()
