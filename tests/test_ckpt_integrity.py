"""Checkpoint lineage integrity (picotron_tpu/ckpt_integrity +
checkpoint.py surgery): commit manifests and verification, corruption
chaos kinds, lineage fallback in latest_valid_step, retention GC,
save-dir preflight, probe-failure telemetry routing, and the
tools/ckpt_doctor.py fsck CLI."""

import json
import os
import sys

import jax.numpy as jnp
import pytest

from picotron_tpu.checkpoint import CheckpointManager
from picotron_tpu.ckpt_integrity import (
    MANIFEST_NAME, atomic_write_text, build_manifest, preflight_save_dir,
    retention_plan, verify_step_dir, write_manifest,
)
from picotron_tpu.config import config_from_dict
from picotron_tpu.resilience import chaos
from picotron_tpu.telemetry import bus
from picotron_tpu.train_step import TrainState

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _hygiene():
    """Chaos and the telemetry bus are process-global; start/end inert."""
    chaos.install("")
    bus.install(None)
    yield
    chaos.install("")
    bus.install(None)


class _RecordingTelemetry:
    """Minimal bus target: records every emitted event."""

    def __init__(self):
        self.events = []

    def emit(self, kind, *, category=None, secs=None, **fields):
        self.events.append({"kind": kind, **fields})

    def kinds(self):
        return [e["kind"] for e in self.events]


def _toy_state(step):
    return TrainState(params={"w": jnp.arange(512.0) + step},
                      opt_state={"m": jnp.zeros(512)},
                      step=jnp.asarray(step, jnp.int32))


def _mgr(tmp_path, **ckpt_overrides):
    ck = {"save_dir": str(tmp_path / "ckpt"), "save_frequency": 1,
          "async_save": False, **ckpt_overrides}
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": ck,
        "resilience": {"retry_base_delay": 0.01, "retry_max_delay": 0.02},
    })
    return CheckpointManager(cfg)


def _step_dir(mgr, step):
    return os.path.join(mgr.directory, f"step_{step:08d}")


def _largest_state_file(step_dir):
    best, size = None, -1
    for root, _dirs, files in os.walk(os.path.join(step_dir, "state")):
        for f in files:
            p = os.path.join(root, f)
            if os.path.getsize(p) > size:
                best, size = p, os.path.getsize(p)
    return best


# ---------------------------------------------------------------------------
# manifest build / verify
# ---------------------------------------------------------------------------


def test_save_writes_manifest_and_verifies(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(3), trained_tokens=30)
    sd = _step_dir(mgr, 3)
    man = json.load(open(os.path.join(sd, MANIFEST_NAME)))
    assert man["step"] == 3 and man["file_count"] == len(man["files"])
    assert "meta.json" in man["files"]  # the sidecar is covered too
    assert any(r.startswith("state/") for r in man["files"])
    assert man["total_bytes"] == sum(f["bytes"] for f in man["files"].values())
    assert man["topology"]["world_size"] == 1
    res = mgr.verify_step(3)
    assert res.status == "verified" and res.ok and not res.failures


def test_async_save_commits_manifest_after_barrier(tmp_path):
    mgr = _mgr(tmp_path, async_save=True)
    mgr.save(_toy_state(1), trained_tokens=10)
    mgr.wait_until_finished()  # joins the commit thread too
    assert os.path.exists(os.path.join(_step_dir(mgr, 1), MANIFEST_NAME))
    assert mgr.latest_valid_step() == 1


def test_bitflip_detected_and_names_the_file(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(1))
    sd = _step_dir(mgr, 1)
    victim = _largest_state_file(sd)
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        b = f.read(1)
        f.seek(os.path.getsize(victim) // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    res = mgr.verify_step(1)
    assert res.status == "corrupt"
    rel = os.path.relpath(victim, sd).replace(os.sep, "/")
    assert any(rel in f and "digest" in f for f in res.failures)


def test_truncation_detected_even_shallow(tmp_path):
    """Size checks alone (deep=False) catch truncation/deletion — the
    cheap triage mode ckpt_doctor --shallow uses."""
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(1))
    victim = _largest_state_file(_step_dir(mgr, 1))
    os.truncate(victim, os.path.getsize(victim) // 2)
    res = mgr.verify_step(1, deep=False)
    assert res.status == "corrupt"
    assert any("size" in f for f in res.failures)


def test_torn_manifest_is_corrupt(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(1))
    mp = os.path.join(_step_dir(mgr, 1), MANIFEST_NAME)
    data = open(mp, "rb").read()
    open(mp, "wb").write(data[: len(data) // 2])
    res = mgr.verify_step(1)
    assert res.status == "corrupt"
    assert any(MANIFEST_NAME in f for f in res.failures)


def test_legacy_checkpoint_without_manifest_stays_restorable(tmp_path):
    """Pre-lineage checkpoints (no manifest) must not be orphaned by the
    upgrade: durable + parseable meta.json => restorable ("legacy"), but
    never ranked "verified"."""
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(2), trained_tokens=20)
    os.remove(os.path.join(_step_dir(mgr, 2), MANIFEST_NAME))
    res = mgr.verify_step(2)
    assert res.status == "legacy" and res.ok
    assert mgr.latest_valid_step() == 2
    restored, meta = mgr.restore(_toy_state(0))
    assert int(restored.step) == 2 and meta["trained_tokens"] == 20


def test_atomic_write_leaves_no_tmp_and_replaces(tmp_path):
    p = str(tmp_path / "meta.json")
    atomic_write_text(p, '{"a": 1}')
    atomic_write_text(p, '{"a": 2}')
    assert json.load(open(p)) == {"a": 2}
    assert os.listdir(tmp_path) == ["meta.json"]  # no .tmp.* residue


def test_manifest_skips_tmp_staging_files(tmp_path):
    d = tmp_path / "step"
    (d / "state").mkdir(parents=True)
    (d / "state" / "payload").write_bytes(b"x" * 100)
    (d / "meta.json").write_text("{}")
    (d / "meta.json.tmp.123").write_text("{")  # in-flight staging junk
    man = build_manifest(str(d), step=1)
    assert set(man["files"]) == {"meta.json", "state/payload"}
    write_manifest(str(d), man)
    assert verify_step_dir(str(d)).status == "verified"


# ---------------------------------------------------------------------------
# chaos corruption kinds
# ---------------------------------------------------------------------------


def test_chaos_spec_accepts_corruption_and_kill_kinds():
    evs = chaos.parse_spec("ckpt_corrupt_bitflip@4,ckpt_truncate@6,"
                           "ckpt_torn_meta@8,kill@5")
    assert [e.kind for e in evs] == [
        "ckpt_corrupt_bitflip", "ckpt_truncate", "ckpt_torn_meta", "kill"]


@pytest.mark.parametrize("kind,expect_fragment", [
    ("ckpt_corrupt_bitflip", "digest"),
    ("ckpt_truncate", "size"),
    ("ckpt_torn_meta", "meta.json"),
])
def test_chaos_corruption_kinds_break_verification(tmp_path, kind,
                                                   expect_fragment):
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(4), trained_tokens=40)
    assert mgr.verify_step(4).status == "verified"
    ctrl = chaos.ChaosController(chaos.parse_spec(f"{kind}@4"))
    ctrl.fire("ckpt_committed", step=4, path=_step_dir(mgr, 4))
    ctrl.fire("ckpt_committed", step=4, path=_step_dir(mgr, 4))  # exhausted
    res = mgr.verify_step(4)
    assert res.status == "corrupt"
    assert any(expect_fragment in f for f in res.failures), res.failures


def test_chaos_corruption_wrong_point_or_step_noop(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(4))
    ctrl = chaos.ChaosController(chaos.parse_spec("ckpt_corrupt_bitflip@4"))
    ctrl.fire("step_begin", step=4)  # corruption kinds bind ckpt_committed
    ctrl.fire("ckpt_committed", step=3, path=_step_dir(mgr, 4))
    assert mgr.verify_step(4).status == "verified"


def test_end_to_end_chaos_corruption_during_commit(tmp_path):
    """The full injection path: a chaos spec installed process-wide
    corrupts the checkpoint as its commit completes (the ckpt_committed
    fire inside CheckpointManager._commit), and the next save's lineage
    walk falls back over it."""
    mgr = _mgr(tmp_path)
    chaos.install("ckpt_corrupt_bitflip@2")
    mgr.save(_toy_state(1), trained_tokens=10)
    mgr.save(_toy_state(2), trained_tokens=20)  # corrupted at commit
    assert mgr.verify_step(1).status == "verified"
    assert mgr.verify_step(2).status == "corrupt"
    assert mgr.latest_valid_step() == 1
    restored, meta = mgr.restore(_toy_state(0))
    assert int(restored.step) == 1 and meta["trained_tokens"] == 10


# ---------------------------------------------------------------------------
# lineage fallback + telemetry events
# ---------------------------------------------------------------------------


def test_latest_valid_step_walks_lineage_and_emits_ckpt_corrupt(tmp_path):
    tel = _RecordingTelemetry()
    bus.install(tel)
    mgr = _mgr(tmp_path)
    for s in (2, 4):
        mgr.save(_toy_state(s), trained_tokens=10 * s)
    victim = _largest_state_file(_step_dir(mgr, 4))
    os.remove(victim)  # valid manifest, deleted array file
    assert mgr.latest_valid_step() == 2
    corrupt = [e for e in tel.events if e["kind"] == "ckpt_corrupt"]
    assert corrupt and corrupt[0]["step"] == 4
    assert any("missing" in f for f in corrupt[0]["failures"])


def test_probe_failure_routed_through_bus(tmp_path):
    """_probe_failed is an event stream citizen now, not just a stderr
    warning: flaky-store noise must be countable in telemetry_report."""
    tel = _RecordingTelemetry()
    bus.install(tel)
    mgr = _mgr(tmp_path)
    mgr.save(_toy_state(1))

    class BrokenUtils:
        @staticmethod
        def is_checkpoint_finalized(path):
            raise RuntimeError("metadata service melted")

    class FakeOcp:
        utils = BrokenUtils

    mgr._ocp = FakeOcp
    with pytest.warns(UserWarning, match="durability probe"):
        assert mgr.latest_step() is None
    probe = [e for e in tel.events if e["kind"] == "ckpt_probe_failed"]
    assert probe and "melted" in probe[0]["error"]


# ---------------------------------------------------------------------------
# retention policy + GC
# ---------------------------------------------------------------------------


def test_retention_plan_policy():
    steps = list(range(1, 11))
    keep, delete = retention_plan(steps, keep_last=0)
    assert (keep, delete) == (steps, [])  # 0 disables GC
    keep, delete = retention_plan(steps, keep_last=2)
    assert keep == [9, 10] and delete == list(range(1, 9))
    keep, delete = retention_plan(steps, keep_last=2, keep_every=5)
    assert keep == [5, 9, 10]
    keep, delete = retention_plan(steps, keep_last=1, protect=[3, 99])
    assert keep == [3, 10]  # protect applies only to existing steps
    assert 99 not in keep + delete
    assert sorted(keep + delete) == steps


def test_gc_keep_last_2_over_10_saves(tmp_path):
    """The acceptance criterion: keep_last=2 over a 10-save run leaves
    exactly the expected step dirs after every prune, and
    latest_valid_step resolves throughout."""
    mgr = _mgr(tmp_path, keep_last=2)
    for s in range(1, 11):
        mgr.save(_toy_state(s), trained_tokens=s)
        expect = {f"step_{x:08d}" for x in (s - 1, s) if x >= 1}
        assert set(os.listdir(mgr.directory)) == expect
        assert mgr.latest_valid_step() == s
    restored, _ = mgr.restore(_toy_state(0))
    assert int(restored.step) == 10


def test_gc_keep_every_pins_anchor_steps(tmp_path):
    mgr = _mgr(tmp_path, keep_last=1, keep_every=4)
    for s in range(1, 10):
        mgr.save(_toy_state(s))
    assert [int(d.split("_")[1]) for d in sorted(os.listdir(mgr.directory))] \
        == [4, 8, 9]


def test_gc_never_deletes_last_verified_even_keep_last_1(tmp_path):
    """keep_last=1 with a corrupt newest step: the last verified
    checkpoint is the only restore fallback and must survive GC."""
    mgr = _mgr(tmp_path, keep_last=0)  # build the lineage without pruning
    for s in (1, 2, 3):
        mgr.save(_toy_state(s), trained_tokens=10 * s)
    os.truncate(_largest_state_file(_step_dir(mgr, 3)), 1)  # corrupt newest
    aggressive = _mgr(tmp_path, keep_last=1)
    res = aggressive.gc()
    # keep_last=1 keeps the newest (3, corrupt); the protection clause
    # keeps 2 (last verified); 1 is pruned
    assert res["kept"] == [2, 3] and res["deleted"] == [1]
    assert sorted(os.listdir(mgr.directory)) == [
        "step_00000002", "step_00000003"]
    assert aggressive.latest_valid_step() == 2
    restored, meta = aggressive.restore(_toy_state(0))
    assert int(restored.step) == 2 and meta["trained_tokens"] == 20


def test_gc_dry_run_deletes_nothing(tmp_path):
    mgr = _mgr(tmp_path, keep_last=0)  # in-save GC disabled
    for s in (1, 2, 3):
        mgr.save(_toy_state(s))
    mgr3 = _mgr(tmp_path, keep_last=1)
    plan = mgr3.gc(dry_run=True)
    assert plan["deleted"] == [1, 2]
    assert len(os.listdir(mgr.directory)) == 3  # untouched


# ---------------------------------------------------------------------------
# save-dir preflight
# ---------------------------------------------------------------------------


def test_preflight_ok_on_writable_dir(tmp_path):
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(tmp_path / "ckpt"),
                       "save_frequency": 2},
    })
    est = preflight_save_dir(cfg)
    assert est > 0
    assert os.path.isdir(tmp_path / "ckpt")
    assert not os.listdir(tmp_path / "ckpt")  # probe file cleaned up


def test_preflight_rejects_uncreatable_save_dir(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("i am a file")
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(blocker / "ckpt"),
                       "save_frequency": 2},
    })
    with pytest.raises(RuntimeError, match="cannot be created"):
        preflight_save_dir(cfg)


def test_preflight_rejects_insufficient_headroom(tmp_path, monkeypatch):
    import shutil as _shutil

    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(tmp_path / "ckpt"),
                       "save_frequency": 2, "keep_last": 4},
    })
    monkeypatch.setattr(_shutil, "disk_usage",
                        lambda p: type("DU", (), {"free": 10})())
    with pytest.raises(RuntimeError, match="GB free"):
        preflight_save_dir(cfg)


def test_preflight_skips_url_stores():
    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": "gs://nonexistent-bucket/ckpt",
                       "save_frequency": 2},
    })
    assert preflight_save_dir(cfg) > 0  # no local probe, estimate only


# ---------------------------------------------------------------------------
# tools/ckpt_doctor.py
# ---------------------------------------------------------------------------


def _load_doctor():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ckpt_doctor", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "ckpt_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_ckpt_doctor_flags_exactly_the_corrupt_step(tmp_path, capsys):
    mgr = _mgr(tmp_path)
    for s in (2, 4, 6):
        mgr.save(_toy_state(s), trained_tokens=s)
    victim = _largest_state_file(_step_dir(mgr, 4))
    with open(victim, "r+b") as f:
        f.seek(0)
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    doctor = _load_doctor()
    rows = doctor.scan(mgr.directory)
    assert [(r["step"], r["verdict"]) for r in rows] == [
        (2, "verified"), (4, "corrupt"), (6, "verified")]
    assert doctor.main([mgr.directory, "--json"]) == 1  # corrupt => exit 1
    out = json.loads(capsys.readouterr().out)
    assert [r["verdict"] for r in out["steps"]] == [
        "verified", "corrupt", "verified"]
    # markdown render smoke
    assert doctor.main([mgr.directory, "--markdown"]) == 1
    md = capsys.readouterr().out
    assert "| 4 | corrupt |" in md


def test_ckpt_doctor_gc_dry_run_then_apply(tmp_path, capsys):
    mgr = _mgr(tmp_path)
    for s in range(1, 6):
        mgr.save(_toy_state(s))
    doctor = _load_doctor()
    assert doctor.main([mgr.directory, "--gc", "--keep-last", "2",
                        "--dry-run"]) == 0
    assert len(os.listdir(mgr.directory)) == 5  # dry run: untouched
    assert doctor.main([mgr.directory, "--gc", "--keep-last", "2"]) == 0
    assert sorted(os.listdir(mgr.directory)) == [
        "step_00000004", "step_00000005"]
    assert mgr.latest_valid_step() == 5
