"""Layout-planner tests: the search must rank layouts deterministically,
hold the global batch constant across candidates, prune HBM non-fits, and
— the acceptance bar — never propose a config tools/memcheck.py rejects.
Also the fast-tier smoke the CI satellite asks for: the cost model priced
over every preset in runs/ (analytic — a cost-model regression breaks
tier-1, not a fleet decision)."""

import importlib.util
import json
import math
import os

import pytest

from picotron_tpu.analysis.cost_model import CostModel
from picotron_tpu.analysis.planner import (
    best_point, candidate_configs, estimate_hbm_gib, plan, planner_gap,
    reprice_traced, verify_hbm,
)
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig, load_config,
    resolve_preset,
)

ROOT = os.path.join(os.path.dirname(__file__), "..")


def tiny_base(ga=8, mbs=1, seq=64, model="debug-tiny"):
    cfg = Config(
        distributed=DistributedConfig(),
        model=ModelConfig(name=model, **resolve_preset(model)),
        training=TrainingConfig(seq_length=seq, micro_batch_size=mbs,
                                gradient_accumulation_steps=ga),
    )
    cfg.validate()
    return cfg


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# enumeration + ranking
# ---------------------------------------------------------------------------


def test_candidates_cover_axes_and_hold_global_batch():
    base = tiny_base(ga=8)
    gb = base.global_batch_size
    cands = candidate_configs(base, 8)
    assert len(cands) > 20
    layouts = {(c.distributed.dp_size, c.distributed.tp_size,
                c.distributed.pp_size, c.distributed.cp_size)
               for c in cands}
    assert (8, 1, 1, 1) in layouts and (1, 2, 4, 1) in layouts
    for c in cands:
        assert c.distributed.world_size == 8
        assert c.global_batch_size == gb, c
    # ep > 1 requires a MoE model: no dense candidate may carry it
    assert all(c.distributed.ep_size == 1 for c in cands)
    moe = candidate_configs(tiny_base(model="debug-tiny-moe"), 8)
    assert any(c.distributed.ep_size > 1 for c in moe)


def test_invalid_layouts_are_skipped():
    # debug-tiny has 4 heads / 2 kv heads: tp=8 (heads % tp != 0) and
    # pp=8 (> 4 layers) must not appear
    cands = candidate_configs(tiny_base(), 8)
    assert all(c.distributed.tp_size <= 2 for c in cands)
    assert all(c.distributed.pp_size <= 4 for c in cands)


def test_plan_enumerates_mpmd_and_overrides_round_trip():
    """Every pp>1 layout is priced under both executors (plus interleaved
    variants where v divides the per-group layer slots), and an mpmd plan
    point's --override line survives the tools/memcheck.py override
    mechanism: dotted paths into the raw JSON, bare strings for
    string-typed fields like pipeline.executor."""
    from picotron_tpu.config import config_from_dict

    pts = plan(tiny_base(), 8, CostModel("v5e"))
    mpmd_pts = [p for p in pts if "mpmd" in p.label]
    assert mpmd_pts, [p.label for p in pts]
    assert any("interleaved" in p.label for p in mpmd_pts)
    assert any("mpmd-1f1b" in p.label for p in mpmd_pts)

    point = next(p for p in mpmd_pts if "interleaved" in p.label)
    line = point.overrides_line()
    assert "pipeline.executor=mpmd" in line
    assert "pipeline.schedule=interleaved" in line

    # the memcheck --override application: JSON values where they parse,
    # bare strings otherwise (legitimate only for string-typed fields)
    raw = {"model": {"name": "debug-tiny"},
           "training": {"seq_length": 64, "micro_batch_size": 1,
                        "gradient_accumulation_steps": 8}}
    for ov in line.split()[1:]:
        dotted, _, val = ov.partition("=")
        node = raw
        *path, key = dotted.split(".")
        for part in path:
            node = node.setdefault(part, {})
        try:
            node[key] = json.loads(val)
        except ValueError:
            node[key] = val
    cfg = config_from_dict(raw)  # validates
    assert cfg.pipeline.executor == "mpmd"
    assert cfg.pipeline.schedule == "interleaved"
    assert cfg.pipeline.interleave >= 2
    assert cfg.distributed.pp_size == point.cfg.distributed.pp_size


def test_plan_ranks_and_is_deterministic():
    base = tiny_base()
    model = CostModel("v5e")
    pts = plan(base, 8, model)
    assert pts, "8 chips of debug-tiny must have feasible layouts"
    times = [p.cost.total_s for p in pts]
    assert times == sorted(times)
    assert [p.label for p in pts] == [p.label for p in plan(base, 8, model)]
    for p in pts:
        assert p.hbm_fits
        assert math.isfinite(p.cost.total_s) and p.cost.total_s > 0


def test_hbm_prune_rejects_what_cannot_fit():
    base = tiny_base()
    # debug-tiny needs ~MBs; a 1e-5 GiB capacity rejects everything
    assert plan(base, 8, CostModel("v5e"), hbm_gib=1e-5) == []
    pts = plan(base, 8, CostModel("v5e"), hbm_gib=1e-5,
               include_infeasible=True)
    assert pts and not any(p.hbm_fits for p in pts)


def test_estimate_hbm_monotone_in_sharding():
    # more model sharding -> less per-device memory
    whole = estimate_hbm_gib(tiny_base())
    tp2 = estimate_hbm_gib(tiny_base().replace(
        distributed=DistributedConfig(tp_size=2)))
    assert tp2 < whole
    off = estimate_hbm_gib(tiny_base().replace(
        training=TrainingConfig(seq_length=64,
                                optimizer_offload=True)))
    assert off < whole


def test_planner_gap_flags_slow_layout():
    # a deliberately comm-heavy layout of a tiny model must show a
    # positive gap vs the planner's best at the same chip count
    cfg = tiny_base().replace(
        distributed=DistributedConfig(tp_size=2, cp_size=4))
    cur, best, gap = planner_gap(cfg, CostModel("v5e"))
    assert best is not None
    assert gap >= 0.0
    assert best.cost.total_s <= cur.total_s


# ---------------------------------------------------------------------------
# acceptance: memcheck agreement
# ---------------------------------------------------------------------------


def test_winner_passes_memcheck_and_rejected_points_are_skipped():
    """The planner must never propose a config tools/memcheck.py rejects:
    the verified winner's XLA memory breakdown fits the capacity, and a
    capacity below the winner's own footprint forces verify to reject."""
    base = tiny_base(ga=2)
    model = CostModel("v5e")
    pts = plan(base, 8, model)
    winner = best_point(pts, verify=True, hbm_gib=model.gen.hbm_gib,
                        model=model)
    assert winner is not None
    assert winner.memcheck_ok is True
    assert winner.memcheck_gib <= model.gen.hbm_gib
    # the analytic screen agreed with memcheck's verdict on the winner
    assert winner.hbm_fits
    # and a capacity the measured footprint exceeds must flip the verdict
    tight = winner.memcheck_gib / 2
    assert verify_hbm(pts[0], tight) is False
    assert pts[0].memcheck_ok is False


def test_reprice_traced_top_points():
    base = tiny_base(ga=2)
    model = CostModel("v5e")
    pts = plan(base, 8, model)
    pts = reprice_traced(pts, model, top_k=2)
    traced = [p for p in pts if p.traced_comm_s is not None]
    assert len(traced) == 2
    for p in traced:
        assert p.traced_comm_s >= 0


# ---------------------------------------------------------------------------
# CLI + runs/ preset smoke (the fast-tier CI gate)
# ---------------------------------------------------------------------------


def test_cli_plan_chips8(capsys):
    lp = load_tool("layout_planner")
    rc = lp.main(["--chips", "8", "--model", "debug-tiny", "--seq", "64",
                  "--top", "5", "--json"])
    assert rc == 0
    rows = [json.loads(l) for l in
            capsys.readouterr().out.strip().splitlines()]
    assert 1 <= len(rows) <= 5
    assert rows[0]["predicted_step_ms"] > 0
    assert rows[0]["overrides"].startswith("--override ")
    steps = [r["predicted_step_ms"] for r in rows]
    assert steps == sorted(steps)


def test_cli_validate_sweep_reproduces_measured_ranking(capsys):
    """Acceptance: the planner CLI reproduces the measured ranking of the
    SWEEP_r03–r05 configs (per-round Spearman)."""
    lp = load_tool("layout_planner")
    rc = lp.main(["--validate-sweep", "--json"])
    assert rc == 0
    ra = json.loads(capsys.readouterr().out)
    assert ra["min_per_round"] >= 0.85
    assert ra["pooled"] >= 0.85


def test_cli_markdown_table(capsys):
    lp = load_tool("layout_planner")
    rc = lp.main(["--chips", "8", "--model", "debug-tiny", "--seq", "64",
                  "--markdown", "--top", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "| rank | layout |" in out
    assert "predicted fastest:" in out


RUN_PRESETS = sorted(
    d for d in os.listdir(os.path.join(ROOT, "runs"))
    if os.path.isfile(os.path.join(ROOT, "runs", d, "config.json")))


@pytest.mark.parametrize("preset", RUN_PRESETS)
def test_cost_model_prices_every_runs_preset(preset):
    """Analytic smoke over the presets in runs/: every preset must price
    to a finite positive step time with a sane decomposition on both
    shipped generations, and the planner must find a feasible layout at
    the preset's own chip count on its natural generation. Pure
    arithmetic — this is the tier-1 tripwire for cost-model regressions."""
    cfg = load_config(os.path.join(ROOT, "runs", preset, "config.json"))
    gen = "v5p" if "v5p" in preset else "v5e"
    cost = CostModel(gen).predict(cfg)
    assert math.isfinite(cost.total_s) and cost.total_s > 0
    assert cost.compute_s > 0
    assert cost.exposed_comm_s >= 0
    if cfg.distributed.world_size > 1:
        assert cost.comm, f"{preset}: multi-chip layout priced zero comm"
        cur, best, gap = planner_gap(cfg, CostModel(gen))
        assert best is not None, f"{preset}: planner found no layout"
        assert math.isfinite(gap)
        # a negative gap is legal only when the config itself fails the
        # HBM screen (the feasible best can then be slower than an
        # infeasible incumbent)
        from picotron_tpu.analysis.planner import (
            _HBM_MARGIN, estimate_hbm_gib,
        )

        if gap < 0:
            assert estimate_hbm_gib(cfg) > \
                CostModel(gen).gen.hbm_gib * _HBM_MARGIN, preset


def test_shardcheck_cli_cost_smoke(capsys):
    """tools/shardcheck.py --cost over a preset: the costed ranking rides
    the audit report (the CI-wired smoke the ISSUE asks for)."""
    sc = load_tool("shardcheck")
    rc = sc.main(["--preset", "tiny-dense", "--cost", "--json"])
    assert rc == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert row["ok"]
    pc = row["info"]["collectives"]["predicted_comm"]
    assert pc["total_ms"] > 0
    assert row["cost"]["predicted_step_ms"] > 0
    assert row["cost"]["planner_best"]
