"""End-to-end trainer CLI tests: run `train.main()` against a config file on
the simulated mesh — the analogue of the reference's CPU-config integration
story (ref: README.md:40-47 `torchrun ... --use_cpu`). Covers the loop, the
de-facto log-line API, checkpoint save/resume (incl. the dataloader
position), the max_tokens stop condition, and host-side prefetch."""

import json
import re
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # integration tier: run with plain `pytest tests/`; dev loop = -m 'not slow'

sys.path.insert(0, "tools")

from picotron_tpu import train  # noqa: E402
from extract_metrics import LINE_RE  # noqa: E402


def write_cfg(tmp_path, name="cfg.json", **overrides):
    cfg = {
        "distributed": {"dp_size": 2, "tp_size": 2, "use_cpu": True},
        "model": {"name": "debug-tiny", "dtype": "float32"},
        "training": {"total_train_steps": 5, "seq_length": 32,
                     "micro_batch_size": 2,
                     "gradient_accumulation_steps": 2,
                     "remat": False, "seed": 3},
        "dataset": {"name": "synthetic", "num_workers": 0},
        "checkpoint": {"save_dir": str(tmp_path / "ckpt")},
        "logging": {"log_frequency": 1},
    }
    for section, vals in overrides.items():
        cfg.setdefault(section, {}).update(vals)
    path = tmp_path / name
    path.write_text(json.dumps(cfg))
    return str(path)


def run_main(cfg_path, capsys):
    train.main(["--config", cfg_path])
    return capsys.readouterr().out


def test_trainer_cli_end_to_end(tmp_path, capsys):
    out = run_main(write_cfg(tmp_path), capsys)
    rows = [m.groupdict() for line in out.splitlines()
            if (m := LINE_RE.search(line))]
    assert [int(r["step"]) for r in rows] == [1, 2, 3, 4, 5]
    losses = [float(r["loss"]) for r in rows]
    assert all(np.isfinite(losses))
    # synthetic data is random tokens: no real signal, but the first-step
    # loss must start near ln(vocab) and optimization must not diverge
    assert abs(losses[0] - np.log(256)) < 0.5
    assert losses[-1] <= losses[0]
    assert "training done" in out

    # The run's structured twin: telemetry.jsonl next to the checkpoints
    # carries per-phase timings, per-step records matching the console
    # lines, the exact compile split, and a closing run_summary.
    events = [json.loads(line) for line in
              open(tmp_path / "ckpt" / "telemetry.jsonl")]
    steps = [e for e in events if e["kind"] == "step"]
    assert [e["step"] for e in steps] == [1, 2, 3, 4, 5]
    assert [round(e["loss"], 4) for e in steps] == \
        [float(r["loss"]) for r in rows]
    summary = events[-1]
    assert summary["kind"] == "run_summary"
    cats = summary["goodput"]["seconds_by_category"]
    assert cats["compute"] > 0 and cats["compile"] > 0
    phase_steps = {e["step"] for e in events
                   if e["kind"] == "phase" and e["phase"] == "step"}
    assert phase_steps == {1, 2, 3, 4, 5}


def test_trainer_resume_continues_data_and_steps(tmp_path, capsys):
    """Save at step 3, resume, finish at 6: the resumed run must pick up the
    step count, token count, and dataloader position (ADVICE r1: no replay
    of consumed data)."""
    n_samples = 64  # small epoch so cursor arithmetic is exercised
    first = write_cfg(
        tmp_path, name="a.json",
        training={"total_train_steps": 3, "num_samples": n_samples},
        checkpoint={"save_frequency": 3})
    out1 = run_main(first, capsys)
    assert "saved checkpoint" in out1

    meta = json.loads((tmp_path / "ckpt" / "step_00000003" /
                       "meta.json").read_text())
    assert meta["step"] == 3
    # 3 steps x (2 mbs x 2 gas x 2 dp) batches of 32 tokens
    assert meta["trained_tokens"] == 3 * 8 * 32
    assert meta["dataloader"] == {"epoch": 0, "cursor": 24}

    second = write_cfg(
        tmp_path, name="b.json",
        training={"total_train_steps": 6, "num_samples": n_samples},
        checkpoint={"load_path": str(tmp_path / "ckpt"),
                    "save_frequency": 6})
    out2 = run_main(second, capsys)
    rows = [m.groupdict() for line in out2.splitlines()
            if (m := LINE_RE.search(line))]
    assert [int(r["step"]) for r in rows] == [4, 5, 6]
    meta2 = json.loads((tmp_path / "ckpt" / "step_00000006" /
                        "meta.json").read_text())
    assert meta2["trained_tokens"] == 6 * 8 * 32
    assert meta2["dataloader"] == {"epoch": 0, "cursor": 48}


def test_trainer_auto_resume_preemption_recovery(tmp_path, capsys):
    """auto_resume with no load_path: the same config re-run (as after a
    preemption + scheduler resubmission) continues from save_dir's newest
    durable checkpoint; a fresh run (empty save_dir) starts from scratch."""
    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 3},
        checkpoint={"save_frequency": 2, "auto_resume": True})
    out1 = run_main(cfg, capsys)
    rows1 = [int(m.group("step")) for line in out1.splitlines()
             if (m := LINE_RE.search(line))]
    assert rows1 == [1, 2, 3]  # empty save_dir: fresh start

    # "preemption": the process died after the step-2 save; resubmission
    # reruns the identical config with a higher budget
    cfg2 = write_cfg(
        tmp_path, name="resub.json",
        training={"total_train_steps": 5},
        checkpoint={"save_frequency": 2, "auto_resume": True})
    out2 = run_main(cfg2, capsys)
    assert "auto_resume: found checkpoints" in out2
    rows2 = [int(m.group("step")) for line in out2.splitlines()
             if (m := LINE_RE.search(line))]
    assert rows2 == [4, 5]  # resumed at the final step-3 save


def test_ckpt_preflight_fails_fast_on_unwritable_save_dir(tmp_path,
                                                          monkeypatch):
    """A doomed save_dir (here: a file where the directory should go)
    must kill the run during startup preflight — before any compile or
    training — not at the first periodic save."""
    monkeypatch.delenv("PICOTRON_PREFLIGHT", raising=False)
    blocker = tmp_path / "blocker"
    blocker.write_text("i am a file, not a directory")
    cfg = write_cfg(
        tmp_path,
        checkpoint={"save_dir": str(blocker / "ckpt"), "save_frequency": 2})
    with pytest.raises(RuntimeError, match="checkpoint preflight"):
        train.main(["--config", cfg])


def test_trainer_restart_falls_back_over_corrupt_newest_ckpt(tmp_path,
                                                             capsys):
    """In-process twin of the ckpt_corrupt_bitflip chaos scenario: run to
    completion with periodic saves, bit-flip the newest committed
    checkpoint, and re-run with a higher budget — auto_resume must verify,
    emit the fallback, and resume from the prior verified step."""
    import os

    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 4},
        checkpoint={"save_frequency": 2, "auto_resume": True})
    run_main(cfg, capsys)
    # corrupt the newest (step-4) checkpoint's largest array payload
    state_dir = tmp_path / "ckpt" / "step_00000004" / "state"
    victim = max((p for p in state_dir.rglob("*") if p.is_file()),
                 key=lambda p: p.stat().st_size)
    size = victim.stat().st_size
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))

    cfg2 = write_cfg(
        tmp_path, name="resub.json",
        training={"total_train_steps": 6},
        checkpoint={"save_frequency": 2, "auto_resume": True})
    out = run_main(cfg2, capsys)
    rows = [int(m.group("step")) for line in out.splitlines()
            if (m := LINE_RE.search(line))]
    assert rows == [3, 4, 5, 6]  # resumed from step 2, NOT the corrupt 4
    assert "at step 2" in out  # build_state's resume line


def test_trainer_eval_loop(tmp_path, capsys):
    """eval_frequency runs a forward-only validation pass on a disjoint
    synthetic stream and logs val_loss lines; the final step always
    evaluates."""
    import re as _re

    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 5, "eval_frequency": 2,
                  "eval_steps": 2})
    out = run_main(cfg, capsys)
    evals = [(int(m.group(1)), float(m.group(2))) for m in
             _re.finditer(r"\[eval  (\d+)\] val_loss: ([\d.]+)", out)]
    assert [s for s, _ in evals] == [2, 4, 5]
    assert all(np.isfinite(v) and v > 0 for _, v in evals)
    # eval is forward-only on held-out data: values near ln(vocab), and the
    # training stream (memorizable) must not be what eval reads — at these
    # step counts val_loss stays near its starting point
    assert all(abs(v - np.log(256)) < 1.0 for _, v in evals)


def test_trainer_max_tokens_stops_early(tmp_path, capsys):
    # 3 steps' worth of tokens (ceil): 2.5 steps -> stops after step 3
    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 100, "max_tokens": int(2.5 * 8 * 32)})
    out = run_main(cfg, capsys)
    rows = [m.groupdict() for line in out.splitlines()
            if (m := LINE_RE.search(line))]
    assert [int(r["step"]) for r in rows][-1] == 3


def test_trainer_chaos_sigterm_emergency_ckpt_and_lossless_resume(
        tmp_path, capsys):
    """Kill-and-resume, in-process: chaos delivers a real SIGTERM at step
    2; the trainer must finish the in-flight step, write a durable
    emergency checkpoint (dataloader cursor included) even though periodic
    saving is OFF, and exit EXIT_PREEMPTED. The auto_resume rerun must
    continue at step 3 without replaying data, and the combined loss curve
    must equal an uninterrupted run's exactly."""
    from picotron_tpu.resilience import EXIT_PREEMPTED

    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 5},
        checkpoint={"save_frequency": 0, "auto_resume": True},
        resilience={"chaos": "sigterm@2"})
    with pytest.raises(SystemExit) as exc:
        train.main(["--config", cfg])
    assert exc.value.code == EXIT_PREEMPTED
    out1 = capsys.readouterr().out
    assert "emergency checkpoint" in out1
    rows1 = [m.groupdict() for line in out1.splitlines()
             if (m := LINE_RE.search(line))]
    assert [int(r["step"]) for r in rows1] == [1, 2]  # in-flight step done

    meta = json.loads((tmp_path / "ckpt" / "step_00000002" /
                       "meta.json").read_text())
    assert meta["step"] == 2
    assert meta["trained_tokens"] == 2 * 8 * 32
    assert meta["dataloader"] == {"epoch": 0, "cursor": 16}

    # resubmission: same config, chaos spun down (the env override a real
    # supervisor would use)
    import os as _os
    _os.environ["PICOTRON_CHAOS"] = ""
    try:
        out2 = run_main(cfg, capsys)
    finally:
        _os.environ.pop("PICOTRON_CHAOS", None)
    assert "auto_resume: found checkpoints" in out2
    rows2 = [m.groupdict() for line in out2.splitlines()
             if (m := LINE_RE.search(line))]
    assert [int(r["step"]) for r in rows2] == [3, 4, 5]

    # no data replay, no lost state: the stitched curve equals an
    # uninterrupted run of the same config bit-for-bit
    base = write_cfg(tmp_path, name="base.json",
                     training={"total_train_steps": 5},
                     checkpoint={"save_dir": str(tmp_path / "ckpt_base")})
    out_base = run_main(base, capsys)
    base_losses = [float(m.group("loss")) for line in out_base.splitlines()
                   if (m := LINE_RE.search(line))]
    stitched = [float(r["loss"]) for r in rows1 + rows2]
    np.testing.assert_allclose(stitched, base_losses, rtol=1e-6)


def test_trainer_nan_guard_skip_policy_completes(tmp_path, capsys):
    """nan_grad chaos under guard_policy=skip: the poisoned step is
    dropped in-jit, the run completes to the full budget, and the final
    step/token accounting matches a fault-free run."""
    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 5},
        checkpoint={"save_frequency": 5},
        resilience={"chaos": "nan_grad@3", "guard_policy": "skip"})
    out = run_main(cfg, capsys)
    assert "batch skipped" in out
    assert "training done" in out
    meta = json.loads((tmp_path / "ckpt" / "step_00000005" /
                       "meta.json").read_text())
    assert meta["step"] == 5
    assert meta["trained_tokens"] == 5 * 8 * 32


def test_trainer_nan_guard_abort_exits_distinctly(tmp_path, capsys):
    from picotron_tpu.resilience import EXIT_DIVERGED

    cfg = write_cfg(
        tmp_path,
        training={"total_train_steps": 5},
        resilience={"chaos": "nan_grad@2", "guard_policy": "abort"})
    with pytest.raises(SystemExit) as exc:
        train.main(["--config", cfg])
    assert exc.value.code == EXIT_DIVERGED
    assert "aborting" in capsys.readouterr().out


def test_trainer_prefetch_matches_sync(tmp_path, capsys):
    """num_workers > 0 (background prefetch thread) must not change the
    training stream."""
    out_sync = run_main(
        write_cfg(tmp_path, name="s.json", dataset={"num_workers": 0}),
        capsys)
    out_pre = run_main(
        write_cfg(tmp_path, name="p.json", dataset={"num_workers": 2}),
        capsys)

    def losses(out):
        return [float(m.group("loss")) for line in out.splitlines()
                if (m := LINE_RE.search(line))]

    assert losses(out_sync) == losses(out_pre)
