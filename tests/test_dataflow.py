"""Shardflow tests — collective provenance, implicit-reshard detection,
and the static jit-variant prover (analysis/dataflow.py + variants.py).

Three layers, mirroring the acceptance criteria:

- provenance over the real config matrix: >= 90% of the lowered step's
  effective collectives attributed to a source site, zero implicit ops,
  zero predicted boundary reshards, every attributed site explained by an
  intended-schedule rule;
- a deliberately mis-specced fixture (declared P('dp') input consumed
  replicated by a shard_map) both predicted statically (with the spec fix
  named) AND confirmed against the compiled module, where the
  GSPMD-minted all-gather is visible;
- the variant prover: compile-once certified for the train step and the
  serve programs on clean inputs, signature-space explosion and
  uncommitted feeds flagged on planted ones — and the runtime twin, where
  CompileWatch observes the exact extra executable the prover predicted.
"""

import glob
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from picotron_tpu.analysis import (
    audit_feeds, check_engine_feed, collect_sites, predict_boundary_reshards,
    prove_serve_programs, prove_train_step, run_shardcheck,
)
from picotron_tpu.analysis.dataflow import (
    attribute_collectives, compiled_collectives, intended_rule, root_paths,
)
from picotron_tpu.analysis.collectives import parse_collectives
from picotron_tpu.analysis.trace import lower_train_step
from tests.test_shardcheck import MATRIX, mkcfg

ROOT = os.path.join(os.path.dirname(__file__), "..")


# ---------------------------------------------------------------------------
# provenance on the real config matrix
# ---------------------------------------------------------------------------

# the layout classes with distinct collective schedules; the full matrix
# (incl. offload variants) is covered by test_shardcheck's green gate,
# which now runs these checks too
_PROV_CONFIGS = ("dense-dp2tp2cp2", "dense-pp2dp2", "moe-ep2dp2")


@pytest.fixture(scope="module")
def traced():
    """One shared trace per layout class — every provenance assertion
    below reads it, none re-traces."""
    out = {}
    for name in _PROV_CONFIGS:
        cfg = mkcfg(**MATRIX[name])
        out[name] = (cfg, lower_train_step(cfg))
    return out


@pytest.mark.parametrize("name", _PROV_CONFIGS)
def test_provenance_attributes_all_collectives(traced, name):
    """The acceptance bar: >= 90% of the lowered module's effective
    collectives attributed to a jaxpr site; on the shipped layouts it is
    100%, with zero implicit (GSPMD-minted) ops."""
    cfg, low = traced[name]
    sites = collect_sites(low.jaxpr, root_paths(low.state, low.batch))
    ops = [o for o in parse_collectives(low.text) if o.effective]
    attributed, implicit = attribute_collectives(cfg, sites, ops)
    assert ops, "multi-axis layouts must lower effective collectives"
    assert len(attributed) / len(ops) >= 0.90
    assert implicit == [], [op.line for op in implicit]


@pytest.mark.parametrize("name", _PROV_CONFIGS)
def test_provenance_sites_carry_source_and_roots(traced, name):
    """Every site names the picotron_tpu line that issued it; data-carrying
    sites trace back to root state/batch paths (def-use provenance)."""
    _, low = traced[name]
    sites = collect_sites(low.jaxpr, root_paths(low.state, low.batch))
    assert sites
    for s in sites:
        assert s.source.startswith("picotron_tpu/"), s.describe()
        assert s.axes, s.describe()
    rooted = [s for s in sites if s.roots]
    assert len(rooted) >= len(sites) * 0.5, \
        [s.describe() for s in sites if not s.roots]
    roots = {r for s in rooted for r in s.roots}
    assert any(r.startswith("state/params/") for r in roots)


@pytest.mark.parametrize("name", _PROV_CONFIGS)
def test_provenance_every_site_is_intended(traced, name):
    """Each attributed site matches an intended-schedule rule (grad sync,
    TP psum, ring shift, expert dispatch, ...) — the shipped layouts have
    no collective a human would need to explain."""
    cfg, low = traced[name]
    sites = collect_sites(low.jaxpr, root_paths(low.state, low.batch))
    ops = [o for o in parse_collectives(low.text) if o.effective]
    attributed, _ = attribute_collectives(cfg, sites, ops)
    unexplained = [s.describe() for _, s in attributed
                   if intended_rule(cfg, s) is None]
    assert unexplained == []


@pytest.mark.parametrize("name", _PROV_CONFIGS)
def test_no_boundary_reshards_on_shipped_layouts(traced, name):
    cfg, low = traced[name]
    assert predict_boundary_reshards(cfg, low.jaxpr, low.state,
                                     low.batch) == []


# ---------------------------------------------------------------------------
# the mis-specced fixture: predicted statically, confirmed compiled
# ---------------------------------------------------------------------------


def _two_device_mesh():
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 simulated devices")
    return Mesh(np.array(jax.devices()[:2]), ("dp",))


def _shard_map_prog(mesh, in_spec):
    from jax.experimental.shard_map import shard_map

    def prog(tree):
        f = shard_map(lambda a: a * 2.0, mesh=mesh,
                      in_specs=in_spec, out_specs=in_spec)
        return {"x": f(tree["x"])}

    return prog


def test_misspecced_input_predicted_with_fix_named():
    """Declared P('dp') input consumed replicated by the program's
    shard_map: the audit predicts the GSPMD reshard at the boundary and
    names the exact spec change that removes it."""
    mesh = _two_device_mesh()
    x = jax.ShapeDtypeStruct((8, 8), jnp.float32,
                             sharding=NamedSharding(mesh, P("dp")))
    state = {"x": x}
    traced_step = jax.jit(_shard_map_prog(mesh, P())).trace(state)
    found = predict_boundary_reshards(None, traced_step.jaxpr, state, ())
    assert len(found) == 1, found
    r = found[0]
    assert r.path == "state/x"
    assert r.declared == "('dp',)" and r.used == "()"
    assert r.nbytes == 8 * 8 * 4
    assert "PartitionSpec()" in r.fix and "state/x" in r.fix

    # negative control: matching specs predict nothing
    traced_ok = jax.jit(_shard_map_prog(mesh, P("dp"))).trace(state)
    assert predict_boundary_reshards(None, traced_ok.jaxpr, state, ()) == []


def test_misspecced_input_mints_all_gather_in_compiled_module():
    """The compiled-module confirmation: the predicted reshard is REAL —
    the optimized HLO contains an all-gather no jaxpr site issued (it is
    invisible in the pre-partitioning StableHLO)."""
    mesh = _two_device_mesh()
    x = jax.ShapeDtypeStruct((8, 8), jnp.float32,
                             sharding=NamedSharding(mesh, P("dp")))
    lowered = jax.jit(_shard_map_prog(mesh, P())).lower({"x": x})
    pre = [o for o in parse_collectives(lowered.as_text()) if o.effective]
    assert pre == []  # nothing authored...
    minted = compiled_collectives(lowered)
    assert any(op.kind == "all_gather" for op in minted), \
        [op.line for op in minted]  # ...yet GSPMD gathered

    # matching specs compile collective-free
    ok = jax.jit(_shard_map_prog(mesh, P("dp"))).lower({"x": x})
    assert [op.kind for op in compiled_collectives(ok)] == []


# ---------------------------------------------------------------------------
# variant prover
# ---------------------------------------------------------------------------


def test_train_step_proves_compile_once(traced):
    cfg, low = traced["dense-dp2tp2cp2"]
    rep = prove_train_step(cfg, low=low)
    assert rep.ok(), rep.render(verbose=True)
    info = rep.info["variants"]
    assert info["proven"] and info["signatures"] == 1
    assert info["uncommitted"] == 0


def test_audit_feeds_flags_uncommitted_and_divergent():
    sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    committed = {"x": jax.device_put(jnp.zeros((8,)), sh)}
    uncommitted = {"x": jnp.zeros((8,))}

    rep = audit_feeds([committed], entry="clean")
    assert rep.ok() and rep.info["variants"]["proven"]

    rep = audit_feeds([committed, uncommitted], entry="dirty")
    assert not rep.ok()
    assert rep.info["variants"]["signatures"] == 2
    assert any("UNCOMMITTED" in f.message for f in rep.warnings())
    assert any("compile-once is NOT provable" in f.message
               for f in rep.errors())


def test_uncommitted_device_put_runtime_twin():
    """The end-to-end acceptance fixture: a deliberate no-sharding
    jax.device_put is (a) flagged by the source lint, (b) proven a
    variant hazard statically, and (c) confirmed by CompileWatch — the
    uncommitted re-feed of the SAME shapes mints exactly one extra
    executable, and is stable thereafter."""
    from picotron_tpu.analysis.source_lint import lint_file
    from picotron_tpu.telemetry.recompile import CompileWatch

    sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    committed = jax.device_put(jnp.ones((16,), jnp.float32), sh)
    uncommitted = jax.device_put(jnp.ones((16,), jnp.float32))

    # (a) the lint rule names the smell in source form
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write("import jax\n"
                "def feed(x):\n"
                "    return jax.device_put(x)\n")
        path = f.name
    try:
        lrep = lint_file(path, "fixture.py")
        assert any("UNCOMMITTED" in w.message for w in lrep.warnings())
    finally:
        os.unlink(path)

    # (b) the prover: the two feeds split the signature space
    vrep = audit_feeds([{"x": committed}, {"x": uncommitted}], entry="twin")
    assert not vrep.ok() and vrep.info["variants"]["signatures"] == 2

    # (c) the runtime twin
    watch = CompileWatch().install()
    try:
        if not watch.supported:
            pytest.skip("compile events not observable on this jax")
        step = jax.jit(lambda x: x * 2.0)
        step(committed)
        watch.drain()
        step(uncommitted)  # same shape/dtype — only commitment differs
        n, _ = watch.drain()
        assert n == 1  # exactly the executable the prover predicted
        step(uncommitted)
        n, _ = watch.drain()
        assert n == 0  # and the space is closed again
    finally:
        watch.uninstall()


def test_serve_programs_prove_and_flag_uncommitted_params():
    from picotron_tpu.config import ModelConfig, resolve_preset

    mc = ModelConfig(**resolve_preset("debug-tiny"))
    rep = prove_serve_programs(mc)
    assert rep.ok() and rep.info["variants"]["proven"]
    assert rep.info["variants"]["signatures"] == 1

    uncommitted = {"embedding": jnp.zeros((8, 4))}
    rep = prove_serve_programs(mc, params=uncommitted)
    info = rep.info["variants"]
    assert not info["proven"] and info["uncommitted"] == ["embedding"]
    assert any("place_for_decode" in f.message for f in rep.warnings())


def test_engine_feed_check_proves_live_engine():
    """check_engine_feed over a real ServeEngine: init commits every
    persistent leaf (params included — the hole this prover found), so
    the live feed proves compile-once; engine.variant_report carries it."""
    from picotron_tpu.config import ModelConfig, ServeConfig, resolve_preset
    from picotron_tpu.models.llama import init_params
    from picotron_tpu.serve.engine import ServeEngine

    mc = ModelConfig(dtype="float32", **{
        **resolve_preset("debug-tiny"), "max_position_embeddings": 64})
    params = init_params(mc, jax.random.key(0))  # raw == uncommitted
    eng = ServeEngine(params, mc, ServeConfig(
        decode_slots=2, block_size=4, num_blocks=16, prefill_chunk=4,
        max_model_len=32))
    try:
        rep = check_engine_feed(eng)
        assert rep.ok(), rep.render(verbose=True)
        info = rep.info["variants"]
        assert info["proven"] and info["uncommitted"] == []
        assert eng.variant_report is not None
        assert eng.variant_report.info["variants"]["proven"]
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# full-check integration + CLI
# ---------------------------------------------------------------------------


def test_run_shardcheck_includes_new_checks():
    rep = run_shardcheck(mkcfg(), checks=("provenance", "variants"))
    assert rep.ok(), rep.render(verbose=True)
    assert rep.info["provenance"]["attribution_pct"] >= 90.0
    assert rep.info["variants"]["train_step"]["proven"]
    assert rep.info["variants"]["serve"]["proven"]


def test_cli_provenance_and_variants_flags(capsys):
    from tests.test_tools import load_tool

    sc = load_tool("shardcheck")
    rc = sc.main(["--preset", "tiny-dense", "--provenance", "--variants",
                  "--json"])
    assert rc == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert row["ok"]
    prov = row["info"]["provenance"]
    assert prov["attribution_pct"] >= 90.0
    assert prov["implicit_ops"] == 0 and prov["boundary_reshards"] == 0
    var = row["info"]["variants"]
    assert var["train_step"]["proven"] and var["serve"]["proven"]
    # focus flags restrict the run: no collectives/donation tables
    assert "collectives" not in row["info"]

    rc = sc.main(["--preset", "tiny-dense", "--provenance", "--variants"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "provenance:" in out and "attributed (100.0%)" in out
    assert "proven compile-once" in out
