"""ICI cost-model tests: analytic hop counts and per-collective byte
volumes pinned for known mesh shapes (no hardware, pure arithmetic), axis
placement by generation, traced-op pricing, and the acceptance bar — the
model must reproduce the measured ranking of the SWEEP_r03–r05 configs
(Spearman rank agreement, per round)."""

import math

import pytest

from picotron_tpu.analysis.calibration import (
    load_measured_rows, measured_step_seconds, rank_agreement, row_to_point,
)
from picotron_tpu.analysis.cost_model import (
    GENERATIONS, AxisLink, Calibration, CostModel, line_diameter,
    place_axes, resolve_generation, ring_diameter, spearman,
    with_calibration,
)
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset,
)


def mkcfg(model="debug-tiny", seq=64, mbs=1, ga=1, dist=None, train=None):
    cfg = Config(
        distributed=DistributedConfig(**(dist or {})),
        model=ModelConfig(name=model, **resolve_preset(model)),
        training=TrainingConfig(seq_length=seq, micro_batch_size=mbs,
                                gradient_accumulation_steps=ga,
                                **(train or {})),
    )
    cfg.validate()
    return cfg


# ---------------------------------------------------------------------------
# hop counts + placement
# ---------------------------------------------------------------------------


def test_ring_vs_line_diameters():
    # ring: bidirectional wraparound halves the worst hop distance
    assert ring_diameter(8) == 4
    assert ring_diameter(16) == 8
    assert ring_diameter(3) == 1
    # line (torus slice without wraparound): worst hop walks the slice
    assert line_diameter(8) == 7
    assert line_diameter(2) == 1


def test_generation_wrap_rule():
    # v5e sub-slices of the 2D torus are meshes: an 8-axis is a line;
    # v5p 3D slices close into rings from a full side of 4
    v5e = place_axes({"tp": 8}, GENERATIONS["v5e"])["tp"]
    v5p = place_axes({"tp": 8}, GENERATIONS["v5p"])["tp"]
    assert v5e.kind == "line" and v5e.diameter == 7
    assert v5p.kind == "ring" and v5p.diameter == 4
    # a full v5e 16-ring wraps
    assert place_axes({"tp": 16}, GENERATIONS["v5e"])["tp"].kind == "ring"


def test_placement_innermost_axes_get_dedicated_dims():
    links = place_axes({"dp": 2, "tp": 4, "cp": 2, "pp": 1, "ep": 1},
                       GENERATIONS["v5e"])
    # tp and cp (innermost) own the two v5e torus dims at full bandwidth;
    # dp folds and pays a stride penalty
    assert links["tp"].stride == 1 and links["cp"].stride == 1
    assert links["dp"].stride > 1
    assert links["dp"].bandwidth < links["tp"].bandwidth
    # size-1 axes are not placed at all
    assert "pp" not in links and "ep" not in links


def test_v5p_three_axes_fit_without_folding():
    links = place_axes({"dp": 2, "tp": 4, "cp": 2, "pp": 1, "ep": 1},
                       GENERATIONS["v5p"])
    assert all(l.stride == 1 for l in links.values())


def test_resolve_generation_from_device_kind():
    assert resolve_generation("TPU v5 lite").name == "v5e"
    assert resolve_generation("TPU v5p").name == "v5p"
    assert resolve_generation("TPU v4").name == "v4"
    assert resolve_generation("cpu-test-device").name == "v5e"  # fallback


# ---------------------------------------------------------------------------
# per-collective formulas (byte volumes pinned, alpha removed)
# ---------------------------------------------------------------------------


def _no_latency(gen="v5e"):
    return CostModel(gen, Calibration(alpha_link_s=0.0))


def test_collective_byte_volume_factors():
    cm = _no_latency()
    bw = 45e9
    ring = AxisLink("tp", 4, "ring", bw, 1)
    v = 1e9
    # all-gather / reduce-scatter: V*(n-1)/n over both ring directions
    ag = cm.collective_secs("all_gather", v, ring)
    assert ag == pytest.approx(v * 3 / 4 / (2 * bw))
    assert cm.collective_secs("reduce_scatter", v, ring) == pytest.approx(ag)
    # all-reduce = reduce-scatter + all-gather
    assert cm.collective_secs("all_reduce", v, ring) == pytest.approx(2 * ag)
    # neighbor ppermute: one payload per link
    assert cm.collective_secs("collective_permute", v, ring) == \
        pytest.approx(v / bw)
    # all-to-all: mean distance n/4, both directions
    assert cm.collective_secs("all_to_all", v, ring) == \
        pytest.approx(v * 4 / (4 * 2 * bw))


def test_line_pays_more_than_ring():
    cm = _no_latency()
    bw = 45e9
    ring = AxisLink("cp", 8, "ring", bw, 1)
    line = AxisLink("cp", 8, "line", bw, 1)
    for kind in ("all_gather", "all_reduce", "all_to_all",
                 "collective_permute"):
        assert cm.collective_secs(kind, 1e9, line) > \
            cm.collective_secs(kind, 1e9, ring)
    # the line ppermute wrap walks the whole slice
    assert cm.collective_secs("collective_permute", 1e9, line) == \
        pytest.approx(1e9 * 7 / bw)


def test_size_one_axis_costs_nothing():
    cm = _no_latency()
    one = AxisLink("tp", 1, "line", 45e9, 1)
    assert cm.collective_secs("all_reduce", 1e9, one) == 0.0


# ---------------------------------------------------------------------------
# traced-op pricing
# ---------------------------------------------------------------------------


def test_price_ops_matches_axes():
    from picotron_tpu.analysis.collectives import CollectiveOp

    cfg = mkcfg(dist=dict(dp_size=2, tp_size=2, cp_size=2), ga=2)
    cm = _no_latency()
    ops = [
        # grad sync over the fused data axes dp*ep*cp = 4
        CollectiveOp("all_reduce", 4, 2, 1 << 20, (256, 1024), "f32", 1),
        # a tp-sized all-gather
        CollectiveOp("all_gather", 2, 4, 1 << 18, (64, 1024), "bf16", 2),
        # the cp ring
        CollectiveOp("collective_permute", None, 8, 1 << 16, (64, 256),
                     "bf16", 3),
        # compiled-away op must not be priced
        CollectiveOp("all_reduce", 1, 8, 1 << 20, (1,), "f32", 4),
    ]
    priced = cm.price_ops(cfg, ops)
    assert len(priced) == 3
    by_line = {p["line"]: p for p in priced}
    assert set(by_line[1]["axes"]) == {"dp", "cp"}  # ep=1 drops out
    assert by_line[2]["axes"] in (("tp",), ("cp",))  # both size 2
    assert by_line[3]["axes"] == ("cp",)
    assert all(p["secs"] > 0 for p in priced)


def test_priced_schedule_from_lowered_text():
    # lower a dp=2 step once and price its real schedule
    cfg = mkcfg(dist=dict(dp_size=2), ga=2)
    cm = CostModel("v5e")
    priced, comm_s = cm.priced_schedule(cfg)
    assert priced, "a dp=2 step must emit at least the grad all-reduce"
    assert comm_s > 0


# ---------------------------------------------------------------------------
# analytic step prediction
# ---------------------------------------------------------------------------


def test_predict_decomposition_consistency():
    cfg = mkcfg(dist=dict(dp_size=2, tp_size=2, pp_size=2), ga=4)
    cost = CostModel("v5e").predict(cfg)
    assert cost.n_chips == 8
    assert cost.compute_s > 0
    # spmd lockstep-scan bubble: compute * 2*(pp-1)/ga (full-price idle
    # ticks — the mpmd executor halves this, test below)
    assert cost.bubble_s == pytest.approx(cost.compute_s * 2 / 4)
    assert cost.total_s >= cost.compute_s + cost.bubble_s
    assert cost.exposed_comm_s <= cost.comm_s
    names = {t.name for t in cost.comm}
    assert "grad_sync" in names and "tp_psum" in names
    assert "pp_boundary" in names
    d = cost.as_dict()
    assert d["predicted_step_ms"] == pytest.approx(cost.total_s * 1e3,
                                                   abs=5e-4)  # ms rounding


def test_predict_mpmd_bubble_and_label():
    """The executor knob changes only the bubble term: mpmd halves the
    spmd fill/drain (and divides by v under interleaving) but pays a
    host-dispatch charge per scheduled program — at tiny compute the
    dispatch dominates, at scale the halved bubble wins."""
    import dataclasses

    from picotron_tpu.analysis.cost_model import layout_label
    from picotron_tpu.config import PipelineConfig

    base = mkcfg(dist=dict(dp_size=2, tp_size=2, pp_size=2), ga=4)
    cm = CostModel("v5e")
    spmd = cm.predict(base)
    for pl, v in [(PipelineConfig(executor="mpmd"), 1),
                  (PipelineConfig(executor="mpmd", schedule="interleaved",
                                  interleave=2), 2)]:
        cfg = dataclasses.replace(base, pipeline=pl)
        cfg.validate()
        cost = cm.predict(cfg)
        assert cost.compute_s == pytest.approx(spmd.compute_s)
        dispatch = 2 * 4 * 2 * v * cm.calib.host_dispatch_s
        assert cost.bubble_s == pytest.approx(
            cost.compute_s * 1 / (v * 4) + dispatch)
        assert "mpmd" in layout_label(cfg)
    assert "v2" in layout_label(
        dataclasses.replace(base, pipeline=PipelineConfig(
            executor="mpmd", schedule="interleaved", interleave=2)))
    # the dispatch term scales with ga*pp*v; zeroing it makes mpmd's
    # bubble strictly half of spmd's at v=1
    free = with_calibration(cm, host_dispatch_s=0.0)
    cfg = dataclasses.replace(base, pipeline=PipelineConfig(executor="mpmd"))
    assert free.predict(cfg).bubble_s == pytest.approx(spmd.bubble_s / 2)


def test_predict_prices_every_promised_axis():
    # the same per-axis promises audit_collectives enforces on traces
    cfg = mkcfg(model="debug-tiny-moe", dist=dict(ep_size=2, dp_size=2),
                ga=2)
    names = {t.name for t in CostModel("v5e").predict(cfg).comm}
    assert "ep_dispatch" in names
    cfg = mkcfg(dist=dict(cp_size=4), ga=2)
    names = {t.name for t in CostModel("v5e").predict(cfg).comm}
    assert "cp_ring" in names
    cfg = mkcfg(dist=dict(tp_size=2, dp_size=2, sequence_parallel=True),
                ga=2)
    names = {t.name for t in CostModel("v5e").predict(cfg).comm}
    assert "sp_gather" in names and "sp_scatter" in names


def test_offload_term_scales_with_params_and_pcie():
    cfg = mkcfg(ga=4, train=dict(optimizer_offload=True))
    base = CostModel("v5e").predict(cfg)
    assert base.offload_s > 0
    slow = with_calibration(CostModel("v5e"),
                            pcie_bandwidth=1e9).predict(cfg)
    assert slow.offload_s > base.offload_s


def test_dp_weak_scaling():
    # dp grows the global batch: same per-step compute, 8x the tokens
    one = CostModel("v5e").predict(mkcfg())
    eight = CostModel("v5e").predict(mkcfg(dist=dict(dp_size=8)))
    assert eight.tokens_per_step == 8 * one.tokens_per_step
    assert eight.compute_s == pytest.approx(one.compute_s)
    assert eight.tokens_per_sec > one.tokens_per_sec


# ---------------------------------------------------------------------------
# spearman + calibration data plumbing
# ---------------------------------------------------------------------------


def test_spearman_basics():
    assert spearman([1, 2, 3, 4], [10, 20, 30, 40]) == pytest.approx(1.0)
    assert spearman([1, 2, 3, 4], [40, 30, 20, 10]) == pytest.approx(-1.0)
    assert abs(spearman([1, 2, 3, 4], [1, 3, 2, 4])) < 1.0
    with pytest.raises(ValueError):
        spearman([1], [1])


def test_row_to_point_parses_metric_and_config_string():
    pt = row_to_point({
        "metric": "mfu_SmolLM-1.7B-24L_seq2048",
        "tokens_per_sec_per_chip": 8806.1,
        "config": "mbs3 ga43 dots_attn offload + fused grad engine",
    }, "t")
    assert pt is not None
    t = pt.cfg.training
    assert t.micro_batch_size == 3
    assert t.gradient_accumulation_steps == 43
    assert t.optimizer_offload and t.remat_policy == "dots_attn"
    assert pt.cfg.model.num_hidden_layers == 24
    assert pt.cfg.training.seq_length == 2048
    # decode / error rows are not mfu points
    assert row_to_point({"metric": "decode_SmolLM-1.7B-24L_batch8",
                         "value": 793.9}, "t") is None


def test_rank_agreement_matches_measured_sweeps():
    """The acceptance bar: predicted tokens/s must reproduce the measured
    per-round orderings of SWEEP_r03–r05 (each round ranks internally —
    rows from different rounds ran different code)."""
    points = load_measured_rows()
    assert len(points) >= 12, "SWEEP_r03-r05 rows are the fixture"
    ra = rank_agreement(points)
    assert set(ra["per_round"]) == {"SWEEP_r03.jsonl", "SWEEP_r04.jsonl",
                                    "SWEEP_r05.jsonl"}
    for src, rho in ra["per_round"].items():
        assert rho >= 0.85, (src, rho, ra["rows"])
    assert ra["pooled"] >= 0.85


def test_predictions_within_2x_of_measured():
    """Ranking is the contract, but the absolute numbers must stay sane:
    every calibrated prediction within 2x of its measured row."""
    model = CostModel("v5e")
    for p in load_measured_rows():
        pred = model.predict(p.cfg).tokens_per_sec_per_chip
        ratio = pred / p.tokens_per_sec_per_chip
        assert 0.5 < ratio < 2.0, (p.metric, ratio)


def test_measured_step_seconds_from_telemetry_events():
    events = [
        {"kind": "phase", "phase": "step", "secs": 0.10, "step": 1},
        {"kind": "phase", "phase": "step", "secs": 0.12, "step": 2},
        {"kind": "phase", "phase": "sync", "secs": 0.01, "step": 1},
        {"kind": "step", "loss": 1.0},
    ]
    m = measured_step_seconds(events)
    assert m["n_steps"] == 2
    assert m["step_s"] == pytest.approx(0.12)
    assert m["sync_s"] == pytest.approx(0.01)
    assert measured_step_seconds([{"kind": "step"}]) is None


def test_audit_collectives_cost_info():
    """audit_collectives(cost_model=...) prices the traced schedule into
    the report's info table (the shardcheck --cost wiring)."""
    from picotron_tpu.analysis import audit_collectives

    cfg = mkcfg(dist=dict(dp_size=2), ga=2)
    rep = audit_collectives(cfg, cost_model=CostModel("v5e"))
    assert rep.ok(), rep.render()
    pc = rep.info["collectives"]["predicted_comm"]
    assert pc["generation"] == "v5e"
    assert pc["total_ms"] > 0
    assert math.isfinite(pc["total_ms"])
    assert pc["by_kind_ms"].get("all_reduce", 0) > 0
