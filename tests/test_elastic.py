"""Elastic scale-out (picotron_tpu/resilience/elastic.py +
tools/elastic_resize.py): constant-global-batch resize planning, ZeRO-1
shard round-trip bitwise parity, the restore-time topology guard in both
modes, the offline re-stamp CLI (incl. its refuse-corrupt safety), and
ckpt_doctor's source-topology column. The full multi-process dp_resize
chaos scenario is the slow-marked half in test_resilience.py."""

import json
import os
import sys

import jax
import numpy as np
import pytest

from picotron_tpu.checkpoint import CheckpointManager
from picotron_tpu.config import (
    CheckpointConfig, Config, DistributedConfig, ModelConfig, TrainingConfig,
)
from picotron_tpu.mesh import MeshEnv
from picotron_tpu.parallel.api import (
    abstract_master, init_sharded_state, offload_zero1_info,
)
from picotron_tpu.resilience import elastic


# ---------------------------------------------------------------------------
# Resize planning / cursor translation / topology helpers (pure)
# ---------------------------------------------------------------------------


def test_plan_resize_prefers_keeping_mbs():
    # dp 2 -> 1 at gbs 4: per-replica batch doubles into ga
    p = elastic.plan_resize(micro_batch_size=2,
                            gradient_accumulation_steps=1,
                            dp_size=2, dp_new=1)
    assert (p.micro_batch_size, p.gradient_accumulation_steps) == (2, 2)
    assert p.global_batch_size == 4
    assert p.overrides() == {
        "distributed": {"dp_size": 1},
        "training": {"micro_batch_size": 2,
                     "gradient_accumulation_steps": 2},
    }


def test_plan_resize_shrinks_mbs_on_growth():
    # dp 2 -> 4 at gbs 4: per-replica batch halves below mbs
    p = elastic.plan_resize(micro_batch_size=2,
                            gradient_accumulation_steps=1,
                            dp_size=2, dp_new=4)
    assert (p.micro_batch_size, p.gradient_accumulation_steps) == (1, 1)
    assert p.global_batch_size == 4


def test_plan_resize_respects_ep_and_rejects_indivisible():
    p = elastic.plan_resize(micro_batch_size=2,
                            gradient_accumulation_steps=2,
                            dp_size=4, dp_new=2, ep_size=2)
    assert p.global_batch_size == 32
    assert (p.micro_batch_size * p.gradient_accumulation_steps
            * p.dp_new * 2) == 32
    with pytest.raises(ValueError, match="not divisible"):
        elastic.plan_resize(micro_batch_size=2,
                            gradient_accumulation_steps=1,
                            dp_size=2, dp_new=3)


def test_translate_cursor_is_passthrough_at_constant_gbs():
    st = {"epoch": 3, "cursor": 12}
    assert elastic.translate_dataloader_state(st, gbs_old=4,
                                              gbs_new=4) == st
    # a changed gbs whose boundary the cursor doesn't land on is a hard
    # error — never a silent replay/skip
    with pytest.raises(ValueError, match="step boundary"):
        elastic.translate_dataloader_state({"epoch": 0, "cursor": 6},
                                           gbs_old=6, gbs_new=4)


def test_topology_mismatch_and_describe():
    a = elastic.topology_from_distributed(
        DistributedConfig(dp_size=2, tp_size=2))
    assert elastic.describe_topology(a) == "dp2 pp1 ep1 cp1 tp2"
    assert a["world_size"] == 4
    b = dict(a, dp=4, world_size=8)
    assert elastic.topology_mismatch(a, b) == ["dp"]
    assert elastic.topology_mismatch(a, dict(a)) == []
    assert elastic.topology_mismatch(None, a) == []  # nothing recorded


def test_topology_records_and_compares_slices():
    """The slice count rides the topology schema as placement metadata:
    recorded by topology_from_distributed, rendered only when > 1 (the
    single-slice string stays byte-identical to the pre-slices one),
    compared with a default of 1 so pre-slices checkpoints read as
    single-slice — and it never multiplies into world_size."""
    multi = elastic.topology_from_distributed(
        DistributedConfig(dp_size=2, tp_size=2, cp_size=2,
                          slices=2, dcn_axes="dp"))
    assert multi["slices"] == 2
    assert multi["world_size"] == 8  # slices partition the axes, not x2
    assert elastic.describe_topology(multi) == \
        "dp2 pp1 ep1 cp2 tp2 slices2"
    solo = elastic.topology_from_distributed(
        DistributedConfig(dp_size=2, tp_size=2, cp_size=2))
    assert "slices" not in elastic.describe_topology(solo)
    assert elastic.topology_mismatch(multi, solo) == ["slices"]
    # a pre-slices topology dict (no field at all) means single-slice
    legacy = {ax: solo[ax] for ax in elastic.TOPOLOGY_AXES}
    assert elastic.topology_mismatch(legacy, multi) == ["slices"]
    assert elastic.topology_mismatch(legacy, solo) == []


def test_saved_topology_meta_fallback(tmp_path):
    """Pre-manifest (legacy) step dirs fall back to meta.json's recorded
    config; a dir recording neither yields None (guard disengages)."""
    step = tmp_path / "step_00000001"
    step.mkdir()
    assert elastic.saved_topology(str(step)) is None
    (step / "meta.json").write_text(json.dumps(
        {"config": {"distributed": {"dp_size": 2, "tp_size": 4}}}))
    topo = elastic.saved_topology(str(step))
    assert topo["dp"] == 2 and topo["tp"] == 4 and topo["world_size"] == 8


# ---------------------------------------------------------------------------
# ZeRO-1 shard arithmetic: N -> M -> N bitwise round trip
# ---------------------------------------------------------------------------


def test_zero1_resize_round_trip_is_bitwise():
    """The acceptance-criteria pin: fp32 ZeRO-1 optimizer shards pushed
    through a 4 -> 2 -> 4 resize round trip are bitwise identical to the
    never-resized twin — on the REAL per-leaf placements offload_zero1_
    info derives for a dp=4 zero1 run, not synthetic shapes."""
    cfg = Config(distributed=DistributedConfig(dp_size=4, zero1=True))
    info = offload_zero1_info(cfg, abstract_master(cfg))
    assert info is not None and any(p is not None for p in info)
    leaves = jax.tree.leaves(abstract_master(cfg))
    rng = np.random.default_rng(0)
    checked = 0
    for leaf, place in zip(leaves, info):
        if place is None:
            continue
        dim, _axes, sizes = place
        n = int(np.prod(sizes))
        full = rng.standard_normal(leaf.shape).astype(np.float32)
        never = elastic.split_zero1(full, dim, n)  # the un-resized twin
        round_trip = elastic.resize_zero1(
            elastic.resize_zero1(never, dim, n // 2), dim, n)
        assert len(round_trip) == n
        for a, b in zip(never, round_trip):
            assert a.tobytes() == b.tobytes()  # bitwise, not allclose
        # and the regathered full leaf is the original bytes
        assert elastic.regather_zero1(round_trip,
                                      dim).tobytes() == full.tobytes()
        checked += 1
    assert checked > 0


def test_zero1_resize_leaves_and_indivisible():
    shards = elastic.split_zero1(np.arange(8, dtype=np.float32), 0, 4)
    with pytest.raises(ValueError, match="not divisible"):
        elastic.resize_zero1(shards, 0, 3)
    # leaf-list form: None placements pass through untouched
    out = elastic.resize_zero1_leaves(
        [shards, np.float32(7.0)],
        [(0, ("dp",), (4,)), None])
    assert out[1] == np.float32(7.0)
    for a, b in zip(out[0], shards):
        assert a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# Restore-time guard + offline re-stamp CLI (real checkpoint stores)
# ---------------------------------------------------------------------------


def make_cfg(tmp_path, *, elastic_on=False, mbs=1, ga=1, **dist):
    return Config(
        distributed=DistributedConfig(**dist),
        model=ModelConfig(dtype="float32"),
        training=TrainingConfig(seq_length=32, micro_batch_size=mbs,
                                gradient_accumulation_steps=ga,
                                remat=False),
        checkpoint=CheckpointConfig(save_dir=str(tmp_path / "ckpt"),
                                    async_save=False, elastic=elastic_on),
    )


def _save_step(cfg):
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    mgr = CheckpointManager(cfg, menv)
    mgr.save(state, trained_tokens=64,
             dataloader_state={"epoch": 0, "cursor": 0})
    mgr.wait_until_finished()
    return state


def _load_tool():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "elastic_resize", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "elastic_resize.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_elastic_restore_rejects_changed_global_batch(tmp_path):
    """checkpoint.elastic permits the topology change but still pins the
    invariant: a resize that would drift global_batch_size is refused,
    with the overrides that restore it named in the error."""
    cfg_a = make_cfg(tmp_path, dp_size=2, mbs=1, ga=1)  # gbs 2
    _save_step(cfg_a)
    cfg_b = make_cfg(tmp_path, dp_size=1, mbs=1, ga=1,  # gbs 1
                     elastic_on=True)
    menv_b = MeshEnv.from_config(cfg_b)
    template = init_sharded_state(cfg_b, menv_b, jax.random.key(1))
    with pytest.raises(RuntimeError, match="global_batch_size") as exc:
        CheckpointManager(cfg_b, menv_b).restore(template)
    assert "gradient_accumulation_steps=2" in str(exc.value)


def test_elastic_resize_tool_restamps_store(tmp_path):
    """tools/elastic_resize.py end-to-end: dry-run touches nothing; the
    real run rewrites meta.json + re-commits the manifest for dp=1 at
    constant global batch, the step re-verifies, and the re-stamped store
    restores into a dp=1 mesh with elastic OFF — byte-identical params."""
    cfg_a = make_cfg(tmp_path, dp_size=2, tp_size=2, mbs=2, ga=1)
    state = _save_step(cfg_a)
    save_dir = cfg_a.checkpoint.save_dir
    [step_dir] = [os.path.join(save_dir, d) for d in os.listdir(save_dir)
                  if d.startswith("step_")]
    tool = _load_tool()

    before = open(os.path.join(step_dir, "meta.json")).read()
    assert tool.main([save_dir, "--dp", "1", "--dry-run"]) == 0
    assert open(os.path.join(step_dir, "meta.json")).read() == before

    assert tool.main([save_dir, "--dp", "1"]) == 0
    meta = json.load(open(os.path.join(step_dir, "meta.json")))
    assert meta["config"]["distributed"]["dp_size"] == 1
    assert meta["config"]["training"]["micro_batch_size"] == 2
    assert meta["config"]["training"]["gradient_accumulation_steps"] == 2
    assert meta["elastic_restamp"]["to"]["dp"] == 1
    topo = elastic.saved_topology(step_dir)
    assert topo["dp"] == 1 and topo["tp"] == 2 and topo["world_size"] == 2

    from picotron_tpu.ckpt_integrity import verify_step_dir
    assert verify_step_dir(step_dir).status == "verified"

    # the re-stamped store now IS a dp=1 checkpoint: restoring it on a
    # dp=1 mesh needs no elastic flag
    cfg_b = make_cfg(tmp_path, dp_size=1, tp_size=2, mbs=2, ga=2)
    menv_b = MeshEnv.from_config(cfg_b)
    template = init_sharded_state(cfg_b, menv_b, jax.random.key(1))
    restored, meta2 = CheckpointManager(cfg_b, menv_b).restore(template)
    assert "elastic_resize" not in meta2
    np.testing.assert_array_equal(
        np.asarray(restored.params["embedding"]),
        np.asarray(state.params["embedding"]))


def test_elastic_resize_tool_refuses_corrupt_store(tmp_path):
    """Safety pin: re-stamping rebuilds the manifest from current bytes,
    so running on a corrupt step would bless the corruption as verified.
    The tool must refuse and leave the store untouched."""
    cfg = make_cfg(tmp_path, dp_size=2, mbs=2, ga=1)
    _save_step(cfg)
    save_dir = cfg.checkpoint.save_dir
    [step_dir] = [os.path.join(save_dir, d) for d in os.listdir(save_dir)
                  if d.startswith("step_")]
    state_files = [os.path.join(r, f)
                   for r, _d, fs in os.walk(os.path.join(step_dir, "state"))
                   for f in fs]
    victim = max(state_files, key=os.path.getsize)
    with open(victim, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))

    tool = _load_tool()
    assert tool.main([save_dir, "--step", "0", "--dp", "1"]) == 1
    meta = json.load(open(os.path.join(step_dir, "meta.json")))
    assert meta["config"]["distributed"]["dp_size"] == 2  # untouched
    assert "elastic_restamp" not in meta


# ---------------------------------------------------------------------------
# pp / joint dp x pp elastic allow-path
# ---------------------------------------------------------------------------


def _fake_step(tmp_path, *, mbs=1, ga=1, **dist):
    """A legacy (meta.json-only) step dir recording a saved topology —
    enough for check_restore_topology, without a real Orbax store."""
    step = tmp_path / "saved" / "step_00000002"
    step.mkdir(parents=True)
    dcfg = {f"{ax}_size": int(dist.get(f"{ax}_size", 1))
            for ax in elastic.TOPOLOGY_AXES}
    dcfg["slices"] = int(dist.get("slices", 1))
    meta = {"config": {
        "distributed": dcfg,
        "training": {"micro_batch_size": mbs,
                     "gradient_accumulation_steps": ga},
    }}
    (step / "meta.json").write_text(json.dumps(meta))
    return str(step), meta


def test_restore_topology_pp_allow_path(tmp_path):
    """A pure-pp mismatch rides the elastic allow-path: pp does not enter
    the global batch, so the resize record comes back with no batch
    re-plan needed (the padded-layer-stack slot check in
    checkpoint.restore gates even splits separately)."""
    step_dir, meta = _fake_step(tmp_path, mbs=2, ga=2, pp_size=2)
    cfg = make_cfg(tmp_path, mbs=2, ga=2, elastic_on=True)  # pp=1 mesh
    rec = elastic.check_restore_topology(
        step_dir, meta, cfg, step=2, save_dir=str(tmp_path / "saved"))
    assert rec["axes"] == ["pp"]
    assert rec["from"]["pp"] == 2 and rec["to"]["pp"] == 1


def test_restore_topology_joint_dp_pp_allow_path(tmp_path):
    """dp and pp resize jointly: the record names both axes and the
    constant-global-batch invariant is still enforced through the dp
    half (mbs x ga x dp unchanged)."""
    step_dir, meta = _fake_step(tmp_path, mbs=1, ga=1, dp_size=2,
                                pp_size=2)                       # gbs 2
    cfg = make_cfg(tmp_path, dp_size=1, mbs=1, ga=2,             # gbs 2
                   elastic_on=True)
    rec = elastic.check_restore_topology(
        step_dir, meta, cfg, step=2, save_dir=str(tmp_path / "saved"))
    assert rec["axes"] == ["dp", "pp"]

    # same joint mismatch with elastic OFF: the error names both axes
    # and quotes a re-stamp invocation carrying BOTH flags
    cfg_off = make_cfg(tmp_path, dp_size=1, mbs=1, ga=2)
    with pytest.raises(RuntimeError, match="dp, pp") as exc:
        elastic.check_restore_topology(
            step_dir, meta, cfg_off, step=2,
            save_dir=str(tmp_path / "saved"))
    assert "--dp 1" in str(exc.value) and "--pp 1" in str(exc.value)


def test_restore_topology_pure_pp_mismatch_renders_pp_flag(tmp_path):
    """The elastic-off error for a pure-pp mismatch must quote a --pp
    re-stamp line, not a --dp no-op that would not fix it."""
    step_dir, meta = _fake_step(tmp_path, mbs=2, ga=2, pp_size=2)
    cfg = make_cfg(tmp_path, mbs=2, ga=2)  # pp=1 mesh, elastic off
    with pytest.raises(RuntimeError) as exc:
        elastic.check_restore_topology(
            step_dir, meta, cfg, step=2, save_dir=str(tmp_path / "saved"))
    assert "--pp 1" in str(exc.value)
    assert "--dp" not in str(exc.value)


@pytest.mark.parametrize("axis", ["tp", "cp", "ep"])
def test_restore_topology_rejects_unsupported_axis_even_elastic(
        tmp_path, axis):
    """The allow-path is {dp, pp} ONLY: nothing re-partitions the weight
    math tp/cp/ep split, so a mismatch there must raise even with
    checkpoint.elastic on — never proceed into an unsupported reshard."""
    step_dir, meta = _fake_step(tmp_path, mbs=2, ga=1,
                                **{f"{axis}_size": 2})
    cfg = make_cfg(tmp_path, mbs=2, ga=1, elastic_on=True)  # all axes 1
    with pytest.raises(RuntimeError, match="not elastic-resizable") as exc:
        elastic.check_restore_topology(
            step_dir, meta, cfg, step=2, save_dir=str(tmp_path / "saved"))
    assert axis in str(exc.value)


def test_restore_topology_slices_mismatch_names_both(tmp_path):
    """Satellite pin: restoring a 2-slice checkpoint into a single-slice
    (and dp-shrunk) mesh with elastic OFF fails naming BOTH topologies —
    slices included — and quotes a re-stamp line carrying --slices; with
    elastic on, the resize record lists slices among the changed axes
    (the slice-loss recovery allow-path)."""
    step_dir, meta = _fake_step(tmp_path, mbs=1, ga=1, dp_size=2,
                                slices=2)                        # gbs 2
    cfg_off = make_cfg(tmp_path, dp_size=1, mbs=1, ga=2)         # gbs 2
    with pytest.raises(RuntimeError) as exc:
        elastic.check_restore_topology(
            step_dir, meta, cfg_off, step=2,
            save_dir=str(tmp_path / "saved"))
    msg = str(exc.value)
    assert "slices2" in msg                      # the saved topology
    assert "dp1 pp1 ep1 cp1 tp1" in msg          # the current one
    assert "dp, slices" in msg                   # mismatched axes named
    assert "--dp 1" in msg and "--slices 1" in msg

    cfg_on = make_cfg(tmp_path, dp_size=1, mbs=1, ga=2, elastic_on=True)
    rec = elastic.check_restore_topology(
        step_dir, meta, cfg_on, step=2, save_dir=str(tmp_path / "saved"))
    assert rec["axes"] == ["dp", "slices"]
    assert rec["from"]["slices"] == 2 and rec["to"]["slices"] == 1


def test_resize_invocation_renders_mismatched_axes():
    """The quoted re-stamp command renders a flag per ACTUALLY-mismatched
    supported axis (regression: it used to always print --dp)."""
    cur = {"dp": 4, "pp": 2}
    pp_only = elastic.resize_invocation("/s", 3, cur, axes=("pp",))
    assert pp_only.endswith("--pp 2") and "--dp" not in pp_only
    both = elastic.resize_invocation("/s", 3, cur, axes=("dp", "pp"))
    assert "--dp 4" in both and "--pp 2" in both
    dp_only = elastic.resize_invocation("/s", 3, cur)
    assert "--dp 4" in dp_only and "--pp" not in dp_only


def test_elastic_resize_tool_restamps_pp(tmp_path):
    """--pp on the offline tool: an uneven split is refused (store
    untouched, slot mismatch named), an even split re-stamps pp as pure
    metadata — the batch plan is untouched when --dp is absent — and the
    re-stamped store restores on a pp=1 mesh with elastic OFF,
    byte-identical params."""
    cfg_a = make_cfg(tmp_path, pp_size=2, mbs=2, ga=2)
    state = _save_step(cfg_a)
    save_dir = cfg_a.checkpoint.save_dir
    [step_dir] = [os.path.join(save_dir, d) for d in os.listdir(save_dir)
                  if d.startswith("step_")]
    tool = _load_tool()

    # uneven split: 4 layers pad to 4 slots at pp=2 but 6 at pp=3 —
    # refused before anything is rewritten
    before = open(os.path.join(step_dir, "meta.json")).read()
    assert tool.main([save_dir, "--pp", "3"]) == 1
    assert open(os.path.join(step_dir, "meta.json")).read() == before

    assert tool.main([save_dir, "--pp", "1"]) == 0
    meta = json.load(open(os.path.join(step_dir, "meta.json")))
    assert meta["config"]["distributed"]["pp_size"] == 1
    # pure-pp: the batch plan is untouched
    assert meta["config"]["training"]["micro_batch_size"] == 2
    assert meta["config"]["training"]["gradient_accumulation_steps"] == 2
    assert meta["elastic_restamp"]["to"]["pp"] == 1
    topo = elastic.saved_topology(step_dir)
    assert topo["pp"] == 1 and topo["world_size"] == 1

    from picotron_tpu.ckpt_integrity import verify_step_dir
    assert verify_step_dir(step_dir).status == "verified"

    cfg_b = make_cfg(tmp_path, pp_size=1, mbs=2, ga=2)
    menv_b = MeshEnv.from_config(cfg_b)
    template = init_sharded_state(cfg_b, menv_b, jax.random.key(1))
    restored, meta2 = CheckpointManager(cfg_b, menv_b).restore(template)
    assert "elastic_resize" not in meta2
    np.testing.assert_array_equal(
        np.asarray(restored.params["embedding"]),
        np.asarray(state.params["embedding"]))


def test_elastic_resize_tool_restamps_joint_dp_pp(tmp_path):
    """--dp and --pp together: the dp half re-factors the batch at
    constant global batch, the pp half re-stamps the stage count, and one
    manifest re-commit covers both."""
    cfg_a = make_cfg(tmp_path, dp_size=2, pp_size=2, mbs=2, ga=1)  # gbs 4
    _save_step(cfg_a)
    save_dir = cfg_a.checkpoint.save_dir
    [step_dir] = [os.path.join(save_dir, d) for d in os.listdir(save_dir)
                  if d.startswith("step_")]
    tool = _load_tool()
    assert tool.main([save_dir, "--dp", "1", "--pp", "1"]) == 0
    meta = json.load(open(os.path.join(step_dir, "meta.json")))
    assert meta["config"]["distributed"]["dp_size"] == 1
    assert meta["config"]["distributed"]["pp_size"] == 1
    assert meta["config"]["training"]["micro_batch_size"] == 2
    assert meta["config"]["training"]["gradient_accumulation_steps"] == 2
    topo = elastic.saved_topology(step_dir)
    assert topo["dp"] == 1 and topo["pp"] == 1 and topo["world_size"] == 1


def test_elastic_resize_tool_restamps_slices(tmp_path):
    """--slices on the offline tool (the slice-loss recovery re-stamp):
    a 2-slice store records slices=2 in its manifest topology; a target
    count the resumed config would refuse (slices > dp*pp) is rejected
    with the store untouched; --slices 1 re-stamps it single-slice as
    pure placement metadata — dp and the batch plan untouched — and the
    step re-verifies."""
    cfg_a = make_cfg(tmp_path, dp_size=2, tp_size=2, mbs=2, ga=1,
                     slices=2, dcn_axes="dp")
    _save_step(cfg_a)
    save_dir = cfg_a.checkpoint.save_dir
    [step_dir] = [os.path.join(save_dir, d) for d in os.listdir(save_dir)
                  if d.startswith("step_")]
    # satellite pin: the manifest topology records the slice count
    topo = elastic.saved_topology(step_dir)
    assert topo["slices"] == 2
    assert elastic.describe_topology(topo).endswith("slices2")

    tool = _load_tool()
    before = open(os.path.join(step_dir, "meta.json")).read()
    assert tool.main([save_dir, "--slices", "3"]) == 1  # 3 ∤ dp*pp = 2
    assert open(os.path.join(step_dir, "meta.json")).read() == before

    assert tool.main([save_dir, "--slices", "1"]) == 0
    meta = json.load(open(os.path.join(step_dir, "meta.json")))
    assert meta["config"]["distributed"]["slices"] == 1
    assert meta["config"]["distributed"]["dp_size"] == 2   # untouched
    assert meta["config"]["training"]["micro_batch_size"] == 2
    assert meta["config"]["training"]["gradient_accumulation_steps"] == 1
    assert meta["elastic_restamp"]["to"]["slices"] == 1
    topo = elastic.saved_topology(step_dir)
    assert topo.get("slices", 1) == 1 and topo["dp"] == 2

    from picotron_tpu.ckpt_integrity import verify_step_dir
    assert verify_step_dir(step_dir).status == "verified"


# ---------------------------------------------------------------------------
# ckpt_doctor source-topology column
# ---------------------------------------------------------------------------


def test_ckpt_doctor_reports_source_topology(tmp_path, capsys):
    import importlib.util

    cfg = make_cfg(tmp_path, dp_size=2, tp_size=2, slices=2,
                   dcn_axes="dp")
    _save_step(cfg)
    spec = importlib.util.spec_from_file_location(
        "ckpt_doctor_topo", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "ckpt_doctor.py"))
    doctor = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = doctor
    spec.loader.exec_module(doctor)

    rows = doctor.scan(cfg.checkpoint.save_dir)
    assert rows[0]["topology"]["dp"] == 2
    assert rows[0]["topology"]["tp"] == 2
    assert rows[0]["topology"]["slices"] == 2

    assert doctor.main([cfg.checkpoint.save_dir, "--markdown"]) == 0
    md = capsys.readouterr().out
    assert "dp2 pp1 ep1 cp1 tp2 slices2" in md
    assert "| step | verdict | topology |" in md
    assert doctor.main([cfg.checkpoint.save_dir, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["steps"][0]["topology"]["dp"] == 2
    assert out["steps"][0]["topology"]["slices"] == 2
