"""Resilience subsystem tests (picotron_tpu/resilience): chaos spec
parsing and firing, retry backoff, divergence-guard policies, watchdog,
preemption handler, the in-jit non-finite skip, loader reset/retry, and
checkpoint-save retry — all on CPU, fault injection included (the chaos
harness exists precisely so these paths are tier-1-testable instead of
being exercised for the first time by a real outage). The slow tier runs
tools/chaos.py's full kill-and-recover scenarios."""

import json
import os
import random
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from picotron_tpu.config import config_from_dict
from picotron_tpu.resilience import (
    DivergenceGuard, GuardAction, PreemptionHandler, RetryPolicy, Watchdog,
    backoff_delays, chaos, retry_call,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _chaos_hygiene():
    """Chaos state is process-global (library injection points reach it
    without plumbing); every test starts and ends inert."""
    chaos.install("")
    yield
    chaos.install("")


# ---------------------------------------------------------------------------
# chaos spec + controller
# ---------------------------------------------------------------------------


def test_chaos_spec_parses_all_fields():
    evs = chaos.parse_spec("sigterm@3, ckpt_io@2x2,data_stall@4~1.5,"
                           "nan_grad@5x2")
    assert [(e.kind, e.step, e.count, e.secs) for e in evs] == [
        ("sigterm", 3, 1, 0.0), ("ckpt_io", 2, 2, 0.0),
        ("data_stall", 4, 1, 1.5), ("nan_grad", 5, 2, 0.0)]
    assert chaos.parse_spec("") == []


@pytest.mark.parametrize("bad", [
    "bogus@3",        # unknown kind
    "sigterm",        # missing @STEP
    "ckpt_io@x",      # non-numeric step
    "data_stall@3",   # sleep kind without ~SECS
    "hang@2~0",       # zero-duration sleep
    "ckpt_io@2#1",    # #TICK on a kind with no schedule_tick meaning
    "nan_grad@3#2",   # ditto — poison is a step property, not an op one
    "slice_lost@3#1",  # a slice dies between steps, never mid-schedule
])
def test_chaos_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        chaos.parse_spec(bad)


def test_chaos_spec_errors_are_actionable():
    """The two parse errors teach the full surface: a malformed event
    names the complete KIND@STEP[xCOUNT][~SECS][#TICK] grammar, an
    unknown kind enumerates every valid kind (slice_lost included) —
    and the module docstring documents the grammar it parses."""
    with pytest.raises(ValueError, match=r"\[xCOUNT\]\[~SECS\]\[#TICK\]"):
        chaos.parse_spec("sigterm")
    with pytest.raises(ValueError) as ei:
        chaos.parse_spec("bogus@3")
    for kind in chaos.KINDS:
        assert kind in str(ei.value), kind
    assert "slice_lost" in chaos.KINDS
    assert "slice_lost" in chaos.__doc__
    assert "slice_lost" in chaos._POINT_KINDS["step_begin"]
    assert "slice_lost" not in chaos._TICK_KINDS


def test_chaos_slice_lost_fires_sigkill_naming_the_slice(monkeypatch,
                                                         capsys):
    """slice_lost: SIGKILL at step_begin of the named step — per process,
    like a whole slice going dark at once — with the lost slice named in
    the log so a multi-host transcript is attributable."""
    calls = []
    monkeypatch.setattr(chaos.os, "kill",
                        lambda pid, sig: calls.append((pid, sig)))
    ctrl = chaos.ChaosController(chaos.parse_spec("slice_lost@3"))
    ctrl.fire("step_begin", step=2)
    assert not calls
    ctrl.fire("step_begin", step=3)
    assert calls == [(os.getpid(), signal.SIGKILL)]
    ctrl.fire("step_begin", step=3)  # budget of 1: exhausted
    assert len(calls) == 1
    err = capsys.readouterr().err
    assert "slice_lost: the slice hosting process" in err
    assert "elastic_resize.py --slices" in err


def test_chaos_spec_parses_tick_suffix():
    """`KIND@STEP#TICK` addresses a named schedule tick inside the MPMD
    walk; the suffix composes with ~SECS and is None when absent."""
    evs = chaos.parse_spec("sigterm@3#2,hang@4~120#1,kill@5")
    assert [(e.kind, e.step, e.tick) for e in evs] == [
        ("sigterm", 3, 2), ("hang", 4, 1), ("kill", 5, None)]
    assert evs[1].secs == 120.0
    ctrl = chaos.ChaosController(evs)
    assert ctrl.has_tick_events()
    assert "#2" in ctrl.describe() and "#1" in ctrl.describe()
    assert not chaos.ChaosController(
        chaos.parse_spec("sigterm@3")).has_tick_events()


def test_chaos_tick_events_fire_only_at_matching_schedule_tick():
    """The two injection sites are disjoint: a #TICK event ignores
    step_begin and non-matching ticks; an event WITHOUT a tick never
    fires at schedule_tick (it would double-fire with step_begin)."""
    ctrl = chaos.ChaosController(
        chaos.parse_spec("hang@2~0.01#3,ckpt_io@2x1"))
    tick_ev = next(e for e in ctrl.events if e.kind == "hang")
    ctrl.fire("step_begin", step=2)          # #3 event: not its point
    assert tick_ev.fired == 0
    ctrl.fire("schedule_tick", step=2, tick=1, stage=0, op="F", mb=0)
    assert tick_ev.fired == 0                # wrong tick
    ctrl.fire("schedule_tick", step=1, tick=3, stage=0, op="F", mb=0)
    assert tick_ev.fired == 0                # wrong step
    # the tick-less ckpt_io event must NOT raise here either — it is
    # bound to its own points, and never to schedule_tick
    ctrl.fire("schedule_tick", step=2, tick=3, stage=1, op="B", mb=1)
    assert tick_ev.fired == 1                # the named (step, tick)


def test_chaos_io_event_fires_count_times_then_exhausts():
    ctrl = chaos.ChaosController(chaos.parse_spec("ckpt_io@2x2"))
    ctrl.fire("ckpt_save", step=1)  # wrong step: no-op
    for _ in range(2):
        with pytest.raises(OSError):
            ctrl.fire("ckpt_save", step=2)
    ctrl.fire("ckpt_save", step=2)  # budget exhausted: passes
    ctrl.fire("data_produce", step=2)  # wrong point: never fires


def test_chaos_nan_budget_survives_rollback_reencounter():
    """nan_grad@4 poisons the FIRST execution of step 4 only — after a
    guard rollback re-runs step 4, the exhausted event must stay quiet or
    the run would re-live the divergence forever."""
    ctrl = chaos.ChaosController(chaos.parse_spec("nan_grad@4x2"))
    assert ctrl.has_nan_grad()
    assert not ctrl.poison_step(3)
    assert ctrl.poison_step(4)
    assert ctrl.poison_step(5)      # x2: second consecutive execution
    assert not ctrl.poison_step(4)  # re-encounter after rollback: exhausted
    assert not chaos.ChaosController([]).has_nan_grad()


def test_chaos_env_var_overrides_config_spec(monkeypatch):
    monkeypatch.setenv("PICOTRON_CHAOS", "")
    assert not chaos.install("sigterm@3").active  # supervisor-restart story
    monkeypatch.setenv("PICOTRON_CHAOS", "ckpt_io@1")
    ctrl = chaos.install("")
    assert [e.kind for e in ctrl.events] == ["ckpt_io"]


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------


def test_backoff_delays_double_and_cap():
    pol = RetryPolicy(attempts=5, base_delay=0.5, max_delay=3.0, jitter=0.0)
    assert list(backoff_delays(pol)) == [0.5, 1.0, 2.0, 3.0]


def test_backoff_jitter_bounds_are_deterministic_with_seeded_rng():
    pol = RetryPolicy(attempts=4, base_delay=1.0, max_delay=100.0,
                      jitter=0.5)
    a = list(backoff_delays(pol, rng=random.Random(7)))
    b = list(backoff_delays(pol, rng=random.Random(7)))
    assert a == b
    for base, got in zip([1.0, 2.0, 4.0], a):
        assert base <= got <= base * 1.5


def test_retry_call_recovers_then_reraises():
    calls, sleeps = [], []
    pol = RetryPolicy(attempts=3, base_delay=0.25, max_delay=1.0,
                      jitter=0.0)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("blip")
        return 42

    assert retry_call(flaky, policy=pol, sleep=sleeps.append) == 42
    assert len(calls) == 3 and sleeps == [0.25, 0.5]

    def dead():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(dead, policy=pol, sleep=sleeps.append)


def test_retry_call_does_not_retry_programming_errors():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry_call(broken, policy=RetryPolicy(attempts=5, base_delay=0.0),
                   sleep=lambda d: None)
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# divergence guard
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("policy,action", [
    ("skip", GuardAction.SKIP),
    ("rollback", GuardAction.ROLLBACK),
    ("abort", GuardAction.ABORT),
])
def test_guard_nonfinite_maps_policy_to_action(policy, action):
    g = DivergenceGuard(policy, max_trips=5)
    assert g.observe(1, 2.5) == (GuardAction.OK, "")
    got, why = g.observe(2, float("nan"))
    assert got is action and "non-finite" in why
    # the in-step detector flag alone must also trip
    got, _ = g.observe(3, 2.5, nonfinite=1.0)
    assert got is action


def test_guard_consecutive_trips_escalate_to_abort():
    g = DivergenceGuard("skip", max_trips=3)
    g.observe(1, 1.0)
    assert g.observe(2, float("inf"))[0] is GuardAction.SKIP
    assert g.observe(3, float("inf"))[0] is GuardAction.SKIP
    action, why = g.observe(4, float("inf"))
    assert action is GuardAction.ABORT and "not recovering" in why
    # a healthy step resets the streak
    g2 = DivergenceGuard("skip", max_trips=2)
    for s in range(1, 9, 2):
        assert g2.observe(s, 1.0)[0] is GuardAction.OK
        assert g2.observe(s + 1, float("nan"))[0] is GuardAction.SKIP


def test_guard_spike_zscore_trips_and_quarantines():
    g = DivergenceGuard("rollback", spike_zscore=6.0, spike_window=8)
    rng = np.random.default_rng(0)
    for s in range(8):  # fill the window with ~N(2, 0.01) losses
        assert g.observe(s, 2.0 + 0.01 * rng.standard_normal())[0] \
            is GuardAction.OK
    action, why = g.observe(9, 8.0)
    assert action is GuardAction.ROLLBACK and "spike" in why
    # the spike was NOT folded into the window: a repeat still trips
    assert g.observe(10, 8.0)[0] is GuardAction.ROLLBACK
    # normal losses keep flowing
    assert g.observe(11, 2.0)[0] is GuardAction.OK


def test_guard_spike_needs_full_window_and_ignores_descent():
    g = DivergenceGuard("abort", spike_zscore=3.0, spike_window=8)
    # window not yet full: even a big jump is not judged
    assert g.observe(1, 2.0)[0] is GuardAction.OK
    assert g.observe(2, 50.0)[0] is GuardAction.OK
    g2 = DivergenceGuard("abort", spike_zscore=3.0, spike_window=8)
    for s in range(8):
        g2.observe(s, 5.0 - 0.1 * s)
    # downward movement (ordinary descent) never trips
    assert g2.observe(9, 1.0)[0] is GuardAction.OK


# ---------------------------------------------------------------------------
# watchdog + preemption
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_stall_and_dumps_stacks(capsys):
    fired = []
    w = Watchdog(timeout=0.2, on_timeout=lambda: fired.append(1))
    w.start()
    try:
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        w.stop()
    assert fired
    err = capsys.readouterr().err
    assert "[watchdog] no progress" in err
    assert "picotron-watchdog" in err  # its own stack is in the dump too


def test_watchdog_beats_keep_it_alive():
    fired = []
    w = Watchdog(timeout=0.3, on_timeout=lambda: fired.append(1))
    w.start()
    try:
        for step in range(12):
            w.beat("step", step)
            time.sleep(0.05)
    finally:
        w.stop()
    assert not fired


def test_watchdog_disabled_is_inert():
    w = Watchdog(timeout=0.0)
    w.start()
    assert not w.started  # timeout 0 never spawns the thread
    w.beat("step", 1)
    w.stop()


def test_retry_backoff_heartbeats_watchdog():
    """A legitimate retry backoff longer than the watchdog timeout must
    not be misread as a hang (retry sleeps are chunked + heartbeat)."""
    fired = []
    w = Watchdog(timeout=1.5, on_timeout=lambda: fired.append(1))
    w.start()
    try:
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("blip")
            return "ok"

        got = retry_call(flaky, policy=RetryPolicy(
            attempts=2, base_delay=2.5, max_delay=2.5, jitter=0.0))
        assert got == "ok"
        time.sleep(0.1)
    finally:
        w.stop()
    assert not fired


def test_watchdog_fire_writes_flight_postmortem(tmp_path):
    """The flightdeck pin: when the watchdog fires, the installed
    telemetry facade's flight recorder dumps its last-K-steps window
    BEFORE the exit path runs — the postmortem is the only record an
    os._exit(77) leaves behind."""
    from picotron_tpu.telemetry import Telemetry, bus
    from picotron_tpu.telemetry.flightdeck import FlightRecorder
    from picotron_tpu.telemetry.flightdeck.flight import POSTMORTEM_NAME

    tel = Telemetry(sinks=[])
    tel.flight = FlightRecorder(str(tmp_path), max_steps=4)
    bus.install(tel)
    fired = []
    try:
        tel.emit("phase", phase="data", secs=0.1, book=False, step=3)
        tel.record_step(3, "[step] ...", loss=2.0)
        w = Watchdog(timeout=0.2, on_timeout=lambda: fired.append(1))
        w.start()
        try:
            deadline = time.monotonic() + 5.0
            while not fired and time.monotonic() < deadline:
                time.sleep(0.05)
        finally:
            w.stop()
    finally:
        tel.close()
    assert fired
    doc = json.load(open(tmp_path / POSTMORTEM_NAME))
    assert doc["reason"] == "watchdog"
    assert doc["step"] == 3  # the last step the recorder saw
    assert doc["steps"][-1]["step"] == 3
    assert doc["extra"]["stalled_s"] >= 0.2
    # the watchdog_timeout bus event reached the recorder too
    assert any(e["kind"] == "watchdog_timeout"
               for e in doc["recent_events"])


def test_preemption_handler_catches_sigterm_and_restores():
    h = PreemptionHandler()
    prev = signal.getsignal(signal.SIGTERM)
    assert h.install()
    try:
        assert not h.triggered
        signal.raise_signal(signal.SIGTERM)
        assert h.triggered and h.signum == signal.SIGTERM
    finally:
        h.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_preemption_second_sigint_raises_keyboardinterrupt():
    with PreemptionHandler() as h:
        signal.raise_signal(signal.SIGINT)
        assert h.triggered
        with pytest.raises(KeyboardInterrupt):
            signal.raise_signal(signal.SIGINT)


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def _cfg(resilience=None, training=None, **kw):
    raw = {"model": {"name": "debug-tiny", "dtype": "float32"},
           "training": {"seq_length": 32, **(training or {})},
           "resilience": resilience or {}, **kw}
    return config_from_dict(raw)


def test_resilience_config_defaults_and_validation():
    cfg = _cfg()
    assert cfg.resilience.guard_policy == "abort"
    assert cfg.resilience.watchdog_timeout == 0.0
    with pytest.raises(ValueError):
        _cfg(resilience={"guard_policy": "retry"})
    with pytest.raises(ValueError):
        _cfg(resilience={"chaos": "bogus@3"})
    with pytest.raises(ValueError):
        _cfg(resilience={"retry_attempts": 0})
    with pytest.raises(ValueError):
        _cfg(resilience={"spike_zscore": -1.0})
    with pytest.raises(ValueError):
        _cfg(resilience={"watchdog_timeout": -5})


def test_offload_rejects_in_jit_skip_policy():
    with pytest.raises(ValueError, match="guard_policy"):
        _cfg(resilience={"guard_policy": "skip"},
             training={"optimizer_offload": True,
                       "gradient_accumulation_steps": 2},
             model={"name": "debug-tiny", "dtype": "bfloat16"})


def test_eval_steps_zero_with_eval_enabled_rejected():
    # the train.py:236 ZeroDivisionError class of config: eval on, no
    # batches to average over
    with pytest.raises(ValueError, match="eval"):
        _cfg(training={"eval_frequency": 2, "eval_steps": 0})


# ---------------------------------------------------------------------------
# in-jit guard + loader + checkpoint integration (single tiny compile each)
# ---------------------------------------------------------------------------


def _tiny_cfg(**resilience):
    return config_from_dict({
        "distributed": {"dp_size": 1},
        "model": {"name": "debug-tiny", "dtype": "float32"},
        "training": {"seq_length": 16, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1, "remat": False},
        "resilience": resilience,
    })


def test_in_jit_skip_preserves_state_on_injected_nan():
    """The poisoned step (chaos nan_grad path) must leave params AND
    optimizer state bit-identical under policy 'skip', advance the step
    counter, and flag the metrics; the next clean step must train."""
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    cfg = _tiny_cfg(guard_policy="skip")
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    dl = MicroBatchDataLoader(cfg, menv)
    poison_fn = make_train_step(cfg, menv, inject_nan=True)
    step_fn = make_train_step(cfg, menv)

    before = [np.asarray(x).copy() for x in jax.tree.leaves(state.params)]
    state, m = poison_fn(state, next(dl))
    m = {k: float(v) for k, v in jax.block_until_ready(m).items()}
    assert m["nonfinite"] == 1.0 and not np.isfinite(m["loss"])
    assert not np.isfinite(m["grad_norm"])
    after = [np.asarray(x) for x in jax.tree.leaves(state.params)]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)
    assert int(state.step) == 1  # the skipped batch still counts a step

    state, m = step_fn(state, next(dl))
    m = {k: float(v) for k, v in jax.block_until_ready(m).items()}
    assert m["nonfinite"] == 0.0 and np.isfinite(m["grad_norm"])
    clean = [np.asarray(x) for x in jax.tree.leaves(state.params)]
    assert any(not np.array_equal(b, c) for b, c in zip(before, clean))


def test_guard_metrics_absent_when_policy_off():
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    cfg = _tiny_cfg(guard_policy="off")
    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    dl = MicroBatchDataLoader(cfg, menv)
    _, m = make_train_step(cfg, menv)(state, next(dl))
    assert set(m) == {"loss"}


def test_loader_retries_chaos_data_io_and_resets():
    """An injected transient read failure costs a (tiny) backoff, not the
    run, and the delivered stream is unchanged; reset() repositions an
    already-running prefetch loader for the rollback path."""
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.mesh import MeshEnv

    cfg = config_from_dict({
        "distributed": {"dp_size": 1},
        "model": {"name": "debug-tiny"},
        "training": {"seq_length": 16, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1},
        "dataset": {"num_workers": 2},
        "resilience": {"retry_base_delay": 0.01, "retry_max_delay": 0.02},
    })
    menv = MeshEnv.from_config(cfg)

    def batches(dl, n):
        return [np.asarray(next(dl)[0]) for _ in range(n)]

    clean = batches(MicroBatchDataLoader(cfg, menv), 4)

    chaos.install("data_io@2x2")  # two failures assembling batch 2
    dl = MicroBatchDataLoader(cfg, menv)
    faulted = batches(dl, 4)
    for c, f in zip(clean, faulted):
        np.testing.assert_array_equal(c, f)

    # rollback repositioning: jump back to the start of batch 3
    dl.reset({"epoch": 0, "cursor": 2 * cfg.global_batch_size})
    np.testing.assert_array_equal(np.asarray(next(dl)[0]), clean[2])
    dl.close()


def test_loader_exhausted_retries_surface_on_training_thread():
    from picotron_tpu.data import MicroBatchDataLoader
    from picotron_tpu.mesh import MeshEnv

    cfg = config_from_dict({
        "distributed": {"dp_size": 1},
        "model": {"name": "debug-tiny"},
        "training": {"seq_length": 16, "micro_batch_size": 2,
                     "gradient_accumulation_steps": 1},
        "dataset": {"num_workers": 2},
        "resilience": {"retry_attempts": 2, "retry_base_delay": 0.01,
                       "retry_max_delay": 0.02},
    })
    menv = MeshEnv.from_config(cfg)
    chaos.install("data_io@1x99")  # outlasts the 2-attempt budget
    dl = MicroBatchDataLoader(cfg, menv)
    with pytest.raises(RuntimeError, match="prefetch thread died"):
        next(dl)
    dl.close()


def _toy_state(step=2):
    from picotron_tpu.train_step import TrainState

    return TrainState(params={"w": jnp.arange(4.0)},
                      opt_state={"m": jnp.zeros(4)},
                      step=jnp.asarray(step, jnp.int32))


def test_checkpoint_save_retries_injected_io_error(tmp_path):
    from picotron_tpu.checkpoint import CheckpointManager

    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(tmp_path), "save_frequency": 1,
                       "async_save": False},
        "resilience": {"retry_base_delay": 0.01, "retry_max_delay": 0.02},
    })
    chaos.install("ckpt_io@2x2")  # default 3 attempts absorb 2 failures
    mgr = CheckpointManager(cfg)
    mgr.save(_toy_state(step=2), trained_tokens=128,
             dataloader_state={"epoch": 0, "cursor": 8})
    assert mgr.latest_step() == 2
    meta = json.load(open(tmp_path / "step_00000002" / "meta.json"))
    assert meta["trained_tokens"] == 128

    chaos.install("ckpt_io@3x99")  # outlasts the budget: surfaces
    with pytest.raises(OSError):
        mgr.save(_toy_state(step=3))


def test_durability_probe_retries_transient_errors(tmp_path):
    """The promoted _probe_failed path: a transient metadata-read error
    must cost a short retry, not hide a durable checkpoint from
    auto_resume."""
    from picotron_tpu.checkpoint import CheckpointManager

    cfg = config_from_dict({
        "model": {"name": "debug-tiny"},
        "checkpoint": {"save_dir": str(tmp_path), "save_frequency": 1,
                       "async_save": False},
        "resilience": {"retry_base_delay": 0.01, "retry_max_delay": 0.02},
    })
    mgr = CheckpointManager(cfg)
    mgr.save(_toy_state(step=4))

    real = mgr._ocp.utils.is_checkpoint_finalized
    calls = []

    class FlakyUtils:
        @staticmethod
        def is_checkpoint_finalized(path):
            calls.append(1)
            if len(calls) == 1:
                raise OSError("transient metadata blip")
            return real(path)

    class FakeOcp:
        utils = FlakyUtils

    mgr._ocp = FakeOcp
    assert mgr.latest_step() == 4  # first probe attempt failed, retry won
    assert len(calls) >= 2


# ---------------------------------------------------------------------------
# full kill-and-recover scenarios (tools/chaos.py) — slow tier
# ---------------------------------------------------------------------------


def _load_chaos_cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_cli", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "chaos.py"))
    mod = importlib.util.module_from_spec(spec)
    # registered under its spec name so dataclasses can resolve the
    # module's postponed annotations (PEP 563 strings) during class build
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_chaos_cli_lists_every_scenario(capsys):
    cli = _load_chaos_cli()
    assert cli.main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in ("sigterm", "ckpt_io", "nan_skip", "nan_rollback",
                 "data_stall", "ckpt_corrupt_bitflip", "dp_resize",
                 "pp_resize", "slice_lost", "mpmd_sigterm",
                 "serve_engine_dead", "serve_overload"):
        assert name in out


def test_chaos_postmortem_matcher_structural(tmp_path):
    """Fast structural pin for the scenarios' check_after_fault hook:
    tools/chaos.py asserts that each abnormal exit left a flightdeck
    postmortem whose reason and last recorded step equal the injected
    fault — exercised here against crafted dumps instead of a full
    kill-and-recover run."""
    cli = _load_chaos_cli()
    p = tmp_path / "flightdeck_postmortem.json"
    # the hook is wired into the three abnormal-exit scenarios, each
    # bound to its fault's reason and injection step
    expected = {"sigterm": ("preempted", cli.STEPS // 2),
                "nan_rollback": ("rollback", cli.STEPS - 2),
                "data_stall": ("watchdog", cli.STEPS // 2)}
    for name, (reason, fault_step) in expected.items():
        sc = cli.SCENARIOS[name]
        assert sc.check_after_fault is not None, name
        if p.exists():
            p.unlink()
        # with no postmortem on disk the scenario must fail loudly
        err = sc.check_after_fault(str(tmp_path))
        assert err and "flightdeck_postmortem.json" in err, (name, err)
        # the matching dump passes; a wrong reason or step does not
        p.write_text(json.dumps({
            "reason": reason, "step": fault_step, "ts": 0.0,
            "steps": [{"step": fault_step, "phases": {"step": 1.0}}],
            "recent_events": []}))
        assert sc.check_after_fault(str(tmp_path)) is None, name
        p.write_text(json.dumps({
            "reason": "exception", "step": fault_step, "ts": 0.0,
            "steps": [{"step": fault_step}], "recent_events": []}))
        assert "reason" in sc.check_after_fault(str(tmp_path)), name
    good = {"reason": "preempted", "step": 3, "ts": 0.0,
            "steps": [{"step": 3, "phases": {"step": 1.0}}],
            "recent_events": []}
    p.write_text(json.dumps(good))
    assert "step" in cli._postmortem_matches(
        str(tmp_path), reason="preempted", fault_step=4)
    p.write_text(json.dumps({**good, "steps": []}))
    assert "empty" in cli._postmortem_matches(
        str(tmp_path), reason="preempted", fault_step=3)
    p.write_text("{torn")
    assert "unreadable" in cli._postmortem_matches(
        str(tmp_path), reason="preempted", fault_step=3)


# Per-scenario telemetry assertions: the injected fault's cost must be
# BOOKED — as the named badput categories in the goodput ledger and/or as
# the named event kinds in the stream (picotron_tpu/telemetry; the
# telemetry half of the acceptance criteria).
_SCENARIO_TELEMETRY = {
    "sigterm": {"badput": ["preempt", "restore"],
                "events": ["chaos", "preempt_signal", "preempted"]},
    "ckpt_io": {"badput": ["retry_backoff"],
                "events": ["chaos", "retry"]},
    "nan_skip": {"badput": [], "events": ["chaos", "guard"]},
    "nan_rollback": {"badput": ["restore", "replay"],
                     "events": ["chaos", "guard", "rollback"]},
    # the hung data phase never completes, so the stall time reaches the
    # ledger via the watchdog's own timeout event (category data_wait)
    "data_stall": {"badput": ["restore", "data_wait"],
                   "events": ["chaos", "watchdog_timeout"]},
    # newest committed checkpoint bit-flipped then SIGKILL: the restart's
    # verify-on-restore emits ckpt_corrupt, falls back to the prior
    # verified step, and re-buys the lost ground (replayed steps)
    "ckpt_corrupt_bitflip": {"badput": ["restore"],
                             "events": ["chaos", "ckpt_corrupt",
                                        "ckpt_commit"]},
}


@pytest.mark.slow
@pytest.mark.parametrize("scenario", [
    "sigterm", "ckpt_io", "nan_skip", "nan_rollback", "data_stall",
    "ckpt_corrupt_bitflip"])
def test_chaos_scenario_recovers_to_baseline(tmp_path, scenario):
    """The acceptance contract, both halves: under each injected failure
    the supervised run (a) ends at the same final step and trained_tokens
    as a fault-free baseline — the failure cost restarts, not training
    progress — and (b) leaves a telemetry.jsonl from which
    tools/telemetry_report.py reproduces the run's step count and books
    the injected fault's cost as badput."""
    import importlib.util

    cli = _load_chaos_cli()
    assert cli.run_scenario(scenario, str(tmp_path))

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    stream = os.path.join(tmp_path, "fault", "ckpt", "telemetry.jsonl")
    s = rep.summarize(rep.load_events(stream))
    # every scenario recovers to the full fault-free step count, and the
    # stream (appended across supervised restarts) shows each step trained
    assert s["steps"]["count"] == cli.STEPS
    assert s["steps"]["max"] == cli.STEPS
    expect = _SCENARIO_TELEMETRY[scenario]
    for cat in expect["badput"]:
        assert s["categories"].get(cat, 0.0) > 0, \
            f"{scenario}: badput category {cat!r} not booked: " \
            f"{s['categories']}"
    for kind in expect["events"]:
        assert s["events"].get(kind, 0) > 0, \
            f"{scenario}: event {kind!r} absent: {s['events']}"
    if scenario in ("nan_rollback", "ckpt_corrupt_bitflip"):
        assert s["steps"]["replayed"] > 0  # re-trained ground is counted


@pytest.mark.slow
def test_chaos_dp_resize_scenario(tmp_path):
    """Elastic scale-out, the full multi-process scenario: dp=2 SIGKILLed,
    re-stamped to dp=1 offline, SIGKILLed again, finished at dp=4 via
    checkpoint.elastic. run_dp_resize itself asserts final step/tokens,
    per-step loss-trajectory parity vs the fault-free dp=2 baseline, the
    `resize` goodput booking, and the elastic_resize event; here we
    additionally pin that the whole saga trained every step exactly once
    (no replay — a resize costs restore time, not ground)."""
    import importlib.util

    cli = _load_chaos_cli()
    assert cli.run_dp_resize(str(tmp_path))

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    stream = os.path.join(tmp_path, "fault", "ckpt", "telemetry.jsonl")
    s = rep.summarize(rep.load_events(stream))
    assert s["steps"]["count"] == cli.STEPS
    assert s["steps"]["max"] == cli.STEPS
    assert s["steps"]["replayed"] == 0
    assert s["categories"].get("resize", 0.0) > 0
    assert s["resize"]["events"] >= 1


@pytest.mark.slow
def test_chaos_slice_lost_scenario(tmp_path):
    """Whole-slice loss, the full multi-process scenario: a 2-slice run
    with the hierarchical dp reduction live is killed by slice_lost@3,
    the store is re-stamped single-slice offline (--slices 1), and the
    surviving chips finish at dp=1 via checkpoint.elastic. run_slice_lost
    itself asserts the slice-naming log line, the manifest slice counts
    before/after the re-stamp, final step/tokens, per-step loss parity vs
    the single-slice baseline, and the resize booking; here we pin zero
    replay — losing a slice costs a resize, not ground."""
    import importlib.util

    cli = _load_chaos_cli()
    assert cli.run_slice_lost(str(tmp_path))

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    stream = os.path.join(tmp_path, "fault", "ckpt", "telemetry.jsonl")
    s = rep.summarize(rep.load_events(stream))
    assert s["steps"]["count"] == cli.STEPS
    assert s["steps"]["max"] == cli.STEPS
    assert s["steps"]["replayed"] == 0
    assert s["categories"].get("resize", 0.0) > 0
    assert s["resize"]["events"] >= 1


def _load_telemetry_report():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report", os.path.join(os.path.dirname(__file__), "..",
                                         "tools", "telemetry_report.py"))
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    return rep


@pytest.mark.slow
def test_chaos_pp_resize_scenario(tmp_path):
    """Elastic PIPELINE resize, the full multi-process scenario: pp=2
    MPMD SIGKILLed, re-stamped to pp=1 offline (--pp), SIGKILLed again,
    finished at pp=2 via checkpoint.elastic. run_pp_resize itself asserts
    loss-trajectory parity, final step/tokens, the resize booking, and
    the compile-once prover pin on the rebuilt stage programs; here we
    additionally pin zero replay across the whole saga."""
    cli = _load_chaos_cli()
    assert cli.run_pp_resize(str(tmp_path))

    rep = _load_telemetry_report()
    stream = os.path.join(tmp_path, "fault", "ckpt", "telemetry.jsonl")
    s = rep.summarize(rep.load_events(stream))
    assert s["steps"]["count"] == cli.STEPS
    assert s["steps"]["max"] == cli.STEPS
    assert s["steps"]["replayed"] == 0
    assert s["categories"].get("resize", 0.0) > 0
    assert s["resize"]["events"] >= 1


@pytest.mark.slow
def test_chaos_mpmd_sigterm_scenario(tmp_path):
    """Mid-schedule fault hardening, the full multi-process scenario:
    SIGTERM at a named (stage, tick, op) drains to the step boundary
    (emergency ckpt, exit 75) and resumes losslessly; a forced
    mid-schedule hang is watchdog-reported naming the live op. The
    runner asserts the log markers; here we re-pin the zero-replay claim
    on the sigterm leg's telemetry stream."""
    cli = _load_chaos_cli()
    assert cli.run_mpmd_sigterm(str(tmp_path))

    rep = _load_telemetry_report()
    stream = os.path.join(tmp_path, "sigterm", "ckpt", "telemetry.jsonl")
    s = rep.summarize(rep.load_events(stream))
    assert s["steps"]["count"] == cli.STEPS
    assert s["steps"]["max"] == cli.STEPS
    assert s["steps"]["replayed"] == 0


# ---------------------------------------------------------------------------
# serve-side chaos (PR 20): grammar + firing semantics + full scenarios
# ---------------------------------------------------------------------------


def test_chaos_serve_grammar_and_points():
    """The serving kinds parse under the unchanged KIND@STEP[xCOUNT]
    [~SECS] grammar (the step position carries the REQUEST id), are
    registered in KINDS and the module docstring, and are admitted only
    at their serve points: storms at routing, hangs at dispatch, engine
    death at both."""
    evs = chaos.parse_spec("engine_dead@4,decode_hang@2~0.5,"
                           "shed_storm@6x3")
    assert [(e.kind, e.step, e.count, e.secs) for e in evs] == [
        ("engine_dead", 4, 1, 0.0), ("decode_hang", 2, 1, 0.5),
        ("shed_storm", 6, 3, 0.0)]
    for kind in ("engine_dead", "decode_hang", "shed_storm"):
        assert kind in chaos.KINDS
        assert kind in chaos.__doc__
    assert chaos._POINT_KINDS["serve_route"] == (
        "engine_dead", "shed_storm")
    assert chaos._POINT_KINDS["serve_dispatch"] == (
        "engine_dead", "decode_hang")
    with pytest.raises(ValueError, match="~SECS"):
        chaos.parse_spec("decode_hang@2")  # a hang needs a duration


def test_chaos_engine_dead_raises_with_engine_ctx():
    """engine_dead raises ChaosEngineDead carrying the engine id from
    the firing context — at either serve point, once per budget."""
    ctrl = chaos.ChaosController(chaos.parse_spec("engine_dead@5"))
    ctrl.fire("serve_route", 4, engine=1)  # wrong request: inert
    with pytest.raises(chaos.ChaosEngineDead) as ei:
        ctrl.fire("serve_route", 5, engine=1)
    assert ei.value.engine == 1
    ctrl.fire("serve_route", 5, engine=1)  # budget of 1: exhausted

    ctrl = chaos.ChaosController(chaos.parse_spec("engine_dead@7"))
    with pytest.raises(chaos.ChaosEngineDead) as ei:
        ctrl.fire("serve_dispatch", 7, engine=0)
    assert ei.value.engine == 0


def test_chaos_decode_hang_sleeps_inside_dispatch():
    ctrl = chaos.ChaosController(chaos.parse_spec("decode_hang@2~0.05"))
    t0 = time.monotonic()
    ctrl.fire("serve_dispatch", 2, engine=0)
    assert time.monotonic() - t0 >= 0.05
    t0 = time.monotonic()
    ctrl.fire("serve_dispatch", 2, engine=0)  # budget drained: no sleep
    assert time.monotonic() - t0 < 0.05


def test_chaos_shed_storm_budget_is_consecutive():
    """shed_storm@REQxN is a STORM: it arms on request REQ and then
    sheds every subsequently routed request until the xCOUNT budget
    drains — one event models a contiguous overload burst."""
    ctrl = chaos.ChaosController(chaos.parse_spec("shed_storm@6x3"))
    ctrl.fire("serve_route", 5, engine=0)  # before REQ: inert
    for rid in (6, 7, 8):
        with pytest.raises(chaos.ChaosShed):
            ctrl.fire("serve_route", rid, engine=0)
    ctrl.fire("serve_route", 9, engine=0)  # budget drained
    # storms exist only at routing, never inside a dispatch
    ctrl = chaos.ChaosController(chaos.parse_spec("shed_storm@6"))
    ctrl.fire("serve_dispatch", 6, engine=0)


@pytest.mark.slow
def test_chaos_serve_engine_dead_scenario(tmp_path):
    """Fleet failover, the full subprocess scenario: bench --serve
    --fleet 2 with engine_dead@2 kills a replica mid-burst; the runner
    asserts all 8 requests finish with per-request token digests
    bit-identical to the fleet-of-1 oracle at temperature 0.7, at least
    one re-dispatch, zero leaked blocks, a serve_engine_dead flightdeck
    postmortem, and digest-exact determinism across a repeat leg."""
    cli = _load_chaos_cli()
    assert cli.run_serve_engine_dead(str(tmp_path))


@pytest.mark.slow
def test_chaos_serve_overload_scenario(tmp_path):
    """Deadline load shedding, the full subprocess scenario: a 1-slot
    engine under a 10-request burst with deadline_ms=6 sheds the tail
    deterministically (same shed ids on a repeat leg), the admitted
    requests' digests match the no-deadline leg, queue-wait p95 stays
    within the deadline, and telemetry_report books the shed seconds
    under the `shed` badput category and renders the serving view."""
    cli = _load_chaos_cli()
    assert cli.run_serve_overload(str(tmp_path))
