"""analysis/trace.py coverage: the abstract lowering that feeds every
analyzer (collectives audit, hazards, cost pricing) must capture the
lowered module text without materializing arrays, for single- and
multi-axis layouts, and across the compat.py JAX-version shim (the
`jax.shard_map` vs `jax.experimental.shard_map` spelling)."""

import jax
import pytest

from picotron_tpu import compat
from picotron_tpu.analysis.collectives import parse_collectives
from picotron_tpu.analysis.trace import abstract_batch, lower_train_step
from picotron_tpu.config import (
    Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset,
)
from picotron_tpu.mesh import MeshEnv


def mkcfg(dist=None, ga=1, seq=64):
    cfg = Config(
        distributed=DistributedConfig(**(dist or {})),
        model=ModelConfig(name="debug-tiny",
                          **resolve_preset("debug-tiny")),
        training=TrainingConfig(seq_length=seq, micro_batch_size=1,
                                gradient_accumulation_steps=ga),
    )
    cfg.validate()
    return cfg


def test_lowering_captures_module_text_without_materializing():
    low = lower_train_step(mkcfg())
    # the five LoweredStep fields are all populated
    assert isinstance(low.text, str) and len(low.text) > 100
    assert "module" in low.text  # StableHLO module header
    assert low.lowered is not None and low.step_fn is not None
    # state and batch are ABSTRACT: shape/dtype only, nothing on device
    for leaf in jax.tree_util.tree_leaves(low.state):
        assert isinstance(leaf, jax.ShapeDtypeStruct), type(leaf)
    ids, targets = low.batch
    assert isinstance(ids, jax.ShapeDtypeStruct)
    assert ids.shape == targets.shape == (1, 1, 64)


def test_abstract_batch_shape_tracks_layout():
    cfg = mkcfg(dist=dict(dp_size=2, cp_size=2), ga=3)
    menv = MeshEnv.from_config(cfg)
    ids, targets = abstract_batch(cfg, menv)
    # [grad_acc, dp*ep*mbs, seq], seq kept FULL (cp shards via sharding)
    assert ids.shape == (3, 2, 64)
    assert ids.sharding.spec == menv.batch_sharding().spec


def test_lowered_text_carries_the_promised_collectives():
    # the dp=2 grad all-reduce must be parseable straight off the capture
    low = lower_train_step(mkcfg(dist=dict(dp_size=2), ga=2))
    ops = [op for op in parse_collectives(low.text) if op.effective]
    assert any(op.kind == "all_reduce" and op.group_size == 2
               for op in ops), low.text[:500]


def test_explicit_menv_is_honored():
    cfg = mkcfg(dist=dict(dp_size=2))
    menv = MeshEnv.from_config(cfg)
    low = lower_train_step(cfg, menv)
    assert low.batch[0].sharding.mesh == menv.mesh


def test_lowering_across_compat_shim(monkeypatch):
    """compat.shard_map falls back to jax.experimental.shard_map when the
    public spelling is absent (pre-vma JAX). On new JAX, hiding
    jax.shard_map must yield the same lowered collective schedule; on
    pre-vma JAX the experimental spelling IS the live path, and the shim
    must report it consistently — either way the capture works and the
    analyzers cannot tell the difference."""
    cfg = mkcfg(dist=dict(dp_size=2), ga=2)
    ref_ops = [(op.kind, op.group_size) for op in
               parse_collectives(lower_train_step(cfg).text)
               if op.effective]
    assert ("all_reduce", 2) in ref_ops

    if hasattr(jax, "shard_map"):
        pytest.importorskip("jax.experimental.shard_map")
        monkeypatch.delattr(jax, "shard_map")
        assert not hasattr(jax, "shard_map")  # compat takes the old path
        shim_ops = [(op.kind, op.group_size) for op in
                    parse_collectives(lower_train_step(cfg).text)
                    if op.effective]
        assert sorted(map(str, shim_ops)) == sorted(map(str, ref_ops))
    else:
        # pre-vma JAX: the lowering above already went through the
        # experimental spelling; the shim must agree there is no vma
        # type system to lean on
        import importlib

        importlib.import_module("jax.experimental.shard_map")  # must exist
        assert not compat.HAS_VMA
        assert compat.vma(jax.numpy.ones(())) == frozenset()


def test_compat_pcast_vma_are_consistent():
    """The shim helpers trace.py's lowering leans on: pcast is value-
    identity, vma returns a set, and require_vma raises only without the
    vma type system."""
    import numpy as np

    x = jax.numpy.ones((4,))
    y = compat.pcast(x, ("dp",)) if compat.HAS_VMA else compat.pcast(x, ())
    np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert isinstance(compat.vma(x), frozenset)
    if compat.HAS_VMA:
        compat.require_vma("test")  # must not raise
    else:
        with pytest.raises(RuntimeError):
            compat.require_vma("test")
