#!/usr/bin/env python
"""Benchmark harness: train-step throughput + MFU on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (plus
auxiliary fields). `vs_baseline` compares achieved MFU against the driver's
north-star bar of 40% MFU (BASELINE.json; the reference reports ~50% MFU for
SmolLM-1.7B on 8xH100 and 38% for Llama-2-7B on 64xH100, ref: README.md:7).

Defaults are sized for a single TPU chip: a depth-reduced SmolLM-1.7B, seq
2048, bf16 compute over fp32 master params. On a multi-chip host it
data-parallelizes over all local chips automatically.

`--sweep` runs the breadth matrix instead (BASELINE.md asks for tokens/s/chip
+ MFU across configurations; DP x TP x PP x CP needs chips this host lacks,
so the single-chip axes are model size / depth, sequence length, and batch):
one JSON line per config, headline config last.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

# (model, layers [None = preset depth], seq, mbs, extra-kwargs) — ordered so
# the headline metric is the LAST line, keeping `python bench.py --sweep |
# tail -1` compatible with the single-run output.
OFFLOAD_24L = dict(grad_acc=64, remat_policy="dots_attn", optimizer_offload=True)
SWEEP = [
    ("SmolLM-360M", None, 2048, 6, {}),   # full-depth model, no reduction
    ("SmolLM-1.7B", 8, 4096, 2, {}),
    ("SmolLM-1.7B", 4, 16384, 1, {}),     # long-context: blocked-KV flash
    ("SmolLM-1.7B", 8, 2048, 5, {}),      # depth-reduced peak-MFU config
    # Llama-2-7B per-layer anchor (the reference's second published
    # figure is 38% at 7B on 64xH100, ref README.md:7): 4 of 32 layers
    # fit one chip with offload; per-layer work (h=4096, 11008-wide MLP,
    # MHA 32:32) is identical to the full model
    ("Llama-2-7B", 4, 4096, 2,
     dict(grad_acc=16, remat_policy="dots_attn", optimizer_offload=True)),
    # MoE on hardware (the reference has no MoE): Mixtral-8x7B's full
    # 1.41 B-param expert bank at 1 layer — GShard capacity dispatch +
    # the streamed update over the [E, H, I] banks (ep=1 on one chip)
    ("Mixtral-8x7B", 1, 2048, 2,
     dict(grad_acc=64, remat_policy="dots", optimizer_offload=True)),
    # FULL depth at seq 4096 — long context + optimizer offload compose
    # (row-group update streaming keeps the embedding/lm_head transients
    # off the peak; PERF.md r4)
    ("SmolLM-1.7B", None, 4096, 1, OFFLOAD_24L),
    # headline: the FULL 24-layer model on one chip — fp32 master + Adam
    # moments live in pinned host memory (optimizer_offload), the fused
    # grad engine accumulates dW in-scan (PERF.md r5), and grad-acc 43
    # amortizes the PCIe round trip (mbs 3 x 43 x 2048 = 264k tokens/step
    # ~= SmolLM's real ~2M-token global batch at the reference's 8-GPU
    # scale; mbs 3 fits because the fused engine never materializes the
    # per-microbatch grad tree). Beats the reference's full-depth ~50%
    # bar (ref: README.md:7).
    ("SmolLM-1.7B", None, 2048, 3, dict(OFFLOAD_24L, grad_acc=43)),
]


def require_backend(force_cpu: bool) -> None:
    """Fail fast, with a one-line story, when no accelerator backend comes
    up — the raw xla_bridge traceback captured in BENCH_r05.json (the axon
    tunnel down mid-round) is exactly what this replaces. `--cpu` pins the
    host platform for a structural smoke run instead."""
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.devices()
    except Exception as e:
        raise SystemExit(
            f"bench: no TPU backend reachable "
            f"(JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<unset>')}, "
            f"{type(e).__name__}); rerun with --cpu or fix the tunnel")


def bench_config(model: str, layers, seq: int, mbs: int, *,
                 grad_acc: int = 1, remat: bool = True,
                 remat_policy: str = "dots",
                 adam_moments_dtype: str = "bfloat16", ce_chunk: int = 0,
                 optimizer_offload: bool = False, n_chips: int = None):
    """The exact Config a bench invocation trains — shared by the timed run
    and `--shardcheck` so the static audit can never drift from what the
    benchmark measures."""
    from picotron_tpu.config import (
        Config, DistributedConfig, ModelConfig, TrainingConfig, resolve_preset,
    )

    n_chips = n_chips if n_chips is not None else len(jax.devices())
    preset = resolve_preset(model)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", seq), seq
    )
    if layers:
        preset["num_hidden_layers"] = layers
    cfg = Config(
        distributed=DistributedConfig(dp_size=n_chips),
        model=ModelConfig(name=model, **preset),
        training=TrainingConfig(
            seq_length=seq,
            micro_batch_size=mbs,
            gradient_accumulation_steps=grad_acc,
            remat=remat,
            remat_policy=remat_policy,
            adam_moments_dtype=adam_moments_dtype,
            ce_chunk_size=ce_chunk,
            optimizer_offload=optimizer_offload,
        ),
    )
    cfg.validate()
    return cfg


def run_one(model: str, layers, seq: int, mbs: int, *, grad_acc: int = 1,
            steps: int = 8, warmup: int = 2, remat: bool = True,
            remat_policy: str = "dots", adam_moments_dtype: str = "bfloat16",
            ce_chunk: int = 0, optimizer_offload: bool = False,
            profile: str | None = None,
            profile_steps: int | None = None,
            telemetry: str | None = None,
            trace: str | None = None) -> dict:
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step
    from picotron_tpu.telemetry import Histogram, JsonlSink
    from picotron_tpu.telemetry.flightdeck import SpanTracer
    from picotron_tpu.utils import device_peak_flops, flops_per_token, mfu

    n_chips = len(jax.devices())
    cfg = bench_config(model, layers, seq, mbs, grad_acc=grad_acc,
                       remat=remat, remat_policy=remat_policy,
                       adam_moments_dtype=adam_moments_dtype,
                       ce_chunk=ce_chunk,
                       optimizer_offload=optimizer_offload,
                       n_chips=n_chips)

    menv = MeshEnv.from_config(cfg)
    state = init_sharded_state(cfg, menv, jax.random.key(0))
    step = make_train_step(cfg, menv)

    b_global = mbs * n_chips
    toks = jax.random.randint(
        jax.random.key(1), (grad_acc, b_global, seq + 1),
        0, cfg.model.vocab_size,
    )
    sharding = menv.batch_sharding()
    batch = (jax.device_put(toks[..., :-1], sharding),
             jax.device_put(toks[..., 1:], sharding))

    for _ in range(max(warmup, 1)):  # >=1 so compile stays out of the timing
        state, metrics = step(state, batch)
    float(metrics["loss"])  # value fetch: cannot return before the warmup chain ran

    # Time N chained steps, fetching ONLY the final loss. The data dependency
    # (loss_N needs state_{N-1} needs ... state_0) forces every step to have
    # executed before the fetch returns, while avoiding a host<->device
    # round-trip per step (which inflates step time by the transport latency;
    # ~100ms/step over a remote-tunnel backend). block_until_ready is NOT
    # trustworthy here — with donated (aliased) state buffers it can return
    # before the execution chain has run; a value fetch cannot lie.
    # --profile-steps N caps the capture window to the LAST N timed steps:
    # a full-window capture at the mbs-3 headline OOMs the chip (the fused
    # scan's in-flight slices + xprof's device trace buffers, PERF.md r5);
    # a single-step window is the documented capture config that fits.
    cap = steps if profile_steps is None else min(profile_steps, steps)
    if profile and cap >= steps:
        jax.profiler.start_trace(profile)
    t0 = time.perf_counter()
    for i in range(steps):
        if profile and cap < steps and i == steps - cap:
            float(metrics["loss"])  # drain the chain before the window
            jax.profiler.start_trace(profile)
        state, metrics = step(state, batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    if profile:
        jax.profiler.stop_trace()

    tokens_per_step = b_global * grad_acc * seq
    tokens_per_sec = tokens_per_step * steps / dt
    peak = device_peak_flops()
    mfu_frac = mfu(tokens_per_sec, cfg.model, seq, n_chips, peak)

    # Per-step distribution: the chained timing above is the headline mean
    # (no per-step host round-trip), but a mean hides stragglers — a second
    # pass times each step individually with a value fetch and reports
    # p50/p95 from the histogram registry. The per-step sync adds the
    # host<->device transport latency to every sample, so p50 can sit a
    # touch above the chained mean; the p95/p50 RATIO is the straggler
    # signal. `telemetry` (``--telemetry FILE``) additionally writes every
    # sample to the JSONL sink (one bench_step event per step +
    # bench_summary), the same stream tools/telemetry_report.py reads.
    hist = Histogram()
    sink = JsonlSink(telemetry) if telemetry else None
    # ``--trace FILE``: record one flightdeck span per timed step and
    # export the Chrome-trace JSON; the span-recording cost rides inside
    # these samples, so p50 here vs the chained mean above IS the
    # enabled-path overhead (plus the per-span microbench below, which
    # measures the tracer call in isolation).
    tracer = SpanTracer() if trace else None
    for i in range(steps):
        t0 = time.perf_counter()
        state, metrics = step(state, batch)
        float(metrics["loss"])  # value fetch: the step must have executed
        dt_i = time.perf_counter() - t0
        hist.observe(dt_i)
        if tracer is not None:
            tracer.complete("step", dur_s=dt_i, i=i)
        if sink is not None:
            sink.emit({"ts": time.time(), "kind": "bench_step", "i": i,
                       "secs": round(dt_i, 6),
                       "tokens_per_sec": round(tokens_per_step / dt_i, 1)})

    layer_tag = f"-{cfg.model.num_hidden_layers}L"
    row = {
        "metric": f"mfu_{model.split('/')[-1]}{layer_tag}_seq{seq}",
        "value": round(mfu_frac, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu_frac / 0.40, 4),
        "tokens_per_sec_per_chip": round(tokens_per_sec / n_chips, 1),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "n_chips": n_chips,
        "device_kind": jax.devices()[0].device_kind,
        "peak_flops_per_chip": peak,
        "flops_per_token": flops_per_token(cfg.model, seq),
        "loss": final_loss,
        # NOTE: the bench feeds the SAME random batch every step (pure perf
        # harness) — `loss` trends toward memorization and says nothing
        # about model quality; see tests/test_train_e2e.py for real training.
        "loss_is_fixed_batch_memorization": True,
        "step_time_ms_mean": round(dt / steps * 1e3, 2),
        "step_time_ms_p50": round(hist.p50 * 1e3, 2),
        "step_time_ms_p95": round(hist.p95 * 1e3, 2),
    }
    if tracer is not None:
        # Per-span cost measured in isolation (a train step records a
        # handful of spans: 3-4 phases + any MPMD ticks): this is the
        # number PERF.md documents as the enabled-path overhead.
        probe = SpanTracer()
        n_probe = 10_000
        t0 = time.perf_counter()
        for i in range(n_probe):
            probe.complete("probe", dur_s=1e-6, i=i)
        span_cost_us = (time.perf_counter() - t0) / n_probe * 1e6
        tracer.export(trace)
        row["trace"] = trace
        row["trace_events"] = len(tracer)
        row["trace_span_cost_us"] = round(span_cost_us, 3)
    if sink is not None:
        sink.emit({"ts": time.time(), "kind": "bench_summary", **row})
        sink.close()
    return row


def run_decode(model: str, layers, prompt_len: int, max_new: int,
               batch: int, steps: int = 3, tp: int = 1) -> dict:
    """Generation throughput on the chip (the reference is training-only,
    ref: README.md:2 — this is the beyond-parity feature's number): one
    JSON line with steady-state decode tokens/s as the headline value plus
    the prefill rate. The prefill/decode split comes from differencing a
    max_new=1 run (prefill + one sample) against the full run — the two
    phases live inside one jitted program, so there is no boundary to
    time directly.

    tp > 1 re-places the params into the training TP shardings over tp
    chips (`place_for_decode`; pure GSPMD decode, XLA shards the KV cache
    and inserts the collectives) — the 7B-scale decode arrangement. The
    Llama-2-7B per-layer decode anchor is the 4L proxy:
    `bench.py --decode --model Llama-2-7B --layers 4` (single chip bf16;
    add --tp 2 on a multi-chip host for the sharded path)."""
    import numpy as np

    from picotron_tpu.config import ModelConfig, resolve_preset
    from picotron_tpu.generate import generate, place_for_decode
    from picotron_tpu.models.llama import init_params

    preset = resolve_preset(model)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", 0), prompt_len + max_new)
    if layers:
        preset["num_hidden_layers"] = layers
    mcfg = ModelConfig(name=model, **preset)
    params = jax.jit(
        lambda k: jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               init_params(mcfg, k)))(jax.random.key(0))
    params = place_for_decode(params, mcfg, tp=tp)
    prompts = jax.random.randint(jax.random.key(1), (batch, prompt_len),
                                 0, mcfg.vocab_size)

    def timed(n_new: int) -> float:
        np.asarray(generate(params, mcfg, prompts, n_new))  # compile
        best = float("inf")
        for _ in range(steps):
            t0 = time.perf_counter()
            np.asarray(generate(params, mcfg, prompts, n_new))
            best = min(best, time.perf_counter() - t0)
        return best

    t_prefill = timed(1)
    t_full = timed(max_new)
    if t_full <= t_prefill:
        # Timing jitter on a loaded host can make the differenced decode
        # time <= 0 (tiny models, max_new close to 2). One re-measure
        # absorbs a transient stall; a repeat means the measurement is
        # genuinely degenerate and must fail loudly — an inf/negative
        # decode tok/s must never be recorded (ADVICE r5).
        t_prefill = timed(1)
        t_full = timed(max_new)
    if t_full <= t_prefill:
        raise RuntimeError(
            f"decode timing degenerate: full run ({t_full * 1e3:.3f} ms for "
            f"{max_new} tokens) was not slower than the prefill-only run "
            f"({t_prefill * 1e3:.3f} ms) — increase --max-new-tokens or "
            f"re-run on an idle host; refusing to report a nonsensical "
            f"decode rate")
    dt = max(t_full - t_prefill, 1e-9)
    decode_tps = batch * (max_new - 1) / dt
    tp_tag = f"-tp{tp}" if tp > 1 else ""
    return {
        "metric": f"decode_{model.split('/')[-1]}"
                  f"-{mcfg.num_hidden_layers}L{tp_tag}",
        "tp": tp,
        "value": round(decode_tps, 1),
        "unit": "decode_tokens_per_sec",
        "prefill_tokens_per_sec": round(batch * prompt_len / t_prefill, 1),
        "batch": batch,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new,
        "decode_ms_per_token_per_seq": round(dt / (max_new - 1) * 1e3, 2),
        "device_kind": jax.devices()[0].device_kind,
    }


def make_serve_trace(n_requests: int, rate: float, prompt_len: int,
                     max_new: int, vocab: int, seed: int = 0) -> list:
    """Synthetic arrival trace: mixed prompt lengths in
    [prompt_len/8, prompt_len], mixed output budgets in
    [max_new/8, max_new] (wide spread — real traffic is heavy-tailed,
    and the spread is precisely what continuous batching monetizes),
    Poisson arrivals at `rate` req/s (rate <= 0 = everything arrives at
    t=0 — the saturation/throughput trace; a finite rate exercises
    queue_wait under load). Deterministic per seed, so serve and
    baseline always score the same workload."""
    import numpy as np

    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for _ in range(n_requests):
        plen = int(rng.integers(max(prompt_len // 8, 1), prompt_len + 1))
        olen = int(rng.integers(max(max_new // 8, 1), max_new + 1))
        prompt = rng.integers(0, vocab, size=plen).tolist()
        if rate > 0:
            t += float(rng.exponential(1.0 / rate))
        out.append((prompt, olen, t))
    return out


# Printed (stderr) and embedded in the JSON whenever a wall-clock ratio
# is reported from a CPU host: the 0.85-1.19 spread PERF.md r7 recorded
# was host-load noise being read as a regression/win.
_WALL_NOTE = ("wall-clock ratios on a shared CPU host are load-noisy "
              "(observed 0.85-1.19x swings on identical configs); the "
              "deterministic decode_slot_steps ratio is the headline — "
              "treat wall numbers as median-of-repeats sanity only, and "
              "use the PERF.md on-TPU protocol for real speedups")


def run_serve(model: str, layers, *, slots: int, block_size: int,
              num_blocks: int, prefill_chunk: int, prompt_len: int,
              max_new: int, n_requests: int, rate: float, tp: int = 1,
              decode_interval: int = 4, seed: int = 0,
              repeats: int = 3,
              telemetry: str | None = None) -> dict:
    """Continuous batching + paged KV cache (picotron_tpu/serve) against
    the batch-static `generate` baseline, on the same synthetic arrival
    trace. One JSON line. The HEADLINE value is the deterministic
    structural ratio `static_decode_slot_steps / decode_slot_steps` —
    decode slot-steps each side burns (the engine stops paying for
    retired/ragged sequences; the static sampler decodes the trace max
    for every batch), identical on every host. Wall-clock tokens/s and
    `vs_static` are reported as the MEDIAN over `repeats` timed runs per
    side (both sides compile-warm: a 2-request mini-trace warms the
    engine's two programs, one throwaway generate call warms the
    baseline's) with the per-run walls kept in the row — on a shared CPU
    they are load-noisy (see `wall_note`), so they sanity-check the
    structural ratio rather than headline it. rate > 0 makes the engine
    wall include arrival gaps; use the default rate=0 saturation trace
    for vs_static anchors."""
    import statistics

    import numpy as np

    from picotron_tpu.config import ModelConfig, ServeConfig, resolve_preset
    from picotron_tpu.generate import generate, place_for_decode
    from picotron_tpu.models.llama import init_params
    from picotron_tpu.serve import ServeEngine
    from picotron_tpu.telemetry import JsonlSink, Telemetry

    cap = prompt_len + max_new
    preset = resolve_preset(model)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", 0), cap)
    if layers:
        preset["num_hidden_layers"] = layers
    mcfg = ModelConfig(name=model, **preset)
    params = jax.jit(
        lambda k: jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               init_params(mcfg, k)))(jax.random.key(0))
    if tp > 1:
        # tp=1 placement is semantically a no-op but COMMITS the tree,
        # which pins every jit variant to explicit shardings — skipping
        # it keeps the single-chip path on the fast uncommitted dispatch
        params = place_for_decode(params, mcfg, tp=tp)
    scfg = ServeConfig(decode_slots=slots, block_size=block_size,
                       num_blocks=num_blocks, prefill_chunk=prefill_chunk,
                       max_model_len=cap, decode_interval=decode_interval)
    trace = make_serve_trace(n_requests, rate, prompt_len, max_new,
                             mcfg.vocab_size, seed)
    useful_tokens = sum(olen for _, olen, _ in trace)

    # compile-warm both programs (decode shape = slot count, prefill
    # shape = chunk size — both identical to the real trace's)
    warm = ServeEngine(params, mcfg, scfg)
    warm.run([(trace[0][0], 2), (trace[1 % len(trace)][0], 2)])
    warm.close()

    repeats = max(repeats, 1)
    serve_walls, summary = [], None
    for rep in range(repeats):
        # telemetry on the first repeat only: one stream per bench row
        tel = (Telemetry(sinks=[JsonlSink(telemetry)])
               if telemetry and rep == 0 else None)
        eng = ServeEngine(params, mcfg, scfg, telemetry=tel)
        t0 = time.perf_counter()
        eng.run(trace)
        serve_walls.append(time.perf_counter() - t0)
        summary = summary or eng.summary  # identical across repeats
        eng.close()
        if tel is not None:
            tel.close()
    serve_wall = statistics.median(serve_walls)

    # batch-static baseline: ceil(N/slots) generate() batches in arrival
    # order, every prompt right-padded to the trace max and every batch
    # decoding the trace-max budget (the shapes a static offline sampler
    # is stuck with) — one warm-up call, then timed end to end
    p_max = max(len(p) for p, _, _ in trace)
    o_max = max(olen for _, olen, _ in trace)
    groups = [trace[i:i + slots] for i in range(0, len(trace), slots)]

    def static_batch(group):
        ids = np.zeros((slots, p_max), np.int32)
        for j, (p, _, _) in enumerate(group):
            ids[j, :len(p)] = p
        return jnp.asarray(ids)

    np.asarray(generate(params, mcfg, static_batch(groups[0]), o_max))
    static_walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for g in groups:
            np.asarray(generate(params, mcfg, static_batch(g), o_max))
        static_walls.append(time.perf_counter() - t0)
    static_wall = statistics.median(static_walls)

    serve_tps = useful_tokens / serve_wall
    static_tps = useful_tokens / static_wall
    slot_steps = summary["decode_steps"] * decode_interval
    static_slot_steps = len(groups) * o_max
    print(f"# {_WALL_NOTE}", file=sys.stderr)
    tp_tag = f"-tp{tp}" if tp > 1 else ""
    ms = lambda v: round(v * 1e3, 2) if v is not None else None  # noqa: E731
    return {
        "metric": f"serve_{model.split('/')[-1]}"
                  f"-{mcfg.num_hidden_layers}L{tp_tag}",
        # headline = the structural decode-work ratio, deterministic on
        # any host; > 1 means continuous batching did strictly less
        # slot-step work than the batch-static sampler on this trace
        "value": round(static_slot_steps / max(slot_steps, 1), 3),
        "unit": "static_over_serve_decode_slot_steps",
        "serve_tokens_per_sec": round(serve_tps, 1),
        "vs_static": round(serve_tps / static_tps, 3),
        "static_tokens_per_sec": round(static_tps, 1),
        "wall_repeats": repeats,
        "serve_walls_s": [round(w, 4) for w in serve_walls],
        "static_walls_s": [round(w, 4) for w in static_walls],
        "wall_note": _WALL_NOTE,
        "requests": n_requests,
        "arrival_rate": rate,
        "useful_tokens": useful_tokens,
        "prompt_len_max": p_max,
        "max_new_max": o_max,
        "slots": slots,
        "block_size": block_size,
        "num_blocks": summary["num_blocks"],
        "prefill_chunk": prefill_chunk,
        "tp": tp,
        "ttft_p50_ms": ms(summary["ttft_p50_s"]),
        "ttft_p95_ms": ms(summary["ttft_p95_s"]),
        "token_latency_p50_ms": ms(summary["token_latency_p50_s"]),
        "token_latency_p95_ms": ms(summary["token_latency_p95_s"]),
        "queue_wait_p50_ms": ms(summary["queue_wait_p50_s"]),
        "queue_wait_p95_ms": ms(summary["queue_wait_p95_s"]),
        "slot_occupancy": summary["slot_occupancy"],
        "pool_peak_utilization": summary["pool_peak_utilization"],
        "preemptions": summary["preemptions"],
        "decode_steps": summary["decode_steps"],
        "decode_compiles": summary["decode_compiles"],
        # the raw slot-step counts behind the headline ratio — continuous
        # batching must be strictly lower on any ragged trace
        "decode_slot_steps": slot_steps,
        "static_decode_slot_steps": static_slot_steps,
        "device_kind": jax.devices()[0].device_kind,
    }


def run_serve_fleet(model: str, layers, *, fleet: int, slots: int,
                    block_size: int, num_blocks: int, prefill_chunk: int,
                    prompt_len: int, max_new: int, n_requests: int,
                    rate: float, decode_interval: int = 4, seed: int = 0,
                    temperature: float = 0.0, deadline_ms: float = 0.0,
                    chaos_spec: str | None = None, tick_s: float = 0.001,
                    telemetry: str | None = None) -> dict:
    """Fleet serving (picotron_tpu/serve/fleet): N engine replicas behind
    one queue on a synthetic arrival trace, with optional serve-side
    chaos (engine_dead@REQ / decode_hang@REQ~SECS / shed_storm@REQ) and
    deadline load shedding. One JSON line, built for the recovery
    scenarios in tools/chaos.py: per-request sha1 token digests (the
    failover-parity oracle compares them across fleet sizes and fault
    legs), the shed id set (deterministic on the virtual trace clock),
    and the survivor-pool leak count. Everything except wall seconds is
    structural — identical on any host."""
    import hashlib

    from picotron_tpu.config import ModelConfig, ServeConfig, resolve_preset
    from picotron_tpu.models.llama import init_params
    from picotron_tpu.resilience import chaos as chaos_mod
    from picotron_tpu.serve import FleetSupervisor
    from picotron_tpu.telemetry import JsonlSink, Telemetry

    cap = prompt_len + max_new
    preset = resolve_preset(model)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", 0), cap)
    if layers:
        preset["num_hidden_layers"] = layers
    mcfg = ModelConfig(name=model, **preset)
    params = jax.jit(
        lambda k: jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               init_params(mcfg, k)))(jax.random.key(0))
    scfg = ServeConfig(decode_slots=slots, block_size=block_size,
                       num_blocks=num_blocks, prefill_chunk=prefill_chunk,
                       max_model_len=cap, decode_interval=decode_interval,
                       fleet_size=fleet, deadline_ms=deadline_ms)
    trace = make_serve_trace(n_requests, rate, prompt_len, max_new,
                             mcfg.vocab_size, seed)

    tel = None
    if telemetry:
        from picotron_tpu.telemetry.flightdeck import FlightRecorder

        tel = Telemetry(sinks=[JsonlSink(telemetry)])
        tel.flight = FlightRecorder(
            os.path.dirname(os.path.abspath(telemetry)), max_steps=8)
    if chaos_spec:
        chaos_mod.install(chaos_spec)
    try:
        fl = FleetSupervisor(params, mcfg, scfg, temperature=temperature,
                             seed=seed, telemetry=tel, tick_s=tick_s)
        t0 = time.perf_counter()
        results = fl.run(trace)
        wall = time.perf_counter() - t0
    finally:
        if chaos_spec:
            chaos_mod.install("")  # disarm: one spec, one run
    summary = fl.summary
    shed = fl.all_shed
    digest = lambda toks: hashlib.sha1(  # noqa: E731
        " ".join(map(str, toks)).encode()).hexdigest()[:16]
    row = {
        "metric": f"serve_fleet{fleet}_{model.split('/')[-1]}"
                  f"-{mcfg.num_hidden_layers}L",
        # headline: every admitted request finished — the recovery
        # scenarios assert completed + shed == submitted even with an
        # engine killed mid-burst
        "value": len(results),
        "unit": "completed_requests",
        "fleet": fleet,
        "requests": n_requests,
        "completed": len(results),
        "shed": len(shed),
        "shed_ids": sorted(r["id"] for r in shed),
        "redispatched": summary["redispatched"],
        "engines_dead": summary["engines_dead"],
        "drains": summary["drains"],
        "leaked_blocks": summary["leaked_blocks"],
        "output_tokens": summary["output_tokens"],
        "wall_s": round(wall, 4),
        "wall_note": _WALL_NOTE,
        "arrival_rate": rate,
        "temperature": temperature,
        "deadline_ms": deadline_ms,
        "chaos": chaos_spec or "",
        "tick_s": tick_s,
        "slots": slots,
        "decode_steps": summary["decode_steps"],
        "decode_compiles": summary["decode_compiles"],
        "preemptions": summary["preemptions"],
        "ttft_p50_ms": (round(summary["ttft_p50_s"] * 1e3, 2)
                        if summary["ttft_p50_s"] is not None else None),
        "ttft_p95_ms": (round(summary["ttft_p95_s"] * 1e3, 2)
                        if summary["ttft_p95_s"] is not None else None),
        "queue_wait_p50_ms": (
            round(summary["queue_wait_p50_s"] * 1e3, 2)
            if summary["queue_wait_p50_s"] is not None else None),
        "queue_wait_p95_ms": (
            round(summary["queue_wait_p95_s"] * 1e3, 2)
            if summary["queue_wait_p95_s"] is not None else None),
        "per_engine_requests": [pe["requests"]
                                for pe in summary["per_engine"]],
        # the parity pin: same trace + same seed must produce the same
        # digest per id regardless of fleet size, failover, or shedding
        # of OTHER requests
        "request_digests": {str(r["id"]): digest(r["tokens"])
                            for r in results},
        "device_kind": jax.devices()[0].device_kind,
    }
    fl.close()
    if tel is not None:
        tel.close()
    return row


def make_burst_trace(slots: int, prompt_len: int, prefill_chunk: int,
                     decode_interval: int, max_new: int, vocab: int,
                     seed: int = 0) -> list:
    """Deterministic long-prefill burst (everything arrives at t=0):
    `slots` SHORT requests (tiny prompt, a decode budget sized to keep
    the decode side busy for the whole long-prefill grind) followed by
    `slots` LONG requests (full `prompt_len` prompt, small budget).

    A colocated engine admits the shorts into every slot; the longs are
    stuck in the queue until the shorts RETIRE (admission is coupled to
    decode slots), and when they do, every slot flips to chunked prefill
    at once — max consecutive decode-dispatch stalls ~= the long
    prefill's ceil(prompt_len / prefill_chunk) ticks. A disaggregated
    engine admits the longs into the PREFILL pool immediately, so their
    prefill overlaps the shorts' decode and the handoff lands on an
    already-warm decode pool — the stall streak collapses. That stall
    drop is the bench headline."""
    import numpy as np

    rng = np.random.default_rng(seed)
    prefill_ticks = -(-prompt_len // prefill_chunk)
    short_len = max(prompt_len // 8, 2)
    # outlast the long prefill by a few dispatches so the decode pool is
    # never the reason the longs look stall-free
    short_budget = min(decode_interval * (prefill_ticks + 4), max_new)
    long_budget = max(max_new // 8, decode_interval)
    out = []
    for _ in range(slots):
        prompt = rng.integers(0, vocab, size=short_len).tolist()
        out.append((prompt, short_budget, 0.0))
    for _ in range(slots):
        prompt = rng.integers(0, vocab, size=prompt_len).tolist()
        out.append((prompt, long_budget, 0.0))
    return out


def run_serve_disagg(model: str, layers, *, slots: int, block_size: int,
                     num_blocks: int, prefill_chunk: int, prompt_len: int,
                     max_new: int, n_requests: int, rate: float,
                     decode_interval: int = 4, seed: int = 0,
                     draft_lens=(1, 2, 3),
                     telemetry: str | None = None) -> dict:
    """Disaggregated vs colocated serving (picotron_tpu/serve/disagg) on
    the deterministic long-prefill burst trace, plus two sweep
    artifacts. One JSON line:

    - headline: the drop in max consecutive decode-dispatch stall ticks
      (colocated minus disagg) on the burst trace — the number
      disaggregation exists to buy, and fully deterministic.
    - `slo_curve`: per arrival rate (derived from --rate, 0 = the
      saturation point only), TTFT/TPOT/queue-wait percentiles for both
      engines on the SAME Poisson trace — the
      disaggregated-vs-colocated SLO comparison.
    - `acceptance_sweep`: the n-gram speculator over `draft_lens` on the
      mixed saturation trace: acceptance rate, decode dispatches, and
      draft accounting per draft length (speculative decode is
      token-identical to non-speculative by construction, so this is
      pure work-per-dispatch accounting, not a quality trade).

    Stall ticks, slot-steps, handoffs, and acceptance are structural —
    identical on any host; only the secondary wall fields are timing
    (see `wall_note`)."""
    from picotron_tpu.analysis.cost_model import CostModel
    from picotron_tpu.config import ModelConfig, ServeConfig, resolve_preset
    from picotron_tpu.models.llama import init_params
    from picotron_tpu.serve import DisaggServeEngine, ServeEngine
    from picotron_tpu.telemetry import JsonlSink, Telemetry

    cap = prompt_len + max_new
    preset = resolve_preset(model)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", 0), cap)
    if layers:
        preset["num_hidden_layers"] = layers
    mcfg = ModelConfig(name=model, **preset)
    params = jax.jit(
        lambda k: jax.tree.map(lambda x: x.astype(jnp.bfloat16),
                               init_params(mcfg, k)))(jax.random.key(0))

    def scfg(**kw):
        base = dict(decode_slots=slots, block_size=block_size,
                    num_blocks=num_blocks, prefill_chunk=prefill_chunk,
                    max_model_len=cap, decode_interval=decode_interval)
        base.update(kw)
        return ServeConfig(**base)

    def run(engine_cls, cfg, trace, tel=None):
        eng = engine_cls(params, mcfg, cfg, telemetry=tel)
        t0 = time.perf_counter()
        eng.run(trace)
        wall = time.perf_counter() - t0
        summary = eng.summary
        eng.close()
        return summary, wall

    # --- burst headline: stall ticks colocated vs disagg -------------
    burst = make_burst_trace(slots, prompt_len, prefill_chunk,
                             decode_interval, max_new, mcfg.vocab_size,
                             seed)
    # compile-warm both engines' programs on a 2-request mini-trace
    for cls, cfg in ((ServeEngine, scfg()),
                     (DisaggServeEngine, scfg(disagg=True))):
        warm = cls(params, mcfg, cfg)
        warm.run([(burst[0][0], 2), (burst[1][0], 2)])
        warm.close()

    tel = (Telemetry(sinks=[JsonlSink(telemetry)]) if telemetry else None)
    colo, colo_wall = run(ServeEngine, scfg(), burst)
    dis, dis_wall = run(DisaggServeEngine, scfg(disagg=True), burst, tel)
    if tel is not None:
        tel.close()

    # --- SLO curve: both engines on the same Poisson trace -----------
    base_rate = rate if rate > 0 else 0.0
    rates = ([base_rate * f for f in (0.5, 1.0, 2.0)]
             if base_rate > 0 else [0.0])
    slo_curve = []
    ms = lambda v: round(v * 1e3, 2) if v is not None else None  # noqa: E731
    for r in rates:
        trace = make_serve_trace(n_requests, r, prompt_len, max_new,
                                 mcfg.vocab_size, seed)
        point: dict = {"rate": round(r, 3), "requests": n_requests}
        for tag, cls, cfg in (("colocated", ServeEngine, scfg()),
                              ("disagg", DisaggServeEngine,
                               scfg(disagg=True))):
            s, _ = run(cls, cfg, trace)
            point[tag] = {
                "ttft_p50_ms": ms(s["ttft_p50_s"]),
                "ttft_p95_ms": ms(s["ttft_p95_s"]),
                "tpot_p50_ms": ms(s["tpot_p50_s"]),
                "tpot_p95_ms": ms(s["tpot_p95_s"]),
                "queue_wait_p95_ms": ms(s["queue_wait_p95_s"]),
                "decode_stall_ticks_max": s["decode_stall_ticks_max"],
            }
        slo_curve.append(point)

    # --- acceptance-rate sweep: n-gram speculator per draft length ---
    sweep_trace = make_serve_trace(n_requests, 0.0, prompt_len, max_new,
                                   mcfg.vocab_size, seed)
    acceptance_sweep = []
    for dl in draft_lens:
        s, _ = run(DisaggServeEngine,
                   scfg(disagg=True, speculator="ngram", draft_len=dl),
                   sweep_trace)
        acceptance_sweep.append({
            "draft_len": dl,
            "acceptance_rate": s["acceptance_rate"],
            "draft_tokens": s["draft_tokens"],
            "accepted_draft_tokens": s["accepted_draft_tokens"],
            "decode_steps": s["decode_steps"],
            "output_tokens": s["output_tokens"],
        })

    handoff_s, handoff_bytes = CostModel("v5e").price_kv_handoff(
        mcfg, scfg(disagg=True))
    print(f"# {_WALL_NOTE}", file=sys.stderr)
    return {
        "metric": f"serve_disagg_{model.split('/')[-1]}"
                  f"-{mcfg.num_hidden_layers}L",
        # headline: deterministic stall-streak drop on the burst trace
        "value": (colo["decode_stall_ticks_max"]
                  - dis["decode_stall_ticks_max"]),
        "unit": "decode_stall_ticks_drop",
        "colocated_stall_ticks_max": colo["decode_stall_ticks_max"],
        "disagg_stall_ticks_max": dis["decode_stall_ticks_max"],
        "burst_requests": len(burst),
        "prompt_len": prompt_len,
        "max_new": max_new,
        "slots": slots,
        "prefill_slots": dis["prefill_slots"],
        "handoffs": dis["handoffs"],
        "handoff_blocks": dis["handoff_blocks"],
        "handoff_s": dis["handoff_s"],
        "predicted_handoff_ms_worstcase": round(handoff_s * 1e3, 3),
        "predicted_handoff_bytes_worstcase": handoff_bytes,
        "prefill_slot_occupancy": dis["prefill_slot_occupancy"],
        "decode_compiles": dis["decode_compiles"],
        "preemptions": dis["preemptions"],
        "colocated_wall_s": round(colo_wall, 4),
        "disagg_wall_s": round(dis_wall, 4),
        "wall_note": _WALL_NOTE,
        "slo_curve": slo_curve,
        "acceptance_sweep": acceptance_sweep,
        "device_kind": jax.devices()[0].device_kind,
    }


def run_pp_tick_sweep(model: str, layers, seq: int, mbs: int, *,
                      pp: int = 4, n_micros=(2, 4, 8, 16), steps: int = 4,
                      warmup: int = 1, interleave: int = 2) -> dict:
    """SPMD-vs-MPMD pipeline tick cost: time the train step at several
    microbatch counts and fit step_ms = slope * n_micro + intercept per
    executor — the PERF.md r4 instrument (whose hand-fit put the SPMD
    fill/drain intercept at ~454 ms for pp=4 on simulated devices),
    automated. The slope is the per-tick steady-state cost; the intercept
    is the fill/drain + fixed overhead — the number the MPMD executor
    exists to shrink, because the SPMD lockstep scan pays ~a full traced
    tick per idle schedule slot while the host-side walker pays ~nothing.
    One JSON line per (executor, n_micro) sample, then a summary line
    with both fits, the intercept drop, and the schedule-table tick
    accounting (where interleaved-v2 must beat 1f1b at pp=4)."""
    from picotron_tpu.config import (
        Config, DistributedConfig, ModelConfig, PipelineConfig,
        TrainingConfig, resolve_preset,
    )
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step
    from picotron_tpu.parallel.mpmd import schedule_stats

    n_chips = len(jax.devices())
    if n_chips % pp != 0 or n_chips < pp:
        raise SystemExit(f"--pp-tick-sweep: {n_chips} device(s) not "
                         f"divisible into pp={pp} stages")
    dp = n_chips // pp
    preset = resolve_preset(model)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", seq), seq)
    if layers:
        preset["num_hidden_layers"] = layers
    depth = preset["num_hidden_layers"]
    metric = f"pp_tick_sweep_{model.split('/')[-1]}-{depth}L_pp{pp}"

    def timed(executor: str, n_micro: int) -> float:
        cfg = Config(
            distributed=DistributedConfig(dp_size=dp, pp_size=pp),
            model=ModelConfig(name=model, **preset),
            training=TrainingConfig(seq_length=seq, micro_batch_size=mbs,
                                    gradient_accumulation_steps=n_micro),
            pipeline=(PipelineConfig(executor="mpmd")
                      if executor == "mpmd" else PipelineConfig()),
        )
        cfg.validate()
        menv = MeshEnv.from_config(cfg)
        state = init_sharded_state(cfg, menv, jax.random.key(0))
        step = make_train_step(cfg, menv)
        toks = jax.random.randint(jax.random.key(1),
                                  (n_micro, mbs * dp, seq + 1),
                                  0, cfg.model.vocab_size)
        sharding = menv.batch_sharding()
        batch = (jax.device_put(toks[..., :-1], sharding),
                 jax.device_put(toks[..., 1:], sharding))
        for _ in range(max(warmup, 1)):
            state, metrics = step(state, batch)
        float(metrics["loss"])  # drain the warmup chain
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        float(metrics["loss"])  # value fetch: every step must have run
        return (time.perf_counter() - t0) / steps * 1e3

    fits = {}
    for executor in ("spmd", "mpmd"):
        xs, ys = [], []
        for n_micro in n_micros:
            step_ms = timed(executor, n_micro)
            xs.append(float(n_micro))
            ys.append(step_ms)
            print(json.dumps({"metric": metric, "executor": executor,
                              "n_micro": n_micro,
                              "step_time_ms": round(step_ms, 2)}),
                  flush=True)
        n = len(xs)
        mx, my = sum(xs) / n, sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs) or 1.0
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / var
        fits[executor] = {
            "ms_per_microbatch": round(slope, 2),
            "intercept_ms": round(my - slope * mx, 2),
        }

    # Schedule-table accounting (host arithmetic, parallel/mpmd.py): the
    # idle units each schedule implies at the sweep's largest n_micro —
    # in FULL units (1 unit = one stage's F+B), executor-independent.
    nm = max(n_micros)
    acct = {k: schedule_stats(k, nm, pp) for k in ("spmd", "1f1b", "gpipe")}
    slots = -(-depth // pp)  # ceil
    if interleave >= 2 and slots % interleave == 0:
        acct[f"interleaved-v{interleave}"] = schedule_stats(
            "interleaved", nm, pp, interleave)
    acct["zb"] = schedule_stats("zb", nm, pp)

    drop_ms = fits["spmd"]["intercept_ms"] - fits["mpmd"]["intercept_ms"]
    base = fits["spmd"]["intercept_ms"]
    row = {
        "metric": metric,
        "value": round(drop_ms / base, 4) if base else None,
        "unit": "mpmd_intercept_drop_fraction",
        "intercept_drop_ms": round(drop_ms, 2),
        "spmd": fits["spmd"],
        "mpmd": fits["mpmd"],
        "n_micros": list(n_micros),
        "pp": pp, "dp": dp, "mbs": mbs, "seq": seq,
        "schedule_bubble_units": {
            k: round(v["bubble_units"], 3) for k, v in acct.items()},
        "device_kind": jax.devices()[0].device_kind,
    }
    print(json.dumps(row), flush=True)
    return row


def run_cp_flavor_sweep(model: str, layers, seqs, mbs: int, *,
                        cp: int = 0, steps: int = 3,
                        warmup: int = 1) -> list:
    """Ring vs Ulysses vs mesh context parallelism on the same train step:
    one JSON row per (seq, cp_flavor) with the measured step time and the
    ICI cost model's prediction for this device kind — the instrument for
    the PERF.md Round 13 question (where does the 2D mesh schedule's
    crossover land on real ICI, and does the topology model's predicted
    ordering hold?). A flavor the head counts cannot schedule (Ulysses
    needs heads % cp == 0) reports `infeasible` instead of vanishing —
    on GQA models that asymmetry IS the mesh flavor's reason to exist.

    On TPU: `python bench.py --cp-flavor-sweep --model Llama-3.1-8B
    --seqs 8192 16384 32768 65536`. CPU runs (`--cpu`, 8 simulated
    devices, debug-tiny) are structural anchors only — they prove the
    three flavors lower and step, not how fast.
    """
    from picotron_tpu.analysis.cost_model import CostModel
    from picotron_tpu.config import (
        Config, DistributedConfig, ModelConfig, TrainingConfig,
        resolve_preset, resolved_cp_mesh,
    )
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    n_chips = len(jax.devices())
    cp = cp or n_chips
    if n_chips % cp or cp < 2:
        raise SystemExit(f"--cp-flavor-sweep: {n_chips} device(s) not "
                         f"divisible into cp={cp} sequence shards")
    dp = n_chips // cp
    preset = resolve_preset(model)
    max_seq = max(seqs)
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", max_seq), max_seq)
    if layers:
        preset["num_hidden_layers"] = layers
    cost_model = CostModel(jax.devices()[0].device_kind)
    rows = []
    for seq in seqs:
        for flavor in ("ring", "ulysses", "mesh"):
            row = {"metric": f"cp_flavor_{model.split('/')[-1]}_cp{cp}",
                   "cp_flavor": flavor, "cp": cp, "dp": dp, "seq": seq,
                   "mbs": mbs,
                   "device_kind": jax.devices()[0].device_kind,
                   "is_tpu": jax.devices()[0].platform == "tpu"}
            cfg = Config(
                distributed=DistributedConfig(dp_size=dp, cp_size=cp,
                                              cp_flavor=flavor),
                model=ModelConfig(name=model, **preset),
                training=TrainingConfig(seq_length=seq,
                                        micro_batch_size=mbs),
            )
            try:
                cfg.validate()
            except ValueError as e:
                row["infeasible"] = str(e)[:160]
                rows.append(row)
                print(json.dumps(row), flush=True)
                continue
            if flavor == "mesh":
                cp_x, cp_y = resolved_cp_mesh(cfg)
                row["cp_mesh"] = f"{cp_x}x{cp_y}"
            row["predicted_step_ms"] = round(
                cost_model.predict(cfg).total_s * 1e3, 2)
            menv = MeshEnv.from_config(cfg)
            state = init_sharded_state(cfg, menv, jax.random.key(0))
            step = make_train_step(cfg, menv)
            toks = jax.random.randint(jax.random.key(1),
                                      (1, mbs * dp, seq + 1),
                                      0, cfg.model.vocab_size)
            sharding = menv.batch_sharding()
            batch = (jax.device_put(toks[..., :-1], sharding),
                     jax.device_put(toks[..., 1:], sharding))
            for _ in range(max(warmup, 1)):
                state, metrics = step(state, batch)
            float(metrics["loss"])  # drain the warmup chain
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step(state, batch)
            float(metrics["loss"])  # value fetch: every step must have run
            step_ms = (time.perf_counter() - t0) / steps * 1e3
            tokens_per_step = mbs * dp * seq
            row.update({
                "step_time_ms": round(step_ms, 2),
                "tokens_per_sec": round(tokens_per_step / step_ms * 1e3, 1),
                "loss": float(metrics["loss"]),
            })
            rows.append(row)
            print(json.dumps(row), flush=True)
    return rows


def run_bwd_grid_sweep(model: str, seq: int, batch: int, steps: int = 5,
                       blocks=None) -> list:
    """Block-size sweep of the flash attention KERNEL PAIR (fwd, fwd+bwd)
    at long sequence — the instrument for VERDICT r5 next #8: at seq 16k
    the bwd pair runs ~2.3x the fwd wall but the pair sits below the
    causal roofline, and PERF.md r5 attributes the excess to *pipeline
    overhead* (program count x per-program ramp), which is exactly what
    the (block_q, block_k) grid shape controls. One JSON line per combo
    with the pair's achieved TFLOP/s and fraction of the device's causal
    attention roofline; combos whose fp32 [BQ, BK] score block exceeds
    VMEM report their compile error instead of silently vanishing.

    Run on hardware: `python bench.py --bwd-grid-sweep --seq 16384`.
    The jnp fallback makes CPU runs structural smoke only.
    """
    from picotron_tpu.config import resolve_preset
    from picotron_tpu.ops.flash_attention import flash_attention
    from picotron_tpu.utils import device_peak_flops

    preset = resolve_preset(model)
    hq = preset["num_attention_heads"]
    hkv = preset["num_key_value_heads"]
    d = preset["hidden_size"] // hq
    on_tpu = jax.devices()[0].platform == "tpu"
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    ks = jax.random.split(jax.random.key(0), 4)
    q = jax.random.normal(ks[0], (batch, seq, hq, d), dt)
    k = jax.random.normal(ks[1], (batch, seq, hkv, d), dt)
    v = jax.random.normal(ks[2], (batch, seq, hkv, d), dt)
    do = jax.random.normal(ks[3], (batch, seq, hq, d), dt)

    # causal attention flops: qk + pv matmuls over the lower triangle
    # (2 * 2 * B*H*S^2*D / 2); backward re-derives s/p and runs the three
    # grad matmuls -> 2.5x the forward's matmul flops
    fwd_flops = 2 * batch * hq * seq * seq * d
    pair_flops = fwd_flops * 3.5
    peak = device_peak_flops()

    def timed(fn, *args) -> float:
        fn(*args).block_until_ready()  # compile
        best = float("inf")
        for _ in range(steps):
            t0 = time.perf_counter()
            float(fn(*args))  # value fetch: the chain must have executed
            best = min(best, time.perf_counter() - t0)
        return best

    blocks = blocks or [(256, 256), (512, 512), (512, 1024), (1024, 512),
                        (1024, 1024), (1024, 2048), (2048, 1024),
                        (2048, 2048)]
    rows = []
    for bq, bk in blocks:
        row = {"metric": f"bwd_grid_{model.split('/')[-1]}_seq{seq}",
               "block_q": bq, "block_k": bk, "seq": seq, "batch": batch,
               "unit": "pair_fraction_of_peak",
               "device_kind": jax.devices()[0].device_kind,
               "is_tpu_kernel": on_tpu}
        try:
            def fwd(q, k, v, bq=bq, bk=bk):
                return jnp.sum(flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk)
                    .astype(jnp.float32))

            def pair(q, k, v, do, bq=bq, bk=bk):
                def f(q_, k_, v_):
                    return flash_attention(q_, k_, v_, causal=True,
                                           block_q=bq, block_k=bk)

                out, vjp = jax.vjp(f, q, k, v)
                dq, dk, dv = vjp(do)
                return (jnp.sum(out.astype(jnp.float32))
                        + jnp.sum(dq.astype(jnp.float32))
                        + jnp.sum(dk.astype(jnp.float32))
                        + jnp.sum(dv.astype(jnp.float32)))

            t_fwd = timed(jax.jit(fwd), q, k, v)
            t_pair = timed(jax.jit(pair), q, k, v, do)
            row.update({
                "fwd_ms": round(t_fwd * 1e3, 3),
                "pair_ms": round(t_pair * 1e3, 3),
                "bwd_over_fwd": round((t_pair - t_fwd) / t_fwd, 2),
                "pair_tflops": round(pair_flops / t_pair / 1e12, 2),
                "value": round(pair_flops / t_pair / peak, 4),
            })
        except Exception as e:  # VMEM-exceeding combos are data, not noise
            row["error"] = f"{type(e).__name__}: {str(e)[:160]}"
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    # Default (no flags) = the HEADLINE config: the full 24-layer
    # SmolLM-1.7B on one chip via optimizer_offload (fp32 master + Adam
    # moments in pinned host memory), mbs 2 x grad-acc 64 = 262k
    # tokens/step — SmolLM's real ~2M-token global batch at the
    # reference's 8-GPU scale. Any explicit shape flag opts out of the
    # auto-config (see the resolution block below); `--layers 8 --mbs 5`
    # reproduces the depth-reduced peak-MFU proxy (62.6%, PERF.md).
    ap.add_argument("--model", default="SmolLM-1.7B")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--mbs", type=int, default=None)
    ap.add_argument("--grad-acc", type=int, default=None)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--remat-policy", default=None,
                    choices=["full", "dots", "dots_attn", "dots_lean", "dots_norms", "dots_offload"])
    ap.add_argument("--ce-chunk", type=int, default=0,
                    help="stream the LM-head CE over vocab chunks of this "
                         "size (0 = fused): ~tokens*vocab*2B less peak HBM "
                         "for one extra chunk matmul in backward — a "
                         "memory knob for big-vocab models (Llama-3 128k); "
                         "costs ~5%% MFU at SmolLM shapes (PERF.md)")
    ap.add_argument("--optimizer-offload", action="store_true",
                    help="ZeRO-Offload-style optimizer-state offload: fp32 "
                         "master + Adam moments live in pinned HOST memory, "
                         "the device keeps a bf16 compute copy — the lever "
                         "that fits full-depth SmolLM-1.7B (~21 GB of "
                         "state) on one 15.75 GB chip. Amortize the PCIe "
                         "round trip with --grad-acc >= 16")
    ap.add_argument("--adam-moments-dtype", default="bfloat16",
                    choices=["float32", "bfloat16"],
                    help="bf16 moments halve optimizer-state HBM traffic "
                         "(profiled at ~9%% of step time fp32) and memory")
    ap.add_argument("--layers", type=int, default=None,
                    help="override the preset's layer count (bench a "
                         "depth-reduced variant of a big model); pass 0 "
                         "for the preset's full depth. Defaults to 8 for "
                         "the default SmolLM-1.7B only, full depth for any "
                         "explicitly chosen model and for --decode")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the timed steps "
                         "into DIR (open with xprof/tensorboard; see "
                         "README 'Profiling'). SURVEY.md §5 prescribes "
                         "profiler traces as the TPU observability story.")
    ap.add_argument("--profile-steps", type=int, default=None,
                    help="capture only the LAST N timed steps in the "
                         "--profile trace (default: all). The memory-tight "
                         "configs need a single-step window: a full-window "
                         "capture OOMs the mbs-3 offload headline "
                         "(in-flight fused-scan slices + xprof device "
                         "buffers; PERF.md). Use `--profile DIR "
                         "--profile-steps 1`.")
    ap.add_argument("--trace", metavar="FILE", default=None,
                    help="record a flightdeck span per timed step and "
                         "export Chrome-trace/Perfetto JSON to FILE "
                         "(validate with tools/trace_export.py "
                         "--validate); adds trace_span_cost_us to the "
                         "JSON row — the enabled-path overhead number "
                         "PERF.md documents")
    ap.add_argument("--telemetry", metavar="FILE", default=None,
                    help="write per-step timing samples + the summary row "
                         "to this JSONL file (picotron_tpu/telemetry sink "
                         "schema; summarize with tools/telemetry_report.py)")
    ap.add_argument("--sweep", action="store_true",
                    help="run the breadth matrix (one JSON line per config, "
                         "headline last) instead of a single config")
    ap.add_argument("--shardcheck", action="store_true",
                    help="statically audit the resolved config instead of "
                         "timing it: spec lint, collective-schedule audit, "
                         "donation/recompile hazards (picotron_tpu/"
                         "analysis) — no step execution, works without a "
                         "TPU; exit status reflects the findings")
    ap.add_argument("--decode", action="store_true",
                    help="measure generation instead of training: prefill "
                         "tokens/s + steady-state decode tokens/s on the "
                         "chip (KV-cache path, generate.py)")
    ap.add_argument("--batch", type=int, default=8,
                    help="--decode: sequences decoded in parallel")
    ap.add_argument("--prompt-len", type=int, default=512,
                    help="--decode: prefill length")
    ap.add_argument("--max-new-tokens", type=int, default=128,
                    help="--decode: decode steps measured")
    ap.add_argument("--tp", type=int, default=1,
                    help="--decode: shard the params (and, via GSPMD, the "
                         "KV cache) over N chips with the training TP "
                         "layout (generate.place_for_decode) — the "
                         "7B-scale decode arrangement")
    ap.add_argument("--serve", action="store_true",
                    help="measure the serving stack (picotron_tpu/serve: "
                         "continuous batching + paged KV cache) on a "
                         "synthetic arrival trace vs the batch-static "
                         "generate baseline: tokens/s, p50/p95 TTFT, "
                         "per-token latency, slot/pool utilization")
    ap.add_argument("--requests", type=int, default=16,
                    help="--serve: requests in the synthetic trace")
    ap.add_argument("--rate", type=float, default=0.0,
                    help="--serve: Poisson arrival rate in requests/s "
                         "(0 = all arrive at t=0, the saturation trace "
                         "the vs_static anchor uses)")
    ap.add_argument("--serve-slots", type=int, default=8,
                    help="--serve: in-flight decode batch width (the one "
                         "static shape of the decode program)")
    ap.add_argument("--block-size", type=int, default=16,
                    help="--serve: tokens per paged-cache block")
    ap.add_argument("--num-blocks", type=int, default=0,
                    help="--serve: physical blocks in the shared KV pool "
                         "(0 = worst-case auto: slots * ceil(cap/block); "
                         "set lower to exercise preemption)")
    ap.add_argument("--prefill-chunk", type=int, default=64,
                    help="--serve: prompt tokens prefilled per engine "
                         "iteration, interleaved 1:1 with decode steps")
    ap.add_argument("--decode-interval", type=int, default=4,
                    help="--serve: decode steps scanned inside one "
                         "dispatch (amortizes host overhead; retirement "
                         "latency quantizes to it)")
    ap.add_argument("--serve-repeats", type=int, default=3,
                    help="--serve: timed wall-clock runs per side; the "
                         "reported wall is the median (the structural "
                         "decode_slot_steps headline needs one run)")
    ap.add_argument("--disagg", action="store_true",
                    help="--serve: disaggregated vs colocated engines "
                         "(picotron_tpu/serve/disagg) — deterministic "
                         "decode-stall drop on a long-prefill burst "
                         "trace, a --rate SLO curve for both engines, "
                         "and an n-gram speculator acceptance sweep")
    ap.add_argument("--draft-lens", type=int, nargs="*", default=[1, 2, 3],
                    help="--serve --disagg: speculator draft lengths "
                         "for the acceptance sweep")
    ap.add_argument("--fleet", type=int, default=0,
                    help="--serve: run N engine replicas behind one "
                         "queue (picotron_tpu/serve/fleet) instead of "
                         "the vs-static comparison — failover "
                         "re-dispatch, deadline shedding, per-request "
                         "token digests for the parity oracle")
    ap.add_argument("--chaos", metavar="SPEC", default=None,
                    help="--serve --fleet: serve-side chaos spec "
                         "(engine_dead@REQ, decode_hang@REQ~SECS, "
                         "shed_storm@REQ[xN]; same grammar as "
                         "resilience.chaos) injected in the fleet "
                         "dispatch loop")
    ap.add_argument("--deadline-ms", type=float, default=0.0,
                    help="--serve --fleet: per-request deadline on the "
                         "virtual trace clock; a request still queued "
                         "past it is shed (0 = no deadline)")
    ap.add_argument("--serve-temperature", type=float, default=0.0,
                    help="--serve --fleet: sampling temperature (keys "
                         "fold per (request id, token index), so "
                         "failover parity holds at any temperature)")
    ap.add_argument("--tick-s", type=float, default=0.001,
                    help="--serve --fleet: virtual trace-clock seconds "
                         "per fleet iteration (what makes shed "
                         "decisions deterministic)")
    ap.add_argument("--serve-seed", type=int, default=0,
                    help="--serve --fleet: trace + sampling seed")
    ap.add_argument("--pp-tick-sweep", action="store_true",
                    help="fit step time vs n_micro per pipeline executor "
                         "(SPMD lockstep scan vs MPMD per-stage programs) "
                         "at --pp stages: slope = ms/tick, intercept = "
                         "fill/drain + fixed overhead — the PERF.md r4 "
                         "table, automated, with the MPMD column. One "
                         "JSON line per sample + a summary line with the "
                         "intercept drop and schedule-tick accounting. "
                         "Needs a device count divisible by --pp (use "
                         "--cpu for 8 simulated hosts)")
    ap.add_argument("--pp", type=int, default=4,
                    help="--pp-tick-sweep: pipeline stages")
    ap.add_argument("--n-micros", type=int, nargs="*",
                    default=[2, 4, 8, 16],
                    help="--pp-tick-sweep: microbatch counts to fit over")
    ap.add_argument("--cp-flavor-sweep", action="store_true",
                    help="time the train step under each cp flavor (ring/"
                         "ulysses/mesh) at each --seqs length, with the "
                         "cost model's prediction alongside (PERF.md "
                         "Round 13 protocol); one JSON line per row. "
                         "With --cpu, runs on 8 simulated devices as a "
                         "structural anchor")
    ap.add_argument("--seqs", type=int, nargs="*",
                    default=[8192, 16384, 32768, 65536],
                    help="sequence lengths for --cp-flavor-sweep")
    ap.add_argument("--cp", type=int, default=0,
                    help="cp degree for --cp-flavor-sweep (0 = all "
                         "devices)")
    ap.add_argument("--bwd-grid-sweep", action="store_true",
                    help="sweep flash-attention (block_q, block_k) over "
                         "the fwd / fwd+bwd kernel pair at --seq (use "
                         "16384 for the VERDICT r5 #8 question: is the "
                         "16k bwd-pair excess pipeline overhead the grid "
                         "shape can shrink?); one JSON line per combo")
    ap.add_argument("--cpu", action="store_true",
                    help="run on the host CPU platform (structural smoke "
                         "run, numbers not comparable) instead of failing "
                         "when no TPU backend is reachable")
    args = ap.parse_args()

    if (args.pp_tick_sweep or args.cp_flavor_sweep) and args.cpu:
        # Provision the simulated stage x data devices BEFORE the first
        # backend-initializing jax call (require_backend's jax.devices()
        # pins the client) — same ordering contract as tools/memcheck.py.
        from picotron_tpu.mesh import force_host_device_count

        force_host_device_count(max(args.pp if args.pp_tick_sweep
                                    else args.cp, 8))

    # Backend probe BEFORE any mode: a down TPU tunnel must be one line,
    # not the xla_bridge traceback BENCH_r05.json recorded. Children of
    # --sweep inherit the pinned JAX_PLATFORMS via the environment.
    require_backend(args.cpu)

    if args.shardcheck and (args.sweep or args.decode or args.profile
                            or args.bwd_grid_sweep or args.serve
                            or args.pp_tick_sweep or args.cp_flavor_sweep):
        ap.error("--shardcheck is its own mode; incompatible with "
                 "--sweep/--decode/--profile/--bwd-grid-sweep/--serve/"
                 "--pp-tick-sweep/--cp-flavor-sweep")

    if args.cp_flavor_sweep:
        if (args.sweep or args.decode or args.profile
                or args.bwd_grid_sweep or args.serve or args.pp_tick_sweep):
            ap.error("--cp-flavor-sweep is its own mode; incompatible "
                     "with --sweep/--decode/--profile/--bwd-grid-sweep/"
                     "--serve/--pp-tick-sweep")
        run_cp_flavor_sweep(args.model, args.layers or 0,
                            tuple(args.seqs), args.mbs or 1, cp=args.cp,
                            steps=args.steps, warmup=args.warmup)
        return

    if args.pp_tick_sweep:
        if (args.sweep or args.decode or args.profile
                or args.bwd_grid_sweep or args.serve):
            ap.error("--pp-tick-sweep is its own mode; incompatible with "
                     "--sweep/--decode/--profile/--bwd-grid-sweep/--serve")
        run_pp_tick_sweep(args.model, args.layers or 0, args.seq,
                          args.mbs or 1, pp=args.pp,
                          n_micros=tuple(args.n_micros),
                          steps=args.steps, warmup=args.warmup)
        return

    if args.serve:
        if args.sweep or args.decode or args.profile or args.bwd_grid_sweep:
            ap.error("--serve is its own mode; incompatible with "
                     "--sweep/--decode/--profile/--bwd-grid-sweep")
        if args.max_new_tokens < 1 or args.requests < 2:
            ap.error("--serve needs --max-new-tokens >= 1 and "
                     "--requests >= 2")
        if args.fleet:
            if args.disagg or args.tp > 1:
                ap.error("--fleet places each replica on its own device; "
                         "incompatible with --disagg/--tp in this bench "
                         "mode")
            print(json.dumps(run_serve_fleet(
                args.model, args.layers or 0, fleet=args.fleet,
                slots=args.serve_slots, block_size=args.block_size,
                num_blocks=args.num_blocks,
                prefill_chunk=args.prefill_chunk,
                prompt_len=args.prompt_len,
                max_new=args.max_new_tokens, n_requests=args.requests,
                rate=args.rate, decode_interval=args.decode_interval,
                seed=args.serve_seed, temperature=args.serve_temperature,
                deadline_ms=args.deadline_ms, chaos_spec=args.chaos,
                tick_s=args.tick_s, telemetry=args.telemetry)))
            return
        if args.disagg:
            if args.tp > 1:
                ap.error("--disagg places each pool on its own device; "
                         "incompatible with --tp (the mesh-sharded path "
                         "colocates the pools on the mesh)")
            print(json.dumps(run_serve_disagg(
                args.model, args.layers or 0, slots=args.serve_slots,
                block_size=args.block_size, num_blocks=args.num_blocks,
                prefill_chunk=args.prefill_chunk,
                prompt_len=args.prompt_len,
                max_new=args.max_new_tokens, n_requests=args.requests,
                rate=args.rate, decode_interval=args.decode_interval,
                draft_lens=tuple(args.draft_lens),
                telemetry=args.telemetry)))
            return
        print(json.dumps(run_serve(
            args.model, args.layers or 0, slots=args.serve_slots,
            block_size=args.block_size, num_blocks=args.num_blocks,
            prefill_chunk=args.prefill_chunk, prompt_len=args.prompt_len,
            max_new=args.max_new_tokens, n_requests=args.requests,
            rate=args.rate, tp=args.tp,
            decode_interval=args.decode_interval,
            repeats=args.serve_repeats,
            telemetry=args.telemetry)))
        return

    if args.bwd_grid_sweep:
        if args.sweep or args.decode or args.profile:
            ap.error("--bwd-grid-sweep is its own mode; incompatible with "
                     "--sweep/--decode/--profile")
        run_bwd_grid_sweep(args.model, args.seq, args.mbs or 1,
                           steps=args.steps)
        return

    if args.decode:
        if args.sweep or args.profile:
            ap.error("--decode is its own mode; incompatible with "
                     "--sweep/--profile")
        if args.max_new_tokens < 2:
            # the prefill/decode split differences a max_new=1 run
            # against the full run — guard BEFORE the expensive compiles
            ap.error("--decode needs --max-new-tokens >= 2")
        print(json.dumps(run_decode(
            args.model, args.layers or 0, args.prompt_len,
            args.max_new_tokens, args.batch, steps=args.steps,
            tp=args.tp)))
        return

    if args.sweep:
        import subprocess
        import sys

        from picotron_tpu.config import resolve_preset

        # the matrix pins per-config shape flags; only these compose with it
        # (attr name -> (default, real flag spelling), so the error names
        # flags the user can actually type; ADVICE r2)
        defaults = {"model": ("SmolLM-1.7B", "--model"),
                    "seq": (2048, "--seq"), "mbs": (None, "--mbs"),
                    "grad_acc": (None, "--grad-acc"),
                    "layers": (None, "--layers"),
                    "ce_chunk": (0, "--ce-chunk"),
                    "remat_policy": (None, "--remat-policy"),
                    "optimizer_offload": (False, "--optimizer-offload"),
                    "profile": (None, "--profile"),
                    "profile_steps": (None, "--profile-steps"),
                    "tp": (1, "--tp"),
                    "telemetry": (None, "--telemetry"),
                    "trace": (None, "--trace"),
                    "no_remat": (False, "--no-remat")}
        clashing = [flag for k, (v, flag) in defaults.items()
                    if getattr(args, k) != v]
        if clashing:
            ap.error(f"--sweep runs a fixed config matrix; incompatible "
                     f"with: {', '.join(clashing)}")
        # One FRESH process per row, a settle pause before each attempt,
        # and best-of-N per row: a row launched too close to another
        # session's teardown on this tunnel terminal can read 10-20x low
        # (measured: the offload headline 23% vs its reproducible
        # standalone 43.4%, a proxy row 2.7% vs 58%), non-deterministically
        # per row. The max over isolated attempts recovers the uncontended
        # number; `attempts` in the JSON records EVERY attempt's value so
        # the spread behind each row is visible, not hidden (ADVICE r4),
        # and a >20% disagreement between the first two attempts triggers
        # a THIRD so no reported value rests on a single non-reproduced
        # run (VERDICT r4 #6). Isolation also means one OOM cannot take
        # the rest down.
        for model, layers, seq, mbs, extra in SWEEP:
            depth = layers or resolve_preset(model)["num_hidden_layers"]
            # the row's extras are serialized into child FLAGS below — an
            # unknown key would silently measure a different config than
            # declared (code review r4)
            unknown = set(extra) - {"grad_acc", "remat_policy",
                                    "optimizer_offload"}
            if unknown:
                raise ValueError(f"SWEEP extras {sorted(unknown)} have no "
                                 f"child-flag serialization; add them to "
                                 f"the cmd construction")
            kw = {"remat_policy": "dots", **extra}
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--model", model, "--layers", str(layers or 0),
                   "--seq", str(seq), "--mbs", str(mbs),
                   "--grad-acc", str(kw.get("grad_acc", 1)),
                   "--remat-policy", kw["remat_policy"],
                   "--adam-moments-dtype", args.adam_moments_dtype,
                   "--steps", str(args.steps),
                   "--warmup", str(args.warmup)]
            if kw.get("optimizer_offload"):
                cmd.append("--optimizer-offload")
            results, errs = [], []

            def one_attempt():
                time.sleep(45)
                res = subprocess.run(cmd, capture_output=True, text=True)
                line = (res.stdout.strip().splitlines()[-1]
                        if res.stdout.strip() else "")
                if res.returncode == 0 and line.startswith("{"):
                    results.append(json.loads(line))
                else:
                    errs.append(res.stderr.strip()[-200:] or "no output")

            for attempt in range(2):
                one_attempt()
            vals = sorted(d["value"] for d in results)
            # tie-break a flaky row (VERDICT r4 #6): a >20% disagreement
            # OR fewer than two successful attempts (one errored — or BOTH
            # errored, which the old `len == 1` test missed, ADVICE r5)
            # leave the row unconfirmed — take a third attempt either way
            if len(vals) < 2 or vals[0] < 0.8 * vals[1]:
                one_attempt()
            if results:
                best = max(results, key=lambda d: d["value"])
                best["attempts"] = sorted(d["value"] for d in results)
                print(json.dumps(best), flush=True)
            else:  # one OOM must not kill the matrix
                print(json.dumps({
                    "metric": f"mfu_{model.split('/')[-1]}-{depth}L_seq{seq}",
                    "error": errs[-1],
                }), flush=True)
        return

    # Flag resolution: the bare default is the full-depth headline config
    # (offload + mbs 2 x ga 64 + full remat). ANY explicit shape/policy
    # flag opts out of the auto-config (an old invocation like
    # `bench.py --mbs 5` must keep meaning the depth-reduced proxy, not
    # silently become a 24L run that OOMs); --optimizer-offload composes
    # with explicit flags as requested.
    no_shape_flags = (args.layers is None and args.mbs is None
                      and args.grad_acc is None
                      and args.remat_policy is None)
    if args.model == "SmolLM-1.7B" and no_shape_flags \
            and not args.optimizer_offload:
        args.optimizer_offload = True
    if args.optimizer_offload:
        args.layers = args.layers or 0
        args.mbs = args.mbs or 3
        args.grad_acc = args.grad_acc or 43
        args.remat_policy = args.remat_policy or "dots_attn"
    else:
        if args.layers is None and args.model == "SmolLM-1.7B":
            # without offload the full model's state exceeds one chip;
            # 8 layers is the honest depth-reduced proxy (PERF.md)
            args.layers = 8
        args.mbs = args.mbs or 5
        args.grad_acc = args.grad_acc or 1
        args.remat_policy = args.remat_policy or "dots"
    if args.shardcheck:
        import sys

        from picotron_tpu.analysis import run_shardcheck

        cfg = bench_config(
            args.model, args.layers, args.seq, args.mbs,
            grad_acc=args.grad_acc, remat=not args.no_remat,
            remat_policy=args.remat_policy,
            adam_moments_dtype=args.adam_moments_dtype,
            ce_chunk=args.ce_chunk,
            optimizer_offload=args.optimizer_offload)
        rep = run_shardcheck(cfg)
        # human report on stderr; stdout keeps bench's one-JSON-line contract
        print(rep.render(verbose=True), file=sys.stderr)
        print(json.dumps({
            "metric": f"shardcheck_{args.model.split('/')[-1]}"
                      f"-{cfg.model.num_hidden_layers}L_seq{args.seq}",
            "value": 1.0 if rep.ok() else 0.0,
            "unit": "static_analysis_green",
            "errors": len(rep.errors()),
            "warnings": len(rep.warnings()),
        }))
        raise SystemExit(0 if rep.ok() else 1)
    print(json.dumps(run_one(
        args.model, args.layers, args.seq, args.mbs, grad_acc=args.grad_acc,
        steps=args.steps, warmup=args.warmup, remat=not args.no_remat,
        remat_policy=args.remat_policy,
        adam_moments_dtype=args.adam_moments_dtype, ce_chunk=args.ce_chunk,
        optimizer_offload=args.optimizer_offload, profile=args.profile,
        profile_steps=args.profile_steps, telemetry=args.telemetry,
        trace=args.trace)))


if __name__ == "__main__":
    main()
