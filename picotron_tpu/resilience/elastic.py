"""Elastic scale-out: restore a checkpoint into a resized dp / pp mesh.

The resilience stack (preemption/watchdog/guards) and the verified
checkpoint lineage (ckpt_integrity) recover a fixed-shape world: every
restart resumes at the topology the checkpoint was saved under. Production
fleets shrink and grow — losing a slice of a spot/preemptible pod must
cost a resize, not the run. This module supplies the pieces that make a
topology change safe:

- **Topology read/compare** — `saved_topology` reads the source layout a
  step dir was committed under (the commit manifest's `topology` field;
  meta.json's recorded config for pre-manifest checkpoints), and
  `topology_mismatch` names the axes that differ from the restoring run's
  mesh. `checkpoint.restore` routes every mismatch through here: hard
  RuntimeError (naming both layouts and the fix) when elastic resume is
  off, a validated resize when `checkpoint.elastic` is on.
- **Resize planning at constant global batch** — `plan_resize` recomputes
  (micro_batch_size, gradient_accumulation_steps) for a new dp so
  `global_batch_size = mbs * ga * dp * ep` is unchanged. Constant global
  batch is THE elastic invariant: it keeps the optimizer trajectory
  comparable (same tokens per update) and makes the dataloader cursor —
  which counts consumed sample blocks — valid verbatim on the resized
  run. A dp the global batch cannot host fails with the arithmetic.
- **Dataloader cursor translation** — `translate_dataloader_state`
  carries the `(epoch, cursor)` position across the resize. At constant
  global batch the position is layout-independent (the loader assembles
  the GLOBAL batch on every process and the mesh sharding does the
  splitting), so translation is validation + pass-through: token-exact,
  no sample replayed, none skipped (pinned by the N->M->N sample-trace
  test in tests/test_elastic.py).
- **ZeRO-1 shard arithmetic** — `split_zero1` / `regather_zero1` /
  `resize_zero1` are the host-side regather/re-split primitives for the
  per-leaf 1/N optimizer shards described by `zero1_info`
  (parallel/api.offload_zero1_info). Pure numpy concatenate/split along
  the recorded shard dim: an N->M->N round trip is bitwise identity by
  construction, which the fp32 parity test pins against a never-resized
  twin. (For the in-mesh zero1 path the global arrays are unchanged —
  only the sharding annotation extends over dp — so Orbax's
  restore-to-template reshard IS the re-split; these helpers serve the
  offload layout and the parity proof.)

Supported resize axes are SUPPORTED_ELASTIC_AXES = (dp, pp), alone or
jointly. dp rides the constant-global-batch plan + ZeRO-1 re-split below;
pp rides the padded global layer stack (pp_layer_placement pads to a
common multiple, so every even-split pp stores the SAME stack — the
restore-time slot-layout check in checkpoint.restore gates uneven splits)
and the MPMD executor's rebuild-from-config startup (per-stage programs +
schedule table are derived from the run config, boundary ring buffers are
rebuilt per process start). pp does not enter `mbs * ga * dp * ep`, so a
pure-pp resize holds global batch constant trivially. tp/cp/ep stay hard
errors either way: nothing re-partitions the weight math they split.

Two consumption flavors, both exercised by `tools/chaos.py --scenario
dp_resize` (and its pp twin `--scenario pp_resize`):

1. **Offline re-stamp** (`tools/elastic_resize.py`): rewrite a verified
   step dir's meta.json for the new layout and re-commit its manifest.
   The resumed run needs no special config — the checkpoint simply IS a
   dp=M checkpoint afterwards.
2. **Restore-time resize** (`checkpoint.elastic=true`): the restoring run
   detects the mismatch, validates the constant-global-batch invariant,
   books the restore under the `resize` goodput category, and lets Orbax
   reshard into the target template.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

# The mesh axes a checkpoint's source topology is compared on. world_size
# is derived (product of these); process_count is a launch detail Orbax
# already absorbs (global arrays restore under any process->device map).
TOPOLOGY_AXES = ("dp", "pp", "ep", "cp", "tp")

# The axes a resize (offline re-stamp or checkpoint.elastic) can actually
# carry a checkpoint across. dp: constant-global-batch re-factoring +
# ZeRO-1 re-split (PR 11). pp: the padded global layer stack is identical
# for every pp whose slot layout matches (even splits — the restore-time
# pp_layer_placement check gates it), and the MPMD executor rebuilds its
# per-stage programs + schedule table from config at startup, so no array
# surgery is needed. tp/cp/ep re-partition WEIGHT math (head splits,
# expert placement, sequence shards) that neither the re-stamp tool nor
# Orbax's reshard validates — a mismatch there must fail loudly even when
# elastic is on, never proceed into an unsupported restore. slices: the
# slice count is physical placement metadata, not an array sharding — a
# restore into fewer slices (the slice-loss recovery path) is just a
# dp/pp resize whose recorded slice count also changed, so it rides the
# same machinery (tools/chaos.py --scenario slice_lost pins it e2e).
SUPPORTED_ELASTIC_AXES = ("dp", "pp", "slices")


def topology_from_distributed(dist) -> dict:
    """Axis sizes of a DistributedConfig (or its to_json_dict() dict) in
    the manifest's topology schema."""
    get = (dist.get if isinstance(dist, dict)
           else lambda k, d=None: getattr(dist, k, d))
    topo = {ax: int(get(f"{ax}_size", 1) or 1) for ax in TOPOLOGY_AXES}
    topo["world_size"] = int(np.prod([topo[ax] for ax in TOPOLOGY_AXES]))
    # physical slice count rides along (field name `slices`, not a mesh
    # axis: it never enters world_size — slices partition the axes above)
    topo["slices"] = int(get("slices", 1) or 1)
    return topo


def describe_topology(topo: Optional[dict]) -> str:
    """Compact operator-facing rendering: 'dp2 pp1 ep1 cp1 tp2' — with a
    'slices2' suffix only for multi-slice layouts (single-slice stays
    byte-identical to the pre-slices rendering)."""
    if not topo:
        return "unknown"
    s = " ".join(f"{ax}{topo.get(ax, '?')}" for ax in TOPOLOGY_AXES)
    if int(topo.get("slices", 1) or 1) > 1:
        s += f" slices{int(topo['slices'])}"
    return s


def saved_topology(step_dir: str) -> Optional[dict]:
    """Source topology a committed step dir was saved under: the commit
    manifest's `topology` field when present (PR 5 lineage), else derived
    from meta.json's recorded config (pre-manifest checkpoints), else
    None (nothing recorded — no compatibility claim can be made)."""
    from picotron_tpu.ckpt_integrity.manifest import MANIFEST_NAME

    man_path = os.path.join(step_dir, MANIFEST_NAME)
    try:
        with open(man_path) as f:
            topo = json.load(f).get("topology") or {}
        if any(ax in topo for ax in TOPOLOGY_AXES):
            return {k: int(v) for k, v in topo.items()
                    if isinstance(v, (int, float))}
    except (FileNotFoundError, json.JSONDecodeError, ValueError):
        pass
    try:
        with open(os.path.join(step_dir, "meta.json")) as f:
            dist = (json.load(f).get("config") or {}).get("distributed")
        if dist:
            return topology_from_distributed(dist)
    except (FileNotFoundError, json.JSONDecodeError, ValueError):
        pass
    return None


def topology_mismatch(saved: Optional[dict],
                      current: Optional[dict]) -> list[str]:
    """Axis names whose size differs between a saved and current topology
    ([] when compatible or when either side recorded nothing)."""
    if not saved or not current:
        return []
    axes = [ax for ax in TOPOLOGY_AXES
            if saved.get(ax) is not None and current.get(ax) is not None
            and int(saved[ax]) != int(current[ax])]
    # slice count compares with a default of 1: a pre-slices checkpoint
    # recorded no field, which means single-slice — restoring it into a
    # multi-slice mesh (or vice versa) IS a topology change
    if int(saved.get("slices", 1) or 1) != int(current.get("slices", 1) or 1):
        axes.append("slices")
    return axes


def resize_invocation(save_dir: str, step: int, current: dict,
                      axes=("dp",)) -> str:
    """The offline re-stamp command that would adapt the checkpoint to
    this run's shape — quoted verbatim in the mismatch RuntimeError.
    Renders a flag per ACTUALLY-mismatched supported axis (a pure-pp
    mismatch must print a `--pp` line, not a `--dp` no-op that would not
    fix it)."""
    flags = " ".join(f"--{ax} {int(current[ax])}"
                     for ax in SUPPORTED_ELASTIC_AXES if ax in axes)
    return (f"python tools/elastic_resize.py {save_dir} "
            f"--step {step} {flags}".rstrip())


# ---------------------------------------------------------------------------
# Resize planning at constant global batch
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ResizePlan:
    """A dp resize that preserves global_batch_size = mbs * ga * dp * ep."""

    dp_old: int
    dp_new: int
    micro_batch_size: int
    gradient_accumulation_steps: int
    global_batch_size: int

    def overrides(self) -> dict:
        """Config-section updates the resized run needs (merge into the
        run config's distributed/training sections)."""
        return {
            "distributed": {"dp_size": self.dp_new},
            "training": {
                "micro_batch_size": self.micro_batch_size,
                "gradient_accumulation_steps":
                    self.gradient_accumulation_steps,
            },
        }

    def overrides_line(self) -> str:
        return (f"distributed.dp_size={self.dp_new} "
                f"training.micro_batch_size={self.micro_batch_size} "
                f"training.gradient_accumulation_steps="
                f"{self.gradient_accumulation_steps}")


def plan_resize(*, micro_batch_size: int, gradient_accumulation_steps: int,
                dp_size: int, dp_new: int, ep_size: int = 1) -> ResizePlan:
    """Re-factor (mbs, ga) for a new dp at constant global batch.

    Preference order: keep mbs and scale ga (same per-device step shape —
    no recompile of the microbatch program beyond the dp axis), else keep
    ga and scale mbs, else put the whole per-replica batch into mbs.
    Raises ValueError when the global batch cannot be split over dp_new
    replicas at all — the caller must change global batch deliberately,
    never have it drift under a resize."""
    if dp_new < 1:
        raise ValueError(f"dp_new must be >= 1, got {dp_new}")
    gbs = micro_batch_size * gradient_accumulation_steps * dp_size * ep_size
    per_replica = gbs // (dp_new * ep_size)
    if per_replica * dp_new * ep_size != gbs or per_replica < 1:
        raise ValueError(
            f"global batch {gbs} (= mbs {micro_batch_size} x ga "
            f"{gradient_accumulation_steps} x dp {dp_size} x ep {ep_size}) "
            f"cannot be kept constant at dp={dp_new} x ep={ep_size}: "
            f"{gbs} is not divisible by {dp_new * ep_size}. Elastic resize "
            f"holds global batch fixed; pick a dp that divides it")
    if per_replica % micro_batch_size == 0:
        mbs, ga = micro_batch_size, per_replica // micro_batch_size
    elif per_replica % gradient_accumulation_steps == 0:
        mbs = per_replica // gradient_accumulation_steps
        ga = gradient_accumulation_steps
    else:
        mbs, ga = per_replica, 1
    return ResizePlan(dp_old=dp_size, dp_new=dp_new, micro_batch_size=mbs,
                      gradient_accumulation_steps=ga,
                      global_batch_size=gbs)


def saved_global_batch(meta: dict) -> Optional[int]:
    """global_batch_size recorded in a checkpoint's meta.json config, or
    None when the checkpoint predates config recording."""
    cfg = meta.get("config") or {}
    tr, dist = cfg.get("training") or {}, cfg.get("distributed") or {}
    try:
        return (int(tr["micro_batch_size"])
                * int(tr["gradient_accumulation_steps"])
                * int(dist.get("dp_size", 1))
                * int(dist.get("ep_size", 1)))
    except (KeyError, TypeError, ValueError):
        return None


def translate_dataloader_state(state: dict, *, gbs_old: int,
                               gbs_new: int) -> dict:
    """Carry a loader's (epoch, cursor) position across a resize.

    The cursor counts consumed sample blocks of the GLOBAL stream, so at
    constant global batch the position is layout-independent and carries
    verbatim — token-exact by construction. A changed global batch is
    only representable when the cursor lands on a whole number of new
    steps (otherwise the position falls mid-batch: some samples would be
    replayed or skipped), and elastic resize never changes it — so
    anything else is a hard error, not a rounding."""
    epoch, cursor = int(state["epoch"]), int(state["cursor"])
    if cursor % gbs_new != 0:
        raise ValueError(
            f"dataloader cursor {cursor} (epoch {epoch}) consumed under "
            f"global batch {gbs_old} does not land on a step boundary of "
            f"global batch {gbs_new}; elastic resize requires constant "
            f"global batch (cursor must be token-exact — no sample "
            f"replayed, none skipped)")
    return {"epoch": epoch, "cursor": cursor}


# ---------------------------------------------------------------------------
# ZeRO-1 shard arithmetic (host-side, bitwise)
# ---------------------------------------------------------------------------


def split_zero1(full: np.ndarray, dim: int, n: int) -> list[np.ndarray]:
    """Split one fp32 leaf into n equal ZeRO-1 shards along its recorded
    shard dim (zero1_info's `dim`). Mirrors optimizer.z1_slice's
    dynamic_slice_in_dim partitioning: contiguous equal blocks, shard i
    owning rows [i*L/n, (i+1)*L/n)."""
    if dim >= full.ndim or full.shape[dim] % n != 0:
        raise ValueError(
            f"cannot split shape {full.shape} into {n} shards along dim "
            f"{dim}: {full.shape[dim] if dim < full.ndim else '?'} rows "
            f"not divisible")
    return [np.ascontiguousarray(s) for s in np.split(full, n, axis=dim)]


def regather_zero1(shards: list[np.ndarray], dim: int) -> np.ndarray:
    """Reassemble the full leaf from its per-replica 1/N shards (the
    inverse of split_zero1; the host-side analogue of the update's
    all-gather)."""
    return np.concatenate(shards, axis=dim)


def resize_zero1(shards: list[np.ndarray], dim: int,
                 n_new: int) -> list[np.ndarray]:
    """Re-split 1/N optimizer shards as 1/M: regather, then split. Pure
    memory movement of the same fp32 bytes — no arithmetic touches the
    values, so an N->M->N round trip is bitwise identity (pinned against
    a never-resized twin in tests/test_elastic.py)."""
    return split_zero1(regather_zero1(shards, dim), dim, n_new)


def resize_zero1_leaves(shard_lists: list, zero1_info: list,
                        n_new_of=None) -> list:
    """Apply resize_zero1 across a flattened leaf list. `shard_lists[i]`
    is the list of shards for leaf i (or a single array when
    zero1_info[i] is None — unsharded leaves carry through untouched).
    `n_new_of(place)` maps a leaf's (dim, axes, sizes) placement to its
    new shard count; default = same count (identity round trip)."""
    out = []
    for shards, place in zip(shard_lists, zero1_info):
        if place is None:
            out.append(shards)
            continue
        dim, _axes, sizes = place
        n_new = (n_new_of(place) if n_new_of is not None
                 else int(np.prod(sizes)))
        out.append(resize_zero1(shards, dim, n_new))
    return out


# ---------------------------------------------------------------------------
# Restore-time compatibility check (the checkpoint.restore hook)
# ---------------------------------------------------------------------------


def check_restore_topology(step_dir: str, meta: dict, cfg,
                           *, step: int, save_dir: str) -> Optional[dict]:
    """Route a topology mismatch at restore time.

    Returns None when the saved and current topologies agree (or the
    checkpoint recorded none — pre-lineage stores keep restoring).
    On a mismatch:

    - any axis outside SUPPORTED_ELASTIC_AXES ({dp, pp}) differs:
      RuntimeError naming the unsupported axis — `checkpoint.elastic`
      cannot authorize a tp/cp/ep change, because nothing re-partitions
      the weight math those axes split.
    - `checkpoint.elastic` off: RuntimeError naming both topologies and
      the `tools/elastic_resize.py` invocation (flags for the
      actually-mismatched axes) that would re-stamp the checkpoint for
      this mesh — a changed fleet shape must never resume silently wrong.
    - `checkpoint.elastic` on, mismatch within {dp, pp}: validate the
      constant-global-batch invariant (raising with the exact overrides
      that restore it when violated) and return the resize record
      {"from", "to", "axes"} for the caller to book/emit. A pp change is
      additionally gated by the padded-layer-stack slot check in
      checkpoint.restore (even splits only), which runs right after this
      guard.
    """
    saved = saved_topology(step_dir)
    current = topology_from_distributed(cfg.distributed)
    axes = topology_mismatch(saved, current)
    if not axes:
        return None
    unsupported = [ax for ax in axes if ax not in SUPPORTED_ELASTIC_AXES]
    if unsupported:
        raise RuntimeError(
            f"checkpoint step {step} under {save_dir} was saved at "
            f"topology [{describe_topology(saved)}] but this run's mesh "
            f"is [{describe_topology(current)}] (mismatched axes: "
            f"{', '.join(axes)}); axis "
            f"{'/'.join(unsupported)} is not elastic-resizable "
            f"(supported: {', '.join(SUPPORTED_ELASTIC_AXES)}) — "
            f"checkpoint.elastic cannot reshard across it. Restore on "
            f"the saved {'/'.join(unsupported)} size")
    if not getattr(cfg.checkpoint, "elastic", False):
        raise RuntimeError(
            f"checkpoint step {step} under {save_dir} was saved at "
            f"topology [{describe_topology(saved)}] but this run's mesh "
            f"is [{describe_topology(current)}] (mismatched axes: "
            f"{', '.join(axes)}); refusing to resume silently across a "
            f"topology change. Either restore on the saved topology, "
            f"re-stamp the checkpoint offline with\n"
            f"  {resize_invocation(save_dir, step, current, axes)}\n"
            f"or set checkpoint.elastic=true to reshard at restore time "
            f"(global batch must stay constant)")
    gbs_saved = saved_global_batch(meta)
    gbs_now = cfg.global_batch_size
    if gbs_saved is not None and gbs_saved != gbs_now:
        plan = None
        try:
            tr = meta["config"]["training"]
            plan = plan_resize(
                micro_batch_size=int(tr["micro_batch_size"]),
                gradient_accumulation_steps=int(
                    tr["gradient_accumulation_steps"]),
                dp_size=int(saved.get("dp", 1)),
                dp_new=int(current["dp"]),
                ep_size=int(current.get("ep", 1)))
        except (KeyError, TypeError, ValueError):
            pass
        hint = (f"; to keep it constant at dp={current['dp']}: --override "
                f"{plan.overrides_line()}" if plan is not None else "")
        raise RuntimeError(
            f"elastic restore of step {step} ({describe_topology(saved)} "
            f"-> {describe_topology(current)}) changes global_batch_size "
            f"{gbs_saved} -> {gbs_now}, which breaks the token-exact "
            f"dataloader cursor and the loss-parity guarantee{hint}")
    return {"from": saved, "to": current, "axes": axes}
