"""Graceful preemption: turn SIGTERM into a durable checkpoint, not a
lost run.

Preemptible/spot TPU pods get SIGTERM with a short grace window before
the machine disappears. The handler only records the request (signal
handlers must not run Python of any consequence — the main thread may be
inside an XLA dispatch); the step loop polls `triggered` after each step,
finishes the in-flight step, writes an emergency checkpoint including the
dataloader position, and exits `EXIT_PREEMPTED`. This record-only design
is also what makes a MID-SCHEDULE preemption safe on the MPMD executor:
a SIGTERM landing inside the schedule walk (parallel/mpmd._run_schedule;
chaos can inject one at a named (stage, tick, op) via `sigterm@N#T`)
merely sets the flag, so the walk drains to the step boundary and the
emergency checkpoint never persists half-accumulated gradients. A supervisor that
resubmits the same config with `checkpoint.auto_resume` then continues
losslessly — no replayed data, no lost steps.

SIGINT rides the same path so a Ctrl-C during local runs also exits with
durable state; a *second* SIGINT restores the default handlers and raises
KeyboardInterrupt for the impatient.

Multi-host note: a real preemption signals every host; the emergency save
is a coordinated Orbax write, so all processes must take this path —
which they do, because each receives its own SIGTERM (and chaos's
``sigterm@N`` self-delivers on every process at the same step).
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import Optional

EXIT_PREEMPTED = 75


class PreemptionHandler:
    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self._event = threading.Event()
        self._prev: dict = {}
        self.signum: Optional[int] = None

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def install(self) -> bool:
        """Install handlers; returns False (and stays inert) when not on
        the main thread, where CPython forbids signal.signal."""
        try:
            for s in self.SIGNALS:
                self._prev[s] = signal.signal(s, self._on_signal)
        except ValueError:
            self.uninstall()
            return False
        return True

    def uninstall(self) -> None:
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._prev = {}

    def __enter__(self) -> "PreemptionHandler":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _on_signal(self, signum, frame) -> None:
        if self._event.is_set() and signum == signal.SIGINT:
            # Second Ctrl-C: the user wants out NOW, durable or not.
            self.uninstall()
            raise KeyboardInterrupt
        self.signum = signum
        self._event.set()
        name = signal.Signals(signum).name
        print(f"[preemption] caught {name}; will "
              f"finish the in-flight step, write an emergency checkpoint, "
              f"and exit {EXIT_PREEMPTED}", file=sys.stderr, flush=True)
        # Record-only event (the drain time itself is booked by the
        # driver's preempt-save phase). Runs in the Python-level handler
        # between bytecodes on the main thread, so the sink write is safe.
        from picotron_tpu.telemetry import bus

        bus.emit("preempt_signal", signal=name)
