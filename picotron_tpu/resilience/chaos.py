"""Deterministic fault injection — make every recovery path testable on CPU.

A chaos spec is a comma-separated list of events, each

    KIND@STEP[xCOUNT][~SECS]

- ``KIND``: one of ``sigterm`` / ``sigint`` (deliver that signal to this
  process at the start of step STEP — exercises the real preemption
  handler), ``hang`` (sleep SECS in the step loop at step STEP),
  ``ckpt_io`` (raise OSError from the next COUNT checkpoint-save attempts
  at step STEP — exercises the save retry), ``data_io`` (same for the next
  COUNT batch-assembly attempts at *batch* STEP), ``data_stall`` (sleep
  SECS while producing batch STEP — exercises the watchdog), and
  ``nan_grad`` (poison the gradients/loss of COUNT step executions
  starting at the first execution of step STEP — a budget, so a
  guard-rollback re-run of the same step number does not re-fire;
  exercises the divergence guard; injected inside the jitted step via
  ``make_train_step(..., inject_nan=True)``).
- ``xCOUNT`` defaults to 1; ``~SECS`` defaults to 0 and is required for the
  sleep kinds.

Examples: ``sigterm@3``, ``ckpt_io@2x2,nan_grad@4``, ``data_stall@3~10``.

The spec comes from ``resilience.chaos`` in the config; the
``PICOTRON_CHAOS`` environment variable, when set (even to the empty
string), overrides it — that is how a supervisor restarts a chaos run
without the fault recurring. Events key on the step/batch *number*, so
injection is deterministic and identical across processes of a multi-host
run (every process self-delivers its SIGTERM at the same step, the way a
real preemption hits every host of a pod at once).

Injection points call `fire(point, step)`; an inactive controller (the
default) makes those calls free, so library code carries the hooks
unconditionally.
"""

from __future__ import annotations

import os
import re
import signal
import sys
import time
from dataclasses import dataclass, field

KINDS = ("sigterm", "sigint", "hang", "ckpt_io", "data_io", "data_stall",
         "nan_grad")

# Which event kinds an injection point can trigger. "nan_grad" has no fire
# point: the driver reads nan_grad_steps() and routes those steps through
# the poisoned jitted step instead (a host-side hook cannot reach inside
# the compiled program).
_POINT_KINDS = {
    "step_begin": ("sigterm", "sigint", "hang"),
    "ckpt_save": ("ckpt_io",),
    "data_produce": ("data_io", "data_stall"),
}

_EVENT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<step>\d+)"
    r"(?:x(?P<count>\d+))?(?:~(?P<secs>\d+(?:\.\d+)?))?$")


@dataclass
class ChaosEvent:
    kind: str
    step: int          # 1-based training step (or batch number for data_*)
    count: int = 1     # xN: firings before the event is exhausted
    secs: float = 0.0  # ~S: sleep duration for hang / data_stall
    fired: int = field(default=0, compare=False)


def parse_spec(spec: str) -> list[ChaosEvent]:
    """Parse a chaos spec; raises ValueError naming the bad event."""
    events = []
    for item in (spec or "").replace(" ", "").split(","):
        if not item:
            continue
        m = _EVENT_RE.match(item)
        if not m:
            raise ValueError(
                f"bad chaos event {item!r}: expected KIND@STEP[xCOUNT][~SECS]"
                f" with KIND in {KINDS}")
        kind = m.group("kind")
        if kind not in KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r} in {item!r}; known: {KINDS}")
        secs = float(m.group("secs") or 0.0)
        if kind in ("hang", "data_stall") and secs <= 0:
            raise ValueError(
                f"chaos event {item!r} needs a ~SECS duration (e.g. "
                f"{kind}@{m.group('step')}~5)")
        events.append(ChaosEvent(kind=kind, step=int(m.group("step")),
                                 count=int(m.group("count") or 1), secs=secs))
    return events


def _log(msg: str) -> None:
    # stderr, every process: chaos firings must be visible even from
    # non-logging hosts (they are the whole point of a chaos run).
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def _emit(e: "ChaosEvent", point: str, step: int) -> None:
    # Record-only telemetry (no category: the injected fault's COST is
    # booked by whatever it disrupts — the stalled data phase, the retry
    # backoff, the rollback — so booking the injection too would
    # double-count). The event ties the booked badput to its cause in
    # the JSONL stream.
    from picotron_tpu.telemetry import bus

    bus.emit("chaos", chaos_kind=e.kind, point=point, step=step,
             fired=e.fired, count=e.count)


class ChaosController:
    def __init__(self, events: list[ChaosEvent]):
        self.events = list(events)

    @property
    def active(self) -> bool:
        return bool(self.events)

    def describe(self) -> str:
        return ", ".join(
            f"{e.kind}@{e.step}" + (f"x{e.count}" if e.count > 1 else "")
            + (f"~{e.secs:g}" if e.secs else "")
            for e in self.events)

    def has_nan_grad(self) -> bool:
        """True when the spec names any nan_grad event — the driver then
        compiles the poisoned step twin."""
        return any(e.kind == "nan_grad" for e in self.events)

    def poison_step(self, step: int) -> bool:
        """Should this step execution run with poisoned gradients?
        nan_grad@S xN fires on the first N step *executions* starting at
        the first execution of step S — a budget, not a step predicate:
        after a guard rollback re-runs step S (on the post-poison data the
        rollback skipped to), an exhausted event must not re-fire, or the
        run would re-live the same divergence forever."""
        for e in self.events:
            if e.kind != "nan_grad" or e.fired >= e.count:
                continue
            if e.fired > 0 or step == e.step:
                e.fired += 1
                _log(f"poisoning gradients at step {step} "
                     f"({e.fired}/{e.count})")
                _emit(e, "poison_step", step)
                return True
        return False

    def fire(self, point: str, step: int) -> None:
        """Trigger any event bound to `point` whose step matches and whose
        firing budget is not exhausted. May sleep, raise OSError, or
        deliver a signal to this process."""
        for e in self.events:
            if (e.kind not in _POINT_KINDS.get(point, ())
                    or e.step != step or e.fired >= e.count):
                continue
            e.fired += 1
            _log(f"firing {e.kind} at {point} step {step} "
                 f"({e.fired}/{e.count})")
            _emit(e, point, step)
            if e.kind in ("sigterm", "sigint"):
                os.kill(os.getpid(),
                        signal.SIGTERM if e.kind == "sigterm"
                        else signal.SIGINT)
            elif e.kind in ("hang", "data_stall"):
                time.sleep(e.secs)
            else:  # ckpt_io / data_io
                raise OSError(
                    f"chaos-injected {e.kind} failure at {point} "
                    f"step {step} ({e.fired}/{e.count})")


# Module-level controller: library injection points (checkpoint.py,
# data.py) reach chaos without any plumbing; train.main installs per run.
_controller = ChaosController([])


def install(spec: str = "") -> ChaosController:
    """Activate chaos for this process. `spec` is the config's
    resilience.chaos; PICOTRON_CHAOS, when set, wins (empty value =
    disable — the supervisor-restart story)."""
    env = os.environ.get("PICOTRON_CHAOS")
    if env is not None:
        spec = env
    global _controller
    _controller = ChaosController(parse_spec(spec))
    return _controller


def controller() -> ChaosController:
    return _controller


def fire(point: str, step: int) -> None:
    _controller.fire(point, step)
