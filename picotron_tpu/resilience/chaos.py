"""Deterministic fault injection — make every recovery path testable on CPU.

A chaos spec is a comma-separated list of events, each

    KIND@STEP[xCOUNT][~SECS][#TICK]

- ``KIND``: one of ``sigterm`` / ``sigint`` (deliver that signal to this
  process at the start of step STEP — exercises the real preemption
  handler), ``kill`` (SIGKILL at the start of step STEP: a hard crash —
  no handler, no emergency checkpoint; exercises supervisor restart from
  whatever is durable), ``slice_lost`` (SIGKILL at the start of step STEP,
  logged as the loss of this process's slice: the whole-slice failure mode
  of a multi-slice pod — every process of the lost slice dies at once and
  the job cannot come back at the old shape; recovery is a supervised
  restart into FEWER slices via ``tools/elastic_resize.py --slices`` or
  ``checkpoint.elastic``, pinned e2e by ``tools/chaos.py --scenario
  slice_lost``), ``hang`` (sleep SECS in the step loop at step
  STEP), ``ckpt_io`` (raise OSError from the next COUNT checkpoint-save
  attempts at step STEP — exercises the save retry), ``data_io`` (same
  for the next COUNT batch-assembly attempts at *batch* STEP),
  ``data_stall`` (sleep SECS while producing batch STEP — exercises the
  watchdog), ``nan_grad`` (poison the gradients/loss of COUNT step
  executions starting at the first execution of step STEP — a budget, so
  a guard-rollback re-run of the same step number does not re-fire;
  exercises the divergence guard; injected inside the jitted step via
  ``make_train_step(..., inject_nan=True)``), and the corruption kinds
  ``ckpt_corrupt_bitflip`` / ``ckpt_truncate`` / ``ckpt_torn_meta``
  (mutate the checkpoint COMMITTED at step STEP on disk — flip a byte in
  the largest array payload, truncate it to half, or tear meta.json —
  exercising manifest verification and the lineage fallback in
  checkpoint.latest_valid_step).
- ``xCOUNT`` defaults to 1; ``~SECS`` defaults to 0 and is required for the
  sleep kinds.
- ``#TICK`` (signal/sleep kinds only) moves the event INSIDE the MPMD
  schedule walk: instead of firing at the start of step STEP, it fires at
  the named schedule tick of that step's walk — the `schedule_tick` point
  parallel/mpmd._run_schedule calls per dispatched op, with the live
  (stage, tick, op) as context. This is how a preemption or hang is
  injected mid-schedule rather than between steps; an event without
  ``#TICK`` never fires there, and a ``#TICK`` event never fires at
  step_begin.

Serving faults key on the REQUEST id instead of the step number (the
serve fleet's dispatch loop fires them with the request id in the STEP
position — same grammar, different clock): ``engine_dead@REQ`` (the
engine request REQ is being routed to, or decoding on, dies abruptly —
state discarded wholesale, the in-process equivalent of SIGKILLing one
replica; the FleetSupervisor catches `ChaosEngineDead` and re-dispatches
the dead engine's residents onto survivors), ``decode_hang@REQ~SECS``
(sleep SECS inside the decode dispatch path while request REQ is
resident — exercises the serve watchdog, which names the hung
``serve engine=K dispatch=decode`` and dumps a ``serve_hang``
postmortem), and ``shed_storm@REQ`` (force the deadline shed decision
for request REQ regardless of its actual queue wait; ``xCOUNT`` sheds
the next COUNT routed requests — the overload-burst storm).

Examples: ``sigterm@3``, ``ckpt_io@2x2,nan_grad@4``, ``data_stall@3~10``,
``ckpt_corrupt_bitflip@4,kill@5``, ``sigterm@3#2`` (mid-schedule),
``hang@4~120#1``, ``engine_dead@4``, ``decode_hang@2~5``,
``shed_storm@6x3``.

The spec comes from ``resilience.chaos`` in the config; the
``PICOTRON_CHAOS`` environment variable, when set (even to the empty
string), overrides it — that is how a supervisor restarts a chaos run
without the fault recurring. Events key on the step/batch *number*, so
injection is deterministic and identical across processes of a multi-host
run (every process self-delivers its SIGTERM at the same step, the way a
real preemption hits every host of a pod at once).

Injection points call `fire(point, step)`; an inactive controller (the
default) makes those calls free, so library code carries the hooks
unconditionally.
"""

from __future__ import annotations

import os
import re
import signal
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

KINDS = ("sigterm", "sigint", "kill", "slice_lost", "hang", "ckpt_io",
         "data_io", "data_stall", "nan_grad", "ckpt_corrupt_bitflip",
         "ckpt_truncate", "ckpt_torn_meta",
         "engine_dead", "decode_hang", "shed_storm")


class ChaosEngineDead(Exception):
    """Raised by fire() at a serve point for `engine_dead`: the engine
    handling this request dies abruptly. The FleetSupervisor catches it,
    discards the engine's state wholesale (pool and all — nothing
    graceful, the SIGKILL analogue for an in-process replica) and
    re-dispatches its residents; anything else letting it propagate is a
    bug, which is exactly what the chaos run would surface."""

    def __init__(self, engine=None):
        super().__init__(f"chaos: engine {engine} dead")
        self.engine = engine


class ChaosShed(Exception):
    """Raised by fire() at the serve_route point for `shed_storm`: the
    supervisor must shed this request as if its deadline were already
    blown — the deterministic stand-in for a burst arriving faster than
    admission can drain."""

# Which event kinds an injection point can trigger. "nan_grad" has no fire
# point: the driver reads nan_grad_steps() and routes those steps through
# the poisoned jitted step instead (a host-side hook cannot reach inside
# the compiled program). "ckpt_committed" fires from CheckpointManager's
# commit (manifest written, process 0) with the step dir as context — the
# corruption kinds mutate a checkpoint the store considers good.
_POINT_KINDS = {
    # slice_lost is step_begin-only: a slice dies between steps from the
    # surviving scheduler's viewpoint; mid-schedule slice death is the
    # #TICK kill (the MPMD walk cannot tell which process vanished)
    "step_begin": ("sigterm", "sigint", "kill", "slice_lost", "hang"),
    # inside the MPMD schedule walk (parallel/mpmd._run_schedule), one
    # call per dispatched op with ctx (tick, stage, op, mb); only #TICK
    # events fire here
    "schedule_tick": ("sigterm", "sigint", "kill", "hang"),
    "ckpt_save": ("ckpt_io",),
    "data_produce": ("data_io", "data_stall"),
    "ckpt_committed": ("ckpt_corrupt_bitflip", "ckpt_truncate",
                       "ckpt_torn_meta"),
    # serve points fire with a REQUEST id in the step position (the
    # serving clock is requests, not steps). serve_route: the fleet is
    # routing request REQ to an engine (ctx: engine). serve_dispatch:
    # an engine is about to run a decode dispatch with request REQ
    # resident (ctx: engine) — the point a hang must hit for the
    # watchdog to name the dispatch.
    "serve_route": ("engine_dead", "shed_storm"),
    "serve_dispatch": ("engine_dead", "decode_hang"),
}

# Kinds that may carry a #TICK suffix (the schedule_tick-capable set).
_TICK_KINDS = ("sigterm", "sigint", "kill", "hang")

_EVENT_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<step>\d+)"
    r"(?:x(?P<count>\d+))?(?:~(?P<secs>\d+(?:\.\d+)?))?"
    r"(?:#(?P<tick>\d+))?$")


@dataclass
class ChaosEvent:
    kind: str
    step: int          # 1-based training step (or batch number for data_*)
    count: int = 1     # xN: firings before the event is exhausted
    secs: float = 0.0  # ~S: sleep duration for hang / data_stall
    tick: Optional[int] = None  # #T: fire at this MPMD schedule tick
    fired: int = field(default=0, compare=False)


def parse_spec(spec: str) -> list[ChaosEvent]:
    """Parse a chaos spec; raises ValueError naming the bad event."""
    events = []
    for item in (spec or "").replace(" ", "").split(","):
        if not item:
            continue
        m = _EVENT_RE.match(item)
        if not m:
            raise ValueError(
                f"bad chaos event {item!r}: expected "
                f"KIND@STEP[xCOUNT][~SECS][#TICK] with KIND in {KINDS}")
        kind = m.group("kind")
        if kind not in KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r} in {item!r}; known: {KINDS}")
        secs = float(m.group("secs") or 0.0)
        if kind in ("hang", "data_stall", "decode_hang") and secs <= 0:
            raise ValueError(
                f"chaos event {item!r} needs a ~SECS duration (e.g. "
                f"{kind}@{m.group('step')}~5)")
        tick = m.group("tick")
        if tick is not None and kind not in _TICK_KINDS:
            raise ValueError(
                f"chaos event {item!r}: #TICK (mid-schedule injection) "
                f"only applies to {_TICK_KINDS}, not {kind!r}")
        events.append(ChaosEvent(kind=kind, step=int(m.group("step")),
                                 count=int(m.group("count") or 1), secs=secs,
                                 tick=int(tick) if tick is not None
                                 else None))
    return events


def _log(msg: str) -> None:
    # stderr, every process: chaos firings must be visible even from
    # non-logging hosts (they are the whole point of a chaos run).
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def _emit(e: "ChaosEvent", point: str, step: int, **ctx) -> None:
    # Record-only telemetry (no category: the injected fault's COST is
    # booked by whatever it disrupts — the stalled data phase, the retry
    # backoff, the rollback — so booking the injection too would
    # double-count). The event ties the booked badput to its cause in
    # the JSONL stream; schedule_tick firings carry the live
    # (stage, tick, op, mb) so a mid-schedule fault is addressable.
    from picotron_tpu.telemetry import bus

    bus.emit("chaos", chaos_kind=e.kind, point=point, step=step,
             fired=e.fired, count=e.count, **ctx)


class ChaosController:
    def __init__(self, events: list[ChaosEvent]):
        self.events = list(events)

    @property
    def active(self) -> bool:
        return bool(self.events)

    def describe(self) -> str:
        return ", ".join(
            f"{e.kind}@{e.step}" + (f"x{e.count}" if e.count > 1 else "")
            + (f"~{e.secs:g}" if e.secs else "")
            + (f"#{e.tick}" if e.tick is not None else "")
            for e in self.events)

    def has_tick_events(self) -> bool:
        """True when any event targets a schedule tick — the MPMD step
        then resolves the exact training-step number for its walk (a
        host sync it otherwise skips)."""
        return any(e.tick is not None for e in self.events)

    def has_nan_grad(self) -> bool:
        """True when the spec names any nan_grad event — the driver then
        compiles the poisoned step twin."""
        return any(e.kind == "nan_grad" for e in self.events)

    def poison_step(self, step: int) -> bool:
        """Should this step execution run with poisoned gradients?
        nan_grad@S xN fires on the first N step *executions* starting at
        the first execution of step S — a budget, not a step predicate:
        after a guard rollback re-runs step S (on the post-poison data the
        rollback skipped to), an exhausted event must not re-fire, or the
        run would re-live the same divergence forever."""
        for e in self.events:
            if e.kind != "nan_grad" or e.fired >= e.count:
                continue
            if e.fired > 0 or step == e.step:
                e.fired += 1
                _log(f"poisoning gradients at step {step} "
                     f"({e.fired}/{e.count})")
                _emit(e, "poison_step", step)
                return True
        return False

    def fire(self, point: str, step: int, **ctx) -> None:
        """Trigger any event bound to `point` whose step matches and whose
        firing budget is not exhausted. May sleep, raise OSError, deliver
        a signal to this process, or corrupt committed bytes on disk
        (`ctx["path"]` carries the checkpoint step dir for the
        ckpt_committed point). A #TICK event fires ONLY at the
        schedule_tick point when `ctx["tick"]` matches; an event without
        a tick never fires there — the two injection sites are disjoint
        by construction."""
        for e in self.events:
            if (e.kind not in _POINT_KINDS.get(point, ())
                    or e.fired >= e.count):
                continue
            if e.kind == "shed_storm":
                # a STORM: fires on request REQ, then keeps firing on
                # every subsequently routed request until its xCOUNT
                # budget drains (the nan_grad budget arrangement) — one
                # event sheds a contiguous run of arrivals.
                if e.fired == 0 and e.step != step:
                    continue
            elif e.step != step:
                continue
            if point == "schedule_tick":
                if e.tick is None or ctx.get("tick") != e.tick:
                    continue
            elif e.tick is not None:
                continue
            e.fired += 1
            where = (f" (stage={ctx.get('stage')} tick={ctx.get('tick')} "
                     f"op={ctx.get('op')} mb={ctx.get('mb')})"
                     if point == "schedule_tick" else
                     (f" (engine={ctx.get('engine')})"
                      if point.startswith("serve_") else ""))
            unit = "request" if point.startswith("serve_") else "step"
            _log(f"firing {e.kind} at {point} {unit} {step}{where} "
                 f"({e.fired}/{e.count})")
            _emit(e, point, step,
                  **{k: v for k, v in ctx.items()
                     if k in ("tick", "stage", "op", "mb", "engine")})
            if e.kind == "engine_dead":
                raise ChaosEngineDead(ctx.get("engine"))
            if e.kind == "shed_storm":
                raise ChaosShed(f"chaos: shed_storm at request {step}")
            if e.kind == "decode_hang":
                time.sleep(e.secs)
                continue
            if e.kind in ("sigterm", "sigint"):
                os.kill(os.getpid(),
                        signal.SIGTERM if e.kind == "sigterm"
                        else signal.SIGINT)
            elif e.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            elif e.kind == "slice_lost":
                # whole-slice death: every process of the slice vanishes
                # at once, ungracefully. Self-delivered SIGKILL per
                # process (deterministic across the pod, like the signal
                # kinds); the log names the slice this process sits on so
                # a multi-host transcript reads as one slice going dark.
                try:
                    import jax
                    proc = jax.process_index()
                except Exception:
                    proc = 0
                _log(f"slice_lost: the slice hosting process {proc} is "
                     f"gone (SIGKILL, no emergency checkpoint) — the job "
                     f"cannot restart at this slice count; resize with "
                     f"tools/elastic_resize.py --slices or "
                     f"checkpoint.elastic=true")
                os.kill(os.getpid(), signal.SIGKILL)
            elif e.kind in ("hang", "data_stall"):
                time.sleep(e.secs)
            elif e.kind in _CORRUPTIONS:
                _CORRUPTIONS[e.kind](ctx["path"])
            else:  # ckpt_io / data_io
                raise OSError(
                    f"chaos-injected {e.kind} failure at {point} "
                    f"step {step} ({e.fired}/{e.count})")


# ---------------------------------------------------------------------------
# Checkpoint corruption — the silent-data-corruption failure class. Each
# mutates a COMMITTED step dir (manifest already written, store considers
# it good), so recovery must come from verification + lineage fallback,
# not the commit protocol. Deterministic targets: the largest payload file
# is the same on every run of the same config.
# ---------------------------------------------------------------------------


def _largest_payload(step_dir: str) -> str:
    """Biggest file under the step's `state` dir — with Orbax's ocdbt
    layout that is an array data blob, the realistic bit-rot victim."""
    best, best_size = None, -1
    for root, _dirs, files in os.walk(os.path.join(step_dir, "state")):
        for f in files:
            p = os.path.join(root, f)
            size = os.path.getsize(p)
            if size > best_size:
                best, best_size = p, size
    if best is None:
        raise FileNotFoundError(f"no state payload files under {step_dir}")
    return best


def _corrupt_bitflip(step_dir: str) -> None:
    p = _largest_payload(step_dir)
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))
    _log(f"flipped a byte mid-file in {p}")


def _corrupt_truncate(step_dir: str) -> None:
    p = _largest_payload(step_dir)
    os.truncate(p, os.path.getsize(p) // 2)
    _log(f"truncated {p} to half")


def _corrupt_torn_meta(step_dir: str) -> None:
    p = os.path.join(step_dir, "meta.json")
    with open(p, "rb") as f:
        data = f.read()
    with open(p, "wb") as f:
        f.write(data[:max(1, len(data) // 2)])
    _log(f"tore {p} (half-written JSON)")


_CORRUPTIONS = {
    "ckpt_corrupt_bitflip": _corrupt_bitflip,
    "ckpt_truncate": _corrupt_truncate,
    "ckpt_torn_meta": _corrupt_torn_meta,
}


# Module-level controller: library injection points (checkpoint.py,
# data.py) reach chaos without any plumbing; train.main installs per run.
_controller = ChaosController([])


def install(spec: str = "") -> ChaosController:
    """Activate chaos for this process. `spec` is the config's
    resilience.chaos; PICOTRON_CHAOS, when set, wins (empty value =
    disable — the supervisor-restart story)."""
    env = os.environ.get("PICOTRON_CHAOS")
    if env is not None:
        spec = env
    global _controller
    _controller = ChaosController(parse_spec(spec))
    return _controller


def controller() -> ChaosController:
    return _controller


def fire(point: str, step: int, **ctx) -> None:
    _controller.fire(point, step, **ctx)
