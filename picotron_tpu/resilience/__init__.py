"""Runtime resilience: the dynamic half of failure handling.

PR 1 (shardcheck) made *static* failures cheap to catch before a run; this
package does the same for *runtime* failures — the things that actually end
long TPU pre-training runs in practice:

- **preemption** (SIGTERM from the scheduler): `PreemptionHandler` catches
  the signal, the driver finishes the in-flight step, writes an emergency
  checkpoint (with the dataloader position) inside the grace window, and
  exits `EXIT_PREEMPTED` so an external supervisor resubmits into
  `checkpoint.auto_resume`.
- **divergence** (NaN/Inf loss or grads, loss spikes): `DivergenceGuard`
  watches the step metrics and answers skip / rollback / abort per the
  configured policy (`resilience.guard_policy`); the in-jit half of `skip`
  lives in `train_step.guard_nonfinite`.
- **flaky I/O** (checkpoint stores, dataset reads): `retry_call` wraps the
  checkpoint save/restore and dataloader production paths with exponential
  backoff + jitter — the generalization of checkpoint.py's old one-shot
  `_probe_failed` durability probe.
- **hangs** (stuck collective, stalled data producer): `Watchdog` watches
  for step-loop progress, dumps every thread's Python stack on timeout, and
  exits `EXIT_WATCHDOG` non-zero so the supervisor restarts the job.
- **topology changes** (fleet shrink/grow on spot/preemptible pods):
  `elastic` lets a checkpoint saved at dp=N resume into a dp=M mesh —
  topology guard at restore time, constant-global-batch resize planning,
  ZeRO-1 shard regather/re-split, token-exact dataloader cursor carry.
  `tools/elastic_resize.py` is the offline re-stamp CLI.
- **testability**: `chaos` injects each of these failures deterministically
  by step (`PICOTRON_CHAOS` / `resilience.chaos`), so every recovery path
  above runs on CPU in tier-1 instead of being exercised for the first time
  by a real outage. `tools/chaos.py` drives whole-scenario recovery runs.

Exit codes are the contract with the external supervisor (distinct from
Python's generic 1 so a wrapper script can distinguish "resubmit me"
from "a human must look"): 75 preempted-with-durable-state, 76 diverged,
77 watchdog-killed. See README "Fault tolerance" for the recovery matrix.
"""

from picotron_tpu.resilience import chaos, elastic
from picotron_tpu.resilience.guards import (
    EXIT_DIVERGED, DivergenceGuard, GuardAction,
)
from picotron_tpu.resilience.preemption import EXIT_PREEMPTED, PreemptionHandler
from picotron_tpu.resilience.retry import RetryPolicy, backoff_delays, retry_call
from picotron_tpu.resilience.watchdog import EXIT_WATCHDOG, Watchdog

__all__ = [
    "EXIT_DIVERGED",
    "EXIT_PREEMPTED",
    "EXIT_WATCHDOG",
    "DivergenceGuard",
    "GuardAction",
    "PreemptionHandler",
    "RetryPolicy",
    "Watchdog",
    "backoff_delays",
    "chaos",
    "elastic",
    "retry_call",
]
