"""Retry with exponential backoff + jitter for flaky I/O.

The general form of checkpoint.py's old one-shot `_probe_failed` durability
probe: checkpoint save/restore and dataset reads against GCS/NFS fail
transiently in long runs, and a single attempt turns a 2-second blip into a
dead job. `retry_call` retries a bounded number of times with doubling,
jittered delays (jitter decorrelates the processes of a multi-host run so
a shared-store hiccup does not produce a synchronized retry stampede).

`retry_on` defaults to OSError only — programming errors must not be
retried into a 3x-slower crash. Backoff sleeps longer than 1 s are chunked
and heartbeat the active watchdog, so a legitimate retry window is not
misread as a hang.
"""

from __future__ import annotations

import random
import sys
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from picotron_tpu.resilience import watchdog as _watchdog
from picotron_tpu.telemetry import bus as _telemetry


@dataclass(frozen=True)
class RetryPolicy:
    attempts: int = 3        # total tries (1 = no retry)
    base_delay: float = 0.5  # delay before the first retry (seconds)
    max_delay: float = 30.0  # cap on any single delay
    jitter: float = 0.25     # each delay is scaled by 1 + U(0, jitter)

    @classmethod
    def from_config(cls, rcfg) -> "RetryPolicy":
        """Policy from a config ResilienceConfig block."""
        return cls(attempts=rcfg.retry_attempts,
                   base_delay=rcfg.retry_base_delay,
                   max_delay=rcfg.retry_max_delay)


def backoff_delays(policy: RetryPolicy,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Delays before retries 1..attempts-1: min(max, base * 2^i), each
    scaled by 1 + U(0, jitter). Pass a seeded rng for determinism."""
    rand = rng.random if rng is not None else random.random
    for i in range(max(0, policy.attempts - 1)):
        d = min(policy.max_delay, policy.base_delay * (2.0 ** i))
        if policy.jitter > 0:
            d = min(policy.max_delay, d * (1.0 + policy.jitter * rand()))
        yield d


def _heartbeat_sleep(seconds: float) -> None:
    """Sleep in <=1 s chunks, beating the active watchdog between chunks —
    a 30 s checkpoint-retry backoff must not trip a 10 s watchdog."""
    deadline = time.monotonic() + seconds
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        time.sleep(min(1.0, remaining))
        _watchdog.touch("retry-backoff")


def retry_call(fn: Callable, *args,
               policy: RetryPolicy = RetryPolicy(),
               retry_on: tuple = (OSError,),
               describe: str = "",
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None,
               **kwargs):
    """Call fn(*args, **kwargs), retrying `retry_on` exceptions up to
    policy.attempts total tries. Re-raises the last failure. Exceptions
    outside `retry_on` propagate immediately."""
    delays = backoff_delays(policy, rng)
    for attempt in range(1, policy.attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt == policy.attempts:
                raise
            delay = next(delays)
            target = describe or getattr(fn, "__name__", "call")
            print(f"[retry] {target}: "
                  f"attempt {attempt}/{policy.attempts} failed ({e!r}); "
                  f"retrying in {delay:.2f}s", file=sys.stderr, flush=True)
            # The backoff sleep is pure badput — booked to the goodput
            # ledger (category retry_backoff) when telemetry is installed.
            _telemetry.emit("retry", category="retry_backoff", secs=delay,
                            target=target, attempt=attempt,
                            attempts=policy.attempts, error=repr(e))
            if sleep is time.sleep:
                _heartbeat_sleep(delay)
            else:  # injected sleep (tests): hand over the whole delay
                sleep(delay)
