"""Liveness watchdog: a hung step or stalled data producer must kill the
process, not freeze it.

A wedged collective (one host of a pod died mid all-reduce) or a stuck
data producer leaves the step loop blocked forever with zero signal — the
job burns its reservation until a human notices. The watchdog is a daemon
thread that expects the step loop to `beat()` at each phase (data fetch,
step dispatch, metric sync, save); when no beat arrives within the
configured timeout it dumps every thread's Python stack plus the
last-known (phase, step) to stderr and exits `EXIT_WATCHDOG`, so an
external supervisor restarts the job into checkpoint auto_resume.

The driver arms the watchdog only after the first step completes: step 1
includes XLA compilation, whose duration is unbounded and would need an
absurd timeout to cover. Exit uses os._exit — the whole premise is that
the main thread is stuck, so cleanup handlers cannot be trusted to run.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

EXIT_WATCHDOG = 77

# Active instances, so out-of-loop waits (retry backoff sleeps) can
# heartbeat without plumbing a watchdog handle through every layer.
_ACTIVE: list["Watchdog"] = []


def touch(phase: str = "touch", step: Optional[int] = None) -> None:
    """Beat every active watchdog (no-op when none is armed). The MPMD
    schedule walk beats here once per dispatched op with a phase naming
    the live (stage, tick, op), so a mid-schedule stall is reported as
    that op, not a bare stack dump."""
    for w in list(_ACTIVE):
        w.beat(phase, step)


def active() -> bool:
    """True when any watchdog is armed — lets hot loops skip building
    per-op phase strings no one would read."""
    return bool(_ACTIVE)


class Watchdog:
    def __init__(self, timeout: float,
                 on_timeout: Optional[Callable[[], None]] = None,
                 poll: Optional[float] = None,
                 reason: str = "watchdog"):
        self.timeout = timeout
        self.enabled = timeout > 0
        self._on_timeout = on_timeout
        # Flightdeck postmortem reason for a firing — the serve fleet
        # arms with reason="serve_hang" so a hung decode dispatch is
        # distinguishable from a wedged training step in the dump.
        self.reason = reason
        self._poll = poll or max(0.05, min(timeout / 4.0, 1.0)) \
            if self.enabled else 1.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last = (time.monotonic(), "init", None)

    @property
    def started(self) -> bool:
        return self._thread is not None

    def beat(self, phase: str, step: Optional[int] = None) -> None:
        # A single tuple assignment: atomic under the GIL, so beats from
        # other threads (retry heartbeats) need no lock.
        self._last = (time.monotonic(), phase, step)

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self.beat("armed")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="picotron-watchdog")
        _ACTIVE.append(self)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            t, phase, step = self._last
            age = time.monotonic() - t
            if age > self.timeout:
                self._fire(age, phase, step)
                return

    def _fire(self, age: float, phase: str, step) -> None:
        from picotron_tpu.utils import dump_all_stacks

        where = f"phase={phase!r}" + (f" step={step}" if step is not None
                                      else "")
        print(f"[watchdog] no progress for {age:.1f}s "
              f"(timeout {self.timeout:g}s); last {where} — dumping stacks "
              f"and exiting {EXIT_WATCHDOG} for supervisor restart",
              file=sys.stderr, flush=True)
        # The hung phase never completes, so its PhaseTimer booking never
        # happens — this event is the only accounting of the burned time.
        # JSONL flushes per event, so it is durable before the os._exit.
        try:
            from picotron_tpu.telemetry import bus

            bus.emit("watchdog_timeout", secs=age,
                     category=("data_wait" if phase == "data" else "other"),
                     phase=phase, step=step, timeout=self.timeout)
        except Exception:  # noqa: BLE001 — the exit below must still happen
            pass
        # Flight-recorder postmortem: the last-K-steps window, written
        # before the exit below (os._exit runs no cleanup handlers, so
        # this is the only chance).
        try:
            from picotron_tpu.telemetry import bus

            tel = bus.active()
            if tel is not None and getattr(tel, "flight", None) is not None:
                tel.flight.dump(self.reason, step=step, phase=phase,
                                stalled_s=round(age, 3))
        except Exception:  # noqa: BLE001 — the exit below must still happen
            pass
        try:
            dump_all_stacks(sys.stderr)
        except Exception:  # noqa: BLE001 — the exit below must still happen
            pass
        sys.stderr.flush()
        if self._on_timeout is not None:
            self._on_timeout()
        else:
            os._exit(EXIT_WATCHDOG)
