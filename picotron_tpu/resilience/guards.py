"""Divergence guards: decide what to do when a step goes bad.

Two detectors over the per-step metrics:

- **non-finite**: NaN/Inf loss or gradient norm (the jitted step surfaces
  both as metrics when guards are enabled — parallel/api.make_train_step).
- **loss spike**: rolling z-score of the loss against the last
  `spike_window` healthy steps; a z above `spike_zscore` trips (0
  disables). Bad steps are excluded from the window so a NaN cannot
  poison the statistics it is judged against.

The response is the configured `resilience.guard_policy`:

- ``skip`` — drop the batch, keep optimizer state. For non-finite steps
  the update suppression happens *inside* the jitted step
  (train_step.guard_nonfinite: with donated buffers the host cannot
  resurrect the pre-step state), so the guard only reports. A spike under
  ``skip`` can only be quarantined from the window — its update is already
  applied; use ``rollback`` when spikes must not touch the weights.
- ``rollback`` — restore the last known-good checkpoint (durable AND
  manifest-verified: the driver's restore walks past a corrupt newest
  step down the retention chain, see checkpoint.latest_valid_step) and
  skip past the poison data range (the driver repositions the dataloader
  to the cursor *after* the bad batch).
- ``abort`` — exit `EXIT_DIVERGED` and let a human look.

`max_guard_trips` consecutive trips escalate to abort regardless of
policy: a guard that keeps tripping is not recovering, and an unbounded
skip/rollback loop would burn the reservation re-living the same failure.
"""

from __future__ import annotations

import enum
import math
from collections import deque
from typing import Optional

EXIT_DIVERGED = 76


class GuardAction(enum.Enum):
    OK = "ok"
    SKIP = "skip"
    ROLLBACK = "rollback"
    ABORT = "abort"


class DivergenceGuard:
    def __init__(self, policy: str, spike_zscore: float = 0.0,
                 spike_window: int = 32, max_trips: int = 3):
        assert policy in ("skip", "rollback", "abort"), policy
        self.policy = policy
        self.spike_zscore = spike_zscore
        self.max_trips = max_trips
        self._window: deque[float] = deque(maxlen=spike_window)
        self._trips = 0

    @classmethod
    def from_config(cls, rcfg) -> "DivergenceGuard":
        return cls(rcfg.guard_policy, spike_zscore=rcfg.spike_zscore,
                   spike_window=rcfg.spike_window,
                   max_trips=rcfg.max_guard_trips)

    def _spike(self, loss: float) -> Optional[float]:
        """z-score of `loss` against the window when it trips, else None.
        Requires a full window: early-training losses move fast and a
        short window would flag ordinary descent noise."""
        if (self.spike_zscore <= 0
                or len(self._window) < self._window.maxlen):
            return None
        n = len(self._window)
        mean = sum(self._window) / n
        var = sum((x - mean) ** 2 for x in self._window) / (n - 1)
        # Floor the std so a flat window (constant loss) cannot turn an
        # epsilon wiggle into an infinite z.
        std = max(math.sqrt(var), 1e-6 * max(abs(mean), 1.0))
        z = (loss - mean) / std
        return z if z >= self.spike_zscore else None

    def observe(self, step: int, loss: float,
                grad_norm: Optional[float] = None,
                nonfinite: Optional[float] = None
                ) -> tuple[GuardAction, str]:
        """Feed one step's metrics; returns (action, reason). `nonfinite`
        is the jitted step's own verdict (covers per-leaf grad Inf the
        norm could mask by overflowing); loss/grad_norm are re-checked
        host-side so the guard also works with plain metrics."""
        why = None
        if not math.isfinite(loss):
            why = f"non-finite loss ({loss})"
        elif grad_norm is not None and not math.isfinite(grad_norm):
            why = f"non-finite grad norm ({grad_norm})"
        elif nonfinite is not None and nonfinite > 0.5:
            why = "non-finite loss/gradients (in-step detector)"
        else:
            z = self._spike(loss)
            if z is not None:
                why = (f"loss spike (z={z:.1f} >= {self.spike_zscore:g} "
                       f"over {len(self._window)} steps)")
        if why is None:
            self._window.append(loss)
            self._trips = 0
            return GuardAction.OK, ""
        self._trips += 1
        if self._trips >= self.max_trips and self.policy != "abort":
            return GuardAction.ABORT, (
                f"{why}; {self._trips} consecutive guard trips "
                f"(max {self.max_trips}) — policy {self.policy!r} is not "
                f"recovering")
        if self.policy == "abort":
            return GuardAction.ABORT, why
        if self.policy == "skip":
            return GuardAction.SKIP, why
        return GuardAction.ROLLBACK, why
