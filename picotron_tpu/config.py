"""Typed configuration for picotron-tpu.

One explicit config object threaded through the whole program — this replaces
both of the reference's config channels: the JSON file
(ref: template/base_config.json:1-52) and the shadow environment-variable
channel (`FLASH_ATTEN` / `CONTEXT_PARALLEL` / `DEVICE` / `DTYPE`, ref:
train.py:65-77, model.py:127-158, context_parallel.py:10-12), which SURVEY.md
§5 flags as a design wart.

The JSON schema is compatible with the reference's: a reference config.json
loads unchanged (unknown keys are ignored; the `environment` section is
irrelevant on TPU). Model hyperparameters resolve from a built-in preset
registry instead of a network `AutoConfig` fetch (ref: create_config.py:51-55)
— TPU pods frequently run with zero egress, so presets are first-class and
explicit overrides always win.
"""

from __future__ import annotations

import dataclasses
import json
import math
from dataclasses import dataclass, field
from typing import Any, Optional


# ---------------------------------------------------------------------------
# Model preset registry (replaces network AutoConfig lookup).
# Hyperparameters are the public ones for each model family.
# ---------------------------------------------------------------------------

MODEL_PRESETS: dict[str, dict[str, Any]] = {
    # SmolLM family (Llama architecture)
    "HuggingFaceTB/SmolLM-135M": dict(
        vocab_size=49152, hidden_size=576, intermediate_size=1536,
        num_hidden_layers=30, num_attention_heads=9, num_key_value_heads=3,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    "HuggingFaceTB/SmolLM-360M": dict(
        vocab_size=49152, hidden_size=960, intermediate_size=2560,
        num_hidden_layers=32, num_attention_heads=15, num_key_value_heads=5,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    "HuggingFaceTB/SmolLM-1.7B": dict(
        vocab_size=49152, hidden_size=2048, intermediate_size=8192,
        num_hidden_layers=24, num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    # Llama-2
    "meta-llama/Llama-2-7b-hf": dict(
        vocab_size=32000, hidden_size=4096, intermediate_size=11008,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=32,
        max_position_embeddings=4096, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    "meta-llama/Llama-2-13b-hf": dict(
        vocab_size=32000, hidden_size=5120, intermediate_size=13824,
        num_hidden_layers=40, num_attention_heads=40, num_key_value_heads=40,
        max_position_embeddings=4096, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    "meta-llama/Llama-2-70b-hf": dict(
        vocab_size=32000, hidden_size=8192, intermediate_size=28672,
        num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8,
        max_position_embeddings=4096, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    # Llama-3
    "meta-llama/Meta-Llama-3-8B": dict(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=8192, rope_theta=500000.0, rms_norm_eps=1e-5,
    ),
    # Llama-3.1/3.2 (llama3-type RoPE scaling for 128k context)
    "meta-llama/Llama-3.1-8B": dict(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=131072, rope_theta=500000.0,
        rms_norm_eps=1e-5,
        rope_scaling=dict(rope_type="llama3", factor=8.0,
                          low_freq_factor=1.0, high_freq_factor=4.0,
                          original_max_position_embeddings=8192),
    ),
    "meta-llama/Llama-3.1-70B": dict(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8,
        max_position_embeddings=131072, rope_theta=500000.0,
        rms_norm_eps=1e-5,
        rope_scaling=dict(rope_type="llama3", factor=8.0,
                          low_freq_factor=1.0, high_freq_factor=4.0,
                          original_max_position_embeddings=8192),
    ),
    "meta-llama/Llama-3.2-1B": dict(
        vocab_size=128256, hidden_size=2048, intermediate_size=8192,
        num_hidden_layers=16, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=131072, rope_theta=500000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=True,
        rope_scaling=dict(rope_type="llama3", factor=32.0,
                          low_freq_factor=1.0, high_freq_factor=4.0,
                          original_max_position_embeddings=8192),
    ),
    "meta-llama/Llama-3.2-3B": dict(
        vocab_size=128256, hidden_size=3072, intermediate_size=8192,
        num_hidden_layers=28, num_attention_heads=24, num_key_value_heads=8,
        max_position_embeddings=131072, rope_theta=500000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=True,
        rope_scaling=dict(rope_type="llama3", factor=32.0,
                          low_freq_factor=1.0, high_freq_factor=4.0,
                          original_max_position_embeddings=8192),
    ),
    # TinyLlama
    "TinyLlama/TinyLlama-1.1B-Chat-v1.0": dict(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=22, num_attention_heads=32, num_key_value_heads=4,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    # Qwen2 family (Llama-like + attention qkv bias; small ones tie the
    # LM head to the embedding)
    "Qwen/Qwen2-0.5B": dict(
        vocab_size=151936, hidden_size=896, intermediate_size=4864,
        num_hidden_layers=24, num_attention_heads=14, num_key_value_heads=2,
        max_position_embeddings=32768, rope_theta=1e6, rms_norm_eps=1e-6,
        attention_bias=True, tie_word_embeddings=True,
    ),
    "Qwen/Qwen2-1.5B": dict(
        vocab_size=151936, hidden_size=1536, intermediate_size=8960,
        num_hidden_layers=28, num_attention_heads=12, num_key_value_heads=2,
        max_position_embeddings=32768, rope_theta=1e6, rms_norm_eps=1e-6,
        attention_bias=True, tie_word_embeddings=True,
    ),
    "Qwen/Qwen2-7B": dict(
        vocab_size=152064, hidden_size=3584, intermediate_size=18944,
        num_hidden_layers=28, num_attention_heads=28, num_key_value_heads=4,
        max_position_embeddings=32768, rope_theta=1e6, rms_norm_eps=1e-6,
        attention_bias=True,
    ),
    # Mixtral (MoE family; beyond the reference's dense-only coverage)
    "mistralai/Mixtral-8x7B-v0.1": dict(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=32768, rope_theta=1e6, rms_norm_eps=1e-5,
        num_experts=8, num_experts_per_token=2,
    ),
    "mistralai/Mixtral-8x22B-v0.1": dict(
        vocab_size=32768, hidden_size=6144, intermediate_size=16384,
        num_hidden_layers=56, num_attention_heads=48, num_key_value_heads=8,
        max_position_embeddings=65536, rope_theta=1e6, rms_norm_eps=1e-5,
        num_experts=8, num_experts_per_token=2,
    ),
    # Tiny debug model for tests / CI
    "picotron-tpu/debug-tiny": dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
    ),
    # Tiny Qwen2-style debug model (qkv bias + tied embeddings)
    "picotron-tpu/debug-tiny-qwen": dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
        attention_bias=True, tie_word_embeddings=True,
    ),
    # Tiny MoE debug model (8 experts, top-2)
    "picotron-tpu/debug-tiny-moe": dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=2048, rope_theta=10000.0, rms_norm_eps=1e-5,
        num_experts=8, num_experts_per_token=2,
    ),
}

# Aliases so shorthand names in configs resolve too.
_PRESET_ALIASES = {
    "SmolLM-135M": "HuggingFaceTB/SmolLM-135M",
    "SmolLM-360M": "HuggingFaceTB/SmolLM-360M",
    "HuggingFaceTB/SmolLM-360M-Instruct": "HuggingFaceTB/SmolLM-360M",
    "SmolLM-1.7B": "HuggingFaceTB/SmolLM-1.7B",
    "HuggingFaceTB/SmolLM-1.7B-Instruct": "HuggingFaceTB/SmolLM-1.7B",
    "Llama-2-7B": "meta-llama/Llama-2-7b-hf",
    "Llama-2-13B": "meta-llama/Llama-2-13b-hf",
    "Llama-2-70B": "meta-llama/Llama-2-70b-hf",
    "Llama-3-8B": "meta-llama/Meta-Llama-3-8B",
    "Llama-3.1-8B": "meta-llama/Llama-3.1-8B",
    "Llama-3.1-70B": "meta-llama/Llama-3.1-70B",
    "Mixtral-8x22B": "mistralai/Mixtral-8x22B-v0.1",
    "Llama-3.2-1B": "meta-llama/Llama-3.2-1B",
    "Llama-3.2-3B": "meta-llama/Llama-3.2-3B",
    "TinyLlama-1.1B": "TinyLlama/TinyLlama-1.1B-Chat-v1.0",
    "Mixtral-8x7B": "mistralai/Mixtral-8x7B-v0.1",
    "Qwen2-0.5B": "Qwen/Qwen2-0.5B",
    "Qwen2-1.5B": "Qwen/Qwen2-1.5B",
    "Qwen2-7B": "Qwen/Qwen2-7B",
    "debug-tiny": "picotron-tpu/debug-tiny",
    "debug-tiny-qwen": "picotron-tpu/debug-tiny-qwen",
    "debug-tiny-moe": "picotron-tpu/debug-tiny-moe",
}


def resolve_preset(name: str) -> dict[str, Any]:
    key = _PRESET_ALIASES.get(name, name)
    if key in MODEL_PRESETS:
        return dict(MODEL_PRESETS[key])
    raise KeyError(
        f"Unknown model preset {name!r}. Known presets: "
        f"{sorted(MODEL_PRESETS) + sorted(_PRESET_ALIASES)}. "
        "Pass explicit hyperparameters in the `model` config section instead."
    )


def resolve_hf_name(name: str) -> str:
    """Canonical HF hub id for a preset shorthand ('SmolLM-1.7B' ->
    'HuggingFaceTB/SmolLM-1.7B'); unknown names pass through unchanged."""
    return _PRESET_ALIASES.get(name, name)


def model_config_from_hf_json(path_or_dict) -> dict[str, Any]:
    """ModelConfig kwargs from a local HF `config.json` — the OFFLINE
    equivalent of the reference's network AutoConfig fetch
    (ref: create_config.py:51-55): any Llama/Qwen2/Mixtral-family model
    outside the preset registry resolves from its config file instead of
    hand-typed hyperparameters. Pass a path or an already-parsed dict."""
    if isinstance(path_or_dict, dict):
        hf = path_or_dict
    else:
        with open(path_or_dict) as f:
            hf = json.load(f)

    mtype = hf.get("model_type", "llama")
    supported = ("llama", "mistral", "mixtral", "qwen2")
    if mtype not in supported:
        raise ValueError(
            f"model_type {mtype!r} is not a supported architecture family "
            f"({supported}); the model layer (models/llama.py) implements "
            "the Llama lineage")

    heads = hf["num_attention_heads"]
    out: dict[str, Any] = {
        "vocab_size": hf["vocab_size"],
        "hidden_size": hf["hidden_size"],
        "intermediate_size": hf["intermediate_size"],
        "num_hidden_layers": hf["num_hidden_layers"],
        "num_attention_heads": heads,
        "num_key_value_heads": hf.get("num_key_value_heads", heads),
        "max_position_embeddings": hf.get("max_position_embeddings", 2048),
        "rope_theta": float(hf.get("rope_theta", 10000.0)),
        "rms_norm_eps": float(hf.get("rms_norm_eps", 1e-5)),
        "tie_word_embeddings": bool(hf.get("tie_word_embeddings", False)),
        # Qwen2 carries qkv bias as attention_bias=absent + model_type;
        # Llama exposes the flag directly
        "attention_bias": bool(hf.get("attention_bias",
                                      mtype == "qwen2")),
    }
    act = hf.get("hidden_act", "silu")
    if act in ("silu", "swish"):
        out["hidden_act"] = "silu"
    elif act == "gelu":
        # transformers' ACT2FN "gelu" is the EXACT erf GELU — mapping it
        # to the tanh approximation would silently drift logits vs the HF
        # reference (code review r4)
        out["hidden_act"] = "gelu"
    elif act in ("gelu_new", "gelu_pytorch_tanh"):
        out["hidden_act"] = "gelu_tanh"
    else:
        raise ValueError(
            f"hidden_act {act!r} unsupported (silu/gelu gated MLPs only)")
    if hf.get("rope_scaling"):
        out["rope_scaling"] = dict(hf["rope_scaling"])
    if hf.get("num_local_experts"):  # Mixtral-style MoE
        out["num_experts"] = hf["num_local_experts"]
        out["num_experts_per_token"] = hf.get("num_experts_per_tok", 2)
    return out


# ---------------------------------------------------------------------------
# Config sections — mirror the reference JSON sections one-to-one.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DistributedConfig:
    """4D parallel layout (ref: template/base_config.json:2-10)."""

    tp_size: int = 1
    cp_size: int = 1
    pp_size: int = 1
    dp_size: int = 1
    pp_engine: str = "1f1b"  # "1f1b" | "afab"
    # Sequence layout across cp shards: "zigzag" gives each shard one early
    # and one late chunk so causal attention work is balanced around the ring
    # (the reference splits contiguously and carries the known imbalance +
    # a zigzag TODO, ref: data.py:105-109, tests/test_dataloader.py:136).
    # "contiguous" reproduces the reference layout.
    cp_layout: str = "zigzag"
    # Context-parallel attention schedule: "" derives the flavor from
    # model.attn_impl (back-compat: "ring"/"ulysses"/"mesh" there select
    # directly, anything else defaults to the ring). "mesh" is the 2D
    # schedule (ops/mesh_attention.py): cp factors into cp_x x cp_y, an
    # Ulysses-style head scatter runs within cp_y subgroups and a K/V
    # ring over the cp_x rows — same per-hop volume as the ring but only
    # cp_x-1 hops, head divisibility required only by cp_y.
    cp_flavor: str = ""  # "" | "ring" | "ulysses" | "mesh"
    # Mesh-flavor factorization "XxY" (e.g. "2x4": cp_x=2 ring rows,
    # cp_y=4 head-scatter columns); X*Y must equal cp_size. "" picks the
    # most-square feasible factorization (resolved_cp_mesh); the planner
    # enumerates all feasible ones against the ICI cost model.
    cp_mesh: str = ""
    # Expert parallelism: shards MoE expert banks over a dedicated mesh
    # axis; acts as an additional data axis for non-expert computation
    # (batch over the fused ('dp','ep') axes). Requires a MoE model
    # (model.num_experts > 0) when > 1.
    ep_size: int = 1
    # Megatron-style sequence parallelism over the tp axis (the reference
    # leaves this as a TODO, ref: utils.py:66): between blocks the residual
    # stream / norms are sharded [*, S/tp, H] and the TP entry/exit
    # collectives become all_gather / reduce_scatter (same bytes as the
    # psum they replace; tp x less pipeline boundary traffic). Memory: the
    # tp x shrink applies only to the norm/residual tensors BETWEEN g and
    # f — measured ~5% of total activation memory with remat off and ~0
    # under the dots remat policies, whose saved dot outputs sit after the
    # gather and stay full-sequence (tools/memcheck.py --override, PERF.md
    # round 4). Use it for the boundary traffic, not as a memory lever.
    sequence_parallel: bool = False
    # ZeRO-1 optimizer-state sharding (beyond the reference): shards the
    # Adam moments over 'dp' in addition to their param's tp/pp/ep
    # sharding. GSPMD turns the sharding annotation into the per-shard
    # update + all-gather schedule, cutting resident moment memory by
    # ~dp_size — measured on SmolLM-1.7B dp8: 13.5 -> 1.69 GiB/device of
    # moments, 20.25 -> 8.44 GiB/device total state (tools/memcheck.py
    # --override distributed.zero1=true; PERF.md round 4).
    zero1: bool = False
    # Per-layer-class TP partitioning (ATP, arxiv 2301.08658). Presets:
    # "megatron" — the fixed column/row pattern (qkv/up column-parallel,
    # o/down row-parallel + exit psum; today's default); "row" — row-first
    # (qkv/up input-sharded with a psum at the projection exit, o/down
    # column-parallel with a feature all-gather exit; attention runs
    # tp-replicated); "2d" — tp = tp_x x tp_y factorization (subgroup
    # collectives over tp_mesh; see parallel/tp_strategies.py); "adaptive"
    # — per-layer-class cost-model argmin (resolved_tp_strategy). An
    # explicit per-class spec is also accepted:
    # "qkv=col,o=row,up=col,down=row,head=col" (pairings must be legal —
    # parse_tp_strategy).
    tp_strategy: str = "megatron"
    # Activation-sync mode for the TP block exit: "sync" keeps the
    # row-parallel psum (or the SP reduce-scatter) on the critical path;
    # "deferred" replaces it with a reduce-scatter over the sequence whose
    # gather half is hoisted into the NEXT block's entry (ParallelCtx.pre),
    # so the residual stream stays seq-sharded between blocks and XLA can
    # hide the gather behind the block-entry compute (partially-
    # synchronized-activation TP, arxiv 2506.19645). Numerics are exact
    # (RMSNorm is per-token); parity is pinned against the sync path.
    tp_sync: str = "sync"
    # 2D strategy factorization "XxY" (tp_x x tp_y, tp_x * tp_y == tp_size);
    # "" picks the most-square feasible factorization (resolved_tp_mesh).
    tp_mesh: str = ""
    # Multi-slice topology: number of TPU slices joined over DCN (the
    # data-center network). 1 = single slice, everything on ICI. When > 1
    # the slice granules are absorbed into the DCN-tolerant axes (dp first,
    # then pp — mesh._split_axes_over_dcn) and the static slice-boundary
    # auditor (analysis/boundary.py) proves at preflight that only
    # collectives over the declared dcn_axes cross the cut.
    slices: int = 1
    # Which mesh axes are DECLARED as allowed to cross the inter-slice DCN
    # link, comma-separated subset of "dp,pp". The auditor classifies every
    # traced replica group against this declaration: groups on a declared
    # axis that straddle the cut are "boundary" (expected, priced at the
    # dcn tier); groups on any other axis that straddle it are
    # "violating" (a named preflight error).
    dcn_axes: str = "dp,pp"
    # Runtime hierarchical dp gradient reduction across the slice cut:
    # reduce-scatter inside the slice + a shard-per-slice all-reduce over
    # DCN + intra-slice all-gather (parallel/hier_reduce.py), replacing the
    # flat dp all-reduce the single-slice step emits. "auto" turns it on
    # exactly when slices > 1 and dp physically carries a slice granule;
    # "on" requires such a layout (refuses to silently no-op); "off" keeps
    # the flat all-reduce (the A/B twin the parity tests pin against).
    hier_dp_reduce: str = "auto"
    # Accepted for reference-JSON compatibility; ignored (XLA picks transport).
    backend: str = "jax"
    use_cpu: bool = False

    @property
    def world_size(self) -> int:
        return (self.tp_size * self.cp_size * self.pp_size * self.dp_size
                * self.ep_size)

    def validate(self) -> None:
        for name in ("tp_size", "cp_size", "pp_size", "dp_size", "ep_size"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got {getattr(self, name)}")
        if self.pp_engine not in ("1f1b", "afab"):
            raise ValueError(f"pp_engine must be '1f1b' or 'afab', got {self.pp_engine!r}")
        if self.cp_layout not in ("zigzag", "contiguous"):
            raise ValueError(
                f"cp_layout must be 'zigzag' or 'contiguous', got {self.cp_layout!r}")
        if self.cp_flavor not in ("", "ring", "ulysses", "mesh"):
            raise ValueError(
                f"cp_flavor must be one of ring/ulysses/mesh (or empty to "
                f"derive from model.attn_impl), got {self.cp_flavor!r}")
        if self.cp_flavor and self.cp_size == 1:
            raise ValueError(
                f"cp_flavor={self.cp_flavor!r} requires cp_size > 1 (it "
                "names a context-parallel schedule)")
        if self.cp_mesh:
            cp_x, cp_y = parse_cp_mesh(self.cp_mesh)
            if cp_x * cp_y != self.cp_size:
                raise ValueError(
                    f"cp_mesh '{self.cp_mesh}' must factor the cp degree: "
                    f"{cp_x} * {cp_y} != cp_size ({self.cp_size})")
        parse_tp_strategy(self.tp_strategy)  # raises with the field named
        if self.tp_strategy != "megatron" and self.tp_size == 1:
            raise ValueError(
                f"tp_strategy={self.tp_strategy!r} requires tp_size > 1 "
                "(it names a tensor-parallel partitioning)")
        if self.tp_sync not in ("sync", "deferred"):
            raise ValueError(
                f"tp_sync must be 'sync' or 'deferred', got {self.tp_sync!r}")
        if self.tp_sync == "deferred" and self.tp_size == 1:
            raise ValueError(
                "tp_sync='deferred' requires tp_size > 1 (it reschedules "
                "the TP block-exit collective)")
        if self.tp_mesh:
            tp_x, tp_y = parse_cp_mesh(self.tp_mesh)
            if tp_x * tp_y != self.tp_size:
                raise ValueError(
                    f"tp_mesh '{self.tp_mesh}' must factor the tp degree: "
                    f"{tp_x} * {tp_y} != tp_size ({self.tp_size})")
        if self.slices < 1:
            raise ValueError(f"slices must be >= 1, got {self.slices}")
        axes = parse_dcn_axes(self.dcn_axes)
        if self.slices > 1:
            # Mirror mesh._split_axes_over_dcn: the slice count must divide
            # dp*pp so ep/cp/tp collectives stay on ICI. The declaration may
            # be NARROWER than the house rule (that is how a mis-declared
            # layout is caught by the boundary auditor), but it cannot name
            # an ICI-only axis.
            if self.slices > self.dp_size * self.pp_size or (
                    self.dp_size * self.pp_size) % self.slices != 0:
                raise ValueError(
                    f"slices ({self.slices}) must divide dp*pp "
                    f"({self.dp_size}*{self.pp_size}="
                    f"{self.dp_size * self.pp_size}) — ep/cp/tp collectives "
                    "must stay on ICI. Rebalance the layout so dp*pp "
                    "absorbs the slice count.")
            if not axes:
                raise ValueError(
                    "dcn_axes must declare at least one crossing axis "
                    "when slices > 1 (subset of 'dp,pp')")
        if self.hier_dp_reduce not in ("auto", "on", "off"):
            raise ValueError(
                f"hier_dp_reduce must be 'auto', 'on' or 'off', got "
                f"{self.hier_dp_reduce!r}")
        if self.hier_dp_reduce == "on":
            import math

            if (self.slices <= 1 or "dp" not in axes
                    or math.gcd(self.dp_size, self.slices) <= 1):
                raise ValueError(
                    "hier_dp_reduce='on' requires a multi-slice layout "
                    "whose dp axis physically carries a slice granule "
                    "(slices > 1, 'dp' in dcn_axes, gcd(dp_size, slices) "
                    "> 1); use 'auto' to enable it only when the layout "
                    "calls for it")


DCN_TOLERANT_AXES = ("dp", "pp")


def parse_dcn_axes(spec: str) -> tuple[str, ...]:
    """Parse a dcn_axes declaration into an ordered (dp-first) axis tuple.

    Accepts a comma-separated subset of the DCN-tolerant axes ("dp", "pp");
    the empty string means no axis is declared (only legal at slices == 1).
    """
    axes = tuple(a.strip() for a in spec.split(",") if a.strip())
    bad = [a for a in axes if a not in DCN_TOLERANT_AXES]
    if bad:
        raise ValueError(
            f"dcn_axes may only name the DCN-tolerant axes "
            f"{DCN_TOLERANT_AXES} (got {bad} in {spec!r}) — ep/cp/tp "
            "collectives must stay on ICI")
    if len(set(axes)) != len(axes):
        raise ValueError(f"dcn_axes has duplicate axes: {spec!r}")
    return tuple(a for a in DCN_TOLERANT_AXES if a in axes)


def _parse_mesh2(spec: str, field: str) -> tuple[int, int]:
    parts = spec.lower().split("x")
    try:
        m_x, m_y = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise ValueError(
            f"{field} must be 'XxY' (two positive integers, e.g. '2x4'), "
            f"got {spec!r}") from None
    if m_x < 1 or m_y < 1:
        raise ValueError(f"{field} factors must be >= 1, got {spec!r}")
    return m_x, m_y


def parse_cp_mesh(spec: str) -> tuple[int, int]:
    """'XxY' -> (cp_x, cp_y), with a field-naming error (not a bare int
    crash) on malformed input."""
    return _parse_mesh2(spec, "cp_mesh")


def parse_tp_mesh(spec: str) -> tuple[int, int]:
    """'XxY' -> (tp_x, tp_y) for the 2D TP strategy factorization."""
    return _parse_mesh2(spec, "tp_mesh")


# Layer classes a TP strategy assigns a partitioning to, and the legal
# (entry, exit) pairings: the qkv/o and up/down pairs bracket a block, so
# the entry's output layout must be what the exit consumes.
TP_STRATEGY_CLASSES = ("qkv", "o", "up", "down", "head")
_TP_STRATEGY_PRESETS = {
    "megatron": {"qkv": "col", "o": "row", "up": "col", "down": "row",
                 "head": "col"},
    "row": {"qkv": "row", "o": "col", "up": "row", "down": "col",
            "head": "col"},
    "2d": {"qkv": "2d", "o": "2d", "up": "2d", "down": "2d", "head": "col"},
}
_TP_LEGAL_PAIRS = {("col", "row"), ("row", "col"), ("2d", "2d")}


def parse_tp_strategy(spec: str):
    """Parse distributed.tp_strategy into a per-class dict
    {qkv,o,up,down,head} -> {col,row,2d}, or None for "adaptive" (whose
    resolution needs the cost model — resolved_tp_strategy). Presets
    megatron/row/2d expand to full dicts; an explicit "k=v,..." spec may
    name a subset of classes (the rest default to megatron) but must keep
    the (qkv,o) and (up,down) pairings legal and head column-parallel."""
    if spec == "adaptive":
        return None
    if spec in _TP_STRATEGY_PRESETS:
        return dict(_TP_STRATEGY_PRESETS[spec])
    out = dict(_TP_STRATEGY_PRESETS["megatron"])
    for item in spec.split(","):
        if "=" not in item:
            raise ValueError(
                f"tp_strategy must be a preset (megatron/row/2d/adaptive) "
                f"or a 'class=value,...' spec over "
                f"{'/'.join(TP_STRATEGY_CLASSES)}, got {spec!r}")
        k, _, v = item.partition("=")
        k, v = k.strip(), v.strip()
        if k not in TP_STRATEGY_CLASSES:
            raise ValueError(
                f"tp_strategy names unknown layer class {k!r} (classes: "
                f"{', '.join(TP_STRATEGY_CLASSES)})")
        if v not in ("col", "row", "2d"):
            raise ValueError(
                f"tp_strategy value for {k!r} must be col/row/2d, got {v!r}")
        out[k] = v
    if out["head"] != "col":
        raise ValueError(
            "tp_strategy head must be 'col' (the vocab-parallel head/CE is "
            "the only supported head partitioning; row is priced by the "
            "cost model but has no runtime path)")
    for entry, exit_ in (("qkv", "o"), ("up", "down")):
        if (out[entry], out[exit_]) not in _TP_LEGAL_PAIRS:
            raise ValueError(
                f"tp_strategy pairing {entry}={out[entry]}/{exit_}="
                f"{out[exit_]} is not a legal (entry, exit) pair — the "
                f"entry's output layout must feed the exit (legal: "
                f"col/row, row/col, 2d/2d)")
    return out


@dataclass(frozen=True)
class ModelConfig:
    """Llama-family architecture hyperparameters.

    Resolved from a preset by name with explicit overrides on top
    (ref: create_config.py:51-63 does the same via AutoConfig + overrides).
    """

    name: str = "picotron-tpu/debug-tiny"
    vocab_size: int = 256
    hidden_size: int = 64
    intermediate_size: int = 128
    num_hidden_layers: int = 4
    num_attention_heads: int = 4
    num_key_value_heads: int = 2
    max_position_embeddings: int = 2048
    rope_theta: float = 10000.0
    # HF-style rope_scaling config (Llama-3.1/3.2's {"rope_type": "llama3",
    # "factor": 8.0, ...} or {"rope_type": "linear", "factor": N}); None =
    # unscaled. Stored internally as a sorted (key, value) tuple so the
    # frozen config stays hashable (generation jits with the config as a
    # static argument); pass a plain dict, __post_init__ normalizes.
    rope_scaling: Optional[Any] = None
    rms_norm_eps: float = 1e-5
    # Qwen2-style architecture variants: bias on the q/k/v projections, and
    # an LM head tied to the embedding matrix (logits = h @ embedding.T; no
    # separate lm_head parameter — the Llama family unties, ref:
    # checkpoint.py:88-91 force-creates lm_head).
    attention_bias: bool = False
    tie_word_embeddings: bool = False
    # Gated-MLP activation: "silu" (Llama/Qwen/Mixtral SwiGLU), "gelu"
    # (EXACT erf GELU — transformers' "gelu"), or "gelu_tanh" (the tanh
    # approximation — transformers' "gelu_pytorch_tanh"/"gelu_new",
    # the Gemma-style GeGLU) — widens the --from-hf-config long tail
    # beyond pure-SwiGLU families.
    hidden_act: str = "silu"
    dtype: str = "bfloat16"  # compute/activation dtype; master params are fp32
    # Attention implementation: "auto" picks flash on TPU / reference on CPU;
    # CP > 1 always routes through the ring (ref: model.py:148-158 dispatch).
    attn_impl: str = "auto"  # "auto" | "flash" | "reference" | "ring"
    # Mixture-of-experts (beyond the reference, SURVEY §2.2 marks EP absent):
    # num_experts = 0 keeps the dense SwiGLU MLP; > 0 replaces every MLP with
    # a top-k-routed expert bank (Mixtral-style: softmax over the top-k
    # router logits) plus a load-balancing aux loss. Experts shard over the
    # 'ep' mesh axis; dispatch is capacity-bounded (GShard-style) so shapes
    # stay static for XLA.
    num_experts: int = 0
    num_experts_per_token: int = 2
    moe_intermediate_size: Optional[int] = None  # default: intermediate_size
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # Router z-loss coefficient (ST-MoE eq. 5; 1e-3 there). 0 disables.
    router_z_coef: float = 0.0
    # Compute router aux statistics (balance f/P, z-loss mean) over the
    # GLOBAL batch via pmean over the data axes — layout-exact losses
    # (identical for any dp/cp/ep factorization). False = per-device
    # statistics (cheaper by two [E]-sized pmeans per layer, differs across
    # layouts by O(shard variance)).
    router_aux_global: bool = True
    # Accepted for reference compat (ref uses them to pick CUDA kernels).
    use_flash_attention: bool = True
    use_fused_adam: bool = True

    def __post_init__(self):
        rs = self.rope_scaling
        if isinstance(rs, dict):
            rs = tuple(sorted(rs.items()))
        elif isinstance(rs, (list, tuple)) and rs:
            # JSON round-trip (to_json_dict -> config_from_dict) turns the
            # tuple of pairs into nested lists — re-normalize so the frozen
            # config stays hashable
            rs = tuple(sorted(tuple(pair) for pair in rs))
        object.__setattr__(self, "rope_scaling", rs or None)

    @property
    def rope_scaling_dict(self) -> Optional[dict]:
        return dict(self.rope_scaling) if self.rope_scaling else None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def expert_ffn_size(self) -> int:
        return self.moe_intermediate_size or self.intermediate_size

    def validate(self) -> None:
        if self.attn_impl not in ("auto", "flash", "reference", "ring",
                                  "ulysses", "mesh"):
            raise ValueError(
                f"attn_impl must be one of auto/flash/reference/ring/"
                f"ulysses/mesh, got {self.attn_impl!r}"
            )
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError("hidden_size must be divisible by num_attention_heads")
        if self.num_attention_heads % self.num_key_value_heads != 0:
            raise ValueError("num_attention_heads must be divisible by num_key_value_heads")
        if self.head_dim % 2 != 0:
            raise ValueError("head_dim must be even for RoPE")
        if self.hidden_act not in ("silu", "gelu", "gelu_tanh"):
            raise ValueError(
                f"hidden_act must be 'silu', 'gelu', or 'gelu_tanh', got "
                f"{self.hidden_act!r}")


@dataclass(frozen=True)
class TrainingConfig:
    """(ref: template/base_config.json:20-29)."""

    seed: int = 42
    learning_rate: float = 3e-4
    # LR schedule: "constant" (the reference's behavior, ref: train.py:209
    # builds a bare AdamW), "cosine" (linear warmup -> cosine decay to
    # lr * lr_min_ratio over total_train_steps), or "linear" (warmup ->
    # linear decay). Warmup counts from step 0 even on resume — the
    # schedule reads the restored optimizer step count.
    lr_schedule: str = "constant"
    lr_warmup_steps: int = 0
    lr_min_ratio: float = 0.1
    weight_decay: float = 0.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    # "float32" | "bfloat16": bf16 halves Adam-moment memory (update math
    # stays fp32) — the knob that fits SmolLM-1.7B's optimizer on one v5e.
    adam_moments_dtype: str = "float32"
    # ZeRO-Offload-style optimizer-state offload (beyond the reference,
    # whose CUDA path keeps everything in GPU memory): the fp32 master
    # params and both Adam moments live permanently in pinned HOST memory;
    # the device keeps only a bf16 compute copy of the params plus the fp32
    # gradient accumulator. The update streams leaf-by-leaf through the
    # device (host->device DMA, fused AdamW, device->host write-back), so
    # per-step PCIe traffic is params+moments each way — amortize it with
    # gradient_accumulation_steps >= ~16. This is the lever that fits
    # full-depth SmolLM-1.7B (fp32 master + grads + moments ~21 GB) on one
    # 15.75 GB v5e chip with NO numerics compromise: the master update math
    # is identical to the on-device path; only per-microbatch grads are
    # bf16 (they accumulate in fp32, the standard mixed-precision
    # arrangement).
    optimizer_offload: bool = False
    grad_clip_norm: float = 0.0  # 0 disables clipping
    total_train_steps: int = 200
    seq_length: int = 1024
    micro_batch_size: int = 1
    gradient_accumulation_steps: int = 1
    num_samples: Optional[int] = None
    max_tokens: Optional[int] = None
    # Periodic validation: every eval_frequency training steps, run
    # eval_steps batches from the eval source (dataset.eval_split for HF
    # datasets; a disjoint-seed synthetic stream otherwise) and log
    # val_loss. 0 disables (the reference has no eval loop).
    eval_frequency: int = 0
    eval_steps: int = 8
    # Stream the LM-head cross-entropy over vocab chunks of this many
    # columns: the [tokens, vocab] logits never materialize (neither as a
    # forward tensor nor a saved backward residual — chunks recompute),
    # trading one extra chunk matmul in backward for ~tokens*vocab*2 bytes
    # of peak HBM. 0 disables (fused single-matmul CE). Must divide
    # vocab_size / tp_size or it silently falls back to fused.
    ce_chunk_size: int = 0
    # Gradient rematerialization for long-context / big-model memory savings.
    remat: bool = True
    # "full" recomputes everything in backward (max memory savings);
    # "dots" saves matmul outputs and recomputes only elementwise ops —
    # usually within a few % of no-remat speed at a fraction of the memory;
    # "dots_attn" saves only the attention-side dots and recomputes the MLP
    # (~2.6x less activation HBM than "dots" for ~+7% step FLOPs — the
    # memory/speed midpoint that pairs with optimizer_offload);
    # "dots_norms" additionally saves RMSNorm outputs (~2 activations/layer
    # more HBM, less backward recompute).
    remat_policy: str = "dots"
    # Gradient engine for the non-pipeline microbatch loop: "ad"
    # differentiates each microbatch and tree-adds into the fp32
    # accumulator (one whole-tree temp write + one whole-tree add per
    # microbatch — measured 26 ms of serialized roofline HBM traffic per
    # microbatch at SmolLM-1.7B, PERF.md r5); "fused" runs the manual
    # backward layer scan (parallel/fused_bwd.py) that accumulates each
    # layer's dW in-scan, eliminating both passes. "auto" picks "fused"
    # whenever it is supported (any single-pipeline-stage layout —
    # dp/tp/SP/cp ring|ulysses/ep/MoE — under remat dots_attn; see the
    # README eligibility matrix) and gradient accumulation is in play.
    # Numerics match the AD engine (pinned by tests/test_fused_bwd.py).
    grad_engine: str = "auto"


@dataclass(frozen=True)
class DatasetConfig:
    """(ref: template/base_config.json:30-35). `synthetic` replaces network
    datasets in tests/benchmarks (deterministic PRNG token stream)."""

    name: str = "synthetic"
    subset_name: Optional[str] = None
    tokenizer_name: Optional[str] = None
    num_workers: int = 0
    num_proc: int = 1
    split: str = "train"
    # HF split for the validation loader (training.eval_frequency > 0);
    # None with a synthetic source uses a disjoint seed stream.
    eval_split: Optional[str] = None
    text_column: str = "text"


@dataclass(frozen=True)
class CheckpointConfig:
    """(ref: template/base_config.json:36-40)."""

    save_dir: str = "ckpt"
    save_frequency: int = 0  # 0 disables periodic saving
    # Async Orbax save (SURVEY §5): save() stages device->host copies and
    # returns; the disk write overlaps subsequent training steps. The
    # trainer waits for durability at exit. False = blocking saves (the
    # reference's behavior, ref: checkpoint.py:246-260).
    async_save: bool = True
    load_path: str = ""
    # Resume from the newest durable checkpoint in save_dir when no
    # load_path is given (no-op when save_dir holds none). This is the
    # in-process half of preemption recovery: the reference's scheduler
    # resubmits failed jobs (ref: submit_slurm_jobs.py:157-172) but each
    # resubmission restarts from scratch unless resume is hand-configured;
    # with auto_resume a resubmitted/preempted job continues where its
    # last completed save left off — the standard arrangement for
    # preemptible TPU pods.
    auto_resume: bool = False
    # Optional HF safetensors dir to materialize initial weights from (the
    # reference's bootstrap reads safetensors but only as shape templates,
    # ref: checkpoint.py:93-101; we actually load the values).
    init_from_hf: str = ""
    # Retention GC (picotron_tpu/ckpt_integrity): after each durable
    # commit, prune step dirs beyond the keep_last newest. 0 disables
    # (keep everything — the pre-lineage behavior). keep_every
    # additionally pins steps divisible by it forever (sparse anchors
    # under an aggressive keep_last). The last *verified* checkpoint is
    # never deleted regardless of policy — keep_last=1 with a corrupt
    # newest step keeps the restore fallback alive.
    keep_last: int = 0
    keep_every: int = 0
    # Elastic resume (picotron_tpu/resilience/elastic.py): allow restore
    # into a mesh whose topology differs from the one the checkpoint was
    # saved under (e.g. dp=2 -> dp=4 after a fleet resize). Orbax reshards
    # the global arrays onto the new mesh; the restore validates that
    # global_batch_size is unchanged (the token-exact cursor / loss-parity
    # invariant) and books the restore under the `resize` goodput
    # category. False = a topology mismatch at restore time is a hard
    # error naming the tools/elastic_resize.py re-stamp that would fix it.
    elastic: bool = False


@dataclass(frozen=True)
class ResilienceConfig:
    """Runtime fault tolerance (picotron_tpu/resilience; beyond the
    reference, whose loop dies on the first NaN, hang, or preemption).
    See README "Fault tolerance" for the recovery matrix."""

    # Fault-injection spec, e.g. "sigterm@3,ckpt_io@2x2" (resilience/
    # chaos.py documents the grammar). Empty = no injection. The
    # PICOTRON_CHAOS env var, when set, overrides this field.
    chaos: str = ""
    # Response to a tripped divergence guard (non-finite loss/grads, loss
    # spike): "skip" drops the batch but keeps optimizer state (the
    # non-finite half runs inside the jitted step), "rollback" restores
    # the last durable checkpoint and skips past the poison data range,
    # "abort" exits EXIT_DIVERGED (76), "off" disables the guards — and
    # with them the per-step host sync they require; use "off" to recover
    # fully-async stepping when logging is sparse.
    guard_policy: str = "abort"
    # Rolling loss-spike detection: trip when the loss sits spike_zscore
    # standard deviations above the mean of the last spike_window healthy
    # steps. 0 disables (non-finite detection stays on).
    spike_zscore: float = 0.0
    spike_window: int = 32
    # Consecutive guard trips before escalating to abort regardless of
    # policy — a guard that keeps tripping is not recovering.
    max_guard_trips: int = 3
    # Retry-with-backoff policy for flaky I/O (checkpoint save/restore,
    # durability probes, dataset reads): total attempts and the
    # exponential-backoff delay bounds in seconds.
    retry_attempts: int = 3
    retry_base_delay: float = 0.5
    retry_max_delay: float = 30.0
    # Seconds without step-loop progress before the watchdog dumps all
    # thread stacks and exits EXIT_WATCHDOG (77) for a supervisor
    # restart. 0 disables. Armed only after the first step completes
    # (step 1 includes unbounded XLA compile time); set it well above a
    # normal step + checkpoint write.
    watchdog_timeout: float = 0.0

    def validate(self) -> None:
        if self.guard_policy not in ("off", "skip", "rollback", "abort"):
            raise ValueError(
                f"guard_policy must be off/skip/rollback/abort, got "
                f"{self.guard_policy!r}")
        if self.spike_zscore < 0:
            raise ValueError(
                f"spike_zscore must be >= 0, got {self.spike_zscore}")
        if self.spike_window < 4:
            # fewer points make the z-score statistically meaningless and
            # trip on ordinary early-training descent
            raise ValueError(
                f"spike_window must be >= 4, got {self.spike_window}")
        if self.max_guard_trips < 1:
            raise ValueError(
                f"max_guard_trips must be >= 1, got {self.max_guard_trips}")
        if self.retry_attempts < 1:
            raise ValueError(
                f"retry_attempts must be >= 1, got {self.retry_attempts}")
        if self.retry_base_delay < 0 or self.retry_max_delay < self.retry_base_delay:
            raise ValueError(
                f"retry delays must satisfy 0 <= base <= max, got "
                f"base={self.retry_base_delay} max={self.retry_max_delay}")
        if self.watchdog_timeout < 0:
            raise ValueError(
                f"watchdog_timeout must be >= 0, got {self.watchdog_timeout}")
        if self.chaos:
            # Parse errors at config load, not at step N mid-run.
            from picotron_tpu.resilience.chaos import parse_spec

            parse_spec(self.chaos)


@dataclass(frozen=True)
class ServeConfig:
    """Serving stack (picotron_tpu/serve): continuous batching + paged KV
    cache on the decode path. Sizing contract: per-slot capacity is
    max_model_len tokens (table width = ceil(max_model_len / block_size));
    the POOL is num_blocks fixed-size blocks shared by every slot — cache
    HBM scales with num_blocks, not decode_slots x max_model_len, which is
    the whole point (ragged request lengths stop stranding cache memory).
    Oversubscribe deliberately: the scheduler preempts youngest-first when
    the pool runs dry."""

    # In-flight decode batch width: the ONE static shape the decode step
    # is compiled for (slots are refilled mid-flight, never reshaped).
    decode_slots: int = 8
    # Tokens per physical cache block. Smaller = less fragmentation waste
    # per sequence (at most block_size - 1 slots), larger = smaller block
    # tables and fewer scatter indices.
    block_size: int = 16
    # Physical blocks in the shared pool. 0 = auto: decode_slots *
    # ceil(max_model_len / block_size) — the no-oversubscription worst
    # case (same HBM as a contiguous cache at max length). Set it
    # explicitly to actually bank the paged-cache memory win.
    num_blocks: int = 0
    # Prompt tokens prefilled per engine iteration; one chunk interleaves
    # with each decode step so a long prompt cannot stall in-flight
    # decodes. Also the prefill program's static shape (prompts pad to a
    # chunk multiple; padded positions are sentinel-dropped).
    prefill_chunk: int = 64
    # Per-sequence capacity (prompt + generated). 0 = the model's
    # max_position_embeddings.
    max_model_len: int = 0
    # Decode steps run INSIDE one dispatch (a lax.scan over the decode
    # step, with in-flight EOS forcing identical to generate.py's scan):
    # amortizes the per-dispatch host overhead over this many tokens per
    # slot. The scheduler only sees tokens every interval, so admission/
    # retirement latency quantizes to it and a request that hits EOS or
    # its budget mid-interval pays the leftover steps as padding — keep
    # it small (2-8) for interactive SLOs, 1 for exact per-token
    # scheduling.
    decode_interval: int = 4

    # --- disaggregated serving (serve/disagg.py): prefill pool / decode
    # pool as separately placed programs with paged-KV block handoff, so
    # a burst of long prefills cannot stall decode dispatches ---
    # Split prefill and decode into two pools (DisaggServeEngine).
    disagg: bool = False
    # Prefill-pool slot width (the prefill program's static batch shape).
    # 0 = decode_slots.
    prefill_slots: int = 0
    # Physical blocks in the prefill pool. 0 = auto: prefill_slots *
    # ceil(max_model_len / block_size) — every prefill slot can hold a
    # full-length prompt without backpressure.
    prefill_num_blocks: int = 0
    # Device placement per pool: an index into jax.devices(). -1 = auto
    # (prefill on the first device, decode on the last — distinct devices
    # whenever the host has more than one, colocated otherwise).
    prefill_device: int = -1
    decode_device: int = -1

    # --- fleet serving (serve/fleet.py): a FleetSupervisor fronting N
    # engine replicas with health/failover/drain — the robustness layer
    # in front of the single-engine stack ---
    # Engine replicas behind the supervisor. 1 = no fleet (the
    # single-engine paths are untouched). Each replica gets its own
    # device (round-robin over jax.devices()), its own KV pool, and the
    # SAME base sampling key, so failover re-dispatch is bit-identical.
    fleet_size: int = 1
    # Default admission deadline applied to requests that do not carry
    # their own: a request still queued once its wait exceeds this many
    # milliseconds is SHED (rejected, serve_shed event, booked to the
    # `shed` ledger category) instead of admitted late. 0 = no deadline.
    deadline_ms: float = 0.0
    # Grace budget for FleetSupervisor.drain(): how long (trace-clock
    # seconds) a draining engine may keep its residents before they are
    # forcibly re-dispatched onto the survivors and the engine retires
    # anyway.
    drain_grace_s: float = 5.0

    # --- speculative decode (serve/spec_decode.py): multi-token decode
    # inside the decode_interval scan, verify-and-accept in one dispatch,
    # sampling keys still derived from (request id, token index) so
    # accept/reject cannot perturb tokens ---
    # 'off' (one token per slot per step) or 'ngram' (self-drafting
    # n-gram speculator over each slot's recent context — no draft
    # model, the prompt-lookup arrangement).
    speculator: str = "off"
    # Tokens drafted (and verified) per decode step when the speculator
    # is on; each step emits 1..draft_len+1 tokens per slot.
    draft_len: int = 3

    def validate(self) -> None:
        for name in ("decode_slots", "block_size", "prefill_chunk",
                     "decode_interval"):
            if getattr(self, name) < 1:
                raise ValueError(
                    f"serve.{name} must be >= 1, got {getattr(self, name)}")
        if self.num_blocks < 0:
            raise ValueError(
                f"serve.num_blocks must be >= 0 (0 = auto), got "
                f"{self.num_blocks}")
        if self.max_model_len < 0:
            raise ValueError(
                f"serve.max_model_len must be >= 0 (0 = model limit), got "
                f"{self.max_model_len}")
        for name in ("prefill_slots", "prefill_num_blocks"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"serve.{name} must be >= 0 (0 = auto), got "
                    f"{getattr(self, name)}")
        for name in ("prefill_device", "decode_device"):
            if getattr(self, name) < -1:
                raise ValueError(
                    f"serve.{name} must be a device index or -1 (auto), "
                    f"got {getattr(self, name)}")
        if self.fleet_size < 1:
            raise ValueError(
                f"serve.fleet_size must be >= 1, got {self.fleet_size}")
        if self.deadline_ms < 0:
            raise ValueError(
                f"serve.deadline_ms must be >= 0 (0 = no deadline), got "
                f"{self.deadline_ms}")
        if self.drain_grace_s < 0:
            raise ValueError(
                f"serve.drain_grace_s must be >= 0, got "
                f"{self.drain_grace_s}")
        if self.speculator not in ("off", "ngram"):
            raise ValueError(
                f"serve.speculator must be 'off' or 'ngram', got "
                f"{self.speculator!r}")
        if self.speculator != "off" and self.draft_len < 1:
            raise ValueError(
                f"serve.draft_len must be >= 1 when a speculator is on, "
                f"got {self.draft_len}")
        if self.draft_len < 0:
            raise ValueError(
                f"serve.draft_len must be >= 0, got {self.draft_len}")


@dataclass(frozen=True)
class LoggingConfig:
    """(ref: template/base_config.json:41-45)."""

    use_wandb: bool = False
    project_name: str = "picotron-tpu"
    run_name: Optional[str] = None
    log_frequency: int = 1
    # jax.profiler trace capture (SURVEY.md §5: the reference has no
    # profiler story; on TPU xprof traces are how compute/collective
    # overlap is verified). None disables; a directory enables capture of
    # steps [profile_start_step, profile_start_step + profile_num_steps).
    profile_dir: Optional[str] = None
    profile_start_step: int = 3
    profile_num_steps: int = 3
    # Structured telemetry (picotron_tpu/telemetry): write the per-host
    # JSONL event stream — step-phase timings, goodput ledger, resilience
    # events, per-step metrics — to `telemetry.jsonl` next to the
    # checkpoints (telemetry_dir overrides the location). Append-mode, so
    # a supervised restart continues the same stream and
    # tools/telemetry_report.py can account replayed steps across
    # restarts. The stdout log line is unaffected either way (its format
    # is frozen; tools/extract_metrics.py parses it).
    telemetry_jsonl: bool = True
    telemetry_dir: Optional[str] = None
    # Size-capped JSONL rotation: when > 0, a telemetry.jsonl exceeding
    # this many MB is rotated once to `telemetry.jsonl.1` and a fresh
    # segment starts. Readers (tools/telemetry_report.py,
    # tools/extract_metrics.py) read `.1` then the live file, so
    # cross-restart replay accounting survives rotation. 0 = unbounded.
    telemetry_max_mb: float = 0.0
    # flightdeck span tracer (telemetry/flightdeck/tracer.py): a
    # directory enables span recording (train phases, MPMD schedule
    # ticks, serve request lifecycles, resilience instants) exported as
    # Chrome-trace/Perfetto JSON `trace.json` on close. None disables —
    # the disabled path allocates nothing.
    trace_dir: Optional[str] = None
    # flightdeck crash flight recorder (telemetry/flightdeck/flight.py):
    # ring of the last N steps' phase timings + metrics + spans, dumped
    # to `flightdeck_postmortem.json` on abnormal exits (watchdog 77,
    # divergence abort/rollback, preemption 75, unhandled exceptions).
    # 0 disables.
    flight_steps: int = 8
    # flightdeck drift sentinel (telemetry/flightdeck/sentinel.py):
    # online watch of rolling step time, sync share vs the cost model's
    # predicted exposed comm, and data-wait share. A quantity breaching
    # `sentinel_ratio` x baseline (and `sentinel_zscore` sigmas where
    # the window has variance) for `sentinel_patience` consecutive
    # steps emits ONE `sentinel_alert` event and auto-dumps the flight
    # recorder.
    sentinel: bool = False
    sentinel_window: int = 32
    sentinel_zscore: float = 4.0
    sentinel_ratio: float = 1.5
    sentinel_patience: int = 3

    def validate(self) -> None:
        if self.telemetry_max_mb < 0:
            raise ValueError(
                f"logging.telemetry_max_mb must be >= 0 (0 disables "
                f"rotation), got {self.telemetry_max_mb}")
        if self.flight_steps < 0:
            raise ValueError(
                f"logging.flight_steps must be >= 0 (0 disables the "
                f"flight recorder), got {self.flight_steps}")
        if self.sentinel_window < 4:
            raise ValueError(
                f"logging.sentinel_window must be >= 4, got "
                f"{self.sentinel_window}")
        if self.sentinel_ratio <= 1.0:
            raise ValueError(
                f"logging.sentinel_ratio must be > 1.0, got "
                f"{self.sentinel_ratio}")
        if self.sentinel_zscore <= 0.0:
            raise ValueError(
                f"logging.sentinel_zscore must be > 0, got "
                f"{self.sentinel_zscore}")
        if self.sentinel_patience < 1:
            raise ValueError(
                f"logging.sentinel_patience must be >= 1, got "
                f"{self.sentinel_patience}")


@dataclass(frozen=True)
class PipelineConfig:
    """Pipeline executor selection (picotron_tpu/parallel/mpmd.py).

    executor='spmd' is the reference twin: the whole pipeline is one jitted
    program over the full mesh and the schedule is a lockstep lax.scan over
    the 1f1b table — every device runs every tick, so an IDLE tick costs a
    full traced unit (PERF.md r4 measured the implied bubble at 7.0 ticks
    for pp=4). executor='mpmd' compiles one program per stage and drives
    them from the host-side schedule table; idle ticks cost ~0 host time
    (arxiv 2412.14374), which is what makes interleaved schedules
    profitable at all (see the PERF.md "Interleaved-PP rejection" note,
    which is scoped to the SPMD executor)."""

    # 'spmd' (lockstep scan twin, the default) or 'mpmd' (per-stage
    # programs + host-side schedule).
    executor: str = "spmd"
    # Schedule grammar: '1f1b' (one-forward-one-backward, depth-first
    # backward priority), 'gpipe' (all forwards then all backwards — the
    # AFAB dependency shape, useful as a debugging twin), 'interleaved'
    # (virtual stages: each device group owns `interleave` non-contiguous
    # layer chunks, shrinking the bubble to (pp-1)/v units). mpmd only for
    # anything but '1f1b'.
    schedule: str = "1f1b"
    # Virtual pipeline chunks per device group (v). 1 = plain schedules;
    # >= 2 requires schedule='interleaved' and executor='mpmd'.
    interleave: int = 1

    def validate(self) -> None:
        if self.executor not in ("spmd", "mpmd"):
            raise ValueError(
                f"pipeline.executor must be 'spmd' or 'mpmd', got "
                f"{self.executor!r}")
        if self.schedule not in ("1f1b", "gpipe", "interleaved"):
            raise ValueError(
                f"pipeline.schedule must be '1f1b', 'gpipe', or "
                f"'interleaved', got {self.schedule!r}")
        if self.interleave < 1:
            raise ValueError(
                f"pipeline.interleave must be >= 1, got {self.interleave}")


@dataclass(frozen=True)
class Config:
    distributed: DistributedConfig = field(default_factory=DistributedConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    training: TrainingConfig = field(default_factory=TrainingConfig)
    dataset: DatasetConfig = field(default_factory=DatasetConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)

    # -- derived quantities (ref: data.py:17-20) --

    @property
    def global_batch_size(self) -> int:
        t = self.training
        return (t.micro_batch_size * t.gradient_accumulation_steps
                * self.distributed.dp_size * self.distributed.ep_size)

    @property
    def tokens_per_step(self) -> int:
        return self.global_batch_size * self.training.seq_length

    @property
    def seq_length_per_device(self) -> int:
        return self.training.seq_length // self.distributed.cp_size

    def validate(self) -> None:
        self.distributed.validate()
        self.model.validate()
        self.logging.validate()
        self.resilience.validate()
        self.serve.validate()
        self.pipeline.validate()
        if self.serve.max_model_len > self.model.max_position_embeddings:
            raise ValueError(
                f"serve.max_model_len ({self.serve.max_model_len}) exceeds "
                f"max_position_embeddings "
                f"({self.model.max_position_embeddings})")
        if self.model.num_experts and (self.serve.disagg
                                       or self.serve.speculator != "off"):
            # The serving engines chunk every prefill, and MoE routing is
            # capacity-bounded PER CALL: the same prompt split into chunks
            # routes (and drops) tokens differently than one batched pass,
            # so chunked prefill is not parity-guaranteed for MoE (the
            # PR-7 KNOWN issue, now a hard error instead of a footnote).
            # The engines themselves reject MoE at construction; this
            # catches the intent at config load.
            raise ValueError(
                "serve.disagg / serve.speculator do not support MoE "
                "models (model.num_experts > 0): chunked prefill routes "
                "tokens through per-call capacity-bounded expert dispatch, "
                "which is not parity-guaranteed against the offline "
                "sampler; serve dense models only")
        if self.serve.fleet_size > 1 and self.model.num_experts:
            # Same root cause as the disagg guard above: every fleet
            # replica chunk-prefills, and failover re-dispatch replays a
            # request's prefix through a DIFFERENT chunking on the
            # survivor — for MoE that changes routing, so the
            # bit-identical-failover contract cannot hold.
            raise ValueError(
                "serve.fleet_size > 1 does not support MoE models "
                "(model.num_experts > 0): failover re-dispatch replays "
                "prefixes through per-call capacity-bounded expert "
                "dispatch, which is not parity-guaranteed; serve dense "
                "models only")
        if self.serve.fleet_size > 1 and self.serve.speculator != "off":
            # The n-gram drafter's context is engine-local state that a
            # failover re-dispatch does not carry — tokens stay identical
            # (verify-and-accept guarantees that) but the fleet's
            # redispatch-latency and acceptance accounting would be
            # engine-dependent; keep the combination a hard error until
            # it is pinned.
            raise ValueError(
                "serve.fleet_size > 1 does not support speculative decode "
                "(serve.speculator != 'off'): the drafter's context is "
                "engine-local and is not carried across failover "
                "re-dispatch; set serve.speculator='off' or "
                "serve.fleet_size=1")
        d, m, t = self.distributed, self.model, self.training
        ck = self.checkpoint
        if ck.keep_last < 0 or ck.keep_every < 0:
            raise ValueError(
                f"checkpoint.keep_last/keep_every must be >= 0 (0 "
                f"disables), got keep_last={ck.keep_last} "
                f"keep_every={ck.keep_every}")
        if self.resilience.guard_policy == "skip" and t.optimizer_offload:
            # The in-jit skip selects the pre-update params/opt state,
            # but the offload update streams the host master in place —
            # there is no pre-update tree left to select.
            raise ValueError(
                "resilience.guard_policy='skip' is not supported with "
                "training.optimizer_offload (the streamed host-master "
                "update cannot be un-applied in-step); use 'rollback' "
                "or 'abort'")
        if m.num_attention_heads % d.tp_size != 0:
            raise ValueError("num_attention_heads must be divisible by tp_size")
        if m.num_key_value_heads % d.tp_size != 0:
            raise ValueError("num_key_value_heads must be divisible by tp_size")
        if m.vocab_size % d.tp_size != 0:
            raise ValueError("vocab_size must be divisible by tp_size")
        if d.tp_strategy != "megatron":
            # Non-default strategies rewire the block matmuls through the
            # strategy hooks (parallel/tp_strategies.py); the hook set is
            # dense-model, single-stage, tp-only for now. "adaptive" may
            # resolve to megatron, but its eligibility is the strict set
            # (resolution depends on the ICI generation; gating on the
            # resolved spec would make validity generation-dependent).
            for bad, why in (
                (d.pp_size > 1, "pp_size > 1 (the strategy hooks assume a "
                 "single-stage residual stream)"),
                (d.cp_size > 1, "cp_size > 1 (non-megatron head layouts "
                 "change the tp-local head counts the cp schedules "
                 "divide)"),
                (d.ep_size > 1 or m.num_experts > 0, "MoE models (the "
                 "expert bank keeps the megatron f/g pattern)"),
                (d.sequence_parallel, "sequence_parallel (SP rebinds the "
                 "f/g hooks the strategies replace; use tp_sync='deferred' "
                 "for the seq-sharded residual instead)"),
                (m.attention_bias, "attention_bias (row/2d qkv would need "
                 "a post-collective bias add)"),
            ):
                if bad:
                    raise ValueError(
                        f"tp_strategy={d.tp_strategy!r} does not support "
                        f"{why}")
        if d.tp_sync == "deferred":
            if d.tp_strategy != "megatron":
                raise ValueError(
                    "tp_sync='deferred' composes only with "
                    "tp_strategy='megatron' (the deferred reduce-scatter/"
                    "gather pair reschedules the megatron exit psum; 2d/"
                    "row exits are subgroup collectives with their own "
                    "schedule)")
            if d.pp_size > 1:
                raise ValueError(
                    "tp_sync='deferred' requires pp_size=1 (the seq-sharded "
                    "residual would change the pipeline boundary buffers)")
            if m.num_experts > 0:
                raise ValueError(
                    "tp_sync='deferred' does not support MoE models yet "
                    "(the expert dispatch assumes the sync f/g schedule)")
            if t.seq_length % (d.cp_size * d.tp_size) != 0:
                raise ValueError(
                    "tp_sync='deferred' shards the cp-local sequence over "
                    "tp: seq_length must be divisible by cp_size * tp_size "
                    f"(= {d.cp_size * d.tp_size}), got {t.seq_length}")
        if d.tp_mesh and "2d" not in (parse_tp_strategy(d.tp_strategy)
                                      or {}).values() \
                and d.tp_strategy != "adaptive":
            raise ValueError(
                f"tp_mesh={d.tp_mesh!r} only applies when tp_strategy "
                f"uses the 2d partitioning (got tp_strategy="
                f"{d.tp_strategy!r})")
        if (d.cp_flavor and m.attn_impl in ("ring", "ulysses", "mesh")
                and m.attn_impl != d.cp_flavor):
            raise ValueError(
                f"distributed.cp_flavor={d.cp_flavor!r} contradicts "
                f"model.attn_impl={m.attn_impl!r} — set one of them (or "
                "attn_impl='auto' and let cp_flavor pick the schedule)")
        flavor = resolved_cp_flavor(self)
        if flavor == "ulysses":
            if (m.num_attention_heads // d.tp_size) % d.cp_size != 0 or (
                    m.num_key_value_heads // d.tp_size) % d.cp_size != 0:
                raise ValueError(
                    "the ulysses cp flavor scatters the tp-local heads over "
                    "cp: num_attention_heads/tp and num_key_value_heads/tp "
                    f"must be divisible by cp_size ({d.cp_size}); use the "
                    "ring or mesh flavor for head counts that do not divide")
        if d.cp_mesh and flavor != "mesh":
            raise ValueError(
                f"cp_mesh={d.cp_mesh!r} only applies to the mesh cp flavor "
                f"(resolved flavor here: {flavor or 'none — cp_size is 1'}); "
                "set cp_flavor='mesh' or attn_impl='mesh'")
        if flavor == "mesh":
            cp_x, cp_y = resolved_cp_mesh(self)
            if cp_y > 1 and (
                    (m.num_attention_heads // d.tp_size) % cp_y != 0
                    or (m.num_key_value_heads // d.tp_size) % cp_y != 0):
                raise ValueError(
                    f"mesh cp flavor with cp_mesh {cp_x}x{cp_y} scatters "
                    f"the tp-local heads over the inner factor: "
                    "num_attention_heads/tp and num_key_value_heads/tp "
                    f"must be divisible by cp_y ({cp_y}); pick a smaller "
                    "cp_y (cp_y=1 degenerates to the ring schedule)")
        if d.ep_size > 1 and m.num_experts == 0:
            raise ValueError(
                "ep_size > 1 requires a mixture-of-experts model "
                "(model.num_experts > 0)")
        if m.num_experts:
            if m.num_experts % d.ep_size != 0:
                raise ValueError(
                    f"num_experts ({m.num_experts}) must be divisible by "
                    f"ep_size ({d.ep_size})")
            if not 1 <= m.num_experts_per_token <= m.num_experts:
                raise ValueError(
                    f"num_experts_per_token must be in [1, num_experts], "
                    f"got {m.num_experts_per_token} of {m.num_experts}")
            if m.expert_ffn_size % d.tp_size != 0:
                raise ValueError(
                    "expert ffn size must be divisible by tp_size")
        if t.remat_policy not in ("full", "dots", "dots_attn", "dots_lean",
                                  "dots_norms", "dots_offload"):
            raise ValueError(
                f"remat_policy must be 'full', 'dots', 'dots_attn', "
                f"'dots_lean', 'dots_norms', or 'dots_offload', got "
                f"{t.remat_policy!r}")
        if t.adam_moments_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"adam_moments_dtype must be 'float32' or 'bfloat16', got "
                f"{t.adam_moments_dtype!r}")
        if t.lr_schedule not in ("constant", "cosine", "linear"):
            raise ValueError(
                f"lr_schedule must be constant/cosine/linear, got "
                f"{t.lr_schedule!r}")
        if t.lr_warmup_steps < 0 or t.lr_warmup_steps > t.total_train_steps:
            raise ValueError(
                f"lr_warmup_steps must be in [0, total_train_steps], got "
                f"{t.lr_warmup_steps}")
        if not 0.0 <= t.lr_min_ratio <= 1.0:
            # a negative ratio would drive the decayed LR below zero and
            # silently ascend the loss late in training
            raise ValueError(
                f"lr_min_ratio must be in [0, 1], got {t.lr_min_ratio}")
        if t.ce_chunk_size < 0:
            raise ValueError(
                f"ce_chunk_size must be >= 0, got {t.ce_chunk_size}")
        if t.ce_chunk_size > 0:
            vshard = m.vocab_size // d.tp_size
            if t.ce_chunk_size >= vshard:
                # a chunk spanning the whole per-shard vocab IS the fused
                # path — the implementation would silently take it, and the
                # user set the knob precisely to avoid that memory (ADVICE
                # r3: the old check let any value >= vshard through)
                raise ValueError(
                    f"ce_chunk_size ({t.ce_chunk_size}) must be smaller "
                    f"than the per-tp-shard vocab (vocab_size/tp_size = "
                    f"{vshard}); at or above it chunking degenerates to "
                    f"the fused CE path")
            if vshard % t.ce_chunk_size != 0:
                # a non-dividing chunk would silently fall back to the
                # fused path — the user set the knob to AVOID that memory
                raise ValueError(
                    f"ce_chunk_size ({t.ce_chunk_size}) must divide the "
                    f"per-tp-shard vocab (vocab_size/tp_size = {vshard})")
        if t.grad_engine not in ("auto", "ad", "fused"):
            raise ValueError(
                f"grad_engine must be auto/ad/fused, got {t.grad_engine!r}")
        if t.grad_engine == "fused":
            from picotron_tpu.parallel.fused_bwd import fused_bwd_supported

            if not fused_bwd_supported(self):
                raise ValueError(
                    "grad_engine='fused' requires a single pipeline stage "
                    "(pp_size=1) and remat with remat_policy='dots_attn' "
                    "— the save set the manual backward is derived from. "
                    "dp/tp/sequence_parallel/cp (ring and ulysses)/ep/MoE "
                    "all compose (see the README grad-engine eligibility "
                    "matrix); use 'auto' to fall back to the AD engine "
                    "automatically")
        if t.optimizer_offload:
            # zero1 COMPOSES with offload (r5): the host master/moments
            # shard over the fused data axes, each process streams 1/dp
            # of the state, and the update all-gathers the refreshed
            # bf16 params — dp x less host RAM and PCIe per process.
            if self.model.dtype != "bfloat16":
                raise ValueError(
                    "optimizer_offload requires model.dtype='bfloat16' "
                    "(the device-resident compute copy is the model's "
                    "compute dtype; an fp32 compute copy would duplicate "
                    "the master and save nothing)")
            if d.pp_size > 1 and d.pp_engine == "afab":
                # afab differentiates through the pipeline scan, so param
                # cotangents accumulate in the param dtype — bf16 under
                # offload, losing exactly the low bits the fp32 master
                # keeps. The 1f1b manual-VJP path accumulates its grads in
                # fp32 explicitly (pp.py g_zero) and is the supported
                # offload x pp combination (ADVICE r4).
                raise ValueError(
                    "optimizer_offload with pp_engine='afab' would "
                    "accumulate microbatch gradients in bf16 (the AD "
                    "path's cotangent dtype is the bf16 param dtype); "
                    "use pp_engine='1f1b' (the default), whose manual "
                    "VJP accumulates gradients in fp32")
        lg = self.logging
        if lg.profile_dir is not None:
            if lg.profile_start_step < 1:
                raise ValueError(
                    f"profile_start_step must be >= 1 (steps are 1-based), "
                    f"got {lg.profile_start_step}")
            if lg.profile_num_steps < 1:
                raise ValueError(
                    f"profile_num_steps must be >= 1, got "
                    f"{lg.profile_num_steps}")
        if t.seq_length < 1:
            raise ValueError(f"seq_length must be >= 1, got {t.seq_length}")
        if t.seq_length % d.cp_size != 0:
            raise ValueError("seq_length must be divisible by cp_size")
        if (d.sequence_parallel
                and t.seq_length % (d.cp_size * d.tp_size) != 0):
            raise ValueError(
                "sequence_parallel shards the cp-local sequence over tp: "
                "seq_length must be divisible by cp_size * tp_size "
                f"(= {d.cp_size * d.tp_size}), got {t.seq_length}")
        if (d.cp_size > 1 and d.cp_layout == "zigzag"
                and t.seq_length % (2 * d.cp_size) != 0):
            raise ValueError(
                f"zigzag cp_layout needs seq_length divisible by 2*cp_size "
                f"({2 * d.cp_size}); got {t.seq_length}. Use "
                f"cp_layout='contiguous' or adjust seq_length."
            )
        if t.seq_length > m.max_position_embeddings:
            # Same bound the reference applies by construction (ref:
            # train.py:159 sets seq_length == max_position_embeddings).
            raise ValueError(
                f"seq_length ({t.seq_length}) exceeds max_position_embeddings "
                f"({m.max_position_embeddings})"
            )
        if d.pp_size > m.num_hidden_layers:
            raise ValueError(
                f"pp_size ({d.pp_size}) cannot exceed num_hidden_layers ({m.num_hidden_layers})"
            )
        # num_hidden_layers % pp_size may be nonzero: the stacked layer axis
        # is padded with identity (all-zero) layers and the remainder goes to
        # early stages (ref: pipeline_parallel.py:42-51 distribute_layers);
        # see models.llama.pp_layer_placement.
        if t.eval_frequency < 0 or (t.eval_frequency > 0 and t.eval_steps < 1):
            raise ValueError(
                "eval_frequency must be >= 0 and eval_steps >= 1 when "
                f"eval is enabled, got {t.eval_frequency}/{t.eval_steps}")
        if t.gradient_accumulation_steps < 1:
            raise ValueError(
                f"gradient_accumulation_steps must be >= 1, got "
                f"{t.gradient_accumulation_steps}"
            )
        pl = self.pipeline
        if pl.executor == "spmd":
            if pl.schedule != "1f1b" or pl.interleave != 1:
                raise ValueError(
                    "the spmd executor only runs the lockstep 1f1b scan "
                    "(schedule='1f1b', interleave=1); alternative schedules "
                    "require pipeline.executor='mpmd', where an idle tick "
                    f"stops costing a full traced unit — got "
                    f"schedule={pl.schedule!r} interleave={pl.interleave}")
        else:  # mpmd
            if d.pp_size < 2:
                raise ValueError(
                    "pipeline.executor='mpmd' requires pp_size >= 2 (with "
                    "one stage there is nothing to schedule; the single "
                    "jitted program IS the spmd executor)")
            if t.optimizer_offload:
                raise ValueError(
                    "pipeline.executor='mpmd' does not compose with "
                    "training.optimizer_offload yet (the streamed host "
                    "update assumes the monolithic step program); use the "
                    "spmd executor for offload runs")
            if m.num_experts:
                raise ValueError(
                    "pipeline.executor='mpmd' does not support MoE models "
                    "yet (per-stage submeshes drop the 'ep' axis from the "
                    "stage programs); use the spmd executor")
            if d.sequence_parallel:
                raise ValueError(
                    "pipeline.executor='mpmd' does not support "
                    "sequence_parallel yet (the sp grad sync runs over the "
                    "whole-mesh program); use the spmd executor")
            if d.pp_engine != "1f1b":
                raise ValueError(
                    "pipeline.executor='mpmd' drives the host schedule "
                    "table; set pp_engine='1f1b' (the afab engine is an "
                    "spmd-only differentiation strategy)")
        if pl.schedule == "interleaved":
            if pl.interleave < 2:
                raise ValueError(
                    "pipeline.schedule='interleaved' needs interleave >= 2 "
                    "(v=1 interleaving IS plain 1f1b); got "
                    f"{pl.interleave}")
            slots = -(-m.num_hidden_layers // d.pp_size)  # ceil
            if slots % pl.interleave != 0:
                raise ValueError(
                    f"pipeline.interleave ({pl.interleave}) must divide the "
                    f"per-stage layer slot count (ceil(num_hidden_layers / "
                    f"pp_size) = {slots}) so every virtual chunk is the "
                    f"same shape and compiles once")
        elif pl.interleave != 1:
            raise ValueError(
                f"pipeline.interleave > 1 requires "
                f"pipeline.schedule='interleaved', got "
                f"schedule={pl.schedule!r} interleave={pl.interleave}")

    def to_json_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def replace(self, **sections: Any) -> "Config":
        return dataclasses.replace(self, **sections)


def resolved_cp_flavor(cfg: "Config") -> str:
    """The context-parallel attention schedule this config runs:
    'ring' | 'ulysses' | 'mesh' when cp_size > 1, '' otherwise. The single
    dispatch key for parallel/api.py, parallel/fused_bwd.py, the
    collective-schedule audit and the cost model — distributed.cp_flavor
    wins, model.attn_impl names a flavor directly for back-compat, and the
    default is the ring (no head-divisibility constraint)."""
    d, m = cfg.distributed, cfg.model
    if d.cp_size <= 1:
        return ""
    if d.cp_flavor:
        return d.cp_flavor
    if m.attn_impl in ("ring", "ulysses", "mesh"):
        return m.attn_impl
    return "ring"


def resolved_cp_mesh(cfg: "Config") -> tuple[int, int]:
    """(cp_x, cp_y) for the mesh cp flavor. An explicit distributed.cp_mesh
    wins; otherwise the most-square FEASIBLE factorization (cp_y must
    divide the tp-local q and kv head counts), tie-broken toward the
    larger cp_y — one all_to_all over more (contiguous, innermost-ICI)
    devices is cheaper than an extra serial ring hop. The planner
    enumerates every feasible factorization against the topology-aware
    cost model instead of trusting this default."""
    d, m = cfg.distributed, cfg.model
    cp = d.cp_size
    if d.cp_mesh:
        return parse_cp_mesh(d.cp_mesh)
    hq = m.num_attention_heads // d.tp_size
    hkv = m.num_key_value_heads // d.tp_size
    feasible = [y for y in range(1, cp + 1)
                if cp % y == 0 and hq % y == 0 and hkv % y == 0]
    cp_y = min(feasible, key=lambda y: (abs(y - cp ** 0.5), -y))
    return cp // cp_y, cp_y


def resolved_tp_strategy(cfg: "Config", generation: str = "v5e"):
    """The concrete per-layer-class TP partitioning this config runs:
    a dict {qkv,o,up,down,head} -> {col,row,2d}. The single dispatch key
    for parallel/tp_strategies.py, parallel/sharding.py, the collective
    audit and the cost model. tp_size==1 always resolves to megatron (the
    hooks compile away); "adaptive" resolves deterministically via the
    cost-model per-class argmin against `generation`'s ICI descriptor
    (choose_tp_strategy — pure analytic, no devices touched)."""
    d = cfg.distributed
    if d.tp_size <= 1:
        return dict(_TP_STRATEGY_PRESETS["megatron"])
    spec = parse_tp_strategy(d.tp_strategy)
    if spec is not None:
        return spec
    from picotron_tpu.analysis.cost_model import choose_tp_strategy

    return choose_tp_strategy(cfg, generation=generation)


def resolved_tp_mesh(cfg: "Config") -> tuple[int, int]:
    """(tp_x, tp_y) for the 2d TP partitioning. An explicit
    distributed.tp_mesh wins; otherwise the most-square feasible
    factorization (tp_x must divide the q and kv head counts — always true
    when tp does — and both factors must divide tp), tie-broken toward the
    SMALLER tp_y: tp_y is the replication factor of the row-side matmuls
    and the attention, so among equally-square options less replicated
    compute wins."""
    d, m = cfg.distributed, cfg.model
    tp = d.tp_size
    if d.tp_mesh:
        return parse_tp_mesh(d.tp_mesh)
    feasible = [y for y in range(1, tp + 1)
                if tp % y == 0
                and m.num_attention_heads % (tp // y) == 0
                and m.num_key_value_heads % (tp // y) == 0]
    tp_y = min(feasible, key=lambda y: (abs(y - tp ** 0.5), y))
    return tp // tp_y, tp_y


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------


def _filter_kwargs(cls: type, raw: dict[str, Any]) -> dict[str, Any]:
    names = {f.name for f in dataclasses.fields(cls)}
    return {k: v for k, v in raw.items() if k in names}


def config_from_dict(raw: dict[str, Any]) -> Config:
    """Build a Config from a (reference-schema-compatible) dict."""
    model_raw = dict(raw.get("model", {}))
    name = model_raw.get("name")
    if name:
        try:
            preset = resolve_preset(name)
        except KeyError:
            # Unknown name is only acceptable when the JSON itself carries the
            # architecture — otherwise a typo'd name would silently train the
            # tiny debug defaults.
            core = {"vocab_size", "hidden_size", "intermediate_size", "num_hidden_layers"}
            if not core.issubset(model_raw):
                raise
            preset = {}
        # Explicit values in the JSON override the preset (ref:
        # create_config.py:56-63 same precedence for layer/head overrides).
        merged = {**preset, **{k: v for k, v in model_raw.items() if v is not None}}
    else:
        # No name: a partially-specified architecture would silently merge
        # with the tiny debug defaults — require the core fields, or nothing.
        core = {"vocab_size", "hidden_size", "intermediate_size", "num_hidden_layers"}
        arch_keys = {k for k, v in model_raw.items() if v is not None} - {
            "dtype", "attn_impl", "use_flash_attention", "use_fused_adam"
        }
        if arch_keys and not core.issubset(arch_keys):
            raise ValueError(
                "model section specifies architecture fields without a `name`; "
                f"either set `name` to a preset or provide all of {sorted(core)}"
            )
        merged = model_raw
    # The reference allows `num_hidden_layers: null` meaning "use preset".
    merged = {k: v for k, v in merged.items() if v is not None}

    cfg = Config(
        distributed=DistributedConfig(**_filter_kwargs(DistributedConfig, raw.get("distributed", {}))),
        model=ModelConfig(**_filter_kwargs(ModelConfig, merged)),
        training=TrainingConfig(**_filter_kwargs(TrainingConfig, raw.get("training", {}))),
        dataset=DatasetConfig(**_filter_kwargs(DatasetConfig, raw.get("dataset", {}))),
        checkpoint=CheckpointConfig(**_filter_kwargs(CheckpointConfig, raw.get("checkpoint", {}))),
        logging=LoggingConfig(**_filter_kwargs(LoggingConfig, raw.get("logging", {}))),
        resilience=ResilienceConfig(**_filter_kwargs(ResilienceConfig, raw.get("resilience", {}))),
        serve=ServeConfig(**_filter_kwargs(ServeConfig, raw.get("serve", {}))),
        pipeline=PipelineConfig(**_filter_kwargs(PipelineConfig, raw.get("pipeline", {}))),
    )
    cfg.validate()
    return cfg


def load_config(path: str) -> Config:
    """Load a config JSON (reference schema compatible, ref: train.py:62)."""
    with open(path) as f:
        raw = json.load(f)
    return config_from_dict(raw)


def save_config(cfg: Config, path: str) -> None:
    with open(path, "w") as f:
        json.dump(cfg.to_json_dict(), f, indent=2)


def num_params(m: ModelConfig, active_only: bool = False,
               include_tied_head: bool = False) -> int:
    """Total parameter count (embedding + untied head counted separately,
    matching the reference's accounting in utils.py:50-79). For MoE,
    `active_only` counts the top-k experts a token actually visits — the N
    that belongs in the 6N FLOPs/token formula. `include_tied_head` counts
    the h*v head term even when tie_word_embeddings shares it with the
    embedding: the head MATMUL executes either way, so the FLOPs accounting
    (utils.flops_per_token) must include it or tied models would
    understate MFU by the head's share."""
    h, i, v, l = m.hidden_size, m.intermediate_size, m.vocab_size, m.num_hidden_layers
    kv = m.num_key_value_heads * m.head_dim
    if m.num_experts:
        e_ffn = 3 * h * m.expert_ffn_size  # gate/up/down per expert
        n_ffn_experts = (m.num_experts_per_token if active_only
                         else m.num_experts)
        ffn = h * m.num_experts + n_ffn_experts * e_ffn  # router + experts
    else:
        ffn = 3 * h * i  # gate/up/down
    per_layer = (
        h * h  # q_proj
        + h * kv * 2  # k/v_proj
        + h * h  # out_proj
        + ffn
        + 2 * h  # two RMSNorm weights
    )
    if m.attention_bias:
        per_layer += h + 2 * kv  # q/k/v biases
    head = (h * v if (not m.tie_word_embeddings or include_tied_head)
            else 0)
    return v * h + l * per_layer + h + head  # embed + layers + final_norm (+ head)
