"""Single-device training step: grad-accumulation scan + AdamW update.

Parity with the reference's non-PP `train_step` (ref: train.py:29-55): loop
over gradient-accumulation microbatches, average the loss, one optimizer step.
The Python for-loop with a grad-sync flag becomes a `lax.scan` accumulating
fp32 gradients — the deferred-allreduce semantics the reference implements
with `require_backward_grad_sync` (ref: train.py:41, data_parallel.py:80) are
simply "psum once, after the scan" in the SPMD version
(see picotron_tpu/parallel/api.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from picotron_tpu.config import Config
from picotron_tpu.models.llama import DEFAULT_CTX, ParallelCtx, loss_fn
from picotron_tpu.optimizer import make_optimizer


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar


def init_train_state(cfg: Config, params) -> TrainState:
    opt = make_optimizer(cfg.training)
    return TrainState(params=params, opt_state=opt.init(params),
                      step=jnp.zeros((), jnp.int32))


def accumulate_grads(params, batch, cfg: Config, ctx: ParallelCtx):
    """Scan microbatches, accumulating fp32 grads and the mean loss.

    batch: (input_ids, targets), each [n_micro, mbs, seq].
    """
    n_micro = batch[0].shape[0]

    def micro_step(carry, mb):
        grads_acc, loss_acc = carry
        ids, tgt = mb
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, tgt, cfg.model, ctx)
        grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
        return (grads_acc, loss_acc + loss), None

    zero_grads = jax.tree.map(jnp.zeros_like, params)
    (grads, loss_sum), _ = jax.lax.scan(
        micro_step, (zero_grads, jnp.zeros((), jnp.float32)), batch
    )
    scale = 1.0 / n_micro
    grads = jax.tree.map(lambda g: g * scale, grads)
    return grads, loss_sum * scale


def make_train_step(cfg: Config, ctx: ParallelCtx = DEFAULT_CTX):
    """Build a jittable (state, batch) -> (state, loss) single-device step."""
    opt = make_optimizer(cfg.training)

    def train_step(state: TrainState, batch):
        grads, loss = accumulate_grads(state.params, batch, cfg, ctx)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return train_step
