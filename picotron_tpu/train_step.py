"""Single-device training step: grad-accumulation scan + AdamW update.

Parity with the reference's non-PP `train_step` (ref: train.py:29-55): loop
over gradient-accumulation microbatches, average the loss, one optimizer step.
The Python for-loop with a grad-sync flag becomes a `lax.scan` accumulating
fp32 gradients — the deferred-allreduce semantics the reference implements
with `require_backward_grad_sync` (ref: train.py:41, data_parallel.py:80) are
simply "psum once, after the scan" in the SPMD version
(see picotron_tpu/parallel/api.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from picotron_tpu.config import Config
from picotron_tpu.models.llama import DEFAULT_CTX, ParallelCtx, loss_sum_count
from picotron_tpu.optimizer import make_optimizer


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar


def init_train_state(cfg: Config, params) -> TrainState:
    opt = make_optimizer(cfg.training)
    return TrainState(params=params, opt_state=opt.init(params),
                      step=jnp.zeros((), jnp.int32))


def guard_nonfinite(ok, new_tree, old_tree):
    """In-jit half of the divergence guard's 'skip' policy: when `ok` (a
    scalar bool — loss and grad norm both finite) is False, keep every
    `old_tree` leaf, discarding the poisoned update while preserving
    optimizer state. Must run inside the step — with donated input
    buffers the host cannot resurrect the pre-step state after the fact.
    The step counter still advances, so schedules and token budgets are
    unaffected by a dropped batch."""
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                        new_tree, old_tree)


def accumulate_grads(params, batch, cfg: Config, ctx: ParallelCtx):
    """Scan microbatches, accumulating fp32 grads and the mean loss.

    batch: (input_ids, targets), each [n_micro, mbs, seq].
    """
    def nll(params, ids, tgt):
        total, count, _ = loss_sum_count(params, ids, tgt, cfg.model, ctx)
        return total, count

    def micro_step(carry, mb):
        grads_acc, loss_acc, count_acc = carry
        ids, tgt = mb
        (total, count), grads = jax.value_and_grad(nll, has_aux=True)(
            params, ids, tgt)
        grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
        return (grads_acc, loss_acc + total, count_acc + count), None

    zero_grads = jax.tree.map(jnp.zeros_like, params)
    (grads, nll_total, count), _ = jax.lax.scan(
        micro_step,
        (zero_grads, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        batch,
    )
    # Global token mean: sum of NLL over all microbatches / total valid
    # tokens — same reduction as the parallel path (parallel/api.py), so a
    # dp=1 run matches this baseline even with uneven IGNORE_INDEX counts.
    count = jnp.maximum(count, 1)
    grads = jax.tree.map(lambda g: g / count, grads)
    return grads, nll_total / count


def make_train_step(cfg: Config, ctx: ParallelCtx = DEFAULT_CTX):
    """Build a jittable (state, batch) -> (state, loss) single-device step."""
    opt = make_optimizer(cfg.training)

    def train_step(state: TrainState, batch):
        grads, loss = accumulate_grads(state.params, batch, cfg, ctx)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return train_step
