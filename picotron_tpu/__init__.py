"""picotron-tpu: a TPU-native 5D-parallel LLM pre-training framework.

A from-scratch JAX/XLA/Pallas framework with the capabilities of the
reference `picotron` (HuggingFace's minimalist 4D-parallel trainer), designed
SPMD/compiler-first for TPU:

- one `jax.sharding.Mesh` with axes ``('dp', 'pp', 'ep', 'cp', 'tp')`` replaces the
  per-rank process-group singleton (ref: picotron/process_group_manager.py),
- data / tensor / pipeline / context parallelism are composed inside a single
  `shard_map`-ped train step with explicit XLA collectives
  (`psum` / `all_gather` / `ppermute`) riding ICI,
- the hot attention path is a Pallas flash-attention kernel that exports
  per-block LSE so the context-parallel ring can reuse it.
"""

__version__ = "0.1.0"

from picotron_tpu.config import Config, load_config  # noqa: F401
from picotron_tpu.mesh import MeshEnv  # noqa: F401
from picotron_tpu.data import MicroBatchDataLoader  # noqa: F401
from picotron_tpu.checkpoint import CheckpointManager  # noqa: F401
from picotron_tpu.parallel.api import (  # noqa: F401
    init_sharded_state,
    make_train_step,
)
from picotron_tpu.telemetry import Telemetry  # noqa: F401
