"""Static jit-variant prover — compile-once, certified before any run.

Every `jit` entry point mints a fresh compile whenever the *abstract
signature* of a call changes: any leaf's shape, dtype, weak-type,
sharding, or committed-ness, or any static argument. The failure mode is
always silent — PR 7's serving stack found a 0.6 s mid-trace recompile
only because CompileWatch was listening at runtime. This module makes the
property *provable* on the host, before a chip is touched:

- `signature_of(tree, statics)` canonicalizes one call's abstract
  signature (`AbstractSig`): per-leaf (path, shape, dtype, spec,
  committed, weak_type) plus the static-argument tuple. Two calls compile
  separately iff their `AbstractSig`s differ.
- `audit_feeds(feeds)` enumerates the signature space a call site can
  produce and flags exactly the three variant-minting hazards the issue
  names: an uncommitted array joining a committed signature, a varying
  shape/dtype, and a sharding variant.
- `prove_train_step(cfg)` certifies the training entry point: the initial
  signature (abstract sharded state + batch) must equal the steady-state
  signature (the step's own output fed back in), and every input leaf
  must carry an explicit sharding. One signature -> exactly one compile
  for the whole run, fused-bwd and 1f1b interiors included (they live
  inside this jit; a custom-vjp path cannot mint an outer variant).
- `prove_serve_programs(...)` / `check_engine_feed(engine)` certify the
  decode + prefill programs: slot count is the only shape carrier (all
  decode inputs are [S]/[S, C]-shaped, request identity is data), so the
  signature space is closed iff every persistent input is committed and
  every per-step upload goes through the engine's single replicated
  sharding — the commit-everything discipline, now checked instead of
  trusted.

What "proven" covers — and does not. The proof is over the abstract
signature space: it shows no *input-side* variant can occur. It does not
model jit-cache eviction, explicitly different static arguments (a new
`interval` is a new program — intended), or a JAX upgrade changing
lowering itself. Output shardings are not observable without compiling;
for the train step the stability check pins output avals == input avals,
and the runtime CompileWatch twin tests (tests/test_dataflow.py) confirm
the end-to-end claim on the real cache.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from picotron_tpu.analysis.report import ERROR, INFO, WARNING, Report

CHECK = "variants"


@dataclass(frozen=True)
class AbstractSig:
    """One jit call's canonical abstract signature."""

    treedef: str
    leaves: tuple    # ((path, shape, dtype, spec, committed, weak), ...)
    statics: tuple = ()

    def diff(self, other: "AbstractSig") -> list:
        """Human-readable component differences vs `other`."""
        out = []
        if self.treedef != other.treedef:
            out.append("pytree structure differs")
        a = {leaf[0]: leaf[1:] for leaf in self.leaves}
        b = {leaf[0]: leaf[1:] for leaf in other.leaves}
        names = ("shape", "dtype", "sharding", "committed", "weak_type")
        for path in sorted(set(a) | set(b)):
            if path not in a or path not in b:
                out.append(f"{path}: leaf only on one side")
                continue
            for name, x, y in zip(names, a[path], b[path]):
                if x != y:
                    out.append(f"{path}: {name} {x!r} vs {y!r}")
        if self.statics != other.statics:
            out.append(f"statics {self.statics!r} vs {other.statics!r}")
        return out


def _leaf_sig(path: str, x) -> tuple:
    shape = tuple(getattr(x, "shape", ()))
    dtype = str(getattr(x, "dtype", "?"))
    sharding = getattr(x, "sharding", None)
    if isinstance(x, jax.ShapeDtypeStruct):
        # abstract leaf: an attached sharding *declares* the commitment
        committed = sharding is not None
    else:
        committed = bool(getattr(x, "committed", False))
    spec = None
    if committed and sharding is not None:
        spec = (str(tuple(sharding.spec)) if hasattr(sharding, "spec")
                else type(sharding).__name__)
    weak = bool(getattr(x, "weak_type", False))
    return (path, shape, dtype, spec, committed, weak)


def signature_of(tree, statics: tuple = ()) -> AbstractSig:
    """Canonical `AbstractSig` of one call's argument pytree. `statics`
    must already be hashable (jit would reject them otherwise)."""
    from picotron_tpu.analysis.spec_lint import dict_by_path

    leaves = tuple(_leaf_sig(p, x) for p, x in dict_by_path(tree).items())
    treedef = str(jax.tree_util.tree_structure(tree))
    return AbstractSig(treedef, leaves, tuple(statics))


def audit_feeds(feeds, *, entry: str = "<jit>", statics=None) -> Report:
    """Enumerate the signature space of a call site's possible feeds.

    `feeds`: list of argument pytrees one call site can pass (each
    optionally paired with statics via the `statics` list). More than one
    distinct signature means the entry point compiles more than once; any
    uncommitted concrete leaf is flagged even when the space is closed,
    because commitment spreads through jit outputs — one uncommitted feed
    poisons downstream signatures (the serve-engine hazard)."""
    rep = Report()
    statics = statics or [()] * len(feeds)
    sigs = []
    for tree, st in zip(feeds, statics):
        sigs.append(signature_of(tree, st))
        for path, shape, dtype, spec, committed, weak in sigs[-1].leaves:
            if not committed:
                rep.add(CHECK, WARNING, f"{entry}/{path}",
                        f"feed can be UNCOMMITTED ({dtype}{list(shape)}): "
                        f"a committed array later reaching this leaf keys "
                        f"a different jit signature and mints a recompile "
                        f"— commit it up front with jax.device_put(x, "
                        f"<sharding>)")
    uniq = []
    for s in sigs:
        if s not in uniq:
            uniq.append(s)
    if len(uniq) > 1:
        diffs = uniq[0].diff(uniq[1])
        rep.add(CHECK, ERROR, entry,
                f"{len(uniq)} distinct abstract signatures reach this jit "
                f"entry — compile-once is NOT provable. First divergence: "
                f"{'; '.join(diffs[:4]) or 'statics differ'}")
    rep.info[CHECK] = {"entry": entry, "feeds": len(feeds),
                       "signatures": len(uniq),
                       "proven": len(uniq) <= 1 and rep.ok()}
    return rep


# ---------------------------------------------------------------------------
# Train step
# ---------------------------------------------------------------------------


def prove_train_step(cfg, menv=None, *, low=None) -> Report:
    """Certify the training entry point compiles exactly once.

    Signature space: {initial call} ∪ {steady-state calls}. The initial
    signature comes from the abstract sharded state + batch; the
    steady-state signature feeds the step's own output state back in
    (avals via eval_shape — shardings are not observable abstractly, so
    leaf shardings are compared on the declared input side and aval
    stability covers the output side). Proven iff both signatures agree
    and every input leaf carries an explicit sharding."""
    rep = Report()
    if low is None:
        from picotron_tpu.analysis.trace import lower_train_step

        low = lower_train_step(cfg, menv)
    state, batch = low.state, low.batch

    sig0 = signature_of((state, batch))
    uncommitted = [leaf[0] for leaf in sig0.leaves if not leaf[4]]
    for path in uncommitted:
        rep.add(CHECK, ERROR, path,
                "train-step input leaf has no explicit sharding: the "
                "first committed array reaching it re-keys the jit cache "
                "(init_sharded_state must hand every leaf a NamedSharding)")

    out = jax.eval_shape(low.step_fn, state, batch)
    new_state = out[0] if isinstance(out, tuple) else out
    # steady state: output state replaces input state, batch aval repeats
    drift = []
    if (jax.tree_util.tree_structure(new_state)
            != jax.tree_util.tree_structure(state)):
        drift = ["pytree structure differs across the step"]
    else:
        from picotron_tpu.analysis.spec_lint import dict_by_path

        ins, outs = dict_by_path(state), dict_by_path(new_state)
        for path, a in ins.items():
            b = outs[path]
            if (tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype
                    or getattr(a, "weak_type", False)
                    != getattr(b, "weak_type", False)):
                drift.append(
                    f"state/{path}: {a.dtype}{list(a.shape)} in, "
                    f"{b.dtype}{list(b.shape)} out")
    for d in drift:
        rep.add(CHECK, ERROR, d.split(":")[0],
                f"steady-state signature differs from the initial one "
                f"({d}): step 2 presents a new abstract signature and "
                f"recompiles — a varying-shape call site by construction")

    proven = rep.ok()
    rep.info[CHECK] = {
        "entry": "train_step",
        "signatures": 1 if proven else 2,
        "proven": proven,
        "leaves": len(sig0.leaves),
        "uncommitted": len(uncommitted),
        # interior grad paths (fused-bwd custom_vjp, 1f1b scan) live
        # inside this jit: they cannot mint an outer variant
        "covers": ("train_step", "fused-bwd interior", "pp interior"),
    }
    if proven:
        rep.add(CHECK, INFO, "train_step",
                f"compile-once proven: one abstract signature "
                f"({len(sig0.leaves)} committed leaves, stable avals "
                f"across the step)")
    return rep


# ---------------------------------------------------------------------------
# MPMD stage programs
# ---------------------------------------------------------------------------


def prove_mpmd_stages(cfg, menv=None) -> Report:
    """Certify every MPMD per-stage program compiles exactly once.

    mpmd.mpmd_entry_feeds enumerates, per stage program (fwd and bwd of
    each virtual stage), the abstract argument tuple of EVERY call the
    config's schedule table makes — committed ShapeDtypeStructs with the
    submesh shardings the executor device_puts. audit_feeds then closes
    each entry's signature space; a stage whose scheduled calls disagree
    in abstract signature (a second executable minted mid-schedule) is an
    ERROR, which shardcheck renders as a fatal row.

    The schedule TABLE itself is also re-linted here (mpmd.lint_schedule:
    balanced produce/consume per boundary buffer, no consume-before-
    produce, bounded in-flight live set) so the CLI surfaces the same
    static proof build_schedule enforces at construction time."""
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.mpmd import (build_schedule, lint_schedule,
                                            mpmd_entry_feeds)

    rep = Report()
    menv = menv if menv is not None else MeshEnv.from_config(cfg)
    feeds = mpmd_entry_feeds(cfg, menv)
    entries = {}
    proven_all = True
    for entry in sorted(feeds):
        sub = audit_feeds(feeds[entry], entry=entry)
        rep.findings.extend(sub.findings)
        info = sub.info.get(CHECK, {})
        entries[entry] = info
        proven_all = proven_all and bool(info.get("proven"))
    n_micro = cfg.training.gradient_accumulation_steps
    pp = cfg.distributed.pp_size
    kind = cfg.pipeline.schedule
    table = build_schedule(kind, n_micro, pp, cfg.pipeline.interleave)
    problems = lint_schedule(table, n_micro, pp, cfg.pipeline.interleave,
                             kind=kind)
    for p in problems:  # unreachable via build_schedule (it raises), but
        rep.add(CHECK, ERROR, "schedule", p)  # guards future generators
    lint_info = {"kind": kind, "ops": len(table),
                 "ticks": (max(op.tick for op in table) + 1) if table else 0,
                 "problems": len(problems), "proven": not problems}
    rep.info[CHECK] = {"entry": "mpmd_stages", "programs": len(feeds),
                       "proven": proven_all and not problems,
                       "entries": entries, "schedule_lint": lint_info}
    if proven_all:
        rep.add(CHECK, INFO, "mpmd_stages",
                f"compile-once proven for all {len(feeds)} stage programs "
                f"(schedule {cfg.pipeline.schedule}, every scheduled call "
                f"presents one committed abstract signature per program)")
    return rep


# ---------------------------------------------------------------------------
# Serve programs
# ---------------------------------------------------------------------------

_PERSISTENT = ("params", "_k", "_v", "cos", "sin", "base_key")
_UPLOADED = ("tables", "toks", "positions", "rids", "tidx")


def check_engine_feed(engine) -> Report:
    """Certify a ServeEngine's decode + prefill signature spaces, from the
    arrays the engine actually holds (duck-typed; no serve import).

    Closed iff (a) every persistent device input — params leaves, the KV
    pool, rope tables, the sampling key — is committed, and (b) the
    per-step host uploads all route through the engine's single
    `_rep_sh` sharding (true by construction; recorded here). Slot count
    is the only shape carrier, so with (a) and (b) the signature space is
    exactly {one decode sig} x {one prefill sig}."""
    from picotron_tpu.analysis.spec_lint import dict_by_path

    rep = Report()
    uncommitted = []
    for name in _PERSISTENT:
        tree = getattr(engine, name, None)
        if tree is None:
            continue
        for path, leaf in dict_by_path(tree).items():
            if hasattr(leaf, "committed") and not leaf.committed:
                uncommitted.append(
                    name if path == "<root>" else f"{name}/{path}")
    for path in uncommitted:
        rep.add(CHECK, WARNING, path,
                "persistent serve input is UNCOMMITTED: commitment "
                "spreads through jit outputs, so the first committed "
                "array joining a call (e.g. place_for_decode'd params) "
                "re-keys the decode signature and mints a mid-trace "
                "recompile — device_put it with an explicit sharding at "
                "engine construction")
    proven = not uncommitted
    rep.info[CHECK] = {
        "entry": "serve_decode+prefill",
        "signatures": 1 if proven else 2,
        "proven": proven,
        "uncommitted": uncommitted,
        "upload_sharding": type(getattr(engine, "_rep_sh", None)).__name__,
        "slots": getattr(engine, "num_slots", None),
    }
    if proven:
        rep.add(CHECK, INFO, "serve",
                "compile-once proven for decode and prefill: every "
                "persistent input committed; host uploads share one "
                "replicated sharding; slot count is the only static shape")
    return rep


def prove_serve_programs(model_cfg, serve_cfg=None, *, params=None) -> \
        Report:
    """Static (engine-less) proof for the serve programs of a config:
    constructs the decode/prefill abstract signatures exactly as
    ServeEngine feeds them and certifies the space is closed. With
    `params` (a concrete pytree), their commitment is checked too —
    otherwise params are assumed committed and the engine-side
    `check_engine_feed` covers the live check."""
    import jax.numpy as jnp

    from picotron_tpu.config import ServeConfig
    from picotron_tpu.serve.paged_cache import init_paged_cache
    from picotron_tpu.serve.scheduler import blocks_for

    scfg = serve_cfg or ServeConfig()
    scfg.validate()
    rep = Report()
    max_len = scfg.max_model_len or model_cfg.max_position_embeddings
    max_blocks = blocks_for(max_len, scfg.block_size)
    num_blocks = scfg.num_blocks or scfg.decode_slots * max_blocks
    s = scfg.decode_slots

    # abstract: the real pool for a 7B model is GBs of zeros — the proof
    # only needs the shapes ServeEngine would feed
    cache = jax.eval_shape(lambda: init_paged_cache(
        model_cfg, num_blocks, scfg.block_size, s, max_blocks))
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
    decode_args = {
        "k": jax.ShapeDtypeStruct(cache.k.shape, cache.k.dtype),
        "v": jax.ShapeDtypeStruct(cache.v.shape, cache.v.dtype),
        "tables": i32(s, max_blocks), "toks": i32(s),
        "positions": i32(s), "rids": i32(s), "tidx": i32(s),
    }
    prefill_args = {
        "k": decode_args["k"], "v": decode_args["v"],
        "tables": i32(s, max_blocks),
        "chunk_ids": i32(s, scfg.prefill_chunk),
        "start_pos": i32(s), "n_valid": i32(s), "rids": i32(s),
        "tidx": i32(s),
    }
    # one signature per program: every shape above is a pure function of
    # (model_cfg, serve_cfg) — request identity, positions, and block
    # tables are DATA; nothing a request can do changes an abstract shape
    sig_d = signature_of(decode_args)
    sig_p = signature_of(prefill_args)
    uncommitted = []
    if params is not None:
        from picotron_tpu.analysis.spec_lint import dict_by_path

        uncommitted = [p for p, leaf in dict_by_path(params).items()
                       if hasattr(leaf, "committed") and not leaf.committed]
        for p in uncommitted:
            rep.add(CHECK, WARNING, f"params/{p}",
                    "serve params leaf is uncommitted — commit via "
                    "generate.place_for_decode (or device_put with an "
                    "explicit sharding) before engine construction")
    proven = not uncommitted
    rep.info[CHECK] = {
        "entry": "serve_decode+prefill",
        "signatures": 1 if proven else 2,
        "proven": proven,
        "decode_leaves": len(sig_d.leaves),
        "prefill_leaves": len(sig_p.leaves),
        "uncommitted": uncommitted,
    }
    return rep


def prove_disagg_programs(model_cfg, serve_cfg=None) -> Report:
    """Static proof for the DISAGGREGATED engine's four device programs
    (serve/disagg.py): the prefill-pool chunk program, the decode-pool
    step program, and the two handoff programs (block gather on the
    prefill placement, sentinel-drop scatter on the decode placement).

    Same argument as prove_serve_programs, per pool: every abstract
    shape is a pure function of (model_cfg, serve_cfg) — pool sizes,
    slot counts, and the fixed [max_blocks] handoff index width are
    config constants, while request identity, positions, block tables,
    and the handoff's actual block ids are DATA. One signature per
    program => each pool compiles exactly once per engine lifetime, so
    a prefill burst cannot trigger a decode-side recompile (nor vice
    versa). With `speculator = "ngram"` the decode-pool program is the
    speculative scan; its ctx buffer is [S, CTX_W] with CTX_W constant,
    so the closure argument is unchanged."""
    import jax.numpy as jnp

    from picotron_tpu.config import ServeConfig
    from picotron_tpu.serve.paged_cache import init_paged_cache
    from picotron_tpu.serve.scheduler import blocks_for

    scfg = serve_cfg or ServeConfig()
    scfg.validate()
    if model_cfg.num_experts:
        raise ValueError(
            "disaggregated serving rejects MoE models (chunked-prefill "
            "expert routing is not parity-guaranteed)")
    rep = Report()
    max_len = scfg.max_model_len or model_cfg.max_position_embeddings
    max_blocks = blocks_for(max_len, scfg.block_size)
    s = scfg.decode_slots
    p = scfg.prefill_slots or s
    num_blocks = scfg.num_blocks or s * max_blocks
    pnum_blocks = scfg.prefill_num_blocks or p * max_blocks

    dcache = jax.eval_shape(lambda: init_paged_cache(
        model_cfg, num_blocks, scfg.block_size, s, max_blocks))
    pcache = jax.eval_shape(lambda: init_paged_cache(
        model_cfg, pnum_blocks, scfg.block_size, p, max_blocks))
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)  # noqa: E731
    sds = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731

    decode_args = {
        "k": sds(dcache.k), "v": sds(dcache.v),
        "tables": i32(s, max_blocks), "toks": i32(s),
        "positions": i32(s), "rids": i32(s), "tidx": i32(s),
    }
    if scfg.speculator == "ngram":
        from picotron_tpu.serve.spec_decode import CTX_W

        decode_args["ctx"] = i32(s, CTX_W)
    prefill_args = {
        "k": sds(pcache.k), "v": sds(pcache.v),
        "tables": i32(p, max_blocks),
        "chunk_ids": i32(p, scfg.prefill_chunk),
        "start_pos": i32(p), "n_valid": i32(p), "rids": i32(p),
        "tidx": i32(p),
    }
    # handoff: gather pulls [max_blocks] block rows from the prefill
    # pool; scatter writes the staged buffer into the decode pool.
    # Index vectors are padded to the constant max_blocks width exactly
    # so a request's block COUNT stays data, not shape.
    buf = jax.ShapeDtypeStruct(
        (pcache.k.shape[0], max_blocks) + tuple(pcache.k.shape[2:]),
        pcache.k.dtype)
    gather_args = {"k": sds(pcache.k), "v": sds(pcache.v),
                   "idx": i32(max_blocks)}
    scatter_args = {"k": sds(dcache.k), "v": sds(dcache.v),
                    "buf_k": buf, "buf_v": buf, "idx": i32(max_blocks)}

    sigs = {
        "prefill_pool": signature_of(prefill_args),
        "decode_pool": signature_of(decode_args),
        "handoff_gather": signature_of(gather_args),
        "handoff_scatter": signature_of(scatter_args),
    }
    rep.info[CHECK] = {
        "entry": "serve_disagg",
        "programs": len(sigs),
        "signatures": {name: len(sig.leaves) for name, sig in sigs.items()},
        "proven": True,
        "prefill_slots": p, "decode_slots": s,
        "speculator": scfg.speculator,
    }
    rep.add(CHECK, INFO, "serve_disagg",
            f"compile-once proven for both pools + handoff: "
            f"{len(sigs)} programs, one closed abstract signature each "
            f"(prefill [{p}, {scfg.prefill_chunk}], decode [{s}], "
            f"handoff idx [{max_blocks}])")
    return rep


# ---------------------------------------------------------------------------
# The check (runner wiring)
# ---------------------------------------------------------------------------


def audit_variants(cfg, *, low=None, menv=None) -> Report:
    """The `variants` check run_shardcheck dispatches: the train-step
    proof, plus the static serve proof when the config's model is
    servable (always — the serve programs depend only on ModelConfig)."""
    rep = prove_train_step(cfg, menv, low=low)
    info = {"train_step": rep.info.get(CHECK, {})}
    if cfg.pipeline.executor == "mpmd":
        # per-stage programs: the host executor's jits live OUTSIDE the
        # (twin-lowered) train_step jit, so they need their own proof
        stage_rep = prove_mpmd_stages(cfg, menv)
        rep.findings.extend(stage_rep.findings)
        info["mpmd_stages"] = stage_rep.info.get(CHECK, {})
    try:
        serve_rep = prove_serve_programs(cfg.model)
        rep.findings.extend(serve_rep.findings)
        info["serve"] = serve_rep.info.get(CHECK, {})
    except Exception as e:  # serve stack optional for exotic models
        info["serve"] = {"unavailable": f"{type(e).__name__}: {e}"}
    try:
        disagg_rep = prove_disagg_programs(cfg.model, cfg.serve)
        rep.findings.extend(disagg_rep.findings)
        info["serve_disagg"] = disagg_rep.info.get(CHECK, {})
    except Exception as e:  # e.g. MoE models: disagg serving rejects them
        info["serve_disagg"] = {"unavailable": f"{type(e).__name__}: {e}"}
    rep.info[CHECK] = info
    return rep
