"""Finding/Report types shared by every shardcheck analyzer.

One flat vocabulary for everything the static-analysis pass can say: a
`Finding` is a single checkable fact gone wrong (or worth surfacing), pinned
to a precise location — a param pytree path, an HLO op, or a source
`file:line` — so the user can act on it without re-deriving where it came
from. A `Report` is an ordered bag of findings plus free-form `info`
tables (collective counts, donation coverage) that render even when
everything is green.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITIES = (ERROR, WARNING, INFO)


@dataclass(frozen=True)
class Finding:
    check: str     # analyzer name: spec_lint | collectives | donation | ...
    severity: str  # error | warning | info
    path: str      # pytree path / op reference / file:line
    message: str   # what is wrong and what would fix it

    def __post_init__(self):
        if self.severity not in _SEVERITIES:
            raise ValueError(f"severity must be one of {_SEVERITIES}, "
                             f"got {self.severity!r}")

    def render(self) -> str:
        return f"[{self.severity:7s}] {self.check}: {self.path}: {self.message}"


@dataclass
class Report:
    """Findings from one analyzer (or, merged, from a whole shardcheck run)."""

    findings: list[Finding] = field(default_factory=list)
    # analyzer-specific summary tables keyed by analyzer name — e.g.
    # {"collectives": {"all_reduce": 3, ...}} — rendered under the findings
    info: dict[str, Any] = field(default_factory=dict)

    def add(self, check: str, severity: str, path: str, message: str) -> None:
        self.findings.append(Finding(check, severity, path, message))

    def extend(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.info.update(other.info)
        return self

    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    def ok(self) -> bool:
        return not self.errors()

    def render(self, *, verbose: bool = False) -> str:
        """Human-readable report: errors first, then warnings; info-level
        findings and the summary tables only under `verbose`."""
        order = {ERROR: 0, WARNING: 1, INFO: 2}
        lines = [f.render() for f in
                 sorted(self.findings, key=lambda f: order[f.severity])
                 if verbose or f.severity != INFO]
        if verbose:
            for name, table in self.info.items():
                lines.append(f"-- {name} --")
                if isinstance(table, dict):
                    lines.extend(f"  {k}: {v}" for k, v in table.items())
                else:
                    lines.append(f"  {table}")
        n_err, n_warn = len(self.errors()), len(self.warnings())
        lines.append(f"shardcheck: {n_err} error(s), {n_warn} warning(s)")
        return "\n".join(lines)

    def raise_if_errors(self) -> None:
        if not self.ok():
            raise ShardcheckError(self)


class ShardcheckError(RuntimeError):
    """Raised by fail-fast consumers (train.py preflight) — carries the full
    report so the error output IS the actionable diagnosis."""

    def __init__(self, report: Report):
        self.report = report
        super().__init__("shardcheck found errors:\n" + report.render())
