"""Collective-schedule audit — read the program's communication off its HLO.

The composed step's collectives are implicit: `lax.psum(..., ('dp','ep',
'cp'))` in the shard_map body, GSPMD-inserted reshardings, pipeline
ppermutes. Whether the schedule is the *intended* one (exactly one grad
all-reduce over the data axes; no all-gather quietly materializing a
replicated tensor bigger than any weight) is checkable by parsing the
lowered module text — no TPU time, no execution.

Two rule families:

- **presence**: the schedule a config promises must exist — a grad-sync
  all-reduce whose replica-group size is dp*ep*cp (the fused data axes),
  a pipeline boundary collective_permute when pp > 1, an expert-dispatch
  all_to_all when ep > 1, the Megatron-SP all-gather/reduce-scatter pair
  over tp under sequence_parallel, the K/V-ring collective_permute when
  cp > 1, and the Ulysses seq<->head all_to_all under attn_impl='ulysses'.
  These rules are grad-engine-independent: the fused engine's manual
  backward (parallel/fused_bwd.py) must lower the same per-axis schedule
  the AD engine's transposes produce, so `grad_engine: fused` configs are
  audited, not skipped.
- **budget** (the accidental-replication detector): no all-gather may
  produce an output larger than the configured byte budget. The default
  budget is the largest thing the program legitimately gathers — the
  biggest single param leaf or one microbatch of full-sequence
  activations, whichever is larger; an all-gather above that is some
  tensor being silently un-sharded.

Collectives over size-1 mesh axes lower to replica groups of size 1 and
cost nothing; the audit counts only *effective* ops (group size > 1, or
any cross-device permute pair).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Optional

import jax

from picotron_tpu.analysis.report import ERROR, INFO, Report

CHECK = "collectives"

KINDS = ("all_reduce", "all_gather", "reduce_scatter", "collective_permute",
         "all_to_all")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
                "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2,
                "ui16": 2, "i8": 1, "ui8": 1, "i1": 1}

# stablehlo: replica_groups = dense<[[0, 1], [2, 3]]> : tensor<GxSxi64>
# (the member payload is kept so the slice-boundary auditor can map each
# participant id to its slice — analysis/boundary.py)
_RE_GROUPS = re.compile(
    r"replica_groups = dense<([^>]*)> : tensor<(\d+)x(\d+)xi64>")
# stablehlo: source_target_pairs = dense<...> : tensor<Nx2xi64>
_RE_PAIRS = re.compile(
    r"source_target_pairs = dense<([^>]*)> : tensor<(\d+)x2xi64>")
# result types: "-> tensor<1x32x64xbf16>" (take the last on the line)
_RE_RESULT = re.compile(r"-> tensor<([0-9x]*)x?([a-z]+[0-9]+|i1)>")
# compiled-HLO dialect (optimized module text): replica_groups={{0,2},{1,3}}
_RE_HLO_GROUPS = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
# compiled-HLO iota form: replica_groups=[2,4]<=[8] -> 2 groups of 4
_RE_HLO_IOTA = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?")
_RE_HLO_PAIRS = re.compile(r"source_target_pairs=\{([^}]*)\}")
_RE_HLO_SHAPE = re.compile(r"=\s*([a-z]+[0-9]+|pred)\[([0-9,]*)\]")


def resolved_grad_engine(cfg) -> str:
    """The grad engine the step actually compiles ('fused'/'ad'/'1f1b'),
    resolving 'auto' exactly like parallel/api.py's _device_grads."""
    from picotron_tpu.parallel.fused_bwd import fused_bwd_supported

    if cfg.distributed.pp_size > 1:
        return cfg.distributed.pp_engine
    t = cfg.training
    if (t.grad_engine == "fused"
            or (t.grad_engine == "auto"
                and t.gradient_accumulation_steps > 1
                and fused_bwd_supported(cfg))):
        return "fused"
    return "ad"


@dataclass(frozen=True)
class CollectiveOp:
    kind: str                       # one of KINDS
    group_size: Optional[int]       # participants per replica group
    n_groups: Optional[int]
    nbytes: Optional[int]           # result size, when parseable
    shape: Optional[tuple]
    dtype: Optional[str]
    line: int                       # 1-based line in the module text
    # replica-group membership, when the dialect spells it out: one tuple
    # of participant ids per group (for collective_permute, one (src, tgt)
    # tuple per hop). None when only the G x S shape was recoverable —
    # consumers (analysis/boundary.py) must treat None as unattributable.
    members: Optional[tuple] = None

    @property
    def effective(self) -> bool:
        """Moves bytes between devices (vs a compiled-away size-1 group)."""
        if self.kind == "collective_permute":
            return (self.n_groups or 0) > 0
        return (self.group_size or 0) > 1


def _dense_members(payload: str, n_groups: int, group_size: int):
    """Member tuples from a StableHLO dense<...> payload, or None.

    Handles the explicit `[[0, 1], [2, 3]]` form and the splat form
    (`dense<0>` for a 1x1 tensor). A payload whose integer count does not
    match G x S (elided printing) yields None.
    """
    ids = [int(t) for t in re.findall(r"-?\d+", payload)]
    if len(ids) == 1 and n_groups * group_size > 1:
        ids = ids * (n_groups * group_size)  # splat
    if len(ids) != n_groups * group_size:
        return None
    return tuple(tuple(ids[g * group_size:(g + 1) * group_size])
                 for g in range(n_groups))


def _iota_members(n_groups: int, group_size: int, dims_txt: str,
                  perm_txt: Optional[str]):
    """Member tuples from the compiled-HLO iota form
    `replica_groups=[G,S]<=[d0,d1,...]` (optionally `T(p0,p1,...)`)."""
    dims = [int(d) for d in dims_txt.split(",") if d]
    n = math.prod(dims)
    if n != n_groups * group_size:
        return None
    ids = list(range(n))
    if perm_txt is not None:
        perm = [int(p) for p in perm_txt.split(",") if p]
        if sorted(perm) != list(range(len(dims))):
            return None
        # reshape iota to `dims`, transpose by `perm`, flatten (row-major)
        strides = [0] * len(dims)
        acc = 1
        for ax in reversed(range(len(dims))):
            strides[ax] = acc
            acc *= dims[ax]
        tdims = [dims[p] for p in perm]
        tstrides = [strides[p] for p in perm]
        out = []
        idx = [0] * len(tdims)
        for _ in range(n):
            out.append(sum(i * s for i, s in zip(idx, tstrides)))
            for ax in reversed(range(len(tdims))):
                idx[ax] += 1
                if idx[ax] < tdims[ax]:
                    break
                idx[ax] = 0
        ids = out
    return tuple(tuple(ids[g * group_size:(g + 1) * group_size])
                 for g in range(n_groups))


def _result_bytes(line: str):
    m = None
    for m in _RE_RESULT.finditer(line):
        pass
    if m is None:
        return None, None, None
    dims_txt, dtype = m.group(1), m.group(2)
    dims = tuple(int(d) for d in dims_txt.split("x") if d) if dims_txt \
        else ()
    nbytes = math.prod(dims) * _DTYPE_BYTES.get(dtype, 4) if dims else \
        _DTYPE_BYTES.get(dtype, 4)
    return nbytes, dims, dtype


def parse_collectives(text: str) -> list[CollectiveOp]:
    """Collective ops from module text — StableHLO (`stablehlo.all_reduce`)
    or compiled HLO (`all-reduce(`); both dialects normalize to KINDS."""
    ops: list[CollectiveOp] = []
    lines = text.splitlines()
    for i, line in enumerate(lines):
        kind = None
        for k in KINDS:
            # compiled HLO spells the opcode hyphenated at its call site
            # (`%ag = f32[8,8]{1,0} all-gather(...)`, async `-start`
            # variant included; `-done` and operand references are not
            # new ops)
            if (f"stablehlo.{k}" in line
                    or re.search(
                        rf"(?<![%a-z-]){k.replace('_', '-')}(?:-start)?\(",
                        line)):
                kind = k
                break
        if kind is None:
            continue
        group_size = n_groups = members = None
        if kind == "collective_permute":
            m = _RE_PAIRS.search(line)
            if m:
                n_groups = int(m.group(2))
                members = _dense_members(m.group(1), n_groups, 2)
            else:
                m = _RE_HLO_PAIRS.search(line)
                if m:
                    pairs = [p.strip("{}") for p in
                             m.group(1).split("},{") if p]
                    n_groups = len(pairs)
                    try:
                        members = tuple(
                            tuple(int(x) for x in p.split(","))
                            for p in pairs)
                    except ValueError:
                        members = None
        else:
            m = _RE_GROUPS.search(line)
            if m:
                n_groups, group_size = int(m.group(2)), int(m.group(3))
                members = _dense_members(m.group(1), n_groups, group_size)
            else:
                m = _RE_HLO_IOTA.search(line)
                if m:
                    n_groups, group_size = int(m.group(1)), int(m.group(2))
                    members = _iota_members(n_groups, group_size,
                                            m.group(3), m.group(4))
                else:
                    m = _RE_HLO_GROUPS.search(line)
                    if m:
                        groups = [g.strip("{}") for g in
                                  m.group(1).split("},{")]
                        n_groups = len(groups)
                        group_size = len(groups[0].split(","))
                        try:
                            members = tuple(
                                tuple(int(x) for x in g.split(","))
                                for g in groups)
                        except ValueError:
                            members = None
        # result type: same line for region-free ops, else the region's
        # closing `}) : (...) -> type` a few lines down
        nbytes = dims = dtype = None
        if "stablehlo" in line:
            if "-> tensor<" in line:
                nbytes, dims, dtype = _result_bytes(line)
            else:
                for j in range(i + 1, min(i + 64, len(lines))):
                    if lines[j].lstrip().startswith("})"):
                        nbytes, dims, dtype = _result_bytes(lines[j])
                        break
        else:
            m = _RE_HLO_SHAPE.search(line)
            if m:
                dtype = m.group(1)
                dims = tuple(int(d) for d in m.group(2).split(",") if d)
                nbytes = math.prod(dims) * _DTYPE_BYTES.get(dtype, 4)
        ops.append(CollectiveOp(kind, group_size, n_groups, nbytes, dims,
                                dtype, i + 1, members))
    return ops


def default_gather_budget(cfg, state) -> int:
    """Largest tensor the program legitimately all-gathers in one op: the
    biggest param leaf, or one microbatch of full-sequence activations
    (sequence-parallel / ulysses gathers restore [mbs, S, H])."""
    from picotron_tpu.models.llama import compute_dtype

    import jax.numpy as jnp

    param_max = max(
        (math.prod(p.shape) * jnp.dtype(p.dtype).itemsize
         for p in jax.tree_util.tree_leaves(state.params)), default=0)
    act = (cfg.training.micro_batch_size * cfg.training.seq_length
           * cfg.model.hidden_size
           * jnp.dtype(compute_dtype(cfg.model)).itemsize)
    return max(param_max, act, 1)


def audit_collectives(cfg, *, text: str = None, state=None,
                      budget_bytes: int = None, menv=None,
                      cost_model=None) -> Report:
    """Audit a config's collective schedule. Pass `text` (+ `state`) to
    audit an existing lowering; otherwise the train step is lowered here.
    With `cost_model` (analysis/cost_model.CostModel), the parsed ops are
    additionally priced against the generation's ICI topology and the
    report's info table gains a `predicted_comm` breakdown — the costed
    ranking tools/shardcheck.py --cost surfaces."""
    if text is None:
        from picotron_tpu.analysis.trace import lower_train_step

        low = lower_train_step(cfg, menv)
        text, state = low.text, low.state
    ops = parse_collectives(text)
    eff = [op for op in ops if op.effective]
    d = cfg.distributed
    rep = Report()

    counts = {k: sum(1 for op in eff if op.kind == k) for k in KINDS}
    rep.info[CHECK] = {
        **counts,
        "total_effective": len(eff),
        "compiled_away (size-1 groups)": len(ops) - len(eff),
        # which grad engine the audited program actually lowered — the
        # fused engine's manual backward must emit the same per-axis
        # schedule as the AD engine (SP reduce-scatter/all-gather pair, CP
        # reverse-ring ppermute, Ulysses all-to-all), so the presence
        # rules below audit `grad_engine: fused` configs instead of
        # skipping them; tests/test_shardcheck.py pins the negative case
        # (a deleted SP reduce-scatter must flag).
        "grad_engine": resolved_grad_engine(cfg),
    }

    # -- presence rules ----------------------------------------------------
    grad_group = d.dp_size * d.ep_size * d.cp_size
    if grad_group > 1:
        grad_ars = [op for op in eff if op.kind == "all_reduce"
                    and op.group_size == grad_group]
        if not grad_ars:
            rep.add(CHECK, ERROR, "all_reduce",
                    f"no all-reduce over the fused data axes found "
                    f"(expected replica groups of size dp*ep*cp = "
                    f"{grad_group}): gradients are NOT being synchronized "
                    f"across data-parallel shards")
        else:
            rep.add(CHECK, INFO, "all_reduce",
                    f"{len(grad_ars)} all-reduce op(s) over the fused data "
                    f"axes (group size {grad_group}) — gradient/loss sync")
    if d.pp_size > 1 and not any(op.kind == "collective_permute"
                                 for op in eff):
        rep.add(CHECK, ERROR, "collective_permute",
                f"pp_size={d.pp_size} but the lowered step contains no "
                f"collective_permute: the pipeline boundary exchange is "
                f"missing")
    if (d.ep_size > 1 and cfg.model.num_experts
            and not any(op.kind == "all_to_all" for op in eff)):
        rep.add(CHECK, ERROR, "all_to_all",
                f"ep_size={d.ep_size} with {cfg.model.num_experts} experts "
                f"but no all_to_all: expert dispatch is not crossing the "
                f"'ep' axis (tokens only ever reach local experts)")

    # per-axis attention/SP schedule (engine-independent: the AD engine's
    # transposes and the fused engine's manual backward must both emit
    # these — a fused config that lost one has a broken segment VJP)
    if d.sequence_parallel and d.tp_size > 1:
        sp_rs = [op for op in eff if op.kind == "reduce_scatter"
                 and op.group_size == d.tp_size]
        sp_ag = [op for op in eff if op.kind == "all_gather"
                 and op.group_size == d.tp_size]
        if not sp_rs:
            rep.add(CHECK, ERROR, "reduce_scatter",
                    f"sequence_parallel with tp_size={d.tp_size} but no "
                    f"reduce-scatter over tp: the Megatron-SP row-parallel "
                    f"exit (g) is missing — partial block outputs are "
                    f"never reduced across tp shards")
        if not sp_ag:
            rep.add(CHECK, ERROR, "all_gather",
                    f"sequence_parallel with tp_size={d.tp_size} but no "
                    f"all-gather over tp: the SP column-parallel entry "
                    f"(f) is missing — the seq-sharded residual stream "
                    f"never re-assembles the full sequence")
        if sp_rs and sp_ag:
            rep.add(CHECK, INFO, "sp_pair",
                    f"SP f/g pair present over tp ({len(sp_ag)} "
                    f"all-gather, {len(sp_rs)} reduce-scatter ops of "
                    f"group size {d.tp_size})")
    # deferred activation sync (parallel/tp_strategies.py): the
    # row-parallel exit psum is rescheduled as a reduce-scatter at the
    # block exit whose gather half is hoisted into the NEXT block's entry
    # — the signature is the same AG/RS pair over tp as Megatron-SP, but
    # it must be present even WITHOUT sequence_parallel. One op of each
    # kind per block boundary in the program text (run_layers rolls the
    # layer loop into a lax.scan, so the per-layer count is structural:
    # the scan body lowers each boundary collective once).
    if d.tp_sync == "deferred" and d.tp_size > 1:
        df_rs = [op for op in eff if op.kind == "reduce_scatter"
                 and op.group_size == d.tp_size]
        df_ag = [op for op in eff if op.kind == "all_gather"
                 and op.group_size == d.tp_size]
        if not df_rs:
            rep.add(CHECK, ERROR, "reduce_scatter",
                    f"tp_sync=deferred with tp_size={d.tp_size} but no "
                    f"reduce-scatter over tp: the deferred schedule's "
                    f"block-exit RS is missing — partial row-parallel "
                    f"outputs are never reduced across tp shards")
        if not df_ag:
            rep.add(CHECK, ERROR, "all_gather",
                    f"tp_sync=deferred with tp_size={d.tp_size} but no "
                    f"all-gather over tp: the gather half hoisted into "
                    f"the next block's entry is missing — the seq-sharded "
                    f"residual stream never re-assembles the full "
                    f"sequence")
        if df_rs and df_ag:
            rep.add(CHECK, INFO, "deferred_pair",
                    f"deferred-sync RS/AG pair present over tp "
                    f"({len(df_ag)} all-gather, {len(df_rs)} "
                    f"reduce-scatter ops of group size {d.tp_size})")

    # non-megatron TP strategies (parallel/tp_strategies.py)
    if d.tp_size > 1 and d.tp_strategy != "megatron":
        from picotron_tpu.config import (
            resolved_tp_mesh, resolved_tp_strategy,
        )

        strat = resolved_tp_strategy(cfg)
        if "2d" in strat.values():
            # the 2d schedule's signature: subgroup collectives — an
            # activation/weight all-gather whose group spans exactly the
            # INNER tp_y factor and a partial-sum all_reduce over the
            # OUTER tp_x factor. (A full-tp all_reduce still legitimately
            # appears for the vocab-parallel CE merge, so only the
            # positive subgroup presences are checkable here; the
            # shardflow provenance rule owns implicit-widening detection.)
            tp_x, tp_y = resolved_tp_mesh(cfg)
            if tp_y > 1 and not any(
                    op.kind == "all_gather" and op.group_size == tp_y
                    for op in eff):
                rep.add(CHECK, ERROR, "all_gather",
                        f"2d tp strategy {tp_x}x{tp_y} but no all-gather "
                        f"of group size {tp_y}: the inner-subgroup "
                        f"activation/weight gather is missing")
            if tp_x > 1 and tp_x != d.tp_size and not any(
                    op.kind == "all_reduce" and op.group_size == tp_x
                    for op in eff):
                rep.add(CHECK, ERROR, "all_reduce",
                        f"2d tp strategy {tp_x}x{tp_y} but no all-reduce "
                        f"of group size {tp_x}: the row-matmul partial "
                        f"sum over the outer subgroup is missing")
        if "row" in (strat["qkv"], strat["up"]):
            # row-first entry: a full-tp psum of the projections; its
            # column-parallel exit re-assembles features via all-gather
            if not any(op.kind == "all_reduce"
                       and op.group_size == d.tp_size for op in eff):
                rep.add(CHECK, ERROR, "all_reduce",
                        f"row-first tp strategy but no all-reduce of "
                        f"group size {d.tp_size}: the block-entry "
                        f"projection psum is missing")
            if not any(op.kind == "all_gather"
                       and op.group_size == d.tp_size for op in eff):
                rep.add(CHECK, ERROR, "all_gather",
                        f"row-first tp strategy but no all-gather of "
                        f"group size {d.tp_size}: the column-parallel "
                        f"exit's feature gather is missing")

    if d.cp_size > 1:
        from picotron_tpu.config import resolved_cp_flavor, resolved_cp_mesh

        flavor = resolved_cp_flavor(cfg)
        if flavor == "ulysses":
            cp_a2a = [op for op in eff if op.kind == "all_to_all"
                      and op.group_size == d.cp_size]
            if not cp_a2a:
                rep.add(CHECK, ERROR, "all_to_all",
                        f"cp flavor 'ulysses' with cp_size={d.cp_size} "
                        f"but no all_to_all of group size {d.cp_size}: "
                        f"the Ulysses seq<->head trade is missing")
        elif flavor == "mesh":
            # the 2D schedule's signature: a head-scatter all_to_all whose
            # group spans exactly the INNER factor and a row ring's
            # collective_permute for the outer factor — each degenerate
            # factorization drops exactly its own requirement
            cp_x, cp_y = resolved_cp_mesh(cfg)
            if cp_y > 1 and not any(
                    op.kind == "all_to_all" and op.group_size == cp_y
                    for op in eff):
                rep.add(CHECK, ERROR, "all_to_all",
                        f"mesh cp flavor {cp_x}x{cp_y} but no all_to_all "
                        f"of group size {cp_y}: the head scatter over the "
                        f"inner submesh factor is missing")
            if cp_x > 1 and not any(op.kind == "collective_permute"
                                    for op in eff):
                rep.add(CHECK, ERROR, "collective_permute",
                        f"mesh cp flavor {cp_x}x{cp_y} but the lowered "
                        f"step contains no collective_permute: the row "
                        f"ring over the outer submesh factor is missing")
            if cp_x > 1 and cp_y > 1 and any(
                    op.kind == "all_to_all" and op.group_size == d.cp_size
                    for op in eff):
                rep.add(CHECK, ERROR, "all_to_all",
                        f"mesh cp flavor {cp_x}x{cp_y} but an all_to_all "
                        f"spans the FULL cp axis (group size {d.cp_size}): "
                        f"an implicit reshard widened the 2D schedule's "
                        f"subgroup collective")
        elif not any(op.kind == "collective_permute" for op in eff):
            rep.add(CHECK, ERROR, "collective_permute",
                    f"cp_size={d.cp_size} (ring attention) but the "
                    f"lowered step contains no collective_permute: the "
                    f"K/V ring is missing")

    # -- budget rule: the accidental-replication detector ------------------
    if budget_bytes is None and state is not None:
        budget_bytes = default_gather_budget(cfg, state)
    if budget_bytes is not None:
        for op in eff:
            if op.kind != "all_gather" or op.nbytes is None:
                continue
            if op.nbytes > budget_bytes:
                rep.add(CHECK, ERROR, f"all_gather@L{op.line}",
                        f"all-gather output {op.dtype}{list(op.shape)} is "
                        f"{op.nbytes} bytes, over the replication budget "
                        f"of {budget_bytes} bytes — something sharded is "
                        f"being materialized fully replicated")
        rep.info[CHECK]["gather_budget_bytes"] = budget_bytes

    # -- optional ICI cost pricing ----------------------------------------
    if cost_model is not None:
        priced = cost_model.price_ops(cfg, eff)
        by_kind: dict = {}
        for p in priced:
            by_kind[p["kind"]] = by_kind.get(p["kind"], 0.0) + p["secs"]
        rep.info[CHECK]["predicted_comm"] = {
            "generation": cost_model.gen.name,
            "total_ms": round(sum(p["secs"] for p in priced) * 1e3, 4),
            "by_kind_ms": {k: round(v * 1e3, 4)
                           for k, v in sorted(by_kind.items())},
            "unattributed_ops": sum(1 for p in priced if p["axis_guess"]),
        }
    return rep
