"""Donation and recompilation hazards — trace-time, no execution.

Two failure modes this catches have each cost real TPU hours:

- **Undonated state.** The train step is written `jit(donate_argnums=(0,))`
  so every TrainState buffer is updated in place; losing donation on any
  leaf (a refactor that reorders arguments, a leaf the aliaser cannot
  match) silently doubles that leaf's residency — at SmolLM-1.7B scale the
  difference between fitting and OOM. The lowered module records donation
  per argument (`jax.buffer_donor` / `tf.aliasing_output` attributes);
  this check walks the argument list against the flattened (state, batch)
  pytree and names every state leaf that lost it.

- **Unstable step signature.** jit retraces whenever an input aval changes.
  If the step's *output* state differs from its input state in any aval
  (a weak-typed scalar from a Python-float closure, an int32 counter
  promoted to int64, a dtype change on one leaf), step 2 sees new input
  avals and recompiles — the classic silent 2x compile. `jax.eval_shape`
  over the jitted step makes this a pure host check: output avals must be
  identical to input avals, leaf for leaf.
"""

from __future__ import annotations

import re

import jax

from picotron_tpu.analysis.report import ERROR, WARNING, Report
from picotron_tpu.analysis.spec_lint import dict_by_path

DONATION = "donation"
STABILITY = "recompile"

_DONOR_MARKS = ("jax.buffer_donor", "tf.aliasing_output")


def _main_signature(text: str) -> str:
    """The argument list of the module's public main func — characters
    between '@main(' and its matching ')', quote-aware (sharding strings
    contain parentheses)."""
    start = text.find("@main(")
    if start < 0:
        return ""
    i = start + len("@main(")
    depth, in_str = 1, False
    out = []
    while i < len(text) and depth:
        c = text[i]
        if in_str:
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
        elif c in "([{<":
            depth += 1
        elif c in ")]}>":
            depth -= 1
            if depth == 0:
                break
        out.append(c)
        i += 1
    return "".join(out)


def parse_arg_donation(text: str) -> list[bool]:
    """Per-argument donation flags of the lowered module's main func, in
    argument order (== the flattened input pytree order)."""
    sig = _main_signature(text)
    flags: dict[int, bool] = {}
    # each arg: %argN: tensor<...> {attrs} — attrs optional
    for m in re.finditer(r"%arg(\d+):", sig):
        idx = int(m.group(1))
        nxt = sig.find("%arg", m.end())
        seg = sig[m.end(): nxt if nxt >= 0 else len(sig)]
        flags[idx] = any(mark in seg for mark in _DONOR_MARKS)
    return [flags[i] for i in sorted(flags)]


def check_donation(lowered, state=None, batch=None) -> Report:
    """Every TrainState leaf must be donated; batch leaves must not be.

    Pass the `jax.stages.Lowered` when available — its `args_info` pytree
    carries per-input donation with the original structure, immune to the
    lowering pruning unused arguments. A raw StableHLO string falls back to
    parsing the module's argument attributes (then `state`/`batch` supply
    the leaf paths, in flattened argument order).
    """
    if hasattr(lowered, "args_info"):
        return _check_donation_args_info(lowered.args_info)
    return _check_donation_text(lowered, state, batch)


def _check_donation_args_info(args_info) -> Report:
    rep = Report()
    # args_info mirrors the traced call: ((state, batch), kwargs)
    (state_info, batch_info), _kwargs = args_info
    state_leaves = dict_by_path(state_info)
    n_state = len(state_leaves)
    for path, info in state_leaves.items():
        if not info.donated:
            rep.add(DONATION, ERROR, path,
                    f"TrainState buffer ({info.dtype}{list(info.shape)}) "
                    f"is not donated: the step holds input AND output "
                    f"copies alive simultaneously — re-check "
                    f"donate_argnums and that this leaf round-trips the "
                    f"step with identical shape/dtype")
    for path, info in dict_by_path(batch_info).items():
        if info.donated:
            rep.add(DONATION, WARNING, path,
                    "batch input is donated — batches are rebuilt each "
                    "step so this is harmless, but it suggests "
                    "donate_argnums drifted")
    rep.info[DONATION] = {
        "state_leaves": n_state,
        "donated": sum(1 for i in state_leaves.values() if i.donated),
    }
    return rep


def _check_donation_text(text: str, state, batch) -> Report:
    rep = Report()
    flags = parse_arg_donation(text)
    state_leaves = dict_by_path(state)
    batch_leaves = dict_by_path(batch)
    n_state, n_batch = len(state_leaves), len(batch_leaves)
    if len(flags) != n_state + n_batch:
        # argument list does not line up with the input pytree (constants
        # promoted to args, or a future lowering change) — report coverage
        # coarsely rather than mis-attribute leaves
        donated = sum(flags)
        rep.add(DONATION, WARNING, "<main>",
                f"argument count {len(flags)} != state+batch leaves "
                f"{n_state + n_batch}; coarse check only ({donated} of "
                f"{len(flags)} args donated)")
        if donated < n_state:
            rep.add(DONATION, ERROR, "<main>",
                    f"only {donated} donated arguments for {n_state} state "
                    f"leaves — at least one TrainState buffer is undonated "
                    f"(its memory is held twice across the step)")
        return rep
    paths = list(state_leaves) + list(batch_leaves)
    undonated = [p for p, f in zip(paths[:n_state], flags[:n_state])
                 if not f]
    for p in undonated:
        leaf = state_leaves[p]
        rep.add(DONATION, ERROR, p,
                f"TrainState buffer ({leaf.dtype}{list(leaf.shape)}) is "
                f"not donated: the step holds input AND output copies "
                f"alive simultaneously — re-check donate_argnums and that "
                f"this leaf round-trips the step with identical "
                f"shape/dtype")
    donated_batch = [p for p, f in
                     zip(paths[n_state:], flags[n_state:]) if f]
    for p in donated_batch:
        rep.add(DONATION, WARNING, p,
                "batch input is donated — batches are rebuilt each step so "
                "this is harmless, but it suggests donate_argnums drifted")
    rep.info[DONATION] = {
        "state_leaves": n_state,
        "donated": sum(flags[:n_state]),
    }
    return rep


def check_state_stability(step_fn, state, batch) -> Report:
    """The step's output state avals must equal its input state avals —
    anything else forces a retrace + recompile on the next call."""
    rep = Report()
    out = jax.eval_shape(step_fn, state, batch)
    new_state = out[0] if isinstance(out, tuple) else out
    in_struct = jax.tree_util.tree_structure(state)
    out_struct = jax.tree_util.tree_structure(new_state)
    if in_struct != out_struct:
        rep.add(STABILITY, ERROR, "<state>",
                f"output state pytree structure differs from input "
                f"({out_struct} vs {in_struct}): every step retraces")
        return rep
    ins = dict_by_path(state)
    outs = dict_by_path(new_state)
    for path, a in ins.items():
        b = outs[path]
        if tuple(a.shape) != tuple(b.shape) or a.dtype != b.dtype:
            rep.add(STABILITY, ERROR, path,
                    f"state leaf changes aval across the step: "
                    f"{a.dtype}{list(a.shape)} in, {b.dtype}{list(b.shape)}"
                    f" out — step 2 recompiles the whole program")
        elif getattr(a, "weak_type", False) != getattr(b, "weak_type",
                                                       False):
            rep.add(STABILITY, ERROR, path,
                    "state leaf flips weak_type across the step (a Python "
                    "scalar leaked into the update math): step 2 "
                    "recompiles; wrap the scalar in jnp.asarray with an "
                    "explicit dtype")
    # metrics: weak types here do not recompile (metrics are outputs only)
    # but reveal Python-scalar closures worth pinning
    if isinstance(out, tuple) and len(out) > 1:
        for path, leaf in dict_by_path(out[1]).items():
            if getattr(leaf, "weak_type", False):
                rep.add(STABILITY, WARNING, f"metrics/{path}",
                        "weak-typed metric (Python scalar reached the "
                        "traced output); benign, but pin its dtype")
    rep.info[STABILITY] = {"state_leaves": len(ins)}
    return rep
