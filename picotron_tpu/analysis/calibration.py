"""Fit + validate the cost model against the measured rows on disk.

The repo carries real measured step times: the per-round benchmark sweeps
(SWEEP_r03–r05.jsonl), single-run BENCH_*.json rows, and — when a run has
one — the per-phase timings in telemetry.jsonl. This module turns those
into (config, measured tokens/s) pairs, fits the Calibration constants the
rows can pin down (dense-matmul efficiency curve, attention efficiency,
offload PCIe bandwidth — all the sweep rows are single-chip, so the ICI
side stays analytic until TPU access returns; PERF.md documents that
protocol), and scores rank agreement: the cost model's one job is ordering
layouts, so the metric is Spearman correlation between predicted and
measured tokens/s within each sweep round.

`mfu_<Model>-<L>L_seq<S>` metric names carry the model shape; mbs /
grad-acc / offload come from the row's `config` string when present
(r05+) and otherwise from the benchmark matrix those rounds ran
(bench.py SWEEP — frozen here as _LEGACY_SWEEP so old rows stay
interpretable even if the live matrix moves).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass
from typing import Iterable, Optional

from picotron_tpu.analysis.cost_model import (
    Calibration, CostModel, DEFAULT_CALIBRATION, spearman,
)
from picotron_tpu.config import (
    Config, ModelConfig, TrainingConfig, resolve_preset,
)

_RE_METRIC = re.compile(r"^mfu_(.+)-(\d+)L_seq(\d+)")

# (model, layers, seq) -> training knobs, for rows predating the per-row
# `config` string. Mirrors the bench.py SWEEP matrix as run in r03/r04.
_LEGACY_SWEEP: dict[tuple, dict] = {
    ("SmolLM-360M", 32, 2048): dict(mbs=6, ga=1),
    ("SmolLM-1.7B", 8, 4096): dict(mbs=2, ga=1),
    ("SmolLM-1.7B", 4, 16384): dict(mbs=1, ga=1),
    ("SmolLM-1.7B", 8, 2048): dict(mbs=5, ga=1),
    ("SmolLM-1.7B", 24, 4096): dict(mbs=1, ga=64, offload=True,
                                    remat_policy="dots_attn"),
    ("SmolLM-1.7B", 24, 2048): dict(mbs=2, ga=64, offload=True,
                                    remat_policy="dots_attn"),
    ("Llama-2-7B", 4, 4096): dict(mbs=2, ga=16, offload=True,
                                  remat_policy="dots_attn"),
    ("Mixtral-8x7B", 1, 2048): dict(mbs=2, ga=64, offload=True,
                                    remat_policy="dots"),
}


@dataclass(frozen=True)
class MeasuredPoint:
    """One measured configuration: the Config it ran and what it achieved."""

    cfg: Config
    tokens_per_sec_per_chip: float
    metric: str
    source: str      # file the row came from (its round groups rankings)
    mfu: Optional[float] = None


def _parse_config_string(s: str) -> dict:
    """mbs/ga/offload/remat out of an r05-style row config string like
    'mbs3 ga43 dots_attn offload + fused grad engine'."""
    out: dict = {}
    m = re.search(r"\bmbs(\d+)\b", s)
    if m:
        out["mbs"] = int(m.group(1))
    m = re.search(r"\bga(\d+)\b", s)
    if m:
        out["ga"] = int(m.group(1))
    if "offload" in s:
        out["offload"] = True
    for pol in ("dots_attn", "dots_norms", "dots_lean", "dots_offload",
                "dots", "full"):
        if re.search(rf"\b{pol}\b", s):
            out["remat_policy"] = pol
            break
    return out


def row_to_point(row: dict, source: str) -> Optional[MeasuredPoint]:
    """A SWEEP/BENCH JSON row -> MeasuredPoint, or None for rows that are
    not mfu measurements (decode rows, error rows)."""
    metric = row.get("metric", "")
    m = _RE_METRIC.match(metric)
    tps = row.get("tokens_per_sec_per_chip")
    if not m or not isinstance(tps, (int, float)) or tps <= 0:
        return None
    model, layers, seq = m.group(1), int(m.group(2)), int(m.group(3))
    try:
        preset = resolve_preset(model)
    except KeyError:
        return None
    knobs = dict(_LEGACY_SWEEP.get((model, layers, seq), {}))
    knobs.update(_parse_config_string(row.get("config", "")))
    preset["num_hidden_layers"] = layers
    preset["max_position_embeddings"] = max(
        preset.get("max_position_embeddings", seq), seq)
    cfg = Config(
        model=ModelConfig(name=model, **preset),
        training=TrainingConfig(
            seq_length=seq,
            micro_batch_size=knobs.get("mbs", 1),
            gradient_accumulation_steps=knobs.get("ga", 1),
            optimizer_offload=knobs.get("offload", False),
            remat_policy=knobs.get("remat_policy", "dots"),
            adam_moments_dtype="bfloat16",  # the bench default
        ),
    )
    cfg.validate()
    return MeasuredPoint(cfg, float(tps), metric, source,
                         mfu=row.get("value"))


def load_measured_rows(paths: Optional[Iterable[str]] = None,
                       root: Optional[str] = None) -> list[MeasuredPoint]:
    """MeasuredPoints from SWEEP_*.jsonl (one row per line) and
    BENCH_*.json (the driver wrapper whose `tail` holds the bench output)
    files. Default: every SWEEP_r*.jsonl in `root` (the repo root)."""
    if paths is None:
        root = root or _repo_root()
        paths = sorted(
            os.path.join(root, f) for f in os.listdir(root)
            if re.match(r"SWEEP_r\d+\.jsonl$", f))
    points = []
    for path in paths:
        name = os.path.basename(path)
        with open(path) as f:
            text = f.read()
        for line in text.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            # BENCH_*.json wrappers nest the row under "parsed"
            if isinstance(row.get("parsed"), dict):
                row = row["parsed"]
            pt = row_to_point(row, name)
            if pt is not None:
                points.append(pt)
    return points


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Fitting
# ---------------------------------------------------------------------------


def _sq_log_err(model: CostModel, points: list[MeasuredPoint]) -> float:
    import math

    err = 0.0
    for p in points:
        pred = model.predict(p.cfg).tokens_per_sec_per_chip
        err += math.log(pred / p.tokens_per_sec_per_chip) ** 2
    return err / len(points)


def fit_calibration(points: list[MeasuredPoint],
                    generation: str = "v5e",
                    start: Calibration = DEFAULT_CALIBRATION,
                    rounds: int = 3) -> Calibration:
    """Coordinate-descent least squares (on log step time) over the four
    constants single-chip rows can identify: eff_max, h_half, eff_attn,
    pcie_bandwidth. Deterministic and dependency-free — a few hundred
    analytic predictions, well under a second."""
    if not points:
        return start
    from dataclasses import replace

    space = {
        "eff_max": [start.eff_max * f for f in
                    (0.85, 0.95, 1.0, 1.05, 1.15)],
        "h_half": [start.h_half * f for f in (0.6, 0.8, 1.0, 1.25, 1.6)],
        "eff_attn": [0.28, 0.34, 0.40, 0.48, 0.58],
        "pcie_bandwidth": [start.pcie_bandwidth * f for f in
                           (0.6, 0.8, 1.0, 1.3, 1.7)],
    }
    best = start
    best_err = _sq_log_err(CostModel(generation, best), points)
    for _ in range(rounds):
        for key, grid in space.items():
            for val in grid:
                cand = replace(best, **{key: val})
                err = _sq_log_err(CostModel(generation, cand), points)
                if err < best_err - 1e-12:
                    best, best_err = cand, err
    return best


# ---------------------------------------------------------------------------
# Validation: per-round rank agreement
# ---------------------------------------------------------------------------


def rank_agreement(points: list[MeasuredPoint],
                   model: Optional[CostModel] = None) -> dict:
    """Spearman correlation between predicted and measured tokens/s/chip,
    per source file (rounds are ranked internally — cross-round rows mix
    code versions) plus pooled. Sources with < 3 rows are skipped."""
    model = model or CostModel("v5e")
    by_src: dict[str, list[MeasuredPoint]] = {}
    for p in points:
        by_src.setdefault(p.source, []).append(p)
    out: dict = {"per_round": {}, "rows": []}
    all_pred, all_meas = [], []
    for src, pts in sorted(by_src.items()):
        pred = [model.predict(p.cfg).tokens_per_sec_per_chip for p in pts]
        meas = [p.tokens_per_sec_per_chip for p in pts]
        for p, pr in zip(pts, pred):
            out["rows"].append({
                "metric": p.metric, "source": src,
                "measured_tps_chip": round(p.tokens_per_sec_per_chip, 1),
                "predicted_tps_chip": round(pr, 1),
            })
        all_pred += pred
        all_meas += meas
        if len(pts) >= 3:
            out["per_round"][src] = round(spearman(pred, meas), 4)
    if len(all_meas) >= 3:
        out["pooled"] = round(spearman(all_pred, all_meas), 4)
    vals = out["per_round"].values()
    out["min_per_round"] = min(vals) if vals else None
    return out


# ---------------------------------------------------------------------------
# Telemetry-stream calibration hooks
# ---------------------------------------------------------------------------


def measured_step_seconds(events: list[dict]) -> Optional[dict]:
    """Per-step phase medians out of a telemetry.jsonl event list (the
    tools/telemetry_report.py schema): {'step_s': median step-phase secs,
    'sync_s': median sync-phase secs} — the measured side the `comm` row
    compares the model against, and a per-run calibration residual."""
    phases: dict[str, list[float]] = {}
    for e in events:
        if e.get("kind") == "phase" and isinstance(e.get("secs"),
                                                   (int, float)):
            phases.setdefault(e.get("phase", "?"), []).append(e["secs"])

    def median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else None

    if not phases.get("step"):
        return None
    return {"step_s": median(phases["step"]),
            "sync_s": median(phases.get("sync", [])),
            "n_steps": len(phases["step"])}
