"""Sharding-dataflow audit — WHY each collective exists, not just whether.

The collective-schedule audit (analysis/collectives.py) reads the lowered
module and checks the schedule a layout promises; it cannot say which
source op minted an op, nor spot a collective the *compiler* invented. Both
gaps matter under GSPMD: wherever two PartitionSpecs disagree, the
partitioner silently inserts resharding collectives — the all-gather-
minting smell — and the only runtime symptom is a slower step. This module
closes the loop at trace time:

- **Provenance** (`collect_sites`): walk the traced step's jaxpr —
  recursively through pjit/scan/shard_map/remat/custom-vjp sub-jaxprs —
  and record every collective primitive as a `CollectiveSite`: normalized
  kind, mesh axes, the `picotron_tpu/file:line` that issued it (from the
  equation's traceback), and the root state/batch pytree paths whose data
  feeds it (def-use propagation from the jaxpr's invars).
- **Attribution** (`attribute_collectives`): match the lowered module's
  parsed `CollectiveOp`s back to sites by kind + expected replica-group
  size (the product of the site's mesh-axis sizes). A lowered collective
  no site explains is *implicit* — GSPMD-minted, not authored.
- **Classification** (`intended_rule`): a site is *intended* when it
  matches the schedule contract collectives.py audits for presence —
  data-axes grad/loss sync, TP boundary psum, the Megatron-SP f/g pair,
  ring/pipeline permutes, expert or Ulysses all_to_alls, the ZeRO-1
  shard round-trip. Anything else is surfaced for a human.
- **Boundary reshards** (`predict_boundary_reshards`): compare each
  top-level input's *declared* sharding (the abstract state/batch leaves
  carry NamedShardings) against the partitioning the program *uses* at
  shard_map entries and `sharding_constraint` ops. A mismatch is a
  reshard GSPMD must mint; the finding names the exact spec change that
  removes it. The predicted volume (full logical tensor bytes) feeds the
  ICI cost model so the planner prices unintended collectives the same
  as intended ones.

`audit_dataflow` composes all four into the `provenance` check that
run_shardcheck / tools/shardcheck.py --provenance / the train.py preflight
run. Findings are warnings, never errors: an implicit reshard is a perf
smell to burn down, not a correctness failure.

Everything here is host-only abstract analysis; the one optional
exception is `compiled_collectives` (compile the lowering and diff the
optimized module's collectives against the StableHLO's), which tests use
to confirm a predicted reshard really makes the partitioner mint ops.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

from picotron_tpu.analysis.collectives import parse_collectives
from picotron_tpu.analysis.report import INFO, WARNING, Report

CHECK = "provenance"

# jaxpr collective primitive -> the normalized collectives.KINDS vocabulary
_PRIM_KINDS = {
    "psum": "all_reduce",
    "pmean": "all_reduce",
    "pmax": "all_reduce",
    "pmin": "all_reduce",
    "all_gather": "all_gather",
    "pgather": "all_gather",
    "psum_scatter": "reduce_scatter",
    "reduce_scatter": "reduce_scatter",
    "ppermute": "collective_permute",
    "pshuffle": "collective_permute",
    "all_to_all": "all_to_all",
}

_PKG_MARKER = "picotron_tpu" + os.sep


@dataclass(frozen=True)
class CollectiveSite:
    """One collective primitive in the traced program, with provenance."""

    kind: str        # normalized: a collectives.KINDS member
    primitive: str   # the jaxpr primitive name (psum, ppermute, ...)
    axes: tuple      # mesh axis names the op spans
    source: str      # 'picotron_tpu/<file>:<line>' that issued it
    scope: str       # enclosing function name (+ name_stack when present)
    roots: tuple     # root state/batch paths whose data feeds the op
    group: int = 0   # axis_index_groups subgroup size (0 = the full axis
    #                  — the mesh cp flavor's 2D schedule subgroups its
    #                  collectives, so the lowered replica group is
    #                  smaller than the named axis)

    def describe(self, max_roots: int = 3) -> str:
        roots = ", ".join(self.roots[:max_roots])
        if len(self.roots) > max_roots:
            roots += f", +{len(self.roots) - max_roots} more"
        return (f"{self.primitive}{self.axes} at {self.source} "
                f"[{self.scope}] <- {roots or '<constants>'}")


@dataclass(frozen=True)
class BoundaryReshard:
    """A predicted GSPMD-minted reshard: declared spec != used spec."""

    path: str        # root pytree path of the mismatched input
    declared: str    # PartitionSpec the caller committed the array with
    used: str        # partitioning the program applies at the boundary
    source: str      # boundary location ('shard_map@...' / file:line)
    nbytes: int      # full logical tensor size (the reshard volume)
    fix: str         # the spec change that removes the reshard


# ---------------------------------------------------------------------------
# jaxpr walk: sites + root-path provenance
# ---------------------------------------------------------------------------


def _axis_names(params: dict) -> tuple:
    axes = params.get("axes", params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _site_location(eqn) -> tuple:
    """('picotron_tpu/<file>:<line>', scope) from the equation's traceback —
    the first frame inside the package is the line that issued the op."""
    tb = getattr(eqn.source_info, "traceback", None)
    frames = getattr(tb, "frames", None) or ()
    for f in frames:
        if _PKG_MARKER in f.file_name:
            rel = f.file_name.rsplit(_PKG_MARKER, 1)[-1]
            scope = f.function_name
            stack = str(getattr(eqn.source_info, "name_stack", "") or "")
            if stack:
                scope = f"{scope}/{stack}"
            return f"picotron_tpu/{rel}:{f.line_num}", scope
    return "<outside picotron_tpu>", "<unknown>"


def _sub_jaxprs(value):
    """Open jaxprs reachable from one eqn param value (ClosedJaxpr
    unwrapped; tuples/lists of jaxprs flattened; everything else ignored)."""
    if hasattr(value, "eqns") and hasattr(value, "invars"):
        yield value
    elif hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        yield value.jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


def _read(env: dict, atom) -> frozenset:
    # a Literal carries its value inline and feeds no provenance
    if hasattr(atom, "val"):
        return frozenset()
    return env.get(atom, frozenset())


def _walk(jaxpr, env: dict, sites: list) -> list:
    """Record collective sites; propagate root-path provenance var→var.
    Returns the provenance of `jaxpr`'s outvars (for sub-jaxpr mapping)."""
    for eqn in jaxpr.eqns:
        in_provs = [_read(env, a) for a in eqn.invars]
        in_prov = frozenset().union(*in_provs) if in_provs else frozenset()
        name = eqn.primitive.name
        if name in _PRIM_KINDS:
            src, scope = _site_location(eqn)
            groups = eqn.params.get("axis_index_groups")
            sites.append(CollectiveSite(
                _PRIM_KINDS[name], name, _axis_names(eqn.params),
                src, scope, tuple(sorted(in_prov)),
                len(groups[0]) if groups else 0))
        subs = [s for v in eqn.params.values() for s in _sub_jaxprs(v)]
        for sub in subs:
            inner: dict = {}
            n_in = len(sub.invars)
            if n_in == len(eqn.invars):
                for v, p in zip(sub.invars, in_provs):
                    inner[v] = p
            elif n_in < len(eqn.invars):
                # cond/while: operands trail per-branch constants — align
                # the sub's invars to the LAST n outer invars (exact for
                # the common while-body case; an over-approximation is
                # applied below when even that cannot line up)
                for v, p in zip(sub.invars, in_provs[len(in_provs) - n_in:]):
                    inner[v] = p
            else:
                for v in sub.invars:
                    inner[v] = in_prov
            for v in getattr(sub, "constvars", ()):
                inner.setdefault(v, frozenset())
            out_prov = _walk(sub, inner, sites)
            if len(out_prov) == len(eqn.outvars):
                for v, p in zip(eqn.outvars, out_prov):
                    env[v] = env.get(v, frozenset()) | p
            else:
                for v in eqn.outvars:
                    env[v] = env.get(v, frozenset()) | in_prov
        if not subs:
            for v in eqn.outvars:
                env[v] = in_prov
    return [_read(env, a) for a in jaxpr.outvars]


def root_paths(state, batch) -> list:
    """Flattened (state, batch) leaf paths in jaxpr-invar order, prefixed
    'state/' / 'batch/' — the provenance vocabulary every site reports."""
    from picotron_tpu.analysis.spec_lint import dict_by_path

    return ([f"state/{p}" for p in dict_by_path(state)]
            + [f"batch/{p}" for p in dict_by_path(batch)])


def collect_sites(closed_jaxpr, paths) -> list:
    """Every collective site in a ClosedJaxpr, with provenance from the
    top-level invars labeled by `paths` (see `root_paths`)."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    env: dict = {}
    for var, path in zip(jaxpr.invars, paths):
        env[var] = frozenset([path])
    for var in jaxpr.constvars:
        env[var] = frozenset()
    sites: list = []
    _walk(jaxpr, env, sites)
    return sites


# ---------------------------------------------------------------------------
# Attribution: lowered ops <-> sites
# ---------------------------------------------------------------------------


def _axis_sizes(cfg) -> dict:
    d = cfg.distributed
    return {"dp": d.dp_size, "pp": d.pp_size, "ep": d.ep_size,
            "cp": d.cp_size, "tp": d.tp_size}


def attribute_collectives(cfg, sites, ops) -> tuple:
    """Match effective lowered ops to sites by (kind, replica-group size).

    Group collectives match a site whose mesh-axis-size product equals the
    op's replica-group size; permutes (pair lists, not groups) match any
    effective permute site. Many ops map to one site (a scanned layer
    issues its psum once per iteration), so matching is count-based and
    round-robins across equally-plausible sites. Returns
    (attributed [(op, site)], implicit [op])."""
    sizes = _axis_sizes(cfg)
    by_key: dict = {}
    for s in sites:
        g = s.group or math.prod(sizes.get(a, 1) for a in s.axes)
        by_key.setdefault((s.kind, g), []).append(s)
    permute_sites = [s for s in sites if s.kind == "collective_permute"
                     and math.prod(sizes.get(a, 1) for a in s.axes) > 1]
    rr: dict = {}
    attributed, implicit = [], []
    for op in ops:
        if op.kind == "collective_permute":
            cands = permute_sites
            key = ("collective_permute", None)
        else:
            key = (op.kind, op.group_size)
            cands = by_key.get(key, [])
        if cands:
            i = rr.get(key, 0)
            attributed.append((op, cands[i % len(cands)]))
            rr[key] = i + 1
        else:
            implicit.append(op)
    return attributed, implicit


def intended_rule(cfg, site) -> str:
    """The schedule-contract rule a site satisfies (None = unexplained) —
    mirrors the presence rules audit_collectives enforces on the text."""
    d = cfg.distributed
    ax = set(site.axes)
    if not ax:
        return None
    tp2d = False
    if d.tp_size > 1 and d.tp_strategy not in ("megatron", ""):
        from picotron_tpu.config import resolved_tp_strategy

        tp2d = "2d" in resolved_tp_strategy(cfg).values()
    if site.kind == "all_reduce":
        if ax <= {"dp", "ep", "cp"}:
            return "data-axes grad/loss sync"
        if ax == {"tp"}:
            # covers the megatron boundary psum, the vocab-parallel CE
            # merge, the row-first block-entry projection psum, and —
            # when the site carries an axis_index_groups subgroup — the
            # 2d strategy's partial sum over the outer tp_x factor
            if tp2d and site.group:
                return "2d TP outer-subgroup psum"
            return "TP boundary psum"
        if ax == {"pp"} and d.pp_size > 1:
            # per-stage loss stats and pp-replicated params (embedding /
            # final norm / lm_head) assemble their disjoint partials over
            # the stage axis (parallel/pp.py sync_pp_replicated_grads)
            return "pp replicated-grad/loss-stat sync"
    from picotron_tpu.config import resolved_cp_flavor

    mesh_cp = d.cp_size > 1 and resolved_cp_flavor(cfg) == "mesh"
    if site.kind in ("all_gather", "reduce_scatter"):
        if ax == {"tp"} and d.sequence_parallel:
            return "Megatron-SP f/g pair"
        if ax == {"tp"} and d.tp_sync == "deferred":
            # the rescheduled row-parallel exit: RS at the block exit,
            # gather hoisted to the next block's entry
            return "deferred-sync RS/AG pair"
        if ax == {"tp"} and tp2d and site.group:
            return "2d TP inner-subgroup gather"
        if ax == {"tp"} and d.tp_strategy not in ("megatron", ""):
            # row-first column-parallel exit re-assembling features (and
            # the AD transposes of the entry psum)
            return "TP strategy feature gather"
        if ax == {"dp"} and d.zero1:
            return "ZeRO-1 shard round-trip"
        if ax == {"cp"} and mesh_cp and site.group:
            return "mesh row position gather"
    if site.kind == "collective_permute":
        if ax == {"cp"}:
            return ("mesh row-ring K/V shift" if mesh_cp
                    else "ring-attention K/V shift")
        if ax == {"pp"}:
            return "pipeline boundary exchange"
    if site.kind == "all_to_all":
        if ax == {"ep"}:
            return "expert dispatch/combine"
        if ax == {"cp"}:
            return ("mesh head scatter (cp_y subgroup)"
                    if mesh_cp and site.group
                    else "Ulysses seq<->head trade")
    return None


# ---------------------------------------------------------------------------
# Boundary reshards: declared spec vs used spec
# ---------------------------------------------------------------------------


def _spec_str(spec) -> str:
    return str(tuple(spec)) if spec is not None else "()"


def _names_to_spec(names: dict, rank: int) -> tuple:
    """A shard_map in_names dict ({dim: (axes,)}) as a PartitionSpec-shaped
    tuple of length `rank`."""
    out = []
    for dim in range(rank):
        axes = tuple(names.get(dim, ()))
        out.append(axes[0] if len(axes) == 1 else (axes if axes else None))
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def _declared_specs(state, batch, paths) -> dict:
    """path -> (declared spec tuple, nbytes) for every top-level leaf that
    carries a NamedSharding (abstract leaves from init_sharded_state)."""
    import jax
    import jax.numpy as jnp

    out = {}
    leaves = jax.tree_util.tree_leaves((state, batch))
    for path, leaf in zip(paths, leaves):
        sh = getattr(leaf, "sharding", None)
        spec = getattr(sh, "spec", None)
        if spec is None:
            continue
        nbytes = (math.prod(leaf.shape)
                  * jnp.dtype(leaf.dtype).itemsize) if leaf.shape else 0
        out[path] = (tuple(spec), nbytes)
    return out


def _norm(spec: tuple) -> tuple:
    """Trailing-None-insensitive spec comparison key."""
    spec = tuple(spec)
    while spec and spec[-1] is None:
        spec = spec[:-1]
    return spec


def predict_boundary_reshards(cfg, closed_jaxpr, state, batch) -> list:
    """Predicted GSPMD-minted reshards at top-level sharding boundaries.

    Walks the TOP-LEVEL equations only (a boundary is where data crosses
    from the caller's committed shardings into the program's partitioning):
    shard_map entries compare each input's declared spec against in_names;
    sharding_constraint ops compare against the constraint's spec when the
    constrained value traces back to exactly one root leaf. Each mismatch
    names the spec change that removes the reshard."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    paths = root_paths(state, batch)
    declared = _declared_specs(state, batch, paths)
    var_root = {v: p for v, p in zip(jaxpr.invars, paths)}
    out: list = []

    def check(path, used_spec, source):
        if path not in declared:
            return
        decl, nbytes = declared[path]
        if _norm(decl) == _norm(used_spec):
            return
        out.append(BoundaryReshard(
            path, _spec_str(decl), _spec_str(used_spec), source, nbytes,
            fix=f"commit {path} with PartitionSpec{_spec_str(used_spec)} "
                f"(or change the program to use "
                f"PartitionSpec{_spec_str(decl)}) so GSPMD stops minting "
                f"the reshard"))

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "shard_map":
            in_names = eqn.params.get("in_names", ())
            src, _ = _site_location(eqn)
            for atom, names in zip(eqn.invars, in_names):
                path = (None if hasattr(atom, "val")
                        else var_root.get(atom))
                if path is None or not isinstance(names, dict):
                    continue
                rank = len(getattr(atom, "aval", atom).shape)
                check(path, _names_to_spec(names, rank),
                      f"shard_map@{src}")
        elif name == "sharding_constraint":
            sharding = eqn.params.get("sharding")
            spec = getattr(sharding, "spec", None)
            if spec is None:
                continue
            src, _ = _site_location(eqn)
            for atom in eqn.invars:
                path = (None if hasattr(atom, "val")
                        else var_root.get(atom))
                if path is not None:
                    check(path, tuple(spec), f"sharding_constraint@{src}")
        elif name == "pjit":
            # transparent wrapper at the top level: look through it so the
            # shard_map boundary one level down still sees root vars
            for sub in _sub_jaxprs(eqn.params.get("jaxpr")):
                if len(sub.invars) == len(eqn.invars):
                    inner_roots = {
                        iv: var_root[ov]
                        for iv, ov in zip(sub.invars, eqn.invars)
                        if not hasattr(ov, "val") and ov in var_root}
                    out.extend(_nested_boundaries(cfg, sub, inner_roots,
                                                  declared))
    return out


def _nested_boundaries(cfg, jaxpr, var_root, declared) -> list:
    """One level of look-through for pjit-wrapped bodies (the jitted step
    itself lowers as an outer pjit around the user function)."""
    out: list = []

    def check(path, used_spec, source):
        decl, nbytes = declared.get(path, (None, 0))
        if decl is None or _norm(decl) == _norm(used_spec):
            return
        out.append(BoundaryReshard(
            path, _spec_str(decl), _spec_str(used_spec), source, nbytes,
            fix=f"commit {path} with PartitionSpec{_spec_str(used_spec)} "
                f"(or change the program to use "
                f"PartitionSpec{_spec_str(decl)}) so GSPMD stops minting "
                f"the reshard"))

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            in_names = eqn.params.get("in_names", ())
            src, _ = _site_location(eqn)
            for atom, names in zip(eqn.invars, in_names):
                path = (None if hasattr(atom, "val")
                        else var_root.get(atom))
                if path is None or not isinstance(names, dict):
                    continue
                rank = len(getattr(atom, "aval", atom).shape)
                check(path, _names_to_spec(names, rank),
                      f"shard_map@{src}")
        elif eqn.primitive.name == "sharding_constraint":
            sharding = eqn.params.get("sharding")
            spec = getattr(sharding, "spec", None)
            if spec is None:
                continue
            src, _ = _site_location(eqn)
            for atom in eqn.invars:
                path = (None if hasattr(atom, "val")
                        else var_root.get(atom))
                if path is not None:
                    check(path, tuple(spec), f"sharding_constraint@{src}")
    return out


# ---------------------------------------------------------------------------
# Compiled-module confirmation (optional; compiles — tests only)
# ---------------------------------------------------------------------------


def attribution_by_line(cfg, low) -> dict:
    """Lowered line -> Python source location for every attributed
    collective. The lookup the slice-boundary auditor (analysis/
    boundary.py) uses to name the site that minted a violating op —
    'ring ppermute from ops/ring_attention.py:118 crosses the cut' is
    actionable where a bare StableHLO line number is not. Empty when this
    JAX exposes no pre-lowering jaxpr."""
    if getattr(low, "jaxpr", None) is None:
        return {}
    paths = root_paths(low.state, low.batch)
    sites = collect_sites(low.jaxpr, paths)
    ops = [op for op in parse_collectives(low.text) if op.effective]
    attributed, _ = attribute_collectives(cfg, sites, ops)
    return {op.line: site.source for op, site in attributed}


def compiled_collectives(lowered) -> list:
    """Effective collectives of the OPTIMIZED module — after SPMD
    partitioning, so GSPMD-minted reshards are visible (they never appear
    in the pre-partitioning StableHLO). Compiles the program: cheap for
    the tiny fixtures, not for pod-scale configs."""
    text = lowered.compile().as_text()
    return [op for op in parse_collectives(text) if op.effective]


# ---------------------------------------------------------------------------
# The check
# ---------------------------------------------------------------------------


def audit_dataflow(cfg, *, low=None, menv=None, cost_model=None) -> Report:
    """The `provenance` check: site collection, attribution, intended-vs-
    implicit classification, and boundary-reshard prediction for one
    config's train step. All findings are warnings/info — a reshard is a
    performance smell, not a correctness failure."""
    rep = Report()
    if low is None:
        from picotron_tpu.analysis.trace import lower_train_step

        low = lower_train_step(cfg, menv)
    if getattr(low, "jaxpr", None) is None:
        rep.add(CHECK, WARNING, "<trace>",
                "this JAX version exposes no pre-lowering jaxpr "
                "(jit(...).trace); provenance analysis skipped")
        rep.info[CHECK] = {"unavailable": "no jaxpr capture"}
        return rep

    paths = root_paths(low.state, low.batch)
    sites = collect_sites(low.jaxpr, paths)
    ops = [op for op in parse_collectives(low.text) if op.effective]
    attributed, implicit = attribute_collectives(cfg, sites, ops)
    reshards = predict_boundary_reshards(cfg, low.jaxpr, low.state,
                                         low.batch)

    by_rule: dict = {}
    unexplained_sites = []
    for op, site in attributed:
        rule = intended_rule(cfg, site)
        if rule is None:
            unexplained_sites.append((op, site))
        else:
            by_rule[rule] = by_rule.get(rule, 0) + 1

    by_source: dict = {}
    for op, site in attributed:
        row = by_source.setdefault(site.source, {"ops": 0, "kinds": set(),
                                                 "roots": site.roots})
        row["ops"] += 1
        row["kinds"].add(op.kind)

    for op in implicit:
        rep.add(CHECK, WARNING, f"{op.kind}@L{op.line}",
                f"collective ({op.kind}, group {op.group_size}, "
                f"{op.nbytes or '?'} bytes) has no source site in the "
                f"traced program — GSPMD-minted implicit reshard; check "
                f"the PartitionSpecs of the tensors reaching this op "
                f"(shardcheck --provenance shows the declared-vs-used "
                f"boundary table)")
    for r in reshards:
        rep.add(CHECK, WARNING, r.path,
                f"implicit reshard at {r.source}: declared "
                f"PartitionSpec{r.declared} but the program uses "
                f"PartitionSpec{r.used} ({r.nbytes} bytes re-laid per "
                f"call) — {r.fix}")
    for op, site in unexplained_sites[:8]:
        rep.add(CHECK, INFO, site.source,
                f"collective outside the declared schedule contract: "
                f"{site.describe()}")

    n_attr = len(attributed)
    n_ops = len(ops)
    info = {
        "sites": len(sites),
        "ops_effective": n_ops,
        "ops_attributed": n_attr,
        "attribution_pct": round(100.0 * n_attr / n_ops, 1) if n_ops
        else 100.0,
        "implicit_ops": len(implicit),
        "boundary_reshards": len(reshards),
        "intended_by_rule": dict(sorted(by_rule.items())),
        "unexplained_sites": len(unexplained_sites),
        "by_source": {src: {"ops": row["ops"],
                            "kinds": sorted(row["kinds"]),
                            "roots": list(row["roots"][:4])}
                      for src, row in sorted(by_source.items())},
    }
    if cost_model is not None and (implicit or reshards):
        # price the unintended traffic exactly like the intended schedule:
        # the planner must see reshard cost, not just op counts
        priced = cost_model.price_ops(cfg, implicit)
        implicit_s = sum(p["secs"] for p in priced)
        reshard_s, reshard_bytes = cost_model.price_reshards(cfg, reshards)
        info["implicit_comm_ms"] = round((implicit_s + reshard_s) * 1e3, 4)
        info["implicit_bytes"] = (sum(p["bytes"] for p in priced)
                                  + reshard_bytes)
    rep.info[CHECK] = info
    return rep
