"""shardcheck — trace-time SPMD static analysis (no TPU required).

Every sharding/collective/donation decision this framework makes is
statically checkable by abstract evaluation on CPU: the PartitionSpec
pytree against the param pytree and mesh (spec_lint), the lowered step's
collective schedule (collectives), donation + recompilation hazards
(hazards), and source-level rules (source_lint). `run_shardcheck` composes
them; `preflight` is train.py's fail-fast subset; tools/shardcheck.py is
the CLI.
"""

from picotron_tpu.analysis.boundary import (  # noqa: F401
    ClassifiedOp, SliceTopology, audit_boundary, classify_ops,
)
from picotron_tpu.analysis.collectives import (  # noqa: F401
    CollectiveOp, audit_collectives, parse_collectives,
)
from picotron_tpu.analysis.cost_model import (  # noqa: F401
    Calibration, CostModel, GENERATIONS, StepCost, resolve_generation,
    spearman,
)
from picotron_tpu.analysis.dataflow import (  # noqa: F401
    BoundaryReshard, CollectiveSite, attribute_collectives, audit_dataflow,
    collect_sites, predict_boundary_reshards,
)
from picotron_tpu.analysis.hazards import (  # noqa: F401
    check_donation, check_state_stability, parse_arg_donation,
)
from picotron_tpu.analysis.report import (  # noqa: F401
    Finding, Report, ShardcheckError,
)
from picotron_tpu.analysis.runner import (  # noqa: F401
    ALL_CHECKS, PREFLIGHT_CHECKS, preflight, run_shardcheck,
)
from picotron_tpu.analysis.source_lint import (  # noqa: F401
    lint_file, lint_sources,
)
from picotron_tpu.analysis.spec_lint import (  # noqa: F401
    lint_param_specs, lint_specs,
)
from picotron_tpu.analysis.trace import lower_train_step  # noqa: F401
from picotron_tpu.analysis.variants import (  # noqa: F401
    AbstractSig, audit_feeds, audit_variants, check_engine_feed,
    prove_serve_programs, prove_train_step, signature_of,
)
