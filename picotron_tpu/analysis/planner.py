"""Layout search: rank the dp×tp×pp×cp×ep×flags×executor space by
predicted time.

The cost model prices one layout; the planner enumerates the whole space
for a chip count, prunes the points that cannot fit in HBM, and ranks the
survivors — the CPU replacement for a hardware layout sweep. Three layers
of fidelity, cheapest first:

1. **analytic** (`plan`): every candidate is validated by the Config's own
   `validate()` (head/vocab divisibility, MoE constraints, ...), screened
   by a closed-form HBM estimate (`estimate_hbm_gib`, deliberately
   optimistic-by-margin so it only discards clear non-fits), and priced by
   `CostModel.predict`. Microseconds per point; a v5p-64 space is ~1k
   points.
2. **traced** (`reprice_traced`): the top-K analytic survivors re-costed
   from their *actual* lowered collective schedules (analysis/trace.py +
   `CostModel.price_ops`) — catches schedules the analytic model mispredicts
   (GSPMD resharding, fused-engine differences). Needs simulated devices.
3. **verified** (`verify_hbm`): the proposed winner(s) run through
   tools/memcheck.py's `analyze()` — XLA's own per-device memory
   breakdown — and a point memcheck rejects is marked infeasible and
   skipped, so the planner never proposes a config that does not fit
   (the acceptance bar; tests pin it).

`plan` holds the *global batch* constant across candidates (mbs fixed,
grad-accum rederived per data-parallel width) so every point steps the
same tokens and predicted step times are directly comparable.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import os
from dataclasses import dataclass
from typing import Optional

from picotron_tpu.analysis.cost_model import (
    CostModel, StepCost, layout_label,
)
from picotron_tpu.config import Config, PipelineConfig, num_params

_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}

# activation leaves saved per layer under each remat policy, in units of
# one [mbs, s_local, h]-sized tensor — coarse on purpose: the estimate
# feeds a margin-backed prune, memcheck verifies the winner exactly
_REMAT_ACT_FACTOR = {"full": 2.0, "dots": 12.0, "dots_attn": 5.0,
                     "dots_lean": 4.0, "dots_norms": 14.0,
                     "dots_offload": 5.0}
# safety margin on the analytic estimate vs capacity: keep points whose
# estimate is under margin * HBM, reject the rest
_HBM_MARGIN = 0.92


@dataclass
class PlanPoint:
    """One candidate layout with its predicted cost and HBM screen."""

    cfg: Config
    cost: StepCost
    hbm_est_gib: float
    hbm_fits: bool
    # filled by verify_hbm / reprice_traced when those passes run
    memcheck_gib: Optional[float] = None
    memcheck_ok: Optional[bool] = None
    traced_comm_s: Optional[float] = None

    @property
    def label(self) -> str:
        return layout_label(self.cfg)

    def overrides_line(self) -> str:
        """A ready-to-run tools/memcheck.py-style --override line that
        turns the base config into this layout."""
        d, t = self.cfg.distributed, self.cfg.training
        parts = [f"distributed.dp_size={d.dp_size}",
                 f"distributed.tp_size={d.tp_size}",
                 f"distributed.pp_size={d.pp_size}",
                 f"distributed.cp_size={d.cp_size}",
                 f"distributed.ep_size={d.ep_size}"]
        if d.cp_flavor:
            # the flavor axis the planner enumerated; attn_impl rides
            # along so applying the line to a base whose attn_impl names
            # a different cp schedule cannot contradict the flavor
            parts.append(f"distributed.cp_flavor={d.cp_flavor}")
            parts.append(f"model.attn_impl={self.cfg.model.attn_impl}")
        if d.cp_mesh:
            parts.append(f"distributed.cp_mesh={d.cp_mesh}")
        if d.tp_strategy != "megatron":
            parts.append(f"distributed.tp_strategy={d.tp_strategy}")
        if d.tp_sync != "sync":
            parts.append(f"distributed.tp_sync={d.tp_sync}")
        if d.tp_mesh:
            parts.append(f"distributed.tp_mesh={d.tp_mesh}")
        parts += [
                 f"distributed.sequence_parallel="
                 f"{str(d.sequence_parallel).lower()}",
                 f"distributed.zero1={str(d.zero1).lower()}",
                 f"training.optimizer_offload="
                 f"{str(t.optimizer_offload).lower()}",
                 f"training.gradient_accumulation_steps="
                 f"{t.gradient_accumulation_steps}"]
        p = self.cfg.pipeline
        parts += [f"pipeline.executor={p.executor}",
                  f"pipeline.schedule={p.schedule}",
                  f"pipeline.interleave={p.interleave}"]
        return "--override " + " ".join(parts)

    def as_dict(self) -> dict:
        out = {"layout": self.label,
               "hbm_est_gib": round(self.hbm_est_gib, 3),
               "hbm_fits": self.hbm_fits,
               **self.cost.as_dict(),
               "overrides": self.overrides_line()}
        if self.memcheck_gib is not None:
            out["memcheck_gib"] = round(self.memcheck_gib, 3)
            out["memcheck_ok"] = self.memcheck_ok
        if self.traced_comm_s is not None:
            out["traced_comm_ms"] = round(self.traced_comm_s * 1e3, 3)
        return out


# ---------------------------------------------------------------------------
# HBM estimate (the analytic prune)
# ---------------------------------------------------------------------------


def estimate_hbm_gib(cfg: Config) -> float:
    """Closed-form per-device memory estimate: parameter/optimizer state
    under the layout's sharding + saved activations under the remat
    policy + the logits block. Coarse (no XLA temporaries/padding) —
    use only through the margin in `plan`; memcheck is the truth."""
    m, d, t = cfg.model, cfg.distributed, cfg.training
    n_total = num_params(m)
    shard = d.tp_size * d.pp_size
    n_local = n_total / shard
    if m.num_experts and d.ep_size > 1:
        bank = (m.num_hidden_layers * m.num_experts
                * 3 * m.hidden_size * m.expert_ffn_size)
        n_local -= bank / shard * (1 - 1 / d.ep_size)

    act_b = _DTYPE_BYTES.get(m.dtype, 2)
    mom_b = 2 if t.adam_moments_dtype == "bfloat16" else 4
    dp_shard = d.dp_size if d.zero1 else 1

    by = 0.0
    by += n_local * act_b                      # compute-dtype copy
    if not t.optimizer_offload:
        by += n_local * 4 / dp_shard           # fp32 master
        by += n_local * 2 * mom_b / dp_shard   # Adam moments
    if t.gradient_accumulation_steps > 1 or d.dp_size * d.ep_size > 1:
        by += n_local * 4                      # fp32 grad accumulator

    # saved activations: per-layer factor x in-flight microbatches
    s_local = t.seq_length // d.cp_size
    if d.sequence_parallel:
        s_local = max(s_local // d.tp_size, 1)
    layers_stage = max(m.num_hidden_layers // d.pp_size, 1)
    in_flight = min(t.gradient_accumulation_steps, d.pp_size)
    factor = _REMAT_ACT_FACTOR.get(t.remat_policy if t.remat else "none",
                                   20.0)
    by += (factor * layers_stage * in_flight
           * t.micro_batch_size * s_local * m.hidden_size * act_b)

    # logits + CE block (fp32), on the last stage
    vocab_local = m.vocab_size / d.tp_size
    if t.ce_chunk_size:
        vocab_local = t.ce_chunk_size
    by += t.micro_batch_size * s_local * vocab_local * 4

    return by / (1024 ** 3)


# ---------------------------------------------------------------------------
# Enumeration + ranking
# ---------------------------------------------------------------------------


def _factorizations(n: int, k: int):
    """All ordered k-tuples of positive ints whose product is n."""
    if k == 1:
        yield (n,)
        return
    for f in range(1, n + 1):
        if n % f == 0:
            for rest in _factorizations(n // f, k - 1):
                yield (f,) + rest


def _pipeline_options(base: Config, pp: int) -> list[PipelineConfig]:
    """Executor/schedule candidates for a pp-stage slice of the layout
    space. pp==1 has nothing to schedule; pp>1 adds the mpmd executor
    under 1f1b and every interleave depth that divides the per-stage
    layer slot count (the compile-once constraint Config.validate pins).
    gpipe is deliberately absent: the cost model prices it identically
    to 1f1b (same v) and it exists as a debugging twin, not a layout."""
    opts = [PipelineConfig()]
    if pp <= 1:
        return opts
    opts.append(PipelineConfig(executor="mpmd"))
    slots = -(-base.model.num_hidden_layers // pp)  # ceil
    for v in range(2, slots + 1):
        if slots % v == 0:
            opts.append(PipelineConfig(executor="mpmd",
                                       schedule="interleaved",
                                       interleave=v))
    return opts


_CP_FLAVOR_IMPLS = ("ring", "ulysses", "mesh")


def _cp_flavor_options(base: Config, cp: int, tp: int) -> list[tuple]:
    """(cp_flavor, cp_mesh) candidates for a cp-degree slice of the layout
    space — the flavor is a free planner axis, like sp or zero1. Ring is
    always schedulable; Ulysses needs the tp-local heads to divide by cp;
    mesh enumerates every true-2D factorization whose inner factor divides
    the tp-local query and kv heads (degenerate factorizations ARE the 1D
    flavors, so they are not repeated here)."""
    if cp <= 1:
        return [("", "")]
    opts = [("ring", "")]
    hq = base.model.num_attention_heads // tp
    hkv = base.model.num_key_value_heads // tp
    if hq % cp == 0 and hkv % cp == 0:
        opts.append(("ulysses", ""))
    opts += [("mesh", f"{cp // y}x{y}") for y in range(2, cp)
             if cp % y == 0 and cp // y > 1
             and hq % y == 0 and hkv % y == 0]
    return opts


def _tp_strategy_options(base: Config, tp: int) -> list[tuple]:
    """(tp_strategy, tp_sync, tp_mesh) candidates for a tp-degree slice of
    the layout space — strategy and sync mode are free planner axes, like
    the cp flavor. Megatron-sync is always schedulable; deferred sync
    needs tp > 1 (it reschedules the row-parallel exit psum as RS + a
    hoisted AG); the 2d strategy enumerates every true-2D factorization
    at tp >= 4. The row-first strategy is not enumerated: its entry psum
    spans the full projection width (wider than hidden) plus an exit
    gather, so it is dominated by megatron at every degree the cost model
    prices — tools/layout_planner.py --tp-strategy-table still reports it
    for inspection."""
    opts = [("megatron", "sync", "")]
    if tp <= 1:
        return opts
    opts.append(("megatron", "deferred", ""))
    if tp >= 4:
        from picotron_tpu.analysis.cost_model import feasible_tp_meshes

        opts += [("2d", "sync", f"{x}x{y}")
                 for x, y in feasible_tp_meshes(base, tp)]
    return opts


def candidate_configs(base: Config, chips: int,
                      *, flags: bool = True) -> list[Config]:
    """Every valid layout of `base` over `chips` devices. Flag knobs
    (sequence_parallel / zero1 / optimizer_offload) toggle only where they
    can matter (sp needs tp>1, zero1 needs dp>1); pipeline executor and
    schedule enumerate only where pp > 1 (see _pipeline_options); the cp
    flavor and its mesh factorization enumerate only where cp > 1 (see
    _cp_flavor_options). Grad accumulation is rederived so the global
    batch matches the base config's."""
    t = base.training
    global_batch = base.global_batch_size
    out = []
    for dp, tp, pp, cp, ep in _factorizations(chips, 5):
        denom = t.micro_batch_size * dp * ep
        ga = max(round(global_batch / denom), 1)
        sp_opts = (False, True) if (flags and tp > 1) else (False,)
        z_opts = (False, True) if (flags and dp > 1) else (False,)
        o_opts = (False, True) if flags else (False,)
        pipe_opts = _pipeline_options(base, pp) if flags \
            else [PipelineConfig()]
        cp_opts = _cp_flavor_options(base, cp, tp) if flags \
            else [(base.distributed.cp_flavor if cp > 1 else "",
                   base.distributed.cp_mesh if cp > 1 else "")]
        tp_opts = _tp_strategy_options(base, tp) if flags \
            else [(base.distributed.tp_strategy, base.distributed.tp_sync,
                   base.distributed.tp_mesh)]
        for sp in sp_opts:
            for z1 in z_opts:
                for off in o_opts:
                    for pl in pipe_opts:
                        for flavor, cp_mesh in cp_opts:
                            for tp_strat, tp_sync, tp_mesh in tp_opts:
                                model_cfg = base.model
                                if (model_cfg.attn_impl in _CP_FLAVOR_IMPLS
                                        and flavor
                                        and model_cfg.attn_impl != flavor):
                                    # a base pinned to one cp schedule by
                                    # name would contradict the enumerated
                                    # flavor; rename it (flash lowering is
                                    # unchanged)
                                    model_cfg = dataclasses.replace(
                                        model_cfg, attn_impl=flavor)
                                cfg = base.replace(
                                    model=model_cfg,
                                    distributed=dataclasses.replace(
                                        base.distributed, dp_size=dp,
                                        tp_size=tp, pp_size=pp, cp_size=cp,
                                        ep_size=ep, cp_flavor=flavor,
                                        cp_mesh=cp_mesh,
                                        tp_strategy=tp_strat,
                                        tp_sync=tp_sync, tp_mesh=tp_mesh,
                                        sequence_parallel=sp, zero1=z1),
                                    training=dataclasses.replace(
                                        t, gradient_accumulation_steps=ga,
                                        optimizer_offload=off,
                                        # offload demands bf16 + 1f1b;
                                        # grad_engine auto lets each layout
                                        # pick its engine
                                        grad_engine="auto"),
                                    pipeline=pl,
                                )
                                try:
                                    cfg.validate()
                                except (ValueError, KeyError):
                                    continue
                                out.append(cfg)
    return out


def plan(base: Config, chips: int, model: Optional[CostModel] = None,
         *, flags: bool = True, hbm_gib: Optional[float] = None,
         include_infeasible: bool = False) -> list[PlanPoint]:
    """Rank every candidate layout by predicted step time, HBM-pruned.
    Returns PlanPoints sorted fastest-first; `include_infeasible` keeps
    the pruned points (marked) for reporting."""
    model = model or CostModel()
    cap = hbm_gib if hbm_gib is not None else model.gen.hbm_gib
    pts = []
    for cfg in candidate_configs(base, chips, flags=flags):
        est = estimate_hbm_gib(cfg)
        fits = est <= cap * _HBM_MARGIN
        if not fits and not include_infeasible:
            continue
        pts.append(PlanPoint(cfg, model.predict(cfg), est, fits))
    # rank by time PER TOKEN: ga rounding can leave a candidate stepping
    # slightly more/fewer tokens than the base, and raw step time would
    # reward the smaller batch
    pts.sort(key=lambda p: (not p.hbm_fits,
                            p.cost.total_s / p.cost.tokens_per_step,
                            # deterministic tie-breaks that prefer the
                            # memory-kinder spellings at equal cost
                            not p.cfg.distributed.sequence_parallel,
                            not p.cfg.distributed.zero1,
                            p.label))
    return pts


def reprice_traced(points: list[PlanPoint], model: CostModel,
                   top_k: int = 3) -> list[PlanPoint]:
    """Re-cost the first `top_k` feasible points from their actual lowered
    schedules (requires enough simulated devices for the largest point;
    see tools/layout_planner.py --trace). Re-sorts by the traced total:
    compute/bubble/offload stay analytic, the exposed-comm term is
    replaced by the traced schedule priced per op."""
    done = 0
    for p in points:
        if not p.hbm_fits or done >= top_k:
            continue
        _, comm_s = model.priced_schedule(p.cfg)
        p.traced_comm_s = comm_s
        done += 1
    points.sort(key=lambda p: (
        not p.hbm_fits,
        (p.cost.compute_s + p.cost.bubble_s + p.cost.offload_s
         + (p.traced_comm_s if p.traced_comm_s is not None
            else p.cost.exposed_comm_s))))
    return points


# ---------------------------------------------------------------------------
# memcheck verification (the acceptance gate)
# ---------------------------------------------------------------------------


def _load_memcheck():
    """tools/memcheck.py as a module (tools/ is not a package)."""
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, "tools", "memcheck.py")
    spec = importlib.util.spec_from_file_location("_memcheck_for_plan",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def verify_hbm(point: PlanPoint, hbm_gib: float) -> bool:
    """Run tools/memcheck.py's analyze() (XLA compile-time memory
    breakdown) on the point and record the verdict. Caller must have
    provisioned enough simulated devices for the point's world size."""
    memcheck = _load_memcheck()
    try:
        res = memcheck.analyze(point.cfg)
    except Exception as e:  # a config XLA cannot compile does not fit
        point.memcheck_ok = False
        point.memcheck_gib = float("inf")
        point.memcheck_error = str(e)[:200]
        return False
    total = res["per_device_gib"]["total_estimate"]
    point.memcheck_gib = total
    point.memcheck_ok = total <= hbm_gib
    return point.memcheck_ok


def best_point(points: list[PlanPoint], *, verify: bool = False,
               hbm_gib: Optional[float] = None,
               model: Optional[CostModel] = None) -> Optional[PlanPoint]:
    """The fastest feasible point; with `verify`, walk the ranking until
    one passes memcheck so a rejected config is never proposed."""
    cap = hbm_gib if hbm_gib is not None else (model or CostModel()).gen.hbm_gib
    for p in points:
        if not p.hbm_fits:
            continue
        if not verify:
            return p
        if verify_hbm(p, cap):
            return p
    return None


def planner_gap(cfg: Config, model: Optional[CostModel] = None,
                *, flags: bool = True):
    """(current cost, best PlanPoint, gap fraction) — how much slower the
    given config is predicted to be than the planner's best layout at the
    same chip count. Pure analytic; used by the train.py preflight and
    shardcheck --cost."""
    model = model or CostModel()
    cur = model.predict(cfg)
    pts = plan(cfg, cfg.distributed.world_size, model, flags=flags)
    if not pts:
        return cur, None, 0.0
    best = pts[0]
    # per-token compare (see plan()'s ranking key)
    gap = ((cur.total_s / cur.tokens_per_step)
           / (best.cost.total_s / best.cost.tokens_per_step) - 1.0)
    return cur, best, gap


def slice_plans(cfg: Config, model: Optional[CostModel] = None,
                n_slices: Optional[int] = None) -> list[dict]:
    """Enumerate which DCN-tolerant axis (dp or pp) can absorb the slice
    granules for this layout and price both network tiers for each legal
    split (CostModel.slice_tiers): the intra-slice ICI legs and the
    shard-per-slice DCN leg of the hierarchical decomposition. Rows are
    ranked by total comm — the top row is the boundary the layout should
    declare in `distributed.dcn_axes`. Empty when no axis can absorb the
    slice count (the same divisibility rule mesh._split_axes_over_dcn
    enforces)."""
    model = model or CostModel()
    s = n_slices if n_slices is not None else cfg.distributed.slices
    if s <= 1:
        return []
    d = cfg.distributed
    rows = []
    for axis, size in (("dp", d.dp_size), ("pp", d.pp_size)):
        if size >= s and size % s == 0:
            rows.append(model.slice_tiers(cfg, s, axis))
    rows.sort(key=lambda r: r["total_comm_ms"])
    return rows
