"""Slice-boundary audit — prove which collectives cross the DCN cut.

Multi-slice jobs join TPU slices over DCN, a network orders of magnitude
slower than ICI. The house rule (mesh._split_axes_over_dcn) absorbs the
slice granules into the outermost mesh axes — dp first, then pp — because
the gradient all-reduce (once per step, overlappable) and the pipeline
boundary ppermute (point-to-point) tolerate DCN latency while ep/cp/tp
collectives must not leave a slice. The MPMD-pipeline paper (arxiv
2412.14374) and TASP (arxiv 2509.26541) both reduce to the same
discipline: with two network tiers, the comm schedule must follow the
network — and with two tiers that discipline is only enforceable
statically, before a single step runs.

This module is that static pass. `SliceTopology` captures the slice
count + the *declared* crossing axes (`distributed.dcn_axes`); the
auditor maps every replica-group member id of the traced schedule
(analysis/collectives.py keeps the membership payload) to its slice and
classifies each collective:

- **intra** — every group stays inside one slice (pure ICI traffic);
- **boundary** — groups straddle the cut, but only via axes the config
  *declared* DCN-tolerant (expected traffic, priced at the `dcn` tier of
  analysis/cost_model.py);
- **violating** — groups straddle the cut via an axis NOT declared
  (an ICI-only collective routed over DCN: the named preflight error);
- **unattributable** — the dialect elided the membership payload, so the
  op cannot be proven either way (warning, never silently green).

Two hierarchical presence rules ride along (mutation-tested like every
schedule rule in collectives.py): every crossing reduction must spell
the hierarchical decomposition — either fused (one wide op keeping full
per-slice cohorts, XLA decomposing internally) or explicit
(cohort-1 DCN groups riding an intra-slice reduce-scatter leg, the
runtime schedule parallel/hier_reduce.py emits) — and every boundary
group must split into equal per-slice cohorts; an unequal split means
the DCN leg widened past the small shard-per-slice transfer the
decomposition promises.

Per-tier byte totals use the standard hierarchical algorithm: a crossing
collective of group size n over s slices (cohort m = n/s) does its wide
legs on ICI and moves only shard-width payloads over DCN —
reduce-scatter inside the slice + a small all-reduce across slices +
all-gather inside the slice, for an all-reduce.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from picotron_tpu.analysis.report import ERROR, INFO, WARNING, Report

CHECK = "boundary"

CLASSES = ("intra", "boundary", "violating", "unattributable")

# mesh axis order — the Mesh(grid, AXES) contract in mesh.py; replica ids
# in the lowered text are row-major positions in this grid
AXES = ("dp", "pp", "ep", "cp", "tp")


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """Slice count + declared DCN-crossing axes, resolved against a mesh
    grid. `dcn_shape` holds the per-axis slice granules the house rule
    actually assigns (dp absorbs first, then pp) — the *physical* cut the
    declaration is checked against."""

    n_slices: int
    declared: tuple       # user-declared crossing axes, dp-first order
    grid: tuple           # (dp, pp, ep, cp, tp) axis sizes
    dcn_shape: tuple      # slice granules per grid axis (house rule)

    @classmethod
    def from_config(cls, cfg, n_slices: Optional[int] = None,
                    dcn_axes: Optional[str] = None) -> "SliceTopology":
        from picotron_tpu.config import parse_dcn_axes
        from picotron_tpu.mesh import _split_axes_over_dcn

        d = cfg.distributed
        s = d.slices if n_slices is None else n_slices
        declared = parse_dcn_axes(
            d.dcn_axes if dcn_axes is None else dcn_axes)
        grid = (d.dp_size, d.pp_size, d.ep_size, d.cp_size, d.tp_size)
        if s > 1:
            dcn_shape, _ = _split_axes_over_dcn(grid, s)
        else:
            dcn_shape = (1,) * len(grid)
        return cls(s, declared, grid, dcn_shape)

    @property
    def cut_axes(self) -> tuple:
        """Axes that physically carry a slice granule (the real cut)."""
        return tuple(a for a, g in zip(AXES, self.dcn_shape) if g > 1)

    @property
    def world(self) -> int:
        out = 1
        for n in self.grid:
            out *= n
        return out

    def coords(self, device_id: int) -> tuple:
        """Row-major (dp, pp, ep, cp, tp) grid coordinates of a flat id."""
        c = []
        rem = device_id
        for n in reversed(self.grid):
            c.append(rem % n)
            rem //= n
        return tuple(reversed(c))

    def slice_of(self, device_id: int) -> int:
        """Slice index of a device: the granule (outermost) coordinate of
        each DCN-split axis, row-major — mirroring how
        mesh_utils.create_hybrid_device_mesh lays the dcn_shape as the
        outer factor of each logical axis."""
        idx = 0
        for coord, g, size in zip(self.coords(device_id), self.dcn_shape,
                                  self.grid):
            idx = idx * g + coord // (size // g)
        return idx


@dataclasses.dataclass(frozen=True)
class ClassifiedOp:
    """One effective collective, classified against the slice cut."""

    kind: str
    line: int
    cls: str              # a CLASSES member
    cross_axes: tuple     # axes whose granule coordinate varies in-group
    slices_touched: int   # max distinct slices any one group spans
    group_size: Optional[int]
    ici_bytes: int        # bytes on intra-slice ICI links
    dcn_bytes: int        # bytes crossing the DCN cut (hierarchical form)
    cohorts: tuple = ()   # per-slice member counts of the worst group

    def as_row(self) -> dict:
        return {"kind": self.kind, "line": self.line, "class": self.cls,
                "axes": list(self.cross_axes),
                "slices": self.slices_touched,
                "group": self.group_size,
                "ici_bytes": self.ici_bytes, "dcn_bytes": self.dcn_bytes}


def _wire_bytes(kind: str, nbytes: int, n: int) -> int:
    """Bytes a flat collective of `kind` moves over its links (ring
    algorithms — the same volumes cost_model.collective_secs prices)."""
    if n <= 1 or not nbytes:
        return 0
    if kind == "all_reduce":
        return int(2 * nbytes * (n - 1) / n)
    return int(nbytes * (n - 1) / n)


def classify_ops(ops, topo: SliceTopology) -> list[ClassifiedOp]:
    """Classify every effective parsed op against the slice topology."""
    out = []
    for op in ops:
        if not op.effective:
            continue
        nbytes = op.nbytes or 0
        if op.members is None:
            out.append(ClassifiedOp(op.kind, op.line, "unattributable",
                                    (), 0, op.group_size,
                                    _wire_bytes(op.kind, nbytes,
                                                op.group_size or 2), 0))
            continue
        if op.kind == "collective_permute":
            crossing = sum(1 for src, tgt in op.members
                           if topo.slice_of(src) != topo.slice_of(tgt))
            axes = _varying_granule_axes(
                [p for p in op.members
                 if topo.slice_of(p[0]) != topo.slice_of(p[1])], topo)
            if crossing == 0:
                cls = "intra"
            elif set(axes) <= set(topo.declared):
                cls = "boundary"
            else:
                cls = "violating"
            out.append(ClassifiedOp(
                op.kind, op.line, cls, axes,
                2 if crossing else 1, None,
                nbytes * (len(op.members) - crossing), nbytes * crossing))
            continue
        worst = None  # ((slices, cohort spread), sorted cohort sizes)
        axes: set = set()
        for group in op.members:
            per_slice: dict = {}
            for m in group:
                sl = topo.slice_of(m)
                per_slice[sl] = per_slice.get(sl, 0) + 1
            if len(per_slice) > 1:
                cohorts = tuple(sorted(per_slice.values()))
                key = (len(per_slice), cohorts[-1] - cohorts[0])
                if worst is None or key > worst[0]:
                    worst = (key, cohorts)
                axes |= set(_varying_granule_axes([group], topo))
        n = op.group_size or 1
        if worst is None:
            out.append(ClassifiedOp(op.kind, op.line, "intra", (), 1, n,
                                    _wire_bytes(op.kind, nbytes, n), 0))
            continue
        s, worst_cohorts = worst[0][0], worst[1]
        m = max(n // s, 1)
        # hierarchical split: wide legs stay on ICI at cohort width m;
        # only the shard-per-slice leg crosses DCN
        if op.kind == "all_reduce":
            ici = int(2 * nbytes * (m - 1) / m)
            dcn = int(2 * (nbytes / m) * (s - 1) / s)
        elif op.kind in ("all_gather", "reduce_scatter"):
            ici = int(nbytes * (m - 1) / m)
            dcn = int((nbytes / m) * (s - 1) / s)
        else:  # all_to_all: per-pair payloads, crossing fraction over DCN
            ici = int(nbytes * (m - 1) / n)
            dcn = int(nbytes * m * (s - 1) / n)
        cls = ("boundary" if axes <= set(topo.declared) else "violating")
        out.append(ClassifiedOp(op.kind, op.line, cls,
                                tuple(a for a in AXES if a in axes),
                                s, n, ici, dcn, worst_cohorts))
    return out


def _varying_granule_axes(groups, topo: SliceTopology) -> tuple:
    """Axes whose slice-granule coordinate varies within any given group —
    the axes that CAUSE a straddle (an axis varying only inside its
    per-slice block never changes the slice index)."""
    varying = set()
    for group in groups:
        coord_sets: list = [set() for _ in topo.grid]
        for m in group:
            for i, (c, g, size) in enumerate(zip(topo.coords(m),
                                                 topo.dcn_shape,
                                                 topo.grid)):
                if g > 1:
                    coord_sets[i].add(c // (size // g))
        for i, cs in enumerate(coord_sets):
            if len(cs) > 1:
                varying.add(AXES[i])
    return tuple(a for a in AXES if a in varying)


def audit_boundary(cfg, *, text: str = None, low=None, state=None,
                   menv=None, n_slices: Optional[int] = None,
                   dcn_axes: Optional[str] = None,
                   cost_model=None) -> Report:
    """Audit a config's collective schedule against its slice topology.

    Pass `text` (or a `low` from trace.lower_train_step) to audit an
    existing lowering; otherwise the train step is lowered here.
    `n_slices`/`dcn_axes` override the config's `distributed.slices`/
    `distributed.dcn_axes` — the `tools/shardcheck.py --slices N
    [--dcn-axes dp,pp]` path. With `low`, violating ops are attributed to
    the Python source site that minted them (dataflow.attribution_by_line).
    With `cost_model`, the per-tier byte totals are priced: ICI legs on
    the placed axis links, the DCN leg on the generation's `dcn` tier."""
    rep = Report()
    try:
        topo = SliceTopology.from_config(cfg, n_slices, dcn_axes)
    except ValueError as e:
        rep.add(CHECK, ERROR, "topology", str(e))
        return rep
    if topo.n_slices <= 1:
        rep.info[CHECK] = {"slices": 1, "audited": False}
        rep.add(CHECK, INFO, "topology",
                "single slice — no DCN cut to audit")
        return rep

    if text is None and low is not None:
        text = low.text
    if text is None:
        from picotron_tpu.analysis.trace import lower_train_step

        low = lower_train_step(cfg, menv)
        text = low.text
    from picotron_tpu.analysis.collectives import parse_collectives

    ops = parse_collectives(text)
    classified = classify_ops(ops, topo)
    op_bytes = {op.line: op.nbytes for op in ops}
    counts = {c: sum(1 for r in classified if r.cls == c) for c in CLASSES}
    info = {
        "slices": topo.n_slices,
        "audited": True,
        "dcn_axes": ",".join(topo.declared),
        "cut_axes": ",".join(topo.cut_axes),
        **counts,
        "ici_bytes": sum(r.ici_bytes for r in classified),
        "dcn_bytes": sum(r.dcn_bytes for r in classified),
        "table": [r.as_row() for r in classified],
    }
    rep.info[CHECK] = info

    d = cfg.distributed
    sources = {}
    if low is not None and any(r.cls == "violating" for r in classified):
        # name the Python site that minted each violating op — computed
        # lazily, only when there is a violation to report
        from picotron_tpu.analysis.dataflow import attribution_by_line

        sources = attribution_by_line(cfg, low)
    for r in classified:
        if r.cls == "violating":
            src = sources.get(r.line)
            rep.add(CHECK, ERROR, f"{r.kind}@L{r.line}",
                    f"ici-axis-over-dcn: replica group (size "
                    f"{r.group_size}) straddles {r.slices_touched} slices "
                    f"via axis/axes {list(r.cross_axes)} not declared in "
                    f"dcn_axes={info['dcn_axes']!r}"
                    + (f" (minted at {src})" if src else "")
                    + f" — an ICI-only collective is routed over the DCN "
                    f"cut; declare the axis or rebalance the layout so "
                    f"{list(topo.cut_axes)} absorbs the slice count")
        elif r.cls == "unattributable":
            rep.add(CHECK, WARNING, f"{r.kind}@L{r.line}",
                    "replica-group membership elided by the dialect — "
                    "this op cannot be proven intra-slice")

    # -- hierarchical presence rules (mutation-tested) ---------------------
    # The hierarchical decomposition of a crossing collective is:
    # reduce-scatter inside the slice (over the group's intra-slice
    # cohort) + a small shard-per-slice all-reduce across DCN + all-gather
    # inside the slice. EVERY crossing reduction must exhibit it in one of
    # its two valid spellings, per op (an any-pass rule would let the flat
    # scalar-loss psums mask a grad path whose intra leg was deleted):
    #
    # - fused form — one wide op whose groups keep full per-slice cohorts
    #   (min cohort >= m_expected): XLA's own hierarchical lowering of a
    #   flat psum on a hybrid mesh does the decomposition internally;
    # - explicit form — cohort-1 groups (one member per slice: the
    #   runtime schedule parallel/hier_reduce.py emits), valid only when
    #   the intra-slice reduce-scatter leg is present in the text AND
    #   (when result sizes parse) some intra scatter produced exactly
    #   this op's shard — the "one shard per slice" size claim.
    #
    # tests/test_boundary.py mutates each leg away on both spellings.
    boundary_ops = [r for r in classified if r.cls == "boundary"]
    if "dp" in topo.declared and "dp" in topo.cut_axes:
        g_dp = topo.dcn_shape[AXES.index("dp")]
        m_expected = (d.dp_size // g_dp) * d.ep_size * d.cp_size
        grad_crossers = [r for r in boundary_ops
                         if r.kind in ("all_reduce", "reduce_scatter")
                         and r.cohorts]
        intra_rs = [r for r in classified
                    if r.cls == "intra" and r.kind == "reduce_scatter"]
        intra_rs_bytes = {op_bytes.get(r.line) for r in intra_rs}
        intra_rs_bytes.discard(None)
        if m_expected > 1:
            for r in grad_crossers:
                if min(r.cohorts) >= m_expected:
                    continue  # fused form
                nb = op_bytes.get(r.line)
                if (max(r.cohorts) == 1 and intra_rs
                        and (nb is None or not intra_rs_bytes
                             or nb in intra_rs_bytes)):
                    continue  # explicit form
                rep.add(
                    CHECK, ERROR, "hier_intra_scatter",
                    f"{r.kind}@L{r.line}: dp crosses DCN but this "
                    f"crossing reduction (cohorts "
                    f"{'|'.join(str(c) for c in r.cohorts)}) neither "
                    f"keeps an intra-slice cohort of {m_expected} (the "
                    f"per-slice width of the fused data axes) nor rides "
                    f"a matching intra-slice reduce-scatter leg: the "
                    f"hierarchical decomposition's intra-slice "
                    f"reduce-scatter is missing — full-width gradients "
                    f"would cross DCN instead of one shard per slice")
    for r in boundary_ops:
        if r.cohorts and len(set(r.cohorts)) > 1:
            rep.add(CHECK, ERROR, "hier_dcn_cohort",
                    f"{r.kind}@L{r.line}: a DCN-crossing group splits "
                    f"{'|'.join(str(c) for c in r.cohorts)} across slices "
                    f"— the hierarchical decomposition's DCN leg must "
                    f"carry equal per-slice cohorts of "
                    f"{(r.group_size or 0) // max(r.slices_touched, 1)}; "
                    f"an unequal split widens the inter-slice transfer "
                    f"past one shard per slice")

    if not rep.errors():
        rep.add(CHECK, INFO, "summary",
                f"{counts['intra']} intra-slice, {counts['boundary']} "
                f"boundary op(s) over declared axes "
                f"[{info['dcn_axes']}], 0 violating — "
                f"{info['dcn_bytes']} B cross DCN (hierarchical), "
                f"{info['ici_bytes']} B stay on ICI")

    if cost_model is not None:
        dcn_s = 0.0
        for r in classified:
            if r.dcn_bytes:
                dcn_s += cost_model.dcn_secs(
                    "all_reduce" if r.kind == "all_reduce" else
                    ("collective_permute"
                     if r.kind == "collective_permute" else "all_gather"),
                    r.dcn_bytes, topo.n_slices)
        links = cost_model.axes_for(cfg)
        worst = min((l.bandwidth for l in links.values()), default=None)
        ici_s = (info["ici_bytes"] / worst) if worst else 0.0
        info["dcn_ms"] = round(dcn_s * 1e3, 4)
        info["ici_ms"] = round(ici_s * 1e3, 4)
        info["dcn_generation"] = cost_model.gen.name
    return rep


def render_table(info: dict) -> str:
    """Human classification table for the shardcheck CLI."""
    if not info.get("audited"):
        return "boundary: single slice — no DCN cut to audit"
    rows = info.get("table", [])
    head = (f"boundary: {info['slices']} slice(s), cut on "
            f"[{info['cut_axes']}], declared [{info['dcn_axes']}] — "
            f"{info['intra']} intra / {info['boundary']} boundary / "
            f"{info['violating']} violating / "
            f"{info['unattributable']} unattributable")
    lines = [head]
    lines.append(f"  {'kind':<20}{'line':>6}  {'class':<15}"
                 f"{'axes':<10}{'ici_bytes':>12}{'dcn_bytes':>12}")
    for r in rows:
        lines.append(
            f"  {r['kind']:<20}{r['line']:>6}  {r['class']:<15}"
            f"{','.join(r['axes']) or '-':<10}"
            f"{r['ici_bytes']:>12}{r['dcn_bytes']:>12}")
    if "dcn_ms" in info:
        lines.append(f"  priced[{info['dcn_generation']}]: "
                     f"dcn {info['dcn_ms']} ms, ici {info['ici_ms']} ms")
    return "\n".join(lines)
