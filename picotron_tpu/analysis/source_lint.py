"""AST source lint — repo-wide rules the runtime analyzers cannot see.

Trace-time analysis audits one config's program; some hazards live in the
source itself, on paths a given trace never visits. Rules:

- **jax-core** (error): any use of the semi-private `jax.core` namespace
  (`import jax.core`, `from jax.core import Tracer`, `jax.core.Tracer`
  attribute chains). Its re-exports get shuffled between JAX releases, so
  these break on upgrade at runtime, usually deep inside a jit. The
  sanctioned alternatives: `jax.errors` for tracer-leak detection, or a
  static flag plumbed from the caller when tracedness is knowable at trace
  time (how ops/ulysses.py gets it from make_parallel_ctx).
- **jax-private** (warning): `jax._src` imports. Sometimes unavoidable
  (mesh.py consults distributed global state pre-init); always worth an
  eyebrow, never a hard failure.
- **host-callback** (error): `jax.pure_callback` / `io_callback` /
  `jax.debug.callback` in library code. Everything under picotron_tpu/ can
  end up inside the jitted step, where a host callback serializes the
  device stream per call — the kind of 10x step-time surprise only a real
  TPU run would otherwise reveal.
- **loop-collective** (warning): a `lax` collective (`psum`, `all_gather`,
  `ppermute`, `all_to_all`, ...) issued directly inside a Python
  `for`/`while` loop. A loop over layers or microbatches that emits one
  collective per iteration lowers to N small ops where one batched op
  would do — the unbatched-collective smell the ICI cost model
  (analysis/cost_model.py) prices per-op α-latency for, and exactly the
  shape that hides behind "it traced fine". Collectives inside a function
  *defined* in a loop (a scan body built per-config) do not flag: the
  loop builds the function once, the scan issues the op. Deliberate
  unrolled rings (ops/ring_attention.py's cp-hop chain) suppress
  per-line.
- **uncommitted-device-put** (warning): `jax.device_put(x)` with no
  sharding/device argument. It produces an UNCOMMITTED array — jax keys
  jit executables on commitment, so feeding it where a committed array
  previously flowed mints a second executable for identical shapes (the
  variant hazard analysis/variants.py proves against). Pass the sharding
  explicitly, or use `jax.device_put(x, device=...)`.

Suppress a finding with a `# shardcheck: ok` comment on the line.
"""

from __future__ import annotations

import ast
import os

from picotron_tpu.analysis.report import ERROR, WARNING, Report

CHECK = "source_lint"

_HOST_CALLBACKS = {"pure_callback", "io_callback", "callback",
                   "host_callback"}

# lax collectives whose per-iteration issue inside a Python loop is the
# unbatched-collective smell (see module docstring)
_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "all_to_all", "ppermute", "pshuffle", "psum_scatter"}


def _attr_chain(node) -> list[str]:
    """['jax', 'core', 'Tracer'] for jax.core.Tracer; [] if not a chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str, suppressed: set, rep: Report):
        self.relpath = relpath
        self.suppressed = suppressed
        self.rep = rep
        self._loop_depth = 0

    def _add(self, node, severity, message):
        if node.lineno in self.suppressed:
            return
        self.rep.add(CHECK, severity, f"{self.relpath}:{node.lineno}",
                     message)

    # -- loop-collective scope tracking -----------------------------------
    # A nested function/lambda resets the loop context: defining a scan
    # body inside a loop is fine — the collective runs once per scan, not
    # once per Python iteration.

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = visit_AsyncFor = _visit_loop

    def _visit_fn(self, node):
        saved, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = saved

    visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _visit_fn

    def visit_Call(self, node):
        chain = _attr_chain(node.func)
        if (chain and chain[-1] == "device_put"
                and chain[0] in ("jax", "device_put")
                and len(node.args) < 2
                and not any(kw.arg in ("device", "shardings")
                            for kw in node.keywords)):
            self._add(node, WARNING,
                      "jax.device_put without an explicit sharding "
                      "produces an UNCOMMITTED array — a later feed of it "
                      "where a committed array flowed keys a second jit "
                      "executable for the same shapes (the variant hazard "
                      "analysis/variants.py proves against). Pass the "
                      "sharding positionally or as device=..., or "
                      "suppress with '# shardcheck: ok' if uncommitted "
                      "placement is deliberate")
        if (self._loop_depth > 0 and chain
                and chain[-1] in _COLLECTIVES
                and chain[0] in ("jax", "lax")):
            self._add(node, WARNING,
                      f"collective {'.'.join(chain)} issued inside a "
                      f"Python loop: one op per iteration where a batched "
                      f"collective (or a scan) would issue one — the "
                      f"unbatched-collective smell the ICI cost model "
                      f"prices per-op latency for. Batch it, move it into "
                      f"a scan body, or suppress with '# shardcheck: ok' "
                      f"if the unroll is deliberate")
        self.generic_visit(node)

    def visit_Import(self, node):
        for alias in node.names:
            if alias.name == "jax.core" or \
                    alias.name.startswith("jax.core."):
                self._add(node, ERROR,
                          f"import of semi-private {alias.name!r} — use "
                          f"jax.errors / a static flag instead")
            elif alias.name.startswith("jax._src"):
                self._add(node, WARNING,
                          f"private-namespace import {alias.name!r}")
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        mod = node.module or ""
        if mod == "jax.core" or mod.startswith("jax.core."):
            self._add(node, ERROR,
                      f"import from semi-private 'jax.core' "
                      f"({', '.join(a.name for a in node.names)}) — use "
                      f"jax.errors / a static flag instead")
        elif mod.startswith("jax._src"):
            self._add(node, WARNING,
                      f"private-namespace import from {mod!r}")
        elif mod == "jax":
            for alias in node.names:
                if alias.name == "core":
                    self._add(node, ERROR,
                              "from jax import core — semi-private "
                              "namespace")
                elif alias.name in _HOST_CALLBACKS:
                    self._add(node, ERROR,
                              f"host callback {alias.name!r} imported "
                              f"into library code: inside a jitted path "
                              f"it serializes the device stream per call")
        self.generic_visit(node)

    def visit_Attribute(self, node):
        chain = _attr_chain(node)
        if len(chain) >= 2 and chain[0] == "jax":
            if chain[1] == "core":
                self._add(node, ERROR,
                          f"use of semi-private jax.core "
                          f"({'.'.join(chain)}) — its re-exports move "
                          f"between JAX releases")
            elif chain[1] in ("pure_callback",) or (
                    len(chain) >= 3
                    and chain[1] in ("debug", "experimental")
                    and chain[2] in _HOST_CALLBACKS):
                self._add(node, ERROR,
                          f"host callback {'.'.join(chain)} in library "
                          f"code: inside a jitted path it serializes the "
                          f"device stream per call")
        self.generic_visit(node)


def _suppressed_lines(src: str) -> set:
    return {i + 1 for i, line in enumerate(src.splitlines())
            if "# shardcheck: ok" in line}


def lint_file(path: str, relpath: str = None) -> Report:
    rep = Report()
    relpath = relpath or path
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        rep.add(CHECK, ERROR, f"{relpath}:{e.lineno}",
                f"syntax error: {e.msg}")
        return rep
    _Visitor(relpath, _suppressed_lines(src), rep).visit(tree)
    return rep


def lint_sources(roots=None) -> Report:
    """Lint every .py file under `roots` (default: the picotron_tpu
    package — the code that can land inside the jitted step)."""
    if roots is None:
        roots = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    rep = Report()
    n_files = 0
    for root in roots:
        if os.path.isfile(root):
            files = [root]
            base = os.path.dirname(root)
        else:
            base = os.path.dirname(root.rstrip(os.sep))
            files = sorted(
                os.path.join(dp, f)
                for dp, _, fs in os.walk(root) for f in fs
                if f.endswith(".py"))
        for path in files:
            n_files += 1
            rep.extend(lint_file(path, os.path.relpath(path, base)))
    rep.info[CHECK] = {"files": n_files}
    return rep
