"""Spec lint — PartitionSpec pytree vs. param pytree vs. mesh, statically.

The GSPMD contract this repo trains under is one declarative pytree of
PartitionSpecs (parallel/sharding.py) that must stay leaf-for-leaf aligned
with the model's param pytree (models/llama.py init_params) and
axis-for-axis consistent with the mesh (mesh.py AXES). Nothing enforces
that alignment at authoring time: a renamed param, a spec with one entry
too many, or a sharded dim the mesh axis does not divide all surface as
XLA partitioner errors (or, worse, silent replication) deep inside the
first jit. This lint walks the three structures against each other on the
host — no devices, no tracing — and reports every mismatch with the exact
pytree path.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

import jax
from jax.sharding import PartitionSpec as P

from picotron_tpu.analysis.report import ERROR, Report
from picotron_tpu.mesh import AXES

CHECK = "spec_lint"


def _path_str(path) -> str:
    """'layers/q'-style rendering of a jax key path."""
    parts = []
    for k in path:
        if hasattr(k, "key"):       # DictKey
            parts.append(str(k.key))
        elif hasattr(k, "idx"):     # SequenceKey
            parts.append(str(k.idx))
        elif hasattr(k, "name"):    # GetAttrKey / FlattenedIndexKey
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts) or "<root>"


def _spec_axes(entry) -> tuple:
    """Mesh axes named by one PartitionSpec entry (None -> ())."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def lint_specs(specs: Any, params: Any,
               axis_sizes: Mapping[str, int]) -> Report:
    """Core lint over arbitrary (specs, params) pytrees.

    specs: pytree whose leaves are PartitionSpec; params: matching pytree of
    arrays / ShapeDtypeStructs; axis_sizes: mesh axis name -> size. Pure
    host computation so mutation tests can feed deliberately broken trees.
    """
    rep = Report()
    spec_leaves = dict_by_path(specs, is_leaf=lambda x: isinstance(x, P))
    param_leaves = dict_by_path(params)

    for path in sorted(set(spec_leaves) - set(param_leaves)):
        rep.add(CHECK, ERROR, path,
                "spec leaf has no matching param leaf (stale or misspelled "
                "entry in param_specs)")
    for path in sorted(set(param_leaves) - set(spec_leaves)):
        rep.add(CHECK, ERROR, path,
                "param leaf has no PartitionSpec (param_specs is missing "
                "this leaf; it would be fully replicated by accident)")

    for path in sorted(set(spec_leaves) & set(param_leaves)):
        spec, leaf = spec_leaves[path], param_leaves[path]
        shape = tuple(leaf.shape)
        if len(spec) > len(shape):
            rep.add(CHECK, ERROR, path,
                    f"spec {spec} has {len(spec)} entries but the param "
                    f"has rank {len(shape)} (shape {shape})")
            continue
        seen: dict[str, int] = {}
        for dim, entry in enumerate(spec):
            axes = _spec_axes(entry)
            for a in axes:
                if a not in axis_sizes:
                    rep.add(CHECK, ERROR, path,
                            f"dim {dim}: unknown mesh axis {a!r} (mesh "
                            f"axes: {tuple(axis_sizes)})")
                elif a in seen:
                    rep.add(CHECK, ERROR, path,
                            f"dim {dim}: mesh axis {a!r} already shards "
                            f"dim {seen[a]} — an axis may shard at most "
                            f"one dimension")
                else:
                    seen[a] = dim
            factor = math.prod(axis_sizes.get(a, 1) for a in axes)
            if factor > 1 and shape[dim] % factor != 0:
                rep.add(CHECK, ERROR, path,
                        f"dim {dim} (size {shape[dim]}) is not divisible "
                        f"by mesh axes {axes} (product {factor}) — each "
                        f"device would need a ragged shard")
    rep.info[CHECK] = {
        "spec_leaves": len(spec_leaves),
        "param_leaves": len(param_leaves),
        "mesh_axes": dict(axis_sizes),
    }
    return rep


def dict_by_path(tree: Any, is_leaf=None) -> dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    return {_path_str(path): leaf for path, leaf in flat}


def lint_param_specs(cfg) -> Report:
    """Config-level lint: parallel/sharding.py's specs against the model's
    actual (pp-padded) param tree and the config's mesh axis sizes."""
    from picotron_tpu.parallel.api import abstract_master
    from picotron_tpu.parallel.sharding import param_specs

    d = cfg.distributed
    axis_sizes = dict(zip(AXES, (d.dp_size, d.pp_size, d.ep_size,
                                 d.cp_size, d.tp_size)))
    return lint_specs(param_specs(cfg), abstract_master(cfg), axis_sizes)
