"""Orchestration: run the shardcheck analyzers for a config, cheap first.

`run_shardcheck` is the whole pass (CLI, bench --shardcheck, tests);
`preflight` is the fail-fast subset train.py runs before committing pod
time — spec lint plus the donation/recompile hazards, which come almost
free since the step must be traced anyway. Set PICOTRON_PREFLIGHT=0 to
skip (e.g. when iterating on a config the analyzers flag intentionally).
"""

from __future__ import annotations

import os

from picotron_tpu.analysis.report import Report

ALL_CHECKS = ("spec", "source", "collectives", "boundary", "provenance",
              "variants", "donation", "stability")
PREFLIGHT_CHECKS = ("spec", "donation", "stability", "provenance",
                    "variants", "boundary")


def run_shardcheck(cfg, *, menv=None, checks=ALL_CHECKS,
                   budget_bytes=None, source_roots=None,
                   cost_model=None, slices=None, dcn_axes=None) -> Report:
    """Run the requested analyzers for `cfg`; returns the merged Report.

    Host-only: the trace-time checks lower the train step on an abstract
    mesh (the caller must have provisioned enough simulated devices — see
    tools/shardcheck.py). Cheap structural checks run first so a broken
    spec is reported even when the step cannot trace at all.
    """
    from picotron_tpu.analysis.spec_lint import lint_param_specs

    rep = Report()
    spec_ok = True
    if "spec" in checks:
        spec_rep = lint_param_specs(cfg)
        spec_ok = spec_rep.ok()
        rep.extend(spec_rep)
    if "source" in checks:
        from picotron_tpu.analysis.source_lint import lint_sources

        rep.extend(lint_sources(source_roots))
    trace_checks = {"collectives", "boundary", "provenance", "variants",
                    "donation", "stability"} & set(checks)
    if trace_checks:
        if not spec_ok:
            # a spec the lint rejects usually cannot trace either — stop at
            # the precise structural findings instead of a partitioner
            # backtrace
            return rep
        from picotron_tpu.analysis.trace import lower_train_step

        low = lower_train_step(cfg, menv)
        if "collectives" in trace_checks:
            from picotron_tpu.analysis.collectives import audit_collectives

            rep.extend(audit_collectives(cfg, text=low.text,
                                         state=low.state,
                                         budget_bytes=budget_bytes,
                                         cost_model=cost_model))
        if "boundary" in trace_checks:
            from picotron_tpu.analysis.boundary import audit_boundary

            rep.extend(audit_boundary(cfg, low=low,
                                      n_slices=slices, dcn_axes=dcn_axes,
                                      cost_model=cost_model))
        if "provenance" in trace_checks:
            from picotron_tpu.analysis.dataflow import audit_dataflow

            rep.extend(audit_dataflow(cfg, low=low, cost_model=cost_model))
        if "variants" in trace_checks:
            from picotron_tpu.analysis.variants import audit_variants

            rep.extend(audit_variants(cfg, low=low))
        if "donation" in trace_checks:
            from picotron_tpu.analysis.hazards import check_donation

            rep.extend(check_donation(low.lowered, low.state, low.batch))
        if "stability" in trace_checks:
            from picotron_tpu.analysis.hazards import check_state_stability

            rep.extend(check_state_stability(low.step_fn, low.state,
                                             low.batch))
    return rep


def preflight(cfg, menv=None, *, checks=PREFLIGHT_CHECKS) -> Report:
    """train.py's fail-fast pre-flight. Raises ShardcheckError on errors
    (the exception text IS the rendered report); returns the report
    otherwise. PICOTRON_PREFLIGHT=0 disables."""
    if os.environ.get("PICOTRON_PREFLIGHT", "1") == "0":
        return Report()
    rep = run_shardcheck(cfg, menv=menv, checks=checks)
    rep.raise_if_errors()
    return rep
